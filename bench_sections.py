"""Non-headline benchmark sections, imported by bench.py: the CoCoA SVM at
RCV1 scale and the end-to-end serving-latency pipeline (BASELINE.md configs
"flink-svm CoCoA linear SVM on RCV1-binary" and "flink-queryable-client
top-k recommendation serving from ALS factors").

Each section returns a flat dict merged into bench.py's single JSON line.
All scales are env-tunable (BENCH_SVM_*, BENCH_SERVE_*).
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile
import time
import traceback

import numpy as np


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _pcts(ms: "list[float]") -> dict:
    """Latency percentiles (ms in, ms out) computed THROUGH the serving
    plane's shared histogram ladder (obs.metrics.LATENCY_BUCKETS_S, 16
    log buckets/decade): a bench p50 and a fleet-scraped serving p50 are
    now the identical interpolated-bucket statistic instead of an exact
    rank compared against a bucket estimate.  The ladder is seconds-
    denominated, so convert at the boundary."""
    from flink_ms_tpu.obs.metrics import bucketed_quantiles

    p50, p95, p99 = bucketed_quantiles(
        [m / 1e3 for m in ms], (50, 95, 99))
    return {"p50": round(p50 * 1e3, 3), "p95": round(p95 * 1e3, 3),
            "p99": round(p99 * 1e3, 3)}


# ---------------------------------------------------------------------------
# SVM section: RCV1-shaped CoCoA wall-clock
# ---------------------------------------------------------------------------

def synth_rcv1(n, d, nnz_row, seed=0, flip_p=None):
    """RCV1-binary-shaped synthetic data: ~nnz_row features per row out of
    d, unit-ish values, labels from a sparse linear teacher (the real RCV1
    is not shippable in this image; shape and sparsity match its
    ~700k x 47k, ~70 nnz/row envelope).

    ``flip_p`` (env BENCH_SVM_FLIP, default 0.05): fraction of labels
    flipped.  Noise-free teacher labels understate the risk of the
    aggressive CoCoA+ sigma' regime (VERDICT r2 weak #3 — real labels put
    dual variables on their box constraints); the default workload now
    carries noise, recorded in the artifact as svm_*_label_flip."""
    from flink_ms_tpu.core.formats import SparseData

    if flip_p is None:
        flip_p = float(os.environ.get("BENCH_SVM_FLIP", 0.05))
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, d, size=(n, nnz_row), dtype=np.int64)
    val = rng.normal(size=(n, nnz_row)) / np.sqrt(nnz_row)
    w_true = rng.normal(size=d)
    y = np.sign(np.einsum("nl,nl->n", val, w_true[idx]))
    y[y == 0] = 1
    if flip_p > 0:
        y = np.where(rng.uniform(size=n) < flip_p, -y, y)
    return SparseData(
        labels=y,
        indptr=np.arange(0, (n + 1) * nnz_row, nnz_row),
        indices=idx.ravel(),
        values=val.ravel(),
        n_features=d,
    )


def run_svm_section(devices, platform, small: bool) -> dict:
    import jax.numpy as jnp

    from flink_ms_tpu.ops.svm import (
        SVMConfig,
        SVMModel,
        compile_svm_fit,
        prepare_svm_blocked,
    )
    from flink_ms_tpu.parallel.distributed import to_host_array
    from flink_ms_tpu.parallel.mesh import make_mesh

    n = int(os.environ.get("BENCH_SVM_EXAMPLES", 20_000 if small else 700_000))
    d = int(os.environ.get("BENCH_SVM_FEATURES", 2_000 if small else 47_236))
    nnz_row = int(os.environ.get("BENCH_SVM_NNZ", 20 if small else 70))
    rounds = int(os.environ.get("BENCH_SVM_ROUNDS", 5 if small else 10))
    # K logical SDCA chains: the hardware-parallelism lever (vmapped per
    # device).  sigma' = aggressive CoCoA+ smoothing, valid on sparse data.
    # Default K raised 1024 -> 8192 after a convergence sweep (CPU, add
    # mode, sigma'=8): objective after R rounds is identical for
    # K in {256 .. 32768} — total updates per round are fixed at n, only
    # the serial chain depth changes — so the shortest chains the local-w
    # memory (K x d f32, 1.55 GB at RCV1 scale for 8192) allows win.
    K = int(os.environ.get("BENCH_SVM_BLOCKS", 128 if small else 8192))
    sigma = float(os.environ.get("BENCH_SVM_SIGMA", 8.0))
    lam = float(os.environ.get("BENCH_SVM_LAMBDA", 1e-4))

    t0 = time.time()
    data = synth_rcv1(n, d, nnz_row)
    _log(f"[bench:svm] synth {n}x{d} nnz/row={nnz_row}: {time.time() - t0:.1f}s")

    mesh = make_mesh(devices=devices)
    t0 = time.time()
    problem = prepare_svm_blocked(data, K)
    _log(f"[bench:svm] prepare K={K}: {time.time() - t0:.1f}s "
         f"(rows/chain={problem.rows_per_block})")

    cfg = SVMConfig(
        iterations=rounds,
        local_iterations=problem.rows_per_block,  # one local pass per round
        regularization=lam,
        mode="add",
        sigma_prime=sigma,
    )
    fit, dev_args = compile_svm_fit(problem, cfg, mesh)

    from flink_ms_tpu.utils.profiling import hard_sync

    # steady-state sec/round: same executable (dynamic trip count) timed at
    # 1 round and at `rounds`; difference isolates per-round cost.  The
    # timed region ends in a hard value-fetch sync — block_until_ready is
    # not a reliable barrier on tunneled backends.
    def run_rounds(r):
        t = time.time()
        w, a = fit(jnp.asarray(r, jnp.int32), *dev_args)
        hard_sync(w)
        return time.time() - t, w

    run_rounds(1)  # compile + warmup
    t1, _ = run_rounds(1)
    tn, w_dev = run_rounds(rounds)
    sec_per_round = max((tn - t1) / max(rounds - 1, 1), 1e-9)
    wall = tn

    model = SVMModel(weights=to_host_array(w_dev).astype(np.float64))
    hinge = model.hinge_loss(data, lam)
    _log(f"[bench:svm] {platform}: {sec_per_round:.4f} s/round, "
         f"{wall:.2f}s wall for {rounds} rounds, objective={hinge:.4f}")
    prefix = "svm_small" if small else "svm_rcv1"
    out = {
        f"{prefix}_sec_per_round": round(sec_per_round, 6),
        f"{prefix}_wall_clock_s": round(wall, 3),
        f"{prefix}_hinge_objective": round(hinge, 6),
        f"{prefix}_rounds": rounds,
        f"{prefix}_blocks": K,
        f"{prefix}_examples": n,
        f"{prefix}_label_flip": float(os.environ.get("BENCH_SVM_FLIP", 0.05)),
    }
    # kernel-engine forensics: which inner loop / round-end reduction the
    # auto gates actually picked for this run
    from flink_ms_tpu.ops.svm import _dw_choice, _resolve_inner, _step_choice

    out[f"{prefix}_inner"] = _resolve_inner(problem, cfg, mesh)
    out[f"{prefix}_dw"] = _dw_choice()
    out[f"{prefix}_step"] = _step_choice()
    # quality anchor (VERDICT r3 #3): wall-clock to reach within 1% of a
    # converged reference objective — the "identical hinge" half of the
    # north star.  The reference is this solver at BENCH_SVM_REF_ROUNDS
    # (CoCoA converges to the global optimum of the convex dual, so a long
    # run IS the converged reference); the crossing is scanned at doubling
    # round counts — fresh solves from init on the same executable — so
    # rounds_to_target has power-of-two granularity, and secs_to_target is
    # that count times the steady-state sec/round measured above.
    if os.environ.get("BENCH_SVM_TARGET", "1") == "1":
        try:
            ref_rounds = int(os.environ.get("BENCH_SVM_REF_ROUNDS",
                                            10 if small else 40))
            # each fit call is capped to ~BENCH_SVM_REF_MAX_S of device
            # time: a single >~60 s dispatch through the tunneled backend
            # can kill the TPU worker (round-3 K-sweep: every anchor whose
            # 40-round ref fit exceeded ~60 s crashed with "TPU worker
            # process crashed or restarted"; the ~32 s ones survived).
            # Segments warm-start via fit(..., start=) and are
            # bit-identical to one long fit (absolute-round RNG).
            max_seg_s = float(os.environ.get("BENCH_SVM_REF_MAX_S", 40))
            seg = max(1, int(max_seg_s / max(sec_per_round, 1e-9)))

            def obj_at(r):
                w_r, a_r = dev_args[0], dev_args[5]
                done = 0
                while done < r:
                    step = min(seg, r - done)
                    args = list(dev_args)
                    args[0], args[5] = w_r, a_r
                    w_r, a_r = fit(jnp.asarray(step, jnp.int32), *args,
                                   start=done)
                    hard_sync(w_r)
                    done += step
                return SVMModel(
                    weights=to_host_array(w_r).astype(np.float64)
                ).hinge_loss(data, lam)

            ref_obj = obj_at(ref_rounds)
            target = 1.01 * ref_obj
            r = 1
            while r < ref_rounds and obj_at(r) > target:
                r *= 2
            r = min(r, ref_rounds)
            out[f"{prefix}_converged_objective"] = round(ref_obj, 6)
            out[f"{prefix}_rounds_to_target"] = r
            out["svm_secs_to_target"] = round(r * sec_per_round, 3)
            _log(f"[bench:svm] objective {ref_obj:.6f} @ {ref_rounds} rounds;"
                 f" within 1% by round {r} -> "
                 f"{out['svm_secs_to_target']}s to target")
        except Exception:
            _log(traceback.format_exc())
            out[f"{prefix}_target_error"] = traceback.format_exc(limit=3)

    # CPU stand-in comparison (mirrors the ALS section's vs_baseline): the
    # identical program on the host backend at reduced examples, scaled
    # linearly to the full n.  >1 = the accelerator is that much faster.
    if platform != "cpu" and os.environ.get("BENCH_SKIP_CPU") != "1":
        try:
            import jax

            cpu_n = min(n - n % K if n > K else n, 13 * K)  # divisible by
            # K: the padded-slot count then scales exactly with n
            cpu_n = max(cpu_n, K)
            cpu_data = synth_rcv1(cpu_n, d, nnz_row)
            cpu_problem = prepare_svm_blocked(cpu_data, K)
            # trip count is the CALL argument below; config.iterations is
            # not part of the compiled program
            cpu_cfg = SVMConfig(
                local_iterations=cpu_problem.rows_per_block,
                regularization=lam, mode="add", sigma_prime=sigma,
            )
            cpu_mesh = make_mesh(devices=jax.devices("cpu")[:1])
            cpu_fit, cpu_args = compile_svm_fit(cpu_problem, cpu_cfg, cpu_mesh)

            def cpu_run(r):
                t0 = time.time()
                w, _ = cpu_fit(jnp.asarray(r, jnp.int32), *cpu_args)
                hard_sync(w)
                return time.time() - t0

            cpu_run(1)  # compile + warmup
            t1, t3 = cpu_run(1), cpu_run(3)
            # two-point protocol, same as the accelerator number: the
            # difference strips per-call dispatch + fetch overhead
            cpu_spr = max((t3 - t1) / 2, 1e-9) * (n / cpu_n)
            out[f"{prefix}_vs_baseline"] = round(cpu_spr / sec_per_round, 3)
            _log(f"[bench:svm] CPU stand-in: {cpu_spr:.3f} s/round scaled "
                 f"-> vs_baseline {out[f'{prefix}_vs_baseline']}")
        except Exception:
            _log(traceback.format_exc())
            out[f"{prefix}_baseline_error"] = traceback.format_exc(limit=3)
    return out


def _write_ratings_tsv(path: str, n: int, n_users: int, n_items: int,
                       seed: int, header: bool = False) -> None:
    """Random user\\titem\\trating rows within the served id ranges — shared
    by the SGD-throughput and live-MSE steps."""
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        if header:
            f.write("userId\titemId\trating\n")
        for _ in range(n):
            f.write(
                f"{rng.integers(1, n_users + 1)}\t"
                f"{rng.integers(1, n_items + 1)}\t"
                f"{rng.uniform(1, 5):.3f}\n"
            )


def _wait_for_ingest(jobs, expected: int, what: str, timeout_s: float = 600) -> None:
    """Block until the jobs' tables hold ``expected`` keys combined; loud on
    stall so a latency section never measures a partially-loaded store.
    ``jobs`` is one ServingJob or a list (sharded: disjoint key slices)."""
    if not isinstance(jobs, (list, tuple)):
        jobs = [jobs]

    def count():
        return sum(len(j.table) for j in jobs)

    deadline = time.time() + timeout_s
    while count() < expected and time.time() < deadline:
        time.sleep(0.1)
    if count() < expected:
        raise RuntimeError(
            f"{what} ingest stalled: {count()}/{expected} rows"
        )


# ---------------------------------------------------------------------------
# SVM serving section: flat (query-per-feature) and range-partitioned
# (query-per-bucket) lookup shapes — the reference's SVMPredictRandom and
# RangePartitionSVMPredict harnesses (BASELINE.md rows 2-3)
# ---------------------------------------------------------------------------

def run_svm_serving_section(small: bool) -> dict:
    from flink_ms_tpu.core.params import Params
    from flink_ms_tpu.gen import svm_model_generator
    from flink_ms_tpu.serve import producer
    from flink_ms_tpu.serve.client import QueryClient
    from flink_ms_tpu.serve.consumer import (
        SVM_STATE,
        MemoryStateBackend,
        ServingJob,
        parse_svm_record,
    )
    from flink_ms_tpu.serve.journal import Journal

    n_feat = int(os.environ.get("BENCH_SVMSERVE_FEATURES",
                                2_000 if small else 47_236))
    range_ = int(os.environ.get("BENCH_SVMSERVE_RANGE", 100 if small else 1_000))
    n_q = int(os.environ.get("BENCH_SVMSERVE_QUERIES", 100 if small else 1_000))
    q_nnz = int(os.environ.get("BENCH_SVMSERVE_NNZ", 20 if small else 70))

    tmp = tempfile.mkdtemp(prefix="bench_svmserve_")
    out = {}
    jobs = []
    try:
        # range-partitioned model rows via the generator (reference shape:
        # "bucket,idx:w;..."), flat rows derived from them so both planes
        # serve the same weights
        svm_model_generator.run(Params.from_dict({
            "numFeatures": n_feat, "range": range_,
            "output": os.path.join(tmp, "model"), "parallelism": 1,
        }))
        producer.run(Params.from_dict({
            "journalDir": os.path.join(tmp, "bus"), "topic": "svm-range",
            "input": os.path.join(tmp, "model"),
        }), label="SVM")
        flat_rows = []
        model_buckets = set()
        from flink_ms_tpu.core.formats import parse_svm_range_row

        with open(os.path.join(tmp, "model")) as f:  # parallelism=1: one file
            for line in f:
                if not line.strip():
                    continue
                bucket, pairs = parse_svm_range_row(line.strip())
                model_buckets.add(bucket)
                flat_rows += [f"{idx},{w!r}" for idx, w in pairs]
        flat_journal = Journal(os.path.join(tmp, "bus"), "svm-flat")
        flat_journal.append(flat_rows, flush=False)
        flat_journal.sync()

        range_journal = Journal(os.path.join(tmp, "bus"), "svm-range")
        rjob = ServingJob(
            range_journal, SVM_STATE, parse_svm_record, MemoryStateBackend(),
            host="127.0.0.1", port=0, poll_interval_s=0.01,
        ).start()
        jobs.append(rjob)
        fjob = ServingJob(
            flat_journal, SVM_STATE, parse_svm_record, MemoryStateBackend(),
            host="127.0.0.1", port=0, poll_interval_s=0.01,
        ).start()
        jobs.append(fjob)
        n_buckets = len(model_buckets)  # generator emits n_feat//range + 1
        _wait_for_ingest(rjob, n_buckets, "svm range-plane")
        _wait_for_ingest(fjob, len(flat_rows), "svm flat-plane")

        rng = np.random.default_rng(11)
        queries = [
            np.unique(rng.integers(1, n_feat + 1, q_nnz))
            for _ in range(n_q)
        ]
        # flat plane: one GET per feature (SVMPredictRandom.java:68-81),
        # then the batched variant — the whole sparse vector in ONE MGET
        # round trip, the beat-the-reference path (SURVEY.md §3.5)
        ms, ms_b = [], []
        with QueryClient("127.0.0.1", fjob.port, timeout_s=60) as c:
            for feats in queries:
                t0 = time.perf_counter()
                acc = 0.0
                for fid in feats:
                    payload = c.query_state(SVM_STATE, str(fid))
                    if payload is not None:
                        acc += float(payload)
                ms.append((time.perf_counter() - t0) * 1000.0)
            for feats in queries:
                t0 = time.perf_counter()
                payloads = c.query_states(
                    SVM_STATE, [str(int(f)) for f in feats]
                )
                sum(float(p) for p in payloads if p is not None)
                ms_b.append((time.perf_counter() - t0) * 1000.0)
        out.update({f"svmserve_flat_{q}_ms": v for q, v in _pcts(ms).items()})
        out.update(
            {f"svmserve_flat_mget_{q}_ms": v for q, v in _pcts(ms_b).items()}
        )
        # range plane: one GET per bucket + payload parse
        # (RangePartitionSVMPredict.java:60-101)
        from flink_ms_tpu.core.formats import RangePayloadCache

        parse_cache = RangePayloadCache()
        ms_r = []
        with QueryClient("127.0.0.1", rjob.port, timeout_s=60) as c:
            for feats in queries:
                t0 = time.perf_counter()
                acc = 0.0
                needed = {}
                for fid in feats:
                    needed.setdefault(int(fid) // range_, []).append(int(fid))
                for bucket, fids in needed.items():
                    payload = c.query_state(SVM_STATE, str(bucket))
                    if payload is None:
                        continue
                    # cached vectorized parse + sorted lookup, same as the
                    # range client's hot path
                    ws, _ = parse_cache.gather(payload, fids)
                    acc += float(ws.sum())
                ms_r.append((time.perf_counter() - t0) * 1000.0)
        out.update({f"svmserve_range_{q}_ms": v for q, v in _pcts(ms_r).items()})
        # and the batched variant: every needed bucket in ONE MGET round
        # trip (the reference pays one KvState RPC per bucket,
        # RangePartitionSVMPredict.java:63)
        parse_cache = RangePayloadCache()  # fresh: each variant pays its
        # own cold parses, keeping the two timings comparable
        ms_rb = []
        with QueryClient("127.0.0.1", rjob.port, timeout_s=60) as c:
            for feats in queries:
                t0 = time.perf_counter()
                acc = 0.0
                needed = {}
                for fid in feats:
                    needed.setdefault(int(fid) // range_, []).append(int(fid))
                buckets_q = sorted(needed)
                payloads = c.query_states(
                    SVM_STATE, [str(b) for b in buckets_q]
                )
                for bucket, payload in zip(buckets_q, payloads):
                    if payload is None:
                        continue
                    ws, _ = parse_cache.gather(payload, needed[bucket])
                    acc += float(ws.sum())
                ms_rb.append((time.perf_counter() - t0) * 1000.0)
        out.update(
            {f"svmserve_range_mget_{q}_ms": v for q, v in _pcts(ms_rb).items()}
        )
        # server-side sparse dot (DOT verb): the whole sparse query in ONE
        # round trip, weights resolved against the server's cached parsed
        # bucket rows — the range-partitioning design finally WINNING over
        # the flat planes instead of losing to them (VERDICT r4 missing #2)
        ms_rd = []
        dot_check = None
        with QueryClient("127.0.0.1", rjob.port, timeout_s=60) as c:
            c.sparse_dot(SVM_STATE, range_, [(1, 1.0)])  # index build —
            # untimed on BOTH planes so the timed samples compare
            for feats in queries:
                q_vec = [(int(f), 1.0) for f in feats]
                t0 = time.perf_counter()
                dot, _missing = c.sparse_dot(SVM_STATE, range_, q_vec)
                ms_rd.append((time.perf_counter() - t0) * 1000.0)
                dot_check = dot
        # cross-check the last query against the client-parsed range path
        feats = queries[-1]
        needed = {}
        for fid in feats:
            needed.setdefault(int(fid) // range_, []).append(int(fid))
        acc = 0.0
        with QueryClient("127.0.0.1", rjob.port, timeout_s=60) as c:
            for bucket, fids in needed.items():
                payload = c.query_state(SVM_STATE, str(bucket))
                if payload is not None:
                    ws, _ = parse_cache.gather(payload, fids)
                    acc += float(ws.sum())
        if dot_check is not None and abs(acc - dot_check) > 1e-9 * max(
                1.0, abs(acc)):
            out["svmserve_dot_error"] = (
                f"DOT={dot_check!r} != client-side {acc!r}"
            )
        out.update(
            {f"svmserve_range_dot_{q}_ms": v for q, v in _pcts(ms_rd).items()}
        )
        # native plane: the same range rows through the C++ store + epoll
        # server's DOT (byte-parity-tested against the plane above) —
        # error-isolated like the ALS native section
        try:
            from flink_ms_tpu.serve.native_store import (
                NativeLookupServer,
                NativeStore,
            )

            nstore = NativeStore(os.path.join(tmp, "dot_store"))
            try:
                with open(os.path.join(tmp, "model"), "rb") as f:
                    n_ing, n_errs = nstore.ingest_buf(f.read(), 1)
                if n_ing != n_buckets or n_errs:
                    raise RuntimeError(
                        f"partial native ingest: {n_ing}/{n_buckets} rows, "
                        f"{n_errs} errors — timings would score a smaller "
                        "index"
                    )
                with NativeLookupServer(nstore, SVM_STATE, job_id="bench",
                                        port=0) as nsrv:
                    ms_nd = []
                    with QueryClient("127.0.0.1", nsrv.port,
                                     timeout_s=60) as c:
                        c.sparse_dot(SVM_STATE, range_,
                                     [(1, 1.0)])  # index build
                        for feats in queries:
                            q_vec = [(int(f), 1.0) for f in feats]
                            t0 = time.perf_counter()
                            ndot, _miss = c.sparse_dot(SVM_STATE, range_,
                                                       q_vec)
                            ms_nd.append(
                                (time.perf_counter() - t0) * 1000.0)
                    out.update({f"svmserve_native_dot_{q}_ms": v
                                for q, v in _pcts(ms_nd).items()})
                    if dot_check is not None and abs(ndot - dot_check) \
                            > 1e-9 * max(1.0, abs(dot_check)):
                        out["svmserve_native_dot_error"] = (
                            f"native DOT={ndot!r} != python {dot_check!r}"
                        )
                    _log(f"[bench:svmserve] native DOT {_pcts(ms_nd)} ms")
            finally:
                nstore.close()
        except Exception:
            _log(traceback.format_exc())
            out["svmserve_native_error"] = traceback.format_exc(limit=3)
        out["svmserve_features"] = n_feat
        out["svmserve_buckets"] = n_buckets
        _log(f"[bench:svmserve] flat {_pcts(ms)} ms, "
             f"flat-mget {_pcts(ms_b)} ms, range {_pcts(ms_r)} ms, "
             f"range-dot {_pcts(ms_rd)} ms "
             f"({n_feat} features, {n_buckets} buckets, {q_nnz} nnz/query)")
        return out
    finally:
        for job in jobs:
            job.stop()
        shutil.rmtree(tmp, ignore_errors=True)


# ---------------------------------------------------------------------------
# serving section: generator -> producer -> consumer -> latency harnesses
# ---------------------------------------------------------------------------

def _topk_closed_loop(port, state, n_users, k, concurrency, total_queries,
                      seed):
    """`total_queries` TOPKs spread over `concurrency` closed-loop client
    threads (one persistent connection each) -> (qps, pcts dict).  The
    clock runs from a start barrier to the last reply, so qps includes
    queueing — exactly what a loaded serving plane's caller sees."""
    import threading

    from flink_ms_tpu.serve.client import QueryClient

    per_thread = max(total_queries // concurrency, 1)
    lat_ms = [[] for _ in range(concurrency)]
    errors = []
    barrier = threading.Barrier(concurrency + 1)

    def worker(widx):
        rng = np.random.default_rng(seed + widx)
        try:
            with QueryClient("127.0.0.1", port, timeout_s=600) as c:
                c.ping()  # connection + handler thread up before the clock
                barrier.wait()
                for _ in range(per_thread):
                    uid = int(rng.integers(1, n_users + 1))
                    t0 = time.perf_counter()
                    # raw round trip: reply PARSING is caller-side cost,
                    # not serving cost, and it would water down the
                    # batched-vs-unbatched ratio equally in both arms
                    r = c._roundtrip(f"TOPK\t{state}\t{uid}\t{k}")
                    lat_ms[widx].append((time.perf_counter() - t0) * 1000.0)
                    if not r or r[0] not in "VN":
                        raise RuntimeError(f"bad topk reply: {r!r}")
        except Exception as e:  # pragma: no cover - surfaced via errors
            errors.append(e)
            try:
                barrier.abort()
            except Exception:
                pass

    threads = [
        threading.Thread(target=worker, args=(w,), daemon=True)
        for w in range(concurrency)
    ]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    if errors:
        raise errors[0]
    flat = [x for lane in lat_ms for x in lane]
    return round(len(flat) / elapsed, 1), _pcts(flat)


def _topk_pipelined_loop(port, state, n_users, k, window, total_queries,
                         seed):
    """`total_queries` TOPKs down ONE connection with `window` requests in
    flight (the PR's pipelined client) -> qps.  The server's burst framing
    reads the in-flight window in one sweep and the microbatcher coalesces
    it into shared dispatches — this is the co-designed data plane, vs the
    thread-per-connection strict request/reply loop."""
    from flink_ms_tpu.serve.client import QueryClient

    rng = np.random.default_rng(seed)
    reqs = [
        f"TOPK\t{state}\t{int(rng.integers(1, n_users + 1))}\t{k}"
        for _ in range(total_queries)
    ]
    with QueryClient("127.0.0.1", port, timeout_s=600) as c:
        c.ping()
        t0 = time.perf_counter()
        replies = c.pipeline(reqs, window=window)
        elapsed = time.perf_counter() - t0
    bad = [r for r in replies if not r or r[0] not in "VN"]
    if bad:
        raise RuntimeError(f"bad topk replies: {bad[:3]!r}")
    return round(len(replies) / elapsed, 1)


def run_topk_batched_subsection(job, state, n_users, k, small: bool) -> dict:
    """Cross-request microbatching A/B on the live (warm) serving job,
    same catalog and same index for every cell (the handler's
    ``batching`` flag is flipped in-process, so the arms share every
    other cost).  Two client modes x two arms:

    - threads mode: N closed-loop connections, strict request/reply —
      the pre-PR data plane.  Reports qps + p50/p95/p99 per arm at
      concurrency 1/8/64.  Batching here converts the index-lock convoy
      into orderly dispatches (tails drop) but one core still runs N
      client threads, so the qps gap understates the device-side win.
    - pipelined mode: ONE connection with `conc` requests in flight (the
      PR's pipelined client + server burst framing).  The in-flight
      window coalesces into shared dispatches — this is the co-designed
      path and the throughput headline.

    ``serving_topk_batched_speedup_c64`` is the full-stack ratio: the
    batched pipelined plane over the unbatched thread-per-request plane
    at 64 in-flight requests (the pre-PR serving plane had neither
    batching nor pipelining).  Same-client-mode ratios are also emitted
    (``..._threads_speedup_c*`` / ``..._pipe_speedup_c*``) so no cell of
    the matrix is hidden."""
    out = {}
    handler = job.server.topk_handlers.get(state)
    if handler is None or getattr(handler, "batcher", None) is None:
        out["serving_topk_batched_error"] = "no batching handler on job"
        return out
    total = int(os.environ.get(
        "BENCH_SERVE_TOPKB_QUERIES", 128 if small else 512))
    concurrencies = (1, 8, 64)
    pipe_windows = (8, 64)
    was_batching = handler.batching
    # one-time cost per process, paid up front: compile every padded
    # batch-shape bucket before the clock (a compile landing inside a
    # live dispatch charges tens of ms to every request in that batch)
    handler.index.warm_batch_shapes(k, handler.batcher.max_batch)
    try:
        for arm in ("unbatched", "batched"):
            handler.batching = arm == "batched"
            # steady-state warm-up in both client modes (dispatcher
            # thread, handler threads, socket buffers)
            _topk_closed_loop(
                job.port, state, n_users, k, max(concurrencies),
                4 * max(concurrencies), seed=3)
            _topk_pipelined_loop(
                job.port, state, n_users, k, max(pipe_windows),
                4 * max(pipe_windows), seed=4)
            # the batched threads-mode cells carry an explicit _threads_
            # tag; bare serving_topk_batched_c64_qps is reserved for the
            # headline (the pipelined cell) below
            prefix = (f"serving_topk_{arm}" if arm == "unbatched"
                      else f"serving_topk_{arm}_threads")
            for conc in concurrencies:
                qps, pcts = _topk_closed_loop(
                    job.port, state, n_users, k, conc,
                    max(total, conc * 2), seed=7 + conc)
                out[f"{prefix}_c{conc}_qps"] = qps
                out.update({
                    f"{prefix}_c{conc}_{q}_ms": v
                    for q, v in pcts.items()
                })
                _log(f"[bench:serve] topk {arm} threads c{conc}: {qps} "
                     f"qps, {pcts} ms")
            for win in pipe_windows:
                qps = _topk_pipelined_loop(
                    job.port, state, n_users, k, win,
                    max(2 * total, win * 4), seed=17 + win)
                out[f"serving_topk_{arm}_pipe_c{win}_qps"] = qps
                _log(f"[bench:serve] topk {arm} pipelined c{win}: "
                     f"{qps} qps")
    finally:
        handler.batching = was_batching
    for conc in concurrencies:
        ub = out.get(f"serving_topk_unbatched_c{conc}_qps")
        b = out.get(f"serving_topk_batched_threads_c{conc}_qps")
        if ub and b:
            out[f"serving_topk_threads_speedup_c{conc}"] = round(b / ub, 2)
    for win in pipe_windows:
        ub = out.get(f"serving_topk_unbatched_pipe_c{win}_qps")
        b = out.get(f"serving_topk_batched_pipe_c{win}_qps")
        if ub and b:
            out[f"serving_topk_pipe_speedup_c{win}"] = round(b / ub, 2)
    # the headline: co-designed plane (pipelined + batched) vs the pre-PR
    # plane (thread-per-request, unbatched), both at 64 in flight
    old = out.get("serving_topk_unbatched_c64_qps")
    new = out.get("serving_topk_batched_pipe_c64_qps")
    if old and new:
        out["serving_topk_batched_c64_qps"] = new
        out["serving_topk_batched_speedup_c64"] = round(new / old, 2)
    # lone-request cost of batching: bounded by the coalescing window
    # (the idle fast path should keep it near zero)
    ub = out.get("serving_topk_unbatched_c1_p50_ms")
    b = out.get("serving_topk_batched_threads_c1_p50_ms")
    if ub is not None and b is not None:
        out["serving_topk_batched_c1_p50_regression_ms"] = round(b - ub, 3)
    batcher = handler.batcher
    out["serving_topk_batch_dispatches"] = batcher.dispatches
    out["serving_topk_batch_queries"] = batcher.batched_queries
    out["serving_topk_batch_max_seen"] = batcher.max_batch_seen
    out["serving_topk_batch_inline"] = batcher.inline_singles
    return out


def run_serving_section(small: bool) -> dict:
    from flink_ms_tpu.client import als_predict_random
    from flink_ms_tpu.core.params import Params
    from flink_ms_tpu.gen import als_model_generator
    from flink_ms_tpu.serve import producer
    from flink_ms_tpu.serve.client import QueryClient
    from flink_ms_tpu.serve.consumer import (
        ALS_STATE,
        MemoryStateBackend,
        ServingJob,
        parse_als_record,
    )
    from flink_ms_tpu.serve.journal import Journal

    # The bench host's chip sits behind a network tunnel: per-dispatch RTT
    # is ~100 ms, so a device-resident top-k index pays tunnel latency on
    # every query (round-2 measured 129 ms/query vs 6 ms for the same
    # program on the host backend).  Serving is a host-side plane here —
    # pin the index to the host unless the operator overrides (a real TPU
    # serving host with a locally attached chip wants ambient).
    os.environ.setdefault("TPUMS_TOPK_PLATFORM", "cpu")

    n_users = int(os.environ.get("BENCH_SERVE_USERS", 2_000 if small else 100_000))
    n_items = int(os.environ.get("BENCH_SERVE_ITEMS", 5_000 if small else 900_000))
    k = int(os.environ.get("BENCH_SERVE_K", 8 if small else 16))
    n_get = int(os.environ.get("BENCH_SERVE_QUERIES", 200 if small else 2_000))
    n_topk = int(os.environ.get("BENCH_SERVE_TOPK_QUERIES", 20 if small else 100))
    topk_k = int(os.environ.get("BENCH_SERVE_TOPK_K", 10))

    tmp = tempfile.mkdtemp(prefix="bench_serve_")
    out = {}
    job = None
    try:
        # 1. synthetic model at scale (ALSModelGenerator parity path)
        t0 = time.time()
        als_model_generator.run(Params.from_dict({
            "numUsers": n_users, "numItems": n_items, "latentFactors": k,
            "parallelism": 2, "output": os.path.join(tmp, "model"),
        }))
        gen_s = time.time() - t0
        total_rows = n_users + n_items
        out["gen_rows_per_sec"] = round(total_rows / gen_s)
        _log(f"[bench:serve] generated {total_rows} rows k={k} in {gen_s:.1f}s")

        # 2. producer -> journal
        t0 = time.time()
        producer.run(Params.from_dict({
            "journalDir": os.path.join(tmp, "bus"), "topic": "als-models",
            "input": os.path.join(tmp, "model"),
        }))
        out["producer_rows_per_sec"] = round(total_rows / (time.time() - t0))

        # 3. serving job ingests the full journal
        journal = Journal(os.path.join(tmp, "bus"), "als-models")
        job = ServingJob(
            journal, ALS_STATE, parse_als_record, MemoryStateBackend(),
            host="127.0.0.1", port=0, poll_interval_s=0.01,
        ).start()
        t0 = time.time()
        _wait_for_ingest(job, total_rows, "serving")
        out["ingest_rows_per_sec"] = round(total_rows / (time.time() - t0))
        _log(f"[bench:serve] ingested {total_rows} rows in "
             f"{time.time() - t0:.1f}s")

        # 4. point-lookup latency harness (ALSPredictRandom parity: the
        # uId,iId,prediction,ms CSV IS the artifact, percentiles go in JSON)
        csv_path = os.path.join(tmp, "latency.csv")
        completed = als_predict_random.run(Params.from_dict({
            "jobId": job.job_id, "jobManagerHost": "127.0.0.1",
            "jobManagerPort": job.port, "numQueries": n_get,
            "lowerUserId": 1, "upperUserId": n_users + 1,
            "lowerItemId": 1, "upperItemId": n_items + 1,
            "outputFile": csv_path,
        }))
        out["serving_get_queries"] = completed
        # the CSV logs integral ms (reference contract); percentiles need
        # finer grain, so time the same 2-GET-plus-dot query shape directly
        rng = np.random.default_rng(1)
        ms = []
        with QueryClient("127.0.0.1", job.port, timeout_s=60) as c:
            for _ in range(n_get):
                u = int(rng.integers(1, n_users + 1))
                i = int(rng.integers(1, n_items + 1))
                t0 = time.perf_counter()
                up = c.query_state(ALS_STATE, f"{u}-U")
                ip = c.query_state(ALS_STATE, f"{i}-I")
                if up and ip:
                    uf = [float(t) for t in up.split(";") if t]
                    vf = [float(t) for t in ip.split(";") if t]
                    sum(a * b for a, b in zip(uf, vf))
                ms.append((time.perf_counter() - t0) * 1000.0)
        get_p = _pcts(ms)
        out.update({f"serving_get_{q}_ms": v for q, v in get_p.items()})
        # and the batched-verb variant: both factor rows in ONE round trip
        mg = []
        with QueryClient("127.0.0.1", job.port, timeout_s=60) as c:
            for _ in range(n_get):
                u = int(rng.integers(1, n_users + 1))
                i = int(rng.integers(1, n_items + 1))
                t0 = time.perf_counter()
                c.query_states(ALS_STATE, [f"{u}-U", f"{i}-I"])
                mg.append((time.perf_counter() - t0) * 1000.0)
        out.update({f"serving_mget_{q}_ms": v for q, v in _pcts(mg).items()})

        # 5. top-k latency: first query pays the index build (reported
        # separately), steady-state percentiles after
        with QueryClient("127.0.0.1", job.port, timeout_s=600) as c:
            t0 = time.time()
            first = c.topk(ALS_STATE, "1", topk_k)
            out["serving_topk_build_s"] = round(time.time() - t0, 3)
            assert first, "topk returned nothing"
            rng = np.random.default_rng(0)
            tk_ms = []
            for _ in range(n_topk):
                uid = int(rng.integers(1, n_users + 1))
                t0 = time.time()
                c.topk(ALS_STATE, str(uid), topk_k)
                tk_ms.append((time.time() - t0) * 1000.0)
        out.update({f"serving_topk_{q}_ms": v for q, v in _pcts(tk_ms).items()})
        out["serving_rows"] = total_rows

        _log(f"[bench:serve] GET {get_p} ms, TOPK {_pcts(tk_ms)} ms "
             f"(build {out['serving_topk_build_s']}s)")

        # 5b. cross-request microbatching A/B: qps + p50/p95/p99 at
        # concurrency 1/8/64 over the same warm index, batched vs unbatched
        try:
            out.update(run_topk_batched_subsection(
                job, ALS_STATE, n_users, topk_k, small))
        except Exception:
            _log(traceback.format_exc())
            out["serving_topk_batched_error"] = traceback.format_exc(limit=3)

        # 5c. checkpoint/restore wall time at serving scale (the recovery
        # path's cost: fixed-delay restart replays snapshot + journal tail)
        try:
            ckpt_dir = os.path.join(tmp, "ckpt")
            t0 = time.time()
            job.table.snapshot(ckpt_dir, offset=total_rows)
            out["serving_snapshot_s"] = round(time.time() - t0, 3)
            from flink_ms_tpu.serve.table import ModelTable

            fresh = ModelTable(job.table.n_shards)
            t0 = time.time()
            fresh.restore(ckpt_dir)
            out["serving_restore_s"] = round(time.time() - t0, 3)
            assert len(fresh) == len(job.table)
            del fresh  # a full second table copy must not sit on the
            # SGD/MSE sections' memory
            _log(f"[bench:serve] snapshot {out['serving_snapshot_s']}s, "
                 f"restore {out['serving_restore_s']}s @ {total_rows} rows")
        except Exception:
            _log(traceback.format_exc())
            out["ckpt_error"] = traceback.format_exc(limit=3)

        # 6. online-SGD closed-loop throughput (VERDICT r1 #8): per-rating
        # MGET against the live table + updated rows back into the journal
        # the consumer is tailing.  ratings/s is the metric (each rating
        # emits a user and an item row); the reference design pays two
        # network hops per rating (SGD.java:172-173).  Isolated so a
        # failure here records sgd_error without discarding the serving
        # metrics above.
        try:
            from flink_ms_tpu.online import sgd as online_sgd

            n_sgd = int(
                os.environ.get("BENCH_SGD_RATINGS", 500 if small else 5_000)
            )
            ratings_path = os.path.join(tmp, "sgd_ratings.tsv")
            _write_ratings_tsv(ratings_path, n_sgd, n_users, n_items, seed=7)
            mean_payload = ";".join(["0.1"] * k)
            t0 = time.time()
            processed = online_sgd.run(Params.from_dict({
                "input": ratings_path, "mode": "once", "outputMode": "kafka",
                "journalDir": os.path.join(tmp, "bus"), "topic": "als-models",
                "jobId": job.job_id, "jobManagerHost": "127.0.0.1",
                "jobManagerPort": job.port, "queryTimeout": 60,
                # reference at-least-once semantics (flushOnCheckpoint):
                # no per-row fsync, one sync at end — without this the
                # metric measures tmpdir fsync latency, not the loop
                "flushEveryUpdate": False,
                "userMean": mean_payload, "itemMean": mean_payload,
            }))
            sgd_s = time.time() - t0
            out["sgd_ratings_per_sec"] = round(processed / sgd_s)
            _log(f"[bench:serve] SGD {processed} ratings in {sgd_s:.1f}s "
                 f"({out['sgd_ratings_per_sec']}/s)")
            # and the chunked-MGET variant (--batchSize): one round trip
            # per chunk, carry-forward sequential semantics per rating
            batch = int(os.environ.get("BENCH_SGD_BATCH", 64))
            t0 = time.time()
            processed_b = online_sgd.run(Params.from_dict({
                "input": ratings_path, "mode": "once", "outputMode": "kafka",
                "journalDir": os.path.join(tmp, "bus"), "topic": "als-models",
                "jobId": job.job_id, "jobManagerHost": "127.0.0.1",
                "jobManagerPort": job.port, "queryTimeout": 60,
                "flushEveryUpdate": False, "batchSize": batch,
                "userMean": mean_payload, "itemMean": mean_payload,
            }))
            sgd_bs = time.time() - t0
            out["sgd_batched_ratings_per_sec"] = round(processed_b / sgd_bs)
            out["sgd_batch_size"] = batch
            _log(f"[bench:serve] SGD batched({batch}) {processed_b} ratings "
                 f"in {sgd_bs:.1f}s "
                 f"({out['sgd_batched_ratings_per_sec']}/s)")
        except Exception:
            _log(traceback.format_exc())
            out["sgd_error"] = traceback.format_exc(limit=3)

        # 6b. live MSE evaluation rate (MSE.java:52-69 parity: batch job
        # scoring ratings against the LIVE served model, one user-group
        # lookup + per-rating item lookups, batched into MGETs here).
        # Served from a dedicated BOUNDED-factor plane (VERDICT r2 weak
        # #4): the serving-scale plane above keeps the reference's
        # heavy-tailed ratio-of-uniforms factors — right for latency, but
        # its predictions overflow any sanity bound (r2 recorded 9.5e154).
        # Bounded factors put predictions in [0,5), so mse_live_value is a
        # real regression signal (harness tests assert it < 30).
        mjob = None
        try:
            from flink_ms_tpu.eval import mse as mse_eval

            n_mse = int(os.environ.get("BENCH_MSE_RATINGS",
                                       1_000 if small else 10_000))
            m_users = min(n_users, 20_000)
            m_items = min(n_items, 50_000)
            als_model_generator.run(Params.from_dict({
                "numUsers": m_users, "numItems": m_items,
                "latentFactors": k, "parallelism": 1,
                "distribution": "bounded", "seed": 29,
                "output": os.path.join(tmp, "mse_model"),
            }))
            producer.run(Params.from_dict({
                "journalDir": os.path.join(tmp, "bus"), "topic": "als-mse",
                "input": os.path.join(tmp, "mse_model"),
            }))
            mjob = ServingJob(
                Journal(os.path.join(tmp, "bus"), "als-mse"),
                ALS_STATE, parse_als_record, MemoryStateBackend(),
                host="127.0.0.1", port=0, poll_interval_s=0.01,
            ).start()
            _wait_for_ingest(mjob, m_users + m_items, "mse bounded plane")
            mse_in = os.path.join(tmp, "mse_ratings.tsv")
            _write_ratings_tsv(mse_in, n_mse, m_users, m_items, seed=13,
                               header=True)
            t0 = time.time()
            mse_val = mse_eval.run(Params.from_dict({
                "input": mse_in, "jobId": mjob.job_id,
                "jobManagerHost": "127.0.0.1", "jobManagerPort": mjob.port,
                "queryTimeout": 60,
            }))
            mse_s = time.time() - t0
            if mse_val is None:  # every lookup missed: no measurement
                raise RuntimeError("live MSE scored zero ratings")
            out["mse_live_ratings_per_sec"] = round(n_mse / mse_s)
            out["mse_live_value"] = float(mse_val)
            out["mse_live_rows"] = m_users + m_items
            # band self-check (VERDICT r4 #8): at the DEFAULT full-scale
            # config the bounded plane's MSE is deterministic (~4.44,
            # seeds 29/13) — a value outside +-50% of that flags plane
            # corruption even if the offline cross-check below also
            # breaks.  "< 30" would pass a 6x regression.
            default_cfg = (not small
                           and "BENCH_MSE_RATINGS" not in os.environ
                           and "BENCH_SERVE_USERS" not in os.environ
                           and "BENCH_SERVE_ITEMS" not in os.environ
                           and "BENCH_SERVE_K" not in os.environ)
            if default_cfg:
                expected = 4.44
                out["mse_expected_band"] = [round(expected * 0.5, 2),
                                            round(expected * 1.5, 2)]
                if not (expected * 0.5 <= mse_val <= expected * 1.5):
                    out["mse_band_error"] = (
                        f"live MSE {mse_val:.4g} outside "
                        f"{out['mse_expected_band']} at the default config"
                    )
            _log(f"[bench:serve] live MSE {mse_val:.4f} over {n_mse} ratings "
                 f"in {mse_s:.1f}s ({out['mse_live_ratings_per_sec']}/s, "
                 f"bounded plane {m_users}+{m_items} rows)")
            # ground truth for the gate (VERDICT r3 weak #7: "< 30" would
            # pass a 6x quality regression): the SAME model files scored
            # OFFLINE.  Both paths read identical text rows; they differ
            # only by per-prediction float precision (offline f32 jax,
            # live f64 numpy), so any drift beyond ~1e-5 absolute is a
            # serving-plane defect, not noise.  Isolated try: an offline
            # failure must not retro-label the just-measured LIVE value
            # as an mse_error.
            try:
                mse_off = mse_eval.run(Params.from_dict({
                    "input": mse_in, "model": os.path.join(tmp, "mse_model"),
                }))
                out["mse_offline_value"] = float(mse_off)
                _log(f"[bench:serve] offline MSE ground truth {mse_off:.4f} "
                     f"(live-offline delta {mse_val - mse_off:+.2e})")
            except Exception:
                _log(traceback.format_exc())
                out["mse_offline_error"] = traceback.format_exc(limit=3)
        except Exception:
            _log(traceback.format_exc())
            out["mse_error"] = traceback.format_exc(limit=3)
        finally:
            if mjob is not None:
                mjob.stop()

        # 7. native data plane: same journal through the C++ persistent
        # store + epoll lookup server (the reference's RocksDB + Netty
        # KvState analog).  Error-isolated: native toolchain problems
        # record native_error without costing the section.
        njob = None
        backend = None
        try:
            from flink_ms_tpu.serve.consumer import make_backend

            backend = make_backend("rocksdb", os.path.join(tmp, "chk_native"))
            njob = ServingJob(
                journal, ALS_STATE, parse_als_record, backend,
                host="127.0.0.1", port=0, poll_interval_s=0.01,
                native_server=True,
            ).start()
            # full-ingest barrier: percentiles against a partially-loaded
            # store would mix cheap misses into the numbers.  The replay
            # runs through tpums_ingest_buf (one C++ call per chunk), so
            # this also times the native bulk-ingest plane.
            t0 = time.time()
            _wait_for_ingest(njob, total_rows, "native serving")
            out["serving_native_ingest_rows_per_sec"] = round(
                total_rows / max(time.time() - t0, 1e-9)
            )
            _log(f"[bench:serve] native ingest "
                 f"{out['serving_native_ingest_rows_per_sec']} rows/s")
            rng = np.random.default_rng(3)
            with QueryClient("127.0.0.1", njob.port, timeout_s=60) as c:
                nat = []
                for _ in range(n_get):
                    u = int(rng.integers(1, n_users + 1))
                    i = int(rng.integers(1, n_items + 1))
                    t0 = time.perf_counter()
                    c.query_states(ALS_STATE, [f"{u}-U", f"{i}-I"])
                    nat.append((time.perf_counter() - t0) * 1000.0)
            out.update(
                {f"serving_native_mget_{q}_ms": v for q, v in _pcts(nat).items()}
            )
            _log(f"[bench:serve] native MGET {_pcts(nat)} ms")
            # native TOPK (round 4): catalog scored in C++ straight from
            # the store — first query pays the index scan, then cached
            n_topk = int(os.environ.get("BENCH_SERVE_TOPK_QUERIES",
                                        3 if small else 200))
            with QueryClient("127.0.0.1", njob.port, timeout_s=600) as c:
                t0 = time.perf_counter()
                c.topk(ALS_STATE, str(int(rng.integers(1, n_users + 1))), 10)
                out["serving_native_topk_build_s"] = round(
                    time.perf_counter() - t0, 3)
                ntk = []
                for _ in range(n_topk):
                    u = int(rng.integers(1, n_users + 1))
                    t0 = time.perf_counter()
                    c.topk(ALS_STATE, str(u), 10)
                    ntk.append((time.perf_counter() - t0) * 1000.0)
            out.update({f"serving_native_topk_{q}_ms": v
                        for q, v in _pcts(ntk).items()})
            _log(f"[bench:serve] native TOPK {_pcts(ntk)} ms "
                 f"(build {out['serving_native_topk_build_s']}s)")
        except Exception:
            _log(traceback.format_exc())
            out["native_error"] = traceback.format_exc(limit=3)
        finally:
            if njob is not None:
                njob.stop()
            elif backend is not None:
                # job never started: release the store handle + flock before
                # the tmp dir is removed
                store = getattr(backend, "store", None)
                if store is not None:
                    store.close()

        # 8. sharded plane (ALSKafkaConsumer.java:85-92 scale-out): W REAL
        # worker PROCESSES — the deployment shape, one process per shard
        # (`python -m flink_ms_tpu.serve.sharded`) — each owning a hash
        # slice of the same journal; the client routes MGET to owners and
        # fans TOPK out with a score merge.  Rounds 1-2 ran the workers
        # in-process, which shared one GIL + one XLA runtime and therefore
        # serialized the TOPKV fan-out; process workers measure the plane
        # the docs/tests actually claim.  Ingest barrier via the COUNT
        # verb (shards are disjoint, so the sum is the table size).
        #
        # The DEPLOYMENT plane is native (--stateBackend rocksdb
        # --nativeServer true: C++ persistent store + epoll server per
        # shard), so that is what the canonical serving_shard_* keys
        # measure; the Python plane rides along as the A/B arm
        # (serving_shard_py_*).  Hosts without the native build fall back
        # to the Python plane for the canonical keys and record WHY under
        # a non-_error key — a missing toolchain is an environment
        # condition, not a section failure.
        def measure_shard_plane(prefix, state_backend="memory",
                                extra_args=()):
            from flink_ms_tpu.serve.sharded import (
                ShardedQueryClient,
                spawn_worker_procs,
                stop_worker_procs,
            )

            W = int(os.environ.get("BENCH_SHARD_WORKERS", 3))
            procs, ports = spawn_worker_procs(
                W, os.path.join(tmp, "bus"), "als-models", port_dir=tmp,
                state_backend=state_backend, extra_args=extra_args,
            )
            res = {}
            try:
                rng = np.random.default_rng(5)
                sh = []
                # 600s timeout: the first TOPK pays every worker's index
                # build, like the single-node build in section 5
                with ShardedQueryClient(
                    [("127.0.0.1", pt) for pt in ports], timeout_s=600
                ) as c:
                    deadline = time.time() + 600
                    while c.total_count(ALS_STATE) < total_rows:
                        if time.time() > deadline:
                            raise RuntimeError(
                                f"sharded ingest stalled: "
                                f"{c.total_count(ALS_STATE)}/{total_rows}"
                            )
                        time.sleep(0.2)
                    # active warmup, uncounted: the seconds after worker
                    # startup carry a scheduler/cache transient on small
                    # hosts that would otherwise dominate a short timing
                    # window (scripts/shard_profile.py attribution); warm
                    # until the path is demonstrably settled or 3 s,
                    # whichever first
                    wdeadline = time.time() + 3.0
                    fast = 0
                    while time.time() < wdeadline and fast < 20:
                        u = int(rng.integers(1, n_users + 1))
                        t0 = time.perf_counter()
                        c.query_states(ALS_STATE, [f"{u}-U"])
                        fast = (
                            fast + 1
                            if (time.perf_counter() - t0) < 0.001 else 0
                        )
                    for _ in range(n_get):
                        u = int(rng.integers(1, n_users + 1))
                        i = int(rng.integers(1, n_items + 1))
                        t0 = time.perf_counter()
                        c.query_states(ALS_STATE, [f"{u}-U", f"{i}-I"])
                        sh.append((time.perf_counter() - t0) * 1000.0)
                    # publish MGET percentiles before the TOPK phase so a
                    # TOPK failure cannot discard them
                    res.update({
                        f"{prefix}_mget_{q}_ms": v
                        for q, v in _pcts(sh).items()
                    })
                    res[f"{prefix}_workers"] = W
                    tk = []
                    c.topk(ALS_STATE, "1", topk_k)  # index build per worker
                    for _ in range(max(n_topk // 2, 5)):
                        uid = int(rng.integers(1, n_users + 1))
                        t0 = time.perf_counter()
                        c.topk(ALS_STATE, str(uid), topk_k)
                        tk.append((time.perf_counter() - t0) * 1000.0)
                res.update({
                    f"{prefix}_topk_{q}_ms": v for q, v in _pcts(tk).items()
                })
                _log(f"[bench:serve] sharded({W} procs, "
                     f"{state_backend}{' native' if extra_args else ''}) "
                     f"MGET {_pcts(sh)} ms, TOPK {_pcts(tk)} ms")
            finally:
                stop_worker_procs(procs)
            return res

        native_extra = (
            "--nativeServer", "true",
            "--checkpointDataUri", os.path.join(tmp, "shard_chk"),
        )
        try:
            try:
                out.update(measure_shard_plane(
                    "serving_shard", "rocksdb", native_extra))
                out["serving_shard_plane"] = "native"
                try:
                    out.update(measure_shard_plane("serving_shard_py"))
                except Exception:
                    _log(traceback.format_exc())
                    out["shard_error"] = traceback.format_exc(limit=3)
            except Exception:
                _log(traceback.format_exc())
                out["serving_shard_plane"] = "python"
                out["serving_shard_native_fallback"] = traceback.format_exc(
                    limit=2)
                out.update(measure_shard_plane("serving_shard"))
        except Exception:
            _log(traceback.format_exc())
            out["shard_error"] = traceback.format_exc(limit=3)
        return out
    finally:
        if job is not None:
            job.stop()
        shutil.rmtree(tmp, ignore_errors=True)


# ---------------------------------------------------------------------------
# Serving-ingest section: the vectorized ingest plane (ISSUE 2) — cold-start
# journal->queryable replay throughput and publish->queryable propagation,
# A/B scalar-vs-columnar, with/without the top-k index listener attached
# ---------------------------------------------------------------------------

def run_serving_ingest_section(small: bool) -> dict:
    """Cold-start replay rows/sec + propagation percentiles for the two
    Python ingest paths.

    Four replay arms over one journal: {scalar, columnar} x {top-k index
    on, off}.  "Index on" is THE serving configuration (the index's change
    listener disables the native bulk path, so the Python plane's speed is
    what an ALS serving worker actually ingests at); "index off" isolates
    the listener's cost.  Arms are cross-checked on a deterministic key
    sample — a columnar speedup that changed table contents would be a
    parser bug, not a win.  Propagation probes append one row and spin
    until it is gettable: publish->queryable latency through a LIVE job's
    poll loop, so the floor is poll_interval_s, not parse cost."""
    from flink_ms_tpu.core import formats as F
    from flink_ms_tpu.serve.consumer import (
        ALS_STATE,
        MemoryStateBackend,
        ServingJob,
        parse_als_record,
    )
    from flink_ms_tpu.serve.client import QueryClient
    from flink_ms_tpu.serve.journal import Journal

    os.environ.setdefault("TPUMS_TOPK_PLATFORM", "cpu")
    rows = int(os.environ.get("BENCH_INGEST_ROWS",
                              20_000 if small else 1_000_000))
    k = int(os.environ.get("BENCH_INGEST_K", 8 if small else 16))
    n_prop = int(os.environ.get("BENCH_INGEST_PROP_PROBES",
                                20 if small else 100))
    topk_k = 10
    tmp = tempfile.mkdtemp(prefix="bench_ingest_")
    out = {"serving_ingest_rows": rows, "serving_ingest_k": k}
    try:
        # 1. journal at replay scale (direct append: generator/producer
        # throughput is measured in the serving section already)
        journal = Journal(os.path.join(tmp, "bus"), "als-models")
        n_ids = rows // 2 + 1
        batch = []
        for i in range(rows):
            vec = [((i * 31 + j * 17) % 1000) / 500.0 - 1.0
                   for j in range(k)]
            batch.append(F.format_als_row(
                i % n_ids, "I" if i % 3 else "U", vec))
            if len(batch) >= 100_000:
                journal.append(batch)
                batch = []
        if batch:
            journal.append(batch)
        # deterministic query user for the top-k arms (the generated id
        # stream does not guarantee a "1-U" row exists)
        journal.append(["1,U," + ";".join(["0.5"] * k)])
        rows += 1
        _log(f"[bench:ingest] journal ready: {rows} rows k={k}")

        # pay the once-per-process JIT warm-up off the measured path — on
        # small hosts the warm thread otherwise competes with the replay
        import threading

        from flink_ms_tpu.serve import topk as topk_mod

        topk_mod._warm_jit_async()
        for t in threading.enumerate():
            if t.name == "topk-jit-warm":
                t.join()

        # deterministic cross-arm sample: parity insurance on the bench
        # path (the exhaustive byte-identical check lives in
        # tests/test_ingest_columnar.py)
        sample_ids = range(1, n_ids, max(n_ids // 1000, 1))
        sample_keys = [f"{i}-I" for i in sample_ids] + \
                      [f"{i}-U" for i in sample_ids]
        digests: dict = {}
        topk_res: dict = {}
        journal_rows = rows  # grows as propagation probes append

        for mode in ("scalar", "columnar"):
            for with_index in (True, False):
                tag = f"serving_ingest_{mode}" + \
                    ("" if with_index else "_noidx")
                job = ServingJob(
                    journal, ALS_STATE, parse_als_record,
                    MemoryStateBackend(), host="127.0.0.1", port=0,
                    poll_interval_s=0.005, ingest_mode=mode,
                    topk_index=with_index,
                ).start()
                try:
                    t0 = time.time()
                    deadline = t0 + 1800
                    while job.ingest_rows < journal_rows:
                        if time.time() > deadline:
                            raise RuntimeError(
                                f"{tag} replay stalled: "
                                f"{job.ingest_rows}/{journal_rows}")
                        time.sleep(0.002)
                    replay_s = time.time() - t0
                    out[f"{tag}_rows_per_sec"] = round(
                        journal_rows / replay_s)
                    stats = job.ingest_stats()
                    assert stats["path"] == mode, stats
                    out[f"{tag}_checkpoints_deferred"] = \
                        stats["checkpoints_deferred"]
                    digests[tag] = {
                        key: job.table.get(key) for key in sample_keys
                    }
                    _log(f"[bench:ingest] {tag}: "
                         f"{out[f'{tag}_rows_per_sec']:,} rows/s "
                         f"({replay_s:.2f}s, "
                         f"{stats['batches']} batches, "
                         f"{stats['checkpoints_deferred']} ckpt deferred)")
                    if with_index:
                        # top-k through the wire: the first query pays the
                        # index build over the replayed table
                        with QueryClient("127.0.0.1", job.port,
                                         timeout_s=600) as c:
                            t0 = time.time()
                            topk_res[mode] = c.topk(ALS_STATE, "1", topk_k)
                            out[f"{tag}_topk_build_s"] = round(
                                time.time() - t0, 3)
                        assert topk_res[mode], f"{tag}: topk empty"
                        # publish->queryable propagation: user-row probes
                        # (suffix "-U" keeps the item index identical
                        # across arms) through the live poll loop
                        pm = []
                        payload = ";".join(["0.25"] * k)
                        for p in range(n_prop):
                            key = f"{10_000_000 + journal_rows + p}-U"
                            t0 = time.perf_counter()
                            journal.append([f"{key[:-2]},U,{payload}"])
                            while job.table.get(key) is None:
                                if time.perf_counter() - t0 > 60:
                                    raise RuntimeError(
                                        f"{tag} propagation probe lost")
                                time.sleep(0.0002)
                            pm.append(
                                (time.perf_counter() - t0) * 1000.0)
                        journal_rows += n_prop
                        out.update({
                            f"{tag}_prop_{q}_ms": v
                            for q, v in _pcts(pm).items()
                        })
                        _log(f"[bench:ingest] {tag} propagation "
                             f"{_pcts(pm)} ms")
                finally:
                    job.stop()

        # cross-arm checks: same bytes in, same table out, same top-k
        ref_tag, ref_digest = next(iter(digests.items()))
        for tag, digest in digests.items():
            if digest != ref_digest:
                diff = sum(
                    1 for key in ref_digest
                    if digest[key] != ref_digest[key])
                raise AssertionError(
                    f"ingest parity: {tag} differs from {ref_tag} on "
                    f"{diff}/{len(ref_digest)} sampled keys")
        out["serving_ingest_parity_keys"] = len(ref_digest)
        out["serving_ingest_topk_match"] = (
            topk_res["scalar"] == topk_res["columnar"])
        if not out["serving_ingest_topk_match"]:
            raise AssertionError(
                f"top-k mismatch after replay: scalar={topk_res['scalar']} "
                f"columnar={topk_res['columnar']}")
        out["serving_ingest_speedup"] = round(
            out["serving_ingest_columnar_rows_per_sec"]
            / max(out["serving_ingest_scalar_rows_per_sec"], 1), 2)
        _log(f"[bench:ingest] columnar/scalar speedup "
             f"{out['serving_ingest_speedup']}x (index on), "
             f"topk match, parity on {len(ref_digest)} keys")
        return out
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# ---------------------------------------------------------------------------
# Serving-HA section: availability under replica failure, R=1 vs R=2
# ---------------------------------------------------------------------------

def run_serving_ha_section(small: bool) -> dict:
    """Availability under replica failure: spawn the HA serving plane
    (serve/ha.py) at replication 1 and 2, SIGKILL one replica a third of
    the way through a sustained closed-loop query stream, and report error
    rate / latency percentiles / recovery time per arm.  R=1 reproduces
    the reference design's single-owner outage (queries fail until the
    supervisor respawns and replays); R=2 is the zero-client-visible-
    errors contract pinned by tests/test_ha_serving.py."""
    import signal
    import threading

    from flink_ms_tpu.core import formats as F
    from flink_ms_tpu.serve import registry
    from flink_ms_tpu.serve.client import RetryPolicy
    from flink_ms_tpu.serve.consumer import ALS_STATE
    from flink_ms_tpu.serve.ha import ReplicaSupervisor
    from flink_ms_tpu.serve.journal import Journal

    from flink_ms_tpu.obs.workload import OpenLoopPacer

    n_users = int(os.environ.get("BENCH_HA_USERS", 500 if small else 5_000))
    duration_s = float(
        os.environ.get("BENCH_HA_DURATION_S", 6 if small else 20))
    workers = int(os.environ.get("BENCH_HA_WORKERS", 2))
    rate_qps = float(os.environ.get("BENCH_HA_RATE_QPS", 300))

    tmp = tempfile.mkdtemp(prefix="bench_ha_")
    # fast liveness cadence so detection/recovery fit the bench window; the
    # spawned replicas inherit these via the environment
    saved = {key: os.environ.get(key) for key in
             ("TPUMS_HEARTBEAT_S", "TPUMS_REPLICA_TTL_S",
              "TPUMS_REGISTRY_DIR")}
    os.environ["TPUMS_HEARTBEAT_S"] = os.environ.get(
        "BENCH_HA_HEARTBEAT_S", "0.2")
    os.environ["TPUMS_REPLICA_TTL_S"] = os.environ.get(
        "BENCH_HA_TTL_S", "1.2")
    os.environ["TPUMS_REGISTRY_DIR"] = os.path.join(tmp, "registry")
    out = {}
    try:
        journal = Journal(os.path.join(tmp, "bus"), "models")
        rng = np.random.default_rng(0)
        dim = 8
        journal.append(
            [F.format_als_row(u, "U", rng.normal(size=dim))
             for u in range(n_users)]
            + [F.format_als_row(i, "I", rng.normal(size=dim))
               for i in range(n_users)])
        keys = [f"{u}-U" for u in range(n_users)]

        for replication in (1, 2):
            tag = f"r{replication}"
            sup = ReplicaSupervisor(
                workers, replication, journal.dir, "models",
                os.path.join(tmp, f"ports-{tag}"), state_backend="memory",
                check_interval_s=registry.heartbeat_interval_s(),
                respawn_delay_s=0.1)
            ms, svc_ms, counts = [], [], {"ok": 0, "err": 0}
            stop = threading.Event()

            # tight retry budget (~30 ms of backoff): enough for R=2 to
            # fail over to the sibling replica, NOT enough to ride out the
            # R=1 respawn+replay outage — that contrast is the metric
            def load():
                rnd = np.random.default_rng(1)
                # OPEN loop: a paced schedule that never skips a slot, with
                # latency measured from the INTENDED send time — the R=1
                # outage builds real backlog and it shows in p99 instead of
                # being coordinated-omission'd away by the blocked client
                pacer = OpenLoopPacer(rate_qps)
                with sup.client(
                        retry=RetryPolicy(attempts=3, backoff_s=0.01,
                                          max_backoff_s=0.1),
                        timeout_s=10) as c:
                    while not stop.is_set():
                        key = keys[int(rnd.integers(len(keys)))]
                        t_int = pacer.next_slot()
                        t0 = time.perf_counter()
                        try:
                            if c.query_state(ALS_STATE, key) is None:
                                counts["err"] += 1
                            else:
                                counts["ok"] += 1
                        except Exception:
                            counts["err"] += 1
                        done = time.perf_counter()
                        ms.append((done - t_int) * 1000.0)
                        svc_ms.append((done - t0) * 1000.0)

            with sup.start():
                assert sup.wait_all_ready(120), "HA cluster never ready"
                t_end = time.time() + duration_s
                th = threading.Thread(target=load, daemon=True)
                th.start()
                time.sleep(duration_s / 3.0)
                victim = sup.procs[(0, 0)]
                victim.send_signal(signal.SIGKILL)
                t_kill = time.time()
                _log(f"[bench:ha] {tag}: SIGKILL s0r0 pid={victim.pid}")
                # recovery = kill -> a *new* pid for that replica slot is
                # registered ready (fully replayed, HEALTH-gated)
                t_ready = None
                while time.time() < t_kill + 60:
                    members = registry.resolve_replicas(sup.group_of(0))
                    if any(e.get("replica") == 0 and e.get("ready")
                           and e.get("pid") != victim.pid
                           for e in members):
                        t_ready = time.time()
                        break
                    time.sleep(0.05)
                while time.time() < t_end:
                    time.sleep(0.05)
                stop.set()
                th.join(timeout=30)

            total = counts["ok"] + counts["err"]
            out[f"serving_ha_{tag}_queries"] = total
            out[f"serving_ha_{tag}_errors"] = counts["err"]
            out[f"serving_ha_{tag}_availability"] = (
                round(counts["ok"] / total, 6) if total else None)
            out.update(
                {f"serving_ha_{tag}_{q}_ms": v
                 for q, v in _pcts(ms).items()})
            out.update(
                {f"serving_ha_{tag}_svc_{q}_ms": v
                 for q, v in _pcts(svc_ms).items()})
            out[f"serving_ha_{tag}_recovery_s"] = (
                None if t_ready is None else round(t_ready - t_kill, 2))
            _log(f"[bench:ha] {tag}: {total} queries, "
                 f"{counts['err']} errors, availability "
                 f"{out[f'serving_ha_{tag}_availability']}, recovery "
                 f"{out[f'serving_ha_{tag}_recovery_s']}s")
        out["serving_ha_openloop_rate_qps"] = rate_qps
        return out
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        shutil.rmtree(tmp, ignore_errors=True)


def run_serving_elastic_section(small: bool) -> dict:
    """Latency envelope of a live rescale: run the elastic serving plane
    (serve/elastic.py) at 2 shards under a sustained closed-loop query
    stream, scale out to 4 mid-run, and report p50/p99 for the before /
    during / after windows plus the cutover duration and client-visible
    error count.  The contract pinned by tests/test_elastic_serving.py —
    zero failed queries across the generation swap — is what "during"
    quantifies the latency cost of."""
    import threading

    from flink_ms_tpu.core import formats as F
    from flink_ms_tpu.serve.client import RetryPolicy
    from flink_ms_tpu.serve.consumer import ALS_STATE
    from flink_ms_tpu.serve.elastic import ElasticClient, ScaleController
    from flink_ms_tpu.serve.journal import Journal

    from flink_ms_tpu.obs.workload import OpenLoopPacer

    n_users = int(
        os.environ.get("BENCH_ELASTIC_USERS", 400 if small else 4_000))
    window_s = float(
        os.environ.get("BENCH_ELASTIC_WINDOW_S", 3 if small else 10))
    rate_qps = float(os.environ.get("BENCH_ELASTIC_RATE_QPS", 300))

    tmp = tempfile.mkdtemp(prefix="bench_elastic_")
    saved = {key: os.environ.get(key) for key in
             ("TPUMS_HEARTBEAT_S", "TPUMS_REPLICA_TTL_S",
              "TPUMS_REGISTRY_DIR")}
    os.environ["TPUMS_HEARTBEAT_S"] = os.environ.get(
        "BENCH_ELASTIC_HEARTBEAT_S", "0.2")
    os.environ["TPUMS_REPLICA_TTL_S"] = os.environ.get(
        "BENCH_ELASTIC_TTL_S", "1.2")
    os.environ["TPUMS_REGISTRY_DIR"] = os.path.join(tmp, "registry")
    out = {}
    try:
        journal = Journal(os.path.join(tmp, "bus"), "models")
        rng = np.random.default_rng(0)
        dim = 8
        journal.append(
            [F.format_als_row(u, "U", rng.normal(size=dim))
             for u in range(n_users)]
            + [F.format_als_row(i, "I", rng.normal(size=dim))
               for i in range(n_users)])
        keys = [f"{u}-U" for u in range(n_users)]

        ctl = ScaleController("bench-elastic", journal.dir, "models",
                              port_dir=os.path.join(tmp, "ports"),
                              ready_timeout_s=180)
        phases = {"before": [], "during": [], "after": []}
        svc_phases = {"before": [], "during": [], "after": []}
        phase = ["before"]
        counts = {"ok": 0, "err": 0}
        stop = threading.Event()

        def load():
            rnd = np.random.default_rng(1)
            # open-loop pacing: the cutover stall shows up as backlog in
            # the "during" p99 (latency from intended send), with the
            # old send->reply statistic kept alongside as *_svc_*
            pacer = OpenLoopPacer(rate_qps)
            with ElasticClient(
                    "bench-elastic",
                    retry=RetryPolicy(attempts=6, backoff_s=0.02,
                                      max_backoff_s=0.5),
                    timeout_s=10) as c:
                while not stop.is_set():
                    key = keys[int(rnd.integers(len(keys)))]
                    t_int = pacer.next_slot()
                    t0 = time.perf_counter()
                    try:
                        if c.query_state(ALS_STATE, key) is None:
                            counts["err"] += 1
                        else:
                            counts["ok"] += 1
                    except Exception:
                        counts["err"] += 1
                    done = time.perf_counter()
                    phases[phase[0]].append((done - t_int) * 1000.0)
                    svc_phases[phase[0]].append((done - t0) * 1000.0)

        try:
            rec = ctl.scale_to(2)
            assert rec["shards"] == 2, "bootstrap failed"
            th = threading.Thread(target=load, daemon=True)
            th.start()
            time.sleep(window_s)

            phase[0] = "during"
            t0 = time.time()
            rec = ctl.scale_to(4)
            cutover_s = time.time() - t0
            assert rec["shards"] == 4 and rec["gen"] == 2, "cutover failed"
            phase[0] = "after"
            time.sleep(window_s)
            stop.set()
            th.join(timeout=30)
        finally:
            stop.set()
            ctl.stop(drop_topology=True)

        total = counts["ok"] + counts["err"]
        out["serving_elastic_queries"] = total
        out["serving_elastic_errors"] = counts["err"]
        out["serving_elastic_availability"] = (
            round(counts["ok"] / total, 6) if total else None)
        out["serving_elastic_cutover_s"] = round(cutover_s, 2)
        out["serving_elastic_openloop_rate_qps"] = rate_qps
        for name, ms in phases.items():
            out.update({f"serving_elastic_{name}_{q}_ms": v
                        for q, v in _pcts(ms).items()})
        for name, ms in svc_phases.items():
            out.update({f"serving_elastic_{name}_svc_{q}_ms": v
                        for q, v in _pcts(ms).items()})
        _log(f"[bench:elastic] {total} queries, {counts['err']} errors, "
             f"cutover {out['serving_elastic_cutover_s']}s, p99 "
             f"before/during/after "
             f"{out.get('serving_elastic_before_p99_ms')}/"
             f"{out.get('serving_elastic_during_p99_ms')}/"
             f"{out.get('serving_elastic_after_p99_ms')} ms")
        return out
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        shutil.rmtree(tmp, ignore_errors=True)


def run_serving_rehearsal_section(small: bool) -> dict:
    """Closed-loop production rehearsal (obs/workload.py + obs/slo.py):
    zipfian mixed-verb open-loop traffic with a correlated burst against a
    live 2-shard replicated elastic group, while the autoscaler (tripped
    by the burst) performs a live scale-out and a chaos kill takes down a
    serving replica — all attributed on one timeline and gated by per-verb
    SLOs.  Emits the machine-readable ``SLO_REPORT.json`` artifact; the
    flat keys below are the bench-level summary of it."""
    from flink_ms_tpu.obs.slo import human_summary
    from flink_ms_tpu.obs.workload import run_rehearsal

    out_path = os.environ.get("BENCH_REHEARSAL_OUT", "SLO_REPORT.json")
    report = run_rehearsal(
        out_path=out_path,
        shards=int(os.environ.get("BENCH_REHEARSAL_SHARDS", 2)),
        replication=int(os.environ.get("BENCH_REHEARSAL_REPLICATION", 2)),
        users=int(os.environ.get(
            "BENCH_REHEARSAL_USERS", 200 if small else 2_000)),
        base_qps=float(os.environ.get(
            "BENCH_REHEARSAL_BASE_QPS", 80 if small else 200)),
        peak_qps=float(os.environ.get(
            "BENCH_REHEARSAL_PEAK_QPS", 160 if small else 400)),
        burst_qps=float(os.environ.get(
            "BENCH_REHEARSAL_BURST_QPS", 420 if small else 1_000)),
        warm_s=2.0 if small else 4.0,
        ramp_s=3.0 if small else 6.0,
        burst_s=5.0 if small else 10.0,
        cool_s=3.0 if small else 6.0,
        threads=int(os.environ.get(
            "BENCH_REHEARSAL_THREADS", 4 if small else 8)),
        autoscale=os.environ.get("BENCH_REHEARSAL_AUTOSCALE", "live"),
        kill=os.environ.get("BENCH_REHEARSAL_KILL", "1") != "0",
        seed=0,
    )
    for line in human_summary(report).splitlines():
        _log(f"[bench:rehearsal] {line}")

    wl = report["workload"]
    timeline = report["timeline"]
    out = {
        "serving_rehearsal_ok": report["ok"],
        "serving_rehearsal_scheduled": wl["scheduled"],
        "serving_rehearsal_completed": wl["completed"],
        "serving_rehearsal_achieved_qps": wl["achieved_qps"],
        "serving_rehearsal_max_sched_lag_s": wl["max_sched_lag_s"],
        "serving_rehearsal_errors": report["errors"]["total"],
        "serving_rehearsal_unattributed_errors":
            report["errors"]["unattributed"],
        "serving_rehearsal_breaches": len(report["breaches"]),
        "serving_rehearsal_unattributed_breaches": sum(
            1 for b in report["breaches"] if not b["attributed_to"]),
        "serving_rehearsal_kills": sum(
            1 for e in timeline if "kill" in e.get("kind", "")),
        "serving_rehearsal_cutovers": sum(
            1 for e in timeline if e.get("kind") == "elastic_cutover"),
        "serving_rehearsal_report": report.get("report_path", out_path),
    }
    for verb, v in report["verbs"].items():
        tag = verb.lower()
        out[f"serving_rehearsal_{tag}_availability"] = v["availability"]
        out[f"serving_rehearsal_{tag}_p99_ms"] = v["p99_ms"]
        out[f"serving_rehearsal_{tag}_svc_p99_ms"] = v["service_p99_ms"]
        out[f"serving_rehearsal_{tag}_fleet_p99_ms"] = v["fleet_p99_ms"]
        out[f"serving_rehearsal_{tag}_burn_rate"] = v["burn_rate"]
        out[f"serving_rehearsal_{tag}_p99_bucket_delta"] = \
            v["p99_bucket_delta"]
    return out


def run_serving_watch_section(small: bool) -> dict:
    """Continuous-watch plane cost and efficacy (obs/watch.py):

    1. **overhead (ABAB)** — GET round trips against one in-process
       serving job with the watch loop (0.2 s cadence: fleet scrape +
       canary probe + rules) running vs stopped, interleaved arms; the
       bar is the same <= 3% p50 budget as the metrics on/off harness
       (scripts/obs_overhead_ab.py).
    2. **canary parity** — the live ``tpums_model_live_mse`` probe vs
       ``eval.mse.compute_mse`` over the SAME probe slice read straight
       off the serving table: identical payload strings through identical
       grouping must agree to float-exactness (abs diff gate).
    3. **drift demo** — deliberately-worse factors appended through the
       journal (the live model-publication path); the canary's MSE must
       cross the drift rule's threshold and fire a model_drift alert.
    4. **rehearsal with watch** — the closed-loop rehearsal (kill
       enabled) with a live watcher: the SIGKILL must be detected (page
       alert) within the bound, attributed on the incident timeline
       (zero unattributed pages), and the SLO report gains its
       ``alerts`` section.
    """
    from flink_ms_tpu.core import formats as F
    from flink_ms_tpu.eval.mse import compute_mse
    from flink_ms_tpu.obs.metrics import bucketed_quantiles
    from flink_ms_tpu.obs.rules import Rule, default_rules
    from flink_ms_tpu.obs.watch import FleetWatcher, ModelQualityCanary
    from flink_ms_tpu.obs.workload import run_rehearsal
    from flink_ms_tpu.serve.client import QueryClient
    from flink_ms_tpu.serve.consumer import (ALS_STATE, ServingJob,
                                             make_backend,
                                             parse_als_record)
    from flink_ms_tpu.serve.journal import Journal

    n_users = 200 if small else 1_000
    dim = 4
    n_ratings = 400 if small else 1_500
    n_q = int(os.environ.get("BENCH_WATCH_QUERIES", 300 if small else 800))
    rounds = int(os.environ.get("BENCH_WATCH_ROUNDS", 4))
    overhead_bar_pct = float(os.environ.get("BENCH_WATCH_OVERHEAD_BAR", 3.0))
    detect_bound_s = float(os.environ.get("BENCH_WATCH_DETECT_S", 10.0))

    tmp = tempfile.mkdtemp(prefix="tpums_watch_bench_")
    saved_reg = os.environ.get("TPUMS_REGISTRY_DIR")
    os.environ["TPUMS_REGISTRY_DIR"] = os.path.join(tmp, "registry")
    out: dict = {}
    job = None
    try:
        rng = np.random.default_rng(0)
        uf = rng.normal(size=(n_users, dim))
        itf = rng.normal(size=(n_users, dim))
        journal = Journal(os.path.join(tmp, "bus"), "models")
        journal.append(
            [F.format_als_row(u, "U", uf[u]) for u in range(n_users)]
            + [F.format_als_row(i, "I", itf[i]) for i in range(n_users)])
        users = rng.integers(0, n_users, size=n_ratings)
        items = rng.integers(0, n_users, size=n_ratings)
        # ratings near the model's own predictions: the healthy live MSE
        # is ~noise², leaving the drift threshold orders of magnitude of
        # headroom below the post-drift error
        ratings = (np.einsum("nd,nd->n", uf[users], itf[items])
                   + rng.normal(0.0, 0.05, size=n_ratings))
        job = ServingJob(
            journal, ALS_STATE, parse_als_record,
            make_backend("memory", None),
            host="127.0.0.1", port=0, poll_interval_s=0.01,
        ).start()
        assert job.wait_ready(120)

        def client_factory():
            return QueryClient("127.0.0.1", job.port, timeout_s=30)

        canary = ModelQualityCanary(users, items, ratings,
                                    client_factory, max_probe=256)
        # the overhead arm carries the scrape/retain/evaluate loop only:
        # the bar bounds the passive watch cost; the canary is an explicit
        # probe WORKLOAD (a 256-key MGET against the serving path) whose
        # cost is its own line item, measured in phase 2
        watcher = FleetWatcher(interval_s=0.2, scope="bench_watch")

        # -- 1. ABAB overhead on the GET hot path ------------------------
        lat: dict = {"on": [], "off": []}
        qrng = np.random.default_rng(1)
        with QueryClient("127.0.0.1", job.port, timeout_s=60) as c:
            for _ in range(50):  # steady-state warmup, uncounted
                c.query_state(ALS_STATE, "1-U")
            for r in range(rounds):
                order = ("on", "off") if r % 2 == 0 else ("off", "on")
                for arm in order:
                    if arm == "on":
                        watcher.start()
                    for _ in range(n_q):
                        key = f"{int(qrng.integers(0, n_users))}-U"
                        t0 = time.perf_counter()
                        c.query_state(ALS_STATE, key)
                        lat[arm].append(time.perf_counter() - t0)
                    if arm == "on":
                        watcher.stop()
        p50_on, = bucketed_quantiles(lat["on"], (50,))
        p50_off, = bucketed_quantiles(lat["off"], (50,))
        overhead_pct = (p50_on / p50_off - 1.0) * 100.0
        out["serving_watch_get_p50_on_us"] = round(p50_on * 1e6, 2)
        out["serving_watch_get_p50_off_us"] = round(p50_off * 1e6, 2)
        out["serving_watch_overhead_pct"] = round(overhead_pct, 3)
        out["serving_watch_overhead_bar_pct"] = overhead_bar_pct
        out["serving_watch_overhead_ok"] = overhead_pct <= overhead_bar_pct
        _log(f"[bench:watch] GET p50 on/off "
             f"{p50_on * 1e6:.1f}/{p50_off * 1e6:.1f} us "
             f"-> overhead {overhead_pct:+.2f}% (bar {overhead_bar_pct}%)")

        # -- 2. canary parity vs eval/mse on the same slice --------------
        probe = canary.probe()

        def offline_lookup(key):
            return ModelQualityCanary._parse(job.table.get(key))

        mse_off, n_off, _ = compute_mse(
            canary.users, canary.items, canary.ratings, offline_lookup)
        abs_diff = (abs(probe["mse"] - mse_off)
                    if probe["mse"] is not None and mse_off is not None
                    else None)
        out["serving_watch_mse_live"] = probe["mse"]
        out["serving_watch_mse_offline"] = mse_off
        out["serving_watch_mse_abs_diff"] = abs_diff
        out["serving_watch_mse_parity_ok"] = (
            abs_diff is not None and abs_diff <= 1e-9
            and probe["n_scored"] == n_off)
        out["serving_watch_probe_coverage"] = round(probe["coverage"], 4)
        _log(f"[bench:watch] live MSE {probe['mse']} vs offline {mse_off} "
             f"(diff {abs_diff}, coverage {probe['coverage']:.2%})")

        # -- 3. drift demo: worse model through the journal --------------
        drift_value = float(mse_off) + 0.5
        drift_rules = [r for r in default_rules() if r.name != "model_drift"]
        drift_rules.append(Rule(
            name="model_drift", kind="threshold",
            series="tpums_model_live_mse", mode="latest",
            op=">", value=drift_value, severity="warn",
            description="bench drift gate"))
        journal.append(
            [F.format_als_row(u, "U", rng.normal(size=dim) * 3.0)
             for u in range(n_users)]
            + [F.format_als_row(i, "I", rng.normal(size=dim) * 3.0)
               for i in range(n_users)])
        deadline = time.time() + 60
        while job.offset < journal.end_offset() and time.time() < deadline:
            time.sleep(0.05)
        drift_watcher = FleetWatcher(interval_s=0.1, canary=canary,
                                     rules=drift_rules,
                                     scope="bench_watch_drift")
        drift_fired = False
        ticks = 0
        while ticks < 50 and not drift_fired:
            trs = drift_watcher.tick()
            ticks += 1
            drift_fired = any(t["kind"] == "alert_firing"
                              and t["rule"] == "model_drift" for t in trs)
            if not drift_fired:
                time.sleep(0.05)
        drift_watcher.stop()
        out["serving_watch_drift_fired"] = drift_fired
        out["serving_watch_drift_threshold"] = round(drift_value, 4)
        out["serving_watch_drift_mse"] = (canary.last or {}).get("mse")
        out["serving_watch_drift_ticks"] = ticks
        _log(f"[bench:watch] drift alert fired={drift_fired} after "
             f"{ticks} ticks (mse {(canary.last or {}).get('mse')}, "
             f"threshold {drift_value:.3f})")
        job.stop()
        job = None
    finally:
        if job is not None:
            try:
                job.stop()
            except Exception:
                pass
        if saved_reg is None:
            os.environ.pop("TPUMS_REGISTRY_DIR", None)
        else:
            os.environ["TPUMS_REGISTRY_DIR"] = saved_reg
        shutil.rmtree(tmp, ignore_errors=True)

    # -- 4. rehearsal with the watch loop + injected kill ----------------
    report = run_rehearsal(
        out_path=os.environ.get("BENCH_WATCH_OUT", "SLO_REPORT_WATCH.json"),
        shards=2, replication=2,
        users=200 if small else 1_000,
        base_qps=60 if small else 150,
        peak_qps=120 if small else 300,
        burst_qps=200 if small else 600,
        warm_s=2.0, ramp_s=3.0, burst_s=4.0, cool_s=3.0,
        threads=4,
        autoscale="off", kill=True, seed=0,
        watch=True, watch_interval_s=0.25,
    )
    alerts = report.get("alerts", {})
    det = alerts.get("detection", {})
    out["serving_watch_rehearsal_ok"] = report["ok"]
    out["serving_watch_alerts_fired"] = alerts.get("fired_total")
    out["serving_watch_unattributed_page"] = alerts.get("unattributed_page")
    out["serving_watch_kills"] = det.get("kills")
    out["serving_watch_detect_s"] = det.get("max_s")
    out["serving_watch_detect_bound_s"] = detect_bound_s
    out["serving_watch_detect_ok"] = (
        det.get("kills", 0) > 0 and det.get("detected", 0) > 0
        and det.get("max_s") is not None
        and det.get("max_s") <= detect_bound_s)
    out["serving_watch_avg_tick_s"] = alerts.get("avg_tick_s")
    out["serving_watch_report"] = report.get("report_path")
    _log(f"[bench:watch] rehearsal kill detection "
         f"{det.get('max_s')}s (bound {detect_bound_s}s), "
         f"unattributed pages {alerts.get('unattributed_page')}")
    return out


def run_serving_bootstrap_section(small: bool) -> dict:
    """Recovery and resharding cost vs journal length: is bootstrap
    O(state) or O(history)?  Three arms, each run at journal lengths of
    BENCH_BOOTSTRAP_MULTS x the base row count:

      cold     in-process ServingJob cold start — full replay (the first
               job, which then publishes a snapshot at ready) vs
               snapshot-shipped bootstrap (a second job over the same
               journal), both timed via job.bootstrap_seconds;
      cutover  elastic 2 -> 4 rescale (serve/elastic.py) with snapshots
               on vs off — the g+1 generation either bulk-loads the
               gen-g snapshot family or replays the whole journal;
      ha       ReplicaSupervisor respawn after SIGKILL (1 shard, R=2,
               snapshots on) — kill -> the respawned pid registers ready.

    Headlines are flatness ratios (time at max mult / time at min mult);
    the snapshot-on paths must stay ~flat (<= 1.5x, ISSUE acceptance)
    while replay paths grow with the journal."""
    import signal
    import threading

    from flink_ms_tpu.core import formats as F
    from flink_ms_tpu.serve import registry
    from flink_ms_tpu.serve import snapshot as snapshot_mod
    from flink_ms_tpu.serve.consumer import (
        ALS_STATE,
        MemoryStateBackend,
        ServingJob,
        parse_als_record,
    )
    from flink_ms_tpu.serve.elastic import ScaleController
    from flink_ms_tpu.serve.ha import ReplicaSupervisor
    from flink_ms_tpu.serve.journal import Journal

    keys_n = int(os.environ.get("BENCH_BOOTSTRAP_KEYS",
                                300 if small else 2_000))
    base_rows = int(os.environ.get("BENCH_BOOTSTRAP_BASE_ROWS",
                                   2_000 if small else 20_000))
    mults = sorted(int(m) for m in os.environ.get(
        "BENCH_BOOTSTRAP_MULTS",
        "1,100" if small else "1,10,100").split(",") if m.strip())
    dim = int(os.environ.get("BENCH_BOOTSTRAP_DIM", 8))
    proc_mults = [mults[0], mults[-1]] if len(mults) > 1 else mults

    tmp = tempfile.mkdtemp(prefix="bench_bootstrap_")
    saved = {key: os.environ.get(key) for key in
             ("TPUMS_HEARTBEAT_S", "TPUMS_REPLICA_TTL_S",
              "TPUMS_REGISTRY_DIR")}
    os.environ["TPUMS_HEARTBEAT_S"] = "0.2"
    os.environ["TPUMS_REPLICA_TTL_S"] = "1.2"
    os.environ["TPUMS_REGISTRY_DIR"] = os.path.join(tmp, "registry")
    out: dict = {}

    rng = np.random.default_rng(0)
    vec = rng.normal(size=dim)

    def build_journal(root: str, rows: int) -> Journal:
        # keys_n live keys, then updates cycling over them: the stream a
        # compactor/snapshot exists for — history >> state
        j = Journal(root, "models")
        batch = [F.format_als_row(u, "U", vec) for u in range(keys_n)]
        for i in range(max(0, rows - keys_n)):
            batch.append(F.format_als_row(i % keys_n, "I", vec))
            if len(batch) >= 10_000:
                j.append(batch, flush=False)
                batch = []
        if batch:
            j.append(batch)
        return j

    def wait_plan(root: str, owner=None, members=1, timeout_s=60.0):
        # ready fires BEFORE the snapshot publish (serve/consumer.py flips
        # _ready first), so poll for the manifest(s) before depending on it
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            plan = snapshot_mod.resolve(root, owner=owner)
            if plan is not None and len(plan["members"]) >= members:
                return plan
            time.sleep(0.05)
        raise AssertionError("snapshot never published")

    def in_process_job(j: Journal) -> ServingJob:
        return ServingJob(j, ALS_STATE, parse_als_record,
                          MemoryStateBackend(), port=0, topk_index=False,
                          poll_interval_s=0.02, snapshots=True,
                          snapshot_min_bytes=1)

    try:
        # -- arm 1: in-process cold start, replay vs snapshot -------------
        cold_replay, cold_snap = {}, {}
        for mult in mults:
            rows = base_rows * mult
            j = build_journal(os.path.join(tmp, f"cold{mult}"), rows)
            job1 = in_process_job(j)
            job1.start()
            assert job1.wait_ready(600), "cold replay bootstrap timed out"
            cold_replay[mult] = job1.bootstrap_seconds
            root = snapshot_mod.snapshot_root(j.dir, "models")
            wait_plan(root, owner=(0, 1))
            job1.stop()
            job2 = in_process_job(j)
            job2.start()
            assert job2.wait_ready(600), "snapshot bootstrap timed out"
            assert job2.bootstrap_source == "snapshot", (
                f"expected snapshot bootstrap, got {job2.bootstrap_source}")
            cold_snap[mult] = job2.bootstrap_seconds
            job2.stop()
            out[f"serving_bootstrap_rows_{mult}x"] = rows
            out[f"serving_bootstrap_cold_replay_s_{mult}x"] = round(
                cold_replay[mult], 4)
            out[f"serving_bootstrap_cold_snap_s_{mult}x"] = round(
                cold_snap[mult], 4)
            _log(f"[bench:bootstrap] cold {mult}x ({rows} rows): replay "
                 f"{cold_replay[mult]:.3f}s snapshot {cold_snap[mult]:.3f}s")

        # -- arm 2: elastic 2 -> 4 cutover, snapshots on vs off -----------
        cutover = {True: {}, False: {}}
        for mult in proc_mults:
            rows = base_rows * mult
            for snaps_on in (True, False):
                tag = "on" if snaps_on else "off"
                run_dir = os.path.join(tmp, f"cut{mult}{tag}")
                j = build_journal(os.path.join(run_dir, "bus"), rows)
                ctl = ScaleController(
                    f"bench-boot-{mult}-{tag}", j.dir, "models",
                    port_dir=os.path.join(run_dir, "ports"),
                    ready_timeout_s=600, snapshots=snaps_on,
                    snapshot_min_bytes=1 if snaps_on else None)
                try:
                    rec = ctl.scale_to(2)
                    assert rec["shards"] == 2, "gen-1 bootstrap failed"
                    if snaps_on:
                        # both gen-1 shards must have published before the
                        # g+1 generation can family-load their snapshots
                        wait_plan(snapshot_mod.snapshot_root(
                            j.dir, "models"), members=2)
                    t0 = time.time()
                    rec = ctl.scale_to(4)
                    cutover[snaps_on][mult] = time.time() - t0
                    assert rec["shards"] == 4, "cutover failed"
                finally:
                    ctl.stop(drop_topology=True)
                out[f"serving_bootstrap_cutover_s_{mult}x_{tag}"] = round(
                    cutover[snaps_on][mult], 2)
                _log(f"[bench:bootstrap] cutover {mult}x snapshots={tag}: "
                     f"{cutover[snaps_on][mult]:.2f}s")

        # -- arm 3: HA respawn recovery, snapshots on ---------------------
        ha_rec = {}
        for mult in proc_mults:
            rows = base_rows * mult
            run_dir = os.path.join(tmp, f"ha{mult}")
            j = build_journal(os.path.join(run_dir, "bus"), rows)
            sup = ReplicaSupervisor(
                1, 2, j.dir, "models",
                port_dir=os.path.join(run_dir, "ports"),
                job_group=f"bench-boot-ha-{mult}",
                state_backend="memory", check_interval_s=0.2,
                respawn_delay_s=0.05,
                extra_args=["--snapshotMinBytes", "1"])
            try:
                sup.start()
                assert sup.wait_all_ready(600), "HA fleet never ready"
                wait_plan(snapshot_mod.snapshot_root(j.dir, "models"),
                          owner=(0, 1))
                victim = sup.procs[(0, 0)]
                old_pid = victim.pid
                t_kill = time.time()
                victim.send_signal(signal.SIGKILL)
                deadline = t_kill + 600
                while time.time() < deadline:
                    # a NEW pid registering ready is the unambiguous
                    # recovery signal (the stale record still says ready
                    # until the respawn overwrites it)
                    members = registry.resolve_replicas(sup.group_of(0))
                    if any(e.get("replica") == 0 and e.get("ready")
                           and e.get("pid") not in (None, old_pid)
                           for e in members):
                        ha_rec[mult] = time.time() - t_kill
                        break
                    time.sleep(0.02)
                assert mult in ha_rec, "respawned replica never re-ready"
            finally:
                sup.stop()
            out[f"serving_bootstrap_ha_recovery_s_{mult}x"] = round(
                ha_rec[mult], 2)
            _log(f"[bench:bootstrap] ha {mult}x: recovery "
                 f"{ha_rec[mult]:.2f}s")

        # -- headlines: flatness = t(max mult) / t(min mult) --------------
        def flatness(d: dict):
            lo, hi = min(d), max(d)
            if lo == hi or not d[lo]:
                return None
            return round(d[hi] / max(d[lo], 1e-6), 3)

        out["serving_bootstrap_cold_flatness"] = flatness(cold_snap)
        out["serving_bootstrap_cold_replay_ratio"] = flatness(cold_replay)
        out["serving_bootstrap_cutover_flatness"] = flatness(cutover[True])
        out["serving_bootstrap_cutover_flatness_off"] = flatness(
            cutover[False])
        out["serving_bootstrap_ha_flatness"] = flatness(ha_rec)
        _log(f"[bench:bootstrap] flatness cold/cutover/ha = "
             f"{out['serving_bootstrap_cold_flatness']}/"
             f"{out['serving_bootstrap_cutover_flatness']}/"
             f"{out['serving_bootstrap_ha_flatness']} "
             f"(replay-cold {out['serving_bootstrap_cold_replay_ratio']}, "
             f"cutover-off {out['serving_bootstrap_cutover_flatness_off']})")
        return out
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        shutil.rmtree(tmp, ignore_errors=True)


# ---------------------------------------------------------------------------
# serving-native section: wire protocol v2 A/B on the full native query path
# ---------------------------------------------------------------------------

def _get_loop(port, state, keys, total, proto_mode):
    """Strict request/reply GETs (1 in flight) -> (qps, p50_us)."""
    from flink_ms_tpu.serve.client import QueryClient

    lat_us = []
    with QueryClient("127.0.0.1", port, timeout_s=600,
                     proto=proto_mode) as c:
        c.ping()  # connect + HELLO negotiation outside the clock
        for i in range(min(total, 200)):  # warm both planes' caches
            c._roundtrip(f"GET\t{state}\t{keys[i % len(keys)]}")
        t_all = time.perf_counter()
        for i in range(total):
            t0 = time.perf_counter()
            r = c._roundtrip(f"GET\t{state}\t{keys[i % len(keys)]}")
            lat_us.append((time.perf_counter() - t0) * 1e6)
            if not r or r[0] not in "VN":
                raise RuntimeError(f"bad reply: {r!r}")
        elapsed = time.perf_counter() - t_all
    return round(total / elapsed, 1), round(
        float(np.percentile(lat_us, 50)), 2)


def _get_pipelined(port, state, keys, window, batches, proto_mode):
    """GETs down one connection with `window` in flight -> (qps, p50_us)
    where p50 is the per-request cost of the median window (pipelining
    amortizes framing + syscalls over the whole window — in B2 mode each
    window is ONE frame on the wire each way)."""
    from flink_ms_tpu.serve.client import QueryClient

    per_batch_us = []
    with QueryClient("127.0.0.1", port, timeout_s=600,
                     proto=proto_mode) as c:
        c.ping()
        reqs = [f"GET\t{state}\t{keys[i % len(keys)]}"
                for i in range(window)]
        c.pipeline(reqs, window=window)  # warm-up window
        t_all = time.perf_counter()
        for _ in range(batches):
            t0 = time.perf_counter()
            replies = c.pipeline(reqs, window=window)
            per_batch_us.append(
                (time.perf_counter() - t0) * 1e6 / window)
            bad = [r for r in replies if not r or r[0] not in "VN"]
            if bad:
                raise RuntimeError(f"bad replies: {bad[:3]!r}")
        elapsed = time.perf_counter() - t_all
    return round(batches * window / elapsed, 1), round(
        float(np.percentile(per_batch_us, 50)), 2)


def run_serving_native_section(small: bool) -> dict:
    """The round-8 wire-protocol A/B: tab (v1) vs binary batched (B2)
    framing over the SAME servers, plus a native-fleet elastic cutover
    smoke.  Three subsections:

      get     point lookups against the C++ epoll server at 1/16/64 in
              flight.  At 1 in flight the two framings are within noise
              (both are one small write + one small read); the win is the
              pipelined window, where B2 ships the whole window as one
              frame each way.  Headline:
              ``serving_native_get_b2_c64_p50_us`` (< 15 us acceptance).
      topk    batched TOPK against the Python plane's microbatcher
              (TPUMS_TOPK_BATCH_MAX=64) at 64 in flight.  For a v1 client
              "64 in flight" means 64 strict request/reply connections
              (the line protocol has no in-connection batching); one B2
              connection with window=64 ships each window as a single
              frame and hands the microbatcher all 64 queries atomically.
              A single-connection tab pipeline (``topk_tabpipe``) is
              recorded for context.  Headline:
              ``serving_native_topk_b2_speedup_c64`` (>= 2x acceptance).
      cutover subprocess native fleet (--stateBackend rocksdb
              --nativeServer true) rescaled 2 -> 4 under a query stream:
              zero client-visible errors, cutover wall-clock recorded.
    """
    import threading

    from flink_ms_tpu.core import formats as F
    from flink_ms_tpu.serve.client import QueryClient, RetryPolicy
    from flink_ms_tpu.serve.consumer import ALS_STATE
    from flink_ms_tpu.serve.server import LookupServer
    from flink_ms_tpu.serve.table import ModelTable

    out: dict = {}
    n_keys = int(os.environ.get("BENCH_NATIVE_KEYS",
                                1_024 if small else 8_192))
    get_total = int(os.environ.get("BENCH_NATIVE_GETS",
                                   2_000 if small else 20_000))
    topk_total = int(os.environ.get("BENCH_NATIVE_TOPKS",
                                    256 if small else 1_024))
    dim = 16
    rng = np.random.default_rng(0)
    tmp = tempfile.mkdtemp(prefix="bench_native_")
    saved = {key: os.environ.get(key) for key in
             ("TPUMS_HEARTBEAT_S", "TPUMS_REPLICA_TTL_S",
              "TPUMS_REGISTRY_DIR", "TPUMS_TOPK_BATCH_MAX")}

    def payload(vec):
        return ";".join(repr(round(float(x), 4)) for x in vec)

    try:
        # -- GET framing A/B on the C++ server ----------------------------
        try:
            from flink_ms_tpu.serve.native_store import (NativeLookupServer,
                                                         NativeStore)

            store = NativeStore(os.path.join(tmp, "store"))
            keys = []
            for u in range(n_keys):
                store.put(f"{u}-U", payload(rng.normal(size=dim)))
                keys.append(f"{u}-U")
            with NativeLookupServer(store, ALS_STATE, job_id="bench",
                                    port=0) as nsrv:
                for mode in ("tab", "b2"):
                    qps, p50 = _get_loop(nsrv.port, ALS_STATE, keys,
                                         get_total, mode)
                    out[f"serving_native_get_{mode}_c1_qps"] = qps
                    out[f"serving_native_get_{mode}_c1_p50_us"] = p50
                    for win in (16, 64):
                        qps, p50 = _get_pipelined(
                            nsrv.port, ALS_STATE, keys, win,
                            max(get_total // win, 20), mode)
                        out[f"serving_native_get_{mode}_c{win}_qps"] = qps
                        out[f"serving_native_get_{mode}_c{win}_p50_us"] = p50
                    _log(f"[bench:native] GET {mode}: c1 "
                         f"{out[f'serving_native_get_{mode}_c1_qps']} qps, "
                         f"c64 {out[f'serving_native_get_{mode}_c64_qps']} "
                         f"qps / "
                         f"{out[f'serving_native_get_{mode}_c64_p50_us']} "
                         "us/req p50")
            store.close()
            tab64 = out.get("serving_native_get_tab_c64_qps")
            b64 = out.get("serving_native_get_b2_c64_qps")
            if tab64 and b64:
                out["serving_native_get_b2_speedup_c64"] = round(
                    b64 / tab64, 2)
        except Exception:
            _log(traceback.format_exc())
            out["serving_native_get_error"] = traceback.format_exc(limit=3)

        # -- batched TOPK framing A/B through the microbatcher ------------
        try:
            os.environ["TPUMS_TOPK_BATCH_MAX"] = "64"
            from flink_ms_tpu.serve.topk import make_als_topk_handler

            table = ModelTable(dim)
            n_items = int(os.environ.get("BENCH_NATIVE_ITEMS",
                                         512 if small else 2_048))
            n_users = 256
            for i in range(n_items):
                table.put(f"{i}-I", payload(rng.normal(size=dim)))
            for u in range(n_users):
                table.put(f"{u}-U", payload(rng.normal(size=dim)))
            handler = make_als_topk_handler(table)
            srv = LookupServer({ALS_STATE: table}, host="127.0.0.1",
                               port=0, job_id="bench",
                               topk_handlers={ALS_STATE: handler}).start()
            try:
                k = 10
                handler.index.warm_batch_shapes(k, 64)
                topk_rng = np.random.default_rng(1)
                reqs = [
                    "TOPK\t%s\t%d\t%d" % (
                        ALS_STATE, int(topk_rng.integers(0, n_users)), k)
                    for _ in range(topk_total)
                ]

                # tab headline arm: 64 in flight for a v1 client means 64
                # strict request/reply CONNECTIONS — the line protocol has
                # no in-connection batching, so the microbatcher only sees
                # whatever the 64 sockets happen to deliver concurrently.
                def _tab_worker(my_reqs, barrier, errs, idx):
                    try:
                        with QueryClient("127.0.0.1", srv.port,
                                         timeout_s=600, proto="tab") as c:
                            c._roundtrip(my_reqs[0])  # warm
                            barrier.wait()
                            for r in my_reqs:
                                rep = c._roundtrip(r)
                                if not rep or rep[0] not in "VN":
                                    raise RuntimeError(f"bad topk: {rep!r}")
                    except Exception as e:  # pragma: no cover - surfaced below
                        errs[idx] = e
                        barrier.abort()

                conns = 64
                per_conn = max(topk_total // conns, 4)
                barrier = threading.Barrier(conns + 1)
                errs: dict = {}
                threads = [
                    threading.Thread(
                        target=_tab_worker,
                        args=([reqs[(i * per_conn + j) % len(reqs)]
                               for j in range(per_conn)], barrier, errs, i),
                        daemon=True)
                    for i in range(conns)
                ]
                for t in threads:
                    t.start()
                barrier.wait()
                t0 = time.perf_counter()
                for t in threads:
                    t.join()
                elapsed = time.perf_counter() - t0
                if errs:
                    raise next(iter(errs.values()))
                out["serving_native_topk_tab_c64_qps"] = round(
                    conns * per_conn / elapsed, 1)
                _log(f"[bench:native] TOPK tab c64 (64 conns): "
                     f"{out['serving_native_topk_tab_c64_qps']} qps")

                # tab-pipelined context arm + the B2 arm: one connection,
                # window 64 (B2 ships the window as one frame each way and
                # hands the microbatcher all 64 queries atomically)
                for mode, key in (("tab", "tabpipe"), ("b2", "b2")):
                    with QueryClient("127.0.0.1", srv.port, timeout_s=600,
                                     proto=mode) as c:
                        c.ping()
                        c.pipeline(reqs[:64], window=64)  # warm
                        t0 = time.perf_counter()
                        replies = c.pipeline(reqs, window=64)
                        elapsed = time.perf_counter() - t0
                    bad = [r for r in replies if not r or r[0] not in "VN"]
                    if bad:
                        raise RuntimeError(f"bad topk: {bad[:3]!r}")
                    out[f"serving_native_topk_{key}_c64_qps"] = round(
                        len(replies) / elapsed, 1)
                    _log(f"[bench:native] TOPK {key} c64: "
                         f"{out[f'serving_native_topk_{key}_c64_qps']} qps")
            finally:
                srv.stop()
                if handler.batcher is not None:
                    handler.batcher.close()
            tab = out.get("serving_native_topk_tab_c64_qps")
            b2 = out.get("serving_native_topk_b2_c64_qps")
            if tab and b2:
                out["serving_native_topk_b2_speedup_c64"] = round(
                    b2 / tab, 2)
        except Exception:
            _log(traceback.format_exc())
            out["serving_native_topk_error"] = traceback.format_exc(limit=3)

        # -- native fleet elastic cutover smoke ---------------------------
        try:
            from flink_ms_tpu.serve.elastic import (ElasticClient,
                                                    ScaleController)
            from flink_ms_tpu.serve.journal import Journal

            os.environ["TPUMS_HEARTBEAT_S"] = "0.2"
            os.environ["TPUMS_REPLICA_TTL_S"] = "30"
            os.environ["TPUMS_REGISTRY_DIR"] = os.path.join(tmp, "registry")
            journal = Journal(os.path.join(tmp, "bus"), "models")
            n_rows = 64
            journal.append([F.format_als_row(u, "U", rng.normal(size=4))
                            for u in range(n_rows)])
            jkeys = [f"{u}-U" for u in range(n_rows)]
            ctl = ScaleController(
                "bench-nat", os.path.join(tmp, "bus"), "models",
                port_dir=os.path.join(tmp, "ports"),
                state_backend="rocksdb",
                checkpoint_uri=os.path.join(tmp, "ckpt"),
                extra_args=["--nativeServer", "true"],
                ready_timeout_s=120,
            )
            try:
                ctl.scale_to(2)
                errors = []
                stop = threading.Event()

                def stream():
                    c = ElasticClient(
                        "bench-nat",
                        retry=RetryPolicy(attempts=6, backoff_s=0.02,
                                          max_backoff_s=0.5),
                        timeout_s=10)
                    with c:
                        while not stop.is_set():
                            for kk in jkeys:
                                try:
                                    if c.query_state(ALS_STATE, kk) is None:
                                        errors.append((kk, "missing"))
                                except Exception as e:
                                    errors.append((kk, repr(e)))

                t = threading.Thread(target=stream, daemon=True)
                t.start()
                time.sleep(0.5)
                t0 = time.perf_counter()
                ctl.scale_to(4)
                cutover_s = time.perf_counter() - t0
                time.sleep(0.5)
                stop.set()
                t.join(timeout=30)
                out["serving_native_cutover_s"] = round(cutover_s, 2)
                out["serving_native_cutover_errors"] = len(errors)
                _log(f"[bench:native] elastic 2->4 native cutover "
                     f"{cutover_s:.2f}s, {len(errors)} errors")
            finally:
                ctl.stop(drop_topology=True)
        except Exception:
            _log(traceback.format_exc())
            out["serving_native_cutover_error"] = \
                traceback.format_exc(limit=3)
        return out
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        shutil.rmtree(tmp, ignore_errors=True)

# ---------------------------------------------------------------------------
# Online update plane: co-located sharded SGD workers (ISSUE 9)
# ---------------------------------------------------------------------------

def run_serving_update_plane_section(small: bool) -> dict:
    """Throughput + freshness of the sharded online-update plane
    (serve/update_plane.py) against a live elastic fleet:

    - baseline: the reference-shaped single consumer (online/sgd.py
      --batchSize, the elastic-client path) against the 4-shard fleet —
      the number the plane must beat 10x;
    - reshard: a live producer streams ratings THROUGH a 2->4 cutover;
      the per-partition sequence audit gates zero lost / zero
      double-applied ratings across the generation swap;
    - fleet: hash-routed ratings drained by the co-located workers at 4
      shards, updates/s measured submit->applied-watermark;
    - visibility: client-side submit->queryable probes (rating in, new
      user factor served) on the shared percentile ladder, gated p99.
    """
    import random
    import threading

    from flink_ms_tpu.core import formats as F
    from flink_ms_tpu.core.params import Params
    from flink_ms_tpu.online import sgd as online_sgd
    from flink_ms_tpu.serve import update_plane as up
    from flink_ms_tpu.serve.client import RetryPolicy
    from flink_ms_tpu.serve.consumer import ALS_STATE
    from flink_ms_tpu.serve.elastic import ElasticClient, ScaleController
    from flink_ms_tpu.serve.journal import Journal

    n_users = int(
        os.environ.get("BENCH_UPDATE_USERS", 400 if small else 4_000))
    n_base = int(
        os.environ.get("BENCH_UPDATE_BASELINE_RATINGS",
                       2_000 if small else 10_000))
    n_reshard = int(
        os.environ.get("BENCH_UPDATE_RESHARD_RATINGS",
                       4_000 if small else 20_000))
    n_fleet = int(
        os.environ.get("BENCH_UPDATE_FLEET_RATINGS",
                       24_000 if small else 200_000))
    n_probes = int(os.environ.get("BENCH_UPDATE_PROBES", 40))
    dim = 8

    tmp = tempfile.mkdtemp(prefix="bench_update_")
    saved = {key: os.environ.get(key) for key in
             ("TPUMS_HEARTBEAT_S", "TPUMS_REPLICA_TTL_S",
              "TPUMS_REGISTRY_DIR", "TPUMS_UPDATE_BATCH",
              "TPUMS_UPDATE_POLL_S", "TPUMS_UPDATE_DIM")}
    os.environ["TPUMS_HEARTBEAT_S"] = "0.2"
    os.environ["TPUMS_REPLICA_TTL_S"] = "1.2"
    os.environ["TPUMS_REGISTRY_DIR"] = os.path.join(tmp, "registry")
    # spawned serving workers inherit these for their co-located
    # UpdateWorkers (attach_update_worker reads the env defaults)
    os.environ["TPUMS_UPDATE_BATCH"] = os.environ.get(
        "BENCH_UPDATE_BATCH", "512")
    os.environ["TPUMS_UPDATE_POLL_S"] = "0.005"
    os.environ["TPUMS_UPDATE_DIM"] = str(dim)
    partitions = up.default_partitions()
    out = {}
    try:
        journal = Journal(os.path.join(tmp, "bus"), "models")
        rng = np.random.default_rng(0)
        journal.append(
            [F.format_als_row(u, "U", rng.normal(size=dim))
             for u in range(n_users)]
            + [F.format_als_row(i, "I", rng.normal(size=dim))
               for i in range(n_users)])

        def make_ratings(n, seed):
            rnd = random.Random(seed)
            return [(rnd.randrange(n_users), rnd.randrange(n_users),
                     round(rnd.uniform(0.5, 5.0), 3)) for _ in range(n)]

        def wait_drained(cli, timeout_s=600.0):
            """Block until every submitted rating has an apply-log
            commit; returns drain seconds (None on stall)."""
            target = sum(cli.totals().values())
            t0 = time.perf_counter()
            deadline = t0 + timeout_s
            while time.perf_counter() < deadline:
                wm = up.applied_watermarks(journal.dir, "models", partitions)
                if sum(wm.values()) >= target:
                    return time.perf_counter() - t0
                time.sleep(0.05)
            return None

        ctl = ScaleController(
            "bench-update", journal.dir, "models",
            port_dir=os.path.join(tmp, "ports"), ready_timeout_s=180,
            extra_args=["--updatePlane", "true", "--pollInterval", "0.005"],
        )
        try:
            rec = ctl.scale_to(2)
            assert rec["shards"] == 2, "bootstrap failed"
            cli = up.UpdatePlaneClient(journal.dir, "models",
                                       partitions=partitions)

            # -- reshard arm: live producer across the 2->4 cutover ------
            stop = threading.Event()
            sent = {"n": 0}

            def produce():
                ratings = make_ratings(n_reshard, seed=11)
                for s in range(0, len(ratings), 200):
                    if stop.is_set():
                        break
                    cli.submit_many(ratings[s:s + 200])
                    sent["n"] += len(ratings[s:s + 200])
                    time.sleep(0.005)

            th = threading.Thread(target=produce, daemon=True)
            th.start()
            time.sleep(0.3)
            t0 = time.perf_counter()
            rec = ctl.scale_to(4)
            cutover_s = time.perf_counter() - t0
            assert rec["shards"] == 4 and rec["gen"] == 2, "cutover failed"
            th.join(timeout=120)
            stop.set()
            cli.sync()
            drain_s = wait_drained(cli)
            audit = up.audit_partitions(journal.dir, "models", partitions)
            out["serving_update_reshard_ratings"] = sent["n"]
            out["serving_update_reshard_cutover_s"] = round(cutover_s, 2)
            out["serving_update_reshard_lost"] = audit["lost"]
            out["serving_update_reshard_duplicates"] = audit["duplicates"]
            out["serving_update_reshard_drained"] = drain_s is not None
            _log(f"[bench:update] reshard 2->4: {sent['n']} ratings "
                 f"live, cutover {cutover_s:.2f}s, lost {audit['lost']}, "
                 f"dup {audit['duplicates']}")

            # -- baseline: single batched consumer vs the 4-shard fleet --
            ratings_path = os.path.join(tmp, "ratings.tsv")
            _write_ratings_tsv(ratings_path, n_base, n_users, n_users,
                               seed=5)
            mean_payload = ";".join(["0.0"] * dim)
            t0 = time.perf_counter()
            processed = online_sgd.run(Params.from_dict({
                "input": ratings_path, "mode": "once", "outputMode": "kafka",
                "journalDir": journal.dir, "topic": "models",
                "group": "bench-update", "queryTimeout": 60,
                "flushEveryUpdate": False, "batchSize": 64,
                "userMean": mean_payload, "itemMean": mean_payload,
            }))
            base_s = time.perf_counter() - t0
            base_rps = processed / base_s
            out["serving_update_baseline_ratings_per_sec"] = round(base_rps)
            _log(f"[bench:update] baseline single consumer: {processed} "
                 f"ratings in {base_s:.1f}s ({base_rps:,.0f}/s)")

            # -- fleet throughput at 4 shards ----------------------------
            ratings = make_ratings(n_fleet, seed=23)
            t0 = time.perf_counter()
            for s in range(0, len(ratings), 2_000):
                cli.submit_many(ratings[s:s + 2_000])
            drain_s = wait_drained(cli)
            assert drain_s is not None, "fleet arm failed to drain"
            fleet_s = time.perf_counter() - t0
            fleet_rps = n_fleet / fleet_s
            audit = up.audit_partitions(journal.dir, "models", partitions)
            try:
                n_cpus = len(os.sched_getaffinity(0))
            except (AttributeError, OSError):
                n_cpus = os.cpu_count() or 1
            out["serving_update_plane_updates_per_sec"] = round(fleet_rps)
            out["serving_update_plane_ratings"] = n_fleet
            out["serving_update_plane_speedup_x"] = round(
                fleet_rps / base_rps, 2)
            out["serving_update_plane_clean"] = audit["clean"]
            out["serving_update_cpus"] = n_cpus
            # the fleet speedup = locality x parallelism; with fewer
            # cores than shards the 4 worker processes time-slice one
            # CPU and only the locality term (no per-rating RPC) can
            # show.  Record the context so a low ratio on a starved
            # box reads as "unmeasurable here", not as a regression.
            if n_cpus < 4:
                out["serving_update_plane_core_starved"] = True
            _log(f"[bench:update] fleet 4 shards: {n_fleet} ratings in "
                 f"{fleet_s:.1f}s ({fleet_rps:,.0f}/s, "
                 f"{out['serving_update_plane_speedup_x']}x baseline, "
                 f"audit clean={audit['clean']}, {n_cpus} cpus"
                 + (", CORE-STARVED: parallel term unmeasurable"
                    if n_cpus < 4 else "") + ")")

            # -- submit->queryable visibility ----------------------------
            vis_ms = []
            rnd = random.Random(41)
            with ElasticClient(
                    "bench-update",
                    retry=RetryPolicy(attempts=4, backoff_s=0.02,
                                      max_backoff_s=0.2),
                    timeout_s=10) as c:
                for _ in range(n_probes):
                    u = rnd.randrange(n_users)
                    key = f"{u}-U"
                    before = c.query_state(ALS_STATE, key)
                    t0 = time.perf_counter()
                    cli.submit(u, rnd.randrange(n_users),
                               round(rnd.uniform(0.5, 5.0), 3))
                    deadline = t0 + 5.0
                    while time.perf_counter() < deadline:
                        if c.query_state(ALS_STATE, key) != before:
                            vis_ms.append(
                                (time.perf_counter() - t0) * 1e3)
                            break
                        time.sleep(0.002)
                    time.sleep(0.01)
            out["serving_update_visibility_probes"] = len(vis_ms)
            out.update({f"serving_update_visibility_{q}_ms": v
                        for q, v in _pcts(vis_ms).items()})
            _log(f"[bench:update] visibility: {len(vis_ms)}/{n_probes} "
                 f"probes, p50/p99 "
                 f"{out.get('serving_update_visibility_p50_ms')}/"
                 f"{out.get('serving_update_visibility_p99_ms')} ms")
        finally:
            ctl.stop(drop_topology=True)
        return out
    except Exception:
        _log(traceback.format_exc())
        out["serving_update_plane_error"] = traceback.format_exc(limit=3)
        return out
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        shutil.rmtree(tmp, ignore_errors=True)


def run_serving_rollout_section(small: bool) -> dict:
    """Multi-tenant rollout plane (serve/rollout.py + serve/admission.py),
    two arms.  Arm 1 — blue/green model swap under sustained in-flight
    load: cutover and rollback wall time, client-visible errors (the
    contract pinned by tests/test_rollout.py is ZERO), and whether
    rollback restored the previous model's answers.  Arm 2 — goodput
    under shed: an abusive tenant offers well over its admission quota
    against the same live group while in-quota traffic keeps flowing;
    reports in-quota availability (target >= 99.9%), the abusive
    tenant's served/shed split, and the fleet scrape's shed_per_s /
    admission_pressure autoscaler signals (obs/scrape.fleet_signals)."""
    import threading

    from flink_ms_tpu.core import formats as F
    from flink_ms_tpu.obs.scrape import fleet_signals, scrape_fleet
    from flink_ms_tpu.serve.admission import SHED_MARKER
    from flink_ms_tpu.serve.client import RetryPolicy
    from flink_ms_tpu.serve.consumer import ALS_STATE
    from flink_ms_tpu.serve.elastic import ElasticClient
    from flink_ms_tpu.serve.journal import Journal
    from flink_ms_tpu.serve.rollout import RolloutController

    n_users = int(
        os.environ.get("BENCH_ROLLOUT_USERS", 300 if small else 2_000))
    window_s = float(
        os.environ.get("BENCH_ROLLOUT_WINDOW_S", 2 if small else 6))
    abuse_qps = float(os.environ.get("BENCH_ROLLOUT_ABUSE_QPS", 50))

    tmp = tempfile.mkdtemp(prefix="bench_rollout_")
    saved = {key: os.environ.get(key) for key in
             ("TPUMS_HEARTBEAT_S", "TPUMS_REPLICA_TTL_S",
              "TPUMS_REGISTRY_DIR", "TPUMS_ADMIT_TENANT_QPS")}
    os.environ["TPUMS_HEARTBEAT_S"] = "0.2"
    os.environ["TPUMS_REPLICA_TTL_S"] = "1.2"
    os.environ["TPUMS_REGISTRY_DIR"] = os.path.join(tmp, "registry")
    # the abusive tenant's quota, baked into every worker's admission
    # controller at spawn time; untenanted (in-quota) traffic stays
    # unlimited, so arm 2's availability split is purely the shedder's
    os.environ["TPUMS_ADMIT_TENANT_QPS"] = f"abuse={abuse_qps:g}"
    out = {}
    try:
        dim = 8

        def _seed_model(name: str, seed_val: int) -> Journal:
            j = Journal(os.path.join(tmp, f"bus-{name}"), "models")
            rng = np.random.default_rng(seed_val)
            j.append(
                [F.format_als_row(u, "U", rng.normal(size=dim))
                 for u in range(n_users)]
                + [F.format_als_row(i, "I", rng.normal(size=dim))
                   for i in range(n_users)])
            return j

        j1, j2 = _seed_model("v1", 0), _seed_model("v2", 1)
        keys = [f"{u}-U" for u in range(n_users)]

        ctl = RolloutController(
            "bench-rollout", port_dir=os.path.join(tmp, "ports"),
            journal_dir=j1.dir, topic="models", ready_timeout_s=180)
        counts = {"ok": 0, "err": 0}
        stop = threading.Event()

        def load():
            rnd = np.random.default_rng(2)
            with ElasticClient(
                    "bench-rollout", timeout_s=10,
                    retry=RetryPolicy(attempts=6, backoff_s=0.02,
                                      max_backoff_s=0.5)) as c:
                while not stop.is_set():
                    key = keys[int(rnd.integers(len(keys)))]
                    try:
                        if c.query_state(ALS_STATE, key) is None:
                            counts["err"] += 1
                        else:
                            counts["ok"] += 1
                    except Exception:
                        counts["err"] += 1

        abuse = {"served": 0, "shed": 0, "err": 0}

        def abuse_load():
            rnd = np.random.default_rng(3)
            with ElasticClient(
                    "bench-rollout", timeout_s=10, tenant="abuse",
                    retry=RetryPolicy(attempts=2, backoff_s=0.01,
                                      max_backoff_s=0.1)) as c:
                while not stop.is_set():
                    key = keys[int(rnd.integers(len(keys)))]
                    try:
                        c.query_state(ALS_STATE, key)
                        abuse["served"] += 1
                    except Exception as e:
                        if SHED_MARKER in repr(e):
                            abuse["shed"] += 1
                        else:
                            abuse["err"] += 1

        try:
            rec = ctl.rollout(j1.dir, "models", model_id="v1", shards=2)
            assert rec["gen"] == 1, "bootstrap rollout failed"
            probe_key = keys[0]
            with ElasticClient("bench-rollout", timeout_s=10) as probe:
                v1_answer = probe.query_state(ALS_STATE, probe_key)
            th = threading.Thread(target=load, daemon=True)
            th.start()
            time.sleep(window_s / 2)

            # -- arm 1: blue/green swap + rollback under live traffic
            t0 = time.time()
            ctl.rollout(j2.dir, "models", model_id="v2",
                        verify_min_rows=2 * n_users)
            cutover_s = time.time() - t0
            time.sleep(window_s / 2)
            t0 = time.time()
            ctl.rollback()
            rollback_s = time.time() - t0
            with ElasticClient("bench-rollout", timeout_s=10) as probe:
                restored = probe.query_state(ALS_STATE, probe_key)

            # -- arm 2: overload the abusive tenant, watch goodput
            before_fleet = scrape_fleet()["fleet"]
            t_before = time.time()
            ath = threading.Thread(target=abuse_load, daemon=True)
            ath.start()
            mark = (counts["ok"], counts["err"])
            time.sleep(window_s)
            inq_ok = counts["ok"] - mark[0]
            inq_err = counts["err"] - mark[1]
            stop.set()
            th.join(timeout=30)
            ath.join(timeout=30)
            after_fleet = scrape_fleet()["fleet"]
            sig = fleet_signals(before_fleet, after_fleet,
                                dt_s=time.time() - t_before)
        finally:
            stop.set()
            ctl.stop(drop_topology=True)

        total = counts["ok"] + counts["err"]
        out["serving_rollout_queries"] = total
        out["serving_rollout_errors"] = counts["err"]
        out["serving_rollout_availability"] = (
            round(counts["ok"] / total, 6) if total else None)
        out["serving_rollout_cutover_s"] = round(cutover_s, 2)
        out["serving_rollout_rollback_s"] = round(rollback_s, 2)
        out["serving_rollout_rollback_restored"] = restored == v1_answer
        inq_total = inq_ok + inq_err
        out["serving_rollout_inquota_queries"] = inq_total
        out["serving_rollout_inquota_errors"] = inq_err
        out["serving_rollout_inquota_availability"] = (
            round(inq_ok / inq_total, 6) if inq_total else None)
        out["serving_rollout_abuse_quota_qps"] = abuse_qps
        out["serving_rollout_abuse_served"] = abuse["served"]
        out["serving_rollout_abuse_shed"] = abuse["shed"]
        out["serving_rollout_abuse_other_errors"] = abuse["err"]
        out["serving_rollout_shed_per_s"] = round(sig["shed_per_s"], 2)
        out["serving_rollout_admission_pressure"] = round(
            sig["admission_pressure"], 4)
        _log(f"[bench:rollout] {total} queries, {counts['err']} errors, "
             f"cutover {out['serving_rollout_cutover_s']}s, rollback "
             f"{out['serving_rollout_rollback_s']}s (restored="
             f"{out['serving_rollout_rollback_restored']}); shed arm: "
             f"in-quota avail {out['serving_rollout_inquota_availability']}"
             f", abuse served/shed {abuse['served']}/{abuse['shed']}, "
             f"shed_per_s {out['serving_rollout_shed_per_s']}, pressure "
             f"{out['serving_rollout_admission_pressure']}")
        return out
    except Exception:
        _log(traceback.format_exc())
        out["serving_rollout_error"] = traceback.format_exc(limit=3)
        return out
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        shutil.rmtree(tmp, ignore_errors=True)


# ---------------------------------------------------------------------------
# serving_ann section: retrieval-plane tiers (round 11)
# ---------------------------------------------------------------------------

def run_serving_ann_section(small: bool) -> dict:
    """Exact-vs-sharded-vs-IVF A/B through ``scripts/ann_profile.py``.

    Two arms, each a fresh subprocess (the sharded tier needs
    ``--xla_force_host_platform_device_count`` set BEFORE jax import, so
    the arm cannot run in-process):

    - ``1m``  — the sharded-exact question at the catalog size the host
      path serves today (1M rows; small: 60k);
    - ``10m`` — the IVF question at the catalog size the exact scan dies
      at (10M rows; small: 200k), explicit nlist/nprobe sizing.

    Gates recorded (never raised — a bench section reports, the tests
    enforce): ``recall@100 >= 0.95`` (the ANN contract),
    ``sharded >= 3x`` and ``ivf >= 5x`` qps vs the same arm's exact
    baseline.  ``serving_ann_host_cores`` is recorded because the
    sharded gate is physically unreachable on a single-core host (8
    forced host devices share one core — the mesh layout is then pure
    collective overhead; the parity tests still prove correctness)."""
    import json as _json
    import subprocess

    out: dict = {"serving_ann_host_cores": os.cpu_count()}
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "scripts", "ann_profile.py")
    arms = (
        ("1m",
         int(os.environ.get("BENCH_ANN_ROWS_EXACT",
                            60_000 if small else 1_000_000)),
         {"--nlist": "256" if small else "4096",
          "--nprobe": "32" if small else "64",
          "--trials": "6" if small else "10"}),
        ("10m",
         int(os.environ.get("BENCH_ANN_ROWS_IVF",
                            200_000 if small else 10_000_000)),
         {"--nlist": "512" if small else "4096",
          "--nprobe": "48" if small else "64",
          "--trials": "6" if small else "8"}),
    )
    recalls = []
    for name, rows, extra in arms:
        cmd = [sys.executable, script, "--rows", str(rows),
               "--json", "true", "--recallMin", "0.95"]
        for flag, val in extra.items():
            cmd += [flag, val]
        env = dict(os.environ)
        # the script forces its own host device count; a suite-level
        # XLA_FLAGS (tests) or platform pin must not leak in
        env.pop("XLA_FLAGS", None)
        _log(f"[bench:ann] arm {name}: {rows} rows ({' '.join(cmd[2:])})")
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, env=env,
                timeout=float(os.environ.get(
                    "BENCH_ANN_ARM_TIMEOUT_S",
                    600 if small else 2400)),
            )
            line = proc.stdout.strip().splitlines()[-1] if \
                proc.stdout.strip() else ""
            res = _json.loads(line)
        except Exception:
            _log(traceback.format_exc())
            tail = ""
            try:
                tail = (proc.stderr or "")[-500:]
            except Exception:
                pass
            out[f"serving_ann_{name}_error"] = (
                traceback.format_exc(limit=2) + tail)
            continue
        out[f"serving_ann_{name}_rows"] = res["rows"]
        for key in ("exact_qps", "exact_p50_ms", "sharded_qps",
                    "sharded_p50_ms", "sharded_speedup", "ivf_qps",
                    "ivf_p50_ms", "ivf_speedup", "ivf_build_s",
                    "ivf_nlist", "ivf_nprobe", "ivf_dropped",
                    "ivf_recall_probe", "recall_at_k"):
            if key in res:
                val = res[key]
                out[f"serving_ann_{name}_{key}"] = (
                    round(val, 4) if isinstance(val, float) else val)
        recalls.append(res.get("recall_at_k", 0.0))
        _log(f"[bench:ann] arm {name}: exact {res['exact_qps']:,.0f} qps, "
             f"sharded {res['sharded_speedup']:.2f}x, ivf "
             f"{res['ivf_speedup']:.2f}x @ recall {res['recall_at_k']:.3f}")
    # headline gates (compact artifact): sharded question answered by the
    # 1m arm, the ANN question by the 10m arm
    sharded_x = out.get("serving_ann_1m_sharded_speedup")
    ivf_x = out.get("serving_ann_10m_ivf_speedup")
    out["serving_ann_sharded_speedup"] = sharded_x
    out["serving_ann_ivf_speedup"] = ivf_x
    out["serving_ann_recall_at_100"] = (
        round(min(recalls), 4) if recalls else None)
    out["serving_ann_gate_recall_ok"] = bool(
        recalls and min(recalls) >= 0.95)
    out["serving_ann_gate_sharded_3x"] = bool(
        sharded_x is not None and sharded_x >= 3.0)
    out["serving_ann_gate_ivf_5x"] = bool(
        ivf_x is not None and ivf_x >= 5.0)
    return out


def run_serving_autopilot_section(small: bool) -> dict:
    """Unattended continuous-training flywheel (serve/autopilot.py):

    1. **flywheel** — ratings stream in waves through the update plane
       while the autopilot ticks: each wave is windowed, retrained
       WARM-STARTED from the serving factors, evaluated candidate vs
       incumbent on the rolling held-out slice, and rolled out when it
       wins.  Artifact: retrain count, candidate-win rate, held-out MSE
       trajectory with a monotone non-increasing gate (modulo the noise
       floor — each wave adds data, so quality must not regress).
    2. **warm vs cold** — equal-iteration ALS fits on the final window,
       init from the serving factors vs the cold seed draw: the warm fit
       must score better held-out MSE at 1 iteration, and the artifact
       records how many iterations cold needs to catch up.
    3. **drift -> rollback** — an injected live-MSE regression (the
       canary gauge shortcut through the controller's hook) must drive an
       automatic ``rollback()`` within the detection bound.
    """
    from flink_ms_tpu.core import formats as F
    from flink_ms_tpu.eval.mse import compute_mse, rolling_holdout_split
    from flink_ms_tpu.ops.als import ALSConfig, als_fit, warm_start_factors
    from flink_ms_tpu.parallel.mesh import honor_platform_env, make_mesh
    from flink_ms_tpu.serve.autopilot import AutopilotController
    from flink_ms_tpu.serve.journal import Journal
    from flink_ms_tpu.serve.rollout import RolloutController
    from flink_ms_tpu.serve.update_plane import UpdatePlaneClient

    n = int(os.environ.get("BENCH_AUTOPILOT_USERS", 40 if small else 100))
    k = 4
    waves = int(os.environ.get("BENCH_AUTOPILOT_WAVES", 3))
    iters = int(os.environ.get("BENCH_AUTOPILOT_ITERS", 3))
    detect_bound_s = float(os.environ.get("BENCH_AUTOPILOT_DETECT_S", 5.0))
    noise = 0.05

    tmp = tempfile.mkdtemp(prefix="tpums_autopilot_bench_")
    saved_env = {kk: os.environ.get(kk) for kk in
                 ("TPUMS_REGISTRY_DIR", "TPUMS_HEARTBEAT_S",
                  "TPUMS_REPLICA_TTL_S")}
    os.environ["TPUMS_REGISTRY_DIR"] = os.path.join(tmp, "registry")
    os.environ["TPUMS_HEARTBEAT_S"] = "0.2"
    os.environ["TPUMS_REPLICA_TTL_S"] = "30"
    out: dict = {}
    ctl = None
    try:
        honor_platform_env()
        rng = np.random.default_rng(0)
        U, V = rng.normal(size=(n, k)), rng.normal(size=(n, k))
        uu, ii = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
        uu, ii = uu.ravel(), ii.ravel()
        rr = (np.sum(U[uu] * V[ii], axis=1)
              + rng.normal(0.0, noise, size=len(uu)))
        order = rng.permutation(len(uu))
        ratings = [(int(uu[j]), int(ii[j]), float(rr[j])) for j in order]
        per_wave = len(ratings) // waves

        # v0 incumbent: random factors — wave 1 must win immediately
        j0 = Journal(os.path.join(tmp, "v0"), "models")
        j0.append([F.format_als_row(u, "U", rng.normal(size=k))
                   for u in range(n)]
                  + [F.format_als_row(i, "I", rng.normal(size=k))
                     for i in range(n)])
        ctl = RolloutController("bench-autopilot",
                                port_dir=os.path.join(tmp, "ports"),
                                journal_dir=j0.dir, topic="models",
                                ready_timeout_s=180)
        ctl.rollout(j0.dir, "models", model_id="v0", shards=1)

        producer = UpdatePlaneClient(os.path.join(tmp, "bus"), "models",
                                    partitions=4)
        live = [None]
        pilot = AutopilotController(
            "bench-autopilot", os.path.join(tmp, "bus"),
            os.path.join(tmp, "work"), rollout=ctl, partitions=4,
            min_window=max(per_wave // 2, 1), interval_s=0.05,
            iterations=iters, num_factors=k, drift_source="gauge",
            drift_factor=1.5, live_mse=lambda: live[0])

        # -- 1. the flywheel, one tick per wave --------------------------
        trajectory = []
        warm_starts = 0
        t0 = time.perf_counter()
        for w in range(waves):
            lo, hi = w * per_wave, (w + 1) * per_wave
            producer.submit_many(
                ratings[lo:] if w == waves - 1 else ratings[lo:hi],
                flush=True)
            tick = pilot.tick()
            if "candidate_mse" in tick:
                trajectory.append(round(tick["candidate_mse"], 6))
                warm_starts += bool(tick.get("warm_start"))
            _log(f"[bench:autopilot] wave {w + 1}/{waves}: "
                 f"rows={tick.get('window_rows')} "
                 f"mse={tick.get('candidate_mse')} "
                 f"win={tick.get('win')} gen={tick.get('rollout_gen')}")
        flywheel_s = time.perf_counter() - t0
        s = pilot.summary()
        evals = s["wins"] + s["losses"]
        # monotone non-increasing modulo the noise floor: each wave sees
        # MORE data, so held-out MSE may wobble by the label noise but
        # must not climb past it
        floor = max(2.0 * noise * noise, 0.005)
        monotone = all(b <= a + floor
                       for a, b in zip(trajectory, trajectory[1:]))
        out["serving_autopilot_retrains"] = s["retrains"]
        out["serving_autopilot_rollouts"] = s["rollouts"]
        out["serving_autopilot_win_rate"] = (
            round(s["wins"] / evals, 4) if evals else None)
        out["serving_autopilot_mse_trajectory"] = trajectory
        out["serving_autopilot_mse_monotone"] = monotone
        out["serving_autopilot_warm_started"] = warm_starts
        out["serving_autopilot_flywheel_s"] = round(flywheel_s, 2)

        # -- 2. warm vs cold on the final window -------------------------
        keys_acc = sorted(pilot._acc)
        wu = np.asarray([kk[0] for kk in keys_acc], dtype=np.int64)
        wi = np.asarray([kk[1] for kk in keys_acc], dtype=np.int64)
        wr = np.asarray([pilot._acc[kk] for kk in keys_acc])
        tr_idx, ho_idx = rolling_holdout_split(wu, wi, wr, fraction=0.2,
                                               seed=99)
        prev_u, prev_i = pilot._incumbent_tables()
        uf0, itf0 = warm_start_factors(
            np.unique(wu[tr_idx]), np.unique(wi[tr_idx]), prev_u, prev_i,
            k, seed=42)
        mesh = make_mesh(1)

        def heldout_mse(model):
            table = {f"{int(u)}-U": f for u, f
                     in zip(model.user_ids, model.user_factors)}
            table.update({f"{int(i)}-I": f for i, f
                          in zip(model.item_ids, model.item_factors)})
            mse, _, _ = compute_mse(wu[ho_idx], wi[ho_idx], wr[ho_idx],
                                    table.get)
            return float(mse) if mse is not None else float("inf")

        def fit(n_iters, warm):
            cfg = ALSConfig(num_factors=k, iterations=n_iters,
                            lambda_=0.1, seed=42)
            kw = ({"init_user_factors": uf0, "init_item_factors": itf0}
                  if warm else {})
            t = time.perf_counter()
            m = als_fit(wu[tr_idx], wi[tr_idx], wr[tr_idx], cfg, mesh,
                        **kw)
            return heldout_mse(m), time.perf_counter() - t

        warm_mse, warm_s = fit(1, warm=True)
        cold_mse, cold_s = fit(1, warm=False)
        cold_iters_to_match = None
        for extra in range(1, 9):
            m_mse, _ = fit(extra, warm=False)
            if m_mse <= warm_mse:
                cold_iters_to_match = extra
                break
        out["serving_autopilot_warm_mse_1iter"] = round(warm_mse, 6)
        out["serving_autopilot_cold_mse_1iter"] = round(cold_mse, 6)
        out["serving_autopilot_warm_beats_cold"] = warm_mse < cold_mse
        out["serving_autopilot_cold_iters_to_match"] = cold_iters_to_match
        out["serving_autopilot_warm_fit_s"] = round(warm_s, 3)
        _log(f"[bench:autopilot] warm 1-iter mse {warm_mse:.4f} vs cold "
             f"{cold_mse:.4f}; cold needs {cold_iters_to_match} iters "
             f"to match")

        # -- 3. injected drift -> automatic rollback ---------------------
        baseline_rollbacks = pilot.summary()["rollbacks"]
        live[0] = (pilot.state.get("rollout_probe_mse") or 1.0) * 100.0
        t0 = time.perf_counter()
        detect_s = None
        deadline = time.time() + 60
        while time.time() < deadline:
            pilot.tick()
            if pilot.summary()["rollbacks"] > baseline_rollbacks:
                detect_s = time.perf_counter() - t0
                break
            time.sleep(0.05)
        out["serving_autopilot_rollback_detect_s"] = (
            round(detect_s, 3) if detect_s is not None else None)
        out["serving_autopilot_rollback_ok"] = (
            detect_s is not None and detect_s <= detect_bound_s)
        out["serving_autopilot_detect_bound_s"] = detect_bound_s
        _log(f"[bench:autopilot] drift -> rollback in {detect_s}s "
             f"(bound {detect_bound_s}s)")
        pilot.release_lease()
    finally:
        for kk, v in saved_env.items():
            if v is None:
                os.environ.pop(kk, None)
            else:
                os.environ[kk] = v
        if ctl is not None:
            try:
                ctl.stop(drop_topology=True)
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def run_serving_forensics_section(small: bool) -> dict:
    """Tail-latency forensics efficacy (obs/tracing.py + obs/forensics.py
    + obs/watch.py), the round-14 acceptance demo:

    1. **injected tail** — every 10th traced GET against a live serving
       job carries a deliberate ``injected_slow`` leaf span (a sleep in
       the request path); the slow-vs-fast critical-path diff over the
       span spill must rank that stage **#1** and attribute essentially
       the whole slow-fast gap to it.
    2. **incident forensics** — a p99 quantile alert on the (exemplar-
       linked) request histogram must fire AND its incident record must
       carry at least one exemplar trace id whose assembled span tree
       shows the injected stage on its critical path — the alert NAMES
       the cause, not just the number.

    The hot-path overhead bar for spans+exemplars lives in
    scripts/obs_overhead_ab.py (<= 3% GET p50, ABAB), not here.
    """
    from flink_ms_tpu.core import formats as F
    from flink_ms_tpu.obs import forensics as FX
    from flink_ms_tpu.obs import tracing as T
    from flink_ms_tpu.obs.metrics import get_registry, set_exemplars
    from flink_ms_tpu.obs.rules import Rule
    from flink_ms_tpu.obs.watch import FleetWatcher
    from flink_ms_tpu.serve.client import QueryClient
    from flink_ms_tpu.serve.consumer import (ALS_STATE, ServingJob,
                                             make_backend,
                                             parse_als_record)
    from flink_ms_tpu.serve.journal import Journal

    n_users = 200 if small else 1_000
    n_q = int(os.environ.get("BENCH_FORENSICS_QUERIES",
                             120 if small else 400))
    slow_every = 10
    slow_s = float(os.environ.get("BENCH_FORENSICS_SLOW_S", 0.02))
    series = "tpums_bench_request_seconds"

    tmp = tempfile.mkdtemp(prefix="tpums_forensics_bench_")
    spill = os.path.join(tmp, "spans.jsonl")
    saved = {k: os.environ.get(k)
             for k in ("TPUMS_REGISTRY_DIR", "TPUMS_TRACE")}
    os.environ["TPUMS_REGISTRY_DIR"] = os.path.join(tmp, "registry")
    os.environ["TPUMS_TRACE"] = spill
    prev_ex = set_exemplars(True)
    out: dict = {}
    job = None
    try:
        rng = np.random.default_rng(0)
        journal = Journal(os.path.join(tmp, "bus"), "models")
        journal.append(
            [F.format_als_row(u, "U", rng.normal(size=4))
             for u in range(n_users)])
        job = ServingJob(
            journal, ALS_STATE, parse_als_record,
            make_backend("memory", None),
            host="127.0.0.1", port=0, poll_interval_s=0.01,
        ).start()
        assert job.wait_ready(120)

        rule = Rule(name="bench_p99_latency", kind="threshold",
                    series=series, mode="quantile", q=99.0,
                    op=">", value=slow_s / 4.0, window_s=300.0,
                    severity="warn")
        watcher = FleetWatcher(interval_s=0.1, rules=[rule],
                               scope="bench_forensics")
        watcher.tick()  # baseline scrape: the quantile window needs one

        # -- 1. traced load with an injected slow stage ------------------
        hist = get_registry().histogram(series)
        qrng = np.random.default_rng(1)
        with QueryClient("127.0.0.1", job.port, timeout_s=30) as c:
            for _ in range(30):
                c.query_state(ALS_STATE, "1-U")  # warm, untraced
            for i in range(n_q):
                key = f"{int(qrng.integers(0, n_users))}-U"
                tid = T.new_trace_id()
                t0 = time.perf_counter()
                with T.trace_span(tid):
                    with T.span("bench_request", verb="GET"):
                        if i % slow_every == 0:
                            with T.span("injected_slow"):
                                time.sleep(slow_s)
                        c.query_state(ALS_STATE, key)
                hist.observe(time.perf_counter() - t0, tid=tid)

        # -- 2. the diff must name the injected stage --------------------
        rep = FX.report([spill], slow_q=0.9)
        stages = rep["diff"]["stages"]
        top = stages[0] if stages else {}
        out["serving_forensics_traces"] = rep["traces"]
        out["serving_forensics_events"] = rep["events"]
        out["serving_forensics_stage1"] = top.get("stage")
        out["serving_forensics_stage1_delta_us"] = (
            round(top["delta_s"] * 1e6, 1) if top else None)
        out["serving_forensics_stage1_share"] = top.get("delta_share")
        out["serving_forensics_diff_ok"] = (
            top.get("stage") == "injected_slow"
            and top.get("delta_share", 0.0) >= 0.5)
        _log(f"[bench:forensics] {rep['traces']} traces; #1 stage "
             f"{top.get('stage')} (+{(top.get('delta_s') or 0) * 1e6:.0f}us"
             f", {100 * (top.get('delta_share') or 0):.0f}% of the gap)")

        # -- 3. p99 alert fires and its incident names the stage ---------
        fired = None
        for _ in range(20):
            trs = watcher.tick()
            fired = next((t for t in trs
                          if t["kind"] == "alert_firing"
                          and t["rule"] == rule.name), None)
            if fired:
                break
            time.sleep(0.05)
        watcher.stop()
        tids = (fired or {}).get("exemplar_tids") or []
        incident_stages = set()
        for row in (fired or {}).get("critical_path") or []:
            incident_stages.update(r["stage"] for r in row["critical_path"])
        out["serving_forensics_alert_fired"] = fired is not None
        out["serving_forensics_exemplar_tids"] = len(tids)
        out["serving_forensics_incident_names_stage"] = (
            "injected_slow" in incident_stages)
        out["serving_forensics_ok"] = (
            out["serving_forensics_diff_ok"] and fired is not None
            and len(tids) >= 1 and "injected_slow" in incident_stages)
        _log(f"[bench:forensics] p99 alert fired={fired is not None} "
             f"exemplar_tids={len(tids)} incident_stages="
             f"{sorted(incident_stages)}")
        job.stop()
        job = None
    finally:
        if job is not None:
            try:
                job.stop()
            except Exception:
                pass
        set_exemplars(prev_ex)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(tmp, ignore_errors=True)
    return out

# ---------------------------------------------------------------------------
# geo-distributed serving section: replication lag, staleness, failover time
# ---------------------------------------------------------------------------

def run_serving_geo_section(small: bool) -> dict:
    """Geo-replication efficacy (serve/georepl.py, round 15):

    1. **replication lag under write load** — a home journal takes a
       steady update stream while a follower replicator (5ms poll) keeps
       a second region's journal in byte parity; the sampled
       ``lag_seconds`` distribution is the headline (p99 must sit well
       under the 250ms chaos-gate bar).
    2. **region-local stale reads** — a follower ServingJob answers
       ``st=``-opted queries; every reply carries the follower's
       measured staleness, and every read must succeed (zero errors).
    3. **failover** — the follower's RegionController promotes it after
       the home fleet's heartbeat lease lapses; the wall-clock from
       home-death to the CAS-published new generation is the failover
       metric, and the write forwarder must re-point to the new home.
    """
    from flink_ms_tpu.serve import georepl
    from flink_ms_tpu.serve import registry
    from flink_ms_tpu.serve.client import QueryClient
    from flink_ms_tpu.serve.consumer import (ALS_STATE, ServingJob,
                                             make_backend,
                                             parse_als_record)
    from flink_ms_tpu.serve.journal import Journal

    n_users = 500 if small else 2_000
    load_s = float(os.environ.get("BENCH_GEO_LOAD_S", 2.0 if small else 5.0))
    n_q = int(os.environ.get("BENCH_GEO_QUERIES", 300 if small else 1_000))

    tmp = tempfile.mkdtemp(prefix="tpums_geo_bench_")
    saved = os.environ.get("TPUMS_REGISTRY_DIR")
    os.environ["TPUMS_REGISTRY_DIR"] = os.path.join(tmp, "registry")
    us, eu = os.path.join(tmp, "us"), os.path.join(tmp, "eu")
    out: dict = {}
    rep = ctl = job = None
    try:
        home = Journal(us, "models")
        home.append([f"{u},U,{u * 0.25};1.0;0.5;-0.25"
                     for u in range(n_users)])
        georepl.publish_region_topology(
            "bench-geo", "us",
            {"us": {"journal_dir": us}, "eu": {"journal_dir": eu}},
            topic="models")
        rep = georepl.JournalReplicator(us, eu, "models", "eu",
                                        poll_s=0.005)
        rep.run_until_caught_up()
        rep.start()
        job = ServingJob(
            Journal(eu, "models"), ALS_STATE, parse_als_record,
            make_backend("memory", None),
            host="127.0.0.1", port=0, poll_interval_s=0.01,
        ).start()
        assert job.wait_ready(120)

        # -- 1+2. write load at home, stale reads at the follower --------
        lag_s: list = []
        stale_vals: list = []
        errors = 0
        deadline = time.time() + load_s
        seq = n_users
        rng = np.random.default_rng(0)
        with QueryClient("127.0.0.1", job.port, timeout_s=30,
                         stale=True) as c:
            while time.time() < deadline:
                home.append([f"{seq + i},U,1.0;1.0;1.0;1.0"
                             for i in range(50)])
                seq += 50
                for _ in range(max(1, n_q // 200)):
                    key = f"{int(rng.integers(0, n_users))}-U"
                    if c.query_state(ALS_STATE, key) is None:
                        errors += 1
                    if c.last_staleness_s is not None:
                        stale_vals.append(c.last_staleness_s)
                lag_s.append(rep.lag_seconds())
                time.sleep(0.005)
        lag_p = _pcts([s * 1e3 for s in lag_s])
        out["serving_geo_repl_lag_p50_ms"] = lag_p["p50"]
        out["serving_geo_repl_lag_p99_ms"] = lag_p["p99"]
        out["serving_geo_stale_reads"] = len(stale_vals)
        out["serving_geo_staleness_max_s"] = (
            round(max(stale_vals), 3) if stale_vals else None)
        out["serving_geo_errors"] = errors
        _log(f"[bench:geo] lag p50={lag_p['p50']}ms p99={lag_p['p99']}ms; "
             f"{len(stale_vals)} stale reads, {errors} errors")

        # -- 3. home dies; the follower's controller promotes ------------
        scoped = registry.qualify_region("bench-geo", "us")
        registry.register(f"{scoped}:s0r0", "127.0.0.1", 1, ALS_STATE,
                          replica_of=f"{scoped}/shard-0", ttl_s=0.2)
        fwd = georepl.GeoWriteForwarder("bench-geo", "models")
        ctl = georepl.RegionController("bench-geo", "models", "eu",
                                       replicator=rep, detect_misses=2,
                                       poll_s=0.02).start()
        t_dead = time.time() + 0.2  # the lease's natural expiry = "death"
        promoted = None
        wait_until = time.time() + 15.0
        while time.time() < wait_until:
            if ctl.promoted:
                promoted = time.time()
                break
            time.sleep(0.01)
        failover_ms = (round((promoted - t_dead) * 1e3, 1)
                       if promoted else None)
        fwd._refresh(force=True)
        repointed = fwd.home() == "eu"
        out["serving_geo_failover_ms"] = failover_ms
        out["serving_geo_forwarder_repointed"] = repointed
        out["serving_geo_ok"] = (
            errors == 0 and len(stale_vals) > 0 and promoted is not None
            and failover_ms is not None and failover_ms < 5_000.0
            and repointed and lag_p["p99"] < 250.0)
        _log(f"[bench:geo] failover={failover_ms}ms "
             f"repointed={repointed} ok={out['serving_geo_ok']}")
    finally:
        for closer in (ctl, rep, job):
            if closer is not None:
                try:
                    closer.stop()
                except Exception:
                    pass
        if saved is None:
            os.environ.pop("TPUMS_REGISTRY_DIR", None)
        else:
            os.environ["TPUMS_REGISTRY_DIR"] = saved
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def run_serving_arena_section(small: bool) -> dict:
    """Round-16 shared-memory arena A/B (ISSUE 16): the ONE factor store
    behind all three planes, measured against the dict + per-row-push
    baseline.  Three subsections, each with its own degrade key:

      get        native GET p50/p99 at 64 in flight against the C++
                 server mapping the arena DIRECTLY (zero per-request
                 Python->C++ pushes) vs the same server fed row-by-row
                 from a dict table.  Headline:
                 ``serving_arena_get_b2_c64_p50_us``.
      publish    snapshot publish wall-clock at the loaded row count:
                 dict columnar serialize vs arena quiesce copy vs arena
                 O(1) hardlink publish.  ``serving_arena_reflink`` says
                 whether the filesystem can reflink (FICLONE) — without
                 it the copy arm is bandwidth-bound and only the link
                 arm can show the O(1) win; the speedups reported are
                 what THIS box measured, not the reflink ceiling.
      visibility in-place arena write -> C++-reader queryable, p99 over
                 probes (the zero-copy freshness path: no socket, no
                 snapshot, just the seqlock row flip).

    A box with fewer cores than the writer+reader+bench processes needs
    records ``serving_arena_core_starved`` so slow numbers read as
    "unmeasurable here", not regressions."""
    import random

    from flink_ms_tpu.serve.arena import ArenaModelTable
    from flink_ms_tpu.serve.consumer import ALS_STATE
    from flink_ms_tpu.serve import snapshot as snapshot_mod
    from flink_ms_tpu.serve.table import ModelTable

    out: dict = {}
    n_rows = int(os.environ.get("BENCH_ARENA_ROWS",
                                5_000 if small else 1_000_000))
    get_total = int(os.environ.get("BENCH_ARENA_GETS",
                                   2_000 if small else 20_000))
    n_probes = int(os.environ.get("BENCH_ARENA_PROBES",
                                  50 if small else 200))
    dim = 16
    rng = np.random.default_rng(16)
    tmp = tempfile.mkdtemp(prefix="bench_arena_")
    try:
        n_cpus = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        n_cpus = os.cpu_count() or 1
    out["serving_arena_rows"] = n_rows
    out["serving_arena_cpus"] = n_cpus
    if n_cpus < 3:
        out["serving_arena_core_starved"] = True

    def payload(vec):
        return ";".join(repr(round(float(x), 4)) for x in vec)

    # does this filesystem reflink?  (FICLONE on a scratch pair — the
    # honesty flag for the publish-copy arm)
    try:
        import fcntl

        src = os.path.join(tmp, "rl-src")
        with open(src, "wb") as f:
            f.write(b"x" * 4096)
        with open(src, "rb") as s, open(os.path.join(tmp, "rl-dst"),
                                        "wb") as d:
            fcntl.ioctl(d.fileno(), 0x40049409, s.fileno())
        out["serving_arena_reflink"] = True
    except OSError:
        out["serving_arena_reflink"] = False

    keys = [f"{u}-U" for u in range(n_rows)]
    vals = [payload(rng.normal(size=dim)) for _ in range(n_rows)]

    # -- ingest + native GET through the mmap ----------------------------
    table = None
    try:
        from flink_ms_tpu.serve.native_store import (NativeArena,
                                                     NativeLookupServer)

        table = ArenaModelTable(8, dir=os.path.join(tmp, "arena"))
        t0 = time.perf_counter()
        for i in range(0, n_rows, 8192):
            table.put_many_columns(keys[i:i + 8192], vals[i:i + 8192])
        out["serving_arena_ingest_rows_per_s"] = round(
            n_rows / (time.perf_counter() - t0))
        with NativeArena(table.dir) as arena_h, \
                NativeLookupServer(arena_h, ALS_STATE, job_id="bench-arena",
                                   port=0) as nsrv:
            qps, p50 = _get_loop(nsrv.port, ALS_STATE, keys,
                                 min(get_total, 4_000), "b2")
            out["serving_arena_get_b2_c1_qps"] = qps
            out["serving_arena_get_b2_c1_p50_us"] = p50
            for win in (16, 64):
                frames = max(get_total // win, 20)
                io0 = nsrv.io_stats()
                qps, p50 = _get_pipelined(nsrv.port, ALS_STATE, keys, win,
                                          frames, "b2")
                io1 = nsrv.io_stats()
                out[f"serving_arena_get_b2_c{win}_qps"] = qps
                out[f"serving_arena_get_b2_c{win}_p50_us"] = p50
                # round-17 batched socket loop: reply-path syscalls the
                # server itself counted, per B2 frame served
                out[f"serving_arena_get_b2_c{win}_syscalls_per_frame"] = \
                    round((io1["reply_syscalls"] - io0["reply_syscalls"])
                          / frames, 2)
            out["serving_arena_uring"] = bool(io1["uring"])
            _log(f"[bench:arena] GET b2: c1 "
                 f"{out['serving_arena_get_b2_c1_qps']} qps, c64 "
                 f"{out['serving_arena_get_b2_c64_qps']} qps / "
                 f"{out['serving_arena_get_b2_c64_p50_us']} us/req p50, "
                 f"{out['serving_arena_get_b2_c64_syscalls_per_frame']} "
                 f"reply syscalls/frame "
                 f"(uring={out['serving_arena_uring']})")

            # -- write -> queryable visibility through the C++ reader ----
            vis_ms = []
            rnd = random.Random(16)
            for i in range(n_probes):
                key = keys[rnd.randrange(n_rows)]
                new_val = payload(rng.normal(size=dim))
                t0 = time.perf_counter()
                table.put(key, new_val)
                deadline = t0 + 5.0
                while time.perf_counter() < deadline:
                    if arena_h.get(key) == new_val:
                        vis_ms.append((time.perf_counter() - t0) * 1e3)
                        break
            out["serving_arena_visibility_probes"] = len(vis_ms)
            out.update({f"serving_arena_visibility_{q}_ms": v
                        for q, v in _pcts(vis_ms).items()})
            _log(f"[bench:arena] visibility: {len(vis_ms)}/{n_probes} "
                 f"probes, p99 "
                 f"{out.get('serving_arena_visibility_p99_ms')} ms")
    except Exception:
        _log(traceback.format_exc())
        out["serving_arena_get_error"] = traceback.format_exc(limit=3)

    # -- publish A/B/C at the same row count -----------------------------
    try:
        dict_t = ModelTable(8)
        for i in range(0, n_rows, 8192):
            dict_t.put_many_columns(keys[i:i + 8192], vals[i:i + 8192])
        t0 = time.perf_counter()
        snapshot_mod.publish(os.path.join(tmp, "snap-dict"), dict_t,
                             n_rows, shard=0, num_shards=1)
        dict_s = time.perf_counter() - t0
        out["serving_arena_publish_dict_ms"] = round(dict_s * 1e3, 2)
        if table is None:
            table = ArenaModelTable(8, dir=os.path.join(tmp, "arena"))
            for i in range(0, n_rows, 8192):
                table.put_many_columns(keys[i:i + 8192], vals[i:i + 8192])
        for mode in ("copy", "link"):
            table.publish_mode = mode
            t0 = time.perf_counter()
            snapshot_mod.publish(os.path.join(tmp, f"snap-{mode}"), table,
                                 n_rows, shard=0, num_shards=1)
            mode_s = time.perf_counter() - t0
            out[f"serving_arena_publish_{mode}_ms"] = round(
                mode_s * 1e3, 2)
            out[f"serving_arena_publish_{mode}_speedup_x"] = round(
                dict_s / max(mode_s, 1e-9), 2)
        _log(f"[bench:arena] publish @{n_rows} rows: dict "
             f"{out['serving_arena_publish_dict_ms']} ms, copy "
             f"{out['serving_arena_publish_copy_ms']} ms "
             f"({out['serving_arena_publish_copy_speedup_x']}x), link "
             f"{out['serving_arena_publish_link_ms']} ms "
             f"({out['serving_arena_publish_link_speedup_x']}x), "
             f"reflink={out.get('serving_arena_reflink')}")
    except Exception:
        _log(traceback.format_exc())
        out["serving_arena_publish_error"] = traceback.format_exc(limit=3)
    finally:
        if table is not None:
            try:
                table.close()
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def run_serving_arena_ingest_section(small: bool) -> dict:
    """Round-17 native write plane A/B (ISSUE 17): the SAME columnar
    batches through the pure-Python seqlock writer (TPUMS_ARENA_BATCH=0)
    vs the C++ batch writer, on the same arena geometry.  Two regimes:

      cold    bulk load at BENCH_ARENA_INGEST_ROWS (1M full-scale) in
              8192-row batches — the bootstrap/journal-replay shape.
              Headline: ``serving_arena_ingest_cold_speedup_x`` with the
              ``serving_arena_ingest_10x_gate`` acceptance bit recorded
              honestly (what THIS box measured, pass or fail).
      drip    64-row in-place update batches — the steady-state update
              plane shape, where per-batch fixed costs dominate.

    The arena lives on /dev/shm when it fits (it is a SHARED-MEMORY
    arena — disk-backed tmp adds writeback throttling both arms pay but
    neither would see in production; ``serving_arena_ingest_shm`` says
    which medium this run measured) and both arms run with
    TPUMS_ARENA_PREFAULT=1 so first-touch faults — identical kernel
    work in either arm — don't drown the writer A/B.  Both arms finish
    with byte-identical arena files
    (``serving_arena_ingest_byte_parity``) — the speedup is only worth
    reporting if the fast path writes the exact same bytes.  A box where
    writer + bench share one core records
    ``serving_arena_ingest_core_starved``."""
    import random

    from flink_ms_tpu.serve.arena import ArenaModelTable

    out: dict = {}
    n_rows = int(os.environ.get("BENCH_ARENA_INGEST_ROWS",
                                20_000 if small else 1_000_000))
    drip_batches = int(os.environ.get("BENCH_ARENA_DRIP_BATCHES",
                                      50 if small else 2_000))
    drip_n = 64
    dim = 16
    rng = np.random.default_rng(17)
    shm_dir = "/dev/shm" if os.path.isdir("/dev/shm") else None
    if shm_dir is not None:
        try:  # both arena files plus slack must fit in the tmpfs
            need = 4 * n_rows * 300
            if shutil.disk_usage(shm_dir).free < need:
                shm_dir = None
        except OSError:
            shm_dir = None
    out["serving_arena_ingest_shm"] = shm_dir is not None
    tmp = tempfile.mkdtemp(prefix="bench_arena_ingest_", dir=shm_dir)
    try:
        n_cpus = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        n_cpus = os.cpu_count() or 1
    out["serving_arena_ingest_rows"] = n_rows
    out["serving_arena_ingest_cpus"] = n_cpus
    if n_cpus < 2:
        out["serving_arena_ingest_core_starved"] = True

    def payload(vec):
        return ";".join(repr(round(float(x), 4)) for x in vec)

    keys = [f"{u}-U" for u in range(n_rows)]
    vals = [payload(rng.normal(size=dim)) for _ in range(n_rows)]
    rnd = random.Random(17)
    drips = []
    for b in range(drip_batches):
        dk = [keys[rnd.randrange(n_rows)] for _ in range(drip_n)]
        drips.append((dk, [payload(rng.normal(size=dim)) for _ in dk]))

    # pre-size the geometry like bootstrap does from a snapshot: the A/B
    # question is the write plane, not the (identical-in-both-arms)
    # grow-and-rehash cost that would otherwise dominate at 1M rows
    cap = 1 << max(12, (int(n_rows / 0.8)).bit_length())
    stride = 1 << max(6, max(len(v) for v in vals).bit_length())
    out["serving_arena_ingest_capacity"] = cap
    out["serving_arena_ingest_stride"] = stride

    def run_arm(native: bool):
        prev = {k: os.environ.get(k)
                for k in ("TPUMS_ARENA_BATCH", "TPUMS_ARENA_PREFAULT")}
        os.environ["TPUMS_ARENA_BATCH"] = "1" if native else "0"
        os.environ["TPUMS_ARENA_PREFAULT"] = "1"
        t0 = time.perf_counter()
        try:
            t = ArenaModelTable(
                8, dir=os.path.join(tmp, "n" if native else "p"),
                capacity=cap, stride=stride)
        finally:
            for k, v in prev.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        out[f"serving_arena_ingest_{'native' if native else 'python'}"
            f"_create_s"] = round(time.perf_counter() - t0, 3)
        try:
            if native and t._writer_h is None:
                out["serving_arena_ingest_native_unavailable"] = True
            t0 = time.perf_counter()
            for i in range(0, n_rows, 8192):
                t.put_many_columns(keys[i:i + 8192], vals[i:i + 8192])
            cold_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            for dk, dv in drips:
                t.put_many_columns(list(dk), list(dv))
            drip_s = time.perf_counter() - t0
            t.flush()
            return cold_s, drip_s, t.arena.path
        finally:
            t.close()

    try:
        cold_n, drip_n_s, path_n = run_arm(True)
        cold_p, drip_p_s, path_p = run_arm(False)
        out["serving_arena_ingest_native_rows_per_s"] = round(
            n_rows / cold_n)
        out["serving_arena_ingest_python_rows_per_s"] = round(
            n_rows / cold_p)
        out["serving_arena_ingest_cold_speedup_x"] = round(
            cold_p / max(cold_n, 1e-9), 2)
        out["serving_arena_ingest_10x_gate"] = (
            out["serving_arena_ingest_cold_speedup_x"] >= 10.0)
        total_drip = drip_batches * drip_n
        out["serving_arena_drip_native_rows_per_s"] = round(
            total_drip / max(drip_n_s, 1e-9))
        out["serving_arena_drip_python_rows_per_s"] = round(
            total_drip / max(drip_p_s, 1e-9))
        out["serving_arena_drip_speedup_x"] = round(
            drip_p_s / max(drip_n_s, 1e-9), 2)
        with open(path_n, "rb") as fn_, open(path_p, "rb") as fp_:
            out["serving_arena_ingest_byte_parity"] = (
                fn_.read() == fp_.read())
        _log(f"[bench:arena-ingest] cold @{n_rows}: native "
             f"{out['serving_arena_ingest_native_rows_per_s']} rows/s vs "
             f"python {out['serving_arena_ingest_python_rows_per_s']} "
             f"({out['serving_arena_ingest_cold_speedup_x']}x, 10x gate "
             f"{'PASS' if out['serving_arena_ingest_10x_gate'] else 'FAIL'}"
             f"), drip {out['serving_arena_drip_speedup_x']}x, "
             f"byte_parity={out['serving_arena_ingest_byte_parity']}")
    except Exception:
        _log(traceback.format_exc())
        out["serving_arena_ingest_error"] = traceback.format_exc(limit=3)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def _edge_counter_total(name, **labels):
    """Sum a counter across the in-process metrics registry (the bench
    runs its EdgeProxy in-proc, so its counters land here)."""
    from flink_ms_tpu.obs import metrics as obs_metrics

    total = 0.0
    for c in obs_metrics.get_registry().snapshot().get("counters", []):
        if c["name"] != name:
            continue
        if labels and any(c.get("labels", {}).get(k) != v
                          for k, v in labels.items()):
            continue
        total += c["value"]
    return total


class _SlowableB2Worker:
    """A GET-only B2 worker replica for the hedge A/B: answers from a
    dict, and sleeps ``slow_s`` on a ``slow_frac`` fraction of GETs —
    the intermittently slow replica hedging exists to mask.  (Real
    ServingJobs can't inject slowness; overhead and coalescing are
    measured against a real worker, only the hedge arm uses this.)"""

    def __init__(self, store, *, slow_frac=0.0, slow_s=0.0, seed=0):
        import random
        import socket
        import threading

        from flink_ms_tpu.serve import proto

        self._proto = proto
        self.store = store
        self.slow_frac = slow_frac
        self.slow_s = slow_s
        self._rng = random.Random(seed)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        self._stop = False
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def stop(self):
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass

    def _accept_loop(self):
        import threading

        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn):
        proto = self._proto
        rfile = conn.makefile("rb")
        try:
            if not rfile.readline().decode().startswith(proto.HELLO_LINE):
                return
            conn.sendall((proto.HELLO_REPLY + "\n").encode())
            while not self._stop:
                magic = rfile.read(2)
                if magic != proto.MAGIC:
                    return
                n, shift = 0, 0
                while True:
                    b = rfile.read(1)
                    if not b:
                        return
                    n |= (b[0] & 0x7F) << shift
                    if not b[0] & 0x80:
                        break
                    shift += 7
                body = rfile.read(n)
                records, _ = proto.decode_request_frame(
                    proto.MAGIC + proto.encode_varint(n) + body,
                    trace=True)
                texts = []
                for parts in records:
                    parts = list(parts)
                    if parts and parts[-1].startswith("tid="):
                        parts.pop()
                    if parts[0] == "GET":
                        if self.slow_frac and \
                                self._rng.random() < self.slow_frac:
                            time.sleep(self.slow_s)
                        v = self.store.get(parts[2])
                        texts.append(f"V\t{v}" if v is not None else "N")
                    else:
                        texts.append("E\tbad request")
                conn.sendall(proto.encode_reply_frame(texts))
        except (OSError, ValueError):
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass


def run_serving_edge_section(small: bool) -> dict:
    """Edge proxy tier A/B (serve/edge.py, round 18).  Four arms, each
    answering one question the tier's design hinges on:

      overhead   direct-to-worker vs through-proxy sequential GET
                 latency against the SAME real ServingJob.  Target:
                 p99 overhead < 200µs.  On a box with < 3 usable cores
                 the proxy's event loop, the worker and the bench fight
                 for one CPU, so ``serving_edge_core_starved`` is
                 recorded and the gate is waived (honestly slow, not
                 unmeasurable-as-regression).
      coalesce   hit rate of cross-request GET coalescing under
                 zipf-distributed keys from concurrent pipelining
                 clients — the popularity skew the feature exists for.
      hedge      p999 hedged vs unhedged through two replicas, one of
                 which sleeps 30ms on 5% of its GETs (so ~2.5% of
                 round-robined requests stall; p95 stays fast and the
                 hedge trigger arms from the healthy percentile).
                 Gate: >= 2x p999 cut, same core-starvation waiver.
      idle       RSS footprint of a subprocess proxy holding thousands
                 of idle downstream connections (the millions-of-
                 connections claim, scaled to CI): kB per idle conn.
    """
    import socket
    import threading

    from flink_ms_tpu.serve import registry
    from flink_ms_tpu.serve.client import QueryClient
    from flink_ms_tpu.serve.consumer import (ALS_STATE, ServingJob,
                                             make_backend,
                                             parse_als_record)
    from flink_ms_tpu.serve.edge import (EdgeClient, EdgeProxy,
                                         spawn_edge_procs,
                                         stop_edge_procs)
    from flink_ms_tpu.serve.elastic import generation_group
    from flink_ms_tpu.serve.ha import shard_group
    from flink_ms_tpu.serve.journal import Journal

    n_users = 500 if small else 2_000
    n_gets = int(os.environ.get("BENCH_EDGE_GETS",
                                1_500 if small else 10_000))
    n_hedge = int(os.environ.get("BENCH_EDGE_HEDGE_GETS",
                                 2_000 if small else 8_000))
    n_conns = int(os.environ.get("BENCH_EDGE_CONNS",
                                 2_000 if small else 10_000))

    tmp = tempfile.mkdtemp(prefix="tpums_edge_bench_")
    saved = os.environ.get("TPUMS_REGISTRY_DIR")
    os.environ["TPUMS_REGISTRY_DIR"] = os.path.join(tmp, "registry")
    try:
        n_cpus = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        n_cpus = os.cpu_count() or 1
    starved = n_cpus < 3
    out: dict = {"serving_edge_cpus": n_cpus,
                 "serving_edge_core_starved": starved}
    job = proxy = hp = up = None
    hedge_workers = []
    idle_procs = []
    idle_socks = []
    errors = 0
    try:
        group = "bench-edge"
        journal = Journal(os.path.join(tmp, "bus"), "models")
        journal.append([f"{u},U,{u * 0.25};1.0;0.5;-0.25"
                        for u in range(n_users)])
        keys = [f"{u}-U" for u in range(n_users)]
        job = ServingJob(
            journal, ALS_STATE, parse_als_record,
            make_backend("memory", None),
            host="127.0.0.1", port=0, poll_interval_s=0.01,
            topk_index=False,
            replica_of=shard_group(generation_group(group, 1), 0),
            replica_index=0,
        ).start()
        assert job.wait_ready(120)
        registry.publish_topology(group, 1)

        # -- 1. direct vs through-proxy GET A/B --------------------------
        proxy = EdgeProxy(group, register=False, hedge=False).start()

        def time_gets(c, n):
            nonlocal errors
            lat = []
            rng = np.random.default_rng(18)
            idx = rng.integers(0, n_users, size=n)
            for i in range(n):
                t0 = time.perf_counter()
                if c.query_state(ALS_STATE, f"{int(idx[i])}-U") is None:
                    errors += 1
                lat.append((time.perf_counter() - t0) * 1e6)
            return lat

        with QueryClient("127.0.0.1", job.port, timeout_s=30) as dc:
            time_gets(dc, 200)  # warm both sides of the A/B
            direct_us = time_gets(dc, n_gets)
        with EdgeClient(endpoints=[("127.0.0.1", proxy.port)],
                        timeout_s=30) as pc:
            time_gets(pc, 200)
            proxy_us = time_gets(pc, n_gets)
        d_p = _pcts(direct_us)   # _pcts keys are ms-named; values here µs
        p_p = _pcts(proxy_us)
        overhead_us = round(p_p["p99"] - d_p["p99"], 1)
        out["serving_edge_direct_get_p50_us"] = d_p["p50"]
        out["serving_edge_direct_get_p99_us"] = d_p["p99"]
        out["serving_edge_proxy_get_p50_us"] = p_p["p50"]
        out["serving_edge_proxy_get_p99_us"] = p_p["p99"]
        out["serving_edge_overhead_p99_us"] = overhead_us
        _log(f"[bench:edge] GET p99 direct={d_p['p99']}us "
             f"proxy={p_p['p99']}us overhead={overhead_us}us "
             f"(core_starved={starved})")

        # -- 2. coalesce hit rate under zipf keys ------------------------
        hits0 = _edge_counter_total("tpums_edge_coalesce_hits_total")
        zipf_n = n_gets
        rng = np.random.default_rng(7)
        draws = np.minimum(rng.zipf(1.3, size=zipf_n) - 1,
                           n_users - 1)

        def zipf_client(slot):
            nonlocal errors
            mine = draws[slot::4]
            c = EdgeClient(endpoints=[("127.0.0.1", proxy.port)],
                           timeout_s=30)
            try:
                replies = c.pipeline(
                    [f"GET\t{ALS_STATE}\t{int(u)}-U" for u in mine],
                    window=32)
                errors += sum(1 for r in replies
                              if not r.startswith("V\t"))
            except Exception:
                errors += len(mine)
            finally:
                c.close()

        threads = [threading.Thread(target=zipf_client, args=(s,))
                   for s in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        coalesce_rate = (_edge_counter_total(
            "tpums_edge_coalesce_hits_total") - hits0) / max(zipf_n, 1)
        out["serving_edge_coalesce_hit_rate"] = round(coalesce_rate, 4)
        _log(f"[bench:edge] coalesce hit rate {coalesce_rate:.1%} "
             f"over {zipf_n} zipf GETs")

        # -- 3. hedged vs unhedged p999, one intermittently slow replica -
        hgroup = "bench-edge-h"
        store = {k: "1.0;1.0;1.0;1.0" for k in keys}
        hedge_workers = [
            _SlowableB2Worker(store),
            _SlowableB2Worker(store, slow_frac=0.05, slow_s=0.03, seed=3),
        ]
        for r, w in enumerate(hedge_workers):
            registry.register(
                f"bench:{hgroup}:s0r{r}", "127.0.0.1", w.port, ALS_STATE,
                replica_of=shard_group(generation_group(hgroup, 1), 0),
                replica=r, ready=True, ttl_s=600.0)
        registry.publish_topology(hgroup, 1)
        # floor the hedge delay at 5ms: far under the 30ms stall it must
        # cut, far over scheduler noise (a 1ms floor on a busy CI box
        # fires on noise, doubling load instead of cutting tail)
        hp = EdgeProxy(hgroup, register=False, coalesce=False,
                       hedge=True, hedge_warmup=32, hedge_pct=95,
                       hedge_min_ms=5.0).start()
        up = EdgeProxy(hgroup, register=False, coalesce=False,
                       hedge=False).start()

        def p999(lat):
            s = sorted(lat)
            return round(s[min(int(len(s) * 0.999), len(s) - 1)], 1)

        lat = {}
        for name, port in (("hedged", hp.port), ("unhedged", up.port)):
            with EdgeClient(endpoints=[("127.0.0.1", port)],
                            timeout_s=30) as c:
                time_gets(c, 200)  # arm the hedge latency window
                lat[name] = time_gets(c, n_hedge)
        hedged_p999 = p999(lat["hedged"])
        unhedged_p999 = p999(lat["unhedged"])
        ratio = round(unhedged_p999 / max(hedged_p999, 1e-9), 2)
        out["serving_edge_hedged_p999_us"] = hedged_p999
        out["serving_edge_unhedged_p999_us"] = unhedged_p999
        out["serving_edge_hedge_p999_ratio"] = ratio
        out["serving_edge_hedges_fired"] = round(_edge_counter_total(
            "tpums_edge_hedges_total", result="fired"))
        _log(f"[bench:edge] p999 unhedged={unhedged_p999}us "
             f"hedged={hedged_p999}us ratio={ratio}x")

        # -- 4. idle-connection memory footprint (subprocess proxy) ------
        try:
            import resource
            soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
            if soft < hard:
                resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
            n_conns = min(n_conns, max(hard - 512, 64))
        except Exception:
            pass

        def rss_kb(pid):
            with open(f"/proc/{pid}/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return int(line.split()[1])
            return None

        idle_procs, iports = spawn_edge_procs(
            group, 1, os.path.join(tmp, "idle_ports"))
        time.sleep(0.5)
        rss0 = rss_kb(idle_procs[0].pid)
        for _ in range(n_conns):
            s = socket.create_connection(("127.0.0.1", iports[0]),
                                         timeout=10)
            idle_socks.append(s)
        time.sleep(1.0)
        rss1 = rss_kb(idle_procs[0].pid)
        per_conn = (round((rss1 - rss0) / n_conns, 3)
                    if rss0 is not None and rss1 is not None else None)
        out["serving_edge_idle_conns"] = n_conns
        out["serving_edge_idle_rss_delta_kb"] = (
            rss1 - rss0 if per_conn is not None else None)
        out["serving_edge_idle_kb_per_conn"] = per_conn
        _log(f"[bench:edge] {n_conns} idle conns -> "
             f"{per_conn}kB/conn RSS")

        out["serving_edge_errors"] = errors
        out["serving_edge_ok"] = (
            errors == 0 and coalesce_rate > 0
            and (starved or overhead_us < 200.0)
            and (starved or ratio >= 2.0)
            and per_conn is not None)
        _log(f"[bench:edge] ok={out['serving_edge_ok']}")
    except Exception:
        _log(traceback.format_exc())
        out["serving_edge_error"] = traceback.format_exc(limit=3)
        out["serving_edge_ok"] = False
    finally:
        for s in idle_socks:
            try:
                s.close()
            except OSError:
                pass
        stop_edge_procs(idle_procs)
        for closer in (hp, up, proxy, job):
            if closer is not None:
                try:
                    closer.stop()
                except Exception:
                    pass
        for w in hedge_workers:
            w.stop()
        if saved is None:
            os.environ.pop("TPUMS_REGISTRY_DIR", None)
        else:
            os.environ["TPUMS_REGISTRY_DIR"] = saved
        shutil.rmtree(tmp, ignore_errors=True)
    return out

# ---------------------------------------------------------------------------
# continuous-profiling section: hot-frame attribution, CPU paging, fleet merge
# ---------------------------------------------------------------------------

def run_serving_profiler_section(small: bool) -> dict:
    """Continuous-profiling efficacy (obs/profiler.py + obs/profdiff.py +
    the watch plane's profile attach), the round-19 acceptance demo:

    1. **injected hot function** — a synthetic busy loop burns CPU under
       ``prof_stage("bench_hot")`` between two profiler snapshots; the
       ``profdiff`` regression diff must rank that frame **#1** with
       >= 90% delta-share (the CPU-gated sampler keeps the fleet's
       parked threads out of the denominator).
    2. **CPU alert carries the frame** — a watch-plane rate rule over
       ``tpums_process_cpu_seconds_total`` must fire on the burn AND its
       page must carry ``profile_top_frames`` naming the hot frame — the
       page NAMES the regressing code, not just the number.
    3. **fleet merge** — the PROFILE scrapes of two Python replicas and
       one native lookup server fold into ONE artifact (associative
       merge) holding both planes' cost: Python sampled stacks plus
       ``native;<verb>`` self-time.

    The hot-path overhead bar for the profiler lives in
    scripts/obs_overhead_ab.py (<= 3% GET p50, ABAB), not here.
    """
    import math

    from flink_ms_tpu.core import formats as F
    from flink_ms_tpu.obs import profdiff as PD
    from flink_ms_tpu.obs import profiler as P
    from flink_ms_tpu.obs.rules import Rule
    from flink_ms_tpu.obs.scrape import scrape_fleet_profiles
    from flink_ms_tpu.obs.watch import FleetWatcher
    from flink_ms_tpu.serve.client import QueryClient
    from flink_ms_tpu.serve.consumer import (ALS_STATE, ServingJob,
                                             make_backend,
                                             parse_als_record)
    from flink_ms_tpu.serve.journal import Journal
    from flink_ms_tpu.serve.native_store import (NativeLookupServer,
                                                 NativeStore)

    n_users = 200 if small else 1_000
    hot_s = float(os.environ.get("BENCH_PROF_HOT_S", 1.2))

    tmp = tempfile.mkdtemp(prefix="tpums_prof_bench_")
    saved = {k: os.environ.get(k)
             for k in ("TPUMS_REGISTRY_DIR", "TPUMS_PROF", "TPUMS_PROF_HZ",
                       "TPUMS_PROF_DIR", "TPUMS_PROF_FLUSH_S")}
    os.environ["TPUMS_REGISTRY_DIR"] = os.path.join(tmp, "registry")
    os.environ["TPUMS_PROF"] = "1"
    os.environ["TPUMS_PROF_HZ"] = "97"       # denser for a short bench
    os.environ["TPUMS_PROF_DIR"] = os.path.join(tmp, "prof")
    os.environ["TPUMS_PROF_FLUSH_S"] = "0.2"
    P.stop_profiler()  # fresh instance picks up the bench knobs
    out: dict = {}
    jobs = []
    nstore = nsrv = None
    watcher = None
    try:
        rng = np.random.default_rng(0)
        rows = [F.format_als_row(u, "U", rng.normal(size=4))
                for u in range(n_users)]
        for r in range(2):                   # two Python replicas
            journal = Journal(os.path.join(tmp, f"bus{r}"), "models")
            journal.append(rows)
            # slow poll: the replicas idle during the burn, and a 10ms
            # journal poll burns enough real CPU on a 1-core box to
            # dilute the hot frame's delta-share
            jobs.append(ServingJob(
                journal, ALS_STATE, parse_als_record,
                make_backend("memory", None),
                host="127.0.0.1", port=0, poll_interval_s=0.25,
            ).start())
        for job in jobs:
            assert job.wait_ready(120)
        nstore = NativeStore(os.path.join(tmp, "nstore"))
        for u in range(20):
            nstore.put(f"{u}-U", "0.5;1.5;0.25;-1.0")
        nsrv = NativeLookupServer(nstore, ALS_STATE, job_id="bench-native",
                                  port=0).__enter__()
        prof = P.get_profiler()
        assert prof is not None and prof.running

        # warm both planes so every replica has stacks / verb self-time
        qrng = np.random.default_rng(1)
        for job in jobs:
            with QueryClient("127.0.0.1", job.port, timeout_s=600) as c:
                c.topk(ALS_STATE, "1", 5)   # block through the jit warm
                for _ in range(50):
                    c.query_state(ALS_STATE,
                                  f"{int(qrng.integers(0, n_users))}-U")
        with QueryClient("127.0.0.1", nsrv.port, timeout_s=30) as c:
            for _ in range(200):
                c.query_state(ALS_STATE, f"{int(qrng.integers(0, 20))}-U")

        # rate = increase / window_s (not elapsed), so the window must be
        # about the burn length for a short burst to clear the bar
        rule = Rule(name="bench_cpu_regression", kind="threshold",
                    series=P.CPU_SECONDS_SERIES, mode="rate",
                    window_s=3.0, op=">", value=0.5, severity="page")
        watcher = FleetWatcher(interval_s=0.1, rules=[rule],
                               scope="bench_profiler")
        # settle: any straggling background compile (the replicas' topk
        # warm threads) dilutes the hot frame's delta-share on 1 core
        deadline = time.monotonic() + 30.0
        quiet = 0
        while quiet < 2 and time.monotonic() < deadline:
            c0 = P._process_cpu_s()
            time.sleep(0.25)
            quiet = quiet + 1 if P._process_cpu_s() - c0 < 0.05 else 0

        prof.flush()           # publish the CPU counter pre-burn
        watcher.tick()         # baseline scrape: rate + profile prev

        # -- 1. the injected hot function ------------------------------
        def _burn(stop: float) -> float:
            x = 0.0
            while time.perf_counter() < stop:
                x += math.sqrt(x + 1.0)
            return x

        base = prof.snapshot()
        with P.prof_stage("bench_hot"):
            _burn(time.perf_counter() + hot_s)
        prof.flush()           # publish the burned CPU immediately
        cur = prof.snapshot()

        rep = PD.diff_profiles(base, cur)
        frames = rep["frames"]
        top = frames[0] if frames else {}
        out["serving_profiler_samples"] = cur["samples"] - base["samples"]
        out["serving_profiler_top_frame"] = top.get("frame")
        out["serving_profiler_top_share"] = top.get("delta_share")
        out["serving_profiler_diff_ok"] = bool(
            str(top.get("frame", "")).endswith("._burn")
            and top.get("delta_share", 0.0) >= 0.9)
        _log(f"[bench:profiler] #1 frame {top.get('frame')} "
             f"({100 * (top.get('delta_share') or 0):.0f}% of the gap, "
             f"+{(top.get('delta_s') or 0):.2f}s)")

        # -- 2. the CPU page names the frame ---------------------------
        fired = None
        for _ in range(20):
            trs = watcher.tick()
            fired = next((t for t in trs
                          if t["kind"] == "alert_firing"
                          and t["rule"] == rule.name), None)
            if fired:
                break
            time.sleep(0.05)
        paged = [str(f.get("frame", ""))
                 for f in (fired or {}).get("profile_top_frames") or []]
        out["serving_profiler_alert_fired"] = fired is not None
        out["serving_profiler_page_frames"] = len(paged)
        out["serving_profiler_page_names_frame"] = any(
            f.endswith("._burn") for f in paged)
        _log(f"[bench:profiler] CPU alert fired={fired is not None} "
             f"page_frames={paged[:3]}")

        # -- 3. fleet merge across planes ------------------------------
        fleet = scrape_fleet_profiles()
        native_prof = P.scrape_profile("127.0.0.1", nsrv.port)
        merged = P.merge_profiles([fleet["fleet"]]
                                  + ([native_prof] if native_prof else []))
        native_keys = [k for k in merged["stacks"] if k.startswith("native;")]
        python_keys = [k for k in merged["stacks"]
                       if not k.startswith("native;")]
        out["serving_profiler_replicas"] = fleet["scraped"]
        out["serving_profiler_native_stacks"] = len(native_keys)
        out["serving_profiler_merged_planes"] = merged["meta"]["planes"]
        out["serving_profiler_merge_ok"] = (
            fleet["scraped"] >= 2 and len(native_keys) >= 1
            and len(python_keys) >= 1)
        artifact = os.path.join(os.environ["TPUMS_PROF_DIR"],
                                P.ARTIFACT_NAME)
        out["serving_profiler_artifact"] = os.path.exists(artifact)
        out["serving_profiler_ok"] = (
            out["serving_profiler_diff_ok"]
            and out["serving_profiler_alert_fired"]
            and out["serving_profiler_page_names_frame"]
            and out["serving_profiler_merge_ok"]
            and out["serving_profiler_artifact"])
        _log(f"[bench:profiler] replicas={fleet['scraped']} "
             f"native_stacks={len(native_keys)} "
             f"planes={merged['meta']['planes']} "
             f"ok={out['serving_profiler_ok']}")
    except Exception:
        _log(traceback.format_exc())
        out["serving_profiler_error"] = traceback.format_exc(limit=3)
        out["serving_profiler_ok"] = False
    finally:
        if watcher is not None:
            try:
                watcher.stop()
            except Exception:
                pass
        if nsrv is not None:
            try:
                nsrv.__exit__(None, None, None)
            except Exception:
                pass
        if nstore is not None:
            try:
                nstore.close()
            except Exception:
                pass
        for job in jobs:
            try:
                job.stop()
            except Exception:
                pass
        P.stop_profiler()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(tmp, ignore_errors=True)
    return out

# ---------------------------------------------------------------------------
# push-plane section: update->push latency, edge fan-out, re-score selectivity
# ---------------------------------------------------------------------------

def run_serving_push_section(small: bool) -> dict:
    """Push plane A/B (serve/push.py + the edge hub, round 20).  Three
    arms, each answering one question the subscription design hinges on:

      latency     update->push p99: a KEY subscriber on a direct B2
                  connection, timed from ``table.put`` to the delta
                  arriving at the client.  Target: p99 < 5ms.  On a box
                  with < 3 usable cores the engine's delivery thread,
                  the server and the bench fight for one CPU, so
                  ``serving_push_core_starved`` is recorded and the gate
                  is waived (honestly slow, not unmeasurable).
      fanout      amplification through the edge hub: N downstream KEY
                  subscribers on the same key collapse into ONE upstream
                  subscription; every update must reach all N.  Gate:
                  notifications/upstream-delta >= 100x with zero lost
                  deltas (every client drains exactly M pushes).
      selectivity re-score narrowing under zipf item updates: S TOPK
                  subscribers with diverse query vectors; the member
                  index + entrant filter must re-score only the
                  intersecting subset.  Gate: mean selectivity
                  (candidates / (batches * subs)) < 0.9 AND strictly
                  fewer re-scores than the re-score-everyone baseline.
    """
    import threading

    from flink_ms_tpu.serve import registry
    from flink_ms_tpu.serve.client import QueryClient
    from flink_ms_tpu.serve.consumer import ALS_STATE
    from flink_ms_tpu.serve.edge import EdgeClient, EdgeProxy
    from flink_ms_tpu.serve.elastic import generation_group
    from flink_ms_tpu.serve.ha import shard_group
    from flink_ms_tpu.serve.server import LookupServer
    from flink_ms_tpu.serve.table import ModelTable
    from flink_ms_tpu.serve.topk import make_als_topk_handler

    n_pushes = int(os.environ.get("BENCH_PUSH_UPDATES",
                                  400 if small else 2_000))
    n_fan = int(os.environ.get("BENCH_PUSH_FANOUT",
                               100 if small else 120))
    fan_updates = int(os.environ.get("BENCH_PUSH_FANOUT_UPDATES", 10))
    n_topk_subs = int(os.environ.get("BENCH_PUSH_TOPK_SUBS",
                                     48 if small else 64))
    n_items = 200 if small else 500
    sel_updates = int(os.environ.get("BENCH_PUSH_SEL_UPDATES",
                                     150 if small else 400))

    tmp = tempfile.mkdtemp(prefix="tpums_push_bench_")
    saved = os.environ.get("TPUMS_REGISTRY_DIR")
    os.environ["TPUMS_REGISTRY_DIR"] = os.path.join(tmp, "registry")
    try:
        n_cpus = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        n_cpus = os.cpu_count() or 1
    starved = n_cpus < 3
    out: dict = {"serving_push_cpus": n_cpus,
                 "serving_push_core_starved": starved}
    srv = proxy = None
    fan_clients = []
    try:
        rng = np.random.default_rng(20)
        table = ModelTable(4)
        for i in range(n_items):
            table.put(f"{i}-I", ";".join(
                f"{v:.4f}" for v in rng.normal(size=4)))
        table.put("7-U", "1.0;2.0;0.5;-1.0")
        srv = LookupServer(
            {ALS_STATE: table}, host="127.0.0.1", port=0,
            job_id="bench-push",
            topk_handlers={ALS_STATE: make_als_topk_handler(table)},
        ).start()

        # -- 1. update->push latency (direct B2 subscriber) --------------
        lat_ms = []
        with QueryClient("127.0.0.1", srv.port, proto="b2",
                         push=True, timeout_s=30) as c:
            sub = c.subscribe_key(ALS_STATE, "0-I")
            lost = 0
            for i in range(n_pushes):
                val = f"{i}.0;1.0;2.0;3.0"
                t0 = time.perf_counter()
                table.put("0-I", val)
                msg = c.next_push(timeout_s=5.0)
                dt = (time.perf_counter() - t0) * 1e3
                if msg is None or msg[2] != val:
                    lost += 1
                else:
                    lat_ms.append(dt)
            c.unsubscribe(sub["sub_id"])
        p = _pcts(lat_ms) if lat_ms else {"p50": None, "p95": None,
                                          "p99": None}
        out["serving_push_latency_p50_ms"] = p["p50"]
        out["serving_push_latency_p99_ms"] = p["p99"]
        out["serving_push_latency_lost"] = lost
        _log(f"[bench:push] update->push p50={p['p50']}ms "
             f"p99={p['p99']}ms over {len(lat_ms)} updates "
             f"(core_starved={starved})")

        # -- 2. fan-out amplification through the edge hub ---------------
        group = "bench-push"
        registry.register(
            f"w:{srv.port}", "127.0.0.1", srv.port, ALS_STATE,
            replica_of=shard_group(
                generation_group(registry.qualify_group(group), 1), 0),
            replica=0, ready=True, ttl_s=600.0)
        registry.publish_topology(group, 1)
        proxy = EdgeProxy(group, register=False, hedge=False).start()
        up0 = _edge_counter_total("tpums_push_upstream_deltas_total")
        notif0 = _edge_counter_total("tpums_push_notifications_total")
        for i in range(n_fan):
            fc = EdgeClient(endpoints=[("127.0.0.1", proxy.port)],
                            proto="b2", push=True, timeout_s=30)
            fc.subscribe_key(ALS_STATE, "1-I")
            fan_clients.append(fc)
        fan_lost = 0
        for m in range(fan_updates):
            table.put("1-I", f"9.0;9.0;9.0;{m}.0")
            time.sleep(0.05)  # let the hub drain between bursts
        deadline = time.time() + 30
        for fc in fan_clients:
            got = 0
            while got < fan_updates and time.time() < deadline:
                if fc.next_push(timeout_s=1.0) is not None:
                    got += 1
            fan_lost += fan_updates - got

        up_deltas = _edge_counter_total(
            "tpums_push_upstream_deltas_total") - up0
        notifications = _edge_counter_total(
            "tpums_push_notifications_total") - notif0
        amplification = (round(notifications / up_deltas, 1)
                         if up_deltas else None)
        out["serving_push_fanout_subs"] = n_fan
        out["serving_push_fanout_upstream_deltas"] = round(up_deltas)
        out["serving_push_fanout_notifications"] = round(notifications)
        out["serving_push_fanout_amplification"] = amplification
        out["serving_push_fanout_lost"] = fan_lost
        _log(f"[bench:push] fan-out {n_fan} subs x {fan_updates} "
             f"updates -> {amplification}x amplification, "
             f"lost={fan_lost}")
        for fc in fan_clients:
            fc.close()
        fan_clients = []

        # -- 3. re-score selectivity under zipf item updates -------------
        topk_clients = []
        for s in range(n_topk_subs):
            tc = QueryClient("127.0.0.1", srv.port, proto="b2",
                             push=True, timeout_s=30)
            vec = rng.normal(size=4)
            tc.subscribe_topk(
                ALS_STATE, ";".join(f"{v:.4f}" for v in vec), 8)
            topk_clients.append(tc)
        eng = srv._push_engine
        b0, c0, t0_, r0 = (eng.batches, eng.candidates,
                           eng.candidate_total, eng.rescored)
        draws = np.minimum(rng.zipf(1.3, size=sel_updates) - 1,
                           n_items - 1)
        for i, d in enumerate(draws):
            table.put(f"{int(d)}-I", ";".join(
                f"{v:.4f}" for v in rng.normal(size=4) * 0.5))
            if i % 25 == 0:
                time.sleep(0.05)  # mix batched and solo dirty sets
        deadline = time.time() + 15
        while eng.batches == b0 or eng.candidate_total == t0_:
            if time.time() > deadline:
                break
            time.sleep(0.05)
        time.sleep(0.5)  # drain the last dirty batch
        batches = eng.batches - b0
        candidates = eng.candidates - c0
        population = eng.candidate_total - t0_
        rescored = eng.rescored - r0
        selectivity = (round(candidates / population, 4)
                       if population else None)
        out["serving_push_sel_batches"] = batches
        out["serving_push_sel_rescored"] = rescored
        out["serving_push_sel_population"] = population
        out["serving_push_selectivity"] = selectivity
        _log(f"[bench:push] selectivity {selectivity} "
             f"({rescored} rescored / {population} sub-batches "
             f"over {batches} zipf batches)")
        for tc in topk_clients:
            tc.close()

        out["serving_push_ok"] = (
            lost == 0 and fan_lost == 0
            and (starved or (p["p99"] is not None and p["p99"] < 5.0))
            and amplification is not None and amplification >= 100.0
            and selectivity is not None and selectivity < 0.9
            and population > 0 and rescored < population)
        _log(f"[bench:push] ok={out['serving_push_ok']}")
    except Exception:
        _log(traceback.format_exc())
        out["serving_push_error"] = traceback.format_exc(limit=3)
        out["serving_push_ok"] = False
    finally:
        for fc in fan_clients:
            try:
                fc.close()
            except Exception:
                pass
        for closer in (proxy, srv):
            if closer is not None:
                try:
                    closer.stop()
                except Exception:
                    pass
        if saved is None:
            os.environ.pop("TPUMS_REGISTRY_DIR", None)
        else:
            os.environ["TPUMS_REGISTRY_DIR"] = saved
        shutil.rmtree(tmp, ignore_errors=True)
    return out
