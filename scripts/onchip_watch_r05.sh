#!/usr/bin/env bash
# Round-5 recovery watcher: loop a COMPILE-level probe (scripts/
# compile_probe.py — a devices() listing is not evidence, see the r4/r5
# wedge) and launch the queued measurement plan (scripts/onchip_r05.sh)
# the moment a real jit round-trips.  One plan launch per watcher life;
# the digest (scripts/onchip_digest.py) is left to the operator so a
# short window is spent measuring.
#
# Usage: nohup bash scripts/onchip_watch_r05.sh &   (log: $LOG)
LOG="${LOG:-scripts/onchip_watch_r05.log}"
DEADLINE_S="${DEADLINE_S:-36000}"   # 10h
SLEEP_S="${SLEEP_S:-240}"
HANG_SLEEP_S="${HANG_SLEEP_S:-900}" # a hung probe IS the wedge signature —
                                    # back off so a long outage costs one
                                    # 240s hang per window, not per loop
                                    # (mirrors chip_probe.sh's policy)
start=$(date +%s)
cd "$(dirname "$0")/.."
echo "$(date +%H:%M:%S) watcher up (compile-level probe)" >> "$LOG"
while :; do
  now=$(date +%s)
  if (( now - start > DEADLINE_S )); then
    echo "$(date +%H:%M:%S) deadline — compiles never recovered" >> "$LOG"
    exit 1
  fi
  out=$(timeout 240 python scripts/compile_probe.py 2>/dev/null)
  rc=$?
  out=${out##*$'\n'}
  if [ "$rc" -eq 0 ]; then
    echo "$(date +%H:%M:%S) COMPILES OK ($out) — launching onchip_r05" >> "$LOG"
    bash scripts/onchip_r05.sh scripts/onchip_r05 \
      > scripts/onchip_r05_driver.log 2>&1
    echo "$(date +%H:%M:%S) plan finished rc=$? — run scripts/onchip_digest.py" >> "$LOG"
    exit 0
  fi
  echo "$(date +%H:%M:%S) not ready (rc=$rc ${out:-hang})" >> "$LOG"
  if [ "$rc" -eq 124 ]; then
    sleep "$HANG_SLEEP_S"
  else
    sleep "$SLEEP_S"
  fi
done
