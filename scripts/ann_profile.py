#!/usr/bin/env python
"""Retrieval-plane microbench: exact host index vs mesh-sharded exact vs
IVF ANN, with a recall parity assert (ISSUE 11).

Arms (all over the same clustered synthetic catalog — a mixture of
gaussians, the geometry ALS item factors actually have, and the one IVF's
recall contract is calibrated against):

- ``build``    — time to stand up each tier (device placement + scatter
  warm-up; for IVF also k-means training, the full assignment pass, and
  the build-time recall probe);
- ``probe``    — batched TOPK qps through each tier's steady-state frame
  program (the microbatcher's dispatch path);
- ``re-rank``  — the IVF shortlist re-rank in isolation (probe minus
  coarse quantizer), to show where the ANN milliseconds go.

Parity: IVF results are compared against the exact tier's on the same
query frames — recall@k must clear ``--recallMin`` (default 0.95) or the
script exits non-zero.  Sharded-exact results must match single-device
results EXACTLY (same ids, scores to float tolerance): sharding is a
layout change, not an approximation.

Run host-side (no accelerator needed; the mesh is forced host devices):

    python scripts/ann_profile.py [--rows 200000] [--k 16] [--devices 8] \
        [--frame 16] [--topk 100] [--nlist 0] [--nprobe 0] \
        [--trials 30] [--json false]

``--json true`` prints one machine-readable result object on stdout
(human lines go to stderr) — the ``serving_ann`` bench section consumes
this.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("TPUMS_TOPK_PLATFORM", "cpu")
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _force_devices(n: int) -> None:
    """Must run before jax import: the sharded arm needs a multi-device
    host mesh, which on CPU exists only via this XLA flag."""
    flag = f"--xla_force_host_platform_device_count={n}"
    prior = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in prior:
        os.environ["XLA_FLAGS"] = (prior + " " + flag).strip()


def make_catalog(n: int, d: int, seed: int = 0):
    """Clustered item factors + user-like queries.  Items are a mixture
    of gaussians (ALS factor geometry: items cluster by taste dimension);
    queries are smooth mixtures of cluster directions (users straddle
    tastes) — the harder case for IVF, and the one served in production."""
    import numpy as np

    rng = np.random.default_rng(seed)
    n_clusters = max(16, min(256, n // 2000))
    cents = rng.normal(size=(n_clusters, d)).astype(np.float32) * 3.0
    assign = rng.integers(0, n_clusters, size=n)
    rows = cents[assign] + rng.normal(size=(n, d)).astype(np.float32) * 0.6
    w = rng.dirichlet(np.ones(4), size=512).astype(np.float32)
    picks = rng.integers(0, n_clusters, size=(512, 4))
    queries = np.einsum("qm,qmd->qd", w, cents[picks]).astype(np.float32)
    queries += rng.normal(size=queries.shape).astype(np.float32) * 0.2
    return rows, queries


def build_index(rows, ids, env: dict):
    """One DeviceFactorIndex under the given knob env, bulk-loaded with
    the catalog -> (index, build_seconds)."""
    from flink_ms_tpu.serve.table import ModelTable
    from flink_ms_tpu.serve.topk import DeviceFactorIndex

    prior = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        t0 = time.perf_counter()
        idx = DeviceFactorIndex(ModelTable(), "-I")
        idx.bulk_load(ids, rows)
        build_s = time.perf_counter() - t0
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return idx, build_s


def measure_qps(idx, queries, frame: int, k: int, trials: int):
    """Steady-state batched qps through ``topk_many`` -> (qps, p50_ms,
    p99_ms).  Frames rotate through the query pool so caching can't
    flatter the number."""
    import numpy as np

    frames = [
        queries[(i * frame) % (len(queries) - frame):][:frame]
        for i in range(trials + 3)
    ]
    for f in frames[:3]:
        idx.topk_many(f, k)  # warm the (frame, k) program
    lat = []
    t0 = time.perf_counter()
    for f in frames[3:]:
        t1 = time.perf_counter()
        idx.topk_many(f, k)
        lat.append(time.perf_counter() - t1)
    dt = time.perf_counter() - t0
    return (
        trials * frame / dt,
        float(np.percentile(lat, 50) * 1e3),
        float(np.percentile(lat, 99) * 1e3),
    )


def recall_vs(exact_idx, ann_idx, queries, k: int) -> float:
    hits = total = 0
    for q0 in range(0, min(len(queries), 128), 32):
        batch = queries[q0:q0 + 32]
        ref = exact_idx.topk_many(batch, k)
        got = ann_idx.topk_many(batch, k)
        for r, g in zip(ref, got):
            ref_ids = {i for i, _ in r}
            hits += len(ref_ids & {i for i, _ in g})
            total += len(ref_ids)
    return hits / max(total, 1)


def main(argv=None) -> int:
    from flink_ms_tpu.core.params import Params

    params = Params.from_args(sys.argv[1:] if argv is None else argv)
    rows_n = params.get_int("rows", 200_000)
    d = params.get_int("k", 16)
    devices = params.get_int("devices", 8)
    frame = params.get_int("frame", 16)
    topk = params.get_int("topk", 100)
    nlist = params.get_int("nlist", 0)
    nprobe = params.get_int("nprobe", 0)
    trials = params.get_int("trials", 30)
    recall_min = float(params.get("recallMin", "0.95"))
    as_json = params.get_bool("json", False)
    _force_devices(devices)

    import numpy as np  # noqa: F401  (after XLA_FLAGS is set)

    say = lambda m: print(m, file=sys.stderr)  # noqa: E731
    say(f"[ann-profile] catalog: {rows_n} rows x {d} dims, "
        f"{devices} forced host devices")
    rows, queries = make_catalog(rows_n, d)
    ids = [f"it{i}" for i in range(rows_n)]
    if nlist:
        os.environ["TPUMS_ANN_NLIST"] = str(nlist)
    if nprobe:
        os.environ["TPUMS_ANN_NPROBE"] = str(nprobe)

    result = {"rows": rows_n, "dims": d, "devices": devices,
              "frame": frame, "topk": topk}

    # -- arm 1: single-device exact (the current host path — baseline) --
    exact_idx, b_s = build_index(
        rows, ids, {"TPUMS_TOPK_SHARDED": "0", "TPUMS_TOPK_TIER": "exact"})
    qps, p50, p99 = measure_qps(exact_idx, queries, frame, topk, trials)
    result.update(exact_build_s=b_s, exact_qps=qps,
                  exact_p50_ms=p50, exact_p99_ms=p99)
    say(f"[ann-profile] exact/host:    build {b_s:6.2f}s  "
        f"{qps:>9,.0f} qps  p50 {p50:.2f}ms p99 {p99:.2f}ms")

    # -- arm 2: mesh-sharded exact --
    shard_idx, b_s = build_index(
        rows, ids, {"TPUMS_TOPK_SHARDED": "1", "TPUMS_TOPK_TIER": "exact"})
    assert shard_idx._is_sharded, "sharded arm did not engage the mesh"
    qps, p50, p99 = measure_qps(shard_idx, queries, frame, topk, trials)
    result.update(sharded_build_s=b_s, sharded_qps=qps,
                  sharded_p50_ms=p50, sharded_p99_ms=p99,
                  sharded_speedup=qps / max(result["exact_qps"], 1e-9))
    say(f"[ann-profile] exact/sharded: build {b_s:6.2f}s  "
        f"{qps:>9,.0f} qps  p50 {p50:.2f}ms p99 {p99:.2f}ms  "
        f"({result['sharded_speedup']:.2f}x vs host)")
    # layout parity: same ids, same scores (sharding is not approximate)
    ref = exact_idx.topk_many(queries[:8], 10)
    got = shard_idx.topk_many(queries[:8], 10)
    for r, g in zip(ref, got):
        assert [i for i, _ in r] == [i for i, _ in g], \
            "PARITY FAILURE: sharded ids differ from single-device"
        assert all(abs(a - b) < 1e-3 for (_, a), (_, b) in zip(r, g)), \
            "PARITY FAILURE: sharded scores differ from single-device"

    # -- arm 3: IVF ANN (forced tier; probe+re-rank timed inside) --
    ann_idx, b_s = build_index(
        rows, ids, {"TPUMS_TOPK_SHARDED": "0", "TPUMS_TOPK_TIER": "ivf"})
    assert ann_idx._ann is not None, "IVF arm did not build an ANN tier"
    ann = ann_idx._ann
    qps, p50, p99 = measure_qps(ann_idx, queries, frame, topk, trials)
    recall = recall_vs(exact_idx, ann_idx, queries, topk)
    result.update(
        ivf_build_s=b_s, ivf_qps=qps, ivf_p50_ms=p50, ivf_p99_ms=p99,
        ivf_speedup=qps / max(result["exact_qps"], 1e-9),
        ivf_nlist=ann.nlist, ivf_nprobe=ann.nprobe,
        ivf_list_len=ann.list_len, ivf_dropped=ann.dropped,
        ivf_recall_probe=ann.recall_probe, recall_at_k=recall,
        recall_min=recall_min,
    )
    say(f"[ann-profile] ivf:           build {b_s:6.2f}s  "
        f"{qps:>9,.0f} qps  p50 {p50:.2f}ms p99 {p99:.2f}ms  "
        f"({result['ivf_speedup']:.2f}x vs exact)  "
        f"nlist={ann.nlist} nprobe={ann.nprobe} "
        f"recall@{topk}={recall:.3f} (probe {ann.recall_probe:.3f})")

    # -- re-rank arm: shortlist scoring in isolation (coarse probe cost =
    # ivf total minus this) --
    import jax

    mat = ann_idx._matrix
    q_dev = jax.device_put(queries[:frame])
    ann.search(mat, q_dev, topk)[0].block_until_ready()  # warm
    t0 = time.perf_counter()
    for _ in range(trials):
        ann.search(mat, q_dev, topk)[0].block_until_ready()
    rr = (time.perf_counter() - t0) / trials
    result["ivf_search_kernel_ms"] = rr * 1e3
    say(f"[ann-profile] ivf kernel:    {rr * 1e3:.2f}ms/frame "
        f"(probe+gather+re-rank, host formatting excluded)")

    ok = recall >= recall_min
    result["recall_ok"] = ok
    if as_json:
        print(json.dumps(result))
    if not ok:
        say(f"[ann-profile] RECALL GATE FAILED: {recall:.3f} < {recall_min}")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
