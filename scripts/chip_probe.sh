#!/usr/bin/env bash
# Background chip-recovery watcher.  Loops until the axon TPU tunnel answers
# a real jax.devices() probe with a non-cpu platform, then exits 0 so the
# invoking shell/agent gets a completion signal.  Exits 1 at the deadline.
#
# Probe policy (see memory: axon-tunnel-wedge-workaround):
#   - cheap TCP probe of the loopback relay first: connect + immediate EOF
#     is the wedge fingerprint and costs <1s, so the expensive probe is
#     skipped while the relay is known-dead;
#   - every FULL_EVERY iterations run the real subprocess jax probe anyway
#     (the wedge fingerprint is an observation, not a contract);
#   - the jax probe runs in a subprocess under timeout: a wedged tunnel
#     HANGS backend init rather than erroring.
LOG="${LOG:-/tmp/chip_status_r3}"
DEADLINE_S="${DEADLINE_S:-39600}"   # 11h
SLEEP_S="${SLEEP_S:-300}"
FULL_EVERY="${FULL_EVERY:-6}"
start=$(date +%s)
i=0
cd "$(dirname "$0")/.."
while :; do
  now=$(date +%s)
  if (( now - start > DEADLINE_S )); then
    echo "$(date +%H:%M:%S) deadline reached, chip never recovered" >> "$LOG"
    exit 1
  fi
  i=$((i + 1))
  cheap=$(python - <<'EOF'
import socket
try:
    s = socket.create_connection(("127.0.0.1", 2024), timeout=5)
    s.settimeout(3)
    try:
        data = s.recv(16)
        print("wedged" if data == b"" else "maybe")
    except socket.timeout:
        print("maybe")
    finally:
        s.close()
except Exception:
    print("refused")
EOF
)
  if [ "$cheap" = "maybe" ] || (( i % FULL_EVERY == 0 )); then
    if timeout 120 python -c "
from flink_ms_tpu.parallel.mesh import honor_platform_env
honor_platform_env()
import jax
assert jax.devices()[0].platform != 'cpu'
" >/dev/null 2>&1; then
      echo "$(date +%H:%M:%S) UP (cheap=$cheap)" >> "$LOG"
      exit 0
    fi
    echo "$(date +%H:%M:%S) down (full probe failed, cheap=$cheap)" >> "$LOG"
  else
    echo "$(date +%H:%M:%S) down (cheap=$cheap)" >> "$LOG"
  fi
  sleep "$SLEEP_S"
done
