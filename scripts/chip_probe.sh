#!/usr/bin/env bash
# Background chip-recovery watcher.  Loops until the axon TPU tunnel answers
# a real jax.devices() probe with a non-cpu platform, then exits 0 so the
# invoking shell/agent gets a completion signal.  Exits 1 at the deadline.
#
# Probe policy:
#   - cheap TCP connect of the loopback relay first; only a REFUSED connect
#     skips the expensive probe (round 3 observed a healthy chip answering
#     jax probes behind a relay that still EOF'd instantly, so the old
#     connect+EOF "wedge fingerprint" is a known false positive — no byte is
#     read, the connect result is the whole signal);
#   - a full probe that HANGS to its timeout (rc=124) is the one reliable
#     wedge signature: further full probes are skipped for HANG_BACKOFF_S
#     so a long outage costs one hung probe per backoff window, not per
#     iteration (mirrors bench.py's PROBE_HANG_BACKOFF_S memo);
#   - every FULL_EVERY iterations run the real subprocess jax probe anyway
#     (even refused/backoff is an observation, not a contract);
#   - the jax probe runs in a subprocess under timeout: a wedged tunnel
#     HANGS backend init rather than erroring.
LOG="${LOG:-/tmp/chip_status_r3}"
DEADLINE_S="${DEADLINE_S:-39600}"   # 11h
SLEEP_S="${SLEEP_S:-300}"
FULL_EVERY="${FULL_EVERY:-6}"
HANG_BACKOFF_S="${HANG_BACKOFF_S:-900}"
start=$(date +%s)
i=0
last_hang=0
cd "$(dirname "$0")/.."
while :; do
  now=$(date +%s)
  if (( now - start > DEADLINE_S )); then
    echo "$(date +%H:%M:%S) deadline reached, chip never recovered" >> "$LOG"
    exit 1
  fi
  i=$((i + 1))
  cheap=$(python - <<'EOF'
import socket
try:
    socket.create_connection(("127.0.0.1", 2024), timeout=5).close()
    print("open")
except Exception:
    print("refused")
EOF
)
  skip=""
  [ "$cheap" = "refused" ] && skip=refused
  (( now - last_hang < HANG_BACKOFF_S )) && skip=hang-backoff
  if [ -z "$skip" ] || (( i % FULL_EVERY == 0 )); then
    timeout 120 python -c "
from flink_ms_tpu.parallel.mesh import honor_platform_env
honor_platform_env()
import jax
assert jax.devices()[0].platform != 'cpu'
" >/dev/null 2>&1
    rc=$?
    if (( rc == 0 )); then
      echo "$(date +%H:%M:%S) UP (cheap=$cheap)" >> "$LOG"
      exit 0
    fi
    (( rc == 124 )) && last_hang=$(date +%s)
    echo "$(date +%H:%M:%S) down (full probe rc=$rc, cheap=$cheap)" >> "$LOG"
  else
    echo "$(date +%H:%M:%S) down ($skip)" >> "$LOG"
  fi
  sleep "$SLEEP_S"
done
