#!/usr/bin/env python
"""Digest an onchip_r0N output directory into a decision table.

Each A/B step in scripts/onchip_r04.sh / onchip_r05.sh writes a log whose
LAST JSON-parseable line is the bench `--sections-json` artifact (probe
steps print their own summaries).  This prints the headline key per step
side by side and states the knob decision each pair implies, so a short
tunnel-recovery window is spent measuring, not log-grubbing.

  python scripts/onchip_digest.py [outdir]   (default scripts/onchip_r05)
"""

import json
import os
import sys


def last_json(path):
    try:
        lines = open(path, errors="replace").read().splitlines()
    except OSError:
        return None
    for line in reversed(lines):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def main():
    out = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "onchip_r05")
    if not os.path.isdir(out):
        sys.exit(f"no such outdir: {out}")

    def val(name, *keys):
        """Headline key from a step's artifact — CHIP runs only: a wedged
        step degrades to CPU (or gets SIGTERM'd) yet still emits a full
        artifact, and comparing that against a chip number would flip the
        recommendation (bench.py's own recovery-merge applies the same
        platform/degraded guard)."""
        j = last_json(os.path.join(out, name + ".log"))
        if j is None:
            return None
        if (j.get("platform") == "cpu" or j.get("degraded")
                or j.get("terminated")):
            print(f"  !! {name}: artifact is {j.get('platform')}/"
                  f"degraded={j.get('degraded')} — NOT a chip number, "
                  "excluded")
            return None
        for k in keys:
            if j.get(k) is not None:
                return j[k]
        return None

    print(f"== {out} ==")
    # ALS assembly A/B (5M-nnz probe config): sec/iter, lower wins
    ax = val("als_ab_xla", "value")
    ap = val("als_ab_pallas", "value")
    print(f"ALS assembly   xla={ax}  pallas={ap}  s/iter")
    if ax and ap:
        win = "pallas" if ap < ax else "xla"
        print(f"  -> FLINK_MS_ALS_ASSEMBLY auto should resolve to {win} "
              f"({min(ax, ap) / max(ax, ap):.2f}x)")

    # SVM boundary A/B at RCV1 scale: sec/round, lower wins
    sb = val("svm_ab_base", "svm_rcv1_sec_per_round")
    sp = val("svm_ab_pallas", "svm_rcv1_sec_per_round")
    print(f"SVM boundary   base={sb}  pallas={sp}  s/round")
    if sb and sp:
        win = "pallas" if sp < sb else "einsum/direct"
        print(f"  -> FLINK_MS_SVM_WX0/DW auto should stay/become {win} "
              f"({min(sb, sp) / max(sb, sp):.2f}x)")
        host = 0.339  # BASELINE.md "RCV1 ... Gram inner loop" host-r3 row
        best = min(sb, sp)
        print(f"  -> vs the host {host} s/round (BASELINE.md host-r3 row — "
              f"re-check that row before trusting): {host / best:.2f}")

    # full bench: headline + quality anchor
    fb = last_json(os.path.join(out, "bench_full.log"))
    if fb:
        print(f"full bench     {fb.get('metric')}={fb.get('value')} "
              f"{fb.get('unit')} vs_baseline={fb.get('vs_baseline')} "
              f"mfu={fb.get('mfu')} rmse_ref_delta="
              f"{fb.get('als_rmse_ref_delta')} "
              f"[platform={fb.get('platform')} "
              f"degraded={fb.get('degraded')}]")

    # bf16 exchange quality at full scale (r05 extra step)
    bq = last_json(os.path.join(out, "als_bf16_quality.log"))
    if bq:
        print(f"bf16 exchange  rmse_ref_delta={bq.get('als_rmse_ref_delta')} "
              f"value={bq.get('value')} s/iter "
              f"(CPU-measured quality: -5.4e-6 @5M, +3.1e-6 @20M — "
              f"BASELINE.md)")

    for probe in ("gather_probe_small", "gather_probe_ml20m",
                  "gather_tile16", "gather_tile32", "svm_probe"):
        p = os.path.join(out, probe + ".log")
        if os.path.exists(p):
            tail = open(p, errors="replace").read().splitlines()[-3:]
            print(f"-- {probe}: " + " | ".join(t.strip() for t in tail))


if __name__ == "__main__":
    main()
