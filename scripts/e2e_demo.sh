#!/usr/bin/env bash
# End-to-end walkthrough of the full reference workflow on synthetic data:
#
#   train -> mean-vector -> publish -> serve -> query load -> online SGD
#   (closed loop) -> MSE against the live model
#
# mirroring the reference's operational pipeline (SURVEY.md §3): ALSImpl ->
# ALSMeanVector -> ALSKafkaProducer -> ALSKafkaConsumer -> ALSPredictRandom
# -> SGD -> MSE, with the journal standing in for the Kafka topic and the
# lookup server for Flink queryable state.
#
# Usage: scripts/e2e_demo.sh [workdir]    (defaults to a fresh mktemp dir)
# Runs anywhere: CPU by default (DEMO_PLATFORM=tpu-or-other to override);
# the ambient JAX_PLATFORMS is ignored so the demo works without a chip.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=${DEMO_PLATFORM:-cpu}
WORK=${1:-$(mktemp -d /tmp/flink-ms-tpu-demo.XXXXXX)}
mkdir -p "$WORK"
PY=${PYTHON:-python}
PORT=${PORT:-16123}
JOB_ID=demo-$$

echo "== workspace: $WORK  (serving on 127.0.0.1:$PORT, job $JOB_ID)"

echo "== [1/8] synthetic ratings (50 users x 40 items, 2000 ratings)"
$PY - "$WORK" <<'PYEOF'
import sys, numpy as np
work = sys.argv[1]
rng = np.random.default_rng(42)
n = 2000
users = rng.integers(0, 50, n)
items = rng.integers(0, 40, n)
# low-rank ground truth so training + online updates have signal
uf = rng.normal(size=(50, 4)); vf = rng.normal(size=(40, 4))
ratings = (uf[users] * vf[items]).sum(1) + rng.normal(scale=0.1, size=n)
with open(f"{work}/ratings.tsv", "w") as f:
    f.write("user\titem\trating\n")
    for u, i, r in zip(users, items, ratings):
        f.write(f"{u}\t{i}\t{r:.4f}\n")
# a later batch of "fresh" ratings for the online-SGD update stream
m = 500
uu = rng.integers(0, 50, m); ii = rng.integers(0, 40, m)
rr = (uf[uu] * vf[ii]).sum(1) + rng.normal(scale=0.1, size=m)
with open(f"{work}/updates.tsv", "w") as f:
    for u, i, r in zip(uu, ii, rr):
        f.write(f"{u}\t{i}\t{r:.4f}\n")
PYEOF

echo "== [2/8] batch ALS training (als_train ~ ALSImpl)"
$PY -m flink_ms_tpu.train.als_train \
  --input "$WORK/ratings.tsv" --fieldDelimiter tab --ignoreFirstLine true \
  --iterations 5 --numFactors 8 --lambda 0.1 \
  --userFactors "$WORK/model/userFactors" --itemFactors "$WORK/model/itemFactors"

echo "== [3/8] cold-start mean vectors (mean_vector ~ ALSMeanVector)"
$PY -m flink_ms_tpu.eval.mean_vector --type user \
  --input "$WORK/model/userFactors" --output "$WORK/model/meanU"
$PY -m flink_ms_tpu.eval.mean_vector --type item \
  --input "$WORK/model/itemFactors" --output "$WORK/model/meanI"

echo "== [4/8] publish model rows into the journal (als_producer ~ ALSKafkaProducer)"
$PY -m flink_ms_tpu.serve.als_producer \
  --input "$WORK/model" --journalDir "$WORK/journal" --topic als-model

echo "== [5/8] serving job (als_consumer ~ ALSKafkaConsumer) in background"
$PY -m flink_ms_tpu.serve.als_consumer \
  --journalDir "$WORK/journal" --topic als-model \
  --stateBackend fs --checkpointDataUri "$WORK/ckpt" \
  --host 127.0.0.1 --port "$PORT" --jobId "$JOB_ID" \
  >"$WORK/serving.log" 2>&1 &
SERVE_PID=$!
trap 'kill $SERVE_PID 2>/dev/null || true' EXIT

$PY - "$PORT" <<'PYEOF'
import socket, sys, time
port = int(sys.argv[1])
deadline = time.time() + 60
while time.time() < deadline:
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=2) as s:
            s.sendall(b"PING\n")
            if s.recv(64).startswith(b"PONG"):
                sys.exit(0)
    except OSError:
        time.sleep(0.3)
sys.exit("serving job did not come up")
PYEOF
sleep 2   # let the ingest thread drain the topic into the model table

echo "== [6/8] random-query latency harness (als_predict_random ~ ALSPredictRandom)"
$PY -m flink_ms_tpu.client.als_predict_random \
  --jobId "$JOB_ID" --jobManagerHost 127.0.0.1 --jobManagerPort "$PORT" \
  --numQueries 200 --lowerUserId 0 --upperUserId 49 \
  --lowerItemId 0 --upperItemId 39 --outputFile "$WORK/latency.csv"
echo "   latency csv head:"; head -3 "$WORK/latency.csv" | sed 's/^/     /'

echo "== [7/8] MSE against the live served model, before online updates"
$PY -m flink_ms_tpu.eval.mse --input "$WORK/ratings.tsv" \
  --jobId "$JOB_ID" --jobManagerHost 127.0.0.1 --jobManagerPort "$PORT" \
  --output "$WORK/mse_before.txt"

echo "== [8/8] online SGD on fresh ratings (sgd ~ SGD.java), closing the loop"
$PY -m flink_ms_tpu.online.sgd \
  --input "$WORK/updates.tsv" --mode once --outputMode kafka \
  --journalDir "$WORK/journal" --topic als-model \
  --jobId "$JOB_ID" --jobManagerHost 127.0.0.1 --jobManagerPort "$PORT" \
  --learningRate 0.05
sleep 2   # serving job folds the updated rows back into the state

$PY -m flink_ms_tpu.eval.mse --input "$WORK/ratings.tsv" \
  --jobId "$JOB_ID" --jobManagerHost 127.0.0.1 --jobManagerPort "$PORT" \
  --output "$WORK/mse_after.txt"

echo "== done"
echo "   MSE before online updates: $(cat "$WORK/mse_before.txt")"
echo "   MSE after  online updates: $(cat "$WORK/mse_after.txt")"
echo "   artifacts under $WORK (model/, journal/, ckpt/, latency.csv, serving.log)"
