#!/usr/bin/env python
"""ALS kernel microbenchmark: assembly + solve variants on the current
backend.

Times one full compiled sweep (steady-state, hard-sync barrier) across the
solver (unrolled vs lax) and assembly-precision (highest/high/default)
axes, at a configurable scale.  Used to pick kernel defaults on real
hardware; safe to run on CPU for smoke.

  python scripts/als_microbench.py [--small] [--nnz N] [--rank K]
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_breakdown(A_mod, problem, cfg, mesh, dev_args, hard_sync):
    """Time the user half-sweep's phases separately on one device: the
    opposite-factor gather, the full normal-equation assembly, and the
    batched Cholesky solve.  Isolates where a sweep's wall-clock goes so
    kernel work targets the real bottleneck (single-device layout: dev_args
    leading block axis is 1)."""
    import jax
    import jax.numpy as jnp

    k = cfg.num_factors
    n_u_buckets = len(problem.u.widths)
    itf0 = dev_args[1]
    u_flat = dev_args[2:2 + 2 * n_u_buckets + 1]
    *bucket_args, counts = u_flat
    y_all = itf0[0]
    platform = mesh.devices.flat[0].platform

    @jax.jit
    def gather_only(y_all, *bs):
        # one pass of the raw opposite-factor gathers, reduced to force
        # materialization — row chunked with the SAME bound and transient
        # factor _bucket_normal_eqs uses, so the probe's scan overhead
        # matches the assembly row it is compared against (a full-bucket
        # gather at ML-20M scale RESOURCE_EXHAUSTs a 16 GB chip)
        limit = A_mod._assembly_chunk_bytes()
        transients = 2 if cfg.implicit else 1
        tot = jnp.zeros((), y_all.dtype)
        for j in range(n_u_buckets):
            idx = bs[2 * j]
            w = idx.shape[1]
            C = max(
                min(int(limit // (transients * w * k * 4)), idx.shape[0]), 1
            )
            tot = tot + jax.lax.map(
                lambda ic: jnp.take(y_all, ic, axis=0).sum(),
                idx, batch_size=C,
            ).sum()
        return tot

    @jax.jit
    def assemble_only(y_all, *bs):
        bl = [(bs[2 * j], bs[2 * j + 1])
              for j in range(n_u_buckets)]
        A, b = A_mod._assemble_normal_eqs(
            y_all, bl, cfg.implicit, cfg.alpha, cfg.dtype,
            precision=cfg.assembly_precision,
        )
        return A.sum() + b.sum()

    @jax.jit
    def solve_only(A, b, counts):
        x = A_mod._solve_factors(
            A, b, counts, cfg.lambda_, cfg.weighted_reg, cfg.dtype,
            platform,
        )
        return x

    @jax.jit
    def assemble_full(y_all, *bs):
        bl = [(bs[2 * j], bs[2 * j + 1])
              for j in range(n_u_buckets)]
        return A_mod._assemble_normal_eqs(
            y_all, bl, cfg.implicit, cfg.alpha, cfg.dtype,
            precision=cfg.assembly_precision,
        )

    flat_bufs = [a[0] for a in bucket_args]

    def timeit(fn, *args_):
        out = fn(*args_)
        hard_sync(out if not isinstance(out, tuple) else out[0])
        reps = 5
        t0 = time.time()
        for _ in range(reps):
            out = fn(*args_)
        hard_sync(out if not isinstance(out, tuple) else out[0])
        return (time.time() - t0) / reps

    t_gather = timeit(gather_only, y_all, *flat_bufs)
    t_asm = timeit(assemble_only, y_all, *flat_bufs)
    A, b = assemble_full(y_all, *flat_bufs)
    jax.block_until_ready(A)
    t_solve = timeit(solve_only, A, b, counts[0])
    print(
        f"user half-sweep breakdown (k={k}):\n"
        f"  gather-only   : {t_gather * 1e3:9.2f} ms\n"
        f"  assembly (A,b): {t_asm * 1e3:9.2f} ms  (incl. gather)\n"
        f"  solve         : {t_solve * 1e3:9.2f} ms  "
        f"(batch {int(counts.shape[1])})"
    )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--nnz", type=int, default=None)
    ap.add_argument("--users", type=int, default=None)
    ap.add_argument("--items", type=int, default=None)
    ap.add_argument("--rank", type=int, default=None)
    ap.add_argument("--breakdown", action="store_true",
                    help="time gather/assembly/solve phases separately")
    ap.add_argument("--solvers", default="unrolled,lax")
    ap.add_argument("--precisions", default="highest,high,default")
    ap.add_argument("--exchange", default="f32", choices=["f32", "bf16"],
                    help="factor-exchange dtype (bf16 halves gather bytes)")
    args = ap.parse_args()

    small = args.small
    nnz = args.nnz or (500_000 if small else 20_000_000)
    n_users = args.users or (20_000 if small else 138_493)
    n_items = args.items or (2_000 if small else 26_744)
    rank = args.rank or (16 if small else 50)

    from flink_ms_tpu.parallel.mesh import honor_platform_env

    honor_platform_env()

    import jax
    import jax.numpy as jnp

    from flink_ms_tpu.ops import als as A
    from flink_ms_tpu.parallel.mesh import make_mesh
    from flink_ms_tpu.utils.profiling import hard_sync

    devs = jax.devices()
    accel = [d for d in devs if d.platform != "cpu"] or devs
    mesh = make_mesh(devices=accel[:1])
    print(f"backend: {accel[0].platform} ({getattr(accel[0], 'device_kind', '?')})")

    rng = np.random.default_rng(0)
    users = rng.integers(0, n_users, nnz)
    items = rng.integers(0, n_items, nnz)
    ratings = rng.uniform(1.0, 5.0, nnz)
    t0 = time.time()
    problem = A.prepare_blocked(users, items, ratings, 1)
    print(f"prepare_blocked: {time.time() - t0:.1f}s  "
          f"(u widths={problem.u.widths}, i widths={problem.i.widths})")

    # dev_args depend only on (problem, dtype): upload once, reuse across
    # all solver/precision variants (only the compiled sweep differs)
    base_cfg = A.ALSConfig(num_factors=rank, iterations=1, lambda_=0.1)
    _, dev_args = A.compile_fit(problem, base_cfg, mesh)

    if args.breakdown:
        run_breakdown(A, problem, base_cfg, mesh, dev_args, hard_sync)

    def steady(cfg):
        fit_fn = A._cached_sweep(problem, cfg, mesh)

        def run(trip):
            t = time.time()
            uf, _ = fit_fn(jnp.asarray(trip, jnp.int32), *dev_args)
            hard_sync(uf)
            return time.time() - t

        run(1), run(4)  # compile + warmup
        iters = 4
        while run(iters) < 0.5 and iters < 20_000:
            iters *= 4
        samples = sorted(
            max((run(iters) - run(1)) / (iters - 1), 1e-9) for _ in range(3)
        )
        return samples[1]

    valid_solvers = {"unrolled", "panel", "lax", "pallas", "auto"}
    solvers = args.solvers.split(",")
    unknown = [s for s in solvers if s not in valid_solvers]
    if unknown:
        ap.error(f"unknown solver(s) {unknown}; choose from {sorted(valid_solvers)}")
    for solver in solvers:
        os.environ["FLINK_MS_ALS_SOLVER"] = solver
        for precision in args.precisions.split(","):
            cfg = A.ALSConfig(
                num_factors=rank, iterations=1, lambda_=0.1,
                assembly_precision=precision,
                exchange_dtype=(
                    "bfloat16" if args.exchange == "bf16" else None
                ),
            )
            spi = steady(cfg)
            flops = 2 * nnz * (2 * rank * rank + 2 * rank) + (
                n_users + n_items
            ) * (rank ** 3 / 3 + 4 * rank * rank)
            print(
                f"solver={solver:8s} precision={precision:8s} "
                f"exch={args.exchange}: "
                f"{spi * 1e3:9.2f} ms/iter  "
                f"({flops / spi / 1e12:6.2f} TFLOP/s analytic)"
            )


if __name__ == "__main__":
    main()
