#!/usr/bin/env python
"""Scale-envelope measurement: the BASELINE.json "model-generator synthetic
(10M x 1M, rank=64)" config — generation + block-ALS throughput at a
catalog whose normal-equation tensor (10M x 64 x 64 x 4 B = 163 GB) can
never materialize in HBM.  Requires FLINK_MS_ALS_FUSED=1 (forced on here):
fused assembly+solve bounds the transient at the chunk size instead.

Run MANUALLY on a healthy chip (an OOM'd on-chip process can wedge the
tunnel for hours — see BASELINE.md); start with the defaults below
(half-scale) before attempting SCALE_USERS=10000000.

  SCALE_USERS=5000000 SCALE_ITEMS=500000 SCALE_NNZ=50000000 SCALE_RANK=64 \
      python scripts/scale_envelope.py

Prints one JSON line: prep_s, sec_per_iter, gen_rows_per_sec (device-RNG
rating synthesis), hbm-relevant config echo.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["FLINK_MS_ALS_FUSED"] = "1"

from flink_ms_tpu.parallel.mesh import honor_platform_env  # noqa: E402

honor_platform_env()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from flink_ms_tpu.ops.als import ALSConfig, compile_fit, prepare_blocked  # noqa: E402
from flink_ms_tpu.parallel.mesh import make_mesh  # noqa: E402
from flink_ms_tpu.utils.profiling import hard_sync  # noqa: E402

N_USERS = int(os.environ.get("SCALE_USERS", 5_000_000))
N_ITEMS = int(os.environ.get("SCALE_ITEMS", 500_000))
NNZ = int(os.environ.get("SCALE_NNZ", 50_000_000))
RANK = int(os.environ.get("SCALE_RANK", 64))
ITERS = int(os.environ.get("SCALE_ITERS", 2))


def main():
    out = {"users": N_USERS, "items": N_ITEMS, "nnz": NNZ, "rank": RANK}
    t0 = time.time()
    rng = np.random.default_rng(0)
    users = rng.integers(0, N_USERS, NNZ)
    items = rng.integers(0, N_ITEMS, NNZ)
    ratings = rng.uniform(1.0, 5.0, NNZ)
    out["gen_rows_per_sec"] = round(NNZ / (time.time() - t0))

    devices = jax.devices()
    accel = [d for d in devices if d.platform != "cpu"] or devices
    mesh = make_mesh(devices=accel)
    out["platform"] = accel[0].platform
    print(f"devices: {accel}", file=sys.stderr)

    t0 = time.time()
    problem = prepare_blocked(users, items, ratings, mesh.devices.size)
    out["prep_s"] = round(time.time() - t0, 1)
    print(f"prepare_blocked: {out['prep_s']}s", file=sys.stderr)

    cfg = ALSConfig(num_factors=RANK, iterations=1, lambda_=0.1, seed=3)
    fit, dev_args = compile_fit(problem, cfg, mesh)

    def run(trip):
        t = time.time()
        uf, _ = fit(jnp.asarray(trip, jnp.int32), *dev_args)
        hard_sync(uf)
        return time.time() - t

    run(1)  # compile + warmup
    t1, tn = run(1), run(max(ITERS, 2))
    out["sec_per_iter"] = round(
        max((tn - t1) / (max(ITERS, 2) - 1), 1e-9), 4
    )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
