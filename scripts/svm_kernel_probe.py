#!/usr/bin/env python
"""Probe: attack the single-chip CoCoA round boundary (BASELINE.md: 452 ms
margin gather + 350 ms scatter-add, both 49M-scalar irregular ops against
a 189 KB weight vector that trivially fits VMEM).

Variants (each vs its XLA production-path baseline):
  gather:  wx0[i] = sum_j w[idx[i,j]] * val[i,j]
    xla          jnp.take(w, idx) * val, row-sum (the r3 path)
    pallas       w resident in VMEM, jnp.take inside the kernel, no HBM
                 transient
  scatter: dw = sum_i val[i,j] * dalpha[i] into bins idx[i,j]
    xla          zeros(d).at[flat_idx].add(flat_contrib)
    pallas       VMEM (d,) accumulator across sequential grid steps with
                 in-kernel .at[].add per tile

Usage: python scripts/svm_kernel_probe.py [--interpret] [--nnz N]
"""

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def xla_gather(w, idx, val):
    import jax.numpy as jnp

    return jnp.sum(jnp.take(w, idx, axis=0) * val, axis=1)


def pallas_gather(w, idx, val, tile=512, interpret=False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, m = idx.shape
    assert n % tile == 0

    def kernel(w_ref, idx_ref, val_ref, out_ref):
        wv = w_ref[:]                       # (d,) VMEM-resident
        ix = idx_ref[:]                     # (tile, m)
        g = jnp.take(wv, ix.reshape(-1), axis=0).reshape(tile, m)
        out_ref[:] = jnp.sum(g * val_ref[:], axis=1)

    return pl.pallas_call(
        kernel,
        grid=(n // tile,),
        in_specs=[
            pl.BlockSpec(w.shape, lambda i: (0,), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, m), lambda i: (i, 0)),
            pl.BlockSpec((tile, m), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((tile,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((n,), jnp.float32),
        interpret=interpret,
    )(w, idx, val)


def xla_scatter(d, idx, contrib):
    import jax.numpy as jnp

    return jnp.zeros((d,), jnp.float32).at[idx.reshape(-1)].add(
        contrib.reshape(-1))


def pallas_scatter(d, idx, contrib, tile=512, interpret=False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, m = idx.shape
    assert n % tile == 0
    grid = (n // tile,)

    def kernel(idx_ref, c_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        flat_i = idx_ref[:].reshape(-1)
        flat_c = c_ref[:].reshape(-1)
        out_ref[:] = out_ref[:].at[flat_i].add(flat_c)

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, m), lambda i: (i, 0)),
            pl.BlockSpec((tile, m), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((d,), lambda i: (0,),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((d,), jnp.float32),
        interpret=interpret,
    )(idx, contrib)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interpret", action="store_true")
    ap.add_argument("--nnz", type=int, default=5_000_000)
    ap.add_argument("--m", type=int, default=70)
    ap.add_argument("--d", type=int, default=47_236)
    ap.add_argument("--tile", type=int, default=512)
    args = ap.parse_args()

    if args.interpret:
        import os

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from flink_ms_tpu.parallel.mesh import pin_host_backend

        pin_host_backend()

    import jax

    rng = np.random.default_rng(0)
    n = max(args.nnz // args.m, args.tile)
    n -= n % args.tile
    if args.interpret:
        n = min(n, 2 * args.tile)
    idx = rng.integers(0, args.d, (n, args.m)).astype(np.int32)
    val = rng.standard_normal((n, args.m)).astype(np.float32)
    w = rng.standard_normal(args.d).astype(np.float32)
    dal = rng.standard_normal((n, 1)).astype(np.float32)
    contrib = val * dal
    print(f"n={n} m={args.m} d={args.d} ({n * args.m / 1e6:.1f}M scalars)")

    g_ref = jax.jit(xla_gather)(w, idx, val)
    s_ref = jax.jit(lambda i, c: xla_scatter(args.d, i, c))(idx, contrib)
    jax.block_until_ready((g_ref, s_ref))

    if args.interpret:
        g_p = pallas_gather(w, idx, val, args.tile, interpret=True)
        np.testing.assert_allclose(np.asarray(g_p), np.asarray(g_ref),
                                   rtol=1e-4, atol=1e-4)
        s_p = pallas_scatter(args.d, idx, contrib, args.tile, interpret=True)
        np.testing.assert_allclose(np.asarray(s_p), np.asarray(s_ref),
                                   rtol=2e-3, atol=2e-3)
        print("interpret-mode parity OK (gather + scatter)")
        return

    from flink_ms_tpu.utils.profiling import hard_sync

    def bench(fn, *a, nrep=5):
        out = fn(*a)
        hard_sync(out)
        ts = []
        for _ in range(nrep):
            t0 = time.perf_counter()
            out = fn(*a)
            hard_sync(out)
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2] * 1e3

    import functools

    results = {"gather_xla": bench(jax.jit(xla_gather), w, idx, val)}
    try:
        fn = jax.jit(functools.partial(pallas_gather, tile=args.tile))
        results["gather_pallas"] = bench(fn, w, idx, val)
    except Exception as e:  # noqa: BLE001
        results["gather_pallas"] = f"FAILED: {type(e).__name__}: {str(e)[:240]}"
    results["scatter_xla"] = bench(
        jax.jit(lambda i, c: xla_scatter(args.d, i, c)), idx, contrib)
    try:
        fn = jax.jit(functools.partial(
            pallas_scatter, args.d, tile=args.tile))
        results["scatter_pallas"] = bench(fn, idx, contrib)
    except Exception as e:  # noqa: BLE001
        results["scatter_pallas"] = (
            f"FAILED: {type(e).__name__}: {str(e)[:240]}"
        )
    for name, v in results.items():
        print(f"{name:>16}: {v if isinstance(v, str) else f'{v:8.2f} ms'}")


if __name__ == "__main__":
    main()
