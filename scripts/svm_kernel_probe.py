#!/usr/bin/env python
"""Probe: attack the single-chip CoCoA round boundary (BASELINE.md: 452 ms
margin gather + 350 ms scatter-add, both 49M-scalar irregular ops against
a 189 KB weight vector that trivially fits VMEM).

Variants (each vs its XLA production-path baseline):
  gather:  wx0[i] = sum_j w[idx[i,j]] * val[i,j]
    xla          jnp.take(w, idx) * val, row-sum (the r3 path)
    pallas       w resident in VMEM, jnp.take inside the kernel, no HBM
                 transient
  scatter: dw = sum_i val[i,j] * dalpha[i] into bins idx[i,j]
    xla          zeros(d).at[flat_idx].add(flat_contrib)
    pallas       VMEM (d,) accumulator across sequential grid steps with
                 in-kernel .at[].add per tile

Usage: python scripts/svm_kernel_probe.py [--interpret] [--nnz N]
"""

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def xla_gather(w, idx, val):
    import jax.numpy as jnp

    return jnp.sum(jnp.take(w, idx, axis=0) * val, axis=1)


def pallas_gather(w, idx, val, tile=512, interpret=False):
    """PRODUCTION kernel (flink_ms_tpu.ops.svm_kernels.margin_gather) —
    the probe times exactly what FLINK_MS_SVM_WX0=pallas would run, so a
    kernel tweak can never drift away from the measured numbers."""
    import os

    import jax.numpy as jnp

    from flink_ms_tpu.ops.svm_kernels import margin_gather

    n, m = idx.shape
    os.environ["FLINK_MS_SVM_KERNEL_TILE"] = str(tile)
    platform = "cpu" if interpret else "tpu"
    return margin_gather(
        w, idx.reshape(n, 1, m), val.reshape(n, 1, m), jnp.float32,
        platform,
    ).reshape(n)


def xla_scatter(d, idx, contrib):
    import jax.numpy as jnp

    return jnp.zeros((d,), jnp.float32).at[idx.reshape(-1)].add(
        contrib.reshape(-1))


def pallas_scatter(d, idx, contrib, tile=512, interpret=False):
    """PRODUCTION kernel (flink_ms_tpu.ops.svm_kernels.scatter_add_dw) —
    the probe times exactly what FLINK_MS_SVM_DW=pallas would run."""
    import os

    import jax.numpy as jnp

    from flink_ms_tpu.ops.svm_kernels import scatter_add_dw

    os.environ["FLINK_MS_SVM_KERNEL_TILE"] = str(tile)
    platform = "cpu" if interpret else "tpu"
    return scatter_add_dw(idx, contrib, d, jnp.float32, platform)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interpret", action="store_true")
    ap.add_argument("--nnz", type=int, default=5_000_000)
    ap.add_argument("--m", type=int, default=70)
    ap.add_argument("--d", type=int, default=47_236)
    ap.add_argument("--tile", type=int, default=512)
    args = ap.parse_args()

    import os

    if args.interpret:
        # FORCE the host pin: the launcher ambiently exports
        # JAX_PLATFORMS=axon, so a setdefault would leave the tunnel
        # plugin registered and a wedged tunnel hangs the first jit
        os.environ["JAX_PLATFORMS"] = "cpu"
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        # honor an explicit host pin BEFORE the first backend touch —
        # plain jax.devices() initializes every registered plugin, and a
        # wedged accelerator tunnel HANGS that init rather than erroring
        from flink_ms_tpu.parallel.mesh import pin_host_backend

        pin_host_backend()

    import jax

    rng = np.random.default_rng(0)
    n = max(args.nnz // args.m, args.tile)
    n -= n % args.tile
    if args.interpret:
        n = min(n, 2 * args.tile)
    idx = rng.integers(0, args.d, (n, args.m)).astype(np.int32)
    val = rng.standard_normal((n, args.m)).astype(np.float32)
    w = rng.standard_normal(args.d).astype(np.float32)
    dal = rng.standard_normal((n, 1)).astype(np.float32)
    contrib = val * dal
    print(f"n={n} m={args.m} d={args.d} ({n * args.m / 1e6:.1f}M scalars)")

    g_ref = jax.jit(xla_gather)(w, idx, val)
    s_ref = jax.jit(lambda i, c: xla_scatter(args.d, i, c))(idx, contrib)
    jax.block_until_ready((g_ref, s_ref))

    if args.interpret:
        g_p = pallas_gather(w, idx, val, args.tile, interpret=True)
        np.testing.assert_allclose(np.asarray(g_p), np.asarray(g_ref),
                                   rtol=1e-4, atol=1e-4)
        s_p = pallas_scatter(args.d, idx, contrib, args.tile, interpret=True)
        np.testing.assert_allclose(np.asarray(s_p), np.asarray(s_ref),
                                   rtol=2e-3, atol=2e-3)
        print("interpret-mode parity OK (gather + scatter)")
        return

    from flink_ms_tpu.utils.profiling import hard_sync

    def bench(fn, *a, nrep=5):
        out = fn(*a)
        hard_sync(out)
        ts = []
        for _ in range(nrep):
            t0 = time.perf_counter()
            out = fn(*a)
            hard_sync(out)
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2] * 1e3

    import functools

    on_tpu = jax.devices()[0].platform == "tpu"
    results = {"gather_xla": bench(jax.jit(xla_gather), w, idx, val)}
    if on_tpu:
        try:
            fn = jax.jit(functools.partial(pallas_gather, tile=args.tile))
            results["gather_pallas"] = bench(fn, w, idx, val)
        except Exception as e:  # noqa: BLE001
            results["gather_pallas"] = (
                f"FAILED: {type(e).__name__}: {str(e)[:240]}"
            )
    results["scatter_xla"] = bench(
        jax.jit(lambda i, c: xla_scatter(args.d, i, c)), idx, contrib)
    if on_tpu:
        try:
            fn = jax.jit(functools.partial(
                pallas_scatter, args.d, tile=args.tile))
            results["scatter_pallas"] = bench(fn, idx, contrib)
        except Exception as e:  # noqa: BLE001
            results["scatter_pallas"] = (
                f"FAILED: {type(e).__name__}: {str(e)[:240]}"
            )
    else:
        print("(pallas variants skipped off-TPU: a non-interpret "
              "pallas_call on CPU crawls through the interpreter)")
    for name, v in results.items():
        print(f"{name:>16}: {v if isinstance(v, str) else f'{v:8.2f} ms'}")

    # boundary-scaling demonstration (BASELINE.md: "both boundary terms
    # are per-device and shrink linearly with mesh size"): time the SAME
    # ops at per-device shares of the nnz for D=2,4,8 — the per-device
    # cost at nnz/D is what each chip of a D-mesh would pay
    print("\nper-device boundary at nnz/D (gather + scatter, xla):")
    for D in (1, 2, 4, 8):
        nd = max(n // D, args.tile)
        nd -= nd % args.tile
        g = bench(jax.jit(xla_gather), w, idx[:nd], val[:nd])
        s = bench(jax.jit(lambda i, c: xla_scatter(args.d, i, c)),
                  idx[:nd], contrib[:nd])
        print(f"  D={D}: rows/device={nd} gather {g:7.2f} ms, "
              f"scatter {s:7.2f} ms, boundary {g + s:7.2f} ms")


if __name__ == "__main__":
    main()
