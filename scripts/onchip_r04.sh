#!/usr/bin/env bash
# Round-4 on-chip measurement plan — run when the tunnel recovers.
#
# Order matters: cheap probes first (they decide the kernel defaults),
# then the targeted A/Bs, then the full bench last (also warms the
# persistent compile cache for the driver's end-of-round run).  Every
# step runs in its own subprocess under `timeout` so a wedge costs one
# step, not the session; steps are strictly sequential (concurrent
# compiles through the tunnel are the one observed wedge trigger).
#
# Usage: bash scripts/onchip_r04.sh [outdir]   (default scripts/onchip_r04)
set -u
cd "$(dirname "$0")/.."
OUT="${1:-scripts/onchip_r04}"
mkdir -p "$OUT"
log() { echo "[onchip_r04 $(date +%H:%M:%S)] $*"; }

run_step() { # name timeout_s cmd...
  local name="$1" t="$2"; shift 2
  log "step $name (timeout ${t}s): $*"
  timeout "$t" "$@" >"$OUT/$name.log" 2>&1
  local rc=$?
  log "step $name rc=$rc"
  tail -20 "$OUT/$name.log"
  return $rc
}

# 0. sanity probe: is the chip actually COMPILING?  A devices() listing
#    passes in the observed wedge state (relay up, remote compiles hang),
#    which once burned this plan's whole sequential timeout budget — the
#    probe must round-trip a real jit compile+execute.
run_step probe 240 python scripts/compile_probe.py \
  || { log "chip not compiling — abort"; exit 1; }

# 1. fused gather+contract probe (decides FLINK_MS_ALS_ASSEMBLY):
#    ML-20M user-half-sweep shape (item table 12k->27k rows, k=64)
run_step gather_probe_small 600 python scripts/gather_kernel_probe.py \
  --nnz 5000000 --w 128 --table 12000 --k 64
probe_rc=$?
run_step gather_probe_ml20m 600 python scripts/gather_kernel_probe.py \
  --nnz 5000000 --w 128 --table 27000 --k 64
# row-tile sweep on the winning shape (only if the probe step SUCCEEDED
# and the kernel compiled — a timeout/crash leaves no FAILED marker but
# must not trigger 20 more minutes of sweeps against a wedged chip)
if [ "$probe_rc" -eq 0 ] && ! grep -q FAILED "$OUT/gather_probe_small.log"; then
  run_step gather_tile16 600 python scripts/gather_kernel_probe.py \
    --nnz 5000000 --w 128 --table 12000 --k 64 --row-tile 16
  run_step gather_tile32 600 python scripts/gather_kernel_probe.py \
    --nnz 5000000 --w 128 --table 12000 --k 64 --row-tile 32
fi

# 2. SVM boundary probe (decides FLINK_MS_SVM_WX0 / FLINK_MS_SVM_DW)
#    + the per-device boundary-shrink table at nnz/D
run_step svm_probe 600 python scripts/svm_kernel_probe.py --nnz 49000000

# 3. ALS assembly A/B at the 5M-nnz probe config (the r3 solver-matrix
#    config): xla vs pallas assembly under the pallas solver
run_step als_ab_xla 900 env BENCH_SECTIONS=als BENCH_NNZ=5000000 \
  BENCH_USERS=60000 BENCH_ITEMS=12000 BENCH_RANK=50 BENCH_SKIP_CPU=1 \
  BENCH_SKIP_QUALITY=1 BENCH_ALS_BF16_AB=0 FLINK_MS_ALS_ASSEMBLY=xla \
  python bench.py --sections-json als
run_step als_ab_pallas 900 env BENCH_SECTIONS=als BENCH_NNZ=5000000 \
  BENCH_USERS=60000 BENCH_ITEMS=12000 BENCH_RANK=50 BENCH_SKIP_CPU=1 \
  BENCH_SKIP_QUALITY=1 BENCH_ALS_BF16_AB=0 FLINK_MS_ALS_ASSEMBLY=pallas \
  python bench.py --sections-json als

# 4. SVM round A/B at RCV1 scale: production path vs pallas boundary
run_step svm_ab_base 1200 env BENCH_SECTIONS=svm BENCH_SKIP_CPU=1 \
  python bench.py --sections-json svm
run_step svm_ab_pallas 1200 env BENCH_SECTIONS=svm BENCH_SKIP_CPU=1 \
  FLINK_MS_SVM_WX0=pallas FLINK_MS_SVM_DW=pallas \
  python bench.py --sections-json svm

# 5. full bench at the headline config with whatever won above (operator
#    reads the A/B logs and exports the winning knobs before this, or
#    re-runs manually) — ALSO warms the driver's compile cache
run_step bench_full 3000 python bench.py
cp -f BENCH_DETAIL.json "$OUT/bench_full.detail.json" 2>/dev/null || true

log "done — artifacts in $OUT/"
