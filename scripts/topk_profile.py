#!/usr/bin/env python
"""Profile the top-k scoring engine on the current backend.

Times the XLA matmul + ``jax.lax.top_k`` path at serving-relevant catalog
sizes (26k ≈ ML-20M items, 1M ≈ BASELINE scale envelope), and the device
vs host placement question behind TPUMS_TOPK_PLATFORM.  The Pallas fused
scorer this script originally A/B'd was removed in round 3 (decision in
PARITY.md: the serving index is host-pinned in this deployment, and the
XLA engine already meets the latency envelope).

  python scripts/topk_profile.py [--items N ...] [--rank K] [--topk T]
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--items", type=int, nargs="*", default=[26_744, 1_000_000])
    ap.add_argument("--rank", type=int, default=50)
    ap.add_argument("--topk", type=int, default=10)
    ap.add_argument("--reps", type=int, default=20)
    args = ap.parse_args()

    from flink_ms_tpu.parallel.mesh import honor_platform_env

    honor_platform_env()

    import jax
    import jax.numpy as jnp

    from flink_ms_tpu.utils.profiling import hard_sync

    dev = jax.devices()[0]
    print(f"backend: {dev.platform} ({getattr(dev, 'device_kind', '?')})")

    rng = np.random.default_rng(0)
    for n in args.items:
        k = args.rank
        matrix = rng.standard_normal((n, k)).astype(np.float32)
        md = jnp.asarray(matrix)

        @jax.jit
        def xla_topk(m, q):
            scores = m @ q
            return jax.lax.top_k(scores, args.topk)

        def run_xla(q):
            t0 = time.time()
            s, _ = xla_topk(md, q)
            hard_sync(s)
            return time.time() - t0

        qs = [jnp.asarray(rng.standard_normal(k).astype(np.float32))
              for _ in range(args.reps)]
        run_xla(qs[0])  # warmup/compile
        tx = sorted(run_xla(q) for q in qs)[len(qs) // 2]
        print(f"items={n:>9,} rank={k}: xla {tx * 1e3:7.3f} ms/query")


if __name__ == "__main__":
    main()
