#!/usr/bin/env python
"""Profile the two top-k scoring engines on the current backend.

Times the XLA matmul+jax.lax.top_k path against the Pallas fused kernel
(``ops/topk_pallas.py``) at serving-relevant catalog sizes (26k ≈ ML-20M
items, 1M ≈ BASELINE scale envelope) — the measurement VERDICT r1 asked
for to decide the Pallas kernel's fate.  Safe on CPU (Pallas runs in
interpreter mode there, correctness only; timings meaningful on TPU).

  python scripts/topk_profile.py [--items N ...] [--rank K] [--topk T]
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--items", type=int, nargs="*", default=[26_744, 1_000_000])
    ap.add_argument("--rank", type=int, default=50)
    ap.add_argument("--topk", type=int, default=10)
    ap.add_argument("--reps", type=int, default=20)
    args = ap.parse_args()

    from flink_ms_tpu.parallel.mesh import honor_platform_env

    honor_platform_env()

    import jax
    import jax.numpy as jnp

    from flink_ms_tpu.ops import topk_pallas as TP
    from flink_ms_tpu.utils.profiling import hard_sync

    dev = jax.devices()[0]
    interpret = dev.platform == "cpu"
    print(f"backend: {dev.platform} ({getattr(dev, 'device_kind', '?')}), "
          f"pallas interpret={interpret}")

    rng = np.random.default_rng(0)
    for n in args.items:
        k = args.rank
        matrix = rng.standard_normal((n, k)).astype(np.float32)

        # XLA path: scores = M q, then top_k
        md = jnp.asarray(matrix)

        @jax.jit
        def xla_topk(m, q):
            scores = m @ q
            return jax.lax.top_k(scores, args.topk)

        # Pallas path: packed transposed index
        packed = TP.pack_index(matrix)

        def run_xla(q):
            t0 = time.time()
            s, i = xla_topk(md, q)
            hard_sync(s)
            return time.time() - t0

        def run_pallas(q):
            t0 = time.time()
            s, i = TP.topk_scores(packed, q, args.topk, n, interpret=interpret)
            hard_sync(s)
            return time.time() - t0

        qs = [jnp.asarray(rng.standard_normal(k).astype(np.float32))
              for _ in range(args.reps)]
        # correctness cross-check on the first query
        s0, i0 = xla_topk(md, qs[0])
        sp, ip = TP.topk_scores(packed, qs[0], args.topk, n, interpret=interpret)
        np.testing.assert_allclose(
            np.sort(np.asarray(s0)), np.sort(np.asarray(sp)), rtol=2e-4, atol=1e-4
        )
        # indices too: matching scores with wrong item ids must fail here
        assert set(np.asarray(i0).tolist()) == set(np.asarray(ip).tolist()), (
            i0, ip,
        )
        run_xla(qs[0]); run_pallas(qs[0])  # warmup/compile
        tx = sorted(run_xla(q) for q in qs)[len(qs) // 2]
        tp = sorted(run_pallas(q) for q in qs)[len(qs) // 2]
        print(f"items={n:>9,} rank={k}: xla {tx*1e3:7.3f} ms  "
              f"pallas {tp*1e3:7.3f} ms  ({tx/tp:.2f}x)")


if __name__ == "__main__":
    main()
