#!/usr/bin/env python
"""End-to-end smoke for the edge proxy tier (serve/edge.py): bring up a
2-shard x 2-replica fleet fronted by 2 proxy processes, drive mixed
tab/B2 client threads through the proxies, SIGKILL one proxy mid-load,
and assert the contract the tier exists for —

- zero unattributed client errors: every query either succeeds or is
  transparently retried; a client pinned to the killed proxy rotates to
  the survivor (``proxy_reconnect``) instead of surfacing the death;
- full verb surface through the front door: GET/MGET/TOPK all answer
  through the proxy with the same payloads a direct client sees.

    python scripts/edge_smoke.py [env knobs below]

Knobs (env):
    SMOKE_USERS=120        model rows per side
    SMOKE_THREADS=4        closed-loop client threads (alternating tab/B2)
    SMOKE_SETTLE_S=1.5     load time before and after the proxy kill
    TPUMS_HEARTBEAT_S / TPUMS_REPLICA_TTL_S: liveness cadence (defaults
                           here: 0.25 / 1.5)

Exit code 0 on success, 1 on any failed check.
"""

import json
import os
import random
import signal
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TPUMS_HEARTBEAT_S", "0.25")
os.environ.setdefault("TPUMS_REPLICA_TTL_S", "1.5")
os.environ.setdefault("TPUMS_REGISTRY_DIR",
                      tempfile.mkdtemp(prefix="tpums_edge_smoke_reg_"))

from flink_ms_tpu.core import formats as F  # noqa: E402
from flink_ms_tpu.serve.client import RetryPolicy  # noqa: E402
from flink_ms_tpu.serve.consumer import ALS_STATE  # noqa: E402
from flink_ms_tpu.serve.edge import (  # noqa: E402
    EdgeClient, spawn_edge_procs, stop_edge_procs,
)
from flink_ms_tpu.serve.elastic import ScaleController  # noqa: E402
from flink_ms_tpu.serve.journal import Journal  # noqa: E402

N_USERS = int(os.environ.get("SMOKE_USERS", 120))
THREADS = int(os.environ.get("SMOKE_THREADS", 4))
SETTLE_S = float(os.environ.get("SMOKE_SETTLE_S", 1.5))


def main() -> int:
    base = tempfile.mkdtemp(prefix="tpums_edge_smoke_")
    journal = Journal(os.path.join(base, "bus"), "models")
    rng = np.random.default_rng(7)
    k = 4
    journal.append(
        [F.format_als_row(u, "U", rng.normal(size=k))
         for u in range(N_USERS)]
        + [F.format_als_row(i, "I", rng.normal(size=k))
           for i in range(N_USERS)]
    )
    keys = [f"{u}-U" for u in range(N_USERS)] \
        + [f"{i}-I" for i in range(N_USERS)]

    checks = []

    def check(name, ok, detail=""):
        checks.append((name, bool(ok)))
        print(f"  [{'ok' if ok else 'FAIL'}] {name}"
              + (f" — {detail}" if detail and not ok else ""))

    ok_counts = [0] * THREADS
    errors = []
    stop = threading.Event()

    def load(widx):
        # half the threads speak the frozen tab protocol, half negotiate
        # B2 — both must ride the proxy (and the kill) identically
        c = EdgeClient(
            "edge-smoke", prefer=widx,
            proto=("b2" if widx % 2 else "tab"),
            retry=RetryPolicy(attempts=8, backoff_s=0.02,
                              max_backoff_s=0.5),
            timeout_s=5)
        r = random.Random(widx)
        with c:
            while not stop.is_set():
                key = keys[r.randrange(len(keys))]
                try:
                    if r.random() < 0.2:
                        got = c.query_states(
                            ALS_STATE,
                            [keys[r.randrange(len(keys))]
                             for _ in range(4)])
                        if any(v is None for v in got):
                            errors.append((widx, "mget", "miss"))
                        else:
                            ok_counts[widx] += 1
                    elif r.random() < 0.1:
                        if c.topk(ALS_STATE, str(r.randrange(N_USERS)),
                                  5) is None:
                            errors.append((widx, "topk", "miss"))
                        else:
                            ok_counts[widx] += 1
                    elif c.query_state(ALS_STATE, key) is None:
                        errors.append((widx, key, "miss"))
                    else:
                        ok_counts[widx] += 1
                except Exception as e:  # noqa: BLE001 - the gate itself
                    errors.append((widx, key, repr(e)))

    ctl = ScaleController("edge-smoke", journal.dir, "models",
                          port_dir=os.path.join(base, "ports"),
                          ready_timeout_s=120)
    procs = []
    try:
        t0 = time.time()
        rec = ctl.scale_to(2, replicas=2)
        check("fleet up: gen1, 2 shards x 2 replicas",
              rec["gen"] == 1 and rec["shards"] == 2)
        procs, ports = spawn_edge_procs(
            "edge-smoke", 2, os.path.join(base, "edge_ports"))
        check("2 proxies registered", len(ports) == 2, str(ports))

        probe = EdgeClient("edge-smoke", timeout_s=10)
        vals = probe.query_states(ALS_STATE, keys)
        check("full coverage through proxy",
              all(v is not None for v in vals),
              f"{sum(v is None for v in vals)} missing")

        threads = [threading.Thread(target=load, args=(i,), daemon=True)
                   for i in range(THREADS)]
        for t in threads:
            t.start()
        time.sleep(SETTLE_S)

        # SIGKILL one proxy under load: its clients must rotate to the
        # survivor (retry loop -> proxy_reconnect), never error out
        victim = procs[0]
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait(timeout=10)
        check("proxy killed", victim.poll() is not None)
        time.sleep(SETTLE_S * 2)
        stop.set()
        for t in threads:
            t.join(timeout=30)

        mid_ok = sum(ok_counts)
        check("zero unattributed client errors", not errors,
              f"{len(errors)} errors, first: {errors[:3]}")
        check("load ran through the kill", mid_ok > 0)
        # the survivor absorbed the dead proxy's clients: queries kept
        # succeeding after the kill via the remaining endpoint
        post = EdgeClient("edge-smoke", timeout_s=10)
        v = post.query_state(ALS_STATE, keys[0])
        check("survivor serves after kill", v is not None)
        post.close()
        probe.close()
        print(json.dumps({
            "queries_ok": mid_ok,
            "errors": len(errors),
            "total_s": round(time.time() - t0, 2),
        }, indent=1))
    finally:
        stop.set()
        stop_edge_procs(procs)
        ctl.stop(drop_topology=True)

    failed = [n for n, ok_ in checks if not ok_]
    print(("SMOKE PASS" if not failed else f"SMOKE FAIL: {failed}"))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
