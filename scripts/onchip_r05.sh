#!/usr/bin/env bash
# Round-5 on-chip measurement plan — run at first tunnel recovery
# (scripts/onchip_watch_r05.sh launches it on the first successful
# COMPILE-level probe).
#
# Ordered for a SHORT window, not for decision flow: after four rounds
# with zero driver-witnessed chip numbers, the single most valuable
# artifact is a full bench at the headline config under the known-good
# r3 defaults — so that runs FIRST (also warming the driver's compile
# cache), and the r4 kernel-decision backlog (probes, A/Bs, bf16 step)
# follows in value order.  Every step runs in its own subprocess under
# `timeout`; steps are strictly sequential (concurrent compiles through
# the tunnel are the one observed wedge trigger).
#
# Usage: bash scripts/onchip_r05.sh [outdir]   (default scripts/onchip_r05)
set -u
cd "$(dirname "$0")/.."
OUT="${1:-scripts/onchip_r05}"
mkdir -p "$OUT"
log() { echo "[onchip_r05 $(date +%H:%M:%S)] $*"; }

run_step() { # name timeout_s cmd...
  local name="$1" t="$2"; shift 2
  log "step $name (timeout ${t}s): $*"
  timeout "$t" "$@" >"$OUT/$name.log" 2>&1
  local rc=$?
  log "step $name rc=$rc"
  tail -20 "$OUT/$name.log"
  return $rc
}

# 0. sanity: the chip must answer a real jit compile (a devices() listing
#    passes in the observed wedge state — see scripts/compile_probe.py)
run_step probe 240 python scripts/compile_probe.py \
  || { log "chip not compiling — abort"; exit 1; }

# 1. FULL bench, known-good defaults (pallas solver, bf16 exchange,
#    auto assembly->xla): banks the headline chip artifact this round has
#    never had, and warms the persistent compile cache for the driver's
#    end-of-round run.
run_step bench_full 3000 python bench.py
cp -f BENCH_DETAIL.json "$OUT/bench_full.detail.json" 2>/dev/null || true

# 2. fused gather+contract probe (decides FLINK_MS_ALS_ASSEMBLY):
#    ML-20M user-half-sweep shape (item table 12k->27k rows, k=64)
run_step gather_probe_small 600 python scripts/gather_kernel_probe.py \
  --nnz 5000000 --w 128 --table 12000 --k 64
probe_rc=$?
run_step gather_probe_ml20m 600 python scripts/gather_kernel_probe.py \
  --nnz 5000000 --w 128 --table 27000 --k 64
# row-tile sweep on the winning shape (only if the probe step SUCCEEDED
# and the kernel compiled — a timeout/crash leaves no FAILED marker but
# must not trigger 20 more minutes of sweeps against a wedged chip).
# "Winning" = the probe table where the pallas kernel shows the larger
# win over xla (smaller pallas/xla ratio) — that is the shape where tile
# tuning has the most to gain; an unparseable or FAILED probe leaves the
# 12000-row default.
pick_ratio() { # logfile -> pallas_ms/xla_ms, empty if either is missing
  awk '/^ *xla:/ {x=$2} /^ *pallas:/ {p=$2} \
       END {if (x+0 > 0 && p+0 > 0) printf "%.6f", p / x}' "$1" 2>/dev/null
}
if [ "$probe_rc" -eq 0 ] && ! grep -q FAILED "$OUT/gather_probe_small.log"; then
  TILE_TABLE=12000
  r_small=$(pick_ratio "$OUT/gather_probe_small.log")
  r_ml20m=$(pick_ratio "$OUT/gather_probe_ml20m.log")
  if [ -n "$r_small" ] && [ -n "$r_ml20m" ] && \
     awk -v a="$r_ml20m" -v b="$r_small" 'BEGIN {exit !(a < b)}'; then
    TILE_TABLE=27000
  fi
  log "row-tile sweep table=$TILE_TABLE (pallas/xla small=${r_small:-n/a} ml20m=${r_ml20m:-n/a})"
  run_step gather_tile16 600 python scripts/gather_kernel_probe.py \
    --nnz 5000000 --w 128 --table "$TILE_TABLE" --k 64 --row-tile 16
  run_step gather_tile32 600 python scripts/gather_kernel_probe.py \
    --nnz 5000000 --w 128 --table "$TILE_TABLE" --k 64 --row-tile 32
fi

# 3. ALS assembly A/B at the 5M-nnz probe config (the r3 solver-matrix
#    config): xla vs pallas assembly under the pallas solver
run_step als_ab_xla 900 env BENCH_SECTIONS=als BENCH_NNZ=5000000 \
  BENCH_USERS=60000 BENCH_ITEMS=12000 BENCH_RANK=50 BENCH_SKIP_CPU=1 \
  BENCH_SKIP_QUALITY=1 BENCH_ALS_BF16_AB=0 FLINK_MS_ALS_ASSEMBLY=xla \
  python bench.py --sections-json als
run_step als_ab_pallas 900 env BENCH_SECTIONS=als BENCH_NNZ=5000000 \
  BENCH_USERS=60000 BENCH_ITEMS=12000 BENCH_RANK=50 BENCH_SKIP_CPU=1 \
  BENCH_SKIP_QUALITY=1 BENCH_ALS_BF16_AB=0 FLINK_MS_ALS_ASSEMBLY=pallas \
  python bench.py --sections-json als

# 4. SVM boundary probe (decides FLINK_MS_SVM_WX0 / FLINK_MS_SVM_DW)
#    + the per-device boundary-shrink table at nnz/D
run_step svm_probe 600 python scripts/svm_kernel_probe.py --nnz 49000000

# 5. SVM round A/B at RCV1 scale: production path vs pallas boundary
run_step svm_ab_base 1200 env BENCH_SECTIONS=svm BENCH_SKIP_CPU=1 \
  python bench.py --sections-json svm
run_step svm_ab_pallas 1200 env BENCH_SECTIONS=svm BENCH_SKIP_CPU=1 \
  FLINK_MS_SVM_WX0=pallas FLINK_MS_SVM_DW=pallas \
  python bench.py --sections-json svm

# 6. bf16 exchange quality+timing A/B at ML-20M scale (lost to the r3
#    wedge; quality already pinned device-independently on the host —
#    BASELINE.md — so this re-witnesses in-artifact and times it)
run_step als_bf16_quality 2400 env BENCH_SECTIONS=als \
  BENCH_ALS_EXCHANGE=bf16 BENCH_SKIP_CPU=1 \
  python bench.py --sections-json als

log "done — run: python scripts/onchip_digest.py $OUT"
