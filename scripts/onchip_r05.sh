#!/usr/bin/env bash
# Round-5 on-chip measurement plan — run at first tunnel recovery.
#
# The tunnel was wedged for ALL of round 4 and (so far) round 5, so the
# r4 queue (scripts/onchip_r04.sh: fused-assembly probe + A/B, SVM
# boundary-kernel probe + A/B, full bench last to warm the driver's
# compile cache) is still the unmeasured backlog — run it verbatim, then
# add the one A/B lost to the round-3 wedge: bf16 factor exchange at the
# full ML-20M scale, judged on als_rmse_ref_delta (the kernel default
# stays f32 unless the quality delta is clean; chip timing said +20%
# throughput at the 5M probe, BASELINE.md solver matrix).
#
# Usage: bash scripts/onchip_r05.sh [outdir]   (default scripts/onchip_r05)
set -u
cd "$(dirname "$0")/.."
OUT="${1:-scripts/onchip_r05}"
mkdir -p "$OUT"
log() { echo "[onchip_r05 $(date +%H:%M:%S)] $*"; }

bash scripts/onchip_r04.sh "$OUT"
rc=$?
if [ $rc -ne 0 ]; then
  log "r4 backlog aborted (rc=$rc) — not queueing the bf16 quality A/B"
  exit $rc
fi

log "bf16 exchange quality A/B at ML-20M scale (lost to the r3 wedge)"
timeout 2400 env BENCH_SECTIONS=als BENCH_ALS_EXCHANGE=bf16 \
  BENCH_SKIP_CPU=1 python bench.py --sections-json als \
  >"$OUT/als_bf16_quality.log" 2>&1
log "bf16 step rc=$? — compare als_rmse_ref_delta vs the f32 run in"
log "$OUT/bench_full.detail.json before flipping any default"
