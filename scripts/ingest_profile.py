#!/usr/bin/env python
"""Ingest-plane microbench: journal bytes -> queryable table rows/sec,
scalar per-line path vs columnar chunk path, by chunk size (ISSUE 2).

Measures the LISTENER path — the one every ALS serving job actually runs
(the top-k index registers a change listener, which disables the native
C++ bulk ingest) — so regressions in the parse/put/notify pipeline are
visible outside the full bench.  The two paths are also cross-checked:
table contents must be byte-identical and parse-error counts equal.

Run host-side (no accelerator needed):

    python scripts/ingest_profile.py [--rows 1000000] [--k 16] \
        [--chunkKiB 256,2048,8192] [--listener dirty|topk|none] [--svm false]

Output: one line per (path, chunk size) with rows/sec — per-row ``put()``
baseline, batched scalar, and columnar — plus the columnar speedup vs each.
"""

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("TPUMS_TOPK_PLATFORM", "cpu")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from flink_ms_tpu.core import formats as F  # noqa: E402
from flink_ms_tpu.core.formats import split_journal_chunk  # noqa: E402
from flink_ms_tpu.core.params import Params  # noqa: E402
from flink_ms_tpu.serve.consumer import (  # noqa: E402
    ALS_STATE,
    SVM_STATE,
    parse_als_record,
    parse_svm_record,
)
from flink_ms_tpu.serve.journal import Journal  # noqa: E402
from flink_ms_tpu.serve.table import ModelTable  # noqa: E402


def build_journal(tmp: str, rows: int, k: int, svm: bool) -> Journal:
    journal = Journal(tmp, "ingest-profile")
    batch = []
    for i in range(rows):
        if svm:
            batch.append(f"{i % (rows // 2 + 1)},{i % 97}.5;{i % 13}")
        else:
            vec = [((i * 31 + j * 17) % 1000) / 500.0 - 1.0 for j in range(k)]
            typ = "I" if i % 3 else "U"
            batch.append(F.format_als_row(i % (rows // 2 + 1), typ, vec))
        if len(batch) >= 100_000:
            journal.append(batch)
            batch = []
    if batch:
        journal.append(batch)
    return journal


class DirtySink:
    """Stand-in for the top-k index's listener cost profile: per-key dirty
    marking under a lock (scalar) vs one locked batch update (columnar)."""

    def __init__(self):
        import threading

        self.dirty = set()
        self.lock = threading.Lock()

    def on_put(self, key):
        with self.lock:
            self.dirty.add(key)

    def on_put_many(self, keys):
        with self.lock:
            self.dirty.update(keys)


def run_path(journal: Journal, parse_fn, path: str, chunk_bytes: int,
             listener: str):
    """Replay the whole journal into a fresh table; -> (table, sink,
    rows, errors, seconds).

    ``path``:
    - ``perrow``   — the seed baseline: per-line parse, one ``put()``
      (lock + per-key listener call) per row;
    - ``scalar``   — per-line parse, chunked ``put_many`` (per-key
      listener calls, batched lock);
    - ``columnar`` — the vectorized plane (chunk split + hashed columns
      + one batched listener call per slice).
    """
    table = ModelTable(8)
    sink = None
    if listener == "dirty":
        sink = DirtySink()
        table.add_change_listener(
            sink.on_put, sink.on_put_many if path == "columnar" else None
        )
    elif listener == "topk":
        from flink_ms_tpu.serve.topk import make_als_topk_handler

        make_als_topk_handler(table)
    offset, rows, errors = 0, 0, 0
    t0 = time.perf_counter()
    while True:
        if path == "columnar":
            chunk, next_offset = journal.read_bytes_from(
                offset, max_bytes=chunk_bytes
            )
            if not chunk:
                break
            keys, values, errs, hashes = split_journal_chunk(
                chunk, parse_fn.columnar_mode, with_hashes=True
            )
            errors += errs
            for s in range(0, len(keys), 50_000):
                table.put_many_columns(
                    keys[s:s + 50_000], values[s:s + 50_000],
                    hashes=None if hashes is None else hashes[s:s + 50_000],
                )
            rows += len(keys)
        else:
            lines, next_offset = journal.read_from(
                offset, max_bytes=chunk_bytes
            )
            if not lines:
                break
            batch = []
            for line in lines:
                if not line:
                    continue
                try:
                    batch.append(parse_fn(line))
                except ValueError:
                    errors += 1
            if path == "perrow":
                for key, value in batch:
                    table.put(key, value)
            else:
                for s in range(0, len(batch), 10_000):
                    table.put_many(batch[s:s + 10_000])
            rows += len(batch)
        offset = next_offset
    dt = time.perf_counter() - t0
    return table, sink, rows, errors, dt


def main(argv=None) -> None:
    params = Params.from_args(sys.argv[1:] if argv is None else argv)
    rows = params.get_int("rows", 1_000_000)
    k = params.get_int("k", 16)
    svm = params.get_bool("svm", False)
    listener = params.get("listener", "dirty")  # dirty | topk | none
    chunk_kib = [
        int(c) for c in params.get("chunkKiB", "256,2048,8192").split(",")
    ]
    parse_fn = parse_svm_record if svm else parse_als_record
    state = SVM_STATE if svm else ALS_STATE

    if listener == "topk":
        # pay the once-per-process JIT warm-up BEFORE the timed replays so
        # the warm thread doesn't compete with the path under measurement
        import threading

        from flink_ms_tpu.serve import topk as _topk

        _topk._warm_jit_async()
        for t in threading.enumerate():
            if t.name == "topk-jit-warm":
                t.join()

    with tempfile.TemporaryDirectory() as tmp:
        print(f"[ingest-profile] building {rows} {state} rows (k={k})...",
              file=sys.stderr)
        journal = build_journal(tmp, rows, k, svm)
        ref_table = None
        for kib in chunk_kib:
            chunk_bytes = kib << 10
            res = {}
            for path in ("perrow", "scalar", "columnar"):
                table, sink, n, errs, dt = run_path(
                    journal, parse_fn, path, chunk_bytes, listener
                )
                res[path] = (n / dt, dt)
                print(
                    f"chunk {kib:>6} KiB  {path:>8}: "
                    f"{n / dt:>12,.0f} rows/s  ({n} rows, {errs} errors, "
                    f"{dt:.2f}s, dirty={len(sink.dirty) if sink else '-'})"
                )
                if ref_table is None:
                    ref_table = table
                else:
                    assert table._shards == ref_table._shards, \
                        "PARITY FAILURE: table contents differ between paths"
            print(
                f"chunk {kib:>6} KiB  columnar vs perrow: "
                f"{res['columnar'][0] / res['perrow'][0]:.2f}x | "
                f"vs scalar: {res['columnar'][0] / res['scalar'][0]:.2f}x"
            )


if __name__ == "__main__":
    main()
