#!/usr/bin/env bash
# Run the moment the tunnel recovers (scripts/chip_probe.sh exits 0):
# everything round 3 still wants from the chip, in priority order, each
# step independent and timeout-bounded.  Artifacts under $OUT.
set -u
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/on_recovery_r03}"
mkdir -p "$OUT"

step() {
  local name="$1"; shift
  echo "=== $name: $*" | tee -a "$OUT/run.log"
  timeout "${STEP_TIMEOUT:-2700}" "$@" > "$OUT/$name.log" 2>&1
  echo "    rc=$? ($(tail -c 160 "$OUT/$name.log" | tr '\n' ' '))" \
    | tee -a "$OUT/run.log"
}

# 1. driver-entry compile check (the driver will run this single-chip)
step entry python -c "
from flink_ms_tpu.parallel.mesh import honor_platform_env
honor_platform_env()
import jax
import __graft_entry__ as g
fn, args = g.entry()
jax.block_until_ready(jax.jit(fn)(*args))
print('entry OK on', jax.devices()[0].platform)
"

# 2. full bench with the round-3 defaults (pallas solver + bf16 exchange
#    + the in-artifact exchange A/B) -> candidate BENCH_local_r03 refresh
BENCH_DETAIL_PATH="$OUT/bench_full.detail.json" \
  timeout "${STEP_TIMEOUT:-2700}" python bench.py \
  > "$OUT/bench_full.json" 2> "$OUT/bench_full.log"
echo "bench_full rc=$?" | tee -a "$OUT/run.log"

# 3. the segmented-anchor validation the K-sweep crashes motivated:
#    K=1024 scatter config whose 40-round reference fit previously killed
#    the worker in one >60 s dispatch — must now survive via segments
step svm_k1024_anchor env BENCH_SECTIONS=svm BENCH_SVM_BLOCKS=1024 \
  BENCH_SKIP_CPU=1 BENCH_DETAIL_PATH="$OUT/svm_k1024.detail.json" \
  python bench.py

echo "recovery run complete; artifacts in $OUT" | tee -a "$OUT/run.log"
