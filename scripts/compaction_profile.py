#!/usr/bin/env python
"""Journal-compaction microbench: fold throughput, space reclaimed, and
replay speedup of (compacted prefix + tail) vs full history (ISSUE 7).

Builds a segmented journal whose history is much longer than its live
state (updates cycling over a fixed key set — the stream compaction
exists for), then measures:

  - fold rate       rows/s through ``compact.compact_journal`` (the
                    last-write-wins fold over sealed segments);
  - space reclaimed bytes_out / bytes_in of the fold;
  - replay speedup  wall time to rebuild state from offset 0 before vs
                    after the fold (the recovery path a respawned
                    replica without a snapshot takes).

Parity is asserted, not assumed: the replayed state and malformed-row
counts after the fold must equal the pre-fold replay exactly.

Run host-side (no accelerator needed):

    python scripts/compaction_profile.py [--rows 1000000] [--keys 10000] \
        [--k 16] [--mode als|svm] [--segmentKiB 256] [--malformedPct 2]
"""

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from flink_ms_tpu.core import formats as F  # noqa: E402
from flink_ms_tpu.core.params import Params  # noqa: E402
from flink_ms_tpu.serve.compact import compact_journal  # noqa: E402
from flink_ms_tpu.serve.consumer import (  # noqa: E402
    parse_als_record,
    parse_svm_record,
)
from flink_ms_tpu.serve.journal import Journal  # noqa: E402


def build_journal(tmp: str, rows: int, keys: int, k: int, mode: str,
                  malformed_pct: int, segment_bytes: int) -> Journal:
    journal = Journal(tmp, "compact-profile", segment_bytes=segment_bytes)
    batch = []
    for i in range(rows):
        if malformed_pct and i % 100 < malformed_pct:
            batch.append(f"malformed-row-{i}")  # kept verbatim by the fold
        elif mode == "svm":
            batch.append(f"{i % keys},{i % 97}.5;{i % 13}")
        else:
            vec = [((i * 31 + j * 17) % 1000) / 500.0 - 1.0 for j in range(k)]
            batch.append(F.format_als_row(i % keys, "I", vec))
        # small append batches so segment rotation engages (rotation is
        # checked per append call, not per line)
        if len(batch) >= 2_000:
            journal.append(batch, flush=False)
            batch = []
    if batch:
        journal.append(batch)
    return journal


def replay(journal: Journal, parse_fn):
    """Consumer-identical scalar replay: LWW state + skip-and-count."""
    state, errors, offset = {}, 0, 0
    t0 = time.perf_counter()
    while True:
        lines, next_offset = journal.read_from(offset, max_bytes=4 << 20)
        if not lines and next_offset == offset:
            return state, errors, time.perf_counter() - t0
        for line in lines:
            if not line:
                continue
            try:
                key, value = parse_fn(line)
            except ValueError:
                errors += 1
                continue
            state[key] = value
        offset = next_offset


def main(argv=None) -> None:
    params = Params.from_args(sys.argv[1:] if argv is None else argv)
    rows = params.get_int("rows", 1_000_000)
    keys = params.get_int("keys", 10_000)
    k = params.get_int("k", 16)
    mode = params.get("mode", "als")
    malformed_pct = params.get_int("malformedPct", 2)
    segment_bytes = params.get_int("segmentKiB", 256) << 10
    parse_fn = parse_svm_record if mode == "svm" else parse_als_record

    with tempfile.TemporaryDirectory() as tmp:
        print(f"[compact-profile] building {rows} {mode} rows over "
              f"{keys} keys (segment {segment_bytes >> 10} KiB)...",
              file=sys.stderr)
        journal = build_journal(
            tmp, rows, keys, k, mode, malformed_pct, segment_bytes)

        def disk_bytes():
            # physical footprint: logical offsets never shrink (that's the
            # offset contract), the on-disk segment files do
            return sum(os.path.getsize(os.path.join(tmp, n))
                       for n in os.listdir(tmp))

        size_before = disk_bytes()

        want_state, want_errors, replay_before_s = replay(journal, parse_fn)
        print(f"replay (full history):   {rows / replay_before_s:>12,.0f} "
              f"rows/s  ({replay_before_s:.2f}s, {len(want_state)} keys, "
              f"{want_errors} malformed)")

        t0 = time.perf_counter()
        stats = compact_journal(journal, parse_fn=parse_fn, min_segments=1)
        fold_s = time.perf_counter() - t0
        if stats is None:
            print("nothing to fold (journal fits one active segment); "
                  "lower --segmentKiB", file=sys.stderr)
            sys.exit(2)
        reclaimed_pct = 100.0 * stats["bytes_reclaimed"] / max(
            stats["bytes_in"], 1)
        print(f"fold:                    {stats['rows_in'] / fold_s:>12,.0f} "
              f"rows/s  ({fold_s:.2f}s, {stats['segments_folded']} segments, "
              f"{stats['rows_in']} -> {stats['rows_out']} rows, "
              f"{reclaimed_pct:.1f}% bytes reclaimed)")

        got_state, got_errors, replay_after_s = replay(journal, parse_fn)
        assert got_state == want_state, \
            "PARITY FAILURE: state differs after compaction"
        assert got_errors == want_errors, \
            "PARITY FAILURE: malformed-row count differs after compaction"
        size_after = disk_bytes()
        replayed = stats["rows_out"] + (rows - stats["rows_in"])
        print(f"replay (prefix + tail):  "
              f"{replayed / replay_after_s:>12,.0f} rows/s  "
              f"({replay_after_s:.2f}s, parity OK)")
        print(f"recovery speedup: {replay_before_s / replay_after_s:.1f}x  |  "
              f"disk {size_before} -> {size_after} bytes "
              f"({100.0 * size_after / max(size_before, 1):.1f}%)")


if __name__ == "__main__":
    main()
