import os, sys, time, json, subprocess, tempfile
import numpy as np
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, "/root/repo")
from flink_ms_tpu.core import formats as F
from flink_ms_tpu.serve.journal import Journal
from flink_ms_tpu.serve.sharded import ShardedQueryClient, stop_worker_procs

tmp = tempfile.mkdtemp()
n_items, n_users, k, W = 300_000, 1000, 16, 3
rng = np.random.default_rng(0)
vals = rng.normal(size=(n_items + n_users, k)).astype(np.float32)
j = Journal(tmp + "/bus", "models")
rows = [F.format_als_row(i + 1, "I", vals[i]) for i in range(n_items)]
rows += [F.format_als_row(u + 1, "U", vals[n_items + u]) for u in range(n_users)]
j.append(rows, flush=True)
print("seeded", flush=True)

procs, ports = [], []
env = {**os.environ, "PYTHONPATH": "/root/repo"}
for idx in range(W):
    pf = f"{tmp}/port-{idx}.json"
    procs.append(subprocess.Popen(
        [sys.executable, "-m", "flink_ms_tpu.serve.sharded",
         "--workerIndex", str(idx), "--numWorkers", str(W),
         "--journalDir", tmp + "/bus", "--topic", "models",
         "--stateBackend", "rocksdb", "--nativeServer", "true",
         "--checkpointDataUri", f"{tmp}/chk",
         "--host", "127.0.0.1", "--port", "0", "--portFile", pf],
        env=env, cwd="/root/repo",
        stdout=open(f"{tmp}/w{idx}.log", "wb"), stderr=subprocess.STDOUT))
try:
    for idx in range(W):
        pf = f"{tmp}/port-{idx}.json"
        for _ in range(1200):
            if os.path.exists(pf) and os.path.getsize(pf) > 0:
                ports.append(json.load(open(pf))["port"]); break
            if procs[idx].poll() is not None:
                raise RuntimeError(open(f"{tmp}/w{idx}.log", errors="replace").read()[-500:])
            time.sleep(0.1)
    with ShardedQueryClient([("127.0.0.1", p) for p in ports], timeout_s=600) as c:
        deadline = time.time() + 600
        while time.time() < deadline:
            if c.query_state("ALS_MODEL", f"{n_items}-I") is not None and \
               c.query_state("ALS_MODEL", "1-U") is not None:
                break
            time.sleep(0.5)
        c.topk("ALS_MODEL", "1", 10)  # index builds
        mg, tk = [], []
        for q in range(200):
            u = int(rng.integers(1, n_users + 1)); i = int(rng.integers(1, n_items + 1))
            t0 = time.perf_counter()
            c.query_states("ALS_MODEL", [f"{u}-U", f"{i}-I"])
            mg.append((time.perf_counter() - t0) * 1e3)
        for q in range(60):
            u = int(rng.integers(1, n_users + 1))
            t0 = time.perf_counter()
            c.topk("ALS_MODEL", str(u), 10)
            tk.append((time.perf_counter() - t0) * 1e3)
        mg.sort(); tk.sort()
        print(f"sharded-native({W} workers, {n_items} items): "
              f"MGET p50 {mg[99]:.3f} p95 {mg[189]:.3f} ms, "
              f"TOPK p50 {tk[29]:.3f} p95 {tk[56]:.3f} ms")
finally:
    stop_worker_procs(procs)
