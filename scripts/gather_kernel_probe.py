#!/usr/bin/env python
"""Probe: can a Pallas TPU kernel gather factor rows from a VMEM-resident
table fast enough to beat XLA's HBM gather + materialized transient?

The ALS roofline (BASELINE.md) charges ~2x8 GB/iter to the (r, w, k)
gather transient (TPU dots don't fuse gather producers) plus the random
200 B row gather itself at worst-case effective bandwidth.  The opposite
factor TABLE is small (items 5.3 MB f32, users 27.7 MB f32 / 13.9 MB
bf16), so if Mosaic can gather from a VMEM-resident table inside the
kernel and feed the contraction directly, both terms vanish.

Variants:
  xla        jnp.take from HBM + einsum (the production path, baseline)
  pallas     fused kernel: whole table as a VMEM operand, per-row-tile
             jnp.take inside the kernel + dot_general contraction, (r,w,k)
             never exists outside VMEM
  pallas_bf16  same with a bf16 table (halves VMEM + gather bytes)

Usage: python scripts/gather_kernel_probe.py [--interpret] [--nnz N]
  --interpret: CPU interpret-mode correctness check only (no timing).
On chip, prints ms per assembly pass for each variant.
"""

import argparse
import functools
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def make_case(rng, n_rows, w, n_table, k, dtype=np.float32):
    """One bucket-shaped assembly: (n_rows, w) idx into an (n_table, k)
    table, values, -> A (n_rows, k, k), b (n_rows, k)."""
    idx = rng.integers(0, n_table, (n_rows, w)).astype(np.int32)
    val = rng.uniform(1, 5, (n_rows, w)).astype(dtype)
    table = rng.standard_normal((n_table, k)).astype(dtype)
    return idx, val, table


def xla_assembly(table, idx, val):
    import jax.numpy as jnp

    y = jnp.take(table, idx, axis=0)                      # (r, w, k)
    A = jnp.einsum("rwk,rwl->rkl", y, y, precision="highest",
                   preferred_element_type=jnp.float32)
    b = jnp.einsum("rwk,rw->rk", y, val.astype(y.dtype),
                   precision="highest", preferred_element_type=jnp.float32)
    return A, b


def pallas_assembly(table, idx, val, row_tile=8, interpret=False):
    """PRODUCTION kernel (flink_ms_tpu.ops.gather_assembly
    .fused_bucket_assembly) — the probe times exactly what
    FLINK_MS_ALS_ASSEMBLY=pallas would run, so a kernel tweak can never
    drift away from the measured numbers."""
    import os

    import jax.numpy as jnp

    from flink_ms_tpu.ops.gather_assembly import fused_bucket_assembly

    os.environ["FLINK_MS_ALS_ASSEMBLY_ROW_TILE"] = str(row_tile)
    platform = "cpu" if interpret else "tpu"
    return fused_bucket_assembly(
        table, idx, val, jnp.float32, platform, precision="highest"
    )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interpret", action="store_true")
    ap.add_argument("--nnz", type=int, default=5_000_000)
    ap.add_argument("--rows", type=int, default=0)
    ap.add_argument("--w", type=int, default=128)
    ap.add_argument("--table", type=int, default=12_000)
    ap.add_argument("--k", type=int, default=64)
    ap.add_argument("--row-tile", type=int, default=8)
    ap.add_argument("--vmem-budget", type=int, default=0,
                    help="force FLINK_MS_ALS_ASSEMBLY_VMEM_BYTES (0 = "
                    "default; small values exercise the sliced multi-pass)")
    args = ap.parse_args()
    if args.vmem_budget:
        import os

        os.environ["FLINK_MS_ALS_ASSEMBLY_VMEM_BYTES"] = str(args.vmem_budget)

    import os

    if args.interpret:
        # FORCE the host pin: the launcher ambiently exports
        # JAX_PLATFORMS=axon, so a setdefault would leave the tunnel
        # plugin registered and a wedged tunnel hangs the first jit
        os.environ["JAX_PLATFORMS"] = "cpu"
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        # honor an explicit host pin BEFORE the first backend touch —
        # plain jax.devices() initializes every registered plugin, and a
        # wedged accelerator tunnel HANGS that init rather than erroring
        from flink_ms_tpu.parallel.mesh import pin_host_backend

        pin_host_backend()

    import jax

    rng = np.random.default_rng(0)
    rows = args.rows or max(args.nnz // args.w, args.row_tile)
    rows -= rows % args.row_tile
    if args.interpret:
        rows = min(rows, 64)
    idx, val, table = make_case(rng, rows, args.w, args.table, args.k)
    print(f"rows={rows} w={args.w} table={args.table} k={args.k} "
          f"({rows * args.w / 1e6:.1f}M gathers)")

    a_ref, b_ref = jax.jit(xla_assembly)(table, idx, val)
    a_ref.block_until_ready()

    if args.interpret:
        a_p, b_p = pallas_assembly(table, idx, val, args.row_tile,
                                   interpret=True)
        # multi-slice runs accumulate per-slice partials (reassociated
        # sums), so their parity is to f32 round-off; single-slice runs
        # keep the tight bound
        from flink_ms_tpu.ops.gather_assembly import _n_slices

        sliced = _n_slices(table.shape, table.dtype) > 1
        rtol, atol = (2e-4, 1e-4) if sliced else (1e-5, 1e-5)
        np.testing.assert_allclose(np.asarray(a_p), np.asarray(a_ref),
                                   rtol=rtol, atol=atol)
        np.testing.assert_allclose(np.asarray(b_p), np.asarray(b_ref),
                                   rtol=rtol, atol=atol)
        print(f"sliced={sliced}", end=" ")
        print("interpret-mode parity OK (xla vs pallas fused)")
        return

    from flink_ms_tpu.utils.profiling import hard_sync

    def bench(fn, *a, n=5):
        out = fn(*a)
        hard_sync(out[0])
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            out = fn(*a)
            hard_sync(out[0])
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2] * 1e3

    results = {}
    results["xla"] = bench(jax.jit(xla_assembly), table, idx, val)
    try:
        fn = jax.jit(functools.partial(
            pallas_assembly, row_tile=args.row_tile))
        results["pallas"] = bench(fn, table, idx, val)
    except Exception as e:  # noqa: BLE001
        results["pallas"] = f"FAILED: {type(e).__name__}: {str(e)[:300]}"
    try:
        tb = table.astype(jax.numpy.bfloat16)
        fn = jax.jit(functools.partial(
            pallas_assembly, row_tile=args.row_tile))
        results["pallas_bf16_table"] = bench(fn, tb, idx, val)
    except Exception as e:  # noqa: BLE001
        results["pallas_bf16_table"] = (
            f"FAILED: {type(e).__name__}: {str(e)[:300]}"
        )
    for name, v in results.items():
        print(f"{name:>20}: {v if isinstance(v, str) else f'{v:8.2f} ms'}")


if __name__ == "__main__":
    main()
