#!/usr/bin/env python
"""End-to-end observability smoke: start one real worker process, issue
traced queries, scrape its metrics over the wire, and assert the core
series moved.

    python scripts/obs_smoke.py

What it checks (exit 0 only if ALL hold):
  1. a traced GET / MGET / TOPK round-trip succeeds and the trace id
     comes back in the event ring (client_rpc + server-echoed tid);
  2. ``METRICS`` scrape of the worker returns per-verb request counters
     > 0 and a latency histogram with count > 0;
  3. the registry-driven fleet scrape (``obs.scrape.scrape_fleet``)
     reaches the worker and the merged fleet snapshot carries the same
     non-zero series;
  4. the Prometheus rendering of the scraped snapshot contains the
     ``tpums_server_requests_total`` and ``_bucket`` series.

Knobs: CHAOS-style env not needed — this is a fixed 1-worker smoke.
Set ``TPUMS_TRACE=-`` to watch the structured event log on stderr.
"""

import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

N = 64
K = 4


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="tpums_obs_smoke_")
    # private registry so the fleet scrape sees exactly this worker
    os.environ["TPUMS_REGISTRY_DIR"] = os.path.join(tmp, "registry")

    from flink_ms_tpu.core import formats as F
    from flink_ms_tpu.obs import (
        recent_events,
        render_prometheus,
        trace_span,
    )
    from flink_ms_tpu.obs.scrape import scrape_endpoint, scrape_fleet
    from flink_ms_tpu.serve.client import QueryClient
    from flink_ms_tpu.serve.consumer import ALS_STATE
    from flink_ms_tpu.serve.journal import Journal
    from flink_ms_tpu.serve.sharded import spawn_worker_procs

    journal = Journal(os.path.join(tmp, "bus"), "models")
    rng = np.random.default_rng(0)
    journal.append(
        [F.format_als_row(u, "U", rng.normal(size=K)) for u in range(N)]
        + [F.format_als_row(i, "I", rng.normal(size=K)) for i in range(N)]
    )

    failures = []

    def check(cond, what):
        tag = "ok" if cond else "FAIL"
        print(f"[smoke] {tag}: {what}", file=sys.stderr)
        if not cond:
            failures.append(what)

    procs, ports = spawn_worker_procs(
        1, journal.dir, "models", port_dir=tmp, state_backend="memory"
    )
    port = ports[0]
    try:
        with QueryClient("127.0.0.1", port, timeout_s=60) as c:
            deadline = time.time() + 60
            while time.time() < deadline:
                if c.health(ALS_STATE).get("ready"):
                    break
                time.sleep(0.1)
            check(c.health(ALS_STATE).get("ready"), "worker became ready")
            check(
                "metrics_uri" in c.health(ALS_STATE),
                "HEALTH advertises metrics_uri",
            )

            # --- traced queries -------------------------------------
            with trace_span() as tid:
                got = c.query_state(ALS_STATE, "1-U")
                many = c.query_states(ALS_STATE, ["2-U", "3-I"])
                top = c.topk(ALS_STATE, "1", 5)
            check(got is not None, "traced GET answered")
            check(len(many) == 2, "traced MGET answered")
            check(len(top) == 5, "traced TOPK answered")
            chain = recent_events(tid=tid)
            kinds = [e["kind"] for e in chain]
            check(
                kinds.count("client_rpc") >= 3,
                f"event chain has >=3 client_rpc spans under one tid "
                f"(got {kinds})",
            )

            # --- wire scrape ----------------------------------------
            snap = scrape_endpoint("127.0.0.1", port)
            check(snap is not None, "METRICS scrape reachable")
            series = {}
            hists = {}
            if snap:
                for ctr in snap["counters"]:
                    series[(ctr["name"], ctr["labels"].get("verb"))] = (
                        ctr["value"]
                    )
                for h in snap["histograms"]:
                    hists[(h["name"], h["labels"].get("verb"))] = h["count"]
            check(
                series.get(("tpums_server_requests_total", "GET"), 0) > 0,
                "scraped GET request counter > 0",
            )
            check(
                series.get(("tpums_server_requests_total", "TOPK"), 0) > 0,
                "scraped TOPK request counter > 0",
            )
            check(
                hists.get(("tpums_server_latency_seconds", "GET"), 0) > 0,
                "scraped GET latency histogram count > 0",
            )

            # --- fleet scrape + prometheus rendering ----------------
            fleet = scrape_fleet()
            check(
                len(fleet["replicas"]) == 1 and not fleet["unreachable"],
                "fleet scrape found the worker via the registry",
            )
            merged = fleet["fleet"]
            merged_reqs = sum(
                ctr["value"]
                for ctr in merged.get("counters", [])
                if ctr["name"] == "tpums_server_requests_total"
            )
            check(merged_reqs > 0, "merged fleet request total > 0")
            prom = render_prometheus(merged) if merged else ""
            check(
                "tpums_server_requests_total{" in prom
                and "tpums_server_latency_seconds_bucket{" in prom,
                "prometheus rendering has counter + bucket series",
            )
            if snap:
                print(
                    json.dumps(
                        {
                            "port": port,
                            "series": len(snap["counters"])
                            + len(snap["gauges"])
                            + len(snap["histograms"]),
                            "failures": failures,
                        }
                    )
                )
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()

    if failures:
        print(f"[smoke] {len(failures)} check(s) FAILED", file=sys.stderr)
        return 1
    print("[smoke] all checks passed", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
