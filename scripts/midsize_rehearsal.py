#!/usr/bin/env python
"""Mid-size multi-device rehearsal (VERDICT r3 weak #5): the evidence layer
between the toy-shape dryrun and real multi-chip hardware.

One 8-device CPU-mesh run at ~1M-nnz ALS and ~100k-example SVM that PINS,
not just exercises:
  - per-device factor-shard shapes and the per-device device-arg memory
    footprint (the numbers that decide whether a config fits HBM),
  - exchange-volume accounting under the routed all_to_all (net rows per
    device crossing the interconnect, vs what the all_gather would ship),
  - staging resume across a simulated restart (iteration-boundary
    snapshots, second run resumes instead of recomputing, final factors
    identical to an uninterrupted fit),
  - SVM chain stacking (K > D) with convergence at scale,
  - a serving-plane SLO rehearsal on the closed-loop workload engine
    (obs/workload.py): zipfian mixed-verb load + autoscaler + replica
    kill, report must be schema-valid with zero unattributed errors
    (gate with REHEARSAL_SERVING=0; knobs REHEARSAL_SERVING_SHARDS /
    _REPLICATION / _USERS / _BASE_QPS / _PEAK_QPS / _BURST_QPS /
    _THREADS / _AUTOSCALE / _KILL).

Writes one JSON artifact (default REHEARSAL_r05.json next to the repo
root; override with REHEARSAL_OUT) and exits non-zero on any violated
invariant.  Runtime on one CPU core is minutes — this is a rehearsal, not
a benchmark; sec/iter numbers in the artifact are CPU-mesh numbers and
say nothing about chip performance.
"""

import json
import os
import sys
import time

N_DEV = int(os.environ.get("REHEARSAL_DEVICES", 8))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={N_DEV}"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flink_ms_tpu.parallel.mesh import pin_host_backend  # noqa: E402

pin_host_backend()

import numpy as np  # noqa: E402

ART = {"devices": N_DEV, "checks": []}


def check(name, ok, **info):
    ART["checks"].append({"name": name, "ok": bool(ok), **info})
    status = "OK " if ok else "FAIL"
    print(f"[rehearsal] {status} {name} {info}", flush=True)
    return ok


def main() -> int:
    import jax

    from flink_ms_tpu.ops import als
    from flink_ms_tpu.ops.als import (
        ALSConfig, als_fit, compile_fit, prepare_blocked, rmse,
    )
    from flink_ms_tpu.parallel.mesh import BLOCK_AXIS, make_mesh

    mesh = make_mesh(N_DEV)
    ok = True

    # -- ALS at ~1M nnz ----------------------------------------------------
    n_users = int(os.environ.get("REHEARSAL_USERS", 200_000))
    n_items = int(os.environ.get("REHEARSAL_ITEMS", 40_000))
    nnz = int(os.environ.get("REHEARSAL_NNZ", 1_000_000))
    k = int(os.environ.get("REHEARSAL_RANK", 16))
    rng = np.random.default_rng(11)
    users = rng.integers(0, n_users, nnz)
    items = rng.integers(0, n_items, nnz)
    ratings = rng.uniform(1.0, 5.0, nnz)

    t0 = time.time()
    problem = prepare_blocked(users, items, ratings, N_DEV)
    ART["als"] = {
        "nnz": nnz, "n_users": problem.n_users, "n_items": problem.n_items,
        "rank": k, "users_per_block": problem.u.per_block,
        "items_per_block": problem.i.per_block,
        "prepare_s": round(time.time() - t0, 2),
    }

    # exchange accounting under the routed all_to_all (auto mode decides
    # per half-sweep; at this density the user side must route)
    plan = als._exchange_plan(problem, N_DEV)
    exch = {}
    for name, opp in (("u", problem.i), ("i", problem.u)):
        r = plan[name]
        gather_rows = (N_DEV - 1) * opp.per_block
        exch[name] = {
            "mode": "routed" if r is not None else "gather",
            "gather_rows_per_device": gather_rows,
            "net_rows_per_device": (
                r.net_rows if r is not None else gather_rows
            ),
            "net_bytes_per_device_f32": 4 * k * (
                r.net_rows if r is not None else gather_rows
            ),
        }
    ART["als"]["exchange"] = exch
    # the i-sweep exchanges the big USER factor table (200k rows) — that
    # is the side whose need-lists are sparse enough to route; the u-sweep
    # gathers the small saturated item catalog and correctly stays gather
    ok &= check(
        "als_user_factor_exchange_routes", plan["i"] is not None,
        net=exch["i"]["net_rows_per_device"],
        gather=exch["i"]["gather_rows_per_device"],
    )
    if plan["i"] is not None:
        ok &= check(
            "als_routed_crosses_less",
            exch["i"]["net_rows_per_device"]
            < exch["i"]["gather_rows_per_device"],
            ratio=round(exch["i"]["net_rows_per_device"]
                        / exch["i"]["gather_rows_per_device"], 3),
        )

    # per-device shard shapes + device-arg memory footprint
    cfg = ALSConfig(num_factors=k, iterations=1, lambda_=0.1,
                    exchange_dtype=None)
    fit_fn, dev_args = compile_fit(problem, cfg, mesh)
    uf0 = dev_args[0]
    shard_shapes = {
        str(d.id): s.data.shape for s in uf0.addressable_shards
        for d in [s.device]
    }
    want = (1, problem.u.per_block, k)
    ok &= check(
        "als_factor_shard_shape",
        all(s == want for s in shard_shapes.values())
        and len(shard_shapes) == N_DEV,
        shape=list(want), n_shards=len(shard_shapes),
    )
    per_dev_bytes = 0
    for a in dev_args:
        spec = getattr(a.sharding, "spec", None)
        sharded = bool(spec) and len(spec) > 0 and spec[0] == BLOCK_AXIS
        per_dev_bytes += a.nbytes // (N_DEV if sharded else 1)
    ART["als"]["per_device_arg_bytes"] = int(per_dev_bytes)
    ok &= check("als_per_device_bytes_accounted", per_dev_bytes > 0,
                mib=round(per_dev_bytes / 2**20, 1))

    # one timed step (CPU-mesh number, for the record only)
    import jax.numpy as jnp

    t0 = time.time()
    uf, itf = fit_fn(jnp.asarray(1, jnp.int32), *dev_args)
    jax.block_until_ready(uf)
    ART["als"]["first_iter_incl_compile_s"] = round(time.time() - t0, 2)
    t0 = time.time()
    uf, itf = fit_fn(jnp.asarray(2, jnp.int32), *dev_args)
    jax.block_until_ready(uf)
    ART["als"]["two_iter_steady_s"] = round(time.time() - t0, 2)

    # -- staging resume across a simulated restart -------------------------
    import shutil
    import tempfile

    stage = tempfile.mkdtemp(prefix="rehearsal_stage_")
    try:
        init = (0.1 * rng.standard_normal((problem.n_users, k)),
                0.1 * rng.standard_normal((problem.n_items, k)))
        cfg4 = ALSConfig(num_factors=k, iterations=4, lambda_=0.1,
                         exchange_dtype=None)
        cfg2 = ALSConfig(num_factors=k, iterations=2, lambda_=0.1,
                         exchange_dtype=None)
        # "crash" after 2 staged iterations...
        t0 = time.time()
        als_fit(users, items, ratings, cfg2, mesh, problem=problem,
                init=init, temporary_path=stage)
        staged_after_crash = sorted(os.listdir(stage))
        # ...then a NEW run to 4 iterations resumes from the snapshot:
        # it must be faster than 4 cold iterations and bitwise-match the
        # uninterrupted fit
        t_resume0 = time.time()
        m_resumed = als_fit(users, items, ratings, cfg4, mesh,
                            problem=problem, init=init,
                            temporary_path=stage)
        resume_s = time.time() - t_resume0
        m_straight = als_fit(users, items, ratings, cfg4, mesh,
                             problem=problem, init=init)
        ok &= check(
            "als_staging_resume_snapshots", len(staged_after_crash) >= 1,
            files=staged_after_crash[-2:],
        )
        same = np.allclose(m_resumed.user_factors, m_straight.user_factors,
                           rtol=1e-5, atol=1e-7)
        ok &= check("als_staging_resume_matches_straight_fit", same,
                    resume_s=round(resume_s, 2))
        ART["als"]["rmse_after_4_iters"] = round(
            rmse(m_straight, users, items, ratings), 6)
    finally:
        shutil.rmtree(stage, ignore_errors=True)

    # -- SVM at ~100k examples --------------------------------------------
    from flink_ms_tpu.core.formats import SparseData
    from flink_ms_tpu.ops.svm import SVMConfig, prepare_svm_blocked, svm_fit

    n_ex = int(os.environ.get("REHEARSAL_SVM_EXAMPLES", 100_000))
    n_feat = int(os.environ.get("REHEARSAL_SVM_FEATURES", 5_000))
    nnz_row = 12
    indptr = np.arange(n_ex + 1) * nnz_row
    indices = rng.integers(0, n_feat, n_ex * nnz_row).astype(np.int64)
    values = rng.normal(size=n_ex * nnz_row)
    w_true = rng.normal(size=n_feat)
    scores = np.add.reduceat(values * w_true[indices], indptr[:-1])
    labels = np.where(scores >= 0, 1.0, -1.0)
    flip = rng.random(n_ex) < 0.05
    labels[flip] = -labels[flip]
    data = SparseData(labels=labels, indices=indices, values=values,
                      indptr=indptr, n_features=n_feat)

    K = int(os.environ.get("REHEARSAL_SVM_K", 1024))
    # the RCV1 bench configuration family: CoCoA+ add mode with the
    # aggressive sigma' regime (BASELINE.md K-sweep) — avg mode at K=1024
    # divides every round's progress by K and barely moves at 5 rounds
    svm_cfg = SVMConfig(iterations=5, local_iterations=10,
                        regularization=1e-4, mode="add", sigma_prime=8.0)
    t0 = time.time()
    svm_problem = prepare_svm_blocked(data, K, seed=svm_cfg.seed)
    prep_s = time.time() - t0
    t0 = time.time()
    model0 = svm_fit(data, svm_cfg, mesh, problem=svm_problem)
    fit_s = time.time() - t0
    h5 = model0.hinge_loss(data, svm_cfg.regularization)
    import dataclasses as dc

    h15 = svm_fit(
        data, dc.replace(svm_cfg, iterations=15), mesh, problem=svm_problem
    ).hinge_loss(data, svm_cfg.regularization)
    ART["svm"] = {
        "examples": n_ex, "features": n_feat, "chains": K,
        "chains_per_device": -(-K // N_DEV),
        "prepare_s": round(prep_s, 2), "fit5_s": round(fit_s, 2),
        "hinge_after_5": round(h5, 6), "hinge_after_15": round(h15, 6),
    }
    ok &= check("svm_converges_with_rounds", h15 < h5 < 1.0,
                h5=round(h5, 4), h15=round(h15, 4))
    ok &= check("svm_chains_stack_per_device", K > N_DEV,
                chains_per_device=-(-K // N_DEV))

    # -- multi-process DCN rehearsal: 2 procs x 4 devices over gloo --------
    # (VERDICT r4 #7: the distributed code path — parallel/distributed.py,
    # gloo collectives, single-writer staging, process-0-authoritative
    # resume — must carry the routed exchange and staging-resume at ~1M
    # nnz, not just the in-process 8-device mesh.)  Stand-in for the
    # multi-host run this environment cannot provide.
    if os.environ.get("REHEARSAL_MULTIPROC", "1") != "0":
        import socket as _socket
        import subprocess

        from flink_ms_tpu.core import formats as F

        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        mp_dir = tempfile.mkdtemp(prefix="rehearsal_mp_")
        try:
            csv = os.path.join(mp_dir, "ratings.csv")
            F.write_ratings(csv, users, items, ratings)

            def _run_group(tag, argv_for, extra_env=None, n_procs=2,
                           dev_per_proc=4):
                """Launch an n-process CLI group over a fresh coordinator
                port.  stdout goes to FILES, not pipes: sequentially
                draining piped children deadlocks if a later one fills
                its 64 KB pipe mid-collective while we wait on an earlier
                one.  A hung/failed member must not orphan its siblings
                while the cleanup below deletes its working dir."""
                with _socket.socket() as s:
                    s.bind(("127.0.0.1", 0))
                    port = s.getsockname()[1]
                procs, handles, logs = [], [], []
                try:
                    for pid in range(n_procs):
                        log_path = os.path.join(mp_dir, f"{tag}-p{pid}.log")
                        logs.append(log_path)
                        fh = open(log_path, "wb")
                        handles.append(fh)
                        procs.append(subprocess.Popen(
                            argv_for(pid, port),
                            env={**os.environ, "JAX_PLATFORMS": "cpu",
                                 "XLA_FLAGS":
                                 "--xla_force_host_platform_device_count="
                                 f"{dev_per_proc}",
                                 **(extra_env or {})},
                            cwd=repo_root, stdout=fh,
                            stderr=subprocess.STDOUT))
                    deadline = time.time() + 1800
                    rcs = [p.wait(timeout=max(1.0, deadline - time.time()))
                           for p in procs]
                except Exception:
                    for p in procs:
                        if p.poll() is None:
                            p.kill()
                            p.wait(timeout=30)
                    raise
                finally:
                    for fh in handles:
                        fh.close()
                outs = [open(lp, errors="replace").read() for lp in logs]
                return rcs, outs

            def _als_argv(iterations, tag):
                def argv_for(pid, port):
                    out_dir = os.path.join(mp_dir, f"{tag}-p{pid}")
                    return [sys.executable, "-m",
                            "flink_ms_tpu.train.als_train",
                            "--input", csv, "--ignoreFirstLine", "false",
                            "--iterations", str(iterations),
                            "--numFactors", str(k), "--lambda", "0.1",
                            "--coordinatorAddress", f"127.0.0.1:{port}",
                            "--numProcesses", "2", "--processId", str(pid),
                            "--temporaryPath",
                            os.path.join(mp_dir, f"stage{pid}"),
                            "--userFactors", os.path.join(out_dir, "uf"),
                            "--itemFactors", os.path.join(out_dir, "itf")]
                return argv_for

            # pin the routed path on: auto may pick gather for one side,
            # and this section exists to prove routing across processes
            _routed = {"FLINK_MS_ALS_EXCHANGE_MODE": "routed"}

            t0 = time.time()
            rcs_a, outs_a = _run_group("runA", _als_argv(2, "runA"),
                                      _routed)  # "crash" after 2 iters
            wall_a = round(time.time() - t0, 1)
            ok &= check("mp_als_2proc_crash_run_exits_zero",
                        rcs_a == [0, 0], wall_s=wall_a,
                        tail="" if rcs_a == [0, 0] else outs_a[0][-400:])
            stage0 = os.path.join(mp_dir, "stage0")
            pre = sorted(os.listdir(stage0)) if os.path.isdir(stage0) else []
            t0 = time.time()
            rcs_b, outs_b = _run_group("runB", _als_argv(4, "runB"),
                                      _routed)  # new run resumes
            wall_b = round(time.time() - t0, 1)
            ok &= check("mp_als_resume_run_exits_zero", rcs_b == [0, 0],
                        wall_s=wall_b,
                        tail="" if rcs_b == [0, 0] else outs_b[0][-400:])
            post = sorted(os.listdir(stage0)) if os.path.isdir(stage0) \
                else []
            # the staging dir prunes to a trailing window, so final file
            # listings cannot distinguish resume from cold rerun — the
            # als_fit resume marker on process 0's stdout can
            resumed = "[ALS] staging: resuming from iteration 2" in outs_b[0]
            ok &= check("mp_als_resume_marker_on_process0", resumed,
                        pre=pre[:4], post=post[:6])
            # process-0 output of the resumed run must match an in-process
            # single-process 4-iteration fit (same CLI defaults: seed 42
            # init, lambda 0.1) across the CSV round trip
            if rcs_b == [0, 0]:
                cfg_cli = ALSConfig(num_factors=k, iterations=4, lambda_=0.1)
                ref = als_fit(users, items, ratings, cfg_cli, mesh,
                              problem=problem)
                ids, kinds, rows = F.read_als_model(
                    os.path.join(mp_dir, "runB-p0", "uf"))
                got = {int(i): r for i, kk, r in zip(ids, kinds, rows)}
                nan_row = np.full(k, np.nan)
                match = len(got) == len(ref.user_ids) and all(
                    np.allclose(got.get(int(uid), nan_row), row,
                                rtol=1e-4, atol=1e-5)
                    for uid, row in zip(ref.user_ids, ref.user_factors)
                )
                ok &= check("mp_als_resumed_matches_inprocess_fit", match,
                            users=len(got))
            else:
                ok &= check("mp_als_resumed_matches_inprocess_fit", False,
                            skipped="resume run failed")
            ART["multiproc"] = {
                "processes": 2, "devices_per_process": 4,
                "backend": "gloo", "nnz": nnz, "rank": k,
                "exchange_mode": "routed",
                "crash_run_2it_s": wall_a, "resume_run_4it_s": wall_b,
            }

            # CoCoA SVM over the same 2-proc x 4-device gloo mesh: chains
            # split by the deterministic layout, deltas psum'd over DCN —
            # process-0 output must equal the in-process fit
            svm_lines = []
            for r in range(n_ex):
                lo, hi = indptr[r], indptr[r + 1]
                tok = " ".join(f"{int(j) + 1}:{v}" for j, v in
                               zip(indices[lo:hi], values[lo:hi]))
                svm_lines.append(f"{int(labels[r])} {tok}")  # +-1 labels:
                # a 0/1 encoding would alias -1 onto sign(0) -> +1 in
                # prepare_svm_blocked
            svm_train_path = os.path.join(mp_dir, "svm_train.libsvm")
            with open(svm_train_path, "w") as f:
                f.write("\n".join(svm_lines) + "\n")
            def _svm_argv(pid, port):
                return [sys.executable, "-m",
                        "flink_ms_tpu.train.svm_train",
                        "--training", svm_train_path,
                        "--blocks", "64", "--iteration", "3",
                        "--localIterations", "20",
                        "--coordinatorAddress", f"127.0.0.1:{port}",
                        "--numProcesses", "2", "--processId", str(pid),
                        "--output", os.path.join(mp_dir, f"svm-w{pid}")]

            t0 = time.time()
            sv_rcs, sv_outs = _run_group("svm", _svm_argv)
            wall_svm = round(time.time() - t0, 1)
            ok &= check("mp_svm_2proc_exits_zero", sv_rcs == [0, 0],
                        wall_s=wall_svm,
                        tail="" if sv_rcs == [0, 0] else sv_outs[0][-400:])
            if sv_rcs == [0, 0]:
                sp = prepare_svm_blocked(data, 64, seed=0)
                ref_cfg = SVMConfig(iterations=3, local_iterations=20,
                                    regularization=1.0)
                ref_w = svm_fit(data, ref_cfg, mesh, problem=sp).weights
                got_w = F.read_svm_model(
                    os.path.join(mp_dir, "svm-w0"), n_features=n_feat)
                ok &= check(
                    "mp_svm_matches_inprocess_fit",
                    np.allclose(got_w, ref_w, rtol=1e-4, atol=1e-6),
                    d=n_feat,
                )
                # single-writer output contract across processes
                ok &= check("mp_svm_single_writer",
                            not os.path.exists(
                                os.path.join(mp_dir, "svm-w1")))
                ART["multiproc"]["svm_2proc_3rounds_s"] = wall_svm
            else:
                ok &= check("mp_svm_matches_inprocess_fit", False,
                            skipped="svm pair failed")

            # N>2 process group (VERDICT r4 held the comm cell at
            # "partial — never exercised beyond 2 procs"): 4 procs x
            # 2 devices over gloo — same 8 global devices, so the
            # blocked layout and the in-process reference fit are
            # unchanged; what varies is process count, per-process
            # addressable shards, and the routed exchange now crossing
            # three process boundaries.
            def _als4_argv(pid, port):
                out_dir = os.path.join(mp_dir, f"run4-p{pid}")
                return [sys.executable, "-m",
                        "flink_ms_tpu.train.als_train",
                        "--input", csv, "--ignoreFirstLine", "false",
                        "--iterations", "2",
                        "--numFactors", str(k), "--lambda", "0.1",
                        "--coordinatorAddress", f"127.0.0.1:{port}",
                        "--numProcesses", "4", "--processId", str(pid),
                        "--userFactors", os.path.join(out_dir, "uf"),
                        "--itemFactors", os.path.join(out_dir, "itf")]

            t0 = time.time()
            rcs4, outs4 = _run_group("run4", _als4_argv, _routed,
                                     n_procs=4, dev_per_proc=2)
            wall4 = round(time.time() - t0, 1)
            ok &= check("mp_als_4proc_exits_zero", rcs4 == [0] * 4,
                        wall_s=wall4,
                        tail="" if rcs4 == [0] * 4 else outs4[0][-400:])
            if rcs4 == [0] * 4:
                cfg2_cli = ALSConfig(num_factors=k, iterations=2,
                                     lambda_=0.1)
                ref2 = als_fit(users, items, ratings, cfg2_cli, mesh,
                               problem=problem)
                ids, kinds, rows = F.read_als_model(
                    os.path.join(mp_dir, "run4-p0", "uf"))
                got = {int(i): r for i, kk, r in zip(ids, kinds, rows)}
                nan_row = np.full(k, np.nan)
                match4 = len(got) == len(ref2.user_ids) and all(
                    np.allclose(got.get(int(uid), nan_row), row,
                                rtol=1e-4, atol=1e-5)
                    for uid, row in zip(ref2.user_ids, ref2.user_factors))
                ok &= check("mp_als_4proc_matches_inprocess_fit", match4,
                            users=len(got))
            else:
                ok &= check("mp_als_4proc_matches_inprocess_fit", False,
                            skipped="4-proc run failed")
            ART["multiproc"]["als_4proc_2dev_2it_s"] = wall4
            ART["multiproc"]["groups"] = [
                {"processes": 2, "devices_per_process": 4},
                {"processes": 4, "devices_per_process": 2},
            ]
        except Exception as e:
            # a crashed harness must still land its earlier checks in the
            # artifact (ok=false), not lose them to an unhandled traceback
            ok &= check("mp_section_completes", False,
                        error=f"{type(e).__name__}: {e}")
        finally:
            shutil.rmtree(mp_dir, ignore_errors=True)

    # -- serving-plane rehearsal on the closed-loop workload engine -------
    # (obs/workload.py + obs/slo.py): zipfian mixed-verb open-loop load
    # against a live sharded group with autoscaler + one replica kill, SLO
    # accounting from the fleet scrape.  The hand-rolled query loop this
    # script used to need lives in the engine now — this stage just sets
    # knobs and checks the report.
    if os.environ.get("REHEARSAL_SERVING", "1") != "0":
        from flink_ms_tpu.obs.slo import validate_report
        from flink_ms_tpu.obs.workload import run_rehearsal

        serving_out = os.path.join(
            tempfile.mkdtemp(prefix="rehearsal_serving_"),
            "SLO_REPORT.json")
        try:
            report = run_rehearsal(
                out_path=serving_out,
                shards=int(os.environ.get("REHEARSAL_SERVING_SHARDS", 2)),
                replication=int(
                    os.environ.get("REHEARSAL_SERVING_REPLICATION", 2)),
                users=int(os.environ.get("REHEARSAL_SERVING_USERS", 400)),
                base_qps=float(
                    os.environ.get("REHEARSAL_SERVING_BASE_QPS", 120)),
                peak_qps=float(
                    os.environ.get("REHEARSAL_SERVING_PEAK_QPS", 240)),
                burst_qps=float(
                    os.environ.get("REHEARSAL_SERVING_BURST_QPS", 480)),
                warm_s=2.0, ramp_s=3.0, burst_s=4.0, cool_s=2.0,
                threads=int(
                    os.environ.get("REHEARSAL_SERVING_THREADS", 4)),
                autoscale=os.environ.get(
                    "REHEARSAL_SERVING_AUTOSCALE", "live"),
                kill=os.environ.get("REHEARSAL_SERVING_KILL", "1") != "0",
                seed=0,
            )
            problems = validate_report(report)
            ok &= check("serving_slo_report_schema_valid", not problems,
                        problems=problems[:3])
            ok &= check("serving_zero_unattributed_errors",
                        report["errors"]["unattributed"] == 0,
                        errors=report["errors"]["total"])
            unattr_breaches = [
                b for b in report["breaches"] if not b.get("attribution")]
            ok &= check("serving_breaches_attributed", not unattr_breaches,
                        breaches=len(report["breaches"]))
            wl = report["workload"]
            ok &= check("serving_open_loop_kept_schedule",
                        wl["completed"] == wl["scheduled"],
                        scheduled=wl["scheduled"], completed=wl["completed"],
                        max_lag_s=wl["max_sched_lag_s"])
            ART["serving"] = {
                "ok": report["ok"],
                "scheduled": wl["scheduled"],
                "achieved_qps": wl["achieved_qps"],
                "errors": report["errors"]["total"],
                "breaches": len(report["breaches"]),
                "kills": sum(1 for e in report["timeline"]
                             if "kill" in e.get("kind", "")),
                "verbs": {v: {"availability": d["availability"],
                              "p99_ms": d["p99_ms"],
                              "burn_rate": d["burn_rate"]}
                          for v, d in report["verbs"].items()},
            }
        except Exception as e:
            ok &= check("serving_rehearsal_completes", False,
                        error=f"{type(e).__name__}: {e}")
        finally:
            shutil.rmtree(os.path.dirname(serving_out), ignore_errors=True)

    ART["ok"] = bool(ok)
    out_path = os.environ.get("REHEARSAL_OUT") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "REHEARSAL_r05.json",
    )
    with open(out_path, "w") as f:
        json.dump(ART, f, indent=1)
        f.write("\n")
    print(f"[rehearsal] artifact -> {out_path} ok={ok}", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
