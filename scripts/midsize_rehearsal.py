#!/usr/bin/env python
"""Mid-size multi-device rehearsal (VERDICT r3 weak #5): the evidence layer
between the toy-shape dryrun and real multi-chip hardware.

One 8-device CPU-mesh run at ~1M-nnz ALS and ~100k-example SVM that PINS,
not just exercises:
  - per-device factor-shard shapes and the per-device device-arg memory
    footprint (the numbers that decide whether a config fits HBM),
  - exchange-volume accounting under the routed all_to_all (net rows per
    device crossing the interconnect, vs what the all_gather would ship),
  - staging resume across a simulated restart (iteration-boundary
    snapshots, second run resumes instead of recomputing, final factors
    identical to an uninterrupted fit),
  - SVM chain stacking (K > D) with convergence at scale.

Writes one JSON artifact (default REHEARSAL_r04.json next to the repo
root; override with REHEARSAL_OUT) and exits non-zero on any violated
invariant.  Runtime on one CPU core is minutes — this is a rehearsal, not
a benchmark; sec/iter numbers in the artifact are CPU-mesh numbers and
say nothing about chip performance.
"""

import json
import os
import sys
import time

N_DEV = int(os.environ.get("REHEARSAL_DEVICES", 8))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={N_DEV}"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flink_ms_tpu.parallel.mesh import pin_host_backend  # noqa: E402

pin_host_backend()

import numpy as np  # noqa: E402

ART = {"devices": N_DEV, "checks": []}


def check(name, ok, **info):
    ART["checks"].append({"name": name, "ok": bool(ok), **info})
    status = "OK " if ok else "FAIL"
    print(f"[rehearsal] {status} {name} {info}", flush=True)
    return ok


def main() -> int:
    import jax

    from flink_ms_tpu.ops import als
    from flink_ms_tpu.ops.als import (
        ALSConfig, als_fit, compile_fit, prepare_blocked, rmse,
    )
    from flink_ms_tpu.parallel.mesh import BLOCK_AXIS, make_mesh

    mesh = make_mesh(N_DEV)
    ok = True

    # -- ALS at ~1M nnz ----------------------------------------------------
    n_users = int(os.environ.get("REHEARSAL_USERS", 200_000))
    n_items = int(os.environ.get("REHEARSAL_ITEMS", 40_000))
    nnz = int(os.environ.get("REHEARSAL_NNZ", 1_000_000))
    k = int(os.environ.get("REHEARSAL_RANK", 16))
    rng = np.random.default_rng(11)
    users = rng.integers(0, n_users, nnz)
    items = rng.integers(0, n_items, nnz)
    ratings = rng.uniform(1.0, 5.0, nnz)

    t0 = time.time()
    problem = prepare_blocked(users, items, ratings, N_DEV)
    ART["als"] = {
        "nnz": nnz, "n_users": problem.n_users, "n_items": problem.n_items,
        "rank": k, "users_per_block": problem.u.per_block,
        "items_per_block": problem.i.per_block,
        "prepare_s": round(time.time() - t0, 2),
    }

    # exchange accounting under the routed all_to_all (auto mode decides
    # per half-sweep; at this density the user side must route)
    plan = als._exchange_plan(problem, N_DEV)
    exch = {}
    for name, opp in (("u", problem.i), ("i", problem.u)):
        r = plan[name]
        gather_rows = (N_DEV - 1) * opp.per_block
        exch[name] = {
            "mode": "routed" if r is not None else "gather",
            "gather_rows_per_device": gather_rows,
            "net_rows_per_device": (
                r.net_rows if r is not None else gather_rows
            ),
            "net_bytes_per_device_f32": 4 * k * (
                r.net_rows if r is not None else gather_rows
            ),
        }
    ART["als"]["exchange"] = exch
    # the i-sweep exchanges the big USER factor table (200k rows) — that
    # is the side whose need-lists are sparse enough to route; the u-sweep
    # gathers the small saturated item catalog and correctly stays gather
    ok &= check(
        "als_user_factor_exchange_routes", plan["i"] is not None,
        net=exch["i"]["net_rows_per_device"],
        gather=exch["i"]["gather_rows_per_device"],
    )
    if plan["i"] is not None:
        ok &= check(
            "als_routed_crosses_less",
            exch["i"]["net_rows_per_device"]
            < exch["i"]["gather_rows_per_device"],
            ratio=round(exch["i"]["net_rows_per_device"]
                        / exch["i"]["gather_rows_per_device"], 3),
        )

    # per-device shard shapes + device-arg memory footprint
    cfg = ALSConfig(num_factors=k, iterations=1, lambda_=0.1,
                    exchange_dtype=None)
    fit_fn, dev_args = compile_fit(problem, cfg, mesh)
    uf0 = dev_args[0]
    shard_shapes = {
        str(d.id): s.data.shape for s in uf0.addressable_shards
        for d in [s.device]
    }
    want = (1, problem.u.per_block, k)
    ok &= check(
        "als_factor_shard_shape",
        all(s == want for s in shard_shapes.values())
        and len(shard_shapes) == N_DEV,
        shape=list(want), n_shards=len(shard_shapes),
    )
    per_dev_bytes = 0
    for a in dev_args:
        spec = getattr(a.sharding, "spec", None)
        sharded = bool(spec) and len(spec) > 0 and spec[0] == BLOCK_AXIS
        per_dev_bytes += a.nbytes // (N_DEV if sharded else 1)
    ART["als"]["per_device_arg_bytes"] = int(per_dev_bytes)
    ok &= check("als_per_device_bytes_accounted", per_dev_bytes > 0,
                mib=round(per_dev_bytes / 2**20, 1))

    # one timed step (CPU-mesh number, for the record only)
    import jax.numpy as jnp

    t0 = time.time()
    uf, itf = fit_fn(jnp.asarray(1, jnp.int32), *dev_args)
    jax.block_until_ready(uf)
    ART["als"]["first_iter_incl_compile_s"] = round(time.time() - t0, 2)
    t0 = time.time()
    uf, itf = fit_fn(jnp.asarray(2, jnp.int32), *dev_args)
    jax.block_until_ready(uf)
    ART["als"]["two_iter_steady_s"] = round(time.time() - t0, 2)

    # -- staging resume across a simulated restart -------------------------
    import shutil
    import tempfile

    stage = tempfile.mkdtemp(prefix="rehearsal_stage_")
    try:
        init = (0.1 * rng.standard_normal((problem.n_users, k)),
                0.1 * rng.standard_normal((problem.n_items, k)))
        cfg4 = ALSConfig(num_factors=k, iterations=4, lambda_=0.1,
                         exchange_dtype=None)
        cfg2 = ALSConfig(num_factors=k, iterations=2, lambda_=0.1,
                         exchange_dtype=None)
        # "crash" after 2 staged iterations...
        t0 = time.time()
        als_fit(users, items, ratings, cfg2, mesh, problem=problem,
                init=init, temporary_path=stage)
        staged_after_crash = sorted(os.listdir(stage))
        # ...then a NEW run to 4 iterations resumes from the snapshot:
        # it must be faster than 4 cold iterations and bitwise-match the
        # uninterrupted fit
        t_resume0 = time.time()
        m_resumed = als_fit(users, items, ratings, cfg4, mesh,
                            problem=problem, init=init,
                            temporary_path=stage)
        resume_s = time.time() - t_resume0
        m_straight = als_fit(users, items, ratings, cfg4, mesh,
                             problem=problem, init=init)
        ok &= check(
            "als_staging_resume_snapshots", len(staged_after_crash) >= 1,
            files=staged_after_crash[-2:],
        )
        same = np.allclose(m_resumed.user_factors, m_straight.user_factors,
                           rtol=1e-5, atol=1e-7)
        ok &= check("als_staging_resume_matches_straight_fit", same,
                    resume_s=round(resume_s, 2))
        ART["als"]["rmse_after_4_iters"] = round(
            rmse(m_straight, users, items, ratings), 6)
    finally:
        shutil.rmtree(stage, ignore_errors=True)

    # -- SVM at ~100k examples --------------------------------------------
    from flink_ms_tpu.core.formats import SparseData
    from flink_ms_tpu.ops.svm import SVMConfig, prepare_svm_blocked, svm_fit

    n_ex = int(os.environ.get("REHEARSAL_SVM_EXAMPLES", 100_000))
    n_feat = int(os.environ.get("REHEARSAL_SVM_FEATURES", 5_000))
    nnz_row = 12
    indptr = np.arange(n_ex + 1) * nnz_row
    indices = rng.integers(0, n_feat, n_ex * nnz_row).astype(np.int64)
    values = rng.normal(size=n_ex * nnz_row)
    w_true = rng.normal(size=n_feat)
    scores = np.add.reduceat(values * w_true[indices], indptr[:-1])
    labels = np.where(scores >= 0, 1.0, -1.0)
    flip = rng.random(n_ex) < 0.05
    labels[flip] = -labels[flip]
    data = SparseData(labels=labels, indices=indices, values=values,
                      indptr=indptr, n_features=n_feat)

    K = int(os.environ.get("REHEARSAL_SVM_K", 1024))
    # the RCV1 bench configuration family: CoCoA+ add mode with the
    # aggressive sigma' regime (BASELINE.md K-sweep) — avg mode at K=1024
    # divides every round's progress by K and barely moves at 5 rounds
    svm_cfg = SVMConfig(iterations=5, local_iterations=10,
                        regularization=1e-4, mode="add", sigma_prime=8.0)
    t0 = time.time()
    svm_problem = prepare_svm_blocked(data, K, seed=svm_cfg.seed)
    prep_s = time.time() - t0
    t0 = time.time()
    model0 = svm_fit(data, svm_cfg, mesh, problem=svm_problem)
    fit_s = time.time() - t0
    h5 = model0.hinge_loss(data, svm_cfg.regularization)
    import dataclasses as dc

    h15 = svm_fit(
        data, dc.replace(svm_cfg, iterations=15), mesh, problem=svm_problem
    ).hinge_loss(data, svm_cfg.regularization)
    ART["svm"] = {
        "examples": n_ex, "features": n_feat, "chains": K,
        "chains_per_device": -(-K // N_DEV),
        "prepare_s": round(prep_s, 2), "fit5_s": round(fit_s, 2),
        "hinge_after_5": round(h5, 6), "hinge_after_15": round(h15, 6),
    }
    ok &= check("svm_converges_with_rounds", h15 < h5 < 1.0,
                h5=round(h5, 4), h15=round(h15, 4))
    ok &= check("svm_chains_stack_per_device", K > N_DEV,
                chains_per_device=-(-K // N_DEV))

    ART["ok"] = bool(ok)
    out_path = os.environ.get("REHEARSAL_OUT") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "REHEARSAL_r04.json",
    )
    with open(out_path, "w") as f:
        json.dump(ART, f, indent=1)
        f.write("\n")
    print(f"[rehearsal] artifact -> {out_path} ok={ok}", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
