#!/usr/bin/env bash
# End-to-end SVM workflow on synthetic data, mirroring the reference's
# pipeline (SURVEY.md §3): SVMImpl (CoCoA training, range-partitioned
# output) -> SVMKafkaProducer -> SVMKafkaConsumer -> SVMPredictRandom and
# RangePartitionSVMPredict latency harnesses.
#
# Usage: scripts/e2e_demo_svm.sh [workdir]
# Runs anywhere: CPU by default (DEMO_PLATFORM to override).
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=${DEMO_PLATFORM:-cpu}
WORK=${1:-$(mktemp -d /tmp/flink-ms-tpu-svm-demo.XXXXXX)}
mkdir -p "$WORK"
PY=${PYTHON:-python}
PORT=${PORT:-16124}
JOB_ID=svm-demo-$$
N_FEATURES=200
RANGE=50

echo "== workspace: $WORK  (serving on 127.0.0.1:$PORT, job $JOB_ID)"

echo "== [1/6] synthetic LibSVM training data (1000 x $N_FEATURES, separable)"
$PY - "$WORK" "$N_FEATURES" <<'PYEOF'
import sys, numpy as np
work, n_feat = sys.argv[1], int(sys.argv[2])
rng = np.random.default_rng(42)
w_true = rng.normal(size=n_feat)
with open(f"{work}/train.libsvm", "w") as f:
    for _ in range(1000):
        nnz = rng.integers(5, 20)
        idx = np.sort(rng.choice(n_feat, size=nnz, replace=False))
        val = rng.normal(size=nnz)
        label = 1 if val @ w_true[idx] > 0 else -1
        f.write(f"{label} " + " ".join(
            f"{i + 1}:{v:.4f}" for i, v in zip(idx, val)) + "\n")
PYEOF

echo "== [2/6] CoCoA SVM training, range-partitioned output (svm_train ~ SVMImpl)"
$PY -m flink_ms_tpu.train.svm_train \
  --training "$WORK/train.libsvm" --blocks 4 --iteration 10 \
  --partition true --range "$RANGE" --output "$WORK/model/weights"

echo "== [3/6] publish weight rows into the journal (svm_producer ~ SVMKafkaProducer)"
$PY -m flink_ms_tpu.serve.svm_producer \
  --input "$WORK/model" --journalDir "$WORK/journal" --topic svm-model

echo "== [4/6] serving job (svm_consumer ~ SVMKafkaConsumer) in background"
$PY -m flink_ms_tpu.serve.svm_consumer \
  --journalDir "$WORK/journal" --topic svm-model \
  --stateBackend fs --checkpointDataUri "$WORK/ckpt" \
  --host 127.0.0.1 --port "$PORT" --jobId "$JOB_ID" \
  >"$WORK/serving.log" 2>&1 &
SERVE_PID=$!
trap 'kill $SERVE_PID 2>/dev/null || true' EXIT

$PY - "$PORT" <<'PYEOF'
import socket, sys, time
port = int(sys.argv[1])
deadline = time.time() + 60
while time.time() < deadline:
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=2) as s:
            s.sendall(b"PING\n")
            if s.recv(64).startswith(b"PONG"):
                sys.exit(0)
    except OSError:
        time.sleep(0.3)
sys.exit("serving job did not come up")
PYEOF
sleep 2

echo "== [5/6] query-per-bucket latency harness (range_partition_svm_predict)"
$PY -m flink_ms_tpu.client.range_partition_svm_predict \
  --jobId "$JOB_ID" --jobManagerHost 127.0.0.1 --jobManagerPort "$PORT" \
  --numQueries 200 --maxNoOfFeatures "$N_FEATURES" --range "$RANGE" \
  --outputFile "$WORK/latency_bucket.csv"
echo "   bucket-query latency csv head:"; head -3 "$WORK/latency_bucket.csv" | sed 's/^/     /'

echo "== [6/6] done"
echo "   artifacts under $WORK (model/, journal/, ckpt/, latency_bucket.csv)"
