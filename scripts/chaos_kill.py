#!/usr/bin/env python
"""Fault-injection harness for the HA serving plane (serve/ha.py): run a
replicated shard cluster under a sustained query load while SIGKILLing
random replicas at a configurable rate, and report what the clients saw —
availability (success rate), latency percentiles, and per-kill recovery
time (kill -> the respawned replica registers ready again).

    python scripts/chaos_kill.py [env knobs below]

Knobs (env):
    CHAOS_MODE=ha          "ha" (kill serving replicas, below), "elastic"
                           (kill a WARMING replica mid-bootstrap during a
                           live scale-out — the elastic plane's cutover
                           failure model: the supervisor respawns it,
                           replay resumes, the cutover still completes,
                           and no client ever saw the warming generation),
                           or "snapshot" (run with aggressive snapshot
                           publishing + background journal compaction and
                           SIGKILL replicas mid-publish / mid-fold: every
                           surviving snapshot must still pass its checksum
                           gate, respawns must bootstrap from a snapshot,
                           and clients see zero errors at R >= 2),
                           or "update" (run the sharded online-update
                           plane under a sustained rating stream and
                           SIGKILL co-located UpdateWorkers mid-batch:
                           the sequence audit must show zero lost and
                           zero double-applied ratings, and recovery goes
                           through the standard replay-then-ready path),
                           or "rollout" (SIGKILL a warming replica
                           mid-bulk-load during a live blue/green model
                           rollout while an over-quota tenant hammers the
                           fleet through the cutover: in-quota clients
                           must see zero errors, the abuser must be SHED
                           rather than served, and the rollout must either
                           complete on v2 or abort cleanly back on v1),
                           or "autopilot" (run the continuous-training
                           autopilot as a subprocess under rehearsal load
                           and SIGKILL it twice — once mid-RETRAIN and
                           once mid-ROLLOUT, timed off its persisted
                           phase record: serving availability stays 1.0
                           throughout, the next lease holder steals the
                           dead lease, resumes from the persisted state
                           record and converges to an automatically
                           rolled-out candidate, with zero unattributed
                           pages via the watch wrapper),
                           or "region" (run a two-region deployment —
                           home fleet + geo-replicated follower fleet
                           serving region-local reads — under rehearsal
                           write load, PARTITION the journal replicator
                           mid-segment, then SIGKILL the entire home
                           region including its supervisor: the follower
                           RegionController must promote within 5s,
                           region-local reads stay at availability 1.0
                           throughout, write forwarding re-points to the
                           new home, replication lag p99 before the kill
                           stays under 250ms, and staleness is visible
                           per-read over the wire),
                           or "arena" (SIGKILL the shared-memory arena's
                           single writer mid-row and mid-snapshot-publish
                           while lock-free readers hammer the same mmap:
                           no reader ever sees a torn row — a killed
                           write reads as missing, never garbage — the
                           respawn takes the kernel-released flock and
                           its replay pass repairs every row, reader
                           availability stays 1.0, and bootstrap walks
                           past any mid-publish-torn snapshot member),
                           or "edge" (run mixed tab/B2 load through the
                           edge proxy tier, SIGSTOP-then-SIGKILL one
                           upstream replica and SIGKILL one proxy:
                           hedged requests mask the stalled replica,
                           the mark-down/retry path absorbs its death,
                           clients rotate to the surviving proxy, and
                           no client ever sees an error),
                           or "push" (SIGKILL a subscribed-to replica and
                           an edge proxy mid-update-storm while push
                           subscribers hold live KEY/TOPK subscriptions
                           through the proxy tier: the client-observed
                           sequence audit must show zero missed and zero
                           duplicate notifications across both kills —
                           hub resync bridges the replica death, RESUME
                           against the survivor bridges the proxy death —
                           every KEY subscriber's push-built value
                           converges to the pulled truth, and concurrent
                           pull traffic holds availability 1.0;
                           CHAOS_PUSH_SUBS=6 sets the subscriber count)
    CHAOS_ROWS=20000       seeded journal length (snapshot mode — long
                           history over few keys so the fold has work)
    CHAOS_UPDATE_BATCH=200 ratings per producer tick (update mode)
    CHAOS_WORKERS=2        shards
    CHAOS_REPLICATION=2    replicas per shard (1 reproduces the reference's
                           single-owner outage behavior)
    CHAOS_DURATION_S=30    load window (ha mode)
    CHAOS_KILL_EVERY_S=5   mean seconds between kills (0 disables; ha mode)
    CHAOS_THREADS=4        closed-loop client threads
    CHAOS_USERS=200        model rows per type
    TPUMS_HEARTBEAT_S / TPUMS_REPLICA_TTL_S: liveness cadence (defaults
                           here: 0.25 / 1.5 — fast detection for a demo)

Kill/recovery timeline is logged as structured events through the
observability event log (``flink_ms_tpu.obs.tracing``) — set
``TPUMS_TRACE=<path>`` to persist the JSONL timeline, or ``-`` for
stderr.  Latency percentiles go through the serving plane's shared
bucketed-quantile helper, so they are the same statistic a fleet
scrape would report.

Exit code 1 if any client-visible error occurred at replication >= 2
(the zero-visible-errors contract), 0 otherwise.
"""

import json
import os
import random
import signal
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TPUMS_HEARTBEAT_S", "0.25")
os.environ.setdefault("TPUMS_REPLICA_TTL_S", "1.5")

from flink_ms_tpu.core import formats as F  # noqa: E402
from flink_ms_tpu.obs import bucketed_quantiles, event, recent_events  # noqa: E402
from flink_ms_tpu.serve import registry  # noqa: E402
from flink_ms_tpu.serve.client import RetryPolicy  # noqa: E402
from flink_ms_tpu.serve.consumer import ALS_STATE  # noqa: E402
from flink_ms_tpu.serve.ha import ReplicaSupervisor  # noqa: E402
from flink_ms_tpu.serve.journal import Journal  # noqa: E402

MODE = os.environ.get("CHAOS_MODE", "ha")
W = int(os.environ.get("CHAOS_WORKERS", 2))
R = int(os.environ.get("CHAOS_REPLICATION", 2))
DURATION_S = float(os.environ.get("CHAOS_DURATION_S", 30))
KILL_EVERY_S = float(os.environ.get("CHAOS_KILL_EVERY_S", 5))
THREADS = int(os.environ.get("CHAOS_THREADS", 4))
N_USERS = int(os.environ.get("CHAOS_USERS", 200))
TOPK_PCT = float(os.environ.get("CHAOS_TOPK_PCT", 20))  # % of ops that are TOPK
TOPK_K = int(os.environ.get("CHAOS_TOPK_K", 8))


def seed_journal(base):
    journal = Journal(os.path.join(base, "bus"), "models")
    rng = np.random.default_rng(0)
    k = 4
    journal.append(
        [F.format_als_row(u, "U", rng.normal(size=k))
         for u in range(N_USERS)]
        + [F.format_als_row(i, "I", rng.normal(size=k))
           for i in range(N_USERS)]
    )
    return journal, [f"{u}-U" for u in range(N_USERS)]


def pcts(ms):
    if not ms:
        return {}
    qs = bucketed_quantiles([m / 1e3 for m in ms], (50, 95, 99))
    return {f"p{q}": round(v * 1e3, 3) for q, v in zip((50, 95, 99), qs)}


def main() -> int:
    base = tempfile.mkdtemp(prefix="tpums_chaos_")
    journal, keys = seed_journal(base)

    sup = ReplicaSupervisor(
        W, R, journal.dir, "models", os.path.join(base, "ports"),
        state_backend="memory",
        check_interval_s=registry.heartbeat_interval_s(),
        respawn_delay_s=0.1,
    )
    event("chaos_start", workers=W, replication=R, group=sup.job_group,
          duration_s=DURATION_S, kill_every_s=KILL_EVERY_S)
    ok = [0] * THREADS
    errs = [0] * THREADS
    lat_ms = [[] for _ in range(THREADS)]
    # per-verb attribution: kills hit GET (single shard, failover retries)
    # and TOPK (all-shard fan-out, fails if ANY shard's owner set is down)
    # very differently — report them separately so an outage's blast
    # radius is visible per verb, not smeared into one aggregate.
    VERBS = ("GET", "TOPK")
    verb_ok = [{v: 0 for v in VERBS} for _ in range(THREADS)]
    verb_err = [{v: 0 for v in VERBS} for _ in range(THREADS)]
    verb_ms = [{v: [] for v in VERBS} for _ in range(THREADS)]
    stop = threading.Event()
    kills = []   # (t_kill, shard, replica)

    def load(widx):
        # one HAShardedClient per thread (the client is single-threaded by
        # contract, like ShardedQueryClient)
        c = sup.client(retry=RetryPolicy(
            attempts=6, backoff_s=0.02, max_backoff_s=0.5), timeout_s=10)
        r = random.Random(widx)
        with c:
            if TOPK_PCT > 0:  # warm the TOPK JIT outside the measured loop
                try:
                    c.topk(ALS_STATE, keys[0][:-2], TOPK_K)
                except Exception:
                    pass
            while not stop.is_set():
                key = keys[r.randrange(len(keys))]
                verb = "TOPK" if r.random() * 100.0 < TOPK_PCT else "GET"
                t0 = time.perf_counter()
                try:
                    if verb == "TOPK":
                        good = c.topk(ALS_STATE, key[:-2],
                                      TOPK_K) is not None
                    else:
                        good = c.query_state(ALS_STATE, key) is not None
                except Exception:
                    good = False
                dt_ms = (time.perf_counter() - t0) * 1000.0
                if good:
                    ok[widx] += 1
                    verb_ok[widx][verb] += 1
                else:
                    errs[widx] += 1
                    verb_err[widx][verb] += 1
                lat_ms[widx].append(dt_ms)
                verb_ms[widx][verb].append(dt_ms)

    with sup.start():
        if not sup.wait_all_ready(120):
            event("chaos_abort", reason="cluster never became ready")
            return 2
        threads = [threading.Thread(target=load, args=(i,), daemon=True)
                   for i in range(THREADS)]
        for t in threads:
            t.start()
        t_end = time.time() + DURATION_S
        next_kill = time.time() + (KILL_EVERY_S or float("inf"))
        r = random.Random(42)
        while time.time() < t_end:
            time.sleep(0.05)
            if KILL_EVERY_S and time.time() >= next_kill:
                shard = r.randrange(W)
                replica = r.randrange(R)
                proc = sup.procs.get((shard, replica))
                if proc is not None and proc.poll() is None:
                    event("chaos_kill", shard=shard, replica=replica,
                          pid=proc.pid, group=sup.group_of(shard))
                    proc.send_signal(signal.SIGKILL)
                    kills.append((time.time(), shard, replica))
                next_kill = time.time() + KILL_EVERY_S * (
                    0.5 + r.random())
        stop.set()
        for t in threads:
            t.join(timeout=30)

        # recovery time per kill: kill -> a ready registry entry for that
        # (shard, replica) with a spawn event newer than the kill
        recoveries = []
        for t_kill, shard, replica in kills:
            respawned = [e for e in sup.events
                         if e["action"] == "spawn" and e["t"] > t_kill
                         and e["shard"] == shard
                         and e["replica"] == replica]
            if not respawned:
                recoveries.append(None)
                continue
            deadline = time.time() + 60
            t_ready = None
            while time.time() < deadline:
                members = registry.resolve_replicas(sup.group_of(shard))
                if any(e.get("replica") == replica and e.get("ready")
                       for e in members):
                    t_ready = time.time()
                    break
                time.sleep(0.05)
            rec = None if t_ready is None else round(t_ready - t_kill, 2)
            event("chaos_recovery", shard=shard, replica=replica,
                  recovery_s=rec, recovered=rec is not None)
            recoveries.append(rec)
        # the fleet is about to be torn down deliberately — mark it so the
        # watch loop attributes the replica drop instead of paging blind
        event("chaos_teardown", mode="ha")

    flat = [x for lane in lat_ms for x in lane]
    total_ok, total_err = sum(ok), sum(errs)
    total = total_ok + total_err
    by_verb = {}
    for v in VERBS:
        v_ok = sum(lane[v] for lane in verb_ok)
        v_err = sum(lane[v] for lane in verb_err)
        v_tot = v_ok + v_err
        if not v_tot:
            continue
        by_verb[v] = {
            "queries": v_tot, "ok": v_ok, "errors": v_err,
            "availability": round(v_ok / v_tot, 6),
            "latency_ms": pcts([x for lane in verb_ms for x in lane[v]]),
        }
    summary = {
        "workers": W, "replication": R, "duration_s": DURATION_S,
        "topk_pct": TOPK_PCT,
        "queries": total, "ok": total_ok, "errors": total_err,
        "availability": round(total_ok / total, 6) if total else None,
        "latency_ms": pcts(flat),
        "by_verb": by_verb,
        "kills": len(kills),
        "respawns": sup.respawns,
        "recovery_s": recoveries,
        # full structured timeline (kills, recoveries, supervisor
        # respawn/heartbeat events) from the in-process event ring
        "timeline": [e for e in recent_events()
                     if e["kind"].startswith(("chaos_", "replica_"))],
    }
    print(json.dumps(summary, indent=1))
    return 1 if (R >= 2 and total_err) else 0


def elastic_main() -> int:
    """SIGKILL a WARMING replica mid-bootstrap during a live W -> 2W
    scale-out.  The contract under test (serve/elastic.py failure model):
    generation g serves the whole time, the warming generation's
    supervisor respawns the victim and replay resumes, the cutover still
    completes, and no client sees an error."""
    from flink_ms_tpu.serve.elastic import ElasticClient, ScaleController

    base = tempfile.mkdtemp(prefix="tpums_chaos_elastic_")
    journal, keys = seed_journal(base)
    os.environ.setdefault(
        "TPUMS_REGISTRY_DIR", tempfile.mkdtemp(prefix="tpums_chaos_reg_"))

    ctl = ScaleController("chaos-elastic", journal.dir, "models",
                          port_dir=os.path.join(base, "ports"),
                          ready_timeout_s=180)
    event("chaos_elastic_start", shards=W, target=W * 2)
    ok = [0] * THREADS
    errs = [0] * THREADS
    stop = threading.Event()

    def load(widx):
        c = ElasticClient(
            "chaos-elastic", retry=RetryPolicy(
                attempts=6, backoff_s=0.02, max_backoff_s=0.5),
            timeout_s=10)
        r = random.Random(widx)
        with c:
            while not stop.is_set():
                key = keys[r.randrange(len(keys))]
                try:
                    if c.query_state(ALS_STATE, key) is None:
                        errs[widx] += 1
                    else:
                        ok[widx] += 1
                except Exception:
                    errs[widx] += 1

    result = {}
    try:
        ctl.scale_to(W)
        threads = [threading.Thread(target=load, args=(i,), daemon=True)
                   for i in range(THREADS)]
        for t in threads:
            t.start()

        t0 = time.time()

        def do_scale():
            try:
                result["record"] = ctl.scale_to(W * 2)
            except Exception as e:  # the arm FAILED: cutover aborted
                result["error"] = repr(e)

        st = threading.Thread(target=do_scale)
        st.start()
        # the window: ctl.warming is the bootstrapping generation's
        # supervisor from launch until cutover (or abort).  Only members
        # whose port is already known are fair game — a member killed
        # inside its own launch wait fails the spawn instead of
        # exercising the respawn-and-resume path under test.
        victim = None
        while st.is_alive() and victim is None:
            warm = ctl.warming
            if warm is not None:
                launched = sorted(sr for sr in warm.procs
                                  if sr in warm.ports)
                if launched:
                    sr = launched[0]
                    proc = warm.procs.get(sr)
                    if proc is not None and proc.poll() is None:
                        event("chaos_kill_warming", shard=sr[0],
                              replica=sr[1], pid=proc.pid)
                        proc.send_signal(signal.SIGKILL)
                        victim = sr
            time.sleep(0.01)
        st.join()
        cutover_s = round(time.time() - t0, 2)
        time.sleep(1.0)  # let the load loop exercise the new generation
        stop.set()
        for t in threads:
            t.join(timeout=30)
        active = ctl.active_supervisor
        summary = {
            "mode": "elastic", "shards": W, "target": W * 2,
            "victim": list(victim) if victim else None,
            "cutover_ok": "record" in result,
            "cutover_error": result.get("error"),
            "cutover_s": cutover_s,
            "new_gen": result.get("record", {}).get("gen"),
            "respawns": active.respawns if active else None,
            "ok": sum(ok), "errors": sum(errs),
            "controller_events": ctl.events,
            "timeline": [e for e in recent_events()
                         if e["kind"].startswith(("chaos_", "elastic_",
                                                  "replica_"))],
        }
        print(json.dumps(summary, indent=1, default=str))
        failed = (sum(errs) > 0 or "record" not in result
                  or victim is None or not (active and active.respawns))
        return 1 if failed else 0
    finally:
        stop.set()
        event("chaos_teardown", mode="elastic")
        ctl.stop(drop_topology=True)


def snapshot_main() -> int:
    """SIGKILL replicas mid-snapshot-publish and mid-compaction.  The
    cluster runs with a tiny publish threshold (a snapshot per
    checkpoint) and an aggressive background compactor while a producer
    keeps appending, so kills land inside both write paths.  Contracts
    under test (serve/snapshot.py atomic tmp-dir publish, serve/journal.py
    atomic fold swap): every snapshot visible to resolution still passes
    its checksum gate, respawned replicas bootstrap from a snapshot (not
    full replay), and clients see zero errors at R >= 2."""
    from flink_ms_tpu.serve import snapshot as snapshot_mod
    from flink_ms_tpu.serve.client import QueryClient

    rows = int(os.environ.get("CHAOS_ROWS", 20_000))
    base = tempfile.mkdtemp(prefix="tpums_chaos_snap_")
    # long history over few keys in SMALL segments: both the publisher
    # and the compactor have continuous work to be killed in the middle of
    journal = Journal(os.path.join(base, "bus"), "models",
                      segment_bytes=32 << 10)
    rng = np.random.default_rng(0)
    k = 4
    batch = [F.format_als_row(u, "U", rng.normal(size=k))
             for u in range(N_USERS)]
    for i in range(rows):
        batch.append(F.format_als_row(i % N_USERS, "I", rng.normal(size=k)))
        if len(batch) >= 2_000:
            journal.append(batch, flush=False)
            batch = []
    if batch:
        journal.append(batch)
    keys = [f"{u}-U" for u in range(N_USERS)]
    snap_root = snapshot_mod.snapshot_root(journal.dir, "models")

    # workers inherit these: compact on shard 0 replica 0, fast cadence
    os.environ["TPUMS_COMPACT_INTERVAL_S"] = os.environ.get(
        "TPUMS_COMPACT_INTERVAL_S", "0.2")
    os.environ["TPUMS_COMPACT_MIN_SEGMENTS"] = os.environ.get(
        "TPUMS_COMPACT_MIN_SEGMENTS", "2")
    sup = ReplicaSupervisor(
        W, R, journal.dir, "models", os.path.join(base, "ports"),
        state_backend="memory",
        check_interval_s=registry.heartbeat_interval_s(),
        respawn_delay_s=0.1,
        extra_args=["--snapshotMinBytes", "1", "--compact", "true"],
    )
    event("chaos_snapshot_start", workers=W, replication=R, rows=rows,
          group=sup.job_group, duration_s=DURATION_S,
          kill_every_s=KILL_EVERY_S)
    ok = [0] * THREADS
    errs = [0] * THREADS
    stop = threading.Event()
    kills = []        # (t_kill, shard, replica, old_pid)
    recoveries = []   # (recovery_s or None, bootstrap_source or None)

    def load(widx):
        c = sup.client(retry=RetryPolicy(
            attempts=6, backoff_s=0.02, max_backoff_s=0.5), timeout_s=10)
        r = random.Random(widx)
        with c:
            while not stop.is_set():
                try:
                    good = c.query_state(
                        ALS_STATE, keys[r.randrange(len(keys))]) is not None
                except Exception:
                    good = False
                (ok if good else errs)[widx] += 1

    def produce():
        # keep the journal moving so checkpoints (and therefore snapshot
        # publishes) and folds keep happening throughout the kill window
        r = np.random.default_rng(7)
        i = 0
        while not stop.is_set():
            journal.append(
                [F.format_als_row((i + j) % N_USERS, "I", r.normal(size=k))
                 for j in range(500)], flush=False)
            i += 500
            time.sleep(0.05)

    def other_replicas_ready(shard, replica):
        members = registry.resolve_replicas(sup.group_of(shard))
        return any(e.get("replica") != replica and e.get("ready")
                   for e in members)

    def wait_recovered(shard, replica, old_pid, timeout_s=60.0):
        # a NEW pid registering ready is the unambiguous signal (the
        # stale record still says ready until the respawn overwrites it)
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            members = registry.resolve_replicas(sup.group_of(shard))
            if any(e.get("replica") == replica and e.get("ready")
                   and e.get("pid") not in (None, old_pid)
                   for e in members):
                return True
            time.sleep(0.05)
        return False

    with sup.start():
        if not sup.wait_all_ready(120):
            event("chaos_abort", reason="cluster never became ready")
            return 2
        threads = [threading.Thread(target=load, args=(i,), daemon=True)
                   for i in range(THREADS)]
        threads.append(threading.Thread(target=produce, daemon=True))
        for t in threads:
            t.start()
        t_end = time.time() + DURATION_S
        next_kill = time.time() + (KILL_EVERY_S or float("inf"))
        r = random.Random(42)
        victim_cycle = 0
        while time.time() < t_end:
            time.sleep(0.05)
            if not (KILL_EVERY_S and time.time() >= next_kill):
                continue
            # bias kills onto shard 0 — it hosts the compactor and (like
            # every shard) the replica-0 snapshot publisher — alternating
            # replicas so both the publish and fold paths get hit, but
            # never kill a replica whose peers aren't ready (that would
            # make client errors expected instead of contract-violating)
            shard = 0 if victim_cycle % 2 == 0 else r.randrange(W)
            replica = victim_cycle % R
            victim_cycle += 1
            proc = sup.procs.get((shard, replica))
            if (proc is None or proc.poll() is not None
                    or not other_replicas_ready(shard, replica)):
                next_kill = time.time() + 0.25
                continue
            event("chaos_kill", shard=shard, replica=replica,
                  pid=proc.pid, group=sup.group_of(shard))
            proc.send_signal(signal.SIGKILL)
            t_kill = time.time()
            kills.append((t_kill, shard, replica, proc.pid))
            if wait_recovered(shard, replica, proc.pid):
                rec = round(time.time() - t_kill, 2)
                source = None
                try:
                    with QueryClient(
                            sup.host, sup.ports[(shard, replica)],
                            timeout_s=5) as qc:
                        source = qc.health(ALS_STATE).get(
                            "bootstrap_source")
                except Exception:
                    pass
                event("chaos_recovery", shard=shard, replica=replica,
                      recovery_s=rec, bootstrap_source=source)
                recoveries.append((rec, source))
            else:
                event("chaos_recovery", shard=shard, replica=replica,
                      recovery_s=None, bootstrap_source=None)
                recoveries.append((None, None))
            next_kill = time.time() + KILL_EVERY_S * (0.5 + r.random())
        stop.set()
        for t in threads:
            t.join(timeout=30)

        # checksum-gate audit: every snapshot that resolution would hand
        # a bootstrapping replica must verify; interrupted publishes may
        # leave .tmp- dirs behind, which must stay invisible
        snapshot_audit = {"verified": 0, "plans": 0, "tmp_leftovers": 0,
                          "corrupt": []}
        if os.path.isdir(snap_root):
            snapshot_audit["tmp_leftovers"] = sum(
                1 for n in os.listdir(snap_root) if n.startswith(".tmp-"))
        for shard in range(W):
            plan = snapshot_mod.resolve(snap_root, owner=(shard, W))
            if plan is None:
                continue
            snapshot_audit["plans"] += 1
            for member in plan["members"]:
                try:
                    snapshot_mod.read_columns(member)
                    snapshot_audit["verified"] += 1
                except snapshot_mod.SnapshotCorruptError as e:
                    snapshot_audit["corrupt"].append(str(e))
        event("chaos_teardown", mode="snapshot")

    total_ok, total_err = sum(ok), sum(errs)
    total = total_ok + total_err
    snap_bootstraps = sum(1 for _, src in recoveries if src == "snapshot")
    recovered = [rec for rec, _ in recoveries if rec is not None]
    summary = {
        "mode": "snapshot", "workers": W, "replication": R,
        "rows_seeded": rows, "duration_s": DURATION_S,
        "queries": total, "ok": total_ok, "errors": total_err,
        "availability": round(total_ok / total, 6) if total else None,
        "kills": len(kills), "respawns": sup.respawns,
        "recovery_s": [rec for rec, _ in recoveries],
        "bootstrap_sources": [src for _, src in recoveries],
        "snapshot_bootstraps": snap_bootstraps,
        "snapshot_audit": snapshot_audit,
        "timeline": [e for e in recent_events()
                     if e["kind"].startswith(("chaos_", "replica_"))],
    }
    print(json.dumps(summary, indent=1))
    failed = (
        (R >= 2 and total_err > 0)            # zero-visible-error contract
        or not kills                           # the chaos never happened
        or len(recovered) < len(kills)         # a respawn never came back
        or snapshot_audit["corrupt"]           # a bad checksum was served
        or snapshot_audit["plans"] < W         # a shard has no snapshot
        or snap_bootstraps == 0                # recovery replayed history
    )
    return 1 if failed else 0


def rollout_main() -> int:
    """SIGKILL a warming replica mid-bulk-load during a live blue/green
    model rollout (serve/rollout.py) while an over-quota tenant hammers
    the fleet through the cutover window (serve/admission.py).  Contracts
    under test: the active generation serves v1 the whole time; the
    warming v2 generation's supervisor respawns the victim and the
    rollout still completes (or aborts cleanly, leaving v1 published and
    serving); in-quota clients see ZERO errors while the abusive tenant
    is shed ("over quota") rather than served."""
    from flink_ms_tpu.serve.admission import SHED_MARKER
    from flink_ms_tpu.serve.elastic import ElasticClient
    from flink_ms_tpu.serve.rollout import RolloutController

    base = tempfile.mkdtemp(prefix="tpums_chaos_rollout_")
    os.environ.setdefault(
        "TPUMS_REGISTRY_DIR", tempfile.mkdtemp(prefix="tpums_chaos_reg_"))
    # quota small enough that one closed-loop abuser runs persistently
    # over it — the workers inherit this at spawn
    os.environ.setdefault("TPUMS_ADMIT_TENANT_QPS", "abuse=25")

    k = 4

    def seed_model(name, seed):
        journal = Journal(os.path.join(base, f"bus-{name}"), "models")
        rng = np.random.default_rng(seed)
        journal.append(
            [F.format_als_row(u, "U", rng.normal(size=k))
             for u in range(N_USERS)]
            + [F.format_als_row(i, "I", rng.normal(size=k))
               for i in range(N_USERS)])
        return journal

    j1, j2 = seed_model("v1", 0), seed_model("v2", 1)
    keys = [f"{u}-U" for u in range(N_USERS)]

    ctl = RolloutController("chaos-rollout",
                            port_dir=os.path.join(base, "ports"),
                            journal_dir=j1.dir, topic="models",
                            replication=R, ready_timeout_s=180)
    event("chaos_rollout_start", shards=W, replication=R)
    ok = [0] * THREADS
    errs = [0] * THREADS
    shed = [0]
    abuse_served = [0]
    stop = threading.Event()

    def in_quota_load(widx):
        c = ElasticClient(
            "chaos-rollout", retry=RetryPolicy(
                attempts=6, backoff_s=0.02, max_backoff_s=0.5),
            timeout_s=10)
        r = random.Random(widx)
        with c:
            while not stop.is_set():
                key = keys[r.randrange(len(keys))]
                try:
                    if c.query_state(ALS_STATE, key) is None:
                        errs[widx] += 1
                    else:
                        ok[widx] += 1
                except Exception:
                    errs[widx] += 1

    def abusive_load():
        # tenant rides the wire; sheds come back as "over quota" errors
        # the HA client does NOT failover on.  TOPK is low-priority (shed
        # at the reserve floor), GET holds on until the bucket is empty —
        # drive both so the priority order is exercised.
        c = ElasticClient(
            "chaos-rollout", retry=RetryPolicy(
                attempts=2, backoff_s=0.01, max_backoff_s=0.1),
            timeout_s=10, tenant="abuse")
        r = random.Random(1099)
        with c:
            while not stop.is_set():
                key = keys[r.randrange(len(keys))]
                try:
                    if r.random() < 0.5:
                        c.topk(ALS_STATE, key[:-2], TOPK_K)
                    else:
                        c.query_state(ALS_STATE, key)
                    abuse_served[0] += 1
                except Exception as e:
                    if SHED_MARKER in repr(e):
                        shed[0] += 1

    result = {}
    try:
        # initial deploy: v1 is generation 1
        ctl.rollout(j1.dir, "models", model_id="v1", shards=W)
        threads = [threading.Thread(target=in_quota_load, args=(i,),
                                    daemon=True)
                   for i in range(THREADS)]
        threads.append(threading.Thread(target=abusive_load, daemon=True))
        for t in threads:
            t.start()

        t0 = time.time()

        def do_rollout():
            try:
                result["record"] = ctl.rollout(
                    j2.dir, "models", model_id="v2",
                    verify_min_rows=N_USERS)
            except Exception as e:  # abort must leave v1 serving
                result["error"] = repr(e)

        st = threading.Thread(target=do_rollout)
        st.start()
        # kill one warming (v2) member mid-bulk-load — same window rules
        # as the elastic arm: only members whose port is already known
        victim = None
        while st.is_alive() and victim is None:
            warm = ctl.warming
            if warm is not None:
                launched = sorted(sr for sr in warm.procs
                                  if sr in warm.ports)
                if launched:
                    sr = launched[0]
                    proc = warm.procs.get(sr)
                    if proc is not None and proc.poll() is None:
                        event("chaos_kill_warming", shard=sr[0],
                              replica=sr[1], pid=proc.pid)
                        proc.send_signal(signal.SIGKILL)
                        victim = sr
            time.sleep(0.01)
        st.join()
        cutover_s = round(time.time() - t0, 2)
        time.sleep(1.0)  # keep the overload on the published generation
        stop.set()
        for t in threads:
            t.join(timeout=30)

        status = ctl.status()
        live_model = (status.get("model") or {}).get("model_id")
        completed = "record" in result and live_model == "v2"
        aborted_clean = "error" in result and live_model == "v1"
        summary = {
            "mode": "rollout", "shards": W, "replication": R,
            "victim": list(victim) if victim else None,
            "rollout_ok": completed,
            "rollout_error": result.get("error"),
            "aborted_clean": aborted_clean,
            "cutover_s": cutover_s,
            "live_model": live_model,
            "new_gen": result.get("record", {}).get("gen"),
            "in_quota_ok": sum(ok), "in_quota_errors": sum(errs),
            "abuse_served": abuse_served[0], "abuse_shed": shed[0],
            "controller_events": ctl.events,
            "timeline": [e for e in recent_events()
                         if e["kind"].startswith(("chaos_", "rollout_",
                                                  "replica_"))],
        }
        print(json.dumps(summary, indent=1, default=str))
        failed = (sum(errs) > 0                  # in-quota saw an error
                  or victim is None              # the chaos never happened
                  or not (completed or aborted_clean)
                  or shed[0] == 0)               # the abuser never shed
        return 1 if failed else 0
    finally:
        stop.set()
        event("chaos_teardown", mode="rollout")
        ctl.stop(drop_topology=True)


def autopilot_main() -> int:
    """SIGKILL the continuous-training autopilot twice — the trainer
    mid-RETRAIN and the controller mid-ROLLOUT — under a sustained
    in-quota query load (serve/autopilot.py).  Contracts under test: the
    serving plane never degrades (workers outlive the autopilot by
    construction — zero in-quota errors through both kills), the next
    lease holder STEALS the dead holder's ``<group>#autopilot`` lease and
    resumes from the persisted state record (the sealed-but-untrained
    window is redone, the candidate dir sequence never collides), and the
    flywheel still converges: an automatically trained candidate ends up
    rolled out with no human action."""
    from flink_ms_tpu.serve.elastic import ElasticClient
    from flink_ms_tpu.serve.rollout import RolloutController
    from flink_ms_tpu.serve.update_plane import UpdatePlaneClient

    base = tempfile.mkdtemp(prefix="tpums_chaos_autopilot_")
    os.environ.setdefault(
        "TPUMS_REGISTRY_DIR", tempfile.mkdtemp(prefix="tpums_chaos_reg_"))
    group = "chaos-autopilot"
    k = 4
    n = min(N_USERS, 80)  # the trainer refits every cycle — keep it CI-fast
    rng = np.random.default_rng(0)
    U, V = rng.normal(size=(n, k)), rng.normal(size=(n, k))
    uu, ii = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    uu, ii = uu.ravel(), ii.ravel()
    rr = np.sum(U[uu] * V[ii], axis=1)
    ratings = [(int(u), int(i), float(r)) for u, i, r in zip(uu, ii, rr)]
    # shuffled stream: BOTH halves cover every user, so the first
    # auto-rolled-out candidate can answer every in-quota key (a missing
    # user would read as a serving error when it is merely a cold id)
    random.Random(0).shuffle(ratings)
    half = len(ratings) // 2

    # v0 incumbent: RANDOM factors — any trained candidate beats it, so
    # the very first flywheel turn must end in an automatic rollout
    j0 = Journal(os.path.join(base, "v0"), "models")
    j0.append([F.format_als_row(u, "U", rng.normal(size=k))
               for u in range(n)]
              + [F.format_als_row(i, "I", rng.normal(size=k))
                 for i in range(n)])

    work_dir = os.path.join(base, "work")
    state_path = os.path.join(work_dir, "autopilot_state.json")
    keys = [f"{u}-U" for u in range(n)]
    ctl = RolloutController(group, port_dir=os.path.join(base, "ports"),
                            journal_dir=j0.dir, topic="models",
                            replication=R, ready_timeout_s=180)
    event("chaos_autopilot_start", shards=W, replication=R)
    ok = [0] * THREADS
    errs = [0] * THREADS
    err_sample = []
    stop = threading.Event()

    def in_quota_load(widx):
        c = ElasticClient(
            group, retry=RetryPolicy(
                attempts=6, backoff_s=0.02, max_backoff_s=0.5),
            timeout_s=10)
        r = random.Random(widx)
        with c:
            while not stop.is_set():
                key = keys[r.randrange(len(keys))]
                try:
                    if c.query_state(ALS_STATE, key) is None:
                        errs[widx] += 1
                        if len(err_sample) < 8:
                            err_sample.append((key, "missing"))
                    else:
                        ok[widx] += 1
                except Exception as e:
                    errs[widx] += 1
                    if len(err_sample) < 8:
                        err_sample.append((key, repr(e)))

    def read_phase():
        try:
            with open(state_path) as f:
                return json.load(f).get("phase")
        except (OSError, ValueError):
            return None

    def read_state():
        try:
            with open(state_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    import subprocess

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    log_path = os.path.join(base, "autopilot.log")

    def spawn_pilot():
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        log = open(log_path, "ab")
        return subprocess.Popen(
            [sys.executable, "-m", "flink_ms_tpu.serve.autopilot",
             "--group", group, "--ratingsDir", os.path.join(base, "bus"),
             "--workDir", work_dir,
             "--portDir", os.path.join(base, "ports"),
             "--interval", "0.2", "--minWindow", "50",
             "--iterations", "3", "--numFactors", str(k),
             "--duration", "120"],
            stdout=log, stderr=log, env=env)

    def kill_at_phase(proc, phase, timeout_s=60.0):
        """Poll the PERSISTED phase record (every transition reaches disk
        before the work starts) and SIGKILL the autopilot inside it."""
        deadline = time.time() + timeout_s
        while time.time() < deadline and proc.poll() is None:
            if read_phase() == phase:
                event("chaos_kill_controller",
                      target=f"autopilot@{phase}", pid=proc.pid)
                proc.send_signal(signal.SIGKILL)
                proc.wait()
                return True
            time.sleep(0.005)
        return False

    kills = {"training": False, "rolling-out": False}
    procs = []
    summary = {}
    try:
        ctl.rollout(j0.dir, "models", model_id="v0", shards=W)
        threads = [threading.Thread(target=in_quota_load, args=(i,),
                                    daemon=True) for i in range(THREADS)]
        for t in threads:
            t.start()

        producer = UpdatePlaneClient(os.path.join(base, "bus"), "models")
        producer.submit_many(ratings[:half], flush=True)

        # kill 1: the TRAINER, mid-retrain on the first sealed window
        p1 = spawn_pilot()
        procs.append(p1)
        kills["training"] = kill_at_phase(p1, "training")
        mark = sum(ok)
        deadline = time.time() + 10
        while sum(ok) < mark + 50 and time.time() < deadline:
            time.sleep(0.02)  # serving must keep answering over the corpse

        # kill 2: the CONTROLLER, mid-rollout — the next holder stole the
        # dead lease, redid the window's retrain, and is cutting over
        p2 = spawn_pilot()
        procs.append(p2)
        kills["rolling-out"] = kill_at_phase(p2, "rolling-out")
        mark = sum(ok)
        deadline = time.time() + 10
        while sum(ok) < mark + 50 and time.time() < deadline:
            time.sleep(0.02)

        # the rest of the stream, then an unharassed holder: it resumes
        # from the persisted record and the flywheel converges
        producer.submit_many(ratings[half:], flush=True)
        p3 = spawn_pilot()
        procs.append(p3)
        deadline = time.time() + 120
        converged = False
        while time.time() < deadline and p3.poll() is None:
            topo = registry.resolve_topology(group) or {}
            model_id = (topo.get("model") or {}).get("model_id", "")
            if model_id.startswith("auto-v") and \
                    int(read_state().get("trained_version", 0)) >= \
                    int(read_state().get("window_version", 1)):
                converged = True
                break
            time.sleep(0.1)
        p3.send_signal(signal.SIGTERM)
        try:
            p3.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p3.kill()

        stop.set()
        for t in threads:
            t.join(timeout=30)

        final_state = read_state()
        topo = registry.resolve_topology(group) or {}
        summary = {
            "mode": "autopilot", "shards": W, "replication": R,
            "killed_mid_retrain": kills["training"],
            "killed_mid_rollout": kills["rolling-out"],
            "converged": converged,
            "live_model": (topo.get("model") or {}).get("model_id"),
            "retrains": final_state.get("retrains"),
            "rollouts": final_state.get("rollouts"),
            "window_version": final_state.get("window_version"),
            "trained_version": final_state.get("trained_version"),
            "in_quota_ok": sum(ok), "in_quota_errors": sum(errs),
            "in_quota_error_sample": err_sample,
            "availability": (sum(ok) / max(sum(ok) + sum(errs), 1)),
            "timeline": [e for e in recent_events()
                         if e["kind"].startswith(("chaos_", "rollout_",
                                                  "autopilot_",
                                                  "replica_"))],
        }
        print(json.dumps(summary, indent=1, default=str))
        failed = (sum(errs) > 0                    # serving degraded
                  or not kills["training"]         # kill 1 never landed
                  or not kills["rolling-out"]      # kill 2 never landed
                  or not converged                 # flywheel never closed
                  or int(final_state.get("retrains") or 0) < 2)
        return 1 if failed else 0
    finally:
        stop.set()
        event("chaos_teardown", mode="autopilot")
        for p in procs:
            if p.poll() is None:
                p.kill()
        # dead autopilots orphaned their warming/served generations'
        # workers (no supervisor left to stop them): the registry is
        # PRIVATE to this run, so every locally-recorded live pid in it
        # is ours to reap
        my_host_entries = registry.list_jobs()
        for entry in my_host_entries:
            pid = entry.get("pid")
            if isinstance(pid, int) and pid != os.getpid():
                try:
                    os.kill(pid, signal.SIGKILL)
                except OSError:
                    pass
        ctl.stop(drop_topology=True)


def update_main() -> int:
    """SIGKILL co-located UpdateWorkers mid-stream under a sustained
    rating load.  The cluster runs with the sharded update plane enabled
    (--updatePlane) while a producer keeps routing ratings into the
    per-partition logs; kills land while batches are in flight.
    Contracts under test (serve/update_plane.py): flock leases hand the
    dead worker's partitions to its sibling replica (or its respawned
    self) at the committed watermarks, the sequence audit shows zero lost
    and zero double-applied ratings, and recovery goes through the
    standard supervisor replay-then-ready path."""
    from flink_ms_tpu.serve import update_plane as up

    rate_batch = int(os.environ.get("CHAOS_UPDATE_BATCH", 200))
    base = tempfile.mkdtemp(prefix="tpums_chaos_update_")
    journal, _keys = seed_journal(base)

    sup = ReplicaSupervisor(
        W, R, journal.dir, "models", os.path.join(base, "ports"),
        state_backend="memory",
        check_interval_s=registry.heartbeat_interval_s(),
        respawn_delay_s=0.1,
        extra_args=["--updatePlane", "true", "--pollInterval", "0.02"],
    )
    event("chaos_update_start", workers=W, replication=R,
          group=sup.job_group, duration_s=DURATION_S,
          kill_every_s=KILL_EVERY_S)
    cli = up.UpdatePlaneClient(journal.dir, "models")
    stop = threading.Event()
    kills = []        # (t_kill, shard, replica, old_pid)
    recoveries = []

    def produce():
        r = random.Random(9)
        while not stop.is_set():
            cli.submit_many(
                [(r.randrange(N_USERS), r.randrange(N_USERS),
                  round(r.uniform(0.5, 5.0), 3)) for _ in range(rate_batch)])
            time.sleep(0.05)

    def other_replicas_ready(shard, replica):
        members = registry.resolve_replicas(sup.group_of(shard))
        return any(e.get("replica") != replica and e.get("ready")
                   for e in members)

    def wait_recovered(shard, replica, old_pid, timeout_s=60.0):
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            members = registry.resolve_replicas(sup.group_of(shard))
            if any(e.get("replica") == replica and e.get("ready")
                   and e.get("pid") not in (None, old_pid)
                   for e in members):
                return True
            time.sleep(0.05)
        return False

    drained = False
    with sup.start():
        if not sup.wait_all_ready(120):
            event("chaos_abort", reason="cluster never became ready")
            return 2
        producer = threading.Thread(target=produce, daemon=True)
        producer.start()
        t_end = time.time() + DURATION_S
        next_kill = time.time() + (KILL_EVERY_S or float("inf"))
        r = random.Random(42)
        victim_cycle = 0
        while time.time() < t_end:
            time.sleep(0.05)
            if not (KILL_EVERY_S and time.time() >= next_kill):
                continue
            # alternate replicas across shards; only kill when a sibling
            # is ready to take the partitions over (R=1 exercises the
            # respawn-resumes-own-watermark path instead)
            shard = r.randrange(W)
            replica = victim_cycle % R
            victim_cycle += 1
            proc = sup.procs.get((shard, replica))
            if (proc is None or proc.poll() is not None
                    or (R >= 2 and not other_replicas_ready(shard, replica))):
                next_kill = time.time() + 0.25
                continue
            event("chaos_kill", shard=shard, replica=replica,
                  pid=proc.pid, group=sup.group_of(shard))
            proc.send_signal(signal.SIGKILL)
            t_kill = time.time()
            kills.append((t_kill, shard, replica, proc.pid))
            if wait_recovered(shard, replica, proc.pid):
                rec = round(time.time() - t_kill, 2)
                event("chaos_recovery", shard=shard, replica=replica,
                      recovery_s=rec)
                recoveries.append(rec)
            else:
                event("chaos_recovery", shard=shard, replica=replica,
                      recovery_s=None)
                recoveries.append(None)
            next_kill = time.time() + KILL_EVERY_S * (0.5 + r.random())
        stop.set()
        producer.join(timeout=30)
        cli.sync()
        submitted = sum(cli.totals().values())
        # drain: every submitted rating must reach a committed apply
        # record while the (respawned) fleet is still up
        deadline = time.time() + 120
        while time.time() < deadline:
            wm = up.applied_watermarks(journal.dir, "models")
            if sum(wm.values()) >= submitted:
                drained = True
                break
            time.sleep(0.1)
        event("chaos_teardown", mode="update")

    audit = up.audit_partitions(journal.dir, "models")
    recovered = [rec for rec in recoveries if rec is not None]
    summary = {
        "mode": "update", "workers": W, "replication": R,
        "duration_s": DURATION_S,
        "submitted": audit["submitted"], "applied": audit["applied"],
        "lost": audit["lost"], "duplicates": audit["duplicates"],
        "audit_clean": audit["clean"],
        "drained": drained,
        "kills": len(kills), "respawns": sup.respawns,
        "recovery_s": recoveries,
        "timeline": [e for e in recent_events()
                     if e["kind"].startswith(("chaos_", "replica_"))],
    }
    print(json.dumps(summary, indent=1))
    failed = (
        audit["lost"] > 0                      # a rating vanished
        or audit["duplicates"] > 0             # a rating applied twice
        or not drained                         # the plane wedged
        or not kills                           # the chaos never happened
        or len(recovered) < len(kills)         # a respawn never came back
    )
    return 1 if failed else 0


def region_main() -> int:
    """Partition the cross-region journal replicator mid-segment, then
    SIGKILL the ENTIRE home region — every worker process and the
    supervisor that would have respawned them — while the follower region
    keeps serving region-local reads (serve/georepl.py).  Contracts under
    test: the follower's ``RegionController`` detects home death (zero
    live home entries, lease expiry confirmed) and promotes in under 5s;
    region-local reads see ZERO errors through partition, kill and
    promotion; ``GeoWriteForwarder`` re-points writes to the new home
    without restart; replication lag p99 at rehearsal write rates stays
    under 250ms before the kill; and per-read staleness is visible over
    the wire (``st=``) the whole time."""
    from flink_ms_tpu.serve import georepl
    from flink_ms_tpu.serve.client import QueryClient

    base = tempfile.mkdtemp(prefix="tpums_chaos_region_")
    os.environ.setdefault(
        "TPUMS_REGISTRY_DIR", tempfile.mkdtemp(prefix="tpums_chaos_reg_"))
    us_bus = os.path.join(base, "us", "bus")
    eu_bus = os.path.join(base, "eu", "bus")
    journal = Journal(us_bus, "models")
    rng = np.random.default_rng(0)
    k = 4
    journal.append(
        [F.format_als_row(u, "U", rng.normal(size=k))
         for u in range(N_USERS)]
        + [F.format_als_row(i, "I", rng.normal(size=k))
           for i in range(N_USERS)])
    keys = [f"{u}-U" for u in range(N_USERS)]

    georepl.publish_region_topology(
        "chaos-geo", "us",
        {"us": {"journal_dir": us_bus}, "eu": {"journal_dir": eu_bus}},
        topic="models")
    # seed the follower journal BEFORE its fleet boots, so eu workers
    # bootstrap from a byte-identical replica of home
    rep = georepl.JournalReplicator(us_bus, eu_bus, "models", "eu",
                                    poll_s=0.01)
    rep.run_until_caught_up()

    sup_us = ReplicaSupervisor(
        W, R, us_bus, "models", os.path.join(base, "us", "ports"),
        job_group=registry.qualify_region("chaos-geo", "us"),
        state_backend="memory",
        check_interval_s=registry.heartbeat_interval_s(),
        respawn_delay_s=0.1)
    sup_eu = ReplicaSupervisor(
        W, R, eu_bus, "models", os.path.join(base, "eu", "ports"),
        job_group=registry.qualify_region("chaos-geo", "eu"),
        state_backend="memory",
        check_interval_s=registry.heartbeat_interval_s(),
        respawn_delay_s=0.1)
    event("chaos_region_start", workers=W, replication=R,
          home="us", follower="eu")
    ok = [0] * THREADS
    errs = [0] * THREADS
    staleness_s = []
    lag_samples_s = []
    stop = threading.Event()

    def load(widx):
        # region-local reads against the FOLLOWER fleet only — the home
        # region is about to die, eu must not notice
        c = sup_eu.client(retry=RetryPolicy(
            attempts=6, backoff_s=0.02, max_backoff_s=0.5), timeout_s=10)
        r = random.Random(widx)
        with c:
            while not stop.is_set():
                key = keys[r.randrange(len(keys))]
                try:
                    good = c.query_state(ALS_STATE, key) is not None
                except Exception:
                    good = False
                (ok if good else errs)[widx] += 1

    def stale_probe():
        # one st=-opted client straight at an eu replica: every reply
        # carries the follower's staleness, the wire-visibility contract
        with QueryClient(sup_eu.host, sup_eu.ports[(0, 0)],
                         timeout_s=10, stale=True) as qc:
            r = random.Random(17)
            while not stop.is_set():
                try:
                    qc.query_state(ALS_STATE, keys[r.randrange(len(keys))])
                    if qc.last_staleness_s is not None:
                        staleness_s.append(qc.last_staleness_s)
                except Exception:
                    pass
                time.sleep(0.02)

    def produce():
        # rehearsal write load into the HOME journal: what the replicator
        # must keep up with for the lag gate
        r = np.random.default_rng(7)
        i = 0
        while not stop.is_set():
            journal.append(
                [F.format_als_row((i + j) % N_USERS, "I", r.normal(size=k))
                 for j in range(200)], flush=False)
            i += 200
            time.sleep(0.02)

    promoted_rec = None
    promote_s = None
    repointed = False
    ctl = None
    try:
        sup_us.start()
        sup_eu.start()
        if not (sup_us.wait_all_ready(120) and sup_eu.wait_all_ready(120)):
            event("chaos_abort", reason="a region never became ready")
            return 2
        rep.start()
        ctl = georepl.RegionController("chaos-geo", "models", "eu",
                                       replicator=rep)
        ctl.start()
        fwd = georepl.GeoWriteForwarder("chaos-geo", "models")
        assert fwd.home() == "us"

        threads = [threading.Thread(target=load, args=(i,), daemon=True)
                   for i in range(THREADS)]
        threads.append(threading.Thread(target=stale_probe, daemon=True))
        threads.append(threading.Thread(target=produce, daemon=True))
        for t in threads:
            t.start()

        # phase 1 — rehearsal: sample replication lag under write load
        t_end = time.time() + float(
            os.environ.get("CHAOS_REGION_REHEARSAL_S", 3.0))
        while time.time() < t_end:
            lag_samples_s.append(rep.lag_seconds())
            time.sleep(0.005)

        # phase 2 — partition the replicator mid-segment, then SIGKILL
        # the whole home region: monitor thread FIRST (the supervisor
        # dies with its region — nothing left to respawn the fleet)
        rep.partitioned = True
        event("chaos_partition", mode="region", topic="models",
              region="eu", offset=rep.offset)
        time.sleep(0.3)
        sup_us._stop.set()
        if sup_us._thread is not None:
            sup_us._thread.join(timeout=10)
            sup_us._thread = None
        t_kill = time.time()
        for (shard, replica), proc in sorted(sup_us.procs.items()):
            if proc.poll() is None:
                event("chaos_kill", shard=shard, replica=replica,
                      pid=proc.pid, group=sup_us.group_of(shard))
                proc.send_signal(signal.SIGKILL)

        # phase 3 — the follower controller must promote on its own
        deadline = time.time() + 15
        while time.time() < deadline and ctl.promoted is None:
            time.sleep(0.01)
        promoted_rec = ctl.promoted
        if promoted_rec is not None:
            promote_s = round(time.time() - t_kill, 3)

        # phase 4 — write forwarding re-points to the new home and the
        # forwarded write lands in the eu region's journal dir
        repointed = False
        deadline = time.time() + 10
        while time.time() < deadline:
            if fwd.home() == "eu":
                fwd.submit_many([(1, 2, 3.0)], flush=True)
                repointed = any(
                    ".upd" in n for n in os.listdir(eu_bus))
                break
            time.sleep(0.05)
        time.sleep(1.0)  # region-local reads continue over the corpse
        stop.set()
        for t in threads:
            t.join(timeout=30)
    finally:
        stop.set()
        event("chaos_teardown", mode="region")
        if ctl is not None:
            ctl.stop()
        rep.stop()
        sup_eu.stop()
        sup_us.stop()

    lag_p = pcts([s * 1e3 for s in lag_samples_s])
    total_ok, total_err = sum(ok), sum(errs)
    total = total_ok + total_err
    summary = {
        "mode": "region", "workers": W, "replication": R,
        "home": "us", "follower": "eu",
        "promoted": promoted_rec is not None,
        "promote_s": promote_s,
        "new_gen": (promoted_rec or {}).get("gen"),
        "sealed_offset": ((promoted_rec or {}).get("geo") or {}).get(
            "failover", {}).get("sealed_offset"),
        "forwarder_repointed": repointed,
        "queries": total, "ok": total_ok, "errors": total_err,
        "availability": round(total_ok / total, 6) if total else None,
        "replication_lag_ms": lag_p,
        "lag_samples": len(lag_samples_s),
        "staleness_s": {
            "samples": len(staleness_s),
            "max": round(max(staleness_s), 3) if staleness_s else None,
            "nonzero": sum(1 for s in staleness_s if s > 0),
        },
        "timeline": [e for e in recent_events()
                     if e["kind"].startswith(("chaos_", "region_",
                                              "georepl_", "replica_"))],
    }
    print(json.dumps(summary, indent=1, default=str))
    failed = (
        total_err > 0                          # a region-local read failed
        or promoted_rec is None                # the follower never promoted
        or (promote_s or 99.0) >= 5.0          # promotion too slow
        or not repointed                       # writes still chase the corpse
        or not lag_samples_s
        or lag_p.get("p99", 1e9) >= 250.0      # replicator fell behind
        or not staleness_s                     # staleness never reached wire
    )
    return 1 if failed else 0


_ARENA_WRITER = r"""
import os, random, sys, time, zlib

sys.path.insert(0, sys.argv[4])
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from flink_ms_tpu.serve import snapshot as snap
from flink_ms_tpu.serve.arena import ArenaModelTable

d, snaps, n_users = sys.argv[1], sys.argv[2], int(sys.argv[3])


def val(key, n):
    body = f"{key}|{n}"
    return (body + "|%08x|" % (zlib.crc32(body.encode()) & 0xFFFFFFFF)
            + "p" * 48)


t = ArenaModelTable(4, dir=d, capacity=2048)
# seed/REPAIR pass: rewriting every tracked key is this harness's stand-in
# for the consumer's at-least-once journal replay — it flips any slot the
# previous incarnation left odd (SIGKILLed mid-row) back to valid
for u in range(n_users):
    t.put(f"{u}-U", val(f"{u}-U", 0))
print("READY", flush=True)
r = random.Random(os.getpid())
n = 0
last_pub = 0.0
while True:
    n += 1
    mode = n % 3
    if mode == 0:  # single-row Python seqlock write
        k = f"{r.randrange(n_users)}-U"
        t.put(k, val(k, n))
    elif mode == 1:  # native columnar batch (C++ writer when built)
        ks = [f"{r.randrange(n_users)}-U" for _ in range(32)]
        t.put_many_columns(ks, [val(k, n) for k in ks])
    else:  # CAS in place; drift falls back to LWW re-put like the
        # update plane's repair path
        ks = [f"{r.randrange(n_users)}-U" for _ in range(8)]
        exp = [t.get(k) for k in ks]
        vals = [val(k, n) for k in ks]
        failed = t.cas_many_columns(ks, exp, vals)
        if failed:
            t.put_many_columns([ks[i] for i in failed],
                               [vals[i] for i in failed])
    if time.time() - last_pub > 0.2:
        last_pub = time.time()
        snap.publish(snaps, t, int(time.time() * 1000),
                     shard=0, num_shards=1)
"""


def arena_main() -> int:
    """SIGKILL the single arena writer mid-row and mid-publish while
    lock-free readers hammer the same mmap.  The writer alternates
    single Python puts, native C++ columnar batches, and CAS-in-place
    updates so every write path faces the kill.  Contracts under test
    (serve/arena.py): a kill never yields a TORN row to any reader (the
    seqlock leaves the slot odd -> reads as missing, never garbage), the
    kernel releases the writer flock so the respawn attaches and its
    replay pass repairs every row, reader availability stays 1.0 (zero
    reader errors — the read plane never even notices), and the snapshot
    chain survives mid-publish kills (a torn newest member is detected
    structurally and bootstrap falls down to an older one)."""
    import subprocess
    import zlib

    from flink_ms_tpu.serve import snapshot as snap
    from flink_ms_tpu.serve.arena import Arena, current_path
    from flink_ms_tpu.serve.table import ModelTable

    base = tempfile.mkdtemp(prefix="tpums_chaos_arena_")
    arena_dir = os.path.join(base, "arena")
    snaps = os.path.join(base, "snaps")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def spawn():
        return subprocess.Popen(
            [sys.executable, "-c", _ARENA_WRITER, arena_dir, snaps,
             str(N_USERS), repo],
            stdout=subprocess.PIPE, text=True)

    def wait_ready(proc, timeout_s=60.0):
        line = proc.stdout.readline()
        return "READY" in line

    def valid(key, v):
        parts = v.split("|")
        if len(parts) != 4 or parts[0] != key:
            return False
        body = f"{parts[0]}|{parts[1]}"
        return parts[2] == "%08x" % (zlib.crc32(body.encode()) & 0xFFFFFFFF)

    stop = threading.Event()
    reads = [0] * THREADS
    invalid = [0] * THREADS
    errors = [0] * THREADS

    def reader(slot):
        # C++ reader when the toolchain is here; else the Python seqlock
        # reader — both exercise the same torn-row contract
        get = None
        closer = None
        try:
            from flink_ms_tpu.serve.native_store import NativeArena

            h = NativeArena(arena_dir)
            get, closer = h.get, h.close
        except Exception:
            a = Arena(current_path(arena_dir), writable=False)
            get, closer = a.get, a.close
        r = random.Random(slot)
        try:
            while not stop.is_set():
                key = f"{r.randrange(N_USERS)}-U"
                try:
                    v = get(key)
                except Exception:
                    errors[slot] += 1
                    continue
                reads[slot] += 1
                if v is not None and not valid(key, v):
                    invalid[slot] += 1
        finally:
            try:
                closer()
            except Exception:
                pass

    writer = spawn()
    if not wait_ready(writer):
        event("chaos_abort", reason="arena writer never became ready")
        return 2
    event("chaos_arena_start", users=N_USERS, duration_s=DURATION_S,
          kill_every_s=KILL_EVERY_S, threads=THREADS)
    threads = [threading.Thread(target=reader, args=(i,), daemon=True)
               for i in range(THREADS)]
    for th in threads:
        th.start()
    kills = 0
    respawn_ms = []
    respawn_failed = 0
    t_end = time.time() + DURATION_S
    next_kill = time.time() + KILL_EVERY_S
    try:
        while time.time() < t_end:
            time.sleep(0.05)
            if writer.poll() is not None:
                event("chaos_abort", reason="arena writer died unbidden")
                return 2
            if not (KILL_EVERY_S and time.time() >= next_kill):
                continue
            # NOT "chaos_kill": the arena writer is no fleet replica —
            # no registry entry, no heartbeat — so the alert plane has
            # nothing to detect and the watch wrapper's kill-detection
            # gate must not count these (KILL_KINDS in obs/watch.py)
            event("chaos_arena_kill", pid=writer.pid)
            writer.send_signal(signal.SIGKILL)
            writer.wait()
            kills += 1
            t_kill = time.time()
            writer = spawn()  # flock is kernel-released: attach at once
            if wait_ready(writer):
                respawn_ms.append(round((time.time() - t_kill) * 1e3, 1))
                event("chaos_arena_recovery",
                      recovery_s=respawn_ms[-1] / 1e3)
            else:
                respawn_failed += 1
            next_kill = time.time() + KILL_EVERY_S
        stop.set()
        for th in threads:
            th.join(timeout=30)
    finally:
        stop.set()
        if writer.poll() is None:
            writer.kill()
            writer.wait()
    # final sweep with a FRESH mapping: the last respawn's repair pass
    # must have every row valid — a SIGKILLed write may only ever look
    # missing-then-repaired, never torn
    torn_final = 0
    missing_final = 0
    a = Arena(current_path(arena_dir), writable=False)
    try:
        for u in range(N_USERS):
            key = f"{u}-U"
            v = a.get(key)
            if v is None:
                missing_final += 1
            elif not valid(key, v):
                torn_final += 1
    finally:
        a.close()
    # the snapshot chain must still bootstrap (mid-publish kills may
    # have torn the NEWEST member; the structural gate walks past it)
    corrupt_members = []
    boot = snap.bootstrap(ModelTable(4), snaps, owner=(0, 1),
                          on_corrupt=corrupt_members.append)
    total_reads = sum(reads)
    total_errors = sum(errors)
    avail = (1.0 if total_reads and not total_errors
             else round(1.0 - total_errors / max(total_reads +
                                                 total_errors, 1), 6))
    summary = {
        "mode": "arena", "users": N_USERS, "duration_s": DURATION_S,
        "reads": total_reads,
        "torn_reads": sum(invalid),
        "reader_errors": total_errors,
        "availability": avail,
        "kills": kills,
        "respawn_ms": respawn_ms,
        "respawn_failed": respawn_failed,
        "final_missing": missing_final,
        "final_torn": torn_final,
        "snapshot_bootstrap_rows": (boot or {}).get("rows"),
        "snapshot_members_skipped": len(corrupt_members),
        "timeline": [e for e in recent_events()
                     if e["kind"].startswith("chaos_")],
    }
    print(json.dumps(summary, indent=1))
    failed = (
        not kills                         # the chaos never happened
        or sum(invalid) > 0               # a reader saw a torn row
        or total_errors > 0               # availability < 1.0
        or respawn_failed > 0             # a respawn never came back
        or torn_final > 0                 # repair left garbage behind
        or missing_final > 0              # repair never completed
        or boot is None                   # the snapshot chain broke
    )
    return 1 if failed else 0


def edge_main() -> int:
    """SIGKILL one upstream replica AND one edge proxy under sustained
    mixed tab/B2 load through the proxy tier (serve/edge.py).  The
    replica dies realistically — SIGSTOPped first (a stalling process
    looks exactly like a tail-latency event, which is what hedging
    exists for), then SIGKILLed mid-stall.  Contracts under test: zero
    client-visible errors through both kills; the stalled replica is
    masked by hedged requests to its HA sibling (``tpums_edge_hedges
    _total{result=fired}`` moves at the proxies) and its death by the
    proxy's mark-down-and-retry path; the supervisor respawns it; and
    when a proxy itself dies, its clients rotate to the survivor
    (``EdgeClient`` reconnect) and traffic keeps flowing."""
    from flink_ms_tpu.serve.edge import (
        EdgeClient, spawn_edge_procs, stop_edge_procs,
    )
    from flink_ms_tpu.serve.elastic import ScaleController

    base = tempfile.mkdtemp(prefix="tpums_chaos_edge_")
    os.environ.setdefault(
        "TPUMS_REGISTRY_DIR", tempfile.mkdtemp(prefix="tpums_chaos_reg_"))
    journal, keys = seed_journal(base)
    replication = max(R, 2)  # the hedge needs a sibling to win on

    ctl = ScaleController("chaos-edge", journal.dir, "models",
                          port_dir=os.path.join(base, "ports"),
                          ready_timeout_s=180)
    event("chaos_edge_start", workers=W, replication=replication,
          proxies=2)
    ok = [0] * THREADS
    errs = [0] * THREADS
    err_sample = []
    stop = threading.Event()

    def load(widx):
        c = EdgeClient(
            "chaos-edge", prefer=widx,
            proto=("b2" if widx % 2 else "tab"),
            retry=RetryPolicy(attempts=8, backoff_s=0.02,
                              max_backoff_s=0.5),
            timeout_s=10)
        r = random.Random(widx)
        with c:
            while not stop.is_set():
                key = keys[r.randrange(len(keys))]
                try:
                    if r.random() * 100.0 < TOPK_PCT:
                        good = c.topk(ALS_STATE, key[:-2],
                                      TOPK_K) is not None
                    else:
                        good = c.query_state(ALS_STATE, key) is not None
                except Exception as e:
                    good = False
                    if len(err_sample) < 8:
                        err_sample.append((key, repr(e)))
                (ok if good else errs)[widx] += 1

    def edge_counters(ports):
        """Sum the hedge/reconnect counters across the live proxies."""
        fired = reconnects = 0
        for port in ports:
            try:
                with EdgeClient(endpoints=[("127.0.0.1", port)],
                                timeout_s=5) as mc:
                    snap = mc.metrics()
            except Exception:
                continue
            for c in snap.get("counters", []):
                if c.get("name") == "tpums_edge_hedges_total" and \
                        c.get("labels", {}).get("result") == "fired":
                    fired += c.get("value", 0)
                elif c.get("name") == "tpums_edge_upstream_reconnects_total":
                    reconnects += c.get("value", 0)
        return fired, reconnects

    def wait_recovered(sup, shard, replica, old_pid, timeout_s=60.0):
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            members = registry.resolve_replicas(sup.group_of(shard))
            if any(e.get("replica") == replica and e.get("ready")
                   and e.get("pid") not in (None, old_pid)
                   for e in members):
                return True
            time.sleep(0.05)
        return False

    procs = []
    try:
        ctl.scale_to(W, replicas=replication)
        procs, ports = spawn_edge_procs(
            "chaos-edge", 2, os.path.join(base, "edge_ports"),
            env={
                # fast hedge trigger so the stall window below is ample:
                # arm after 16 latency samples per shard, fire at p90
                # (floor 2ms) — a stopped replica trips this immediately
                "TPUMS_EDGE_HEDGE_WARMUP": "16",
                "TPUMS_EDGE_HEDGE_PCT": "90",
                "TPUMS_EDGE_HEDGE_MIN_MS": "2.0",
            })
        threads = [threading.Thread(target=load, args=(i,), daemon=True)
                   for i in range(THREADS)]
        for t in threads:
            t.start()
        time.sleep(2.0)  # warm the proxies' per-shard latency windows

        fired0, reconn0 = edge_counters(ports)

        # phase 1 — the upstream replica: SIGSTOP (the stall hedging
        # must mask), then SIGKILL mid-stall (the death the mark-down
        # path must absorb).  Only with its sibling ready, or errors
        # would be expected rather than contract-violating.
        sup = ctl.active_supervisor
        victim_sr = (0, 0)
        proc = sup.procs.get(victim_sr)
        stalled = killed_replica = False
        if proc is not None and proc.poll() is None and any(
                e.get("replica") != victim_sr[1] and e.get("ready")
                for e in registry.resolve_replicas(
                    sup.group_of(victim_sr[0]))):
            event("chaos_stall", shard=victim_sr[0],
                  replica=victim_sr[1], pid=proc.pid)
            proc.send_signal(signal.SIGSTOP)
            stalled = True
            time.sleep(1.0)  # hedges fire against the frozen replica
            event("chaos_kill", shard=victim_sr[0],
                  replica=victim_sr[1], pid=proc.pid,
                  group=sup.group_of(victim_sr[0]))
            proc.send_signal(signal.SIGKILL)
            killed_replica = True
        recovered = killed_replica and wait_recovered(
            sup, victim_sr[0], victim_sr[1],
            proc.pid if proc else None)
        fired1, reconn1 = edge_counters(ports)

        # phase 2 — the proxy: plain SIGKILL; its clients must rotate
        # to the survivor and keep being served
        ok_before = sum(ok)
        event("chaos_kill", proxy=0, pid=procs[0].pid,
              group=registry.edge_group("chaos-edge"))
        procs[0].send_signal(signal.SIGKILL)
        procs[0].wait(timeout=10)
        time.sleep(2.0)
        ok_after = sum(ok)
        stop.set()
        for t in threads:
            t.join(timeout=30)

        total_ok, total_err = sum(ok), sum(errs)
        summary = {
            "mode": "edge", "workers": W, "replication": replication,
            "proxies": 2,
            "queries": total_ok + total_err,
            "ok": total_ok, "errors": total_err,
            "error_sample": err_sample,
            "availability": round(
                total_ok / max(total_ok + total_err, 1), 6),
            "replica_stalled": stalled,
            "replica_killed": killed_replica,
            "replica_recovered": recovered,
            "hedges_fired": round(fired1 - fired0),
            "upstream_reconnects": round(reconn1 - reconn0),
            "proxy_killed": procs[0].poll() is not None,
            "ok_through_proxy_kill": ok_after - ok_before,
            "timeline": [e for e in recent_events()
                         if e["kind"].startswith(("chaos_", "edge_",
                                                  "replica_"))],
        }
        print(json.dumps(summary, indent=1, default=str))
        failed = (
            total_err > 0                   # a client saw the chaos
            or not killed_replica           # kill 1 never landed
            or not recovered                # the respawn never came back
            or fired1 - fired0 <= 0         # hedging never masked the stall
            or procs[0].poll() is None      # kill 2 never landed
            or ok_after - ok_before <= 0    # survivors absorbed nobody
        )
        return 1 if failed else 0
    finally:
        stop.set()
        event("chaos_teardown", mode="edge")
        stop_edge_procs(procs)
        ctl.stop(drop_topology=True)


def push_main() -> int:
    """SIGKILL a subscribed-to replica AND an edge proxy mid-update-storm
    while push subscribers hold live KEY/TOPK subscriptions through the
    proxy tier (serve/push.py + the edge push hub).  The storm rewrites
    the hot item factors through the journal — the same ingest path the
    SGD update plane uses — so every write fans out as KEY deltas and
    TOPK shortlist deltas.  Contracts under test: the client-observed
    sequence audit (``push.audit_push_sequences``) shows ZERO missed and
    ZERO duplicate notifications across both kills (the replica death is
    bridged by the hub's resync catch-up delta on the same sub ids; the
    proxy death by RESUME against the survivor — replay or a fresh-id
    snapshot, never a silent gap); every KEY subscriber's push-built
    value converges to the pulled truth after the storm quiesces; and
    concurrent pull traffic holds availability 1.0 throughout."""
    from flink_ms_tpu.serve.edge import (
        EdgeClient, spawn_edge_procs, stop_edge_procs,
    )
    from flink_ms_tpu.serve.elastic import ScaleController
    from flink_ms_tpu.serve.push import apply_delta, audit_push_sequences

    base = tempfile.mkdtemp(prefix="tpums_chaos_push_")
    os.environ.setdefault(
        "TPUMS_REGISTRY_DIR", tempfile.mkdtemp(prefix="tpums_chaos_reg_"))
    journal, keys = seed_journal(base)
    replication = max(R, 2)  # the resync needs a sibling to land on
    n_subs = int(os.environ.get("CHAOS_PUSH_SUBS", 6))
    hot = [f"{i}-I" for i in range(8)]  # the storm's targets

    ctl = ScaleController("chaos-push", journal.dir, "models",
                          port_dir=os.path.join(base, "ports"),
                          ready_timeout_s=180)
    event("chaos_push_start", workers=W, replication=replication,
          proxies=2, subscribers=n_subs)

    stop = threading.Event()        # storm + pull load
    drain_stop = threading.Event()  # subscribers (set AFTER the quiesce)
    ok = [0] * 2
    errs = [0] * 2
    err_sample = []
    audit_events = []  # ("S"|"P", sub_id, seq) in per-sub arrival order
    audit_lock = threading.Lock()
    sub_state = [{"key": None, "value": None, "shortlist": None,
                  "pushes": 0, "resumes": 0, "reconnects": 0, "up": False}
                 for _ in range(n_subs)]
    storm = {"writes": 0}
    eps = []  # filled once the proxies are up

    def storm_loop():
        rng = np.random.default_rng(1)
        while not stop.is_set():
            journal.append([F.format_als_row(i, "I", rng.normal(size=4))
                            for i in range(len(hot))])
            storm["writes"] += len(hot)
            time.sleep(0.05)

    def pull_load(widx):
        c = EdgeClient(endpoints=eps, prefer=widx,
                       proto=("b2" if widx % 2 else "tab"),
                       retry=RetryPolicy(attempts=8, backoff_s=0.02,
                                         max_backoff_s=0.5),
                       timeout_s=10)
        r = random.Random(widx)
        with c:
            while not stop.is_set():
                key = keys[r.randrange(len(keys))]
                try:
                    good = c.query_state(ALS_STATE, key) is not None
                except Exception as e:
                    good = False
                    if len(err_sample) < 8:
                        err_sample.append((key, repr(e)))
                (ok if good else errs)[widx] += 1

    def subscriber(idx):
        st = sub_state[idx]
        topk_sub = (idx == 0)  # one shortlist sub exercises the merged
        key = hot[idx % len(hot)]  # plane; the rest are KEY subs
        if not topk_sub:
            st["key"] = key
        sub = None
        c = None
        backoff = 0
        while not drain_stop.is_set():
            try:
                if c is None:
                    c = EdgeClient(endpoints=eps, prefer=idx + backoff,
                                   proto="b2", push=True, timeout_s=10)
                    if sub is None:
                        if topk_sub:
                            sub = c.subscribe_topk(
                                ALS_STATE, "1.0;2.0;0.5;-1.0", TOPK_K)
                            st["shortlist"] = {}
                            apply_delta(st["shortlist"], "".join(
                                f"+{e};" for e in
                                sub["snapshot"].split(";") if e))
                        else:
                            sub = c.subscribe_key(ALS_STATE, key)
                            st["value"] = sub["snapshot"]
                        with audit_lock:
                            audit_events.append(
                                ("S", sub["sub_id"], sub["seq"]))
                    else:
                        st["resumes"] += 1
                        r = c.resume_subscription(
                            ALS_STATE, "TOPK" if topk_sub else "KEY",
                            "1.0;2.0;0.5;-1.0" if topk_sub else key,
                            TOPK_K if topk_sub else 0,
                            sub["sub_id"], sub["seq"])
                        with audit_lock:
                            audit_events.append(
                                ("S", r["sub_id"], r["seq"]))
                        if r["mode"] == "replay":
                            sub["seq"] = r["seq"]  # deltas follow as pushes
                        else:  # fresh id: the snapshot IS the catch-up
                            sub = r
                            if topk_sub:
                                st["shortlist"] = {}
                                apply_delta(st["shortlist"], "".join(
                                    f"+{e};" for e in
                                    r["snapshot"].split(";") if e))
                            else:
                                st["value"] = r["snapshot"]
                    st["up"] = True
                    backoff = 0
                msg = c.next_push(timeout_s=0.25)
                if msg is None:
                    continue
                sub_id, seq, payload = msg
                with audit_lock:
                    audit_events.append(("P", sub_id, seq))
                sub["seq"] = seq
                st["pushes"] += 1
                if topk_sub:
                    apply_delta(st["shortlist"], payload)
                else:
                    st["value"] = payload
            except Exception:
                st["up"] = False
                try:
                    if c is not None:
                        c.close()
                except Exception:
                    pass
                c = None
                st["reconnects"] += 1
                backoff = min(backoff + 1, 8)
                time.sleep(0.05 * backoff)
        try:
            if c is not None:
                c.close()
        except Exception:
            pass

    def push_counters(ports):
        """Sum the hub's push counters across the live proxies."""
        notif, resumes = 0, {"replay": 0, "snapshot": 0}
        for port in ports:
            try:
                with EdgeClient(endpoints=[("127.0.0.1", port)],
                                timeout_s=5) as mc:
                    snap = mc.metrics()
            except Exception:
                continue
            for cc in snap.get("counters", []):
                if cc.get("name") == "tpums_push_notifications_total":
                    notif += cc.get("value", 0)
                elif cc.get("name") == "tpums_push_resume_total":
                    res = cc.get("labels", {}).get("result")
                    if res in resumes:
                        resumes[res] += cc.get("value", 0)
        return notif, resumes

    def wait_recovered(sup, shard, replica, old_pid, timeout_s=60.0):
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            members = registry.resolve_replicas(sup.group_of(shard))
            if any(e.get("replica") == replica and e.get("ready")
                   and e.get("pid") not in (None, old_pid)
                   for e in members):
                return True
            time.sleep(0.05)
        return False

    procs = []
    threads = []
    try:
        ctl.scale_to(W, replicas=replication)
        procs, ports = spawn_edge_procs(
            "chaos-push", 2, os.path.join(base, "edge_ports"))
        eps.extend(("127.0.0.1", p) for p in ports)
        threads = [threading.Thread(target=pull_load, args=(i,),
                                    daemon=True) for i in range(2)]
        sub_threads = [threading.Thread(target=subscriber, args=(i,),
                                        daemon=True)
                       for i in range(n_subs)]
        storm_t = threading.Thread(target=storm_loop, daemon=True)
        for t in threads + sub_threads:
            t.start()
        deadline = time.time() + 30
        while time.time() < deadline and not all(
                s["up"] for s in sub_state):
            time.sleep(0.05)
        storm_t.start()
        time.sleep(2.0)  # deltas flowing before anything dies

        # phase 1 — the subscribed-to replica: SIGKILL mid-storm.  The
        # hub's upstream pipes die, resync re-subscribes against the HA
        # sibling and emits ONE catch-up delta per downstream sub with
        # the next contiguous seq — the audit below proves no gap.
        sup = ctl.active_supervisor
        victim_sr = (0, 0)
        proc = sup.procs.get(victim_sr)
        killed_replica = False
        if proc is not None and proc.poll() is None and any(
                e.get("replica") != victim_sr[1] and e.get("ready")
                for e in registry.resolve_replicas(
                    sup.group_of(victim_sr[0]))):
            event("chaos_kill", shard=victim_sr[0],
                  replica=victim_sr[1], pid=proc.pid,
                  group=sup.group_of(victim_sr[0]))
            proc.send_signal(signal.SIGKILL)
            killed_replica = True
        recovered = killed_replica and wait_recovered(
            sup, victim_sr[0], victim_sr[1],
            proc.pid if proc else None)
        time.sleep(1.0)  # storm keeps running through the resync

        # phase 2 — the proxy: SIGKILL; its subscribers reconnect to the
        # survivor and RESUME — replay from the survivor's ring if the
        # spec is warm there, else a fresh-id snapshot.  Either way the
        # audit sees a clean baseline, never a hole.
        event("chaos_kill", proxy=0, pid=procs[0].pid,
              group=registry.edge_group("chaos-push"))
        procs[0].send_signal(signal.SIGKILL)
        procs[0].wait(timeout=10)
        time.sleep(2.0)

        stop.set()  # storm off; subscribers keep draining in-flight deltas
        for t in threads:
            t.join(timeout=30)
        storm_t.join(timeout=10)

        # convergence: each KEY subscriber's push-built value must reach
        # the pulled truth once the pipeline drains (bounded wait — the
        # last deltas are still in flight when the storm stops)
        verify = EdgeClient(endpoints=[eps[1]], proto="b2", timeout_s=10,
                            retry=RetryPolicy(attempts=8, backoff_s=0.05,
                                              max_backoff_s=0.5))
        converged = {}
        with verify:
            deadline = time.time() + 15
            pending = {i: s["key"] for i, s in enumerate(sub_state)
                       if s["key"] is not None}
            while pending and time.time() < deadline:
                for i, key in list(pending.items()):
                    truth = verify.query_state(ALS_STATE, key)
                    if truth == sub_state[i]["value"]:
                        converged[i] = True
                        del pending[i]
                if pending:
                    time.sleep(0.25)
            for i in pending:
                converged[i] = False
        drain_stop.set()
        for t in sub_threads:
            t.join(timeout=30)

        audit = audit_push_sequences(audit_events, tiles=8)
        notif, resume_counts = push_counters(ports[1:])
        total_ok, total_err = sum(ok), sum(errs)
        topk_deltas = sub_state[0]["pushes"]
        summary = {
            "mode": "push", "workers": W, "replication": replication,
            "proxies": 2, "subscribers": n_subs,
            "storm_writes": storm["writes"],
            "queries": total_ok + total_err,
            "ok": total_ok, "errors": total_err,
            "error_sample": err_sample,
            "availability": round(
                total_ok / max(total_ok + total_err, 1), 6),
            "replica_killed": killed_replica,
            "replica_recovered": recovered,
            "proxy_killed": procs[0].poll() is not None,
            "pushes_delivered": audit["delivered"],
            "missed": audit["missed"],
            "duplicates": audit["duplicates"],
            "audit_tiles": audit["tiles"],
            "topk_deltas": topk_deltas,
            "resumes": sum(s["resumes"] for s in sub_state),
            "reconnects": sum(s["reconnects"] for s in sub_state),
            "survivor_resumes": resume_counts,
            "survivor_notifications": round(notif),
            "key_converged": converged,
            "timeline": [e for e in recent_events()
                         if e["kind"].startswith(("chaos_", "edge_",
                                                  "push_", "replica_"))],
        }
        print(json.dumps(summary, indent=1, default=str))
        failed = (
            total_err > 0                     # pull plane saw the chaos
            or not killed_replica             # kill 1 never landed
            or not recovered                  # the respawn never came back
            or procs[0].poll() is None        # kill 2 never landed
            or audit["delivered"] <= 0        # no deltas at all: vacuous
            or audit["missed"] > 0            # a subscriber lost a delta
            or audit["duplicates"] > 0        # or saw one twice
            or topk_deltas <= 0               # shortlist plane never moved
            or not all(converged.values())    # push-built value != truth
        )
        return 1 if failed else 0
    finally:
        stop.set()
        drain_stop.set()
        event("chaos_teardown", mode="push")
        stop_edge_procs(procs)
        ctl.stop(drop_topology=True)


def run_with_watch(mode_fn) -> int:
    """The watch arm (CHAOS_WATCH=1, default): run the mode under a live
    ``obs.watch.FleetWatcher`` and tighten the exit gate with the alert
    plane's own contract —

    - zero UNATTRIBUTED page-severity alerts (every page must map to a
      kill/cutover/teardown event in the incident timeline), and
    - the kill -> first-page detection latency bounded by
      ``CHAOS_WATCH_DETECT_S`` (default 10 s) whenever the watcher saw a
      kill while at least one kill was detected at all.

    The watch summary is printed as one ``{"watch": ...}`` JSON line after
    the mode's own artifact, so drivers can consume both."""
    if os.environ.get("CHAOS_WATCH", "1") == "0":
        return mode_fn()
    from flink_ms_tpu.obs.watch import FleetWatcher

    # every mode spawns its own fleet; a private registry dir (operator
    # override respected) keeps the watcher's scrape — and its GC of
    # pid-dead entries — off any unrelated fleet on this host
    os.environ.setdefault(
        "TPUMS_REGISTRY_DIR", tempfile.mkdtemp(prefix="tpums_chaos_reg_"))
    detect_bound_s = float(os.environ.get("CHAOS_WATCH_DETECT_S", 10.0))
    watcher = FleetWatcher(
        interval_s=float(os.environ.get("CHAOS_WATCH_INTERVAL_S", 0.5)),
        scope="chaos",
        attribution_window_s=float(
            os.environ.get("CHAOS_WATCH_ATTR_S", 10.0)))
    watcher.start()
    try:
        rc = mode_fn()
    finally:
        watcher.stop()
    summary = watcher.watch_summary()
    det = summary["detection"]
    watch_failed = (
        summary["unattributed_page"] > 0       # an unexplained page
        or (det["kills"] > 0 and det["detected"] == 0)
        or (det["max_s"] is not None and det["max_s"] > detect_bound_s)
    )
    summary["detect_bound_s"] = detect_bound_s
    summary["watch_ok"] = not watch_failed
    print(json.dumps({"watch": summary}, indent=1, default=str))
    return rc or (1 if watch_failed else 0)


if __name__ == "__main__":
    sys.exit(run_with_watch({"elastic": elastic_main,
                             "snapshot": snapshot_main,
                             "update": update_main,
                             "rollout": rollout_main,
                             "autopilot": autopilot_main,
                             "region": region_main,
                             "arena": arena_main,
                             "edge": edge_main,
                             "push": push_main}.get(MODE, main)))
