#!/usr/bin/env python
"""Round-trip fuzz of the B2 wire protocol (serve/proto.py) against BOTH
decoders: the pure-Python one and the C++ server's (native/lookup_server.cpp).

Three properties, over seeded random verb batches whose fields carry hostile
unicode (``\\x85`` / ``\\u2028`` line separators, emoji, quotes, backslashes,
long runs — everything the line-framed v1 protocol could never carry safely):

1. **encode/decode round trip** — ``decode_request_frame(encode(lines))``
   reproduces the exact parts lists, batch boundaries included.
2. **cross-plane reply parity** — the same batch sent as one B2 frame to the
   C++ server and to the Python server yields identical reply records, and
   each record equals the tab-protocol reply for that line where the line is
   tab-transportable at all.
3. **decoder robustness** — random mutations (bit flips, truncations,
   splices) of valid frames either decode cleanly or raise ``ProtoError`` /
   produce a single ``E\\tbad frame`` reply and a closed connection on the
   wire; never a hang, crash, or torn reply.

    python scripts/proto_fuzz.py [--n 200] [--seed 0] [--no-native]
"""

import argparse
import os
import random
import socket
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flink_ms_tpu.serve import proto  # noqa: E402

# code points chosen to stress line-framing assumptions: ASCII controls the
# tab protocol reserves, unicode line separators, surrogate-adjacent BMP
# chars, astral plane, and plain text
_HOSTILE = ["\x85", "\u2028", "\u2029", "\x1f", "\x00", "\x7f",
            "\ufeff", "\U0001f600", "\xe9", "\"", "\\", "'", " ", "k",
            "0", ";", ":", ",", "."]


def _rand_field(rng, allow_tabs_newlines):
    bits = []
    for _ in range(rng.randrange(0, 24)):
        r = rng.random()
        if r < 0.5:
            bits.append(rng.choice(_HOSTILE))
        elif r < 0.9:
            bits.append(chr(rng.randrange(0x20, 0x7f)))
        else:
            bits.append(chr(rng.randrange(0xa0, 0x2100)))
    s = "".join(bits)
    if not allow_tabs_newlines:
        s = s.replace("\t", " ").replace("\n", " ").replace("\r", " ")
    return s


def _rand_line(rng, allow_tabs_newlines=True):
    verb = rng.choice(list(proto.OPCODES))
    fields = [_rand_field(rng, allow_tabs_newlines)
              for _ in range(proto.FIELD_COUNTS[verb])]
    return "\t".join([verb] + fields)


def fuzz_roundtrip(rng, iterations):
    """Property 1: pure encode/decode identity, including multi-frame
    streams decoded from one buffer."""
    for _ in range(iterations):
        batches = [[_rand_line(rng) for _ in range(rng.randrange(0, 9))]
                   for _ in range(rng.randrange(1, 4))]
        stream = b"".join(proto.encode_request_frame(b) for b in batches)
        pos = 0
        for batch in batches:
            res = proto.decode_request_frame(stream, pos)
            assert res is not None, "complete frame decoded as incomplete"
            records, pos = res
            want = [line.split("\t") for line in batch]
            assert records == want, (records, want)
        assert pos == len(stream)
        # reply framing round-trips the same payloads as opaque text
        texts = [line for batch in batches for line in batch]
        res = proto.decode_reply_frame(proto.encode_reply_frame(texts))
        assert res is not None and res[0] == texts
    print(f"[proto_fuzz] roundtrip: {iterations} batches OK")


def _recv_all(sock):
    out = b""
    while True:
        chunk = sock.recv(65536)
        if not chunk:
            return out
        out += chunk


def _binary_exchange(port, frames):
    with socket.create_connection(("127.0.0.1", port), timeout=10) as sock:
        sock.sendall(b"HELLO\tB2\n" + frames)
        sock.shutdown(socket.SHUT_WR)
        out = _recv_all(sock)
    assert out.startswith(b"HELLO\tB2\n"), out[:64]
    return out[len(b"HELLO\tB2\n"):]


def _decode_replies(buf):
    texts, pos = [], 0
    while pos < len(buf):
        res = proto.decode_reply_frame(buf, pos)
        assert res is not None, "torn reply frame"
        frame, pos = res
        texts.extend(frame)
    return texts


def _tab_replies(port, lines):
    payload = "".join(line + "\n" for line in lines).encode("utf-8")
    with socket.create_connection(("127.0.0.1", port), timeout=10) as sock:
        sock.sendall(payload)
        sock.shutdown(socket.SHUT_WR)
        out = _recv_all(sock)
    return out.decode("utf-8").split("\n")[:-1]


def _tab_transportable(line):
    # a line the v1 framing can carry without splitting: no newline-ish
    # bytes inside any field (the B2 plane has no such restriction)
    return not any(ch in line for ch in "\n\r")


def fuzz_live_parity(rng, iterations, ports):
    """Property 2: identical reply records across planes, and tab parity
    for the transportable subset."""
    checked = tab_checked = 0
    for _ in range(iterations):
        lines = [_rand_line(rng) for _ in range(rng.randrange(1, 17))]
        frame = proto.encode_request_frame(lines)
        replies = {name: _decode_replies(_binary_exchange(port, frame))
                   for name, port in ports.items()}
        for name, rep in replies.items():
            assert len(rep) == len(lines), (name, len(rep), len(lines))
        if len(replies) == 2:
            a, b = replies.values()
            # METRICS bodies differ across planes by construction
            for line, ra, rb in zip(lines, a, b):
                if line.split("\t")[0] not in ("METRICS", "HEALTH"):
                    assert ra == rb, (line, ra, rb)
            checked += len(lines)
        # tab parity where v1 can even carry the line
        name, port = next(iter(ports.items()))
        tab_lines = [l for l in lines
                     if _tab_transportable(l)
                     and l.split("\t")[0] not in ("METRICS", "HEALTH",
                                                  "HELLO")]
        if tab_lines:
            want = _tab_replies(port, tab_lines)
            got = _decode_replies(_binary_exchange(
                port, proto.encode_request_frame(tab_lines)))
            assert got == want, (tab_lines, got, want)
            tab_checked += len(tab_lines)
    print(f"[proto_fuzz] live parity: {checked} cross-plane + "
          f"{tab_checked} tab-parity records OK")


def fuzz_mutations(rng, iterations, ports):
    """Property 3: mutated frames never crash or hang a decoder."""
    wire_checked = 0
    for i in range(iterations):
        lines = [_rand_line(rng) for _ in range(rng.randrange(1, 6))]
        frame = bytearray(proto.encode_request_frame(lines))
        mode = rng.randrange(3)
        if mode == 0 and frame:  # bit flip
            pos = rng.randrange(len(frame))
            frame[pos] ^= 1 << rng.randrange(8)
        elif mode == 1:  # truncate
            frame = frame[:rng.randrange(len(frame))]
        else:  # splice random junk
            pos = rng.randrange(len(frame) + 1)
            junk = bytes(rng.randrange(256)
                         for _ in range(rng.randrange(1, 8)))
            frame = frame[:pos] + junk + frame[pos:]
        blob = bytes(frame)
        # the pure decoder: clean decode, incomplete, or ProtoError only
        try:
            proto.decode_request_frame(blob)
        except proto.ProtoError:
            pass
        # every 8th mutant also goes over the wire: the server must answer
        # with frames and/or one error frame, then close — never hang
        if i % 8 == 0:
            for port in ports.values():
                out = _binary_exchange(port, blob)
                while out:
                    res = proto.decode_reply_frame(out)
                    if res is None:
                        break  # torn tail after an error frame: closed mid-write is fine
                    texts, consumed = res
                    out = out[consumed:]
                    del texts
                wire_checked += 1
    print(f"[proto_fuzz] mutations: {iterations} mutants, "
          f"{wire_checked} wire exchanges OK")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=200,
                    help="iterations per property")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--no-native", action="store_true",
                    help="skip the C++ server (pure-Python parity only)")
    args = ap.parse_args()
    rng = random.Random(args.seed)

    fuzz_roundtrip(rng, args.n)

    import tempfile

    from flink_ms_tpu.serve.consumer import ALS_STATE
    from flink_ms_tpu.serve.server import LookupServer
    from flink_ms_tpu.serve.table import ModelTable
    from flink_ms_tpu.serve.topk import make_als_topk_handler

    rows = [("10-I", "1.0;0.5;-2.0;0.25"), ("11-I", "0.5;0.5;0.5;0.5"),
            ("7-U", "1.0;2.0;0.5;-1.0")]
    table = ModelTable(2)
    for k, v in rows:
        table.put(k, v)
    pysrv = LookupServer(
        {ALS_STATE: table}, host="127.0.0.1", port=0, job_id="fuzz",
        topk_handlers={ALS_STATE: make_als_topk_handler(table)},
    ).start()
    ports = {"python": pysrv.port}
    nsrv = store = None
    if not args.no_native:
        try:
            from flink_ms_tpu.serve.native_store import (NativeLookupServer,
                                                         NativeStore)

            tmp = tempfile.mkdtemp(prefix="proto_fuzz_")
            store = NativeStore(os.path.join(tmp, "store"))
            for k, v in rows:
                store.put(k, v)
            nsrv = NativeLookupServer(store, ALS_STATE, job_id="fuzz",
                                      port=0, topk_suffixes=("-I", "-U"))
            ports["native"] = nsrv.port
        except Exception as e:
            print(f"[proto_fuzz] native plane unavailable ({e}); "
                  "python-only", file=sys.stderr)
    try:
        fuzz_live_parity(rng, args.n, ports)
        fuzz_mutations(rng, args.n, ports)
    finally:
        pysrv.stop()
        if nsrv is not None:
            nsrv.stop()
        if store is not None:
            store.close()
    print(f"[proto_fuzz] PASS (n={args.n}, seed={args.seed}, "
          f"planes={sorted(ports)})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
