#!/usr/bin/env python
"""End-to-end smoke for the elastic serving plane (serve/elastic.py):
bring up 2 shards, scale OUT to 4 under a sustained query loop, scale
back IN to 2, and assert the two contracts the subsystem exists for —

- zero failed queries: no client thread sees an error across either
  cutover (queries ride the generation swap transparently);
- key-coverage parity: every seeded key resolves to the same payload
  before the first cutover, after the scale-out, and after the scale-in
  (``hash%N`` changed twice; the data must not care).

    python scripts/elastic_smoke.py [env knobs below]

Knobs (env):
    SMOKE_USERS=150        model rows per side
    SMOKE_THREADS=3        closed-loop client threads
    SMOKE_SETTLE_S=2       query-loop time at each topology before moving on
    TPUMS_HEARTBEAT_S / TPUMS_REPLICA_TTL_S: liveness cadence (defaults
                           here: 0.25 / 1.5 — fast cutovers for a demo)

Exit code 0 on success, 1 on any error or coverage mismatch.
"""

import json
import os
import random
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TPUMS_HEARTBEAT_S", "0.25")
os.environ.setdefault("TPUMS_REPLICA_TTL_S", "1.5")
os.environ.setdefault("TPUMS_REGISTRY_DIR",
                      tempfile.mkdtemp(prefix="tpums_smoke_reg_"))

from flink_ms_tpu.core import formats as F  # noqa: E402
from flink_ms_tpu.serve.client import RetryPolicy  # noqa: E402
from flink_ms_tpu.serve.consumer import ALS_STATE  # noqa: E402
from flink_ms_tpu.serve.elastic import ElasticClient, ScaleController  # noqa: E402
from flink_ms_tpu.serve.journal import Journal  # noqa: E402

N_USERS = int(os.environ.get("SMOKE_USERS", 150))
THREADS = int(os.environ.get("SMOKE_THREADS", 3))
SETTLE_S = float(os.environ.get("SMOKE_SETTLE_S", 2))


def coverage(client: ElasticClient, keys) -> dict:
    """key -> payload for every seeded key, via the topology-following
    client (one MGET fan-out)."""
    vals = client.query_states(ALS_STATE, keys)
    return dict(zip(keys, vals))


def main() -> int:
    base = tempfile.mkdtemp(prefix="tpums_smoke_")
    journal = Journal(os.path.join(base, "bus"), "models")
    rng = np.random.default_rng(7)
    k = 4
    journal.append(
        [F.format_als_row(u, "U", rng.normal(size=k))
         for u in range(N_USERS)]
        + [F.format_als_row(i, "I", rng.normal(size=k))
           for i in range(N_USERS)]
    )
    keys = [f"{u}-U" for u in range(N_USERS)] \
        + [f"{i}-I" for i in range(N_USERS)]

    checks = []

    def check(name, ok, detail=""):
        checks.append((name, bool(ok)))
        print(f"  [{'ok' if ok else 'FAIL'}] {name}"
              + (f" — {detail}" if detail and not ok else ""))

    ok_counts = [0] * THREADS
    errors = []
    stop = threading.Event()

    def load(widx):
        c = ElasticClient(
            "smoke", retry=RetryPolicy(attempts=6, backoff_s=0.02,
                                       max_backoff_s=0.5),
            timeout_s=10)
        r = random.Random(widx)
        with c:
            while not stop.is_set():
                key = keys[r.randrange(len(keys))]
                try:
                    if c.query_state(ALS_STATE, key) is None:
                        errors.append((widx, key, "miss"))
                    else:
                        ok_counts[widx] += 1
                except Exception as e:
                    errors.append((widx, key, repr(e)))

    ctl = ScaleController("smoke", journal.dir, "models",
                          port_dir=os.path.join(base, "ports"),
                          ready_timeout_s=120)
    try:
        t0 = time.time()
        rec = ctl.scale_to(2)
        check("bootstrap gen1 2 shards", rec["gen"] == 1
              and rec["shards"] == 2)
        probe = ElasticClient("smoke", timeout_s=10)
        cov1 = coverage(probe, keys)
        check("coverage@2 complete",
              all(v is not None for v in cov1.values()),
              f"{sum(v is None for v in cov1.values())} missing")

        threads = [threading.Thread(target=load, args=(i,), daemon=True)
                   for i in range(THREADS)]
        for t in threads:
            t.start()
        time.sleep(SETTLE_S)

        t_out = time.time()
        rec = ctl.scale_to(4)
        out_s = time.time() - t_out
        check("scale-out to gen2 4 shards", rec["gen"] == 2
              and rec["shards"] == 4)
        time.sleep(SETTLE_S)
        cov2 = coverage(probe, keys)
        check("coverage parity after scale-out", cov2 == cov1,
              f"{sum(1 for k_ in keys if cov2[k_] != cov1[k_])} diffs")

        t_in = time.time()
        rec = ctl.scale_to(2)
        in_s = time.time() - t_in
        check("scale-in to gen3 2 shards", rec["gen"] == 3
              and rec["shards"] == 2)
        time.sleep(SETTLE_S)
        cov3 = coverage(probe, keys)
        check("coverage parity after scale-in", cov3 == cov1,
              f"{sum(1 for k_ in keys if cov3[k_] != cov1[k_])} diffs")

        stop.set()
        for t in threads:
            t.join(timeout=30)
        probe.close()
        total_ok = sum(ok_counts)
        check("zero failed queries", not errors,
              f"{len(errors)} errors, first: {errors[:3]}")
        check("query loop exercised both cutovers", total_ok > 0)
        summary = {
            "queries_ok": total_ok,
            "errors": len(errors),
            "scale_out_s": round(out_s, 2),
            "scale_in_s": round(in_s, 2),
            "total_s": round(time.time() - t0, 2),
            "generation_swaps": "per-thread (see events)",
            "controller_events": ctl.events,
        }
        print(json.dumps(summary, indent=1, default=str))
    finally:
        stop.set()
        ctl.stop(drop_topology=True)

    failed = [n for n, ok_ in checks if not ok_]
    print(("SMOKE PASS" if not failed else f"SMOKE FAIL: {failed}"))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
