#!/usr/bin/env python
"""Arena-vs-dict microbench: the shared-memory arena's three promises,
measured head-to-head against the dict ModelTable (ISSUE 16).

Arms per table kind:

    ingest    journal-shaped rows through ``put_many_columns`` -> rows/s
    get       point reads -> ns/row (dict: Python dict hit; arena:
              seqlock probe) and, for the arena, the same reads again
              through the C++ reader (the zero-copy serving path)
    publish   one snapshot publish at the loaded row count -> ms
              (dict: columnar serialize + crc; arena: quiesce reflink /
              extent copy) plus the speedup ratio

Parity is asserted, not assumed: after ingest, the arena's full row set
must equal the dict table's, byte for byte.

Run host-side (no accelerator needed):

    python scripts/arena_profile.py [--rows 1000000] [--k 16] [--gets 200000]
"""

import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from flink_ms_tpu.core import formats as F  # noqa: E402
from flink_ms_tpu.core.params import Params  # noqa: E402
from flink_ms_tpu.serve import snapshot as snapshot_mod  # noqa: E402
from flink_ms_tpu.serve.arena import ArenaModelTable  # noqa: E402
from flink_ms_tpu.serve.table import ModelTable  # noqa: E402


def build_rows(rows: int, k: int):
    keys = []
    vals = []
    for i in range(rows):
        typ = "I" if i % 3 else "U"
        vec = [((i * 31 + j * 17) % 1000) / 500.0 - 1.0 for j in range(k)]
        line = F.format_als_row(i, typ, vec)
        id_, t, payload = line.split(",", 2)
        keys.append(f"{id_}-{t}")
        vals.append(payload)
    return keys, vals


def bench_ingest(table, keys, vals, batch: int = 65536) -> float:
    t0 = time.perf_counter()
    for i in range(0, len(keys), batch):
        table.put_many_columns(keys[i:i + batch], vals[i:i + batch])
    return time.perf_counter() - t0


def bench_gets(get, keys, n: int) -> float:
    step = max(len(keys) // n, 1)
    probe = (keys[::step] * (n // max(len(keys[::step]), 1) + 1))[:n]
    t0 = time.perf_counter()
    for k in probe:
        get(k)
    return (time.perf_counter() - t0) / len(probe)


def bench_publish(root: str, table, offset: int) -> float:
    shutil.rmtree(root, ignore_errors=True)
    t0 = time.perf_counter()
    snapshot_mod.publish(root, table, offset, shard=0, num_shards=1)
    return time.perf_counter() - t0


def main(argv=None) -> None:
    params = Params.from_args(sys.argv[1:] if argv is None else argv)
    rows = params.get_int("rows", 1_000_000)
    k = params.get_int("k", 16)
    gets = params.get_int("gets", 200_000)

    print(f"# arena_profile rows={rows} k={k} gets={gets}", flush=True)
    keys, vals = build_rows(rows, k)
    tmp = tempfile.mkdtemp(prefix="tpums-arena-prof-")
    try:
        results = {}
        dict_t = ModelTable(8)
        results["dict"] = {
            "ingest_s": bench_ingest(dict_t, keys, vals),
            "get_ns": bench_gets(dict_t.get, keys, gets) * 1e9,
            "publish_s": bench_publish(
                os.path.join(tmp, "snap-dict"), dict_t, rows),
        }

        arena_t = ArenaModelTable(8, dir=os.path.join(tmp, "arena"))
        try:
            results["arena"] = {
                "ingest_s": bench_ingest(arena_t, keys, vals),
                "get_ns": bench_gets(arena_t.get, keys, gets) * 1e9,
                "publish_s": bench_publish(
                    os.path.join(tmp, "snap-arena"), arena_t, rows),
            }
            # O(1) hardlink publish (TPUMS_ARENA_PUBLISH=link semantics)
            arena_t.publish_mode = "link"
            results["arena"]["publish_link_s"] = bench_publish(
                os.path.join(tmp, "snap-arena-link"), arena_t, rows)
            arena_t.publish_mode = "copy"
            try:
                from flink_ms_tpu.serve.native_store import NativeArena

                a = NativeArena(os.path.join(tmp, "arena"))
                try:
                    results["arena"]["native_get_ns"] = bench_gets(
                        a.get, keys, gets) * 1e9
                finally:
                    a.close()
            except Exception as e:  # toolchain-less host: Python arms only
                print(f"# native reader unavailable: {e}", flush=True)

            # byte-level parity: the arena IS the dict table, relocated
            mismatch = sum(
                1 for key, val in zip(keys, vals)
                if arena_t.get(key) != dict_t.get(key))
            assert mismatch == 0, f"{mismatch} rows differ arena vs dict"
            n_rows = len(arena_t)
            assert n_rows == len(dict_t), (n_rows, len(dict_t))
        finally:
            arena_t.close()

        for kind in ("dict", "arena"):
            r = results[kind]
            print(f"{kind:6s} ingest {rows / r['ingest_s']:>12,.0f} rows/s   "
                  f"get {r['get_ns']:>8,.0f} ns/row   "
                  f"publish {r['publish_s'] * 1e3:>10,.2f} ms"
                  + (f"   native-get {r['native_get_ns']:,.0f} ns/row"
                     if "native_get_ns" in r else ""))
        d = results["dict"]["publish_s"]
        a = results["arena"]
        print(f"publish speedup vs dict serialize: "
              f"copy {d / max(a['publish_s'], 1e-12):.1f}x, "
              f"link {d / max(a['publish_link_s'], 1e-12):.1f}x (O(1))  "
              f"[parity OK, {n_rows} rows]")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
