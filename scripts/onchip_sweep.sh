#!/usr/bin/env bash
# One-shot on-chip measurement sweep: run when the TPU tunnel is healthy.
# Captures every decision artifact round 2 needs from the real chip into
# $OUT (default /tmp/onchip_sweep):
#   1. ALS solver x precision matrix (moderate scale)  -> als_matrix.log
#   2. ALS phase breakdown (gather/assembly/solve)     -> als_breakdown.log
#   3. XLA vs Pallas top-k profile (26k + 1M items)    -> topk_profile.log
#   4. Full headline bench, uniform workload           -> bench_uniform.json/.log
#   5. Full headline bench, zipf workload              -> bench_zipf.json/.log
# Each step is independent; a failure logs and continues.
set -u
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/onchip_sweep}"
mkdir -p "$OUT"

run() {
  local name="$1"; shift
  echo "=== $name: $*" | tee -a "$OUT/sweep.log"
  timeout "${STEP_TIMEOUT:-1200}" "$@" > "$OUT/$name.log" 2>&1
  echo "    rc=$? ($(tail -c 200 "$OUT/$name.log" | tr '\n' ' ' | tail -c 120))" \
    | tee -a "$OUT/sweep.log"
}

run als_matrix python scripts/als_microbench.py \
  --nnz 5000000 --users 60000 --items 12000 --rank 50 \
  --solvers unrolled,panel,lax,pallas --precisions highest,high,default

run als_breakdown python scripts/als_microbench.py \
  --nnz 5000000 --users 60000 --items 12000 --rank 50 \
  --breakdown --solvers auto --precisions default

run als_bf16_exchange python scripts/als_microbench.py \
  --nnz 5000000 --users 60000 --items 12000 --rank 50 \
  --solvers auto --precisions highest,default --exchange bf16

# fused assembly+solve (FLINK_MS_ALS_FUSED=1): the (n,k,k) tensor never
# hits HBM — the memory-ceiling mode (measured 2026-07-31: pallas 71.8
# vs 62.7 ms/iter unfused; ~14% cost for the unbounded catalog).
FLINK_MS_ALS_FUSED=1 run als_fused python scripts/als_microbench.py \
  --nnz 5000000 --users 60000 --items 12000 --rank 50 \
  --solvers unrolled,panel,lax,pallas --precisions highest,default

# bf16 exchange under the pallas default (2026-07-31: 50.2 vs 62.7
# ms/iter; quality delta auto-captured by bench.py's als section)
run als_bf16_pallas python scripts/als_microbench.py \
  --nnz 5000000 --users 60000 --items 12000 --rank 50 \
  --solvers pallas --precisions highest --exchange bf16

run topk_profile python scripts/topk_profile.py --items 26000 1000000 --rank 50

# CoCoA chain-count sweep on chip (VERDICT r2 #4): the 8192-chain default
# rests on a CPU serial-depth argument that may invert on hardware.  One
# full SVM section per K; sec/round + rounds-to-target land in each log.
# (Gram engine auto-selects per K; CPU shows near-flat sec/round in K.)
for K in 1024 4096 8192 16384; do
  BENCH_SECTIONS=svm BENCH_SVM_BLOCKS=$K BENCH_SKIP_CPU=1 \
    BENCH_DETAIL_PATH="$OUT/svm_k$K.detail.json" \
    timeout "${STEP_TIMEOUT:-1200}" python bench.py \
    > "$OUT/svm_k$K.json" 2> "$OUT/svm_k$K.log"
  echo "svm_k$K rc=$?" | tee -a "$OUT/sweep.log"
done

# Gram-engine A/Bs at the default K: scatter engine baseline, and the
# sorted segment-sum round-end reduction (an unsorted 49M-entry
# scatter-add may serialize on TPU where a sorted reduction streams)
for VAR in "FLINK_MS_SVM_GRAM_BYTES=1 svm_scatter_engine" \
           "FLINK_MS_SVM_DW=sorted svm_gram_sorted_dw"; do
  set -- $VAR
  env "$1" BENCH_SECTIONS=svm BENCH_SKIP_CPU=1 \
    BENCH_DETAIL_PATH="$OUT/$2.detail.json" \
    timeout "${STEP_TIMEOUT:-1200}" python bench.py \
    > "$OUT/$2.json" 2> "$OUT/$2.log"
  echo "$2 rc=$?" | tee -a "$OUT/sweep.log"
done

BENCH_SECTIONS=als,svm,serving,svmserve \
  BENCH_DETAIL_PATH="$OUT/bench_uniform.detail.json" \
  timeout "${STEP_TIMEOUT:-2400}" python bench.py \
  > "$OUT/bench_uniform.json" 2> "$OUT/bench_uniform.log"
echo "bench_uniform rc=$?" | tee -a "$OUT/sweep.log"

BENCH_SKEW=zipf BENCH_SECTIONS=als \
  BENCH_DETAIL_PATH="$OUT/bench_zipf.detail.json" \
  timeout "${STEP_TIMEOUT:-2400}" python bench.py \
  > "$OUT/bench_zipf.json" 2> "$OUT/bench_zipf.log"
echo "bench_zipf rc=$?" | tee -a "$OUT/sweep.log"

echo "sweep complete; artifacts in $OUT" | tee -a "$OUT/sweep.log"
