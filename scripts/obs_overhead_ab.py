#!/usr/bin/env python
"""Measure the metrics-collection overhead on the serving top-k path:
p50/p95 of TOPK round trips with TPUMS_METRICS on vs off, same process,
same warm index, interleaved arms (ABAB) so drift hits both equally.

    python scripts/obs_overhead_ab.py  [N_USERS=2000 N_Q=400 ROUNDS=4]

The acceptance bar (README "Observability"): p50 overhead <= 3%.
Percentiles route through the shared bucket ladder
(``obs.metrics.bucketed_quantiles``), which works in BOTH arms — the
off-arm only disables collection, not offline math.

Round 14 adds the tail-forensics arms: spans (head-sampled at
``TRACE_SAMPLE``, default 1%) plus exemplar-linked histograms on vs both
off, same ABAB discipline, on the GET hot path.  That arm's dispatch-level
p50 overhead is ENFORCED <= 3% (exit 1 past the bar) — the in-process
measurement is reproducible where the socket ratio rides machine noise.

Round 19 adds the continuous-profiler arm: the sampling profiler running
at ``PROF_HZ`` (default 47) vs stopped, same ABAB discipline on the GET
hot path, same ENFORCED <= 3% bar.
"""

import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_USERS = int(os.environ.get("N_USERS", 2000))
N_ITEMS = int(os.environ.get("N_ITEMS", 2000))
K = 16
TOPK = 10
N_Q = int(os.environ.get("N_Q", 400))
ROUNDS = int(os.environ.get("ROUNDS", 4))
TRACE_SAMPLE = float(os.environ.get("TRACE_SAMPLE", 0.01))
TRACE_BAR_PCT = float(os.environ.get("TRACE_BAR_PCT", 3.0))
PROF_HZ = float(os.environ.get("PROF_HZ", 47.0))
PROF_BAR_PCT = float(os.environ.get("PROF_BAR_PCT", 3.0))


def main() -> int:
    from flink_ms_tpu.core import formats as F
    from flink_ms_tpu.obs.metrics import bucketed_quantiles, set_enabled
    from flink_ms_tpu.serve.client import QueryClient
    from flink_ms_tpu.serve.consumer import (
        ALS_STATE,
        ServingJob,
        make_backend,
        parse_als_record,
    )
    from flink_ms_tpu.serve.journal import Journal

    tmp = tempfile.mkdtemp(prefix="tpums_obs_ab_")
    os.environ["TPUMS_REGISTRY_DIR"] = os.path.join(tmp, "registry")
    journal = Journal(os.path.join(tmp, "bus"), "models")
    rng = np.random.default_rng(0)
    journal.append(
        [F.format_als_row(u, "U", rng.normal(size=K)) for u in range(N_USERS)]
        + [F.format_als_row(i, "I", rng.normal(size=K))
           for i in range(N_ITEMS)]
    )
    job = ServingJob(
        journal, ALS_STATE, parse_als_record, make_backend("memory", None),
        host="127.0.0.1", port=0, poll_interval_s=0.01,
    ).start()
    try:
        assert job.wait_ready(120)
        lat = {"on": [], "off": []}
        with QueryClient("127.0.0.1", job.port, timeout_s=600) as c:
            c.topk(ALS_STATE, "1", TOPK)  # index build + jit, uncounted
            for _ in range(50):           # warm the steady-state path
                c.topk(ALS_STATE, "2", TOPK)
            qrng = np.random.default_rng(1)
            for r in range(ROUNDS):
                # alternate arm order per round so drift (thermal, page
                # cache, scheduler) debits both arms equally
                order = ("on", "off") if r % 2 == 0 else ("off", "on")
                for arm in order:
                    set_enabled(arm == "on")
                    for _ in range(N_Q):
                        uid = str(int(qrng.integers(0, N_USERS)))
                        t0 = time.perf_counter()
                        c.topk(ALS_STATE, uid, TOPK)
                        lat[arm].append(time.perf_counter() - t0)
        set_enabled(True)
        out = {}
        for arm in ("on", "off"):
            p50, p95 = bucketed_quantiles(lat[arm], (50, 95))
            out[arm] = {"n": len(lat[arm]),
                        "p50_ms": round(p50 * 1e3, 4),
                        "p95_ms": round(p95 * 1e3, 4),
                        # exact-rank percentiles for the overhead ratio —
                        # the shared ladder's ~7%-wide buckets quantize
                        # too coarsely to resolve a few-percent delta
                        "exact_p50_ms": round(
                            float(np.percentile(lat[arm], 50)) * 1e3, 4)}
        out["p50_overhead_pct"] = round(
            100.0 * (out["on"]["exact_p50_ms"]
                     / out["off"]["exact_p50_ms"] - 1.0), 2)

        # the socket-level ratio above rides ~±5% machine noise; the
        # reproducible signal is the in-process dispatch delta — same
        # verb path minus the kernel round trip — measured ABAB
        srv = job.server
        line = f"TOPK\t{ALS_STATE}\t7\t{TOPK}"
        for _ in range(300):
            srv._dispatch(line)
        disp = {"on": [], "off": []}
        for r in range(6):
            order = ("on", "off") if r % 2 == 0 else ("off", "on")
            for arm in order:
                set_enabled(arm == "on")
                xs = []
                for _ in range(2000):
                    t0 = time.perf_counter()
                    srv._dispatch(line)
                    xs.append(time.perf_counter() - t0)
                disp[arm].append(float(np.percentile(xs, 50)) * 1e6)
        set_enabled(True)
        d_on = float(np.median(disp["on"]))
        d_off = float(np.median(disp["off"]))
        out["dispatch"] = {
            "p50_on_us": round(d_on, 2), "p50_off_us": round(d_off, 2),
            "delta_us": round(d_on - d_off, 2),
            "overhead_pct": round(100.0 * (d_on / d_off - 1.0), 2),
        }
        # --- tail-forensics arms: spans (head-sampled) + exemplars -------
        # Both arms keep metrics ON (the baseline the 3% bar is against is
        # the already-instrumented GET path); the "trace" arm additionally
        # samples trace roots at TRACE_SAMPLE and retains exemplars.
        from flink_ms_tpu.obs import tracing as Tr
        from flink_ms_tpu.obs.metrics import set_exemplars

        get_line = f"GET\t{ALS_STATE}\t7-U"
        for _ in range(300):
            srv._dispatch(get_line)

        # one sampling roll + (when sampled) one span per WINDOW requests,
        # exactly the serve/client.py pipeline() shape — the roll is inside
        # the timed region, amortized the way the real hot path amortizes it
        WINDOW = 32

        def window_us():
            t0 = time.perf_counter()
            tid = Tr.sample_trace()
            if tid is not None:
                with Tr.trace_span(tid):
                    stamped = Tr.stamp(get_line)
                    for _ in range(WINDOW):
                        srv._dispatch(stamped)
            else:
                for _ in range(WINDOW):
                    srv._dispatch(get_line)
            return (time.perf_counter() - t0) / WINDOW * 1e6

        tdisp = {"trace": [], "plain": []}
        for r in range(10):
            order = ("trace", "plain") if r % 2 == 0 else ("plain", "trace")
            for arm in order:
                on = arm == "trace"
                os.environ["TPUMS_TRACE_SAMPLE"] = \
                    str(TRACE_SAMPLE) if on else "0"
                set_exemplars(on)
                xs = [window_us() for _ in range(200)]
                tdisp[arm].append(float(np.percentile(xs, 50)))
        os.environ["TPUMS_TRACE_SAMPLE"] = "0"
        set_exemplars(False)
        # min-of-round-p50s, symmetric across arms: each arm's best round
        # is its contention-free cost, which is what the overhead bar is
        # about — medians ride scheduler/thermal noise that swamps a
        # sub-0.1us per-request delta
        t_on = float(np.min(tdisp["trace"]))
        t_off = float(np.min(tdisp["plain"]))
        trace_pct = 100.0 * (t_on / t_off - 1.0)
        out["trace"] = {
            "sample": TRACE_SAMPLE,
            "p50_on_us": round(t_on, 2), "p50_off_us": round(t_off, 2),
            "delta_us": round(t_on - t_off, 2),
            "overhead_pct": round(trace_pct, 2),
            "bar_pct": TRACE_BAR_PCT,
        }
        # --- continuous-profiler arm: always-on sampler at PROF_HZ ------
        # Same ABAB discipline on the same GET hot path.  The "prof" arm
        # runs the sampling profiler (timer thread + per-dispatch stage
        # mark); the "plain" arm has it stopped.  Metrics stay ON in both
        # arms — the bar is profiler-on vs the already-instrumented path.
        from flink_ms_tpu.obs import profiler as Prof

        pdisp = {"prof": [], "plain": []}
        for r in range(10):
            order = ("prof", "plain") if r % 2 == 0 else ("plain", "prof")
            for arm in order:
                if arm == "prof":
                    os.environ["TPUMS_PROF"] = "1"
                    os.environ.setdefault("TPUMS_PROF_HZ", str(PROF_HZ))
                    Prof.ensure_started()
                else:
                    Prof.stop_profiler()
                xs = []
                for _ in range(200):
                    t0 = time.perf_counter()
                    for _ in range(WINDOW):
                        srv._dispatch(get_line)
                    xs.append(
                        (time.perf_counter() - t0) / WINDOW * 1e6)
                pdisp[arm].append(float(np.percentile(xs, 50)))
        Prof.stop_profiler()
        p_on = float(np.min(pdisp["prof"]))
        p_off = float(np.min(pdisp["plain"]))
        prof_pct = 100.0 * (p_on / p_off - 1.0)
        out["profiler"] = {
            "hz": float(os.environ.get("TPUMS_PROF_HZ", PROF_HZ)),
            "p50_on_us": round(p_on, 2), "p50_off_us": round(p_off, 2),
            "delta_us": round(p_on - p_off, 2),
            "overhead_pct": round(prof_pct, 2),
            "bar_pct": PROF_BAR_PCT,
        }
        print(json.dumps(out, indent=1))
        rc = 0
        if trace_pct > TRACE_BAR_PCT:
            print(f"FAIL: spans+exemplars GET p50 overhead "
                  f"{trace_pct:.2f}% > {TRACE_BAR_PCT}%", file=sys.stderr)
            rc = 1
        if prof_pct > PROF_BAR_PCT:
            print(f"FAIL: profiler GET p50 overhead "
                  f"{prof_pct:.2f}% > {PROF_BAR_PCT}%", file=sys.stderr)
            rc = 1
        return rc
    finally:
        job.stop()


if __name__ == "__main__":
    sys.exit(main())
