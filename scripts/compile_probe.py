#!/usr/bin/env python
"""Compile-level chip probe: distinguishes a truly usable tunnel from the
wedge state where the relay answers `jax.devices()` but every remote
compile hangs (observed all of round 4 and twice in round 5 — the
onchip_r04.sh sanity probe passed in that state and the plan then burned
its full sequential timeout budget against a dead compiler).

Exit codes:
  0  chip answered AND a tiny jit compile+execute completed
  2  devices listed but platform is cpu (degraded / no tunnel)
  3  backend init or compile raised
  (a HANG is handled by the caller's `timeout` -> rc 124)

Prints one line: `compile-ok <platform> <secs>` on success.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from flink_ms_tpu.parallel.mesh import honor_platform_env

    honor_platform_env()
    import jax
    import jax.numpy as jnp

    t0 = time.monotonic()
    try:
        d = jax.devices()[0]
        if d.platform == "cpu":
            print(f"devices-cpu {d}")
            return 2
        out = jax.jit(lambda x: (x @ x).sum())(jnp.ones((128, 128)))
        jax.block_until_ready(out)
    except Exception as e:  # noqa: BLE001 - probe reports, caller decides
        print(f"compile-raise {type(e).__name__}: {str(e)[:200]}")
        return 3
    print(f"compile-ok {d.platform} {time.monotonic() - t0:.1f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
