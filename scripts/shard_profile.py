#!/usr/bin/env python
"""Sharded-plane latency attribution (VERDICT r2 weak #6): where do the
3-worker MGET/TOPK percentiles go vs single-worker — client routing, pool
dispatch, per-worker service time, or merge?

Builds one single-worker plane and one W-worker plane over the same
generated model, then times:
  - single MGET / sharded MGET (pooled fan-out vs sequential)
  - single TOPK / per-worker TOPKV serial / pooled fan-out topk
Run host-side; no accelerator needed (the serving plane is host-resident).

Measurement hazard on small hosts (this box: 1 CPU core): the first
seconds after worker-process startup carry intermittent ~10-100 ms
scheduler stalls that dominate short windows — a 50-query run can sit
entirely inside them (observed 20 ms p50) while a 500-query run on the
same plane settles to 0.07 ms p50.  Keep PROF_QUERIES >= 300 and trust
p50 over the tail percentiles here; on multi-core serving hosts this
artifact does not exist.
"""

import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("TPUMS_TOPK_PLATFORM", "cpu")

from flink_ms_tpu.core.params import Params  # noqa: E402
from flink_ms_tpu.gen import als_model_generator  # noqa: E402
from flink_ms_tpu.serve import producer  # noqa: E402
from flink_ms_tpu.serve.client import QueryClient  # noqa: E402
from flink_ms_tpu.serve.consumer import (  # noqa: E402
    ALS_STATE,
    MemoryStateBackend,
    ServingJob,
    parse_als_record,
)
from flink_ms_tpu.serve.journal import Journal  # noqa: E402
from flink_ms_tpu.serve.sharded import (  # noqa: E402
    ShardedQueryClient,
    spawn_worker_procs,
    stop_worker_procs,
)

N_USERS = int(os.environ.get("PROF_USERS", 30_000))
N_ITEMS = int(os.environ.get("PROF_ITEMS", 300_000))
K = int(os.environ.get("PROF_K", 16))
W = int(os.environ.get("PROF_WORKERS", 3))
N_Q = int(os.environ.get("PROF_QUERIES", 500))
TOPK_K = 10


def pcts(xs):
    xs = sorted(xs)
    return {q: round(xs[min(int(len(xs) * q / 100), len(xs) - 1)], 3)
            for q in (50, 95, 99)}


def timed(fn, n=N_Q, seed=1):
    rng = np.random.default_rng(seed)

    def draw():
        return (int(rng.integers(1, N_USERS + 1)),
                int(rng.integers(1, N_ITEMS + 1)))

    # active warmup, uncounted: the seconds after worker startup carry a
    # scheduler/cache transient on small hosts (observed ~20 ms p50 for a
    # measurement window that sits entirely inside it vs 0.07 ms after);
    # warm until the path is demonstrably settled or 3 s, whichever first
    deadline = time.time() + 3.0
    fast = 0
    while time.time() < deadline and fast < 20:
        u, i = draw()
        t0 = time.perf_counter()
        fn(u, i)
        fast = fast + 1 if (time.perf_counter() - t0) < 0.001 else 0
    out = []
    for _ in range(n):
        u, i = draw()
        t0 = time.perf_counter()
        fn(u, i)
        out.append((time.perf_counter() - t0) * 1000.0)
    return pcts(out)


def main():
    tmp = tempfile.mkdtemp(prefix="shard_prof_")
    t0 = time.time()
    als_model_generator.run(Params.from_dict({
        "numUsers": N_USERS, "numItems": N_ITEMS, "latentFactors": K,
        "parallelism": 4, "output": os.path.join(tmp, "model"),
    }))
    producer.run(Params.from_dict({
        "journalDir": os.path.join(tmp, "bus"), "topic": "als-models",
        "input": os.path.join(tmp, "model"),
    }))
    print(f"gen+produce: {time.time() - t0:.1f}s", file=sys.stderr)

    total = N_USERS + N_ITEMS
    journal = Journal(os.path.join(tmp, "bus"), "als-models")
    single = ServingJob(
        journal, ALS_STATE, parse_als_record, MemoryStateBackend(),
        host="127.0.0.1", port=0, poll_interval_s=0.01,
    ).start()
    # REAL worker processes — the deployment shape; in-process workers
    # share one GIL + XLA runtime and serialize the TOPKV fan-out
    procs, ports = spawn_worker_procs(
        W, os.path.join(tmp, "bus"), "als-models", port_dir=tmp,
    )

    try:
        sc = QueryClient("127.0.0.1", single.port, timeout_s=600)
        shc = ShardedQueryClient([("127.0.0.1", pt) for pt in ports],
                                 timeout_s=600)
        wc = [QueryClient("127.0.0.1", pt, timeout_s=600) for pt in ports]
        deadline = time.time() + 600
        while time.time() < deadline:
            if (len(single.table) >= total
                    and shc.total_count(ALS_STATE) >= total):
                break
            time.sleep(0.2)
        print(f"ingest done: {time.time() - t0:.1f}s", file=sys.stderr)

        print("MGET-2  single :", timed(
            lambda u, i: sc.query_states(ALS_STATE, [f"{u}-U", f"{i}-I"])))
        print("MGET-2  sharded:", timed(
            lambda u, i: shc.query_states(ALS_STATE, [f"{u}-U", f"{i}-I"])))

        def seq_mget(u, i):
            for key in (f"{u}-U", f"{i}-I"):
                wc[shc.owner(key)].query_states(ALS_STATE, [key])
        print("MGET-2  seq-direct:", timed(seq_mget))

        # topk warm (index builds)
        t0 = time.time()
        sc.topk(ALS_STATE, "1", TOPK_K)
        print(f"single index build: {time.time() - t0:.1f}s", file=sys.stderr)
        t0 = time.time()
        shc.topk(ALS_STATE, "1", TOPK_K)
        print(f"sharded index build: {time.time() - t0:.1f}s", file=sys.stderr)

        print("TOPK    single :", timed(
            lambda u, i: sc.topk(ALS_STATE, str(u), TOPK_K), n=60))
        print("TOPK    sharded:", timed(
            lambda u, i: shc.topk(ALS_STATE, str(u), TOPK_K), n=60))

        payload = sc.query_state(ALS_STATE, "1-U")
        for widx, c in enumerate(wc):
            ms = []
            for _ in range(60):
                t0 = time.perf_counter()
                c.topk_by_vector(ALS_STATE, payload, TOPK_K)
                ms.append((time.perf_counter() - t0) * 1000.0)
            print(f"TOPKV   worker{widx} direct:", pcts(ms))

        def serial_fan(u, i):
            up = shc.query_state(ALS_STATE, f"{u}-U")
            if up is None:
                return
            merged = []
            for c in wc:
                r = c.topk_by_vector(ALS_STATE, up, TOPK_K)
                merged.extend(r)
            merged.sort(key=lambda it: -it[1])
            merged[:TOPK_K]
        print("TOPK    serial-fanout:", timed(serial_fan, n=60))

        for c in (sc, shc, *wc):
            c.close()
    finally:
        single.stop()
        stop_worker_procs(procs)


if __name__ == "__main__":
    main()
