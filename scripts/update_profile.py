#!/usr/bin/env python
"""Update-plane microbench: ratings -> published factor rows/sec,
per-rating vs batched vs co-located sharded arms (ISSUE 9).

Measures the SGD apply path in isolation (in-process table, no serving
fleet) so regressions in the rating->rows pipeline are visible outside
the full bench:

- ``perrating``  — the reference shape (SGD.java): one lookup round trip
  and one scalar update per rating;
- ``batched``    — one MGET + the vectorized ``SGDStep.process_batch``
  per chunk (online/sgd.py --batchSize);
- ``colocated``  — the sharded plane (serve/update_plane.py): ratings
  hash-routed into per-partition logs, N co-located UpdateWorkers
  applying through the same batched step, owned reads local, cross-shard
  item reads through the coalesced MGET cache.

All arms run a duplicate-free stream (each user/item once), so the rows
they emit must be BYTE-IDENTICAL; the parity assert covers v1, v0 and
bias semantics before any timing arm runs.

Run host-side (no accelerator needed):

    python scripts/update_profile.py [--ratings 50000] [--k 8] \
        [--workers 4] [--batchSize 256] [--partitions 16]
"""

import os
import random
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("TPUMS_TOPK_PLATFORM", "cpu")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from flink_ms_tpu.core.params import Params  # noqa: E402
from flink_ms_tpu.online.sgd import SGDStep  # noqa: E402
from flink_ms_tpu.serve import update_plane as up  # noqa: E402
from flink_ms_tpu.serve.table import ModelTable  # noqa: E402


def build_table(n: int, k: int, seed: int = 7) -> ModelTable:
    rng = random.Random(seed)
    table = ModelTable(8)
    for i in range(n):
        table.put(f"{i}-U", ";".join(
            f"{rng.uniform(-1, 1):.6f}" for _ in range(k)))
        table.put(f"{i}-I", ";".join(
            f"{rng.uniform(-1, 1):.6f}" for _ in range(k)))
    return table


def build_ratings(n: int, seed: int = 3):
    """Duplicate-free: each user and each item exactly once, so every arm
    computes from the same base vectors and rows are comparable."""
    rng = random.Random(seed)
    items = list(range(n))
    rng.shuffle(items)
    return [(u, items[u], round(rng.uniform(0.5, 5.0), 3)) for u in range(n)]


class TableClient:
    """The co-located arm's 'fleet': MGET answered from the shared table
    (models the cross-shard item fetch without network noise)."""

    def __init__(self, table):
        self.table = table

    def query_states(self, state, keys):
        return [self.table.get(k) for k in keys]

    def close(self):
        pass


def run_perrating(table, ratings, k, version, bias):
    zero = ";".join(["0.0"] * k)
    step = SGDStep(table.get, zero, zero, version=version, update_bias=bias)
    rows = []
    t0 = time.perf_counter()
    for u, i, r in ratings:
        rows.extend(step.process(u, i, r))
    return rows, time.perf_counter() - t0


def run_batched(table, ratings, k, batch_size, version, bias):
    zero = ";".join(["0.0"] * k)
    step = SGDStep(
        table.get, zero, zero, version=version, update_bias=bias,
        lookup_many=lambda keys: [table.get(key) for key in keys],
    )
    rows = []
    t0 = time.perf_counter()
    for s in range(0, len(ratings), batch_size):
        rows.extend(step.process_batch(ratings[s:s + batch_size]))
    return rows, time.perf_counter() - t0


def run_colocated(table, ratings, k, workers, partitions, batch_size,
                  version, bias):
    with tempfile.TemporaryDirectory() as tmp:
        cli = up.UpdatePlaneClient(tmp, "models", partitions=partitions)
        fleet = [up.UpdateWorker(
            tmp, "models", w, workers, table=table,
            client_factory=lambda: TableClient(table),
            partitions=partitions, batch_size=batch_size, poll_s=0.001,
            dim=k, version=version, update_bias=bias,
            visibility_probe=False,
        ).start() for w in range(workers)]
        t0 = time.perf_counter()
        cli.submit_many(ratings)
        deadline = t0 + 300
        while time.perf_counter() < deadline:
            wm = up.applied_watermarks(tmp, "models", partitions)
            if sum(wm.values()) >= len(ratings):
                break
            time.sleep(0.002)
        dt = time.perf_counter() - t0
        for w in fleet:
            w.stop()
        rows = []
        from flink_ms_tpu.serve.journal import Journal
        for p in range(partitions):
            for ln in up._read_all_lines(
                    Journal(tmp, up.apply_topic("models", p))):
                fields = ln.split("\t", 3)
                if len(fields) > 3 and fields[3]:
                    rows.extend(fields[3].split("|"))
        audit = up.audit_partitions(tmp, "models", partitions)
        assert audit["clean"], f"PARITY FAILURE: audit not clean: {audit}"
        return rows, dt


def main(argv=None) -> None:
    params = Params.from_args(sys.argv[1:] if argv is None else argv)
    n = params.get_int("ratings", 50_000)
    k = params.get_int("k", 8)
    workers = params.get_int("workers", 4)
    batch_size = params.get_int("batchSize", 256)
    partitions = params.get_int("partitions", 16)

    # -- parity first: all three arms, byte-identical rows, all semantics --
    print("[update-profile] parity check (v1 / v0 / bias)...",
          file=sys.stderr)
    ptable = build_table(512, k)
    pratings = build_ratings(512)
    for version, bias in (("v1", False), ("v0", False), ("v1", True)):
        ref, _ = run_perrating(ptable, pratings, k, version, bias)
        bat, _ = run_batched(ptable, pratings, k, 64, version, bias)
        col, _ = run_colocated(ptable, pratings, k, workers, partitions,
                               64, version, bias)
        assert sorted(bat) == sorted(ref), \
            f"PARITY FAILURE: batched != per-rating ({version} bias={bias})"
        assert sorted(col) == sorted(ref), \
            f"PARITY FAILURE: co-located != per-rating ({version} bias={bias})"
    print("[update-profile] parity OK", file=sys.stderr)

    # -- timing arms (v1, unbiased — the default closed-loop shape) --
    table = build_table(n, k)
    ratings = build_ratings(n)
    res = {}
    rows, dt = run_perrating(table, ratings, k, "v1", False)
    res["perrating"] = n / dt
    print(f"{'perrating':>10}: {n / dt:>12,.0f} ratings/s "
          f"({len(rows)} rows, {dt:.2f}s)")
    rows, dt = run_batched(table, ratings, k, batch_size, "v1", False)
    res["batched"] = n / dt
    print(f"{'batched':>10}: {n / dt:>12,.0f} ratings/s "
          f"({len(rows)} rows, batch={batch_size}, {dt:.2f}s)")
    rows, dt = run_colocated(table, ratings, k, workers, partitions,
                             batch_size, "v1", False)
    res["colocated"] = n / dt
    print(f"{'colocated':>10}: {n / dt:>12,.0f} ratings/s "
          f"({len(rows)} rows, {workers} workers x {partitions} "
          f"partitions, {dt:.2f}s)")
    print(f"colocated vs perrating: "
          f"{res['colocated'] / res['perrating']:.2f}x | vs batched: "
          f"{res['colocated'] / res['batched']:.2f}x")


if __name__ == "__main__":
    main()
