"""Extended serving soak: the suite's 4s soak run for ~15 minutes with the
NATIVE (rocksdb-parity) backend and repeated process-loss/restart cycles.
Exits 0 iff no reader/writer errors and every key serves after each
restart."""
import os
import socket
import sys
import tempfile
import threading
import time

sys.path.insert(0, "/root/repo")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from flink_ms_tpu.parallel.mesh import pin_host_backend
pin_host_backend()

import numpy as np

from flink_ms_tpu.core import formats as F
from flink_ms_tpu.core.params import Params
from flink_ms_tpu.online import sgd as online_sgd
from flink_ms_tpu.serve.client import QueryClient
from flink_ms_tpu.serve.consumer import (
    ALS_STATE, ServingJob, make_backend, parse_als_record,
)
from flink_ms_tpu.serve.journal import Journal

DURATION_S = float(os.environ.get("SOAK_S", 900))
RESTART_EVERY_S = float(os.environ.get("SOAK_RESTART_S", 180))

rng = np.random.default_rng(0)
k, n_users, n_items = 8, 200, 300
td = tempfile.mkdtemp(prefix="long_soak_")
bus = os.path.join(td, "bus")
j = Journal(bus, "m", segment_bytes=1 << 16, retain_segments=256)
rows = [F.format_als_row(i, t, rng.normal(size=k))
        for t in ("U", "I") for i in range(n_users if t == "U" else n_items)]
rows += ["MEAN,U," + ";".join(["0.0"] * k),
         "MEAN,I," + ";".join(["0.0"] * k)]
j.append(rows, flush=True)
chk = os.path.join(td, "chk")


def wait_until(pred, timeout=60.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.05)
    return False


def start_job():
    job = ServingJob(
        Journal(bus, "m"), ALS_STATE, parse_als_record,
        make_backend("rocksdb", chk), host="127.0.0.1", port=0,
        poll_interval_s=0.01, checkpoint_interval_ms=500,
    ).start()
    return job


job = start_job()
assert wait_until(lambda: len(job.table) >= len(rows)), "initial ingest"

stop = threading.Event()
errors: list = []
reads = {"mget": 0, "topk": 0}
port_lock = threading.Lock()
current_port = [job.port]


def sgd_writer():
    ratings = os.path.join(td, "ratings.tsv")
    recs = [(int(rng.integers(0, n_users)), int(rng.integers(0, n_items)),
             float(rng.uniform(1, 5))) for _ in range(200_000)]
    with open(ratings, "w") as f:
        f.write("".join(f"{u}\t{i}\t{r}\n" for u, i, r in recs))
    while not stop.is_set():
        with port_lock:
            port = current_port[0]
        try:
            online_sgd.run(Params.from_dict({
                "input": ratings, "mode": "continuous", "interval": 20,
                "outputMode": "journal", "journalDir": bus, "topic": "m",
                "jobId": job.job_id, "jobManagerHost": "127.0.0.1",
                "jobManagerPort": port, "queryTimeout": 30,
                "batchSize": 16, "flushEveryUpdate": False,
            }), stop=stop.is_set)
        except Exception as e:  # noqa: BLE001
            # a mid-restart connection error is expected; anything else is a
            # soak failure.  Match by TYPE: ConnectionError covers
            # BrokenPipeError/ConnectionResetError/ConnectionRefusedError
            # (a repr-substring check missed BrokenPipeError, whose repr
            # carries no "Connection"), socket.timeout covers a send into a
            # half-torn-down server.
            expected = isinstance(e, (ConnectionError, socket.timeout))
            if not stop.is_set() and not expected:
                errors.append(f"sgd: {e!r}")
                return
            time.sleep(0.5)


def reader(kind):
    while not stop.is_set():
        with port_lock:
            port = current_port[0]
        try:
            with QueryClient("127.0.0.1", port, timeout_s=30) as c:
                for _ in range(100):
                    if stop.is_set():
                        return
                    u = int(rng.integers(0, n_users))
                    i = int(rng.integers(0, n_items))
                    if kind == "mget":
                        ps = c.query_states(ALS_STATE, [f"{u}-U", f"{i}-I"])
                        assert len(ps) == 2
                        reads["mget"] += 1
                    else:
                        res = c.topk(ALS_STATE, str(u), 5)
                        assert res is None or len(res) <= 5
                        reads["topk"] += 1
        except Exception as e:  # noqa: BLE001
            msg = repr(e)
            if not stop.is_set() and "Connection" not in msg \
                    and "refused" not in msg and "reset" not in msg.lower():
                errors.append(f"{kind}: {msg}")
                return
            time.sleep(0.2)


threads = [threading.Thread(target=sgd_writer, daemon=True),
           threading.Thread(target=reader, args=("mget",), daemon=True),
           threading.Thread(target=reader, args=("topk",), daemon=True)]
for t in threads:
    t.start()

t_end = time.time() + DURATION_S
restarts = 0
while time.time() < t_end and not errors:
    time.sleep(min(RESTART_EVERY_S, max(t_end - time.time(), 1)))
    if time.time() >= t_end:
        break
    # process loss mid-soak: stop without final flush, restart, verify
    job.stop()
    job = start_job()
    with port_lock:
        current_port[0] = job.port
    end = Journal(bus, "m").end_offset()
    ok = wait_until(lambda: job.offset >= end, timeout=120)
    if not ok:
        errors.append(f"restart {restarts}: replay stalled at "
                      f"{job.offset}/{end}")
        break
    with QueryClient("127.0.0.1", job.port, timeout_s=30) as c:
        for u in range(0, n_users, 17):
            if c.query_state(ALS_STATE, f"{u}-U") is None:
                errors.append(f"restart {restarts}: missing key {u}-U")
                break
    restarts += 1
    print(f"[soak] restart {restarts} ok at t+{DURATION_S - (t_end - time.time()):.0f}s, "
          f"reads={reads}", flush=True)

stop.set()
for t in threads:
    t.join(timeout=60)
job.stop()
print(f"[soak] done: restarts={restarts}, reads={reads}, errors={errors}",
      flush=True)
sys.exit(1 if errors else 0)
