#!/usr/bin/env python
"""Headline benchmark: blocked-ALS training throughput (sec/iter) at
MovieLens-20M scale, rank 50 — the BASELINE.md north-star config — plus
roofline (MFU) accounting.

Prints ONE JSON line:
  {"metric": "als_ml20m_sec_per_iter", "value": N, "unit": "s/iter",
   "vs_baseline": R, "mfu": F, "platform": "...", ...extra sections...}

Failure policy (VERDICT r1 "what's weak" #1): a flaky accelerator backend
must never cost the round its number.  Backend init is retried with backoff
on UNAVAILABLE; on final failure the benchmark *degrades to the CPU backend*
and the JSON line carries the captured error in "backend_error" — loud in
the artifact, not an rc=1 traceback.

The reference publishes no numbers (BASELINE.md), so the comparison baseline
is measured in-process: the identical XLA program on the host CPU backend
(all cores — the single-machine stand-in for the reference's TaskManager
cluster), timed at a reduced nnz and scaled linearly to the full config.
vs_baseline > 1 means the TPU path is that many times faster. Override via
env BENCH_BASELINE_SEC_PER_ITER to pin an externally measured Flink baseline.

Env knobs: BENCH_NNZ, BENCH_USERS, BENCH_ITEMS, BENCH_RANK, BENCH_ITERS,
BENCH_SMALL=1 (quick sanity config), BENCH_SKIP_CPU=1, BENCH_PEAK_FLOPS
(per-device peak for MFU; default inferred from device_kind),
BENCH_INIT_ATTEMPTS / BENCH_INIT_BACKOFF_S (backend retry policy),
BENCH_SECTIONS (comma list: als,svm,serving; default all).
"""

import contextlib
import json
import os
import sys
import time
import traceback

import numpy as np


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# backend acquisition (retry + degrade, never crash)
# ---------------------------------------------------------------------------

def acquire_devices():
    """-> (devices, platform, backend_error|None).

    The accelerator backend is probed in a SUBPROCESS with a hard timeout
    first: a hung init (tunnel down — observed to block jax.devices()
    indefinitely rather than raise) must not hang the benchmark.  Probe
    failures retry with backoff on UNAVAILABLE; on final failure the
    benchmark degrades to the CPU backend with the error captured for the
    JSON artifact.  Only after a successful probe does the in-process
    backend initialize."""
    import subprocess

    import jax

    attempts = int(os.environ.get("BENCH_INIT_ATTEMPTS", 4))
    backoff = float(os.environ.get("BENCH_INIT_BACKOFF_S", 10))
    probe_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT_S", 240))
    last_err = None
    hangs = 0
    for i in range(attempts):
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "from flink_ms_tpu.parallel.mesh import honor_platform_env;"
                 "honor_platform_env();"  # the probe must respect an explicit
                 # JAX_PLATFORMS pin exactly like the in-process path will
                 "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, text=True, timeout=probe_timeout,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
        except subprocess.TimeoutExpired:
            last_err = f"backend init hung >{probe_timeout:.0f}s"
            hangs += 1
            _log(f"[bench] init attempt {i + 1}/{attempts}: {last_err}")
            if hangs >= 2:
                # a wedged transport hangs (it does not error): two hung
                # probes already cost 2x the probe timeout — degrade now
                # rather than burn the round's wall-clock on more
                break
            continue
        if probe.returncode == 0:
            # healthy backend: in-process init should take the same fast
            # path — but the tunnel can still drop in the gap, so failures
            # here fall through to the retry/degrade policy too
            try:
                devs = jax.devices()
            except RuntimeError as e:
                last_err = f"{type(e).__name__}: {e}"
                _log(f"[bench] in-process init failed after probe: {e}")
                continue
            accel = [d for d in devs if d.platform != "cpu"]
            if accel:
                return accel, accel[0].platform, None
            return devs, "cpu", None
        tail = (probe.stderr or "").strip().splitlines()
        last_err = tail[-1] if tail else f"probe rc={probe.returncode}"
        transient = "UNAVAILABLE" in last_err or "Unable to initialize" in last_err
        _log(f"[bench] backend init attempt {i + 1}/{attempts} failed: {last_err}")
        if not transient:
            break
        if i + 1 < attempts:
            time.sleep(backoff * (1.5 ** i))
    # degrade: the CPU backend registers independently of the accelerator
    # plugin, so it survives an accelerator init failure — but only if no
    # JAX_PLATFORMS pin excludes it (the ambient launcher export is exactly
    # what pins the failed accelerator in the first place).  Crucially the
    # remote plugin's FACTORY must be dropped before the first backend
    # init: jax initializes every registered plugin even for
    # jax.devices("cpu"), and a wedged tunnel HANGS that init rather than
    # erroring — pin_host_backend() is the difference between a degraded
    # CPU artifact and a bench that never returns.
    os.environ.pop("JAX_PLATFORMS", None)
    from flink_ms_tpu.parallel.mesh import pin_host_backend

    pin_host_backend()
    cpu = jax.devices("cpu")
    _log(f"[bench] degrading to CPU backend after: {last_err}")
    return cpu, "cpu", last_err


# ---------------------------------------------------------------------------
# roofline model
# ---------------------------------------------------------------------------

# bf16 MXU peak per chip (the systolic-array ceiling MFU is judged against;
# fp32 work lowers to bf16 passes on the MXU, so this is the honest
# denominator).  Keyed by substring of jax device_kind, first match wins.
_PEAK_FLOPS_BY_KIND = (
    ("v6", 918e12),       # Trillium / v6e
    ("v5p", 459e12),
    ("v5 lite", 197e12),  # v5e reports "TPU v5 lite"
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops_per_device(device) -> float:
    env = os.environ.get("BENCH_PEAK_FLOPS")
    if env:
        return float(env)
    kind = getattr(device, "device_kind", "").lower()
    if device.platform != "cpu" and not any(
        sub in kind for sub, _ in _PEAK_FLOPS_BY_KIND
    ):
        # tunneled devices may not report a standard TPU kind string; the
        # launcher exports the generation separately
        kind = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    for sub, peak in _PEAK_FLOPS_BY_KIND:
        if sub in kind:
            return peak
    return 0.0  # unknown (CPU fallback etc.) -> MFU reported as null


def als_flops_per_iter(nnz: int, n_users: int, n_items: int, k: int) -> float:
    """Analytic FLOPs of one full ALS iteration (both half-sweeps).

    Per half-sweep over the opposite-side factors Y:
      assembly  A_u += y yᵀ, b_u += r·y per rating: 2k² + 2k flops per nnz
      solve     per entity: Cholesky k³/3 + two triangular solves 2·2k²
    Both orientations touch every rating once, and every user and item row
    gets one solve per iteration."""
    assembly = 2 * nnz * (2 * k * k + 2 * k)
    solves = (n_users + n_items) * (k ** 3 / 3 + 4 * k * k)
    return float(assembly + solves)


# ---------------------------------------------------------------------------
# ALS section
# ---------------------------------------------------------------------------

def synth_ratings(n_users, n_items, nnz, seed=0, skew=None):
    """Synthetic ratings.  BENCH_SKEW=zipf (or skew="zipf") draws item
    popularity and user activity from heavy-tailed marginals (Zipf-like
    s~1, the real MovieLens-20M shape — wide degree spread stresses the
    kernel's bucket padding); default is uniform (the round-2 recorded
    workload)."""
    rng = np.random.default_rng(seed)
    if skew is None:
        skew = os.environ.get("BENCH_SKEW", "")
    if skew == "zipf":
        # bounded zipf via inverse-CDF over the ranked catalog
        def zipf_draw(n_ids, size, s=1.0):
            w = 1.0 / np.arange(1, n_ids + 1) ** s
            cdf = np.cumsum(w)
            cdf /= cdf[-1]  # exact 1.0 at the end: no out-of-range draw
            return np.searchsorted(cdf, rng.uniform(size=size)).astype(np.int64)

        users = zipf_draw(n_users, nnz, s=0.7)   # user activity: milder tail
        items = zipf_draw(n_items, nnz, s=1.0)   # item popularity: zipf-1
    else:
        users = rng.integers(0, n_users, nnz)
        items = rng.integers(0, n_items, nnz)
    ratings = rng.uniform(1.0, 5.0, nnz)
    return users, items, ratings


def time_fit(mesh, problem, cfg_base, iters, repeats=5):
    """Steady-state sec/iter on the compiled sweep with device-resident
    inputs: same executable (dynamic trip count) timed at 1 iteration and at
    `iters`; the difference isolates per-iter cost from dispatch overhead.
    Host<->device transfer happens once, outside the timed region; every
    timed call ends in a hard value-fetch sync (block_until_ready is not a
    reliable barrier on tunneled backends — utils.profiling.hard_sync).
    Median over `repeats`."""
    import jax.numpy as jnp

    from flink_ms_tpu.ops.als import compile_fit
    from flink_ms_tpu.utils.profiling import hard_sync

    iters = max(iters, 2)  # need two points to isolate per-iter cost
    fit_fn, dev_args = compile_fit(problem, cfg_base, mesh)

    def run(trip):
        t0 = time.time()
        uf, itf = fit_fn(jnp.asarray(trip, jnp.int32), *dev_args)
        hard_sync(uf)
        return time.time() - t0

    # same executable for every trip count (dynamic while_loop bound), so
    # amplify until the timed region dwarfs dispatch noise (>= 0.5 s)
    run(1), run(iters)  # compile + warmup
    while run(iters) < 0.5 and iters < 20_000:
        iters *= 4
    samples = []
    for _ in range(repeats):
        t1 = run(1)
        tn = run(iters)
        samples.append(max((tn - t1) / (iters - 1), 1e-9))
    samples.sort()
    return samples[len(samples) // 2]


def run_als_section(devices, platform, small: bool) -> dict:
    import jax

    from flink_ms_tpu.ops.als import ALSConfig, prepare_blocked
    from flink_ms_tpu.parallel.mesh import make_mesh

    n_users = int(os.environ.get("BENCH_USERS", 20_000 if small else 138_493))
    n_items = int(os.environ.get("BENCH_ITEMS", 2_000 if small else 26_744))
    nnz = int(os.environ.get("BENCH_NNZ", 500_000 if small else 20_000_000))
    rank = int(os.environ.get("BENCH_RANK", 16 if small else 50))
    iters = int(os.environ.get("BENCH_ITERS", 3 if small else 5))

    skew = os.environ.get("BENCH_SKEW", "") or "uniform"
    users, items, ratings = synth_ratings(n_users, n_items, nnz)
    cfg = ALSConfig(num_factors=rank, iterations=1, lambda_=0.1, seed=42)
    mesh = make_mesh(devices=devices)
    _log(f"[bench] ALS devices: {devices}, nnz={nnz}, rank={rank}")

    t0 = time.time()
    problem = prepare_blocked(users, items, ratings, mesh.devices.size)
    _log(f"[bench] prepare_blocked: {time.time() - t0:.1f}s")

    sec_per_iter = time_fit(mesh, problem, cfg, iters)
    _log(f"[bench] {platform} steady-state: {sec_per_iter:.3f} s/iter")

    flops = als_flops_per_iter(nnz, n_users, n_items, rank)
    peak = peak_flops_per_device(devices[0]) * len(devices)
    mfu = (flops / sec_per_iter) / peak if peak > 0 else None
    if mfu is not None:
        _log(f"[bench] {flops / 1e9:.1f} GFLOP/iter -> "
             f"{flops / sec_per_iter / 1e12:.2f} TFLOP/s, MFU {mfu:.4f}")

    baseline_env = os.environ.get("BENCH_BASELINE_SEC_PER_ITER")
    if baseline_env:
        baseline = float(baseline_env)
    elif os.environ.get("BENCH_SKIP_CPU") == "1" or platform == "cpu":
        baseline = sec_per_iter  # vs_baseline = 1.0, no comparison available
    else:
        # CPU stand-in baseline at reduced nnz, scaled linearly to full nnz
        cpu_nnz = min(nnz, 2_000_000)
        cpu_dev = jax.devices("cpu")
        cpu_mesh = make_mesh(devices=cpu_dev[:1])
        cu, ci, cr = users[:cpu_nnz], items[:cpu_nnz], ratings[:cpu_nnz]
        cpu_problem = prepare_blocked(cu, ci, cr, 1)
        cpu_spi = time_fit(cpu_mesh, cpu_problem, cfg, 2, repeats=3)
        baseline = cpu_spi * (nnz / cpu_nnz)
        _log(
            f"[bench] CPU stand-in: {cpu_spi:.3f} s/iter @ {cpu_nnz} nnz "
            f"-> scaled {baseline:.3f} s/iter @ {nnz}"
        )

    out = {
        "metric": "als_ml20m_sec_per_iter" if not small else "als_small_sec_per_iter",
        "value": round(sec_per_iter, 6),
        "unit": "s/iter",
        "vs_baseline": round(baseline / sec_per_iter, 3),
        "mfu": round(mfu, 5) if mfu is not None else None,
        "als_flops_per_iter": flops,
        "als_tflops_per_sec": round(flops / sec_per_iter / 1e12, 3),
        "als_nnz": nnz,
        "als_rank": rank,
        "workload_skew": skew,
        # kernel config forensics: which solver/precision/ladder produced
        # this number (env-driven knobs, baked in at trace time)
        "als_solver": os.environ.get("FLINK_MS_ALS_SOLVER", "auto"),
        "als_assembly_precision": cfg.assembly_precision,
        "als_bucket_ratio": os.environ.get("FLINK_MS_ALS_BUCKET_RATIO", "1.5"),
    }

    # BASELINE.json config "als-ms implicit-feedback ALS (confidence-
    # weighted) on MovieLens-20M": same problem layout, HKV mode (psum'd
    # Gramian + confidence-weighted assembly).  Skipped in BENCH_SMALL
    # sanity mode — the key names the ML-20M config and the extra timed
    # section would double the quick run's wall-clock.
    if not small:
        try:
            cfg_imp = ALSConfig(num_factors=rank, iterations=1, lambda_=0.1,
                                seed=42, implicit=True, alpha=40.0)
            spi_imp = time_fit(mesh, problem, cfg_imp, iters)
            out["als_implicit_sec_per_iter"] = round(spi_imp, 6)
            _log(f"[bench] implicit mode: {spi_imp:.3f} s/iter")
        except Exception:
            _log(traceback.format_exc())
            out["als_implicit_error"] = traceback.format_exc(limit=3)

    # BASELINE.json config "flink-als explicit ALS rank=10 on
    # MovieLens-100K (single-node CPU)": the reference's own smallest
    # config shape, timed on one host-CPU device as the single-node
    # reference point
    if not small and os.environ.get("BENCH_SKIP_CPU") != "1":
        try:
            # always uniform: this key mirrors the fixed BASELINE.json
            # reference shape regardless of BENCH_SKEW
            mu, mi, mr = synth_ratings(943, 1_682, 100_000, seed=1,
                                       skew="uniform")
            cfg100 = ALSConfig(num_factors=10, iterations=1, lambda_=0.1)
            cpu_mesh = make_mesh(devices=jax.devices("cpu")[:1])
            p100 = prepare_blocked(mu, mi, mr, 1)
            spi100 = time_fit(cpu_mesh, p100, cfg100, 3, repeats=3)
            out["als_ml100k_cpu_sec_per_iter"] = round(spi100, 6)
            _log(f"[bench] ML-100K rank-10 single-node CPU: {spi100:.4f} s/iter")
        except Exception:
            _log(traceback.format_exc())
            out["als_ml100k_error"] = traceback.format_exc(limit=3)

    return out


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------

def main() -> None:
    # stdout is the artifact: exactly ONE JSON line.  Section code calls
    # CLI mains in-process (producer, SGD, MSE) whose job summaries print
    # to stdout — reroute everything but the final JSON to stderr.
    real_stdout = sys.stdout
    with contextlib.redirect_stdout(sys.stderr):
        result = _run_all()
    print(json.dumps(result), file=real_stdout)


def _run_all() -> dict:
    small = os.environ.get("BENCH_SMALL") == "1"
    sections = os.environ.get(
        "BENCH_SECTIONS", "als,svm,serving,svmserve"
    ).split(",")
    result: dict = {}

    from flink_ms_tpu.parallel.mesh import honor_platform_env

    honor_platform_env()

    try:
        devices, platform, backend_error = acquire_devices()
    except Exception as e:
        _log(traceback.format_exc())
        return {
            "metric": "als_ml20m_sec_per_iter", "value": None,
            "unit": "s/iter", "vs_baseline": None,
            "backend_error": f"no backend at all: {e}",
        }
    result["platform"] = platform
    result["n_devices"] = len(devices)
    result["device_kind"] = getattr(devices[0], "device_kind", "unknown")
    if backend_error:
        result["backend_error"] = backend_error
        if platform == "cpu" and not small:
            # degraded artifact: cap the DEFAULT full-scale ALS config so
            # the CPU fallback finishes in minutes, not the better part
            # of an hour (explicit BENCH_* env still wins; small mode is
            # already small; als_nnz in the JSON records what ran)
            os.environ.setdefault("BENCH_NNZ", "2000000")
            os.environ.setdefault("BENCH_ITERS", "2")

    try:
        if "als" in sections:
            result.update(run_als_section(devices, platform, small))
    except Exception:
        _log(traceback.format_exc())
        result["als_error"] = traceback.format_exc(limit=3)

    # every extra section degrades independently: a failure records its
    # <name>_error key without costing the others their metrics
    extra = (
        ("svm", "run_svm_section", lambda f: f(devices, platform, small)),
        ("serving", "run_serving_section", lambda f: f(small)),
        ("svmserve", "run_svm_serving_section", lambda f: f(small)),
    )
    for name, fn_name, call in extra:
        if name not in sections:
            continue
        try:
            import bench_sections
        except ImportError:
            result[f"{name}_error"] = "bench_sections module not available"
            continue
        fn = getattr(bench_sections, fn_name, None)
        if fn is None:
            result[f"{name}_error"] = f"bench_sections.{fn_name} missing"
            continue
        try:
            result.update(call(fn))
        except Exception:
            _log(traceback.format_exc())
            result[f"{name}_error"] = traceback.format_exc(limit=3)

    if "metric" not in result:
        # headline section failed: still emit a valid, loud artifact
        result.setdefault("metric", "als_ml20m_sec_per_iter")
        result.setdefault("value", None)
        result.setdefault("unit", "s/iter")
        result.setdefault("vs_baseline", None)

    return result


if __name__ == "__main__":
    main()
