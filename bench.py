#!/usr/bin/env python
"""Headline benchmark: blocked-ALS training throughput (sec/iter) at
MovieLens-20M scale, rank 50 — the BASELINE.md north-star config.

Prints ONE JSON line:
  {"metric": "als_ml20m_sec_per_iter", "value": N, "unit": "s/iter",
   "vs_baseline": R}

The reference publishes no numbers (BASELINE.md), so the comparison baseline
is measured in-process: the identical XLA program on the host CPU backend
(all cores — the single-machine stand-in for the reference's TaskManager
cluster), timed at a reduced nnz and scaled linearly to the full config.
vs_baseline > 1 means the TPU path is that many times faster. Override via
env BENCH_BASELINE_SEC_PER_ITER to pin an externally measured Flink baseline.

Env knobs: BENCH_NNZ, BENCH_USERS, BENCH_ITEMS, BENCH_RANK, BENCH_ITERS,
BENCH_SMALL=1 (quick sanity config), BENCH_SKIP_CPU=1.
"""

import json
import os
import sys
import time

import numpy as np


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def synth_ratings(n_users, n_items, nnz, seed=0):
    rng = np.random.default_rng(seed)
    users = rng.integers(0, n_users, nnz)
    items = rng.integers(0, n_items, nnz)
    ratings = rng.uniform(1.0, 5.0, nnz)
    return users, items, ratings


def time_fit(mesh, problem, cfg_base, iters, repeats=5):
    """Steady-state sec/iter on the compiled sweep with device-resident
    inputs: same executable (dynamic trip count) timed at 1 iteration and at
    `iters`; the difference isolates per-iter cost from dispatch overhead.
    Host<->device transfer happens once, outside the timed region; every
    timed call ends in block_until_ready.  Median over `repeats`."""
    import jax
    import jax.numpy as jnp

    from flink_ms_tpu.ops.als import compile_fit

    iters = max(iters, 2)  # need two points to isolate per-iter cost
    fit_fn, dev_args = compile_fit(problem, cfg_base, mesh)

    def run(trip):
        t0 = time.time()
        uf, itf = fit_fn(jnp.asarray(trip, jnp.int32), *dev_args)
        jax.block_until_ready((uf, itf))
        return time.time() - t0

    # same executable for every trip count (dynamic while_loop bound), so
    # amplify until the timed region dwarfs dispatch noise (>= 0.5 s)
    run(1), run(iters)  # compile + warmup
    while run(iters) < 0.5 and iters < 20_000:
        iters *= 4
    samples = []
    for _ in range(repeats):
        t1 = run(1)
        tn = run(iters)
        samples.append(max((tn - t1) / (iters - 1), 1e-9))
    samples.sort()
    return samples[len(samples) // 2]


def main() -> None:
    small = os.environ.get("BENCH_SMALL") == "1"
    n_users = int(os.environ.get("BENCH_USERS", 20_000 if small else 138_493))
    n_items = int(os.environ.get("BENCH_ITEMS", 2_000 if small else 26_744))
    nnz = int(os.environ.get("BENCH_NNZ", 500_000 if small else 20_000_000))
    rank = int(os.environ.get("BENCH_RANK", 16 if small else 50))
    iters = int(os.environ.get("BENCH_ITERS", 3 if small else 5))

    import jax

    from flink_ms_tpu.parallel.mesh import honor_platform_env

    honor_platform_env()

    from flink_ms_tpu.ops.als import ALSConfig, prepare_blocked
    from flink_ms_tpu.parallel.mesh import make_mesh

    users, items, ratings = synth_ratings(n_users, n_items, nnz)
    cfg = ALSConfig(num_factors=rank, iterations=1, lambda_=0.1, seed=42)

    accel = [d for d in jax.devices() if d.platform != "cpu"] or jax.devices()
    mesh = make_mesh(devices=accel)
    _log(f"[bench] devices: {accel}, nnz={nnz}, rank={rank}")

    t0 = time.time()
    problem = prepare_blocked(users, items, ratings, mesh.devices.size)
    _log(f"[bench] prepare_blocked: {time.time() - t0:.1f}s")

    sec_per_iter = time_fit(mesh, problem, cfg, iters)
    _log(f"[bench] TPU steady-state: {sec_per_iter:.3f} s/iter")

    baseline_env = os.environ.get("BENCH_BASELINE_SEC_PER_ITER")
    if baseline_env:
        baseline = float(baseline_env)
    elif os.environ.get("BENCH_SKIP_CPU") == "1":
        baseline = sec_per_iter  # vs_baseline = 1.0, no comparison available
    else:
        # CPU stand-in baseline at reduced nnz, scaled linearly to full nnz
        cpu_nnz = min(nnz, 2_000_000)
        cpu_dev = jax.devices("cpu")
        cpu_mesh = make_mesh(devices=cpu_dev[:1])
        cu, ci, cr = users[:cpu_nnz], items[:cpu_nnz], ratings[:cpu_nnz]
        cpu_problem = prepare_blocked(cu, ci, cr, 1)
        cpu_spi = time_fit(cpu_mesh, cpu_problem, cfg, 2, repeats=3)
        baseline = cpu_spi * (nnz / cpu_nnz)
        _log(
            f"[bench] CPU stand-in: {cpu_spi:.3f} s/iter @ {cpu_nnz} nnz "
            f"-> scaled {baseline:.3f} s/iter @ {nnz}"
        )

    print(
        json.dumps(
            {
                "metric": "als_ml20m_sec_per_iter" if not small else "als_small_sec_per_iter",
                "value": round(sec_per_iter, 6),
                "unit": "s/iter",
                "vs_baseline": round(baseline / sec_per_iter, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
