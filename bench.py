#!/usr/bin/env python
"""Headline benchmark: blocked-ALS training throughput (sec/iter) at
MovieLens-20M scale, rank 50 — the BASELINE.md north-star config — plus
roofline (MFU) accounting.

Prints ONE COMPACT JSON line (headline keys only):
  {"metric": "als_ml20m_sec_per_iter", "value": N, "unit": "s/iter",
   "vs_baseline": R, "mfu": F, "platform": "...", "degraded": bool, ...}
and writes every section key to the BENCH_DETAIL.json sidecar next to this
file.  The split exists because the round-2 driver recorded only a ~2 KB
TAIL of stdout: the full 2.3 KB line lost its head ("{\"metric\"...",
"platform", "backend_error") and recorded as parsed=null.  The compact
line stays well under the tail window; the sidecar carries the rest.

Failure policy (VERDICT r1 "what's weak" #1): a flaky accelerator backend
must never cost the round its number.  Backend init is retried with backoff
on UNAVAILABLE; on final failure the benchmark *degrades to the CPU backend*
and the JSON line carries the captured error in "backend_error" — loud in
the artifact, not an rc=1 traceback.  A degraded run does not give up on
the chip (VERDICT r2 missing #1): between sections it re-probes the tunnel
(skipping the full jax probe only when the relay TCP port is refused or a
recent probe hung — an instant EOF after connect is a KNOWN FALSE POSITIVE
wedge fingerprint as of round 3) and, on recovery, re-runs the ALS+SVM
sections at
FULL scale on the accelerator in a fresh subprocess (this process popped
the remote plugin factories and cannot re-init the backend), merging the
recovered numbers into the artifact with recovered=true.

The reference publishes no numbers (BASELINE.md), so the comparison baseline
is measured in-process: the identical XLA program on the host CPU backend
(all cores — the single-machine stand-in for the reference's TaskManager
cluster), timed at a reduced nnz and scaled linearly to the full config.
vs_baseline > 1 means the TPU path is that many times faster. Override via
env BENCH_BASELINE_SEC_PER_ITER to pin an externally measured Flink baseline.

Env knobs: BENCH_NNZ, BENCH_USERS, BENCH_ITEMS, BENCH_RANK, BENCH_ITERS,
BENCH_SMALL=1 (quick sanity config), BENCH_SKIP_CPU=1, BENCH_PEAK_FLOPS
(per-device peak for MFU; default inferred from device_kind),
BENCH_INIT_ATTEMPTS / BENCH_INIT_BACKOFF_S (backend retry policy),
BENCH_SECTIONS (comma list: als,svm,serving,svmserve,serving_ingest,
serving_ha,serving_elastic,serving_rehearsal,serving_bootstrap,
serving_native,serving_update_plane,serving_rollout,serving_ann,
serving_watch,serving_autopilot,serving_forensics,serving_geo,
serving_arena,serving_arena_ingest,serving_edge,serving_profiler,
serving_push; default all),
BENCH_PUSH_UPDATES / BENCH_PUSH_FANOUT / BENCH_PUSH_TOPK_SUBS /
BENCH_PUSH_SEL_UPDATES (push plane: update->push p99, edge fan-out
amplification, TOPK re-score selectivity under zipf updates),
BENCH_ANN_ROWS_EXACT / BENCH_ANN_ROWS_IVF / BENCH_ANN_ARM_TIMEOUT_S
(retrieval-plane A/B arm sizes: sharded-exact question at 1M rows,
IVF question at 10M, recall@100 >= 0.95 gate recorded),
BENCH_UPDATE_USERS / BENCH_UPDATE_FLEET_RATINGS / BENCH_UPDATE_BATCH /
BENCH_UPDATE_PROBES (online update plane: fleet updates/s vs the
single-consumer baseline, 2->4 reshard audit, submit->queryable p99),
BENCH_NATIVE_KEYS / BENCH_NATIVE_GETS / BENCH_NATIVE_TOPKS /
BENCH_NATIVE_ITEMS (serving-native tab-vs-B2 wire protocol A/B scale),
BENCH_INGEST_ROWS /
BENCH_INGEST_K / BENCH_INGEST_PROP_PROBES (serving-ingest replay scale),
BENCH_HA_USERS / BENCH_HA_DURATION_S / BENCH_HA_WORKERS /
BENCH_HA_HEARTBEAT_S / BENCH_HA_TTL_S (serving-HA kill-a-replica arms),
BENCH_ELASTIC_USERS / BENCH_ELASTIC_WINDOW_S (serving-elastic live
2->4 rescale: p50/p99 before/during/after + cutover duration),
BENCH_HA_RATE_QPS / BENCH_ELASTIC_RATE_QPS (open-loop pacing of the
HA/elastic query arms; latency recorded from intended send time),
BENCH_REHEARSAL_* (closed-loop SLO rehearsal: SHARDS / REPLICATION /
USERS / BASE_QPS / PEAK_QPS / BURST_QPS / THREADS / AUTOSCALE / KILL /
OUT — emits SLO_REPORT.json, see obs/workload.py),
BENCH_BOOTSTRAP_* (KEYS / BASE_ROWS / MULTS / DIM: snapshot-shipped
bootstrap flatness — cold replay-vs-snapshot, elastic 2->4 cutover
with snapshots on/off, HA respawn recovery, each at MULTS x journal),
BENCH_ALS_PRECISION / BENCH_ALS_EXCHANGE (kernel-config A/B),
BENCH_SKIP_QUALITY=1 / BENCH_RMSE_REF_NNZ / BENCH_RMSE_REF_ITERS (ALS
quality anchor), BENCH_SVM_TARGET / BENCH_SVM_REF_ROUNDS / BENCH_SVM_FLIP
(SVM anchor + label noise), BENCH_DETAIL_PATH (sidecar),
BENCH_RECOVER_DEADLINE_S / BENCH_RECOVER_TIMEOUT_S (mid-run recovery).
"""

import contextlib
import json
import os
import sys
import time
import traceback

import numpy as np


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# backend acquisition (retry + degrade, never crash)
# ---------------------------------------------------------------------------

# Probes must round-trip a real jit COMPILE, not just list devices: the
# 2026-08-02 wedge variant answers jax.devices() (backend init succeeds,
# chip listed) while every remote compile hangs indefinitely — a
# devices-level probe passes and the run then hangs in its first
# in-process compile with no timeout.  On a healthy backend the tiny
# matmul adds seconds; on the wedge it converts "hang forever" into the
# probe timeout and a clean degrade.
_PROBE_JIT = (
    "import jax, jax.numpy as jnp;"
    "jax.block_until_ready("
    "jax.jit(lambda x: (x @ x).sum())(jnp.ones((128, 128))));"
)


def acquire_devices():
    """-> (devices, platform, backend_error|None).

    The accelerator backend is probed in a SUBPROCESS with a hard timeout
    first: a hung init (tunnel down — observed to block jax.devices()
    indefinitely rather than raise) must not hang the benchmark.  Probe
    failures retry with backoff on UNAVAILABLE; on final failure the
    benchmark degrades to the CPU backend with the error captured for the
    JSON artifact.  Only after a successful probe does the in-process
    backend initialize."""
    import subprocess

    import jax

    attempts = int(os.environ.get("BENCH_INIT_ATTEMPTS", 4))
    backoff = float(os.environ.get("BENCH_INIT_BACKOFF_S", 10))
    probe_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT_S", 240))
    last_err = None
    hangs = 0
    for i in range(attempts):
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "from flink_ms_tpu.parallel.mesh import honor_platform_env;"
                 "honor_platform_env();"  # the probe must respect an explicit
                 # JAX_PLATFORMS pin exactly like the in-process path will
                 "import jax; p = jax.devices()[0].platform;"
                 + _PROBE_JIT +
                 "print(p)"],
                capture_output=True, text=True, timeout=probe_timeout,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
        except subprocess.TimeoutExpired:
            last_err = f"backend init/compile hung >{probe_timeout:.0f}s"
            hangs += 1
            _log(f"[bench] init attempt {i + 1}/{attempts}: {last_err}")
            if hangs >= 2:
                # a wedged transport hangs (it does not error): two hung
                # probes already cost 2x the probe timeout — degrade now
                # rather than burn the round's wall-clock on more
                break
            continue
        if probe.returncode == 0:
            # healthy backend: in-process init should take the same fast
            # path — but the tunnel can still drop in the gap, so failures
            # here fall through to the retry/degrade policy too
            try:
                devs = jax.devices()
            except RuntimeError as e:
                last_err = f"{type(e).__name__}: {e}"
                _log(f"[bench] in-process init failed after probe: {e}")
                continue
            accel = [d for d in devs if d.platform != "cpu"]
            if accel:
                return accel, accel[0].platform, None
            return devs, "cpu", None
        tail = (probe.stderr or "").strip().splitlines()
        last_err = tail[-1] if tail else f"probe rc={probe.returncode}"
        transient = "UNAVAILABLE" in last_err or "Unable to initialize" in last_err
        _log(f"[bench] backend init attempt {i + 1}/{attempts} failed: {last_err}")
        if not transient:
            break
        if i + 1 < attempts:
            time.sleep(backoff * (1.5 ** i))
    # degrade: the CPU backend registers independently of the accelerator
    # plugin, so it survives an accelerator init failure — but only if no
    # JAX_PLATFORMS pin excludes it (the ambient launcher export is exactly
    # what pins the failed accelerator in the first place).  Crucially the
    # remote plugin's FACTORY must be dropped before the first backend
    # init: jax initializes every registered plugin even for
    # jax.devices("cpu"), and a wedged tunnel HANGS that init rather than
    # erroring — pin_host_backend() is the difference between a degraded
    # CPU artifact and a bench that never returns.
    os.environ.pop("JAX_PLATFORMS", None)
    from flink_ms_tpu.parallel.mesh import pin_host_backend

    pin_host_backend()
    cpu = jax.devices("cpu")
    _log(f"[bench] degrading to CPU backend after: {last_err}")
    return cpu, "cpu", last_err


# ---------------------------------------------------------------------------
# mid-run tunnel recovery (degraded artifact -> accelerator artifact)
# ---------------------------------------------------------------------------

def relay_looks_wedged() -> bool:
    """Cheap (<5 s) classifier for the loopback relay the tunneled chip sits
    behind.  True = relay definitely absent (unconfigured, or TCP connect
    refused), so the expensive jax probe can be skipped; False = worth a
    real probe.  An instant EOF after connect was rounds 2-3's wedge
    fingerprint, but round 3 observed a HEALTHY chip answering jax probes
    behind an EOF-ing relay — so EOF is no longer conclusive and only a
    refused/unconfigured relay short-circuits.  The cost of probing a truly
    wedged tunnel (the probe HANGS to its timeout) is bounded by the
    hang-backoff memo in try_recover_accelerator."""
    import socket

    host = (os.environ.get("PALLAS_AXON_POOL_IPS") or "").split(",")[0].strip()
    if not host:
        return True  # no tunnel configured at all
    port = int(os.environ.get("PALLAS_AXON_RELAY_PORT", 2024))
    try:
        s = socket.create_connection((host, port), timeout=5)
    except OSError:
        return True
    s.close()
    return False


# set to time.time() when a recovery probe HANGS to its timeout (the one
# reliable wedge signature); further probes are skipped for the backoff
# window so a truly wedged tunnel costs one probe timeout per window, not
# one per recovery attempt
_last_probe_hang = 0.0
PROBE_HANG_BACKOFF_S = 900.0


_CHILD_PROC = None  # the in-flight probe/recovery subprocess; the SIGTERM
# emitter must kill it rather than orphan a child holding the chip/tunnel


def _tracked_child(cmd, env, budget, cwd):
    """Popen (not subprocess.run) so a driver-budget SIGTERM can kill an
    in-flight child — a hung jax probe or a full-scale accelerator re-run —
    instead of leaving it contending with whatever the driver does next
    (e.g. queued on-chip measurements).  Raises subprocess.TimeoutExpired
    after killing the child, like subprocess.run would."""
    import subprocess

    global _CHILD_PROC
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True, cwd=cwd)
    _CHILD_PROC = proc
    try:
        out, err = proc.communicate(timeout=budget)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        raise
    finally:
        _CHILD_PROC = None
    return subprocess.CompletedProcess(cmd, proc.returncode, out, err)


def _accel_probe_ok(orig_env: dict, timeout_s: float) -> bool:
    """One subprocess jax probe under the ORIGINAL env (pre-degrade caps and
    pins must not leak in).  True iff a non-cpu backend initializes.  A
    probe that hangs to its timeout records the hang for the backoff memo."""
    import subprocess

    global _last_probe_hang
    try:
        probe = _tracked_child(
            [sys.executable, "-c",
             "from flink_ms_tpu.parallel.mesh import honor_platform_env;"
             "honor_platform_env();"
             "import jax; import sys;"
             "p = jax.devices()[0].platform;"
             + _PROBE_JIT +
             "sys.exit(0 if p != 'cpu' else 1)"],
            orig_env, timeout_s,
            os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        _last_probe_hang = time.time()
        return False
    except Exception:
        return False
    return probe.returncode == 0


ACCEL_SECTIONS = ("als", "svm")  # the only sections that run on the chip


def try_recover_accelerator(result: dict, orig_env: dict, deadline: float,
                            requested_sections=ACCEL_SECTIONS,
                            ignore_hang_backoff: bool = False) -> None:
    """If this run degraded to CPU, check whether the tunnel has come back
    and — if so — re-run the accelerator-bound sections the operator asked
    for (BENCH_SECTIONS ∩ {als, svm}) at full scale in a fresh subprocess,
    merging its JSON over the degraded values.  Called between sections; a
    successful recovery flips degraded -> false.  No-op once recovered,
    when not degraded, past the deadline, or when no accelerator-bound
    section was requested.  ignore_hang_backoff: the end-of-run recovery
    loop probes on its own schedule — the hang memo (which protects the
    between-section path from paying a probe timeout per section) must not
    starve it."""
    import subprocess

    if not result.get("degraded") or result.get("recovered"):
        return
    sections = [s for s in ACCEL_SECTIONS if s in requested_sections]
    if not sections:
        return
    if time.time() > deadline:
        return
    if (not ignore_hang_backoff
            and time.time() - _last_probe_hang < PROBE_HANG_BACKOFF_S):
        return  # a recent probe hung (true wedge signature): don't re-pay
    if relay_looks_wedged():
        return
    _log("[bench] relay answered — probing accelerator for mid-run recovery")
    if not _accel_probe_ok(orig_env, float(
            os.environ.get("BENCH_INIT_TIMEOUT_S", 240))):
        _log("[bench] recovery probe failed; staying degraded")
        return
    budget = float(os.environ.get("BENCH_RECOVER_TIMEOUT_S", 2400))
    # small grace past the deadline only: the artifact line is already out
    # (or imminently will be), so a re-run overrunning the stated recovery
    # budget by minutes would just burn driver wall-clock it can't honor
    budget = max(min(budget, deadline - time.time() + 60), 120)
    _log(f"[bench] accelerator is back — re-running {'+'.join(sections)} "
         f"in a subprocess (budget {budget:.0f}s)")
    env = dict(orig_env)
    env["BENCH_INIT_ATTEMPTS"] = "2"
    try:
        sub = _tracked_child(
            [sys.executable, os.path.abspath(__file__), "--sections-json",
             ",".join(sections)],
            env, budget, os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        result["recovery_error"] = f"recovery subprocess hit {budget:.0f}s cap"
        _log("[bench] " + result["recovery_error"])
        return
    for line in (sub.stderr or "").splitlines():
        _log("[recover] " + line)
    try:
        sub_json = json.loads((sub.stdout or "").strip().splitlines()[-1])
    except Exception:
        result["recovery_error"] = (
            f"recovery rc={sub.returncode}, unparseable stdout"
        )
        _log("[bench] " + result["recovery_error"])
        return
    # acceptance mirrors the normal artifact's section-isolation policy:
    # the HEADLINE must have run on the accelerator; soft per-subsection
    # *_error keys (implicit mode, quality anchor, ...) ride along exactly
    # as they would in a healthy run
    if (sub.returncode != 0 or sub_json.get("platform") == "cpu"
            or sub_json.get("degraded")
            or ("als" in sections and sub_json.get("value") is None)):
        result["recovery_error"] = (
            f"recovery rc={sub.returncode}, "
            f"platform={sub_json.get('platform')}, "
            f"value={sub_json.get('value')}"
        )
        _log("[bench] " + result["recovery_error"])
        return
    # the degraded ALS/SVM keys are overwritten by accelerator values; the
    # serving sections are host-side planes either way, so the artifact's
    # headline platform is the recovered one.  Stale error keys from the
    # degraded attempt must not survive into a recovered artifact.
    result["backend_error_initial"] = result.pop("backend_error", None)
    result.pop("degraded_skipped_config", None)
    for k in [k for k in result
              if k.endswith("_error") and k.startswith(ACCEL_SECTIONS)]:
        del result[k]
    result.update(sub_json)
    result["degraded"] = False
    result["recovered"] = True
    _log("[bench] mid-run recovery succeeded: headline sections re-ran on "
         + str(sub_json.get("platform")))


def final_recovery_loop(result: dict, orig_env: dict, deadline: float,
                        requested_sections=ACCEL_SECTIONS) -> None:
    """End-of-run persistence (VERDICT r3 #1, the third consecutive
    degraded artifact): every section is done, the artifact is degraded,
    and wall-clock remains before the recovery deadline — spend it probing
    for the chip instead of returning early.  Round 3's bench finished
    degraded ~15 min into a wedge that can clear at any time (observed
    outages range from minutes to hours); one hung probe then suppressed
    all further probes for 900 s, which usually outlived the bench.  This
    loop probes on a fixed cadence until the deadline, ignoring the hang
    memo (the cost is bounded: one probe timeout per interval, and the
    bench has nothing else left to do).  BENCH_FINAL_RECOVERY=0 opts out;
    BENCH_RECOVER_PROBE_INTERVAL_S (default 120) sets the idle gap
    between probe attempts."""
    if os.environ.get("BENCH_FINAL_RECOVERY", "1") == "0":
        return
    if not result.get("degraded") or result.get("recovered"):
        return
    if not any(sec in requested_sections for sec in ACCEL_SECTIONS):
        return  # nothing accelerator-bound was asked for: recovery can
        # never fire, so don't idle out the deadline
    # The artifact line is ALREADY emitted by the time this runs (VERDICT
    # r4 #1: round 4 lost the whole artifact to a driver SIGKILL inside
    # this loop), so the loop is pure upside — but still bound it by its
    # own budget so a healthy-driver run doesn't idle out the session:
    # the global recovery deadline (3000 s from start) outlived the
    # round-4 driver budget by at least 1210 s.
    budget = float(os.environ.get("BENCH_FINAL_RECOVERY_BUDGET_S", 900))
    deadline = min(deadline, time.time() + budget)
    interval = float(os.environ.get("BENCH_RECOVER_PROBE_INTERVAL_S", 120))
    attempts = 0
    while (time.time() < deadline and result.get("degraded")
           and not result.get("recovered")):
        attempts += 1
        _log(f"[bench] final recovery loop: attempt {attempts}, "
             f"{deadline - time.time():.0f}s of budget left")
        try:
            try_recover_accelerator(result, orig_env, deadline,
                                    requested_sections,
                                    ignore_hang_backoff=True)
        except Exception:
            _log(traceback.format_exc())
        if result.get("recovered") or time.time() >= deadline:
            break
        time.sleep(min(interval, max(deadline - time.time(), 0)))
    result["final_recovery_attempts"] = attempts


def run_sections_json(sections: str) -> None:
    """`bench.py --sections-json als,svm`: run only the named sections and
    print their FULL merged JSON (one line, stdout) — the recovery
    subprocess entry point.  rc=0 when a backend initialized and the run
    completed; per-subsection *_error keys are soft (same policy as the
    normal artifact) and the CALLER judges the headline keys."""
    real_stdout = sys.stdout
    with contextlib.redirect_stdout(sys.stderr):
        os.environ["BENCH_SECTIONS"] = sections
        result = _run_all(recovery_enabled=False)
    print(json.dumps(result), file=real_stdout, flush=True)


# ---------------------------------------------------------------------------
# roofline model
# ---------------------------------------------------------------------------

# bf16 MXU peak per chip (the systolic-array ceiling MFU is judged against;
# fp32 work lowers to bf16 passes on the MXU, so this is the honest
# denominator).  Keyed by substring of jax device_kind, first match wins.
_PEAK_FLOPS_BY_KIND = (
    ("v6", 918e12),       # Trillium / v6e
    ("v5p", 459e12),
    ("v5 lite", 197e12),  # v5e reports "TPU v5 lite"
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops_per_device(device) -> float:
    env = os.environ.get("BENCH_PEAK_FLOPS")
    if env:
        return float(env)
    kind = getattr(device, "device_kind", "").lower()
    if device.platform != "cpu" and not any(
        sub in kind for sub, _ in _PEAK_FLOPS_BY_KIND
    ):
        # tunneled devices may not report a standard TPU kind string; the
        # launcher exports the generation separately
        kind = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    for sub, peak in _PEAK_FLOPS_BY_KIND:
        if sub in kind:
            return peak
    return 0.0  # unknown (CPU fallback etc.) -> MFU reported as null


def als_flops_per_iter(nnz: int, n_users: int, n_items: int, k: int) -> float:
    """Analytic FLOPs of one full ALS iteration (both half-sweeps).

    Per half-sweep over the opposite-side factors Y:
      assembly  A_u += y yᵀ, b_u += r·y per rating: 2k² + 2k flops per nnz
      solve     per entity: Cholesky k³/3 + two triangular solves 2·2k²
    Both orientations touch every rating once, and every user and item row
    gets one solve per iteration."""
    assembly = 2 * nnz * (2 * k * k + 2 * k)
    solves = (n_users + n_items) * (k ** 3 / 3 + 4 * k * k)
    return float(assembly + solves)


# ---------------------------------------------------------------------------
# ALS section
# ---------------------------------------------------------------------------

def synth_ratings(n_users, n_items, nnz, seed=0, skew=None):
    """Synthetic ratings.  BENCH_SKEW=zipf (or skew="zipf") draws item
    popularity and user activity from heavy-tailed marginals (Zipf-like
    s~1, the real MovieLens-20M shape — wide degree spread stresses the
    kernel's bucket padding); default is uniform (the round-2 recorded
    workload)."""
    rng = np.random.default_rng(seed)
    if skew is None:
        skew = os.environ.get("BENCH_SKEW", "")
    if skew == "zipf":
        # bounded zipf via inverse-CDF over the ranked catalog
        def zipf_draw(n_ids, size, s=1.0):
            w = 1.0 / np.arange(1, n_ids + 1) ** s
            cdf = np.cumsum(w)
            cdf /= cdf[-1]  # exact 1.0 at the end: no out-of-range draw
            return np.searchsorted(cdf, rng.uniform(size=size)).astype(np.int64)

        users = zipf_draw(n_users, nnz, s=0.7)   # user activity: milder tail
        items = zipf_draw(n_items, nnz, s=1.0)   # item popularity: zipf-1
    else:
        users = rng.integers(0, n_users, nnz)
        items = rng.integers(0, n_items, nnz)
    ratings = rng.uniform(1.0, 5.0, nnz)
    return users, items, ratings


def time_fit(mesh, problem, cfg_base, iters, repeats=5):
    """Steady-state sec/iter on the compiled sweep with device-resident
    inputs: same executable (dynamic trip count) timed at 1 iteration and at
    `iters`; the difference isolates per-iter cost from dispatch overhead.
    Host<->device transfer happens once, outside the timed region; every
    timed call ends in a hard value-fetch sync (block_until_ready is not a
    reliable barrier on tunneled backends — utils.profiling.hard_sync).
    Median over `repeats`."""
    import jax.numpy as jnp

    from flink_ms_tpu.ops.als import compile_fit
    from flink_ms_tpu.utils.profiling import hard_sync

    iters = max(iters, 2)  # need two points to isolate per-iter cost
    fit_fn, dev_args = compile_fit(problem, cfg_base, mesh)

    def run(trip):
        t0 = time.time()
        uf, itf = fit_fn(jnp.asarray(trip, jnp.int32), *dev_args)
        hard_sync(uf)
        return time.time() - t0

    # same executable for every trip count (dynamic while_loop bound), so
    # amplify until the timed region dwarfs dispatch noise (>= 0.5 s)
    run(1), run(iters)  # compile + warmup
    while run(iters) < 0.5 and iters < 20_000:
        iters *= 4
    samples = []
    for _ in range(repeats):
        t1 = run(1)
        tn = run(iters)
        samples.append(max((tn - t1) / (iters - 1), 1e-9))
    samples.sort()
    return samples[len(samples) // 2]


def run_als_section(devices, platform, small: bool) -> dict:
    import jax

    from flink_ms_tpu.ops.als import (ALSConfig,
                                      _exchange_plan as _exchange_plan_fn,
                                      prepare_blocked, resolve_exchange,
                                      resolve_solver)
    from flink_ms_tpu.parallel.mesh import make_mesh

    n_users = int(os.environ.get("BENCH_USERS", 20_000 if small else 138_493))
    n_items = int(os.environ.get("BENCH_ITEMS", 2_000 if small else 26_744))
    nnz = int(os.environ.get("BENCH_NNZ", 500_000 if small else 20_000_000))
    rank = int(os.environ.get("BENCH_RANK", 16 if small else 50))
    iters = int(os.environ.get("BENCH_ITERS", 3 if small else 5))

    skew = os.environ.get("BENCH_SKEW", "") or "uniform"
    users, items, ratings = synth_ratings(n_users, n_items, nnz)
    # kernel-config A/B knobs (the solver knob is FLINK_MS_ALS_SOLVER, read
    # inside the kernel): the on-chip sweep flips these per run, and the
    # quality anchor inherits them so a flipped default is convergence-
    # checked in the same artifact that times it
    exch_env = os.environ.get("BENCH_ALS_EXCHANGE") or "auto"
    if exch_env.lower() in ("f32", "float32", "none", "full"):
        exch_env = None  # explicit full precision (jnp.dtype("f32") would
        # otherwise fail at trace time deep inside the sweep)
    elif exch_env.lower() == "bf16":
        exch_env = "bfloat16"
    cfg = ALSConfig(
        num_factors=rank, iterations=1, lambda_=0.1, seed=42,
        assembly_precision=os.environ.get("BENCH_ALS_PRECISION", "highest"),
        exchange_dtype=exch_env,
    )
    mesh = make_mesh(devices=devices)
    _log(f"[bench] ALS devices: {devices}, nnz={nnz}, rank={rank}")

    t0 = time.time()
    problem = prepare_blocked(users, items, ratings, mesh.devices.size)
    _log(f"[bench] prepare_blocked: {time.time() - t0:.1f}s")

    sec_per_iter = time_fit(mesh, problem, cfg, iters)
    _log(f"[bench] {platform} steady-state: {sec_per_iter:.3f} s/iter")

    flops = als_flops_per_iter(nnz, n_users, n_items, rank)
    peak = peak_flops_per_device(devices[0]) * len(devices)
    mfu = (flops / sec_per_iter) / peak if peak > 0 else None
    if mfu is not None:
        _log(f"[bench] {flops / 1e9:.1f} GFLOP/iter -> "
             f"{flops / sec_per_iter / 1e12:.2f} TFLOP/s, MFU {mfu:.4f}")

    baseline_env = os.environ.get("BENCH_BASELINE_SEC_PER_ITER")
    if baseline_env:
        baseline = float(baseline_env)
    elif os.environ.get("BENCH_SKIP_CPU") == "1" or platform == "cpu":
        baseline = sec_per_iter  # vs_baseline = 1.0, no comparison available
    else:
        # CPU stand-in baseline at reduced nnz, scaled linearly to full nnz
        cpu_nnz = min(nnz, 2_000_000)
        cpu_dev = jax.devices("cpu")
        cpu_mesh = make_mesh(devices=cpu_dev[:1])
        cu, ci, cr = users[:cpu_nnz], items[:cpu_nnz], ratings[:cpu_nnz]
        cpu_problem = prepare_blocked(cu, ci, cr, 1)
        cpu_spi = time_fit(cpu_mesh, cpu_problem, cfg, 2, repeats=3)
        baseline = cpu_spi * (nnz / cpu_nnz)
        _log(
            f"[bench] CPU stand-in: {cpu_spi:.3f} s/iter @ {cpu_nnz} nnz "
            f"-> scaled {baseline:.3f} s/iter @ {nnz}"
        )

    out = {
        "metric": "als_ml20m_sec_per_iter" if not small else "als_small_sec_per_iter",
        "value": round(sec_per_iter, 6),
        "unit": "s/iter",
        "vs_baseline": round(baseline / sec_per_iter, 3),
        "mfu": round(mfu, 5) if mfu is not None else None,
        "als_flops_per_iter": flops,
        "als_tflops_per_sec": round(flops / sec_per_iter / 1e12, 3),
        "als_nnz": nnz,
        "als_rank": rank,
        "workload_skew": skew,
        # kernel config forensics: which solver/precision/ladder produced
        # this number (env-driven knobs, baked in at trace time)
        "als_solver": resolve_solver(platform),
        "als_assembly_precision": cfg.assembly_precision,
        "als_bucket_ratio": os.environ.get("FLINK_MS_ALS_BUCKET_RATIO", "1.5"),
        "als_fused": os.environ.get("FLINK_MS_ALS_FUSED", "0"),
        "als_exchange_dtype": resolve_exchange(cfg.exchange_dtype, platform) or "f32",
        # round 4: per-half-sweep exchange plan (routed all_to_all vs
        # gather) and the fused-assembly knob
        "als_exchange_mode": {
            name: ("routed" if r is not None else "gather")
            for name, r in _exchange_plan_fn(problem, len(devices)).items()
        },
        "als_assembly": os.environ.get("FLINK_MS_ALS_ASSEMBLY", "auto"),
    }

    # BASELINE.json config "als-ms implicit-feedback ALS (confidence-
    # weighted) on MovieLens-20M": same problem layout, HKV mode (psum'd
    # Gramian + confidence-weighted assembly).  Skipped in BENCH_SMALL
    # sanity mode — the key names the ML-20M config and the extra timed
    # section would double the quick run's wall-clock.
    if not small:
        try:
            import dataclasses as _dc

            cfg_imp = _dc.replace(cfg, implicit=True, alpha=40.0)
            spi_imp = time_fit(mesh, problem, cfg_imp, iters)
            out["als_implicit_sec_per_iter"] = round(spi_imp, 6)
            _log(f"[bench] implicit mode: {spi_imp:.3f} s/iter")
        except Exception:
            _log(traceback.format_exc())
            out["als_implicit_error"] = traceback.format_exc(limit=3)

    # exchange-dtype A/B (accelerator runs only, BENCH_ALS_BF16_AB=0 to
    # skip): time the OPPOSITE exchange dtype of whatever the timed config
    # resolved to — with the bf16-on-TPU default this records the f32
    # comparison (and under BENCH_ALS_EXCHANGE=bfloat16... the reverse),
    # so every chip artifact carries both sides of the default-flip
    # evidence; the quality anchor records the matching RMSE deltas
    if (not small and platform != "cpu"
            and os.environ.get("BENCH_ALS_BF16_AB", "1") != "0"):
        try:
            import dataclasses as _dc

            resolved = resolve_exchange(cfg.exchange_dtype, platform)
            alt = None if resolved else "bfloat16"
            alt_name = "f32" if alt is None else "bf16"
            cfg_alt = _dc.replace(cfg, exchange_dtype=alt)
            spi_alt = time_fit(mesh, problem, cfg_alt, max(2, iters - 2))
            out[f"als_{alt_name}_sec_per_iter"] = round(spi_alt, 6)
            _log(f"[bench] {alt_name} exchange variant: {spi_alt:.3f} "
                 f"s/iter (timed default: {sec_per_iter:.3f})")
        except Exception:
            _log(traceback.format_exc())
            out["als_exchange_ab_error"] = traceback.format_exc(limit=3)

    # quality anchor: the timed config's convergence, full scale + parity
    # delta vs the f64 reference (skippable: BENCH_SKIP_QUALITY=1)
    if os.environ.get("BENCH_SKIP_QUALITY") != "1":
        try:
            out.update(als_quality_anchor(
                mesh, problem, users, items, ratings, cfg, iters))
        except Exception:
            _log(traceback.format_exc())
            out["als_quality_error"] = traceback.format_exc(limit=3)

    # BASELINE.json config "flink-als explicit ALS rank=10 on
    # MovieLens-100K (single-node CPU)": the reference's own smallest
    # config shape, timed on one host-CPU device as the single-node
    # reference point
    if not small and os.environ.get("BENCH_SKIP_CPU") != "1":
        try:
            # always uniform: this key mirrors the fixed BASELINE.json
            # reference shape regardless of BENCH_SKEW
            mu, mi, mr = synth_ratings(943, 1_682, 100_000, seed=1,
                                       skew="uniform")
            cfg100 = ALSConfig(num_factors=10, iterations=1, lambda_=0.1)
            cpu_mesh = make_mesh(devices=jax.devices("cpu")[:1])
            p100 = prepare_blocked(mu, mi, mr, 1)
            spi100 = time_fit(cpu_mesh, p100, cfg100, 3, repeats=3)
            out["als_ml100k_cpu_sec_per_iter"] = round(spi100, 6)
            _log(f"[bench] ML-100K rank-10 single-node CPU: {spi100:.4f} s/iter")
        except Exception:
            _log(traceback.format_exc())
            out["als_ml100k_error"] = traceback.format_exc(limit=3)

    return out


# ---------------------------------------------------------------------------
# ALS quality anchor (VERDICT r3 #3): the north star is faster *at identical
# RMSE* — record the timed config's train RMSE, and its delta vs a float64
# reference solve on the same data + init at a capped parity scale
# ---------------------------------------------------------------------------

def run_rmse_ref(npz_path: str) -> None:
    """`bench.py --rmse-ref problem.npz`: float64 CPU reference fit.

    Runs in a subprocess because float64 needs jax_enable_x64, which must
    not leak into the benchmark process (it changes promotion semantics
    everywhere).  The caller sets JAX_ENABLE_X64=1, JAX_PLATFORMS=cpu and
    blanks the tunnel env.  Prints one JSON line {"rmse_ref": x}."""
    import jax
    import jax.numpy as jnp

    from flink_ms_tpu.ops.als import ALSConfig, als_fit, rmse
    from flink_ms_tpu.parallel.mesh import make_mesh, pin_host_backend

    pin_host_backend()
    assert jax.config.jax_enable_x64, "--rmse-ref requires JAX_ENABLE_X64=1"
    d = np.load(npz_path)
    cfg = ALSConfig(
        num_factors=int(d["k"]), iterations=int(d["iters"]),
        lambda_=float(d["lam"]), dtype=jnp.float64,
        assembly_precision="highest", exchange_dtype=None,
    )
    os.environ["FLINK_MS_ALS_SOLVER"] = "unrolled"  # the spec-tested solver
    mesh = make_mesh(devices=jax.devices("cpu")[:1])
    model = als_fit(
        d["users"], d["items"], d["ratings"], cfg, mesh,
        init=(d["u0"].astype(np.float64), d["i0"].astype(np.float64)),
    )
    val = rmse(model, d["users"], d["items"], d["ratings"])
    print(json.dumps({"rmse_ref": val}), flush=True)


def als_quality_anchor(mesh, problem, users, items, ratings, cfg_base,
                       iters: int) -> dict:
    """-> {als_rmse_at_iters, als_rmse_ref_delta, ...}.

    als_rmse_at_iters: train RMSE of the TIMED configuration after the
    timed iteration count at full scale — the number that would move if a
    solver/precision/exchange default silently regressed convergence.

    als_rmse_ref_delta: relative RMSE gap, bench config vs the float64
    reference solve (same data slice, same init, equal iterations) at a
    capped parity scale (BENCH_RMSE_REF_NNZ; a full-scale f64 CPU fit
    would cost the round minutes for no extra signal)."""
    import dataclasses
    import subprocess
    import tempfile

    from flink_ms_tpu.ops.als import ALSConfig, als_fit, prepare_blocked, rmse

    out = {}
    k = cfg_base.num_factors
    t0 = time.time()
    cfg_n = dataclasses.replace(cfg_base, iterations=iters)
    model = als_fit(users, items, ratings, cfg_n, mesh, problem=problem)
    out["als_rmse_at_iters"] = round(rmse(model, users, items, ratings), 6)
    out["als_rmse_iters"] = iters
    _log(f"[bench] train RMSE after {iters} iters: "
         f"{out['als_rmse_at_iters']} ({time.time() - t0:.1f}s)")

    if os.environ.get("BENCH_SKIP_CPU") == "1":
        return out
    ref_nnz = min(int(os.environ.get("BENCH_RMSE_REF_NNZ", 1_000_000)),
                  len(ratings))
    iters_p = min(iters, int(os.environ.get("BENCH_RMSE_REF_ITERS", 3)))
    ru, ri, rr = users[:ref_nnz], items[:ref_nnz], ratings[:ref_nnz]
    p_bench = prepare_blocked(ru, ri, rr, mesh.devices.size)
    rng = np.random.default_rng(cfg_base.seed)
    init = (0.1 * rng.standard_normal((p_bench.n_users, k)),
            0.1 * rng.standard_normal((p_bench.n_items, k)))
    cfg_p = dataclasses.replace(cfg_n, iterations=iters_p)
    t0 = time.time()
    m_bench = als_fit(ru, ri, rr, cfg_p, mesh, problem=p_bench, init=init)
    rmse_bench = rmse(m_bench, ru, ri, rr)
    _log(f"[bench] parity fit (bench cfg, {ref_nnz} nnz, {iters_p} iters): "
         f"RMSE {rmse_bench:.6f} ({time.time() - t0:.1f}s)")

    with tempfile.TemporaryDirectory(prefix="bench_rmse_") as td:
        npz = os.path.join(td, "problem.npz")
        np.savez(npz, users=ru, items=ri, ratings=rr, u0=init[0], i0=init[1],
                 k=k, lam=cfg_base.lambda_, iters=iters_p)
        env = dict(os.environ)
        env.update(JAX_ENABLE_X64="1", JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="")  # host-pinned: the reference
        # solve must complete even while the accelerator tunnel is wedged
        t0 = time.time()
        sub = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--rmse-ref", npz],
            capture_output=True, text=True, env=env,
            timeout=float(os.environ.get("BENCH_RMSE_REF_TIMEOUT_S", 900)),
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    if sub.returncode != 0:
        raise RuntimeError(
            f"rmse-ref subprocess rc={sub.returncode}: {sub.stderr[-800:]}"
        )
    rmse_ref = json.loads(sub.stdout.strip().splitlines()[-1])["rmse_ref"]
    out["als_rmse_ref_delta"] = round((rmse_bench - rmse_ref) / rmse_ref, 6)
    out["als_rmse_ref_nnz"] = ref_nnz
    _log(f"[bench] f64 reference RMSE {rmse_ref:.6f} "
         f"({time.time() - t0:.1f}s) -> delta {out['als_rmse_ref_delta']}")

    # exchange-dtype quality side of the A/B (mirrors run_als_section's
    # speed A/B): the same parity fit with the OPPOSITE exchange dtype of
    # whatever the timed config resolved to, against the SAME f64
    # reference — the delta pair is the evidence a default flip needs
    platform_q = mesh.devices.flat[0].platform
    if (platform_q != "cpu"
            and os.environ.get("BENCH_ALS_BF16_AB", "1") != "0"):
        try:
            from flink_ms_tpu.ops.als import resolve_exchange

            resolved = resolve_exchange(cfg_base.exchange_dtype, platform_q)
            alt = None if resolved else "bfloat16"
            alt_name = "f32" if alt is None else "bf16"
            cfg_alt = dataclasses.replace(cfg_p, exchange_dtype=alt)
            m_alt = als_fit(ru, ri, rr, cfg_alt, mesh, problem=p_bench,
                            init=init)
            delta_alt = (rmse(m_alt, ru, ri, rr) - rmse_ref) / rmse_ref
            out[f"als_{alt_name}_rmse_ref_delta"] = round(delta_alt, 6)
            _log(f"[bench] {alt_name}-exchange parity fit -> delta "
                 f"{out[f'als_{alt_name}_rmse_ref_delta']}")
        except Exception:
            _log(traceback.format_exc())
            out["als_exchange_ab_quality_error"] = traceback.format_exc(
                limit=3)
    return out


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------

_DETAIL_PATH = os.environ.get("BENCH_DETAIL_PATH") or os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_DETAIL.json"
)

# stdout-artifact keys, in emit order.  Everything else lives only in the
# sidecar.  Budget: the driver's observed stdout-tail window is ~2 KB; this
# set renders well under half of it at realistic values.
_COMPACT_KEYS = (
    "metric", "value", "unit", "vs_baseline", "mfu", "platform", "n_devices",
    "als_nnz", "als_rank", "als_tflops_per_sec", "als_solver",
    "als_rmse_at_iters", "als_rmse_ref_delta",
    "svm_rcv1_sec_per_round", "svm_rcv1_vs_baseline", "svm_secs_to_target",
    "serving_mget_p50_ms", "serving_topk_p50_ms", "serving_shard_mget_p50_ms",
    "serving_topk_batched_c64_qps", "serving_topk_batched_speedup_c64",
    "serving_ingest_columnar_rows_per_sec", "serving_ingest_speedup",
    "serving_ingest_columnar_prop_p99_ms",
    "serving_ha_r2_availability", "serving_ha_r2_recovery_s",
    "serving_elastic_cutover_s", "serving_elastic_during_p99_ms",
    "serving_elastic_errors",
    "serving_native_get_b2_c64_p50_us", "serving_native_get_b2_speedup_c64",
    "serving_native_topk_b2_speedup_c64", "serving_native_cutover_errors",
    "serving_ann_sharded_speedup", "serving_ann_ivf_speedup",
    "serving_ann_recall_at_100", "serving_ann_gate_recall_ok",
    "serving_watch_overhead_pct", "serving_watch_mse_abs_diff",
    "serving_watch_drift_fired", "serving_watch_detect_s",
    "serving_watch_unattributed_page",
    "serving_autopilot_retrains", "serving_autopilot_win_rate",
    "serving_autopilot_mse_monotone", "serving_autopilot_warm_beats_cold",
    "serving_autopilot_rollback_detect_s",
    "serving_forensics_stage1", "serving_forensics_stage1_share",
    "serving_forensics_diff_ok", "serving_forensics_alert_fired",
    "serving_forensics_exemplar_tids",
    "serving_forensics_incident_names_stage", "serving_forensics_ok",
    "serving_geo_repl_lag_p50_ms", "serving_geo_repl_lag_p99_ms",
    "serving_geo_stale_reads", "serving_geo_staleness_max_s",
    "serving_geo_failover_ms", "serving_geo_errors", "serving_geo_ok",
    "serving_edge_overhead_p99_us", "serving_edge_coalesce_hit_rate",
    "serving_edge_hedge_p999_ratio", "serving_edge_idle_kb_per_conn",
    "serving_edge_core_starved", "serving_edge_errors", "serving_edge_ok",
    "serving_profiler_top_frame", "serving_profiler_top_share",
    "serving_profiler_diff_ok", "serving_profiler_alert_fired",
    "serving_profiler_page_names_frame", "serving_profiler_replicas",
    "serving_profiler_native_stacks", "serving_profiler_ok",
    "serving_push_latency_p99_ms", "serving_push_fanout_amplification",
    "serving_push_selectivity", "serving_push_core_starved",
    "serving_push_ok",
    "mse_live_value", "degraded", "recovered", "terminated", "crash_error",
    "watchdog", "host_ref_ms",
)


def emit_artifact(result: dict, sidecar: bool = True) -> str:
    """Write the full result to the BENCH_DETAIL.json sidecar and return the
    compact single-line JSON for stdout (see module docstring for why the
    stdout artifact must stay small).  sidecar=False skips the detail
    write — the watchdog thread emits snapshots while the main thread may
    be mid-emit itself, and two writers would interleave in the file."""
    if not sidecar:
        # a snapshot emission writes no sidecar — claiming the detail file
        # here would point the driver at stale (or absent) contents from a
        # previous run (r5 advisor); null says "no sidecar for this line"
        result.setdefault("detail", None)
    else:
        try:
            with open(_DETAIL_PATH, "w") as f:
                json.dump(result, f, indent=1, sort_keys=True)
                f.write("\n")
            result["detail"] = os.path.basename(_DETAIL_PATH)
        except OSError as e:
            result["detail"] = f"unwritable: {e}"
    compact = {k: result[k] for k in _COMPACT_KEYS if k in result}
    err_keys = sorted(
        k for k in result
        if k.endswith("_error")
        and k not in ("backend_error", "crash_error")  # each surfaced as
        # its own compact key — neither is a section failure
    )
    if err_keys:
        compact["section_errors"] = err_keys
    if result.get("backend_error"):
        compact["backend_error"] = str(result["backend_error"])[:100]
    compact["detail"] = result["detail"]
    line = json.dumps(compact)
    if len(line) > 1800:  # belt-and-braces: never outgrow the tail window
        for k in ("section_errors", "backend_error", "als_solver",
                  "serving_shard_mget_p50_ms", "serving_topk_p50_ms"):
            compact.pop(k, None)
        line = json.dumps(compact)
    return line


_CURRENT_RESULT: dict = {}
_RECOVERY_CTX = None  # (orig_env, deadline, sections) from _run_all -> main
_ARTIFACT_PRINTED = None  # threading.Event set at the first real stdout
# emission; the watchdog thread stops deferring to it from then on
_PRINT_LOCK = None  # serializes watchdog-vs-main artifact prints so a
# snapshot can never land AFTER the real line (last-line-wins)


def _ensure_headline_keys(result: dict) -> None:
    """Every emitted artifact — normal, crashed, or SIGTERM'd — must carry
    the four headline keys the driver contract names."""
    result.setdefault("metric", "als_ml20m_sec_per_iter")
    result.setdefault("value", None)
    result.setdefault("unit", "s/iter")
    result.setdefault("vs_baseline", None)


def _install_sigterm_emitter(real_stdout) -> None:
    """timeout(1) delivers SIGTERM before escalating to SIGKILL: emit the
    best artifact we have RIGHT NOW so a driver-budget kill can never
    yield parsed=null again (round 4: BENCH_r04.json rc=124, no line)."""
    import signal

    def _emit_and_die(signum, frame):
        proc = _CHILD_PROC
        if proc is not None:
            try:
                proc.kill()
            except Exception:
                pass
        res = dict(_CURRENT_RESULT)
        res["terminated"] = True
        _ensure_headline_keys(res)
        try:
            line = emit_artifact(res)
        except Exception:
            line = json.dumps({
                "metric": "als_ml20m_sec_per_iter", "value": None,
                "unit": "s/iter", "vs_baseline": None, "terminated": True,
            })
        # serialize against a watchdog snapshot mid-print — but only
        # try-acquire: the handler may be interrupting the very thread
        # that holds the lock, and blocking here would deadlock a dying
        # process (r5 advisor).  Either way set _ARTIFACT_PRINTED BEFORE
        # printing so a watchdog wake-up between our print and _exit
        # cannot emit a snapshot AFTER the terminal line (last-line-wins).
        lock, printed = _PRINT_LOCK, _ARTIFACT_PRINTED
        acquired = lock.acquire(blocking=False) if lock is not None else False
        try:
            if printed is not None:
                printed.set()
            try:
                print(line, file=real_stdout, flush=True)
            except Exception:  # reentrant buffered-IO write mid-print: the
                # raw fd write cannot collide with the buffered layer
                try:
                    # leading newline: the interrupted print may have
                    # flushed a partial line; never concatenate onto it
                    os.write(real_stdout.fileno(),
                             ("\n" + line + "\n").encode())
                except Exception:
                    pass
        finally:
            if acquired:
                lock.release()
        os._exit(124)

    try:
        signal.signal(signal.SIGTERM, _emit_and_die)
    except (ValueError, OSError):
        pass  # non-main thread / exotic host: emission-before-loop still holds


def _start_watchdog(real_stdout) -> None:
    """Last line of defense for the driver artifact: a hung IN-PROCESS
    XLA compile blocks the main thread inside a C call, so the SIGTERM
    emitter never runs (CPython defers signal handlers to the bytecode
    loop) and a driver kill would yield parsed=null — the exact r4
    failure, reachable even with compile-level probes if the tunnel
    wedges in the gap between probe and section.  A daemon thread can
    still write stdout, so after BENCH_WATCHDOG_S it emits the live
    partial snapshot and re-emits every BENCH_WATCHDOG_REEMIT_S until
    the real artifact prints.  Premature firing is harmless: the driver
    takes the LAST parseable line, and the normal end-of-run emission
    (or late-recovery re-print) always lands after the watchdog stops."""
    import threading

    global _ARTIFACT_PRINTED, _PRINT_LOCK
    _ARTIFACT_PRINTED = threading.Event()
    _PRINT_LOCK = threading.Lock()
    delay = float(os.environ.get("BENCH_WATCHDOG_S", 1500))
    if delay <= 0:
        return
    reemit = float(os.environ.get("BENCH_WATCHDOG_REEMIT_S", 600))
    printed, lock = _ARTIFACT_PRINTED, _PRINT_LOCK

    def _run():
        if printed.wait(delay):
            return
        while not printed.is_set():
            res = dict(_CURRENT_RESULT)
            res["watchdog"] = True
            res.setdefault("degraded", True)
            res.setdefault("backend_error",
                           "watchdog: run still in flight at deadline")
            _ensure_headline_keys(res)
            try:
                line = emit_artifact(res, sidecar=False)
            except Exception:
                line = json.dumps({
                    "metric": "als_ml20m_sec_per_iter", "value": None,
                    "unit": "s/iter", "vs_baseline": None,
                    "watchdog": True, "degraded": True,
                })
            try:
                with lock:
                    if not printed.is_set():
                        print(line, file=real_stdout, flush=True)
            except Exception:
                pass
            printed.wait(reemit)

    threading.Thread(target=_run, daemon=True,
                     name="artifact-watchdog").start()


def main() -> None:
    # stdout is the artifact: exactly ONE compact JSON line (re-printed at
    # most once on late recovery — the LAST line wins).  Section code
    # calls CLI mains in-process (producer, SGD, MSE) whose job summaries
    # print to stdout — reroute everything but the artifact lines to stderr.
    real_stdout = sys.stdout
    _install_sigterm_emitter(real_stdout)
    _start_watchdog(real_stdout)
    crashed = False
    with contextlib.redirect_stdout(sys.stderr):
        try:
            result = _run_all()
        except Exception as e:  # even a harness crash must leave a line
            _log(traceback.format_exc())
            crashed = True
            result = dict(_CURRENT_RESULT)
            # clamp like backend_error: an XLA traceback str() can be
            # several KB and would outgrow the driver's stdout-tail window
            result["crash_error"] = f"{type(e).__name__}: {e}"[:100]
            _ensure_headline_keys(result)
        ctx = _RECOVERY_CTX
        line = emit_artifact(result)
    # Un-losable artifact (VERDICT r4 #1): print BEFORE any end-of-run
    # recovery probing, so a driver kill mid-loop still leaves a parseable
    # line.  Recovery, if it fires, upgrades the numbers and re-prints.
    if _PRINT_LOCK is not None:
        with _PRINT_LOCK:
            _ARTIFACT_PRINTED.set()  # under the lock: a watchdog snapshot
            # can never land AFTER this real line (last-line-wins)
            print(line, file=real_stdout, flush=True)
    else:
        print(line, file=real_stdout, flush=True)
    if crashed:
        sys.exit(1)  # loud rc, but the line above still parses
    if ctx is None:
        return
    orig_env, deadline, sections = ctx
    already_recovered = bool(result.get("recovered"))
    with contextlib.redirect_stdout(sys.stderr):
        try:
            final_recovery_loop(result, orig_env, deadline, sections)
        except Exception:
            _log(traceback.format_exc())
        recovered_late = result.get("recovered") and not already_recovered
        # refresh the sidecar either way so the loop's diagnostics
        # (final_recovery_attempts, last recovery_error) survive an
        # unrecovered exhaustion; stdout gets a second line ONLY on late
        # recovery (VERDICT r4 #1 prescribes re-print + last-line-wins)
        line = emit_artifact(result)
    if recovered_late:
        print(line, file=real_stdout, flush=True)


def host_reference_ms() -> float:
    """Fixed host workload timed into every artifact (VERDICT r4 weak #7:
    closed-loop SGD throughput halved between rounds with nothing in the
    artifact separating a busier host from a regression).  One 1024x1024
    f32 matmul plus a 200k-step Python loop — BLAS and interpreter speed
    in one number; median of 5.  Cross-round throughput comparisons
    divide by the ratio of the two artifacts' values."""
    rng = np.random.default_rng(0)
    a = rng.standard_normal((1024, 1024)).astype(np.float32)
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        float((a @ a).sum())
        acc = 0
        for i in range(200_000):
            acc += i & 7
        times.append((time.perf_counter() - t0) * 1000.0)
    times.sort()
    return round(times[2], 2)


def _run_all(recovery_enabled: bool = True) -> dict:
    global _CURRENT_RESULT, _RECOVERY_CTX
    _RECOVERY_CTX = None
    small = os.environ.get("BENCH_SMALL") == "1"
    sections = os.environ.get(
        "BENCH_SECTIONS",
        "als,svm,serving,svmserve,serving_ingest,serving_ha,"
        "serving_elastic,serving_rehearsal,serving_bootstrap,"
        "serving_native,serving_update_plane,serving_rollout,serving_ann,"
        "serving_watch,serving_autopilot,serving_forensics,serving_geo,"
        "serving_arena,serving_arena_ingest,serving_edge,serving_profiler,"
        "serving_push"
    ).split(",")
    result: dict = {}
    _CURRENT_RESULT = result  # the SIGTERM emitter's view of progress
    # the pre-degrade environment: recovery subprocesses must see the
    # operator's config, not the caps/pins the degrade path writes below
    orig_env = dict(os.environ)
    deadline = time.time() + float(
        os.environ.get("BENCH_RECOVER_DEADLINE_S", 3000)
    )

    from flink_ms_tpu.parallel.mesh import honor_platform_env

    honor_platform_env()

    try:
        devices, platform, backend_error = acquire_devices()
    except Exception as e:
        _log(traceback.format_exc())
        result["degraded"] = True
        result["backend_error"] = f"no backend at all: {e}"
        _ensure_headline_keys(result)
        return result
    result["platform"] = platform
    result["n_devices"] = len(devices)
    result["device_kind"] = getattr(devices[0], "device_kind", "unknown")
    try:
        result["host_ref_ms"] = host_reference_ms()
        _log(f"[bench] host reference op: {result['host_ref_ms']} ms")
    except Exception:
        _log(traceback.format_exc())
    if backend_error:
        result["backend_error"] = backend_error
        result["degraded"] = True
        if platform == "cpu" and not small:
            # degraded artifact: cap the DEFAULT full-scale ALS config so
            # the CPU fallback finishes in minutes, not the better part
            # of an hour (explicit BENCH_* env still wins; small mode is
            # already small).  The config this run therefore did NOT
            # measure is recorded explicitly — a degraded artifact must
            # name the question it failed to answer, not imply it.
            result["degraded_skipped_config"] = {
                "als_nnz": int(os.environ.get("BENCH_NNZ", 20_000_000)),
                "als_iters": int(os.environ.get("BENCH_ITERS", 5)),
                "platform_wanted": orig_env.get("JAX_PLATFORMS", "axon"),
            }
            os.environ.setdefault("BENCH_NNZ", "2000000")
            os.environ.setdefault("BENCH_ITERS", "2")
            # the quality anchors cost rounds/iters too — cap their CPU
            # budget the same way (explicit env still wins)
            os.environ.setdefault("BENCH_RMSE_REF_NNZ", "500000")
            os.environ.setdefault("BENCH_SVM_REF_ROUNDS", "20")

    try:
        if "als" in sections:
            result.update(run_als_section(devices, platform, small))
    except Exception:
        _log(traceback.format_exc())
        result["als_error"] = traceback.format_exc(limit=3)

    # every extra section degrades independently: a failure records its
    # <name>_error key without costing the others their metrics.  Between
    # sections a degraded run re-probes the tunnel (cheap) and re-runs the
    # accelerator-bound sections on recovery.
    extra = (
        ("svm", "run_svm_section", lambda f: f(devices, platform, small)),
        ("serving", "run_serving_section", lambda f: f(small)),
        ("svmserve", "run_svm_serving_section", lambda f: f(small)),
        ("serving_ingest", "run_serving_ingest_section", lambda f: f(small)),
        ("serving_ha", "run_serving_ha_section", lambda f: f(small)),
        ("serving_elastic", "run_serving_elastic_section",
         lambda f: f(small)),
        ("serving_rehearsal", "run_serving_rehearsal_section",
         lambda f: f(small)),
        ("serving_bootstrap", "run_serving_bootstrap_section",
         lambda f: f(small)),
        ("serving_native", "run_serving_native_section",
         lambda f: f(small)),
        ("serving_update_plane", "run_serving_update_plane_section",
         lambda f: f(small)),
        ("serving_rollout", "run_serving_rollout_section",
         lambda f: f(small)),
        ("serving_ann", "run_serving_ann_section",
         lambda f: f(small)),
        ("serving_watch", "run_serving_watch_section",
         lambda f: f(small)),
        ("serving_autopilot", "run_serving_autopilot_section",
         lambda f: f(small)),
        ("serving_forensics", "run_serving_forensics_section",
         lambda f: f(small)),
        ("serving_geo", "run_serving_geo_section",
         lambda f: f(small)),
        ("serving_arena", "run_serving_arena_section",
         lambda f: f(small)),
        ("serving_arena_ingest", "run_serving_arena_ingest_section",
         lambda f: f(small)),
        ("serving_edge", "run_serving_edge_section",
         lambda f: f(small)),
        ("serving_profiler", "run_serving_profiler_section",
         lambda f: f(small)),
        ("serving_push", "run_serving_push_section",
         lambda f: f(small)),
    )
    for name, fn_name, call in extra:
        if recovery_enabled:
            try:
                try_recover_accelerator(result, orig_env, deadline, sections)
            except Exception:
                _log(traceback.format_exc())
        if name not in sections:
            continue
        if name == "svm" and result.get("recovered"):
            continue  # already re-ran on the accelerator
        try:
            import bench_sections
        except ImportError:
            result[f"{name}_error"] = "bench_sections module not available"
            continue
        fn = getattr(bench_sections, fn_name, None)
        if fn is None:
            result[f"{name}_error"] = f"bench_sections.{fn_name} missing"
            continue
        # bracket the section with registry snapshots: the sidecar detail
        # record carries what the section actually exercised (counters
        # moved, histogram mass added) next to its latency numbers.
        # In-process series only — sections that spawn worker SUBPROCESSES
        # contribute their client-side half here; worker-side series are
        # scraped live via obs.scrape, not captured post-mortem.
        snap_before = None
        try:
            from flink_ms_tpu.obs.metrics import diff_snapshots, get_registry

            snap_before = get_registry().snapshot()
        except Exception:
            pass
        try:
            result.update(call(fn))
        except Exception:
            _log(traceback.format_exc())
            result[f"{name}_error"] = traceback.format_exc(limit=3)
        if snap_before is not None:
            try:
                delta = diff_snapshots(
                    snap_before, get_registry().snapshot())
                if any(delta.values()):
                    result[f"{name}_metrics_delta"] = delta
            except Exception:
                pass
    if recovery_enabled:
        try:
            try_recover_accelerator(result, orig_env, deadline, sections)
        except Exception:
            _log(traceback.format_exc())
        # End-of-run recovery probing is the CALLER's job (main), run
        # AFTER the artifact line is on stdout — hand over the context
        # out-of-band (a ctx key inside `result` would ride os.environ
        # into any emitted artifact).  Round 4 lost the entire artifact
        # to a driver SIGKILL inside the final loop because it ran
        # before emission.
        _RECOVERY_CTX = (orig_env, deadline, sections)

    # headline section failed: still emit a valid, loud artifact
    _ensure_headline_keys(result)

    return result


if __name__ == "__main__":
    if "--rmse-ref" in sys.argv:
        run_rmse_ref(sys.argv[sys.argv.index("--rmse-ref") + 1])
    elif "--sections-json" in sys.argv:
        run_sections_json(sys.argv[sys.argv.index("--sections-json") + 1])
    else:
        main()
