// Public C API of the native serving components (libtpums.so).
//
// Two units link into the one .so consumed over ctypes by
// flink_ms_tpu/serve/native_store.py:
//   store.cpp         — persistent KV store (rocksdb-parity state backend)
//   lookup_server.cpp — epoll TCP lookup server (Netty-KvState-parity data
//                       plane, QueryClientHelper.java:104-139) serving GETs
//                       straight from the store, no Python on the hot path.
#ifndef TPUMS_H_
#define TPUMS_H_

#include <stdint.h>

extern "C" {

// -- store (store.cpp) ------------------------------------------------------
void* tpums_open(const char* dir);
int tpums_put(void* h, const char* k, uint32_t klen, const char* v,
              uint32_t vlen);
// Returns a malloc'd value buffer (caller frees via tpums_free_buf) or
// nullptr; *err_out is set non-zero on I/O failure (vs. key-not-found).
char* tpums_get(void* h, const char* k, uint32_t klen, uint32_t* vlen_out,
                int* err_out);
void tpums_free_buf(char* p);
int tpums_delete(void* h, const char* k, uint32_t klen);
// Bulk-ingest a journal chunk (complete '\n'-terminated lines).  mode 0 =
// ALS rows "id,T,payload" keyed "id-T"; mode 1 = SVM rows keyed by the
// first comma token (no comma: whole line keys an empty payload — the
// Python parser's semantics).  Malformed ALS rows are counted in
// *errs_out and skipped.  Returns 0, or -1 on write failure.
int tpums_ingest_buf(void* h, const char* buf, uint64_t len, int mode,
                     uint64_t* rows_out, uint64_t* errs_out);
uint64_t tpums_count(void* h);
int tpums_flush(void* h);
typedef void (*tpums_key_cb)(const char* key, uint32_t klen, void* ctx);
int tpums_keys(void* h, tpums_key_cb cb, void* ctx);
// Bounded-lock variant: emits whole hash buckets from *cursor until
// >= max_keys keys, advancing the cursor; returns the count (0 = done).
// A rehash between chunks may skip/repeat keys — convergent consumers only.
uint64_t tpums_keys_chunk(void* h, uint64_t* cursor, uint64_t max_keys,
                          tpums_key_cb cb, void* ctx);
uint64_t tpums_log_bytes(void* h);
uint64_t tpums_live_bytes(void* h);
int tpums_compact(void* h);
void tpums_close(void* h);

// -- shared-memory arena reader (arena.cpp) ---------------------------------
// Opens the per-worker mmap'd factor arena written in place by the Python
// consumer (flink_ms_tpu/serve/arena.py — seqlock-versioned fixed-stride
// slots, open-addressing key index).  The returned handle flows through the
// SAME read API as a store handle (tpums_get / tpums_count / tpums_keys /
// tpums_keys_chunk / tpums_log_bytes / tpums_live_bytes / tpums_close), so
// tpums_server_start* serves GET/MGET/B2 — and builds TOPK/DOT indexes —
// straight from the shared pages with zero per-request pushes.  Mutating
// verbs (put/delete/ingest/compact) fail with -1: the consumer's mmap is
// the one writer.  Torn or writer-abandoned rows (odd seqlock) read as
// key-missing, never as a torn value.  A missing CURRENT is not an error:
// the handle attaches lazily once the writer creates the arena, and
// remaps itself when the writer retires a generation (growth).
void* tpums_arena_open(const char* dir);
// Force a remap check (normally implicit per read); -1 on a non-arena
// handle or when no generation file exists yet.
int tpums_arena_refresh(void* h);
// Cumulative seqlock read retries (torn/odd slots observed) — the lock-free
// path's contention signal, exported as tpums_arena_read_retries_total.
uint64_t tpums_arena_read_retries(void* h);
// Arena gauge snapshot for METRICS; -1 on a non-arena handle (how
// lookup_server.cpp detects it serves an arena).  Any out pointer may be
// null.
int tpums_arena_stats(void* h, double* rows, double* capacity,
                      double* resident_bytes, double* retries,
                      double* load_factor);
// Write-plane counter snapshot from the <dir>/writer.stats sidecar the
// native batch writer maintains (batch rows/seconds, CAS outcomes) — how
// the METRICS verb exports tpums_arena_batch_rows_total and friends
// without a Python push.  -1 on a non-arena handle or before any native
// writer has created the sidecar (the handle re-probes per call).  Any
// out pointer may be null.
int tpums_arena_write_stats(void* h, double* batch_rows,
                            double* batch_seconds, double* cas_success,
                            double* cas_retry);
// Thread-CPU seconds burned inside the native write plane (put_batch +
// cas_floats sections, sidecar offset [40:48)) — the profiling plane's
// "native;arena_writer" row.  Separate export so the frozen
// tpums_arena_write_stats ABI never moves; same -1 semantics.
int tpums_arena_write_cpu_seconds(void* h, double* cpu_s);

// -- shared-memory arena writer (arena.cpp) ---------------------------------
// The native half of ArenaModelTable's write path.  A writer handle maps
// ONE generation file read-write; the Python table keeps the flock, the
// CURRENT pointer, growth/rehash, and the table lock (callers MUST hold
// it — there is exactly one writer), and reopens the handle after every
// generation flip.  Row bytes are parity-exact with Arena.put_bytes:
// same seqlock claim order, same seq values, same untouched value tails.
void* tpums_arena_writer_open(const char* path, const char* dir);
void tpums_arena_writer_close(void* h);
// Upsert a columnar batch: kbuf/vbuf are '\n'-joined key/value bytes
// (n-1 separators; rows may not contain '\n' — the caller guards).  Stops
// EARLY at the first row that would need growth (oversize key/value or
// load-factor ceiling) and returns the applied prefix length; the caller
// grows, reopens, and resumes from there.  Returns -1 on malformed blobs
// or a bad handle.  *max_klen_out/*max_vlen_out (may be null) get the
// largest key/value over the applied prefix, feeding the Python side's
// observed-size growth geometry.
long long tpums_arena_put_batch(void* h, const char* kbuf,
                                uint64_t kbuf_len, const char* vbuf,
                                uint64_t vbuf_len, uint64_t n,
                                uint32_t* max_klen_out,
                                uint32_t* max_vlen_out);
// Compare-and-swap the value bytes of one row in place (seqlock odd/even
// preserved, so concurrent readers never see a torn row).  Returns 1 on
// swap, 0 when the current value differs from `expect` (counted as a CAS
// retry — the caller's LWW re-put is the repair), -1 when the key is
// missing or any length exceeds the arena geometry.
int tpums_arena_cas_floats(void* h, const char* k, uint32_t klen,
                           const char* expect, uint32_t explen,
                           const char* newv, uint32_t newlen);

// -- lookup server (lookup_server.cpp) --------------------------------------
// Starts an epoll event loop on its own thread, serving the line protocol of
// flink_ms_tpu/serve/server.py (GET/MGET/COUNT/PING/TOPK/TOPKV) from the
// given open store handle.  `port` 0 picks an ephemeral port.  Returns a
// server handle or nullptr.  tpums_server_start leaves TOPK/TOPKV
// unconfigured (they answer E, parity with a Python server that has no
// registered handler); tpums_server_start2 additionally takes the catalog
// item-key suffix (e.g. "-I") and the TOPK query-entity suffix (e.g. "-U"),
// enabling catalog-scored top-k straight from the store.
void* tpums_server_start(void* store, const char* state_name,
                         const char* job_id, const char* host, int port);
void* tpums_server_start2(void* store, const char* state_name,
                          const char* job_id, const char* host, int port,
                          const char* topk_item_suffix,
                          const char* topk_user_suffix);
// start3 additionally enables HEALTH/METRICS: `latency_bounds` is the shared
// log-bucket ladder (obs/metrics.LATENCY_BUCKETS_S, handed over as exact
// doubles so cross-plane merge_snapshots bounds compare equal) and turns on
// per-verb request/latency/error accounting; with nullptr/0 the server
// behaves like start2 (METRICS answers E).  All variants speak both the tab
// protocol and the HELLO-negotiated B2 binary batch framing (serve/proto.py).
void* tpums_server_start3(void* store, const char* state_name,
                          const char* job_id, const char* host, int port,
                          const char* topk_item_suffix,
                          const char* topk_user_suffix,
                          const double* latency_bounds, int n_bounds);
// Replace the HEALTH verb's base report with a one-line JSON object (the
// owning job's health dict, pushed on every heartbeat); the server splices
// in the live key count and metrics_uri.  NULL or "" reverts to the
// synthesized always-ready report.
void tpums_server_set_health(void* srv, const char* health_json);
// Enable the tail-forensics span spill: traced requests (tab ``tid=``
// stamp or the B2 ``tr=1`` per-record trace field) append one JSONL
// server_reply span record to `path` (obs/tracing.py event schema), with
// size-capped keep-K rotation (path -> path.1 -> ... -> path.K).
// max_bytes <= 0 keeps the 64 MiB default; keep < 0 keeps the default 3;
// NULL or "" path turns the spill off.
void tpums_server_set_trace(void* srv, const char* path,
                            long long max_bytes, int keep);
int tpums_server_port(void* srv);
uint64_t tpums_server_requests(void* srv);
// Reply-path syscall accounting for the batched socket loop: recv()
// invocations, send-side syscalls (sendmsg calls, or io_uring_enter
// submissions — one per batch of dirty connections), bytes sent, and
// whether the io_uring backend passed its runtime probe (0 = epoll +
// scatter-gather sendmsg fallback; TPUMS_URING=0 forces it).  The
// syscalls-per-frame tests read deltas from here instead of strace.
int tpums_server_io_stats(void* srv, uint64_t* recv_calls,
                          uint64_t* reply_syscalls, uint64_t* reply_bytes,
                          int* uring_active);
// Stops the loop, closes all connections, joins the thread, frees the handle.
void tpums_server_stop(void* srv);

}  // extern "C"

#endif  // TPUMS_H_
