// tpums shared-memory arena reader — the native half of the zero-copy
// factor store (flink_ms_tpu/serve/arena.py owns the format and the single
// writer; this unit maps the same file read-only and answers lookups with
// per-row seqlock retry, no lock and no syscall on the hot path).
//
// File layout (little-endian; authoritative doc in serve/arena.py):
//   [0:64)  header: "TPMA" | version u32 | capacity u64 | stride u32 |
//           key_cap u32 | count u64 | generation u64 | retired u32 |
//           pad u32 | mutations u64
//   [64:..) capacity slots of ceil8(12 + key_cap + stride) bytes:
//           seq u32 | klen u32 | vlen u32 | key[key_cap] | value[stride]
//
// Seqlock read: s1 = acquire-load(seq); 0 -> probe-chain end; odd -> the
// writer is mid-row (or died there) — bounded retry, then treat the slot
// as holding nothing and keep probing; copy, fence, re-load; s1 != s2 ->
// torn, retry.  A reader therefore NEVER returns a torn value: a SIGKILLed
// writer leaves an odd seq, which reads as key-missing until the respawned
// consumer's journal replay rewrites the row.  The writer is CPython
// storing through mmap on x86 (TSO store order); the acquire loads here
// are the matching read-side discipline.
//
// Growth: the writer builds generation g+1, repoints CURRENT, then flips
// the old header's `retired` flag.  Readers check the flag per lookup
// (one load) and remap through CURRENT; superseded mappings stay mapped
// until tpums_close so in-flight readers on other threads never fault.
//
// Handles dispatch through the public store API (tpums_get/tpums_count/
// tpums_keys_chunk/...) via the tag in tpums_internal.h, which is what
// lets lookup_server.cpp serve GET/MGET/B2 — and build its TOPK/DOT
// indexes — straight from the mmap with zero per-request Python pushes.

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <mutex>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include "tpums.h"
#include "tpums_internal.h"

namespace {

constexpr uint64_t kHeaderSize = 64;
constexpr uint64_t kSlotHdr = 12;
constexpr int kMaxSeqRetries = 64;

struct Mapping {
  uint8_t* base = nullptr;
  size_t size = 0;
  uint64_t capacity = 0;
  uint32_t stride = 0;
  uint32_t key_cap = 0;
  uint64_t slot_size = 0;
  std::string path;
};

struct ArenaHandle {
  uint32_t tag = kTpumsArenaTag;
  std::string dir;
  std::mutex remap_mu;
  std::atomic<Mapping*> cur{nullptr};
  std::vector<Mapping*> superseded;  // unmapped only at close
  std::atomic<uint64_t> retries{0};
  // writer.stats sidecar (write-plane counters), mapped lazily read-only
  // the first time tpums_arena_write_stats finds the file on disk
  std::atomic<uint8_t*> wstats{nullptr};
};

uint32_t fnv1a(const char* k, uint32_t klen) {
  uint32_t h = 0x811C9DC5u;
  for (uint32_t i = 0; i < klen; ++i) {
    h ^= static_cast<uint8_t>(k[i]);
    h *= 0x01000193u;
  }
  return h;
}

inline uint32_t load_u32_acq(const uint8_t* p) {
  return __atomic_load_n(reinterpret_cast<const uint32_t*>(p),
                         __ATOMIC_ACQUIRE);
}

inline uint64_t load_u64(const uint8_t* p) {
  return __atomic_load_n(reinterpret_cast<const uint64_t*>(p),
                         __ATOMIC_RELAXED);
}

inline uint32_t load_u32_rlx(const uint8_t* p) {
  return __atomic_load_n(reinterpret_cast<const uint32_t*>(p),
                         __ATOMIC_RELAXED);
}

inline void store_u32_rlx(uint8_t* p, uint32_t v) {
  __atomic_store_n(reinterpret_cast<uint32_t*>(p), v, __ATOMIC_RELAXED);
}

inline void store_u32_rel(uint8_t* p, uint32_t v) {
  __atomic_store_n(reinterpret_cast<uint32_t*>(p), v, __ATOMIC_RELEASE);
}

inline void store_u64_rlx(uint8_t* p, uint64_t v) {
  __atomic_store_n(reinterpret_cast<uint64_t*>(p), v, __ATOMIC_RELAXED);
}

// Seqlock payload copies are racy BY DESIGN — the s1/s2 recheck discards
// torn reads, and the odd-seq claim fences torn writes off from readers.
// Plain memcpy is correct under the protocol (x86-TSO plus the seq
// acquire/release pairing), but TSan cannot see the seqlock's logical
// exclusion, so once BOTH the writer (tpums_arena_put_batch / cas) and
// the reader loop are instrumented in one process — exactly what the
// sanitizer gate does — every payload byte would be reported.  Under
// TSan the copies therefore go through per-byte relaxed atomics, which
// TSan models; everywhere else this compiles to memcpy.
#if defined(__SANITIZE_THREAD__)
inline void seqlock_copy(void* dst, const void* src, size_t n) {
  uint8_t* d = static_cast<uint8_t*>(dst);
  const uint8_t* s = static_cast<const uint8_t*>(src);
  for (size_t i = 0; i < n; ++i)
    __atomic_store_n(d + i, __atomic_load_n(s + i, __ATOMIC_RELAXED),
                     __ATOMIC_RELAXED);
}
#else
inline void seqlock_copy(void* dst, const void* src, size_t n) {
  memcpy(dst, src, n);
}
#endif

Mapping* map_file(const std::string& path) {
  int fd = ::open(path.c_str(), O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < static_cast<off_t>(kHeaderSize)) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ,
                    MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  uint8_t* b = static_cast<uint8_t*>(base);
  if (memcmp(b, "TPMA", 4) != 0) {
    munmap(base, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  Mapping* m = new Mapping();
  m->base = b;
  m->size = static_cast<size_t>(st.st_size);
  memcpy(&m->capacity, b + 8, 8);
  memcpy(&m->stride, b + 16, 4);
  memcpy(&m->key_cap, b + 20, 4);
  m->slot_size = (kSlotHdr + m->key_cap + m->stride + 7) & ~7ull;
  m->path = path;
  if (kHeaderSize + m->capacity * m->slot_size > m->size) {
    munmap(base, m->size);
    delete m;
    return nullptr;
  }
  return m;
}

std::string read_current(const std::string& dir) {
  int fd = ::open((dir + "/CURRENT").c_str(), O_RDONLY);
  if (fd < 0) return "";
  char buf[256];
  ssize_t n = read(fd, buf, sizeof(buf) - 1);
  close(fd);
  if (n <= 0) return "";
  buf[n] = 0;
  std::string name(buf);
  while (!name.empty() && (name.back() == '\n' || name.back() == ' '))
    name.pop_back();
  return name.empty() ? "" : dir + "/" + name;
}

// The live mapping, remapping through CURRENT when the writer retired the
// generation we hold (or nothing was mapped yet — the server can start
// before the consumer's first row lands).
Mapping* live_mapping(ArenaHandle* a) {
  Mapping* m = a->cur.load(std::memory_order_acquire);
  if (m != nullptr && load_u32_acq(m->base + 40) == 0) return m;
  std::lock_guard<std::mutex> g(a->remap_mu);
  m = a->cur.load(std::memory_order_acquire);
  if (m != nullptr && load_u32_acq(m->base + 40) == 0) return m;
  std::string path = read_current(a->dir);
  if (path.empty() || (m != nullptr && path == m->path)) return m;
  Mapping* fresh = map_file(path);
  if (fresh == nullptr) return m;
  if (m != nullptr) a->superseded.push_back(m);
  a->cur.store(fresh, std::memory_order_release);
  return fresh;
}

// Seqlock-copy slot `idx` into key/val.  Returns 1 on a stable row, 0 when
// the slot is empty (chain end for lookups), -1 when it holds nothing
// readable (mid-write/odd-stuck/torn past the retry budget).
int read_slot(ArenaHandle* a, const Mapping* m, uint64_t idx,
              std::string* key, std::string* val) {
  const uint8_t* slot = m->base + kHeaderSize + idx * m->slot_size;
  for (int t = 0; t < kMaxSeqRetries; ++t) {
    uint32_t s1 = load_u32_acq(slot);
    if (s1 == 0) return 0;
    if (s1 & 1) {
      a->retries.fetch_add(1, std::memory_order_relaxed);
      continue;
    }
    uint32_t klen = load_u32_rlx(slot + 4);
    uint32_t vlen = load_u32_rlx(slot + 8);
    if (klen > m->key_cap || vlen > m->stride) {
      a->retries.fetch_add(1, std::memory_order_relaxed);
      continue;  // header torn mid-claim
    }
    key->resize(klen);
    seqlock_copy(key->data(), slot + kSlotHdr, klen);
    val->resize(vlen);
    seqlock_copy(val->data(), slot + kSlotHdr + m->key_cap, vlen);
    std::atomic_thread_fence(std::memory_order_acquire);
    uint32_t s2 = load_u32_acq(slot);
    if (s1 == s2) return 1;
    a->retries.fetch_add(1, std::memory_order_relaxed);
  }
  return -1;
}

// -- write plane -----------------------------------------------------------
// The native half of ArenaModelTable's write path.  A writer handle maps
// ONE generation file read-write (the Python table owns the flock, the
// CURRENT pointer, and growth — it reopens the handle after every
// generation flip), so every byte stored here replicates Arena.put_bytes
// exactly: same claim order, same seq values, same untouched value tails.
// Byte-parity with the Python writer is load-bearing (the fuzz gate diffs
// whole arena files) — change Arena.put_bytes and this together or not
// at all.

// writer.stats sidecar: write-plane counters live OUTSIDE the arena
// header (its 64 bytes are fully spoken for) in a fixed 64-byte file the
// C++ server maps read-only for the METRICS verb.
//   [0:4) "TPWS" | [4:8) version u32 | [8:16) batch_rows u64 |
//   [16:24) batch_ns u64 | [24:32) cas_success u64 | [32:40) cas_retry u64 |
//   [40:48) write_cpu_ns u64 (thread-CPU burned in put_batch/cas_floats —
//   the profiling plane's "native;arena_writer" row; old sidecars read as
//   0 here, which every consumer treats as "no data")
constexpr uint64_t kStatsSize = 64;
constexpr size_t kStatsBatchRows = 8;
constexpr size_t kStatsBatchNs = 16;
constexpr size_t kStatsCasSuccess = 24;
constexpr size_t kStatsCasRetry = 32;
constexpr size_t kStatsWriteCpuNs = 40;

uint8_t* map_stats(const std::string& dir, bool writable) {
  std::string p = dir + "/writer.stats";
  int fd = ::open(p.c_str(), writable ? (O_RDWR | O_CREAT) : O_RDONLY,
                  0644);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 ||
      (st.st_size < static_cast<off_t>(kStatsSize) &&
       (!writable || ftruncate(fd, kStatsSize) != 0))) {
    close(fd);
    return nullptr;
  }
  void* b = mmap(nullptr, kStatsSize,
                 writable ? (PROT_READ | PROT_WRITE) : PROT_READ,
                 MAP_SHARED, fd, 0);
  close(fd);
  if (b == MAP_FAILED) return nullptr;
  uint8_t* u = static_cast<uint8_t*>(b);
  if (memcmp(u, "TPWS", 4) != 0) {
    if (!writable) {  // writer hasn't stamped it yet — retry next call
      munmap(b, kStatsSize);
      return nullptr;
    }
    uint32_t ver = 1;
    memcpy(u, "TPWS", 4);
    memcpy(u + 4, &ver, 4);
  }
  return u;
}

inline void stats_add(uint8_t* stats, size_t off, uint64_t delta) {
  if (stats != nullptr)
    __atomic_fetch_add(reinterpret_cast<uint64_t*>(stats + off), delta,
                       __ATOMIC_RELAXED);
}

// Scope guard accumulating this thread's CPU ns into the sidecar's
// write_cpu_ns counter — the arena writer's contribution to the
// continuous-profiling plane.  The negative-nsec case is safe under the
// same modular-uint64 arithmetic the batch_ns accumulation relies on.
struct WriteCpuSection {
  uint8_t* stats;
  struct timespec c0;
  explicit WriteCpuSection(uint8_t* st) : stats(st) {
    if (stats != nullptr) clock_gettime(CLOCK_THREAD_CPUTIME_ID, &c0);
  }
  ~WriteCpuSection() {
    if (stats == nullptr) return;
    struct timespec c1;
    clock_gettime(CLOCK_THREAD_CPUTIME_ID, &c1);
    stats_add(stats, kStatsWriteCpuNs,
              static_cast<uint64_t>(c1.tv_sec - c0.tv_sec) * 1000000000ull +
                  static_cast<uint64_t>(c1.tv_nsec - c0.tv_nsec));
  }
};

struct ArenaWriter {
  uint32_t tag = kTpumsArenaWriterTag;
  uint8_t* base = nullptr;
  size_t size = 0;
  uint64_t capacity = 0;
  uint32_t stride = 0;
  uint32_t key_cap = 0;
  uint64_t slot_size = 0;
  uint8_t* stats = nullptr;
};

inline ArenaWriter* as_writer(void* h) {
  return (h != nullptr &&
          static_cast<TpumsTaggedHandle*>(h)->tag == kTpumsArenaWriterTag)
             ? static_cast<ArenaWriter*>(h)
             : nullptr;
}

inline void bump_mutations(ArenaWriter* w) {
  store_u64_rlx(w->base + 48, load_u64(w->base + 48) + 1);
}

// Claim store discipline: the odd seq goes in with a relaxed atomic store
// followed by a compiler-only fence — x86-TSO never reorders the
// subsequent payload stores above it at runtime (the same contract the
// CPython writer relies on, documented in serve/arena.py), and the fence
// stops the COMPILER from hoisting them.  The closing even store is
// RELEASE, pairing with the reader's acquire load of seq.
bool put_row(ArenaWriter* w, const char* k, uint32_t klen, const char* v,
             uint32_t vlen) {
  uint64_t cap = w->capacity;
  uint64_t idx = fnv1a(k, klen) % cap;
  for (uint64_t probes = 0; probes < cap; ++probes) {
    uint8_t* slot = w->base + kHeaderSize + idx * w->slot_size;
    uint32_t seq = load_u32_rlx(slot);
    uint32_t cur_klen = load_u32_rlx(slot + 4);
    if (seq == 0 && cur_klen == 0) {
      uint64_t n = load_u64(w->base + 24);
      if (n + 1 > cap - (cap >> 3)) return false;  // caller grows
      store_u32_rlx(slot, 1);
      __atomic_signal_fence(__ATOMIC_SEQ_CST);
      seqlock_copy(slot + kSlotHdr, k, klen);
      seqlock_copy(slot + kSlotHdr + w->key_cap, v, vlen);
      store_u32_rlx(slot + 4, klen);
      store_u32_rlx(slot + 8, vlen);
      store_u32_rel(slot, 2);
      store_u64_rlx(w->base + 24, n + 1);
      bump_mutations(w);
      return true;
    }
    if (cur_klen == klen && memcmp(slot + kSlotHdr, k, klen) == 0) {
      // in-place: key immutable after the claim, only vlen+value move
      store_u32_rlx(slot, seq | 1);
      __atomic_signal_fence(__ATOMIC_SEQ_CST);
      seqlock_copy(slot + kSlotHdr + w->key_cap, v, vlen);
      store_u32_rlx(slot + 8, vlen);
      store_u32_rel(slot, (seq | 1) + 1);
      bump_mutations(w);
      return true;
    }
    if (++idx == cap) idx = 0;
  }
  return false;  // full scan with no home: structurally needs growth
}

}  // namespace

extern "C" {

void* tpums_arena_open(const char* dir) {
  ArenaHandle* a = new ArenaHandle();
  a->dir = dir;
  std::string path = read_current(a->dir);
  if (!path.empty()) {
    Mapping* m = map_file(path);
    if (m != nullptr) a->cur.store(m, std::memory_order_release);
  }
  // a missing CURRENT is not fatal: the handle attaches lazily on first
  // read (server started before the consumer created the table)
  return a;
}

int tpums_arena_refresh(void* h) {
  if (!tpums_is_arena(h)) return -1;
  ArenaHandle* a = static_cast<ArenaHandle*>(h);
  return live_mapping(a) != nullptr ? 0 : -1;
}

uint64_t tpums_arena_read_retries(void* h) {
  if (!tpums_is_arena(h)) return 0;
  return static_cast<ArenaHandle*>(h)->retries.load(
      std::memory_order_relaxed);
}

int tpums_arena_stats(void* h, double* rows, double* capacity,
                      double* resident_bytes, double* retries,
                      double* load_factor) {
  if (!tpums_is_arena(h)) return -1;
  ArenaHandle* a = static_cast<ArenaHandle*>(h);
  Mapping* m = live_mapping(a);
  double r = 0, c = 0, res = 0;
  if (m != nullptr) {
    r = static_cast<double>(load_u64(m->base + 24));
    c = static_cast<double>(m->capacity);
    struct stat st;
    if (stat(m->path.c_str(), &st) == 0)
      res = static_cast<double>(st.st_blocks) * 512.0;
  }
  if (rows) *rows = r;
  if (capacity) *capacity = c;
  if (resident_bytes) *resident_bytes = res;
  if (retries)
    *retries = static_cast<double>(
        a->retries.load(std::memory_order_relaxed));
  if (load_factor) *load_factor = c > 0 ? r / c : 0.0;
  return 0;
}

int tpums_arena_write_stats(void* h, double* batch_rows,
                            double* batch_seconds, double* cas_success,
                            double* cas_retry) {
  if (!tpums_is_arena(h)) return -1;
  ArenaHandle* a = static_cast<ArenaHandle*>(h);
  uint8_t* st = a->wstats.load(std::memory_order_acquire);
  if (st == nullptr) {
    std::lock_guard<std::mutex> g(a->remap_mu);
    st = a->wstats.load(std::memory_order_relaxed);
    if (st == nullptr) {
      st = map_stats(a->dir, /*writable=*/false);
      if (st == nullptr) return -1;  // no native writer yet — retry later
      a->wstats.store(st, std::memory_order_release);
    }
  }
  if (batch_rows)
    *batch_rows = static_cast<double>(load_u64(st + kStatsBatchRows));
  if (batch_seconds)
    *batch_seconds = static_cast<double>(load_u64(st + kStatsBatchNs)) / 1e9;
  if (cas_success)
    *cas_success = static_cast<double>(load_u64(st + kStatsCasSuccess));
  if (cas_retry)
    *cas_retry = static_cast<double>(load_u64(st + kStatsCasRetry));
  return 0;
}

int tpums_arena_write_cpu_seconds(void* h, double* cpu_s) {
  // separate export (not a fifth out-param on tpums_arena_write_stats):
  // that ABI is frozen — Python ctypes bindings and the C++ METRICS
  // splice both load it by signature, and old .so / new caller mixes must
  // keep working during a rolling rebuild
  if (!tpums_is_arena(h)) return -1;
  ArenaHandle* a = static_cast<ArenaHandle*>(h);
  uint8_t* st = a->wstats.load(std::memory_order_acquire);
  if (st == nullptr) {
    std::lock_guard<std::mutex> g(a->remap_mu);
    st = a->wstats.load(std::memory_order_relaxed);
    if (st == nullptr) {
      st = map_stats(a->dir, /*writable=*/false);
      if (st == nullptr) return -1;  // no native writer yet — retry later
      a->wstats.store(st, std::memory_order_release);
    }
  }
  if (cpu_s)
    *cpu_s = static_cast<double>(load_u64(st + kStatsWriteCpuNs)) / 1e9;
  return 0;
}

// -- writer plane exports ---------------------------------------------------

void* tpums_arena_writer_open(const char* path, const char* dir) {
  int fd = ::open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < static_cast<off_t>(kHeaderSize)) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, static_cast<size_t>(st.st_size),
                    PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  uint8_t* b = static_cast<uint8_t*>(base);
  if (memcmp(b, "TPMA", 4) != 0) {
    munmap(base, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  ArenaWriter* w = new ArenaWriter();
  w->base = b;
  w->size = static_cast<size_t>(st.st_size);
  memcpy(&w->capacity, b + 8, 8);
  memcpy(&w->stride, b + 16, 4);
  memcpy(&w->key_cap, b + 20, 4);
  w->slot_size = (kSlotHdr + w->key_cap + w->stride + 7) & ~7ull;
  if (w->capacity == 0 ||
      kHeaderSize + w->capacity * w->slot_size > w->size) {
    munmap(base, w->size);
    delete w;
    return nullptr;
  }
  w->stats = map_stats(dir, /*writable=*/true);  // nullptr tolerated
  return w;
}

void tpums_arena_writer_close(void* h) {
  ArenaWriter* w = as_writer(h);
  if (w == nullptr) return;
  munmap(w->base, w->size);
  if (w->stats != nullptr) munmap(w->stats, kStatsSize);
  delete w;
}

long long tpums_arena_put_batch(void* h, const char* kbuf,
                                uint64_t kbuf_len, const char* vbuf,
                                uint64_t vbuf_len, uint64_t n,
                                uint32_t* max_klen_out,
                                uint32_t* max_vlen_out) {
  ArenaWriter* w = as_writer(h);
  if (w == nullptr || kbuf == nullptr || vbuf == nullptr) return -1;
  WriteCpuSection cpu(w->stats);
  struct timespec t0;
  clock_gettime(CLOCK_MONOTONIC, &t0);
  const char* kp = kbuf;
  const char* kend = kbuf + kbuf_len;
  const char* vp = vbuf;
  const char* vend = vbuf + vbuf_len;
  uint32_t maxk = 0, maxv = 0;
  // Parse kAhead rows ahead of the apply point and prefetch each row's
  // home slot: hash-distributed slots in a multi-hundred-MB mapping miss
  // every cache level, and without the pipeline that miss serializes with
  // the row walk (~one full memory round-trip per row).  Parsing ahead
  // overlaps up to kAhead misses with useful work.
  constexpr uint64_t kAhead = 8;
  struct ParsedRow {
    const char* k;
    const char* v;
    uint32_t klen, vlen;
  };
  ParsedRow ring[kAhead];
  uint64_t parsed = 0, applied = 0;
  for (;;) {
    while (parsed < n && parsed - applied < kAhead) {
      const char* knl = kend;
      const char* vnl = vend;
      if (parsed + 1 < n) {
        knl = static_cast<const char*>(memchr(kp, '\n', kend - kp));
        vnl = static_cast<const char*>(memchr(vp, '\n', vend - vp));
        if (knl == nullptr || vnl == nullptr) return -1;  // malformed blobs
      }
      ParsedRow& p = ring[parsed % kAhead];
      p.k = kp;
      p.klen = static_cast<uint32_t>(knl - kp);
      p.v = vp;
      p.vlen = static_cast<uint32_t>(vnl - vp);
      if (p.klen <= w->key_cap && p.vlen <= w->stride) {
        uint8_t* slot = w->base + kHeaderSize +
                        (fnv1a(p.k, p.klen) % w->capacity) * w->slot_size;
        __builtin_prefetch(slot, 1, 1);
        __builtin_prefetch(slot + kSlotHdr + w->key_cap, 1, 1);
      }
      kp = knl + 1;
      vp = vnl + 1;
      ++parsed;
    }
    if (applied == parsed) break;  // drained (or n == 0)
    ParsedRow& p = ring[applied % kAhead];
    // oversize row or load ceiling: stop HERE and report the applied
    // prefix — the Python caller puts the blocker through its growth
    // path, reopens the writer on the new generation, and resumes
    if (p.klen > w->key_cap || p.vlen > w->stride) break;
    if (!put_row(w, p.k, p.klen, p.v, p.vlen)) break;
    if (p.klen > maxk) maxk = p.klen;
    if (p.vlen > maxv) maxv = p.vlen;
    ++applied;
  }
  struct timespec t1;
  clock_gettime(CLOCK_MONOTONIC, &t1);
  stats_add(w->stats, kStatsBatchRows, applied);
  stats_add(w->stats, kStatsBatchNs,
            static_cast<uint64_t>(t1.tv_sec - t0.tv_sec) * 1000000000ull +
                static_cast<uint64_t>(t1.tv_nsec - t0.tv_nsec));
  if (max_klen_out) *max_klen_out = maxk;
  if (max_vlen_out) *max_vlen_out = maxv;
  return static_cast<long long>(applied);
}

int tpums_arena_cas_floats(void* h, const char* k, uint32_t klen,
                           const char* expect, uint32_t explen,
                           const char* newv, uint32_t newlen) {
  ArenaWriter* w = as_writer(h);
  if (w == nullptr || klen > w->key_cap || newlen > w->stride ||
      explen > w->stride)
    return -1;
  WriteCpuSection cpu(w->stats);
  uint64_t cap = w->capacity;
  uint64_t idx = fnv1a(k, klen) % cap;
  for (uint64_t probes = 0; probes < cap; ++probes) {
    uint8_t* slot = w->base + kHeaderSize + idx * w->slot_size;
    uint32_t seq = load_u32_rlx(slot);
    uint32_t cur_klen = load_u32_rlx(slot + 4);
    if (seq == 0 && cur_klen == 0) return -1;  // chain end: key missing
    if (cur_klen == klen && memcmp(slot + kSlotHdr, k, klen) == 0) {
      uint32_t vlen = load_u32_rlx(slot + 8);
      // an odd seq here is a dead prior writer's abandoned claim — the
      // value bytes are unreadable, so report a mismatch and let the
      // caller's LWW re-put repair the slot to even
      if ((seq & 1) != 0 || vlen != explen ||
          memcmp(slot + kSlotHdr + w->key_cap, expect, explen) != 0) {
        stats_add(w->stats, kStatsCasRetry, 1);
        return 0;
      }
      store_u32_rlx(slot, seq | 1);
      __atomic_signal_fence(__ATOMIC_SEQ_CST);
      seqlock_copy(slot + kSlotHdr + w->key_cap, newv, newlen);
      store_u32_rlx(slot + 8, newlen);
      store_u32_rel(slot, (seq | 1) + 1);
      bump_mutations(w);
      stats_add(w->stats, kStatsCasSuccess, 1);
      return 1;
    }
    if (++idx == cap) idx = 0;
  }
  return -1;
}

}  // extern "C"

// -- dispatch targets (store.cpp routes arena-tagged handles here) ---------

char* tpums_arena_get_impl(void* h, const char* k, uint32_t klen,
                           uint32_t* vlen_out, int* err_out) {
  if (err_out) *err_out = 0;
  ArenaHandle* a = static_cast<ArenaHandle*>(h);
  Mapping* m = live_mapping(a);
  if (m == nullptr || klen > m->key_cap) return nullptr;
  uint64_t idx = fnv1a(k, klen) % m->capacity;
  std::string key, val;
  for (uint64_t probes = 0; probes < m->capacity; ++probes) {
    int rc = read_slot(a, m, idx, &key, &val);
    if (rc == 0) return nullptr;  // empty slot: chain end, key missing
    if (rc == 1 && key.size() == klen && memcmp(key.data(), k, klen) == 0) {
      char* buf = static_cast<char*>(malloc(val.size() ? val.size() : 1));
      if (!buf) {
        if (err_out) *err_out = 1;
        return nullptr;
      }
      memcpy(buf, val.data(), val.size());
      *vlen_out = static_cast<uint32_t>(val.size());
      return buf;
    }
    // rc == -1 (odd-stuck/torn): the slot holds no readable row — keep
    // probing; a repaired duplicate of the dead claim lives further on
    if (++idx == m->capacity) idx = 0;
  }
  return nullptr;
}

uint64_t tpums_arena_count_impl(void* h) {
  ArenaHandle* a = static_cast<ArenaHandle*>(h);
  Mapping* m = live_mapping(a);
  return m == nullptr ? 0 : load_u64(m->base + 24);
}

int tpums_arena_keys_impl(void* h, tpums_key_cb cb, void* ctx) {
  ArenaHandle* a = static_cast<ArenaHandle*>(h);
  Mapping* m = live_mapping(a);
  if (m == nullptr) return 0;
  std::string key, val;
  for (uint64_t idx = 0; idx < m->capacity; ++idx) {
    if (read_slot(a, m, idx, &key, &val) == 1)
      cb(key.data(), static_cast<uint32_t>(key.size()), ctx);
  }
  return 0;
}

uint64_t tpums_arena_keys_chunk_impl(void* h, uint64_t* cursor,
                                     uint64_t max_keys, tpums_key_cb cb,
                                     void* ctx) {
  ArenaHandle* a = static_cast<ArenaHandle*>(h);
  Mapping* m = live_mapping(a);
  if (m == nullptr) {
    return 0;
  }
  uint64_t emitted = 0;
  uint64_t idx = *cursor;
  std::string key, val;
  for (; idx < m->capacity && emitted < max_keys; ++idx) {
    if (read_slot(a, m, idx, &key, &val) == 1) {
      cb(key.data(), static_cast<uint32_t>(key.size()), ctx);
      ++emitted;
    }
  }
  *cursor = idx;
  return emitted;
}

uint64_t tpums_arena_log_bytes_impl(void* h) {
  // The store's log_bytes is its index-version proxy (top-k/DOT builders
  // pair it with count to detect churn).  In-place arena updates move
  // neither count nor file size, so the writer bumps a header mutation
  // counter — report that, preserving "changed bytes == changed state".
  ArenaHandle* a = static_cast<ArenaHandle*>(h);
  Mapping* m = live_mapping(a);
  return m == nullptr ? 0 : load_u64(m->base + 48);
}

uint64_t tpums_arena_live_bytes_impl(void* h) {
  ArenaHandle* a = static_cast<ArenaHandle*>(h);
  Mapping* m = live_mapping(a);
  if (m == nullptr) return 0;
  struct stat st;
  if (stat(m->path.c_str(), &st) != 0) return 0;
  return static_cast<uint64_t>(st.st_blocks) * 512ull;
}

void tpums_arena_close_impl(void* h) {
  ArenaHandle* a = static_cast<ArenaHandle*>(h);
  Mapping* m = a->cur.load(std::memory_order_acquire);
  if (m != nullptr) {
    munmap(m->base, m->size);
    delete m;
  }
  for (Mapping* old : a->superseded) {
    munmap(old->base, old->size);
    delete old;
  }
  uint8_t* st = a->wstats.load(std::memory_order_acquire);
  if (st != nullptr) munmap(st, kStatsSize);
  delete a;
}
