// tpums shared-memory arena reader — the native half of the zero-copy
// factor store (flink_ms_tpu/serve/arena.py owns the format and the single
// writer; this unit maps the same file read-only and answers lookups with
// per-row seqlock retry, no lock and no syscall on the hot path).
//
// File layout (little-endian; authoritative doc in serve/arena.py):
//   [0:64)  header: "TPMA" | version u32 | capacity u64 | stride u32 |
//           key_cap u32 | count u64 | generation u64 | retired u32 |
//           pad u32 | mutations u64
//   [64:..) capacity slots of ceil8(12 + key_cap + stride) bytes:
//           seq u32 | klen u32 | vlen u32 | key[key_cap] | value[stride]
//
// Seqlock read: s1 = acquire-load(seq); 0 -> probe-chain end; odd -> the
// writer is mid-row (or died there) — bounded retry, then treat the slot
// as holding nothing and keep probing; copy, fence, re-load; s1 != s2 ->
// torn, retry.  A reader therefore NEVER returns a torn value: a SIGKILLed
// writer leaves an odd seq, which reads as key-missing until the respawned
// consumer's journal replay rewrites the row.  The writer is CPython
// storing through mmap on x86 (TSO store order); the acquire loads here
// are the matching read-side discipline.
//
// Growth: the writer builds generation g+1, repoints CURRENT, then flips
// the old header's `retired` flag.  Readers check the flag per lookup
// (one load) and remap through CURRENT; superseded mappings stay mapped
// until tpums_close so in-flight readers on other threads never fault.
//
// Handles dispatch through the public store API (tpums_get/tpums_count/
// tpums_keys_chunk/...) via the tag in tpums_internal.h, which is what
// lets lookup_server.cpp serve GET/MGET/B2 — and build its TOPK/DOT
// indexes — straight from the mmap with zero per-request Python pushes.

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include "tpums.h"
#include "tpums_internal.h"

namespace {

constexpr uint64_t kHeaderSize = 64;
constexpr uint64_t kSlotHdr = 12;
constexpr int kMaxSeqRetries = 64;

struct Mapping {
  uint8_t* base = nullptr;
  size_t size = 0;
  uint64_t capacity = 0;
  uint32_t stride = 0;
  uint32_t key_cap = 0;
  uint64_t slot_size = 0;
  std::string path;
};

struct ArenaHandle {
  uint32_t tag = kTpumsArenaTag;
  std::string dir;
  std::mutex remap_mu;
  std::atomic<Mapping*> cur{nullptr};
  std::vector<Mapping*> superseded;  // unmapped only at close
  std::atomic<uint64_t> retries{0};
};

uint32_t fnv1a(const char* k, uint32_t klen) {
  uint32_t h = 0x811C9DC5u;
  for (uint32_t i = 0; i < klen; ++i) {
    h ^= static_cast<uint8_t>(k[i]);
    h *= 0x01000193u;
  }
  return h;
}

inline uint32_t load_u32_acq(const uint8_t* p) {
  return __atomic_load_n(reinterpret_cast<const uint32_t*>(p),
                         __ATOMIC_ACQUIRE);
}

inline uint64_t load_u64(const uint8_t* p) {
  return __atomic_load_n(reinterpret_cast<const uint64_t*>(p),
                         __ATOMIC_RELAXED);
}

Mapping* map_file(const std::string& path) {
  int fd = ::open(path.c_str(), O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < static_cast<off_t>(kHeaderSize)) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ,
                    MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  uint8_t* b = static_cast<uint8_t*>(base);
  if (memcmp(b, "TPMA", 4) != 0) {
    munmap(base, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  Mapping* m = new Mapping();
  m->base = b;
  m->size = static_cast<size_t>(st.st_size);
  memcpy(&m->capacity, b + 8, 8);
  memcpy(&m->stride, b + 16, 4);
  memcpy(&m->key_cap, b + 20, 4);
  m->slot_size = (kSlotHdr + m->key_cap + m->stride + 7) & ~7ull;
  m->path = path;
  if (kHeaderSize + m->capacity * m->slot_size > m->size) {
    munmap(base, m->size);
    delete m;
    return nullptr;
  }
  return m;
}

std::string read_current(const std::string& dir) {
  int fd = ::open((dir + "/CURRENT").c_str(), O_RDONLY);
  if (fd < 0) return "";
  char buf[256];
  ssize_t n = read(fd, buf, sizeof(buf) - 1);
  close(fd);
  if (n <= 0) return "";
  buf[n] = 0;
  std::string name(buf);
  while (!name.empty() && (name.back() == '\n' || name.back() == ' '))
    name.pop_back();
  return name.empty() ? "" : dir + "/" + name;
}

// The live mapping, remapping through CURRENT when the writer retired the
// generation we hold (or nothing was mapped yet — the server can start
// before the consumer's first row lands).
Mapping* live_mapping(ArenaHandle* a) {
  Mapping* m = a->cur.load(std::memory_order_acquire);
  if (m != nullptr && load_u32_acq(m->base + 40) == 0) return m;
  std::lock_guard<std::mutex> g(a->remap_mu);
  m = a->cur.load(std::memory_order_acquire);
  if (m != nullptr && load_u32_acq(m->base + 40) == 0) return m;
  std::string path = read_current(a->dir);
  if (path.empty() || (m != nullptr && path == m->path)) return m;
  Mapping* fresh = map_file(path);
  if (fresh == nullptr) return m;
  if (m != nullptr) a->superseded.push_back(m);
  a->cur.store(fresh, std::memory_order_release);
  return fresh;
}

// Seqlock-copy slot `idx` into key/val.  Returns 1 on a stable row, 0 when
// the slot is empty (chain end for lookups), -1 when it holds nothing
// readable (mid-write/odd-stuck/torn past the retry budget).
int read_slot(ArenaHandle* a, const Mapping* m, uint64_t idx,
              std::string* key, std::string* val) {
  const uint8_t* slot = m->base + kHeaderSize + idx * m->slot_size;
  for (int t = 0; t < kMaxSeqRetries; ++t) {
    uint32_t s1 = load_u32_acq(slot);
    if (s1 == 0) return 0;
    if (s1 & 1) {
      a->retries.fetch_add(1, std::memory_order_relaxed);
      continue;
    }
    uint32_t klen, vlen;
    memcpy(&klen, slot + 4, 4);
    memcpy(&vlen, slot + 8, 4);
    if (klen > m->key_cap || vlen > m->stride) {
      a->retries.fetch_add(1, std::memory_order_relaxed);
      continue;  // header torn mid-claim
    }
    key->assign(reinterpret_cast<const char*>(slot + kSlotHdr), klen);
    val->assign(reinterpret_cast<const char*>(slot + kSlotHdr + m->key_cap),
                vlen);
    std::atomic_thread_fence(std::memory_order_acquire);
    uint32_t s2 = load_u32_acq(slot);
    if (s1 == s2) return 1;
    a->retries.fetch_add(1, std::memory_order_relaxed);
  }
  return -1;
}

}  // namespace

extern "C" {

void* tpums_arena_open(const char* dir) {
  ArenaHandle* a = new ArenaHandle();
  a->dir = dir;
  std::string path = read_current(a->dir);
  if (!path.empty()) {
    Mapping* m = map_file(path);
    if (m != nullptr) a->cur.store(m, std::memory_order_release);
  }
  // a missing CURRENT is not fatal: the handle attaches lazily on first
  // read (server started before the consumer created the table)
  return a;
}

int tpums_arena_refresh(void* h) {
  if (!tpums_is_arena(h)) return -1;
  ArenaHandle* a = static_cast<ArenaHandle*>(h);
  return live_mapping(a) != nullptr ? 0 : -1;
}

uint64_t tpums_arena_read_retries(void* h) {
  if (!tpums_is_arena(h)) return 0;
  return static_cast<ArenaHandle*>(h)->retries.load(
      std::memory_order_relaxed);
}

int tpums_arena_stats(void* h, double* rows, double* capacity,
                      double* resident_bytes, double* retries,
                      double* load_factor) {
  if (!tpums_is_arena(h)) return -1;
  ArenaHandle* a = static_cast<ArenaHandle*>(h);
  Mapping* m = live_mapping(a);
  double r = 0, c = 0, res = 0;
  if (m != nullptr) {
    r = static_cast<double>(load_u64(m->base + 24));
    c = static_cast<double>(m->capacity);
    struct stat st;
    if (stat(m->path.c_str(), &st) == 0)
      res = static_cast<double>(st.st_blocks) * 512.0;
  }
  if (rows) *rows = r;
  if (capacity) *capacity = c;
  if (resident_bytes) *resident_bytes = res;
  if (retries)
    *retries = static_cast<double>(
        a->retries.load(std::memory_order_relaxed));
  if (load_factor) *load_factor = c > 0 ? r / c : 0.0;
  return 0;
}

}  // extern "C"

// -- dispatch targets (store.cpp routes arena-tagged handles here) ---------

char* tpums_arena_get_impl(void* h, const char* k, uint32_t klen,
                           uint32_t* vlen_out, int* err_out) {
  if (err_out) *err_out = 0;
  ArenaHandle* a = static_cast<ArenaHandle*>(h);
  Mapping* m = live_mapping(a);
  if (m == nullptr || klen > m->key_cap) return nullptr;
  uint64_t idx = fnv1a(k, klen) % m->capacity;
  std::string key, val;
  for (uint64_t probes = 0; probes < m->capacity; ++probes) {
    int rc = read_slot(a, m, idx, &key, &val);
    if (rc == 0) return nullptr;  // empty slot: chain end, key missing
    if (rc == 1 && key.size() == klen && memcmp(key.data(), k, klen) == 0) {
      char* buf = static_cast<char*>(malloc(val.size() ? val.size() : 1));
      if (!buf) {
        if (err_out) *err_out = 1;
        return nullptr;
      }
      memcpy(buf, val.data(), val.size());
      *vlen_out = static_cast<uint32_t>(val.size());
      return buf;
    }
    // rc == -1 (odd-stuck/torn): the slot holds no readable row — keep
    // probing; a repaired duplicate of the dead claim lives further on
    if (++idx == m->capacity) idx = 0;
  }
  return nullptr;
}

uint64_t tpums_arena_count_impl(void* h) {
  ArenaHandle* a = static_cast<ArenaHandle*>(h);
  Mapping* m = live_mapping(a);
  return m == nullptr ? 0 : load_u64(m->base + 24);
}

int tpums_arena_keys_impl(void* h, tpums_key_cb cb, void* ctx) {
  ArenaHandle* a = static_cast<ArenaHandle*>(h);
  Mapping* m = live_mapping(a);
  if (m == nullptr) return 0;
  std::string key, val;
  for (uint64_t idx = 0; idx < m->capacity; ++idx) {
    if (read_slot(a, m, idx, &key, &val) == 1)
      cb(key.data(), static_cast<uint32_t>(key.size()), ctx);
  }
  return 0;
}

uint64_t tpums_arena_keys_chunk_impl(void* h, uint64_t* cursor,
                                     uint64_t max_keys, tpums_key_cb cb,
                                     void* ctx) {
  ArenaHandle* a = static_cast<ArenaHandle*>(h);
  Mapping* m = live_mapping(a);
  if (m == nullptr) {
    return 0;
  }
  uint64_t emitted = 0;
  uint64_t idx = *cursor;
  std::string key, val;
  for (; idx < m->capacity && emitted < max_keys; ++idx) {
    if (read_slot(a, m, idx, &key, &val) == 1) {
      cb(key.data(), static_cast<uint32_t>(key.size()), ctx);
      ++emitted;
    }
  }
  *cursor = idx;
  return emitted;
}

uint64_t tpums_arena_log_bytes_impl(void* h) {
  // The store's log_bytes is its index-version proxy (top-k/DOT builders
  // pair it with count to detect churn).  In-place arena updates move
  // neither count nor file size, so the writer bumps a header mutation
  // counter — report that, preserving "changed bytes == changed state".
  ArenaHandle* a = static_cast<ArenaHandle*>(h);
  Mapping* m = live_mapping(a);
  return m == nullptr ? 0 : load_u64(m->base + 48);
}

uint64_t tpums_arena_live_bytes_impl(void* h) {
  ArenaHandle* a = static_cast<ArenaHandle*>(h);
  Mapping* m = live_mapping(a);
  if (m == nullptr) return 0;
  struct stat st;
  if (stat(m->path.c_str(), &st) != 0) return 0;
  return static_cast<uint64_t>(st.st_blocks) * 512ull;
}

void tpums_arena_close_impl(void* h) {
  ArenaHandle* a = static_cast<ArenaHandle*>(h);
  Mapping* m = a->cur.load(std::memory_order_acquire);
  if (m != nullptr) {
    munmap(m->base, m->size);
    delete m;
  }
  for (Mapping* old : a->superseded) {
    munmap(old->base, old->size);
    delete old;
  }
  delete a;
}
