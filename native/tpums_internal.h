// Internal handle-dispatch contract between store.cpp and arena.cpp.
//
// Both tpums_open (log-structured store) and tpums_arena_open (mmap'd
// shared-memory arena, read-only plane) hand out opaque void* handles that
// flow through the SAME public read API (tpums_get/tpums_count/...), so the
// epoll lookup server serves either backing without caring which.  The
// first 4 bytes of every handle are a tag; store.cpp checks it and routes
// arena handles to the arena_* implementations below.
#ifndef TPUMS_INTERNAL_H_
#define TPUMS_INTERNAL_H_

#include <stdint.h>

#include "tpums.h"

constexpr uint32_t kTpumsStoreTag = 0x53544F52u;  // "STOR"
constexpr uint32_t kTpumsArenaTag = 0x4152454Eu;  // "AREN"
// Arena WRITER handles (tpums_arena_writer_open) never dispatch through
// the store read API; the distinct tag keeps a writer handle passed to a
// reader verb (or vice versa) an explicit error instead of a crash.
constexpr uint32_t kTpumsArenaWriterTag = 0x41575254u;  // "AWRT"

struct TpumsTaggedHandle {
  uint32_t tag;
};

inline bool tpums_is_arena(void* h) {
  return h != nullptr &&
         static_cast<TpumsTaggedHandle*>(h)->tag == kTpumsArenaTag;
}

// arena.cpp implementations behind the dispatch (reader-plane subset; the
// arena has exactly one writer — the Python consumer — so every mutating
// verb on an arena handle fails with -1 in store.cpp).
char* tpums_arena_get_impl(void* h, const char* k, uint32_t klen,
                           uint32_t* vlen_out, int* err_out);
uint64_t tpums_arena_count_impl(void* h);
int tpums_arena_keys_impl(void* h, tpums_key_cb cb, void* ctx);
uint64_t tpums_arena_keys_chunk_impl(void* h, uint64_t* cursor,
                                     uint64_t max_keys, tpums_key_cb cb,
                                     void* ctx);
uint64_t tpums_arena_log_bytes_impl(void* h);
uint64_t tpums_arena_live_bytes_impl(void* h);
void tpums_arena_close_impl(void* h);

#endif  // TPUMS_INTERNAL_H_
