// Epoll TCP lookup server — the native serving data plane.
//
// TPU-native counterpart of the Flink queryable-state (Netty KvState) server
// answering QueryClientHelper.queryState (QueryClientHelper.java:104-139).
// Speaks the exact line protocol of flink_ms_tpu/serve/server.py so the
// Python query clients work unchanged:
//
//   GET\t<state>\t<key>\n   ->  V\t<value>\n | N\n | E\t<msg>\n
//   MGET\t<state>\t<k1>,<k2>,...\n
//                           ->  M\t<i1>\t<i2>...\n  (per key, in order:
//                               N missing, V<value> found — one round trip
//                               for a whole batch of point lookups)
//   COUNT\t<state>\n        ->  C\t<n>\n  (live key count via tpums_count)
//   PING\n                  ->  PONG\t<job_id>\t<state>\n
//   TOPK\t<state>\t<id>\t<k>\n    ->  V\titem:score;...\n | N\n | E\t...\n
//   TOPKV\t<state>\t<k>\t<f;..>\n ->  V\titem:score;...\n | E\t...\n
//                               (enabled via tpums_server_start2 suffixes;
//                               unconfigured servers answer E for parity
//                               with a Python LookupServer that has no
//                               registered handler)
//
// Top-k scoring (serve/topk.py semantics, ALSPredict.java:74-83's dot
// product run catalog-wide): the catalog is every store key with the item
// suffix (modal payload width wins, malformed rows dropped — the same
// policy as DeviceFactorIndex._snapshot_rows), scored against the query
// vector.  Ranking uses lax.top_k's total order (NaN above +inf) with
// ties to the lower catalog row; the catalog is id-sorted so ties are
// deterministic across rebuilds (the Python plane's tie order is its
// table's insertion order, so tie parity across planes is not promised).
// The index is cached and rebuilt only when the store's (count,
// log_bytes) pair moves — every put/delete/ingest changes log_bytes, so
// a static model pays one scan then serves from the cache.  TOPK/TOPKV
// run on a dedicated worker thread with in-order deferred replies, so
// they never head-of-line-block point lookups on the epoll thread.
//
// One epoll thread, level-triggered, nonblocking sockets; per-connection
// in/out buffers; EPOLLOUT armed only while a response is partially written.
// Store reads go through the public tpums_get API (internally mutex'd), so
// the journal-consumer thread can keep writing while this thread serves.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <locale.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <time.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/uio.h>
#include <unistd.h>

// io_uring is build-gated on the kernel headers (no liburing dependency —
// raw __NR_io_uring_* syscalls) and runtime-gated on a setup probe, so the
// same binary runs on kernels without it (epoll + sendmsg fallback).
#if __has_include(<linux/io_uring.h>)
#include <linux/io_uring.h>
#include <sys/syscall.h>
#define TPUMS_HAVE_URING 1
#endif

#include <algorithm>
#include <array>
#include <atomic>
#include <chrono>
#include <cmath>
#include <memory>
#include <mutex>
#include <condition_variable>
#include <cstdio>
#include <deque>
#include <cstdlib>
#include <map>
#include <numeric>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "tpums.h"

namespace {

constexpr size_t kMaxLine = 1u << 20;   // 1 MB request line cap
constexpr size_t kReadChunk = 64 * 1024;
// Slow-reader protection: a client that pipelines requests without draining
// responses gets disconnected once this much response data is buffered.
constexpr size_t kMaxOutBuffer = 16u << 20;
// Fairness on the single epoll thread: after this many chunks the handler
// returns; level-triggered epoll re-delivers EPOLLIN for the remainder.
constexpr int kMaxChunksPerEvent = 16;

// A reply slot filled asynchronously by the top-k worker thread.  The
// worker writes `text` then publishes with ready.store(release); the epoll
// thread consumes slots strictly in FIFO order per connection, so
// pipelined requests keep their reply order even when a slow TOPK sits
// between two instant GETs.
struct PendingReply {
  std::atomic<bool> ready{false};
  std::string text;
  size_t req_bytes = 0;  // queued request payload accounted to the conn
  std::string tid;  // tab-mode echo: raw wire tid appended before the \n
};

// Cap on unconsumed reply slots per connection — a client flooding TOPKs
// without reading responses is a slow reader by another name.
constexpr size_t kMaxPendingReplies = 4096;

// --------------------------------------------------------------------------
// B2 binary batch framing (serve/proto.py is the spec; this decoder is
// byte-parity-tested against it).  A connection starts in tab mode and
// switches after a successful "HELLO\tB2" line; frames then flow both ways:
//   b"B2" varint(body_len) body;  body = varint(count) records...
// Request record: opcode byte + per-verb fields, each varint(len)+utf8.
// Reply record: varint(len) + the tab reply line without its newline.
constexpr size_t kMaxFrameBody = 8u << 20;  // matches proto.MAX_REQUEST_BODY
constexpr int kMaxVarintBytes = 10;

// Opcode table — must stay in lockstep with proto.OPCODES/FIELD_COUNTS.
struct VerbSpec {
  const char* verb;
  int fields;
};
const VerbSpec kVerbByOp[] = {
    {nullptr, 0},   {"GET", 2},   {"MGET", 2},  {"TOPK", 3},  {"TOPKV", 3},
    {"DOT", 3},     {"COUNT", 1}, {"HEALTH", 1}, {"METRICS", 0}, {"PING", 0},
};
constexpr int kMaxOpcode = 9;

// One in-order output unit: either a single tab reply line (count == 1) or
// a whole B2 reply frame spanning `count` pending slots.  A frame is only
// serialized once ALL its slots are ready — the frame header carries the
// total length, so it cannot stream record by record.
struct OutUnit {
  bool frame = false;
  uint32_t count = 1;
};

// Chunked output buffer: replies accumulate as a deque of coalesced
// chunks instead of one string, so the per-wakeup flush can hand the
// WHOLE backlog to one scatter-gather sendmsg (or one io_uring SQE)
// without re-copying, and a partial send consumes from the front in
// place instead of erase()-shifting megabytes.
struct OutBuf {
  std::deque<std::string> q;
  size_t head = 0;   // bytes of q.front() already sent
  size_t bytes = 0;  // total unsent bytes
  static constexpr size_t kCoalesce = 64 * 1024;

  bool empty() const { return bytes == 0; }
  size_t size() const { return bytes; }

  void append(const char* p, size_t n) {
    if (n == 0) return;
    if (q.empty() || q.back().size() + n > kCoalesce) q.emplace_back();
    q.back().append(p, n);
    bytes += n;
  }
  void append(const std::string& s) { append(s.data(), s.size()); }
  void take(std::string&& s) {  // move large blobs in without a copy
    if (s.empty()) return;
    bytes += s.size();
    if (!q.empty() && q.back().size() + s.size() <= kCoalesce) {
      q.back() += s;
    } else {
      q.push_back(std::move(s));
    }
  }
  size_t fill_iov(struct iovec* iov, size_t max_iov) const {
    size_t n = 0;
    size_t skip = head;  // only the front chunk has sent bytes
    for (const std::string& c : q) {
      if (n == max_iov) break;
      iov[n].iov_base = const_cast<char*>(c.data()) + skip;
      iov[n].iov_len = c.size() - skip;
      skip = 0;
      ++n;
    }
    return n;
  }
  void consume(size_t n) {
    bytes -= n;
    while (n > 0) {
      size_t avail = q.front().size() - head;
      if (n < avail) {
        head += n;
        return;
      }
      n -= avail;
      q.pop_front();
      head = 0;
    }
  }
};

struct Conn {
  int fd = -1;
  std::string in;   // bytes read, not yet parsed into complete lines
  OutBuf out;       // response bytes not yet written
  bool dirty = false;  // queued for the end-of-batch flush
  std::deque<std::shared_ptr<PendingReply>> pending;  // in-order reply slots
  std::deque<OutUnit> units;  // groups pending slots into lines/frames
  size_t pending_req_bytes = 0;  // queued TOPK request payload bytes
  bool writable_armed = false;
  bool eof = false;  // client half-closed: answer what's buffered, then close
  bool binary = false;  // negotiated B2: c->in holds frames, not lines
  bool b2_trace = false;  // HELLO tr=1: every request record carries one
                          // extra trailing trace field (possibly empty)
  bool fatal = false;   // corrupt frame: error frame queued, close after flush
};

// Cached catalog index for TOPK/TOPKV: an immutable row-major (n, width)
// snapshot swapped in whole.  All top-k work (including the first build)
// runs on the dedicated worker thread, so the point-lookup hot path on
// the epoll thread never waits on an O(catalog) scan; once a snapshot
// exists, a moved store version kicks ONE further-background rebuild
// while queries keep answering from the current (briefly stale) snapshot —
// the same serve-stale design as serve/topk.py.
struct TopkIndex {
  std::vector<std::string> ids;
  std::vector<float> matrix;
  int width = 0;
  uint64_t ver_count = ~0ull;
  uint64_t ver_bytes = ~0ull;
};

// Merged sparse-weight index for the DOT verb (serve/server.py
// _merged_range_index parity): every store row whose key is an integer
// bucket id and whose payload parses as ``idx:w;...`` contributes its
// pairs; duplicate feature ids resolve last-wins after a stable sort.
// Same immutable-snapshot + serve-stale lifecycle as TopkIndex.
struct DotIndex {
  std::vector<long long> fids;  // ascending
  std::vector<double> ws;       // aligned with fids
  std::unordered_set<long long> buckets;
  uint64_t ver_count = ~0ull;
  uint64_t ver_bytes = ~0ull;
};

// One queued unit of worker-thread work (TOPK/TOPKV/DOT): the raw request
// operands plus the reply slot already enqueued on the owning connection.
// The shared_ptr keeps the slot alive even if the connection closes
// before the work finishes.
struct TopkTask {
  std::shared_ptr<PendingReply> reply;
  std::string verb, state, query_arg, k_s;
  double t0 = 0.0;  // submit time: worker observes latency incl. queue wait
  std::string tid;     // raw wire tid when the request was traced
  double t0_wall = 0.0;  // wall-clock twin of t0, for span records
};

// Per-verb serving stats on the shared log-bucket ladder (obs/metrics.py
// LATENCY_BUCKETS_S, passed in through tpums_server_start3 so the bounds
// are equal by construction, never re-derived in float math here).  The
// METRICS verb renders these as the same one-line JSON snapshot the Python
// registry emits, so obs/scrape.py merges native and Python workers alike.
struct VerbStat {
  std::vector<uint64_t> counts;  // len(bounds) + 1 (+Inf slot)
  double sum = 0.0;
  uint64_t count = 0;
  uint64_t errors = 0;
  // CPU self-time (CLOCK_THREAD_CPUTIME_ID) spent ANSWERING this verb —
  // the native plane's contribution to the continuous-profiling plane
  // (obs/profiler.py): no sampler runs here, the handler sections are
  // measured directly and exported both as
  // tpums_native_self_seconds_total counters (METRICS) and as synthetic
  // "native;<verb>" folded stacks (PROFILE), so fleet profile merges
  // carry C++ cost next to Python samples in the same seconds unit.
  double cpu_s = 0.0;
};

#ifdef TPUMS_HAVE_URING
// Minimal synchronous io_uring submission ring (no liburing): the epoll
// thread stages one IORING_OP_SENDMSG SQE per dirty connection at the end
// of each wakeup, then ONE io_uring_enter(submit=N, min_complete=N)
// replaces N sendmsg syscalls.  Every send carries MSG_DONTWAIT so a full
// socket buffer completes immediately with -EAGAIN (never parks the ring
// in internal poll-retry, which would stall the whole event loop behind
// one slow reader); leftovers fall back to EPOLLOUT re-arming exactly
// like the non-uring path.
struct Uring {
  int ring_fd = -1;
  unsigned entries = 0;
  unsigned* sq_tail = nullptr;
  unsigned* sq_mask = nullptr;
  unsigned* sq_array = nullptr;
  io_uring_sqe* sqes = nullptr;
  unsigned* cq_head = nullptr;
  unsigned* cq_tail = nullptr;
  unsigned* cq_mask = nullptr;
  io_uring_cqe* cqes = nullptr;
  void* sq_ptr = nullptr;
  size_t sq_sz = 0;
  void* cq_ptr = nullptr;  // == sq_ptr under IORING_FEAT_SINGLE_MMAP
  size_t cq_sz = 0;
  void* sqes_ptr = nullptr;
  size_t sqes_sz = 0;
};

bool uring_init(Uring* u, unsigned want_entries) {
  struct io_uring_params p;
  memset(&p, 0, sizeof(p));
  int fd = static_cast<int>(syscall(__NR_io_uring_setup, want_entries, &p));
  if (fd < 0) return false;  // kernel/seccomp says no — fallback path
  u->ring_fd = fd;
  u->entries = p.sq_entries;
  u->sq_sz = p.sq_off.array + p.sq_entries * sizeof(unsigned);
  u->cq_sz = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
  bool single = (p.features & IORING_FEAT_SINGLE_MMAP) != 0;
  if (single) u->sq_sz = u->cq_sz = std::max(u->sq_sz, u->cq_sz);
  u->sq_ptr = mmap(nullptr, u->sq_sz, PROT_READ | PROT_WRITE,
                   MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQ_RING);
  if (u->sq_ptr == MAP_FAILED) {
    close(fd);
    u->ring_fd = -1;
    return false;
  }
  u->cq_ptr = u->sq_ptr;
  if (!single) {
    u->cq_ptr = mmap(nullptr, u->cq_sz, PROT_READ | PROT_WRITE,
                     MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_CQ_RING);
    if (u->cq_ptr == MAP_FAILED) {
      munmap(u->sq_ptr, u->sq_sz);
      close(fd);
      u->ring_fd = -1;
      return false;
    }
  }
  u->sqes_sz = p.sq_entries * sizeof(io_uring_sqe);
  u->sqes_ptr = mmap(nullptr, u->sqes_sz, PROT_READ | PROT_WRITE,
                     MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQES);
  if (u->sqes_ptr == MAP_FAILED) {
    if (!single) munmap(u->cq_ptr, u->cq_sz);
    munmap(u->sq_ptr, u->sq_sz);
    close(fd);
    u->ring_fd = -1;
    return false;
  }
  uint8_t* sqb = static_cast<uint8_t*>(u->sq_ptr);
  u->sq_tail = reinterpret_cast<unsigned*>(sqb + p.sq_off.tail);
  u->sq_mask = reinterpret_cast<unsigned*>(sqb + p.sq_off.ring_mask);
  u->sq_array = reinterpret_cast<unsigned*>(sqb + p.sq_off.array);
  u->sqes = static_cast<io_uring_sqe*>(u->sqes_ptr);
  uint8_t* cqb = static_cast<uint8_t*>(u->cq_ptr);
  u->cq_head = reinterpret_cast<unsigned*>(cqb + p.cq_off.head);
  u->cq_tail = reinterpret_cast<unsigned*>(cqb + p.cq_off.tail);
  u->cq_mask = reinterpret_cast<unsigned*>(cqb + p.cq_off.ring_mask);
  u->cqes = reinterpret_cast<io_uring_cqe*>(cqb + p.cq_off.cqes);
  return true;
}

void uring_destroy(Uring* u) {
  if (u->ring_fd < 0) return;
  munmap(u->sqes_ptr, u->sqes_sz);
  if (u->cq_ptr != u->sq_ptr) munmap(u->cq_ptr, u->cq_sz);
  munmap(u->sq_ptr, u->sq_sz);
  close(u->ring_fd);
  u->ring_fd = -1;
}
#endif  // TPUMS_HAVE_URING

struct ServerState {
  void* store = nullptr;
  std::string state_name;
  std::string job_id;
  std::string topk_item_suffix;  // non-empty = TOPK/TOPKV enabled
  std::string topk_user_suffix;
  std::mutex topk_mu;            // guards topk_cur swaps
  std::shared_ptr<const TopkIndex> topk_cur;
  std::atomic<bool> topk_building{false};
  std::thread topk_builder;      // spawned/reaped on the topk worker thread
                                 // only; final join in tpums_server_stop
  std::mutex dot_mu;             // guards dot_cur swaps
  std::shared_ptr<const DotIndex> dot_cur;
  std::atomic<bool> dot_building{false};
  std::thread dot_builder;       // same lifecycle as topk_builder
  // TOPK/TOPKV execute on a dedicated worker thread so an O(catalog)
  // index build or score can never head-of-line-block the point-lookup
  // hot path on the epoll thread (the Python plane gets the same
  // isolation from its thread-per-connection model)
  std::mutex task_mu;
  std::condition_variable task_cv;
  std::deque<TopkTask> tasks;
  std::thread topk_worker;
  std::atomic<bool> worker_stop{false};
  int listen_fd = -1;
  int epoll_fd = -1;
  int wake_fd = -1;  // eventfd: poked by tpums_server_stop
  int port = 0;
  std::string host_str;  // bind host, echoed in HEALTH's metrics_uri
  std::atomic<bool> stop{false};
  std::atomic<uint64_t> requests{0};
  std::thread loop;
  std::unordered_map<int, Conn> conns;
  // Reply-path syscall accounting (tpums_server_io_stats): the syscall-
  // batching tests compute deltas from these instead of strace, which the
  // CI sandbox may not allow.  reply_syscalls counts send-side syscalls
  // (sendmsg calls, or io_uring_enter submissions — one per BATCH of
  // dirty connections); recv_calls counts recv() invocations.
  std::atomic<uint64_t> reply_syscalls{0};
  std::atomic<uint64_t> recv_calls{0};
  std::atomic<uint64_t> reply_bytes{0};
  bool uring_on = false;  // runtime probe outcome (TPUMS_URING knob)
  std::vector<int> dirty_fds;  // epoll-thread-only: this batch's flush set
#ifdef TPUMS_HAVE_URING
  Uring uring;
#endif
  // METRICS/HEALTH surface (empty lat_bounds = start2 compat: METRICS
  // answers E\tbad request exactly like the pre-round-8 server)
  std::vector<double> lat_bounds;
  std::mutex metrics_mu;  // guards verb_stats (epoll + worker threads)
  std::map<std::string, VerbStat> verb_stats;  // ordered => stable JSON
  std::mutex health_mu;
  std::string health_json;  // last report pushed via tpums_server_set_health
  // Tail-forensics span spill (obs/tracing.py JSONL schema): path set via
  // tpums_server_set_trace; every TRACED request (trailing tab ``tid=``
  // field, or the B2 ``tr=1`` per-record trace field) appends ONE
  // server_reply span record.  Untraced requests never touch this.
  std::mutex trace_mu;
  std::string trace_path;  // empty = span spill off
  long long trace_max_bytes = 64ll << 20;
  int trace_keep = 3;
  long long trace_file_bytes = -1;  // -1 = stat on next append
  std::atomic<uint64_t> span_seq{0};
};

double now_s() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Wall clock for span records: forensics correlates spans ACROSS processes
// by timestamp, so span t0/ts must be system_clock (now_s() is steady_clock
// and only comparable within this process).
double wall_s() {
  return std::chrono::duration<double>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

// Minimal JSON string escape — tids/verbs come off the wire.
void json_escape_into(std::string& out, const std::string& v) {
  for (unsigned char ch : v) {
    if (ch == '"' || ch == '\\') {
      out.push_back('\\');
      out.push_back(static_cast<char>(ch));
    } else if (ch < 0x20) {
      char buf[8];
      snprintf(buf, sizeof buf, "\\u%04x", ch);
      out += buf;
    } else {
      out.push_back(static_cast<char>(ch));
    }
  }
}

// Size-capped keep-K rotation, mirroring obs/tracing._rotate_locked:
// path -> path.1 -> ... -> path.K, oldest dropped.  Caller holds trace_mu.
void trace_rotate_locked(ServerState* s) {
  const std::string& p = s->trace_path;
  if (s->trace_keep == 0) {
    ::remove(p.c_str());
  } else {
    for (int i = s->trace_keep - 1; i >= 1; --i) {
      std::string src = p + "." + std::to_string(i);
      ::rename(src.c_str(), (p + "." + std::to_string(i + 1)).c_str());
    }
    ::rename(p.c_str(), (p + ".1").c_str());
  }
  s->trace_file_bytes = 0;
}

// Append one server_reply span record for a traced request.  The raw wire
// tid may be the ``tid/sid`` composite (obs/tracing.wire_tid): the part
// after the slash is the CLIENT's rpc span id, recorded here as psid so
// forensics parents this server span under the caller's tree.
void trace_spill(ServerState* s, const std::string& raw_tid,
                 const std::string& verb, double t0_wall, double dur_s,
                 double queue_s, double serve_s, bool is_err) {
  std::lock_guard<std::mutex> g(s->trace_mu);
  if (s->trace_path.empty()) return;
  std::string tid = raw_tid, psid;
  size_t slash = raw_tid.find('/');
  if (slash != std::string::npos) {
    tid = raw_tid.substr(0, slash);
    psid = raw_tid.substr(slash + 1);
  }
  // sid: port-salted sequence — unique across the servers a fanned-out
  // trace touches, which is all tree assembly needs
  char sid[24];
  snprintf(sid, sizeof sid, "%04x%06llx",
           static_cast<unsigned>(s->port & 0xffff),
           static_cast<unsigned long long>(
               (s->span_seq.fetch_add(1, std::memory_order_relaxed) + 1) &
               0xffffff));
  char num[48];
  std::string line = "{\"ts\":";
  snprintf(num, sizeof num, "%.6f", wall_s());
  line += num;
  line += ",\"tid\":\"";
  json_escape_into(line, tid);
  line += "\",\"kind\":\"server_reply\",\"plane\":\"native\",\"sid\":\"";
  line += sid;
  line += "\"";
  if (!psid.empty()) {
    line += ",\"psid\":\"";
    json_escape_into(line, psid);
    line += "\"";
  }
  snprintf(num, sizeof num, ",\"t0\":%.6f", t0_wall);
  line += num;
  snprintf(num, sizeof num, ",\"dur_s\":%.9f", dur_s);
  line += num;
  line += ",\"verb\":\"";
  json_escape_into(line, verb);
  line += "\",\"job_id\":\"";
  json_escape_into(line, s->job_id);
  line += "\",\"port\":" + std::to_string(s->port);
  snprintf(num, sizeof num, ",\"lat_s\":%.6f", dur_s);
  line += num;
  snprintf(num, sizeof num, ",\"queue_wait_s\":%.9f", queue_s);
  line += num;
  snprintf(num, sizeof num, ",\"serve_s\":%.9f", serve_s);
  line += num;
  line += is_err ? ",\"ok\":false}\n" : ",\"ok\":true}\n";
  if (s->trace_file_bytes < 0) {
    struct stat st;
    s->trace_file_bytes =
        (stat(s->trace_path.c_str(), &st) == 0) ? st.st_size : 0;
  }
  if (s->trace_file_bytes >= s->trace_max_bytes && s->trace_max_bytes > 0) {
    trace_rotate_locked(s);
  }
  FILE* f = fopen(s->trace_path.c_str(), "a");
  if (!f) return;
  fwrite(line.data(), 1, line.size(), f);
  fclose(f);
  s->trace_file_bytes += static_cast<long long>(line.size());
}

// This thread's consumed CPU seconds (user+sys).  Cost of one
// clock_gettime on the hot path is ~25ns (vDSO) — two calls bracket each
// handler section, well inside the enforced <=3% profiling-overhead bar.
double thread_cpu_s() {
  struct timespec ts;
  if (clock_gettime(CLOCK_THREAD_CPUTIME_ID, &ts) != 0) return 0.0;
  return static_cast<double>(ts.tv_sec) + ts.tv_nsec * 1e-9;
}

void observe_verb(ServerState* s, const std::string& verb, double dt,
                  bool is_err, double cpu_s = 0.0) {
  if (s->lat_bounds.empty()) return;
  std::lock_guard<std::mutex> g(s->metrics_mu);
  VerbStat& st = s->verb_stats[verb.empty() ? "?" : verb];
  if (st.counts.empty()) st.counts.assign(s->lat_bounds.size() + 1, 0);
  // bucket index: first bound >= dt (std::lower_bound == bisect_left —
  // the Python Histogram.observe rule, so cross-plane merges line up)
  size_t i = std::lower_bound(s->lat_bounds.begin(), s->lat_bounds.end(),
                              dt) -
             s->lat_bounds.begin();
  st.counts[i] += 1;
  st.sum += dt;
  st.count += 1;
  if (is_err) st.errors += 1;
  if (cpu_s > 0.0) st.cpu_s += cpu_s;
}

bool set_nonblocking(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  return flags >= 0 && fcntl(fd, F_SETFL, flags | O_NONBLOCK) == 0;
}

// Unsigned LEB128, appended in place (frame headers and reply records).
void append_varint(std::string& out, uint64_t v) {
  while (true) {
    uint8_t b = v & 0x7F;
    v >>= 7;
    if (v) {
      out.push_back(static_cast<char>(b | 0x80));
    } else {
      out.push_back(static_cast<char>(b));
      return;
    }
  }
}

// 0 = ok (value/pos updated), 1 = need more bytes, 2 = malformed (>10 bytes).
int parse_varint(const char* data, size_t size, size_t* pos, uint64_t* out) {
  uint64_t value = 0;
  int shift = 0;
  for (int i = 0; i < kMaxVarintBytes; ++i) {
    if (*pos + i >= size) return 1;
    uint8_t b = static_cast<uint8_t>(data[*pos + i]);
    value |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *pos += i + 1;
      *out = value;
      return 0;
    }
    shift += 7;
  }
  return 2;
}

// Strict UTF-8 validation with Python codec semantics (rejects overlongs,
// surrogates, > U+10FFFF): binary record fields must decode on the Python
// plane too, so a field Python would refuse is a malformed frame here.
bool utf8_valid(const char* p, size_t n) {
  size_t i = 0;
  while (i < n) {
    unsigned char c = static_cast<unsigned char>(p[i]);
    if (c < 0x80) {
      ++i;
      continue;
    }
    int len;
    uint32_t cp, min_cp;
    if ((c & 0xE0) == 0xC0) {
      len = 2; cp = c & 0x1F; min_cp = 0x80;
    } else if ((c & 0xF0) == 0xE0) {
      len = 3; cp = c & 0x0F; min_cp = 0x800;
    } else if ((c & 0xF8) == 0xF0) {
      len = 4; cp = c & 0x07; min_cp = 0x10000;
    } else {
      return false;
    }
    if (i + static_cast<size_t>(len) > n) return false;
    for (int j = 1; j < len; ++j) {
      unsigned char cc = static_cast<unsigned char>(p[i + j]);
      if ((cc & 0xC0) != 0x80) return false;
      cp = (cp << 6) | (cc & 0x3F);
    }
    if (cp < min_cp || cp > 0x10FFFF || (cp >= 0xD800 && cp <= 0xDFFF))
      return false;
    i += len;
  }
  return true;
}

// json.dumps(..., ensure_ascii=True) string escaping: ASCII passes, the two
// JSON metas escape, controls and non-ASCII become \uXXXX (surrogate pairs
// past the BMP).  Invalid UTF-8 degrades to U+FFFD rather than emitting
// bytes that would break the one-line-JSON contract.
void escape_json_into(std::string& out, const std::string& in) {
  size_t i = 0, n = in.size();
  char tmp[16];
  while (i < n) {
    unsigned char c = static_cast<unsigned char>(in[i]);
    if (c == '"') {
      out += "\\\"";
      ++i;
    } else if (c == '\\') {
      out += "\\\\";
      ++i;
    } else if (c < 0x20) {
      switch (c) {
        case '\n': out += "\\n"; break;
        case '\t': out += "\\t"; break;
        case '\r': out += "\\r"; break;
        case '\b': out += "\\b"; break;
        case '\f': out += "\\f"; break;
        default:
          snprintf(tmp, sizeof(tmp), "\\u%04x", c);
          out += tmp;
      }
      ++i;
    } else if (c < 0x80) {
      out.push_back(static_cast<char>(c));
      ++i;
    } else {
      int len;
      uint32_t cp;
      if ((c & 0xE0) == 0xC0) {
        len = 2; cp = c & 0x1F;
      } else if ((c & 0xF0) == 0xE0) {
        len = 3; cp = c & 0x0F;
      } else if ((c & 0xF8) == 0xF0) {
        len = 4; cp = c & 0x07;
      } else {
        len = 0; cp = 0;
      }
      bool ok = len > 0 && i + static_cast<size_t>(len) <= n;
      for (int j = 1; ok && j < len; ++j) {
        unsigned char cc = static_cast<unsigned char>(in[i + j]);
        if ((cc & 0xC0) != 0x80) ok = false;
        cp = (cp << 6) | (cc & 0x3F);
      }
      if (!ok) {
        out += "\\ufffd";
        ++i;
        continue;
      }
      if (cp >= 0x10000) {
        uint32_t v = cp - 0x10000;
        snprintf(tmp, sizeof(tmp), "\\u%04x\\u%04x",
                 0xD800 + (v >> 10), 0xDC00 + (v & 0x3FF));
      } else {
        snprintf(tmp, sizeof(tmp), "\\u%04x", cp);
      }
      out += tmp;
      i += len;
    }
  }
}

std::string escape_json(const std::string& in) {
  std::string out;
  out.reserve(in.size() + 2);
  escape_json_into(out, in);
  return out;
}

// Split `line` on '\t' into at most `max_parts` pieces (last piece keeps any
// remaining tabs, matching Python's str.split("\t") when the counts line up
// because keys/payloads never contain tabs).
int split_tabs(const std::string& line, std::string* parts, int max_parts) {
  int n = 0;
  size_t start = 0;
  while (n < max_parts - 1) {
    size_t tab = line.find('\t', start);
    if (tab == std::string::npos) break;
    parts[n++] = line.substr(start, tab - start);
    start = tab + 1;
  }
  parts[n++] = line.substr(start);
  return n;
}

bool ends_with(const std::string& str, const std::string& suf) {
  return str.size() >= suf.size() &&
         str.compare(str.size() - suf.size(), suf.size(), suf) == 0;
}

// "C"-locale handle for float parse/format: the embedding process may set
// LC_NUMERIC, which would flip printf/strtod's decimal separator and break
// the wire format; uselocale() scopes the classic locale to this thread
// for the duration of one call.
locale_t c_locale() {
  static locale_t loc = newlocale(LC_ALL_MASK, "C", (locale_t)0);
  return loc;
}

// Shortest round-trip float formatting matching Python's repr(float(f32)):
// the f32 score widens to double exactly; the shortest decimal is found by
// trying %.*e at increasing precision until strtod round-trips (the
// standard pre-<charconv> idiom — this toolchain's libstdc++ lacks
// floating-point to_chars), then the digits are laid out with Python's
// notation rule: scientific only when |x| >= 1e16 or 0 < |x| < 1e-4, an
// integral fixed result gains the trailing ".0", exponents keep printf's
// sign and >= 2 digits ("1e-06"), exactly like float.__repr__.
std::string format_score_d(double d) {
  if (d != d) return "nan";  // Python repr never signs NaN ("-nan" would
  // leak for the sign-bit-set QNaN that 0*inf produces)
  if (d == HUGE_VAL) return "inf";
  if (d == -HUGE_VAL) return "-inf";
  if (d == 0.0) return std::signbit(d) ? "-0.0" : "0.0";
  char buf[64];
  locale_t old = uselocale(c_locale());
  int p = 0;
  for (; p < 17; ++p) {  // p=16 (17 significant digits) always round-trips
    snprintf(buf, sizeof(buf), "%.*e", p, d);
    if (strtod(buf, nullptr) == d) break;
  }
  uselocale(old);
  // buf is "[-]d[.ddd]e±XX": minimal-precision digits can't end in '0'
  // (the shorter string denotes the same decimal and would have won)
  std::string sci(buf);
  bool neg = sci[0] == '-';
  size_t ms = neg ? 1 : 0;
  size_t epos = sci.find('e');
  std::string digits;
  digits += sci[ms];
  if (sci[ms + 1] == '.') digits += sci.substr(ms + 2, epos - ms - 2);
  int exp10 = atoi(sci.c_str() + epos + 1);
  double a = neg ? -d : d;
  std::string out = neg ? "-" : "";
  if (a >= 1e16 || a < 1e-4) {  // Python's scientific-notation rule
    out += digits.substr(0, 1);
    if (digits.size() > 1) {
      out += ".";
      out += digits.substr(1);
    }
    out += "e";
    out += (exp10 < 0) ? "-" : "+";
    int ae = exp10 < 0 ? -exp10 : exp10;
    snprintf(buf, sizeof(buf), "%02d", ae);
    out += buf;
  } else {
    int len = static_cast<int>(digits.size());
    if (exp10 >= len - 1) {  // integral: pad zeros, add ".0"
      out += digits;
      out.append(static_cast<size_t>(exp10 - (len - 1)), '0');
      out += ".0";
    } else if (exp10 >= 0) {  // decimal point inside the digit run
      out += digits.substr(0, exp10 + 1);
      out += ".";
      out += digits.substr(exp10 + 1);
    } else {  // leading "0.000..." zeros
      out += "0.";
      out.append(static_cast<size_t>(-exp10 - 1), '0');
      out += digits;
    }
  }
  return out;
}

std::string format_score(float f) {
  // the f32 score widens to double exactly, so the double repr rule applies
  return format_score_d(static_cast<double>(f));
}

// Parse one float token with Python float() semantics: outer ASCII
// whitespace stripped, one optional sign, then a general/inf/nan parse via
// strtod under the scoped "C" locale (a non-C LC_NUMERIC set by the
// embedding process would otherwise silently reject '.' decimals).  Hex
// floats and strtod's "nan(char-seq)" payload form are rejected explicitly,
// matching Python.
bool parse_float_token(const char* b, const char* e, double* out) {
  while (b < e && (*b == ' ' || *b == '\t' || *b == '\r' || *b == '\n'))
    ++b;
  while (e > b && (e[-1] == ' ' || e[-1] == '\t' || e[-1] == '\r' ||
                   e[-1] == '\n'))
    --e;
  if (b >= e) return false;
  bool neg = false;
  if (*b == '+' || *b == '-') {
    neg = (*b == '-');
    ++b;
    if (b < e && (*b == '+' || *b == '-')) return false;  // "+-1"
  }
  if (b >= e) return false;
  if (e - b >= 2 && b[0] == '0' && (b[1] == 'x' || b[1] == 'X')) return false;
  for (const char* p = b; p < e; ++p) {
    if (*p == '(') return false;  // strtod "nan(...)" that Python refuses
    if (*p == '\0') return false;  // NUL would truncate the C-string parse
  }
  std::string tok(b, e);
  locale_t old = uselocale(c_locale());
  char* endp = nullptr;
  double v = strtod(tok.c_str(), &endp);
  uselocale(old);
  if (endp != tok.c_str() + tok.size()) return false;
  *out = neg ? -v : v;
  return true;
}

// Parse a ";"-separated payload into doubles, skipping empty tokens (the
// Python parser's `float(t) for t if t`).  Returns false on any token that
// does not fully parse; *bad_tok receives the offender for the error
// message.
bool parse_vector(const std::string& payload, std::vector<double>* out,
                  std::string* bad_tok) {
  out->clear();
  size_t start = 0;
  while (start <= payload.size()) {
    size_t semi = payload.find(';', start);
    size_t end = (semi == std::string::npos) ? payload.size() : semi;
    if (end > start) {
      double v = 0.0;
      if (!parse_float_token(payload.data() + start, payload.data() + end,
                             &v)) {
        if (bad_tok) *bad_tok = payload.substr(start, end - start);
        return false;
      }
      out->push_back(v);
    }
    if (semi == std::string::npos) break;
    start = semi + 1;
  }
  return true;
}

// Build a fresh catalog snapshot from the store.  Width policy mirrors
// DeviceFactorIndex._snapshot_rows: modal separator count wins (ties to
// the smaller width, matching np.bincount().argmax()), rows off the modal
// width or with non-numeric tokens are dropped.  The version pair is read
// BEFORE the scan, so writes landing mid-scan leave the snapshot stale and
// the next query kicks another rebuild (convergent, never lossy).
std::shared_ptr<const TopkIndex> build_topk_index(ServerState* s) {
  auto ix = std::make_shared<TopkIndex>();
  ix->ver_count = tpums_count(s->store);
  ix->ver_bytes = tpums_log_bytes(s->store);

  // chunked enumeration: tpums_keys holds the store mutex for the WHOLE
  // scan, which would stall concurrent point gets for the full catalog
  // walk; the chunked variant bounds each lock hold.  A rehash between
  // chunks can repeat keys — the id-sorted dedup below absorbs that.
  std::vector<std::string> keys;
  uint64_t cursor = 0;
  while (tpums_keys_chunk(
             s->store, &cursor, 8192,
             [](const char* key, uint32_t klen, void* ctx) {
               static_cast<std::vector<std::string>*>(ctx)->emplace_back(
                   key, klen);
             },
             &keys) > 0) {
  }
  std::vector<std::string> ids, payloads;
  std::vector<int> widths;
  std::unordered_map<int, int> hist;
  for (const std::string& key : keys) {
    if (!ends_with(key, s->topk_item_suffix)) continue;
    if (key.rfind("MEAN", 0) == 0) continue;     // cold-start rows
    if (!key.empty() && key[0] == '\x01') continue;  // store-internal keys
    uint32_t vlen = 0;
    int err = 0;
    char* buf = tpums_get(s->store, key.data(),
                          static_cast<uint32_t>(key.size()), &vlen, &err);
    if (!buf) continue;
    std::string payload(buf, vlen);
    tpums_free_buf(buf);
    while (!payload.empty() && payload.back() == ';') payload.pop_back();
    int w = 1 + static_cast<int>(
        std::count(payload.begin(), payload.end(), ';'));
    ids.push_back(key.substr(0, key.size() - s->topk_item_suffix.size()));
    payloads.push_back(std::move(payload));
    widths.push_back(w);
    hist[w] += 1;
  }
  int modal = 0, best = 0;
  for (const auto& kv : hist) {
    if (kv.second > best || (kv.second == best && kv.first < modal)) {
      modal = kv.first;
      best = kv.second;
    }
  }
  ix->width = modal;
  // deterministic catalog order: tpums_keys enumerates the store's hash
  // buckets, which would make exact-score ties nondeterministic across
  // rebuilds — sort by id so tie-breaking is stable (lexicographically
  // smaller id wins; the Python plane's own tie order is its table's
  // insertion-dependent iteration order, so cross-plane tie parity is
  // unattainable either way and only determinism is promised)
  std::vector<uint32_t> order(ids.size());
  std::iota(order.begin(), order.end(), 0u);
  std::sort(order.begin(), order.end(), [&ids](uint32_t a, uint32_t b) {
    return ids[a] < ids[b];
  });
  std::vector<double> row;
  for (uint32_t i : order) {
    if (widths[i] != modal) continue;
    if (!ix->ids.empty() && ix->ids.back() == ids[i]) continue;  // chunk
    // rehash duplicate: id-sorted, so repeats are adjacent — keep first
    if (!parse_vector(payloads[i], &row, nullptr)) continue;
    if (static_cast<int>(row.size()) != modal) continue;
    ix->ids.push_back(std::move(ids[i]));
    for (double v : row) ix->matrix.push_back(static_cast<float>(v));
  }
  return ix;
}

// Current snapshot for a query (runs on the top-k worker thread): build
// inline the first time — only queued top-k work waits, never the epoll
// loop — then serve-stale with at most one background rebuild in flight.
std::shared_ptr<const TopkIndex> get_topk_index(ServerState* s) {
  uint64_t count = tpums_count(s->store);
  uint64_t bytes = tpums_log_bytes(s->store);
  std::shared_ptr<const TopkIndex> cur;
  {
    std::lock_guard<std::mutex> g(s->topk_mu);
    cur = s->topk_cur;
  }
  if (cur && cur->ver_count == count && cur->ver_bytes == bytes) return cur;
  if (!cur) {
    cur = build_topk_index(s);
    std::lock_guard<std::mutex> g(s->topk_mu);
    s->topk_cur = cur;
    return cur;
  }
  bool expected = false;
  if (s->topk_building.compare_exchange_strong(expected, true)) {
    if (s->topk_builder.joinable()) s->topk_builder.join();  // reap done run
    s->topk_builder = std::thread([s]() {
      auto fresh = build_topk_index(s);
      {
        std::lock_guard<std::mutex> g(s->topk_mu);
        s->topk_cur = std::move(fresh);
      }
      s->topk_building.store(false, std::memory_order_release);
    });
  }
  return cur;  // briefly stale while the rebuild runs
}

// ---------------------------------------------------------------------------
// Tile scorers: score `cnt` consecutive catalog rows against the query into
// a small L1-resident buffer.  Multi-versioned at runtime (target
// attributes, no -march build-flag change): the baseline is 4-wide SSE2 via
// the gcc/clang vector extension, the fast path 8-wide AVX2+FMA — the scan
// streams the whole matrix per query, so wider ops mainly buy bandwidth
// saturation.  Accumulation lanewise then one horizontal sum: deterministic
// per version; the cross-plane score contract allows accumulation-order
// round-off (test_native_topkv_semantic_parity_random), and the byte-parity
// fixtures are exact on any grouping.

typedef void (*ScoreTileFn)(const float*, int, const float*, size_t, size_t,
                            float*);

static void score_tile_sse2(const float* m, int w, const float* q,
                            size_t lo, size_t cnt, float* out) {
  typedef float v4sf __attribute__((vector_size(16)));
  for (size_t r = 0; r < cnt; ++r) {
    const float* row = m + (lo + r) * w;
    v4sf vacc = {0.f, 0.f, 0.f, 0.f};
    int j = 0;
    for (; j + 4 <= w; j += 4) {
      v4sf a, b;
      __builtin_memcpy(&a, row + j, sizeof a);
      __builtin_memcpy(&b, q + j, sizeof b);
      vacc += a * b;
    }
    float acc = (vacc[0] + vacc[1]) + (vacc[2] + vacc[3]);
    for (; j < w; ++j) acc += row[j] * q[j];
    out[r] = acc;
  }
}

__attribute__((target("avx2,fma")))
static void score_tile_avx2(const float* m, int w, const float* q,
                            size_t lo, size_t cnt, float* out) {
  typedef float v8sf __attribute__((vector_size(32)));
  for (size_t r = 0; r < cnt; ++r) {
    const float* row = m + (lo + r) * w;
    __builtin_prefetch(row + 16 * w);
    v8sf vacc = {0.f, 0.f, 0.f, 0.f, 0.f, 0.f, 0.f, 0.f};
    int j = 0;
    for (; j + 8 <= w; j += 8) {
      v8sf a, b;
      __builtin_memcpy(&a, row + j, sizeof a);
      __builtin_memcpy(&b, q + j, sizeof b);
      vacc += a * b;
    }
    float acc = ((vacc[0] + vacc[4]) + (vacc[1] + vacc[5])) +
                ((vacc[2] + vacc[6]) + (vacc[3] + vacc[7]));
    for (; j < w; ++j) acc += row[j] * q[j];
    out[r] = acc;
  }
}

static ScoreTileFn pick_score_tile() {
  __builtin_cpu_init();
  if (__builtin_cpu_supports("avx2") && __builtin_cpu_supports("fma"))
    return score_tile_avx2;
  return score_tile_sse2;
}

// Score the catalog against `query` and format the top-k payload
// ("item:score;..."), or an E line on a shape/parse failure.  Error
// message text matches the Python server's byte-for-byte so clients see
// one protocol regardless of plane.
std::string topk_payload(ServerState* s, const std::string& query_payload,
                         long k) {
  std::shared_ptr<const TopkIndex> ix = get_topk_index(s);
  std::vector<double> q;
  std::string bad;
  if (!parse_vector(query_payload, &q, &bad)) {
    return "E\ttopk failed: could not convert string to float: '" + bad +
           "'\n";
  }
  size_t n = ix->ids.size();
  if (n == 0) return "V\t\n";  // empty index answers an empty payload
  if (static_cast<int>(q.size()) != ix->width) {
    return "E\ttopk failed: query has " + std::to_string(q.size()) +
           " factors, index has " + std::to_string(ix->width) + "\n";
  }
  // f32 accumulation in four independent partial sums: deterministic,
  // SIMD-friendly under -O2 (no FP-reassociation license needed), and
  // closer to the Python plane's f32 matmul than the old per-row double
  // loop; the cross-plane score contract allows accumulation-order
  // round-off (test_native_topkv_semantic_parity_random).
  std::vector<float> qf(q.begin(), q.end());
  const float* m = ix->matrix.data();
  int w = ix->width;
  size_t k_eff = std::min<size_t>(static_cast<size_t>(k), n);
  // total order matching lax.top_k (measured: NaN sorts ABOVE +inf, ties
  // to the lower row index).  A plain `a > b` comparator is not a strict
  // weak ordering once NaN appears (NaN != x is true while NaN > x is
  // false) — UB for partial_sort; this ranking is total for any input.
  auto score_gt = [](float a, float b) {
    bool na = a != a, nb = b != b;
    if (na || nb) return na && !nb;
    return a > b;
  };
  // candidates carry (score, row) so no O(n) score buffer is ever
  // allocated or written — at 1M rows the old scores vector cost a 4 MB
  // zero-init plus 4 MB of stores per query
  typedef std::pair<float, uint32_t> Cand;
  auto cand_lt = [&score_gt](const Cand& a, const Cand& b) {
    if (score_gt(a.first, b.first)) return true;
    if (score_gt(b.first, a.first)) return false;
    return a.second < b.second;  // lax.top_k tie order
  };
  static const ScoreTileFn score_tile = pick_score_tile();
  auto scan_block = [&](size_t lo, size_t hi, std::vector<Cand>* out) {
    const float* qp = qf.data();
    // selection folded into the scan: score a tile into an L1-resident
    // buffer, then admit against a <=k candidate HEAP with a threshold
    // pre-test — one float compare per row on the hot path and O(log k)
    // per admission (a sorted-insert buffer would be O(k) per admission:
    // quadratic for k ~ catalog, which TOPKV explicitly allows, and
    // O(n*k) on an ascending-score catalog).  With cand_lt as the heap's
    // "less", the front is the WEAKEST candidate: the threshold test and
    // evictions read/remove exactly it.  Scanning ascending i means a new
    // candidate always carries the HIGHEST index, so tying the current
    // weakest (ties rank by lower index) never displaces it — strict
    // score_gt is the admission test.
    std::vector<Cand>& best = *out;
    best.clear();
    best.reserve(k_eff + 1);
    constexpr size_t TILE = 512;
    float buf[TILE];
    for (size_t base = lo; base < hi; base += TILE) {
      size_t cnt = std::min(TILE, hi - base);
      score_tile(m, w, qp, base, cnt, buf);
      for (size_t r = 0; r < cnt; ++r) {
        float acc = buf[r];
        if (best.size() == k_eff && !score_gt(acc, best.front().first))
          continue;
        best.push_back(Cand{acc, static_cast<uint32_t>(base + r)});
        std::push_heap(best.begin(), best.end(), cand_lt);
        if (best.size() > k_eff) {
          std::pop_heap(best.begin(), best.end(), cand_lt);
          best.pop_back();
        }
      }
    }
  };
  // O(catalog) scan + selection parallelized over contiguous row blocks
  // (the round-4 single-threaded double-accumulation scan was ~5x slower
  // than the Python plane's f32 matmul at 1M rows); small catalogs and
  // single-core hosts stay single-threaded
  unsigned hw = std::thread::hardware_concurrency();
  size_t nthreads = hw ? std::min<size_t>(hw, 8) : 1;
  // threads are spawned per query: give each at least ~128k rows so the
  // create/join cost (~0.1-0.2 ms) stays well under its share of the scan
  nthreads = std::min(nthreads, std::max<size_t>(n / 131072, 1));
  std::vector<std::vector<Cand>> cand(nthreads);
  size_t chunk = (n + nthreads - 1) / nthreads;
  std::vector<std::thread> workers;
  for (size_t t = 1; t < nthreads; ++t) {
    size_t lo = t * chunk, hi = std::min(n, lo + chunk);
    if (lo >= hi) continue;
    workers.emplace_back(scan_block, lo, hi, &cand[t]);
  }
  scan_block(0, std::min(n, chunk), &cand[0]);
  for (auto& th : workers) th.join();
  std::vector<Cand> order;
  order.reserve(nthreads * k_eff);
  for (const auto& c : cand) order.insert(order.end(), c.begin(), c.end());
  std::partial_sort(order.begin(), order.begin() + k_eff, order.end(),
                    cand_lt);
  std::string reply = "V\t";
  for (size_t i = 0; i < k_eff; ++i) {
    if (i) reply.push_back(';');
    reply += ix->ids[order[i].second];
    reply.push_back(':');
    reply += format_score(order[i].first);
  }
  reply.push_back('\n');
  return reply;
}

std::string handle_topk(ServerState* s, const std::string& verb,
                        const std::string& state,
                        const std::string& query_arg,
                        const std::string& k_s) {
  if (s->topk_item_suffix.empty() || state != s->state_name) {
    return "E\tno topk index for state: " + state + "\n";
  }
  errno = 0;
  char* endp = nullptr;
  long k = strtol(k_s.c_str(), &endp, 10);
  if (k_s.empty() || endp != k_s.c_str() + k_s.size()) {
    return "E\ttopk failed: invalid literal for int() with base 10: '" +
           k_s + "'\n";
  }
  if (k < 1) return "E\tk must be >= 1\n";
  if (verb == "TOPKV") return topk_payload(s, query_arg, k);
  // TOPK: resolve the query entity's factors from the store (key
  // "<id><user_suffix>"), then score like TOPKV
  std::string user_key = query_arg + s->topk_user_suffix;
  uint32_t vlen = 0;
  int err = 0;
  char* buf = tpums_get(s->store, user_key.data(),
                        static_cast<uint32_t>(user_key.size()), &vlen, &err);
  if (!buf) return "N\n";
  std::string payload(buf, vlen);
  tpums_free_buf(buf);
  return topk_payload(s, payload, k);
}

// METRICS verb: the per-verb stats as the exact one-line JSON snapshot
// schema obs/metrics.py emits (snapshot + synthesize_requests +
// snapshot_to_json_line — compact separators, meta last), so scrape_fleet
// merges native and Python workers through the same merge_snapshots path.
// The requests counter series is synthesized from the histogram count, the
// errors counter is materialized per verb (value 0 included, matching the
// Python plane's lazily-created-but-always-exported counter).
std::string metrics_reply(ServerState* s) {
  std::map<std::string, VerbStat> stats;
  {
    std::lock_guard<std::mutex> g(s->metrics_mu);
    stats = s->verb_stats;
  }
  double ts = std::chrono::duration<double>(
                  std::chrono::system_clock::now().time_since_epoch())
                  .count();
  std::string j = "J\t{\"ts\":";
  j += format_score_d(ts);
  j += ",\"enabled\":true,\"counters\":[";
  bool first = true;
  for (const auto& kv : stats) {
    if (!first) j.push_back(',');
    first = false;
    j += "{\"name\":\"tpums_server_errors_total\",\"labels\":{\"verb\":\"";
    escape_json_into(j, kv.first);
    j += "\"},\"value\":" + std::to_string(kv.second.errors) + "}";
  }
  for (const auto& kv : stats) {
    if (!first) j.push_back(',');
    first = false;
    j += "{\"name\":\"tpums_server_requests_total\",\"labels\":{\"verb\":\"";
    escape_json_into(j, kv.first);
    j += "\"},\"value\":" + std::to_string(kv.second.count) + "}";
  }
  // profiling plane: per-verb handler CPU self-time (the same numbers the
  // PROFILE verb folds into "native;<verb>" stacks), as counters so the
  // watch plane can rate() them like any other series
  for (const auto& kv : stats) {
    if (!first) j.push_back(',');
    first = false;
    j += "{\"name\":\"tpums_native_self_seconds_total\",\"labels\":"
         "{\"verb\":\"";
    escape_json_into(j, kv.first);
    j += "\"},\"value\":" + format_score_d(kv.second.cpu_s) + "}";
  }
  // arena-backed server: the shared-store gauges + the lock-free path's
  // retry counter ride the same snapshot (obs/scrape fleet_signals reads
  // them off either plane — the Python writer exports the same names)
  double a_rows, a_cap, a_res, a_retry, a_lf;
  bool is_arena = tpums_arena_stats(s->store, &a_rows, &a_cap, &a_res,
                                    &a_retry, &a_lf) == 0;
  if (is_arena) {
    if (!first) j.push_back(',');
    first = false;
    j += "{\"name\":\"tpums_arena_read_retries_total\",\"labels\":{},"
         "\"value\":" + std::to_string(static_cast<uint64_t>(a_retry)) + "}";
    // write-plane counters from the writer.stats sidecar the native batch
    // writer maintains — absent until a native writer has run, so the
    // splice is conditional per call (the handle re-probes the file)
    double b_rows, b_secs, c_succ, c_retry;
    if (tpums_arena_write_stats(s->store, &b_rows, &b_secs, &c_succ,
                                &c_retry) == 0) {
      j += ",{\"name\":\"tpums_arena_batch_rows_total\",\"labels\":{},"
           "\"value\":" +
           std::to_string(static_cast<uint64_t>(b_rows)) +
           "},{\"name\":\"tpums_arena_batch_put_seconds_total\","
           "\"labels\":{},\"value\":" +
           format_score_d(b_secs) +
           "},{\"name\":\"tpums_arena_cas_success_total\",\"labels\":{},"
           "\"value\":" +
           std::to_string(static_cast<uint64_t>(c_succ)) +
           "},{\"name\":\"tpums_arena_cas_retry_total\",\"labels\":{},"
           "\"value\":" +
           std::to_string(static_cast<uint64_t>(c_retry)) + "}";
    }
    // write-plane CPU self-time (sidecar, CLOCK_THREAD_CPUTIME_ID in the
    // batch/CAS writers) — the arena writer's row in the fleet profile
    double w_cpu;
    if (tpums_arena_write_cpu_seconds(s->store, &w_cpu) == 0 &&
        w_cpu > 0.0) {
      j += ",{\"name\":\"tpums_arena_write_cpu_seconds_total\","
           "\"labels\":{},\"value\":" + format_score_d(w_cpu) + "}";
    }
  }
  j += "],\"gauges\":[";
  if (is_arena) {
    j += "{\"name\":\"tpums_arena_rows\",\"labels\":{},\"value\":" +
         std::to_string(static_cast<uint64_t>(a_rows)) +
         "},{\"name\":\"tpums_arena_resident_bytes\",\"labels\":{},"
         "\"value\":" + std::to_string(static_cast<uint64_t>(a_res)) +
         "},{\"name\":\"tpums_arena_index_load_factor\",\"labels\":{},"
         "\"value\":" + format_score_d(a_lf) + "}";
  }
  j += "],\"histograms\":[";
  std::string le;
  for (double b : s->lat_bounds) {
    if (!le.empty()) le.push_back(',');
    le += format_score_d(b);
  }
  first = true;
  for (const auto& kv : stats) {
    if (!first) j.push_back(',');
    first = false;
    j += "{\"name\":\"tpums_server_latency_seconds\",\"labels\":{\"verb\":\"";
    escape_json_into(j, kv.first);
    j += "\"},\"le\":[" + le + "],\"counts\":[";
    for (size_t i = 0; i < kv.second.counts.size(); ++i) {
      if (i) j.push_back(',');
      j += std::to_string(kv.second.counts[i]);
    }
    j += "],\"sum\":" + format_score_d(kv.second.sum);
    j += ",\"count\":" + std::to_string(kv.second.count) + "}";
  }
  j += "],\"meta\":{\"job_id\":\"";
  escape_json_into(j, s->job_id);
  j += "\",\"port\":" + std::to_string(s->port) +
       ",\"plane\":\"native\"}}\n";
  return j;
}

// PROFILE verb: the native plane's contribution to the continuous
// profiling plane, shipped exactly like METRICS — one "P\t<json>" line in
// the obs/profiler.py profile schema.  No sampler runs in C++: handler
// sections are measured directly (CLOCK_THREAD_CPUTIME_ID bracketing in
// observe_verb), so the "stacks" are synthetic two-segment folds
// "native;<verb>" weighted in CPU seconds, plus "native;arena_writer"
// from the batch writer's sidecar.  merge_profiles sums these next to
// Python sample-seconds — one unit, one associative fold, one fleet
// flamegraph.
std::string profile_reply(ServerState* s) {
  std::map<std::string, VerbStat> stats;
  {
    std::lock_guard<std::mutex> g(s->metrics_mu);
    stats = s->verb_stats;
  }
  double ts = std::chrono::duration<double>(
                  std::chrono::system_clock::now().time_since_epoch())
                  .count();
  std::string j = "P\t{\"ts\":";
  j += format_score_d(ts);
  j += ",\"hz\":0,\"enabled\":true,\"samples\":0,\"wall_s\":0.0,"
       "\"unit\":\"seconds\",\"stacks\":{";
  bool first = true;
  for (const auto& kv : stats) {
    if (kv.second.cpu_s <= 0.0) continue;
    if (!first) j.push_back(',');
    first = false;
    j += "\"native;";
    escape_json_into(j, kv.first);
    j += "\":" + format_score_d(kv.second.cpu_s);
  }
  double w_cpu;
  if (tpums_arena_write_cpu_seconds(s->store, &w_cpu) == 0 && w_cpu > 0.0) {
    if (!first) j.push_back(',');
    first = false;
    j += "\"native;arena_writer\":" + format_score_d(w_cpu);
  }
  j += "},\"meta\":{\"job_id\":\"";
  escape_json_into(j, s->job_id);
  j += "\",\"port\":" + std::to_string(s->port) +
       ",\"plane\":\"native\"}}\n";
  return j;
}

// HEALTH verb: the owning job pushes its liveness report (ServingJob.health
// as a JSON object) through tpums_server_set_health on every heartbeat;
// the reply splices in the two server-owned fields — live key count and
// the metrics_uri — exactly where the Python plane appends them.  With no
// pushed report (bare server, tests) the reply is byte-identical to a bare
// Python LookupServer's always-ready report.
std::string health_reply(ServerState* s) {
  std::string pushed;
  {
    std::lock_guard<std::mutex> g(s->health_mu);
    pushed = s->health_json;
  }
  std::string keys = std::to_string(tpums_count(s->store));
  std::string uri =
      "tpums://" + s->host_str + ":" + std::to_string(s->port) + "/METRICS";
  if (pushed.size() >= 2 && pushed.front() == '{' && pushed.back() == '}') {
    std::string inner = pushed.substr(1, pushed.size() - 2);
    std::string body = "{" + inner + (inner.empty() ? "" : ", ") +
                       "\"keys\": " + keys + ", \"metrics_uri\": \"" +
                       escape_json(uri) + "\"}";
    return "H\t" + body + "\n";
  }
  return "H\t{\"state\": \"" + escape_json(s->state_name) +
         "\", \"ready\": true, \"status\": \"ready\", \"backlog_bytes\": 0, "
         "\"keys\": " + keys + ", \"job_id\": \"" + escape_json(s->job_id) +
         "\", \"topology_group\": null, \"generation\": null, "
         "\"topology_gen\": null, \"metrics_uri\": \"" + escape_json(uri) +
         "\"}\n";
}

// Answer a non-TOPK request from its pre-split parts (submit_line owns the
// single split_tabs pass — the point-lookup hot path is parsed once).
std::string handle_line(ServerState* s, const std::string* parts, int n) {
  s->requests.fetch_add(1, std::memory_order_relaxed);
  if (parts[0] == "PING") {  // Python matches on parts[0] alone
    return "PONG\t" + s->job_id + "\t" + s->state_name + "\n";
  }
  if (parts[0] == "HELLO" &&
      (n == 2 || (n == 3 && parts[2] == "tr=1"))) {
    // protocol negotiation (serve/proto.py HELLO_LINE): the caller flips
    // the connection to binary iff this answers the accept line.  The
    // tr=1 extension (proto.TRACE_EXT) negotiates per-record trace
    // fields; route_parts latches it on the Conn when the flip happens.
    if (parts[1] == "B2") return "HELLO\tB2\n";
    return "E\tunsupported proto: " + parts[1] + "\n";
  }
  if (parts[0] == "HEALTH" && n == 2) {
    if (parts[1] != s->state_name) {
      return "E\tunknown state: " + parts[1] + "\n";
    }
    return health_reply(s);
  }
  if (parts[0] == "METRICS" && n == 1) {
    // start2-compat servers (no bucket ladder) keep the historical
    // E\tbad request so their byte-parity pins hold
    if (s->lat_bounds.empty()) return "E\tbad request\n";
    return metrics_reply(s);
  }
  if (parts[0] == "PROFILE" && n == 1) {
    // profiling-plane scrape; start2-compat servers (no ladder, so no
    // verb stats accumulate) keep the historical E, exactly like METRICS
    if (s->lat_bounds.empty()) return "E\tbad request\n";
    return profile_reply(s);
  }
  if (parts[0] == "COUNT" && n == 2) {
    if (parts[1] != s->state_name) {
      return "E\tunknown state: " + parts[1] + "\n";
    }
    return "C\t" + std::to_string(tpums_count(s->store)) + "\n";
  }
  if (parts[0] == "GET" && n == 3) {
    if (parts[1] != s->state_name) {
      return "E\tunknown state: " + parts[1] + "\n";
    }
    uint32_t vlen = 0;
    int err = 0;
    char* buf = tpums_get(s->store, parts[2].data(),
                          static_cast<uint32_t>(parts[2].size()), &vlen, &err);
    if (!buf) {
      return err ? "E\tstore read failed\n" : "N\n";
    }
    std::string reply;
    reply.reserve(vlen + 3);
    reply.append("V\t").append(buf, vlen).push_back('\n');
    tpums_free_buf(buf);
    return reply;
  }
  if (parts[0] == "MGET" && n == 3) {
    if (parts[1] != s->state_name) {
      return "E\tunknown state: " + parts[1] + "\n";
    }
    std::string reply = "M";
    const std::string& keys = parts[2];
    size_t start = 0;
    while (true) {
      size_t comma = keys.find(',', start);
      size_t len =
          (comma == std::string::npos ? keys.size() : comma) - start;
      uint32_t vlen = 0;
      int err = 0;
      char* buf = tpums_get(s->store, keys.data() + start,
                            static_cast<uint32_t>(len), &vlen, &err);
      if (!buf) {
        reply += err ? "\tE" : "\tN";  // per-key store error stays in-slot so
                                       // the batch framing survives
      } else {
        reply += "\tV";
        reply.append(buf, vlen);
        tpums_free_buf(buf);
      }
      if (comma == std::string::npos) break;
      start = comma + 1;
    }
    reply.push_back('\n');
    return reply;
  }
  // TOPK/TOPKV never reach here — submit_line routes them to the worker
  // thread before handle_line is called
  return "E\tbad request\n";
}

// ---------------------------------------------------------------------------
// DOT verb: server-side sparse dot over range-partitioned rows
// (serve/server.py semantics contract — replies are byte-parity-tested
// on exactly-representable fixtures).

// Parse one integer token with Python int() semantics: surrounding ASCII
// whitespace stripped, full consumption required.
bool parse_int_token(const char* b, const char* e, long long* out) {
  while (b < e && (*b == ' ' || *b == '\t' || *b == '\r' || *b == '\n'))
    ++b;
  while (e > b && (e[-1] == ' ' || e[-1] == '\t' || e[-1] == '\r' ||
                   e[-1] == '\n'))
    --e;
  if (b >= e) return false;
  std::string tok(b, e);
  errno = 0;
  char* endp = nullptr;
  long long v = strtoll(tok.c_str(), &endp, 10);
  if (errno != 0 || endp != tok.c_str() + tok.size()) return false;
  *out = v;
  return true;
}

// Parse "<int>:<float>" pairs out of a ';'-separated payload with the
// Python planes' exact acceptance rules (serve/server.py DOT query parse
// and core/formats parse_svm_range_payload): ALL trailing semicolons are
// stripped, an EMPTY interior segment rejects the whole payload, each
// segment carries exactly one colon, and numbers may be whitespace-padded
// (Python int()/float() strip).  Rows/queries with any malformed token
// are rejected whole.
bool parse_pairs(const std::string& payload,
                 std::vector<std::pair<long long, double>>* out) {
  size_t n = payload.size();
  while (n > 0 && payload[n - 1] == ';') --n;  // rstrip(';') parity
  size_t start = 0;
  while (start < n) {
    size_t semi = payload.find(';', start);
    if (semi == std::string::npos || semi > n) semi = n;
    if (semi == start) return false;  // empty interior segment
    size_t colon = payload.find(':', start);
    if (colon == std::string::npos || colon >= semi) return false;
    // exactly one colon per segment (Python's colon-count check)
    if (payload.find(':', colon + 1) < semi) return false;
    long long fid = 0;
    if (!parse_int_token(payload.c_str() + start,
                         payload.c_str() + colon, &fid)) {
      return false;
    }
    double val = 0.0;
    if (!parse_float_token(payload.c_str() + colon + 1,
                           payload.c_str() + semi, &val)) {
      return false;
    }
    out->emplace_back(fid, val);
    start = semi + 1;
  }
  return true;
}

std::shared_ptr<const DotIndex> build_dot_index(ServerState* s) {
  auto ix = std::make_shared<DotIndex>();
  ix->ver_count = tpums_count(s->store);
  ix->ver_bytes = tpums_log_bytes(s->store);
  std::vector<std::string> keys;
  uint64_t cursor = 0;
  while (tpums_keys_chunk(
             s->store, &cursor, 8192,
             [](const char* key, uint32_t klen, void* ctx) {
               static_cast<std::vector<std::string>*>(ctx)->emplace_back(
                   key, klen);
             },
             &keys) > 0) {
  }
  // rows concatenate in ASCENDING BUCKET order on both planes (the store
  // iterates hash buckets, the Python table dict shards — neither is
  // publish order, so cross-row duplicate-fid last-wins would otherwise
  // resolve differently per plane for identical contents)
  std::vector<std::pair<long long, std::string>> rows;
  for (const std::string& key : keys) {
    if (key.empty() || key[0] == '\x01') continue;  // store-internal
    long long bucket = 0;
    if (!parse_int_token(key.c_str(), key.c_str() + key.size(), &bucket))
      continue;
    uint32_t vlen = 0;
    int err = 0;
    char* buf = tpums_get(s->store, key.data(),
                          static_cast<uint32_t>(key.size()), &vlen, &err);
    if (!buf) continue;
    rows.emplace_back(bucket, std::string(buf, vlen));
    tpums_free_buf(buf);
  }
  std::sort(rows.begin(), rows.end(),
            [](const std::pair<long long, std::string>& a,
               const std::pair<long long, std::string>& b) {
              return a.first < b.first;
            });
  std::vector<std::pair<long long, double>> pairs;
  for (const auto& row : rows) {
    size_t before = pairs.size();
    if (!parse_pairs(row.second, &pairs)) {
      pairs.resize(before);  // not an idx:w;... row (e.g. flat model)
      continue;
    }
    ix->buckets.insert(row.first);
  }
  // ascending by fid, duplicate ids last-wins (stable sort keeps input
  // order within a run of equal ids — sort_dedup_last parity)
  std::stable_sort(pairs.begin(), pairs.end(),
                   [](const std::pair<long long, double>& a,
                      const std::pair<long long, double>& b) {
                     return a.first < b.first;
                   });
  ix->fids.reserve(pairs.size());
  ix->ws.reserve(pairs.size());
  for (size_t i = 0; i < pairs.size(); ++i) {
    if (i + 1 < pairs.size() && pairs[i + 1].first == pairs[i].first)
      continue;
    ix->fids.push_back(pairs[i].first);
    ix->ws.push_back(pairs[i].second);
  }
  return ix;
}

std::shared_ptr<const DotIndex> get_dot_index(ServerState* s) {
  uint64_t count = tpums_count(s->store);
  uint64_t bytes = tpums_log_bytes(s->store);
  std::shared_ptr<const DotIndex> cur;
  {
    std::lock_guard<std::mutex> g(s->dot_mu);
    cur = s->dot_cur;
  }
  if (cur && cur->ver_count == count && cur->ver_bytes == bytes) return cur;
  if (!cur) {  // first build: only queued worker tasks wait
    cur = build_dot_index(s);
    std::lock_guard<std::mutex> g(s->dot_mu);
    s->dot_cur = cur;
    return cur;
  }
  bool expected = false;
  if (s->dot_building.compare_exchange_strong(expected, true)) {
    if (s->dot_builder.joinable()) s->dot_builder.join();
    s->dot_builder = std::thread([s]() {
      auto fresh = build_dot_index(s);
      {
        std::lock_guard<std::mutex> g(s->dot_mu);
        s->dot_cur = std::move(fresh);
      }
      s->dot_building.store(false, std::memory_order_release);
    });
  }
  return cur;  // briefly stale while the rebuild runs
}

std::string handle_dot(ServerState* s, const std::string& state,
                       const std::string& range_s,
                       const std::string& payload) {
  if (state != s->state_name) {
    return "E\tunknown state: " + state + "\n";
  }
  long long range_ = 0;
  if (!parse_int_token(range_s.c_str(), range_s.c_str() + range_s.size(),
                       &range_)) {
    return "E\tdot failed: invalid literal for int() with base 10: '" +
           range_s + "'\n";
  }
  if (range_ < 1) return "E\trange must be >= 1\n";
  std::vector<std::pair<long long, double>> q;
  if (!parse_pairs(payload, &q)) {
    // message parity: the Python plane reports repr(stripped[:40])
    std::string stripped = payload;
    while (!stripped.empty() && stripped.back() == ';') stripped.pop_back();
    return "E\tdot failed: malformed pair in '" +
           stripped.substr(0, 40) + "'\n";
  }
  std::shared_ptr<const DotIndex> ix = get_dot_index(s);
  double acc = 0.0;
  std::set<long long> missing;
  for (const auto& fv : q) {
    auto it = std::lower_bound(ix->fids.begin(), ix->fids.end(), fv.first);
    if (it != ix->fids.end() && *it == fv.first) {
      acc += fv.second * ix->ws[it - ix->fids.begin()];
    } else {
      // floor division, matching Python's // for any sign
      long long b = fv.first / range_;
      if ((fv.first % range_ != 0) && ((fv.first < 0) != (range_ < 0)))
        --b;
      if (!ix->buckets.count(b)) missing.insert(b);
    }
  }
  std::string reply = "D\t" + format_score_d(acc) + "\t";
  bool first = true;
  for (long long b : missing) {
    if (!first) reply.push_back(',');
    reply += std::to_string(b);
    first = false;
  }
  reply.push_back('\n');
  return reply;
}

// Dedicated top-k worker: pops tasks, computes the (possibly O(catalog))
// reply off the epoll thread, publishes it into the connection's reply
// slot, and pokes the event loop via the eventfd to flush.
void topk_worker_loop(ServerState* s) {
  uint64_t one = 1;
  while (true) {
    TopkTask task;
    {
      std::unique_lock<std::mutex> lk(s->task_mu);
      s->task_cv.wait(lk, [s] {
        return s->worker_stop.load(std::memory_order_acquire) ||
               !s->tasks.empty();
      });
      if (s->worker_stop.load(std::memory_order_acquire)) return;
      task = std::move(s->tasks.front());
      s->tasks.pop_front();
    }
    if (task.reply.use_count() > 1) {  // conn still holds its slot — a
      // closed connection's orphaned tasks skip the O(catalog) work
      double t_pop = now_s();
      double c0 = thread_cpu_s();
      task.reply->text =
          task.verb == "DOT"
              ? handle_dot(s, task.state, task.k_s, task.query_arg)
              : handle_topk(s, task.verb, task.state, task.query_arg,
                            task.k_s);
      // latency includes queue wait (t0 is submit time), mirroring the
      // Python plane's deferred-reply observation at resolve time; an
      // orphaned task is never observed — its Python twin (handler thread
      // gone mid-request) never reaches _finish either.  CPU self-time
      // deliberately does NOT include queue wait: it brackets the worker
      // section only, so the profile says what the core burned, not what
      // the queue delayed.
      double t_done = now_s();
      bool is_err =
          !task.reply->text.empty() && task.reply->text[0] == 'E';
      observe_verb(s, task.verb, t_done - task.t0, is_err,
                   thread_cpu_s() - c0);
      if (!task.tid.empty()) {
        // queue wait vs device/serve split is exactly what the slow-vs-
        // fast diff attributes, so spill both
        trace_spill(s, task.tid, task.verb, task.t0_wall,
                    t_done - task.t0, t_pop - task.t0, t_done - t_pop,
                    is_err);
      }
    }
    task.reply->ready.store(true, std::memory_order_release);
    ssize_t wr = write(s->wake_fd, &one, 8);
    (void)wr;
  }
}

// Move every completed output unit at the FRONT of the queue into the
// connection's out buffer (strict FIFO: an unfinished TOPK blocks only
// replies behind it on ITS connection).  A tab-mode unit is one reply
// line; a B2 unit is a whole reply frame and emits only when every record
// in it is ready, because the frame header carries the total length.
void drain_ready_replies(Conn* c) {
  while (!c->units.empty()) {
    const OutUnit& u = c->units.front();
    if (c->pending.size() < u.count) break;  // defensive: never expected
    bool all_ready = true;
    for (uint32_t i = 0; i < u.count && all_ready; ++i) {
      all_ready = c->pending[i]->ready.load(std::memory_order_acquire);
    }
    if (!all_ready) break;
    if (!u.frame) {
      const PendingReply& pr = *c->pending.front();
      if (!pr.tid.empty() && !pr.text.empty() && pr.text.back() == '\n') {
        // deferred tab reply: append the raw tid echo before the newline
        // (inline replies get theirs inserted at route time)
        std::string line;
        line.reserve(pr.text.size() + pr.tid.size() + 6);
        line.append(pr.text, 0, pr.text.size() - 1);
        line += "\ttid=";
        line += pr.tid;
        line.push_back('\n');
        c->out.take(std::move(line));
      } else {
        c->out.append(pr.text);
      }
    } else {
      std::string body;
      append_varint(body, u.count);
      for (uint32_t i = 0; i < u.count; ++i) {
        const std::string& t = c->pending[i]->text;
        size_t len = t.size();
        if (len && t[len - 1] == '\n') --len;  // reply record = line sans \n
        append_varint(body, len);
        body.append(t.data(), len);
      }
      std::string frame;
      frame.reserve(body.size() + 12);
      frame += "B2";
      append_varint(frame, body.size());
      frame += body;
      c->out.take(std::move(frame));
    }
    for (uint32_t i = 0; i < u.count; ++i) {
      c->pending_req_bytes -= c->pending.front()->req_bytes;
      c->pending.pop_front();
    }
    c->units.pop_front();
  }
}

// Route one request's pre-split parts: TOPK verbs are enqueued for the
// worker thread (reply slot keeps pipelined order); everything else
// answers inline.  `src_bytes` is the wire size of the request (line or
// binary record) for the pending-byte cap; `always_slot` (binary records)
// forces even inline replies through the pending queue so the enclosing
// frame unit can group them.  Returns false when the connection must
// close (pending-flood protection).
bool route_parts(ServerState* s, Conn* c, std::string* parts, int n,
                 size_t src_bytes, bool always_slot,
                 const std::string& tid) {
  if ((parts[0] == "TOPK" || parts[0] == "TOPKV" || parts[0] == "DOT") &&
      n == 4) {
    s->requests.fetch_add(1, std::memory_order_relaxed);
    // slot-count AND byte cap: queued tasks copy the request payload, so
    // a flood of max-size TOPKV lines must trip the same slow-reader
    // policy as buffered responses, not grow the heap unboundedly
    if (c->pending.size() >= kMaxPendingReplies ||
        c->pending_req_bytes + src_bytes > kMaxOutBuffer) {
      return false;
    }
    auto reply = std::make_shared<PendingReply>();
    reply->req_bytes = src_bytes;
    // tab replies echo the raw tid back (drain_ready_replies appends it);
    // B2 replies never carry the tid — the client pairs them by order
    if (!always_slot) reply->tid = tid;
    c->pending_req_bytes += src_bytes;
    c->pending.push_back(reply);
    if (!always_slot) c->units.push_back(OutUnit{false, 1});
    // TOPK operands: state, id, k; TOPKV operands: state, k, payload;
    // DOT operands: state, range, payload (range rides the k_s slot)
    TopkTask task{std::move(reply), parts[0], parts[1],
                  parts[0] == "TOPK" ? parts[2] : parts[3],
                  parts[0] == "TOPK" ? parts[3] : parts[2], now_s(),
                  tid, tid.empty() ? 0.0 : wall_s()};
    {
      std::lock_guard<std::mutex> lk(s->task_mu);
      s->tasks.push_back(std::move(task));
    }
    s->task_cv.notify_one();
    return true;
  }
  double t0 = now_s();
  double t0_wall = tid.empty() ? 0.0 : wall_s();
  double c0 = thread_cpu_s();
  std::string text = handle_line(s, parts, n);
  double dt = now_s() - t0;
  bool is_err = !text.empty() && text[0] == 'E';
  observe_verb(s, parts[0], dt, is_err, thread_cpu_s() - c0);
  if (!tid.empty()) {
    trace_spill(s, tid, parts[0], t0_wall, dt, 0.0, dt, is_err);
  }
  if (parts[0] == "HELLO" && !c->binary && text[0] == 'H' && tid.empty()) {
    // negotiation accepted: every byte after this line is a B2 frame and
    // every reply after this line's is a B2 frame.  A HELLO that carried
    // a tid= stamp stays in tab mode (Python-plane parity: parse_hello
    // rejects the tid extension, so the reply is echoed but the framing
    // never flips).
    c->binary = true;
    if (n == 3) c->b2_trace = true;  // handle_line only accepts tr=1 at n==3
  }
  if (!always_slot && !tid.empty() && !text.empty() && text.back() == '\n') {
    text.insert(text.size() - 1, "\ttid=" + tid);
  }
  if (!always_slot && c->pending.empty()) {
    c->out.take(std::move(text));
  } else {
    // an async reply is still in flight ahead of us (or a frame needs the
    // slot): preserve reply order.  Parked reply text counts against the
    // same byte cap as queued TOPK payloads — the slow-reader check only
    // sees c->out, and a client pipelining GETs behind a slow TOPK
    // without reading must not grow the pending queue unboundedly.
    if (c->pending.size() >= kMaxPendingReplies ||
        c->pending_req_bytes + text.size() > kMaxOutBuffer) {
      return false;
    }
    auto slot = std::make_shared<PendingReply>();
    slot->req_bytes = text.size();
    c->pending_req_bytes += text.size();
    slot->text = std::move(text);
    slot->ready.store(true, std::memory_order_release);
    c->pending.push_back(std::move(slot));
    if (!always_slot) c->units.push_back(OutUnit{false, 1});
  }
  return true;
}

bool submit_line(ServerState* s, Conn* c, const std::string& line) {
  // 5 slots: one more than the widest verb, so an over-long request is
  // distinguishable from an exact TOPK (Python splits unbounded; parity
  // demands "TOPK\ta\tb\tc\td" be a bad request, not a TOPK)
  std::string parts[5];
  // trailing ``\ttid=<raw>`` trace stamp (obs/tracing.pop_tid parity:
  // strip it BEFORE the split so a stamped TOPK still parses as n==4);
  // the value never contains a tab, so "last field" == "no tab after"
  size_t tp = line.rfind("\ttid=");
  if (tp != std::string::npos && tp > 0 && tp + 5 < line.size() &&
      line.find('\t', tp + 1) == std::string::npos) {
    std::string tid = line.substr(tp + 5);
    int n = split_tabs(line.substr(0, tp), parts, 5);
    return route_parts(s, c, parts, n, line.size(), false, tid);
  }
  int n = split_tabs(line, parts, 5);
  return route_parts(s, c, parts, n, line.size(), false, std::string());
}

// Queue the structural-corruption reply (one-record error frame, matching
// serve/proto.error_frame) and poison the connection: it serves what is
// already in flight, flushes, then closes.  Never called for per-verb
// semantic errors — those stay in-slot as ordinary E records.
int fatal_frame(Conn* c, const char* reason) {
  auto slot = std::make_shared<PendingReply>();
  slot->text = std::string("E\tbad frame: ") + reason + "\n";
  slot->ready.store(true, std::memory_order_release);
  c->pending.push_back(std::move(slot));
  c->units.push_back(OutUnit{true, 1});
  c->fatal = true;
  c->in.clear();
  return -1;
}

// Parse ONE complete B2 request frame off c->in and dispatch its records
// as a single burst (one reply frame).  Returns 0 = need more bytes,
// 1 = consumed a frame, -1 = poisoned (error frame queued), -2 = hard
// close (pending-flood caps).  Structural corruption poisons the whole
// connection — record boundaries inside a frame depend on every earlier
// record decoding, so there is no trustworthy resync point.
int parse_one_frame(ServerState* s, Conn* c) {
  const std::string& in = c->in;
  if (in.empty()) return 0;
  if (in[0] != 'B') return fatal_frame(c, "bad magic");
  if (in.size() < 2) return 0;
  if (in[1] != '2') return fatal_frame(c, "bad magic");
  size_t pos = 2;
  uint64_t body_len = 0;
  int vr = parse_varint(in.data(), in.size(), &pos, &body_len);
  if (vr == 1) return 0;
  if (vr == 2) return fatal_frame(c, "bad varint");
  if (body_len > kMaxFrameBody) return fatal_frame(c, "frame too large");
  if (in.size() - pos < body_len) return 0;
  size_t end = pos + body_len;
  uint64_t count = 0;
  vr = parse_varint(in.data(), end, &pos, &count);
  if (vr != 0) return fatal_frame(c, "bad body");
  // decode ALL records before dispatching any: a frame either fully
  // parses or is rejected whole (serve/proto.decode_request_frame parity)
  std::vector<std::vector<std::string>> records;
  std::vector<size_t> rec_bytes;
  std::vector<std::string> rec_tids;
  records.reserve(count);
  // tr=1 connections carry ONE extra trailing length-prefixed field per
  // record — the raw trace id, empty for untraced requests (the Python
  // encoder's record_to_parts/record_from_line twin)
  const int extra = c->b2_trace ? 1 : 0;
  for (uint64_t r = 0; r < count; ++r) {
    size_t rec_start = pos;
    if (pos >= end) return fatal_frame(c, "bad body");
    int op = static_cast<uint8_t>(in[pos++]);
    if (op < 1 || op > kMaxOpcode) return fatal_frame(c, "bad body");
    const VerbSpec& spec = kVerbByOp[op];
    std::vector<std::string> parts;
    parts.reserve(spec.fields + 1 + extra);
    parts.emplace_back(spec.verb);
    for (int f = 0; f < spec.fields + extra; ++f) {
      uint64_t flen = 0;
      vr = parse_varint(in.data(), end, &pos, &flen);
      if (vr != 0 || pos + flen > end) return fatal_frame(c, "bad body");
      if (!utf8_valid(in.data() + pos, flen))
        return fatal_frame(c, "bad body");
      parts.emplace_back(in.data() + pos, flen);
      pos += flen;
    }
    std::string rtid;
    if (extra) {
      rtid = std::move(parts.back());
      parts.pop_back();
    }
    rec_tids.push_back(std::move(rtid));
    rec_bytes.push_back(pos - rec_start);
    records.push_back(std::move(parts));
  }
  if (pos != end) return fatal_frame(c, "bad body");
  for (size_t r = 0; r < records.size(); ++r) {
    std::string parts[5];
    int n = static_cast<int>(records[r].size());
    for (int i = 0; i < n; ++i) parts[i] = std::move(records[r][i]);
    if (!route_parts(s, c, parts, n, rec_bytes[r], true, rec_tids[r])) {
      return -2;
    }
  }
  c->units.push_back(
      OutUnit{true, static_cast<uint32_t>(records.size())});
  c->in.erase(0, end);
  return 1;
}

// Answer every complete request buffered in c->in — lines until the
// connection negotiates B2, frames after.  false = close the conn
// (pending-flood protection tripped); a poisoned conn (corrupt frame)
// returns true so its queued error frame still flushes before close.
bool drain_lines(ServerState* s, Conn* c) {
  while (true) {
    if (c->fatal) {
      c->in.clear();
      return true;
    }
    if (!c->binary) {
      size_t start = 0;
      bool ok = true;
      while (ok && !c->binary) {
        size_t nl = c->in.find('\n', start);
        if (nl == std::string::npos) break;
        ok = submit_line(s, c, c->in.substr(start, nl - start));
        start = nl + 1;
      }
      c->in.erase(0, start);
      if (!ok) return false;
      if (!c->binary) return true;  // no more complete lines buffered
      continue;  // HELLO flipped the mode: the remainder is frames
    }
    int r = parse_one_frame(s, c);
    if (r == 0) return true;
    if (r == -1) return true;  // poisoned: error frame queued
    if (r == -2) return false;
  }
}

void arm_writable(ServerState* s, Conn* c, bool want) {
  if (c->writable_armed == want) return;
  epoll_event ev{};
  ev.events = EPOLLIN | (want ? EPOLLOUT : 0u);
  ev.data.fd = c->fd;
  if (epoll_ctl(s->epoll_fd, EPOLL_CTL_MOD, c->fd, &ev) == 0) {
    c->writable_armed = want;
  }
}

void close_conn(ServerState* s, int fd) {
  epoll_ctl(s->epoll_fd, EPOLL_CTL_DEL, fd, nullptr);
  close(fd);
  s->conns.erase(fd);
}

// -- syscall-batched reply flush -------------------------------------------
// Replies are never sent from the parse/handle path: producers mark the
// connection dirty and the END of each epoll batch flushes every dirty
// connection at once — one scatter-gather sendmsg per connection per
// wakeup (the whole backlog in one iovec array), or, when io_uring is
// live, one SQE per connection and ONE io_uring_enter for all of them.
// A 64-GET B2 frame thus costs one reply syscall, not 64; cross-
// connection bursts share the same single enter.

constexpr size_t kMaxFlushIov = 32;  // per-conn scatter width per shot

void mark_dirty(ServerState* s, Conn* c) {
  if (!c->dirty) {
    c->dirty = true;
    s->dirty_fds.push_back(c->fd);
  }
}

// One sendmsg shot for this conn's backlog.  Leftover bytes (partial send
// or EAGAIN) arm EPOLLOUT — the next wakeup re-batches them.  false =
// peer gone.
bool flush_conn_send(ServerState* s, Conn* c) {
  if (c->out.empty()) {
    arm_writable(s, c, false);
    return true;
  }
  struct iovec iov[kMaxFlushIov];
  size_t niov = c->out.fill_iov(iov, kMaxFlushIov);
  struct msghdr mh;
  memset(&mh, 0, sizeof(mh));
  mh.msg_iov = iov;
  mh.msg_iovlen = niov;
  ssize_t w = sendmsg(c->fd, &mh, MSG_NOSIGNAL);
  s->reply_syscalls.fetch_add(1, std::memory_order_relaxed);
  if (w < 0) {
    if (errno == EAGAIN || errno == EWOULDBLOCK) {
      arm_writable(s, c, true);
      return true;
    }
    return false;  // peer gone
  }
  s->reply_bytes.fetch_add(static_cast<uint64_t>(w),
                           std::memory_order_relaxed);
  c->out.consume(static_cast<size_t>(w));
  arm_writable(s, c, !c->out.empty());
  return true;
}

#ifdef TPUMS_HAVE_URING
// Batched path: stage IORING_OP_SENDMSG SQEs for every conn in `cs`, one
// enter per chunk of ring entries.  Failed conns are appended to doomed.
void flush_uring(ServerState* s, std::vector<Conn*>& cs,
                 std::vector<int>* doomed) {
  Uring* u = &s->uring;
  size_t done = 0;
  std::vector<struct msghdr> msgs(cs.size());
  std::vector<std::array<struct iovec, kMaxFlushIov>> iovs(cs.size());
  while (done < cs.size()) {
    size_t n = std::min(cs.size() - done, static_cast<size_t>(u->entries));
    unsigned tail = *u->sq_tail;  // single submitter: plain read is fine
    for (size_t i = 0; i < n; ++i) {
      Conn* c = cs[done + i];
      size_t niov = c->out.fill_iov(iovs[done + i].data(), kMaxFlushIov);
      struct msghdr* mh = &msgs[done + i];
      memset(mh, 0, sizeof(*mh));
      mh->msg_iov = iovs[done + i].data();
      mh->msg_iovlen = niov;
      unsigned idx = (tail + i) & *u->sq_mask;
      io_uring_sqe* sqe = &u->sqes[idx];
      memset(sqe, 0, sizeof(*sqe));
      sqe->opcode = IORING_OP_SENDMSG;
      sqe->fd = c->fd;
      sqe->addr = reinterpret_cast<uint64_t>(mh);
      // MSG_DONTWAIT: complete with -EAGAIN instead of parking in the
      // kernel's poll-retry — one slow reader must not stall the loop
      sqe->msg_flags = MSG_NOSIGNAL | MSG_DONTWAIT;
      sqe->user_data = static_cast<uint64_t>(done + i);
      u->sq_array[idx] = idx;
    }
    __atomic_store_n(u->sq_tail, tail + n, __ATOMIC_RELEASE);
    int r = static_cast<int>(syscall(__NR_io_uring_enter, u->ring_fd,
                                     static_cast<unsigned>(n),
                                     static_cast<unsigned>(n),
                                     IORING_ENTER_GETEVENTS, nullptr, 0));
    s->reply_syscalls.fetch_add(1, std::memory_order_relaxed);
    if (r < 0) {
      // ring wedged (should not happen): degrade to direct sendmsg so
      // replies still flow
      for (size_t i = 0; i < n; ++i) {
        if (!flush_conn_send(s, cs[done + i]))
          doomed->push_back(cs[done + i]->fd);
      }
      done += n;
      continue;
    }
    unsigned chead = __atomic_load_n(u->cq_head, __ATOMIC_RELAXED);
    unsigned ctail = __atomic_load_n(u->cq_tail, __ATOMIC_ACQUIRE);
    for (; chead != ctail; ++chead) {
      io_uring_cqe* cqe = &u->cqes[chead & *u->cq_mask];
      size_t ci = static_cast<size_t>(cqe->user_data);
      if (ci >= cs.size()) continue;  // defensive
      Conn* c = cs[ci];
      if (cqe->res >= 0) {
        s->reply_bytes.fetch_add(static_cast<uint64_t>(cqe->res),
                                 std::memory_order_relaxed);
        c->out.consume(static_cast<size_t>(cqe->res));
        arm_writable(s, c, !c->out.empty());
      } else if (cqe->res == -EAGAIN || cqe->res == -EWOULDBLOCK) {
        arm_writable(s, c, true);
      } else {
        doomed->push_back(c->fd);
      }
    }
    __atomic_store_n(u->cq_head, chead, __ATOMIC_RELEASE);
    done += n;
  }
}
#endif  // TPUMS_HAVE_URING

// End-of-batch flush: send every dirty connection's backlog, then run the
// deferred close checks (slow reader, half-closed/poisoned and fully
// answered) that used to piggyback on the per-event flush.
void flush_batch(ServerState* s, std::vector<int>* doomed) {
  if (s->dirty_fds.empty()) return;
  auto is_doomed = [doomed](int fd) {
    return std::find(doomed->begin(), doomed->end(), fd) != doomed->end();
  };
  std::vector<Conn*> flushable;
  std::vector<Conn*> sendable;
  for (int fd : s->dirty_fds) {
    auto it = s->conns.find(fd);
    if (it == s->conns.end()) continue;
    it->second.dirty = false;
    if (is_doomed(fd)) continue;
    flushable.push_back(&it->second);
    if (!it->second.out.empty()) sendable.push_back(&it->second);
  }
  s->dirty_fds.clear();
#ifdef TPUMS_HAVE_URING
  if (s->uring_on && !sendable.empty()) {
    flush_uring(s, sendable, doomed);
  } else
#endif
  {
    for (Conn* c : sendable) {
      if (!flush_conn_send(s, c)) doomed->push_back(c->fd);
    }
  }
  for (Conn* c : flushable) {
    if (is_doomed(c->fd)) continue;
    bool ok = true;
    if (c->out.size() > kMaxOutBuffer) ok = false;  // slow reader
    if (ok && (c->eof || c->fatal) && c->out.empty() && c->pending.empty())
      ok = false;  // half-closed/poisoned and fully answered
    if (!ok) doomed->push_back(c->fd);
  }
}

// Read available bytes, answer every complete request; false = close.
// Replies queue in c->out — the end-of-batch flush_batch sends them.
bool on_readable(ServerState* s, Conn* c) {
  char chunk[kReadChunk];
  for (int chunks = 0; chunks < kMaxChunksPerEvent; ++chunks) {
    ssize_t r = recv(c->fd, chunk, sizeof(chunk), 0);
    s->recv_calls.fetch_add(1, std::memory_order_relaxed);
    if (r > 0) {
      c->in.append(chunk, static_cast<size_t>(r));
      // parse as we go so the cap bounds ONE request line/frame, not a
      // burst of pipelined small requests (binary frames get the bigger
      // frame-body cap; an over-declared length already poisoned the conn)
      if (!drain_lines(s, c)) return false;
      size_t in_cap = c->binary ? kMaxFrameBody + 16 : kMaxLine;
      if (c->in.size() > in_cap) return false;   // oversized request
      if (c->out.size() > kMaxOutBuffer) return false;  // slow reader
      continue;
    }
    if (r == 0) {  // orderly half-close: still answer the buffered requests
      c->eof = true;
      break;
    }
    if (errno == EAGAIN || errno == EWOULDBLOCK) break;
    return false;
  }
  if (!drain_lines(s, c)) return false;
  if (c->eof && !c->in.empty() && !c->binary && !c->fatal) {
    // final line without '\n': readline()-at-EOF answers it, so we do too
    // (tab mode only — a partial binary frame at EOF is dropped silently,
    // matching the Python plane's frame loop)
    bool ok = submit_line(s, c, c->in);
    c->in.clear();
    if (!ok) return false;
  }
  drain_ready_replies(c);
  mark_dirty(s, c);
  return true;
}

void event_loop(ServerState* s) {
  epoll_event events[64];
  // Closes are DEFERRED to the end of each epoll batch: closing an fd
  // mid-batch lets accept() reuse the number within the same batch, and a
  // stale event later in the batch would then hit the brand-new
  // connection.  Keeping doomed fds open (just marked) until the batch
  // ends makes fd reuse impossible while any of its events are pending.
  std::vector<int> doomed;
  auto is_doomed = [&doomed](int fd) {
    return std::find(doomed.begin(), doomed.end(), fd) != doomed.end();
  };
  while (!s->stop.load(std::memory_order_acquire)) {
    int n = epoll_wait(s->epoll_fd, events, 64, -1);
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    doomed.clear();
    for (int i = 0; i < n; ++i) {
      int fd = events[i].data.fd;
      uint32_t ev = events[i].events;
      if (fd == s->wake_fd) {
        uint64_t tok;
        ssize_t rd = read(s->wake_fd, &tok, 8);
        (void)rd;
        // the worker finished one or more top-k replies: collect every
        // connection whose pending front is now ready; the end-of-batch
        // flush sends them all in one syscall round
        for (auto& kv : s->conns) {
          if (is_doomed(kv.first)) continue;
          Conn* cc = &kv.second;
          drain_ready_replies(cc);
          mark_dirty(s, cc);
        }
        continue;  // stop flag is checked at the top of the loop
      }
      if (fd == s->listen_fd) {
        while (true) {
          int cfd = accept(s->listen_fd, nullptr, nullptr);
          if (cfd < 0) break;  // EAGAIN or transient error: try next wakeup
          if (!set_nonblocking(cfd)) {
            close(cfd);
            continue;
          }
          int one = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          epoll_event cev{};
          cev.events = EPOLLIN;
          cev.data.fd = cfd;
          if (epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, cfd, &cev) != 0) {
            close(cfd);
            continue;
          }
          s->conns[cfd].fd = cfd;
        }
        continue;
      }
      if (is_doomed(fd)) continue;
      auto it = s->conns.find(fd);
      if (it == s->conns.end()) continue;
      Conn* c = &it->second;
      bool ok = true;
      if (ev & EPOLLERR) ok = false;
      if (ok && (ev & EPOLLIN)) ok = on_readable(s, c);
      if (ok && (ev & EPOLLOUT)) mark_dirty(s, c);
      // the half-closed/poisoned close checks run in flush_batch, after
      // this batch's single syscall round has sent what it can
      if (!ok) doomed.push_back(fd);
    }
    flush_batch(s, &doomed);
    for (int fd : doomed) close_conn(s, fd);
  }
  for (auto& kv : s->conns) close(kv.first);
  s->conns.clear();
}

void destroy(ServerState* s) {
  if (s->listen_fd >= 0) close(s->listen_fd);
  if (s->wake_fd >= 0) close(s->wake_fd);
  if (s->epoll_fd >= 0) close(s->epoll_fd);
#ifdef TPUMS_HAVE_URING
  uring_destroy(&s->uring);
#endif
  delete s;
}

}  // namespace

extern "C" {

void* tpums_server_start3(void* store, const char* state_name,
                          const char* job_id, const char* host, int port,
                          const char* topk_item_suffix,
                          const char* topk_user_suffix,
                          const double* latency_bounds, int n_bounds) {
  if (!store || !state_name) return nullptr;
  auto* s = new ServerState();
  s->store = store;
  s->state_name = state_name;
  s->job_id = job_id ? job_id : "local";
  s->topk_item_suffix = topk_item_suffix ? topk_item_suffix : "";
  s->topk_user_suffix = topk_user_suffix ? topk_user_suffix : "";
  s->host_str = (host && *host) ? host : "0.0.0.0";
  // latency bucket ladder: handed over as the exact doubles of
  // obs/metrics.LATENCY_BUCKETS_S — re-deriving the log ladder here would
  // risk float-math drift and merge_snapshots silently skipping the series
  if (latency_bounds && n_bounds > 0) {
    s->lat_bounds.assign(latency_bounds, latency_bounds + n_bounds);
  }

  s->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    destroy(s);
    return nullptr;
  }
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (!host || !*host || strcmp(host, "0.0.0.0") == 0) {
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
  } else if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    destroy(s);
    return nullptr;
  }
  if (bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
          0 ||
      listen(s->listen_fd, 128) != 0 || !set_nonblocking(s->listen_fd)) {
    destroy(s);
    return nullptr;
  }
  sockaddr_in bound{};
  socklen_t blen = sizeof(bound);
  if (getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&bound), &blen) !=
      0) {
    destroy(s);
    return nullptr;
  }
  s->port = ntohs(bound.sin_port);

  s->epoll_fd = epoll_create1(0);
  s->wake_fd = eventfd(0, EFD_NONBLOCK);
  if (s->epoll_fd < 0 || s->wake_fd < 0) {
    destroy(s);
    return nullptr;
  }
  epoll_event lev{};
  lev.events = EPOLLIN;
  lev.data.fd = s->listen_fd;
  epoll_event wev{};
  wev.events = EPOLLIN;
  wev.data.fd = s->wake_fd;
  if (epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, s->listen_fd, &lev) != 0 ||
      epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, s->wake_fd, &wev) != 0) {
    destroy(s);
    return nullptr;
  }
  // Reply-path batching backend: io_uring when the build found kernel
  // headers AND the runtime setup probe succeeds (seccomp or an old
  // kernel fail it cleanly), else the epoll + sendmsg scatter-gather
  // fallback.  TPUMS_URING=0 forces the fallback.
  const char* ue = getenv("TPUMS_URING");
  bool want_uring = !(ue && ue[0] == '0' && ue[1] == '\0');
#ifdef TPUMS_HAVE_URING
  if (want_uring) s->uring_on = uring_init(&s->uring, 64);
#else
  (void)want_uring;
#endif
  s->loop = std::thread(event_loop, s);
  s->topk_worker = std::thread(topk_worker_loop, s);
  return s;
}

void* tpums_server_start2(void* store, const char* state_name,
                          const char* job_id, const char* host, int port,
                          const char* topk_item_suffix,
                          const char* topk_user_suffix) {
  return tpums_server_start3(store, state_name, job_id, host, port,
                             topk_item_suffix, topk_user_suffix, nullptr, 0);
}

void* tpums_server_start(void* store, const char* state_name,
                         const char* job_id, const char* host, int port) {
  return tpums_server_start3(store, state_name, job_id, host, port, nullptr,
                             nullptr, nullptr, 0);
}

void tpums_server_set_health(void* srv, const char* health_json) {
  if (!srv) return;
  auto* s = static_cast<ServerState*>(srv);
  std::lock_guard<std::mutex> g(s->health_mu);
  s->health_json = health_json ? health_json : "";
}

void tpums_server_set_trace(void* srv, const char* path,
                            long long max_bytes, int keep) {
  if (!srv) return;
  auto* s = static_cast<ServerState*>(srv);
  std::lock_guard<std::mutex> g(s->trace_mu);
  s->trace_path = path ? path : "";
  if (max_bytes > 0) s->trace_max_bytes = max_bytes;
  if (keep >= 0) s->trace_keep = keep;
  s->trace_file_bytes = -1;  // re-stat: the path may have changed
}

int tpums_server_port(void* srv) {
  return srv ? static_cast<ServerState*>(srv)->port : -1;
}

uint64_t tpums_server_requests(void* srv) {
  return srv ? static_cast<ServerState*>(srv)->requests.load() : 0;
}

int tpums_server_io_stats(void* srv, uint64_t* recv_calls,
                          uint64_t* reply_syscalls, uint64_t* reply_bytes,
                          int* uring_active) {
  if (!srv) return -1;
  auto* s = static_cast<ServerState*>(srv);
  if (recv_calls)
    *recv_calls = s->recv_calls.load(std::memory_order_relaxed);
  if (reply_syscalls)
    *reply_syscalls = s->reply_syscalls.load(std::memory_order_relaxed);
  if (reply_bytes)
    *reply_bytes = s->reply_bytes.load(std::memory_order_relaxed);
  if (uring_active) *uring_active = s->uring_on ? 1 : 0;
  return 0;
}

void tpums_server_stop(void* srv) {
  if (!srv) return;
  auto* s = static_cast<ServerState*>(srv);
  s->stop.store(true, std::memory_order_release);
  uint64_t one = 1;
  ssize_t wr = write(s->wake_fd, &one, 8);
  (void)wr;
  if (s->loop.joinable()) s->loop.join();
  // epoll thread gone -> no new tasks; stop the worker, then reap the
  // last background index build before freeing state they read
  s->worker_stop.store(true, std::memory_order_release);
  s->task_cv.notify_all();
  if (s->topk_worker.joinable()) s->topk_worker.join();
  if (s->topk_builder.joinable()) s->topk_builder.join();
  if (s->dot_builder.joinable()) s->dot_builder.join();
  destroy(s);
}

}  // extern "C"
