// Epoll TCP lookup server — the native serving data plane.
//
// TPU-native counterpart of the Flink queryable-state (Netty KvState) server
// answering QueryClientHelper.queryState (QueryClientHelper.java:104-139).
// Speaks the exact line protocol of flink_ms_tpu/serve/server.py so the
// Python query clients work unchanged:
//
//   GET\t<state>\t<key>\n   ->  V\t<value>\n | N\n | E\t<msg>\n
//   MGET\t<state>\t<k1>,<k2>,...\n
//                           ->  M\t<i1>\t<i2>...\n  (per key, in order:
//                               N missing, V<value> found — one round trip
//                               for a whole batch of point lookups)
//   COUNT\t<state>\n        ->  C\t<n>\n  (live key count via tpums_count)
//   PING\n                  ->  PONG\t<job_id>\t<state>\n
//   TOPK\t...\n             ->  E\tno topk index for state: <state>\n
//                               (device-scored top-k stays on the Python
//                               server — this is the point-lookup hot path)
//
// One epoll thread, level-triggered, nonblocking sockets; per-connection
// in/out buffers; EPOLLOUT armed only while a response is partially written.
// Store reads go through the public tpums_get API (internally mutex'd), so
// the journal-consumer thread can keep writing while this thread serves.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <string>
#include <thread>
#include <unordered_map>

#include "tpums.h"

namespace {

constexpr size_t kMaxLine = 1u << 20;   // 1 MB request line cap
constexpr size_t kReadChunk = 64 * 1024;
// Slow-reader protection: a client that pipelines requests without draining
// responses gets disconnected once this much response data is buffered.
constexpr size_t kMaxOutBuffer = 16u << 20;
// Fairness on the single epoll thread: after this many chunks the handler
// returns; level-triggered epoll re-delivers EPOLLIN for the remainder.
constexpr int kMaxChunksPerEvent = 16;

struct Conn {
  int fd = -1;
  std::string in;   // bytes read, not yet parsed into complete lines
  std::string out;  // response bytes not yet written
  bool writable_armed = false;
  bool eof = false;  // client half-closed: answer what's buffered, then close
};

struct ServerState {
  void* store = nullptr;
  std::string state_name;
  std::string job_id;
  int listen_fd = -1;
  int epoll_fd = -1;
  int wake_fd = -1;  // eventfd: poked by tpums_server_stop
  int port = 0;
  std::atomic<bool> stop{false};
  std::atomic<uint64_t> requests{0};
  std::thread loop;
  std::unordered_map<int, Conn> conns;
};

bool set_nonblocking(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  return flags >= 0 && fcntl(fd, F_SETFL, flags | O_NONBLOCK) == 0;
}

// Split `line` on '\t' into at most `max_parts` pieces (last piece keeps any
// remaining tabs, matching Python's str.split("\t") when the counts line up
// because keys/payloads never contain tabs).
int split_tabs(const std::string& line, std::string* parts, int max_parts) {
  int n = 0;
  size_t start = 0;
  while (n < max_parts - 1) {
    size_t tab = line.find('\t', start);
    if (tab == std::string::npos) break;
    parts[n++] = line.substr(start, tab - start);
    start = tab + 1;
  }
  parts[n++] = line.substr(start);
  return n;
}

std::string handle_line(ServerState* s, const std::string& line) {
  s->requests.fetch_add(1, std::memory_order_relaxed);
  // 5 slots: one more than the widest verb, so an over-long request is
  // distinguishable from an exact TOPK (Python splits unbounded; parity
  // demands "TOPK\ta\tb\tc\td" be a bad request, not a TOPK)
  std::string parts[5];
  int n = split_tabs(line, parts, 5);
  if (parts[0] == "PING") {  // Python matches on parts[0] alone
    return "PONG\t" + s->job_id + "\t" + s->state_name + "\n";
  }
  if (parts[0] == "COUNT" && n == 2) {
    if (parts[1] != s->state_name) {
      return "E\tunknown state: " + parts[1] + "\n";
    }
    return "C\t" + std::to_string(tpums_count(s->store)) + "\n";
  }
  if (parts[0] == "GET" && n == 3) {
    if (parts[1] != s->state_name) {
      return "E\tunknown state: " + parts[1] + "\n";
    }
    uint32_t vlen = 0;
    int err = 0;
    char* buf = tpums_get(s->store, parts[2].data(),
                          static_cast<uint32_t>(parts[2].size()), &vlen, &err);
    if (!buf) {
      return err ? "E\tstore read failed\n" : "N\n";
    }
    std::string reply;
    reply.reserve(vlen + 3);
    reply.append("V\t").append(buf, vlen).push_back('\n');
    tpums_free_buf(buf);
    return reply;
  }
  if (parts[0] == "MGET" && n == 3) {
    if (parts[1] != s->state_name) {
      return "E\tunknown state: " + parts[1] + "\n";
    }
    std::string reply = "M";
    const std::string& keys = parts[2];
    size_t start = 0;
    while (true) {
      size_t comma = keys.find(',', start);
      size_t len =
          (comma == std::string::npos ? keys.size() : comma) - start;
      uint32_t vlen = 0;
      int err = 0;
      char* buf = tpums_get(s->store, keys.data() + start,
                            static_cast<uint32_t>(len), &vlen, &err);
      if (!buf) {
        reply += err ? "\tE" : "\tN";  // per-key store error stays in-slot so
                                       // the batch framing survives
      } else {
        reply += "\tV";
        reply.append(buf, vlen);
        tpums_free_buf(buf);
      }
      if (comma == std::string::npos) break;
      start = comma + 1;
    }
    reply.push_back('\n');
    return reply;
  }
  if ((parts[0] == "TOPK" || parts[0] == "TOPKV") && n == 4) {
    // parity with a Python LookupServer that has no registered handler
    return "E\tno topk index for state: " + parts[1] + "\n";
  }
  return "E\tbad request\n";
}

void arm_writable(ServerState* s, Conn* c, bool want) {
  if (c->writable_armed == want) return;
  epoll_event ev{};
  ev.events = EPOLLIN | (want ? EPOLLOUT : 0u);
  ev.data.fd = c->fd;
  if (epoll_ctl(s->epoll_fd, EPOLL_CTL_MOD, c->fd, &ev) == 0) {
    c->writable_armed = want;
  }
}

void close_conn(ServerState* s, int fd) {
  epoll_ctl(s->epoll_fd, EPOLL_CTL_DEL, fd, nullptr);
  close(fd);
  s->conns.erase(fd);
}

// Drain as much of c->out as the socket accepts; false = close the conn.
bool flush_out(ServerState* s, Conn* c) {
  while (!c->out.empty()) {
    ssize_t w = send(c->fd, c->out.data(), c->out.size(), MSG_NOSIGNAL);
    if (w > 0) {
      c->out.erase(0, static_cast<size_t>(w));
      continue;
    }
    if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      arm_writable(s, c, true);
      return true;
    }
    return false;  // peer gone
  }
  arm_writable(s, c, false);
  return true;
}

// Answer every complete line buffered in c->in, leaving the partial tail.
void drain_lines(ServerState* s, Conn* c) {
  size_t start = 0;
  while (true) {
    size_t nl = c->in.find('\n', start);
    if (nl == std::string::npos) break;
    c->out += handle_line(s, c->in.substr(start, nl - start));
    start = nl + 1;
  }
  c->in.erase(0, start);
}

// Read available bytes, answer every complete line; false = close the conn.
bool on_readable(ServerState* s, Conn* c) {
  char chunk[kReadChunk];
  for (int chunks = 0; chunks < kMaxChunksPerEvent; ++chunks) {
    ssize_t r = recv(c->fd, chunk, sizeof(chunk), 0);
    if (r > 0) {
      c->in.append(chunk, static_cast<size_t>(r));
      // parse as we go so the cap bounds ONE request line, not a burst of
      // pipelined small requests
      drain_lines(s, c);
      if (c->in.size() > kMaxLine) return false;   // oversized request line
      if (c->out.size() > kMaxOutBuffer) return false;  // slow reader
      continue;
    }
    if (r == 0) {  // orderly half-close: still answer the buffered requests
      c->eof = true;
      break;
    }
    if (errno == EAGAIN || errno == EWOULDBLOCK) break;
    return false;
  }
  drain_lines(s, c);
  if (c->eof && !c->in.empty()) {
    // final line without '\n': readline()-at-EOF answers it, so we do too
    c->out += handle_line(s, c->in);
    c->in.clear();
  }
  return flush_out(s, c);
}

void event_loop(ServerState* s) {
  epoll_event events[64];
  while (!s->stop.load(std::memory_order_acquire)) {
    int n = epoll_wait(s->epoll_fd, events, 64, -1);
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    for (int i = 0; i < n; ++i) {
      int fd = events[i].data.fd;
      uint32_t ev = events[i].events;
      if (fd == s->wake_fd) {
        uint64_t tok;
        ssize_t rd = read(s->wake_fd, &tok, 8);
        (void)rd;
        continue;  // stop flag is checked at the top of the loop
      }
      if (fd == s->listen_fd) {
        while (true) {
          int cfd = accept(s->listen_fd, nullptr, nullptr);
          if (cfd < 0) break;  // EAGAIN or transient error: try next wakeup
          if (!set_nonblocking(cfd)) {
            close(cfd);
            continue;
          }
          int one = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          epoll_event cev{};
          cev.events = EPOLLIN;
          cev.data.fd = cfd;
          if (epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, cfd, &cev) != 0) {
            close(cfd);
            continue;
          }
          s->conns[cfd].fd = cfd;
        }
        continue;
      }
      auto it = s->conns.find(fd);
      if (it == s->conns.end()) continue;
      Conn* c = &it->second;
      bool ok = true;
      if (ev & EPOLLERR) ok = false;
      if (ok && (ev & EPOLLIN)) ok = on_readable(s, c);
      if (ok && (ev & EPOLLOUT)) ok = flush_out(s, c);
      // half-closed and fully answered (EPOLLHUP arrives with EPOLLIN on a
      // shutdown(WR) peer — the buffered requests must still be served)
      if (ok && c->eof && c->out.empty()) ok = false;
      if (!ok) close_conn(s, fd);
    }
  }
  for (auto& kv : s->conns) close(kv.first);
  s->conns.clear();
}

void destroy(ServerState* s) {
  if (s->listen_fd >= 0) close(s->listen_fd);
  if (s->wake_fd >= 0) close(s->wake_fd);
  if (s->epoll_fd >= 0) close(s->epoll_fd);
  delete s;
}

}  // namespace

extern "C" {

void* tpums_server_start(void* store, const char* state_name,
                         const char* job_id, const char* host, int port) {
  if (!store || !state_name) return nullptr;
  auto* s = new ServerState();
  s->store = store;
  s->state_name = state_name;
  s->job_id = job_id ? job_id : "local";

  s->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    destroy(s);
    return nullptr;
  }
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (!host || !*host || strcmp(host, "0.0.0.0") == 0) {
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
  } else if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    destroy(s);
    return nullptr;
  }
  if (bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
          0 ||
      listen(s->listen_fd, 128) != 0 || !set_nonblocking(s->listen_fd)) {
    destroy(s);
    return nullptr;
  }
  sockaddr_in bound{};
  socklen_t blen = sizeof(bound);
  if (getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&bound), &blen) !=
      0) {
    destroy(s);
    return nullptr;
  }
  s->port = ntohs(bound.sin_port);

  s->epoll_fd = epoll_create1(0);
  s->wake_fd = eventfd(0, EFD_NONBLOCK);
  if (s->epoll_fd < 0 || s->wake_fd < 0) {
    destroy(s);
    return nullptr;
  }
  epoll_event lev{};
  lev.events = EPOLLIN;
  lev.data.fd = s->listen_fd;
  epoll_event wev{};
  wev.events = EPOLLIN;
  wev.data.fd = s->wake_fd;
  if (epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, s->listen_fd, &lev) != 0 ||
      epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, s->wake_fd, &wev) != 0) {
    destroy(s);
    return nullptr;
  }
  s->loop = std::thread(event_loop, s);
  return s;
}

int tpums_server_port(void* srv) {
  return srv ? static_cast<ServerState*>(srv)->port : -1;
}

uint64_t tpums_server_requests(void* srv) {
  return srv ? static_cast<ServerState*>(srv)->requests.load() : 0;
}

void tpums_server_stop(void* srv) {
  if (!srv) return;
  auto* s = static_cast<ServerState*>(srv);
  s->stop.store(true, std::memory_order_release);
  uint64_t one = 1;
  ssize_t wr = write(s->wake_fd, &one, 8);
  (void)wr;
  if (s->loop.joinable()) s->loop.join();
  destroy(s);
}

}  // extern "C"
