// tpums persistent KV store — the native state backend behind the serving
// layer's `--stateBackend rocksdb` mode (the reference keeps served model
// state in RocksDB via JNI — als-ms/pom.xml:120-123, selected at
// ALSKafkaConsumer.java:55-56; SURVEY.md §2.4 calls for a C++ equivalent).
//
// Design: log-structured (bitcask-style). One append-only data log on disk,
// an in-memory hash index of key -> (offset, length) of the latest value.
// - put: append [klen][vlen][key][value] record, update index
// - get: pread the value at the indexed offset (no seek state, thread-safe)
// - open: sequential scan rebuilds the index; a torn tail (crash mid-append)
//   is truncated — recovery is last-complete-record
// - flush: fsync (the checkpoint barrier)
// - compact: rewrite live records to a fresh log when garbage accumulates
//
// Values can exceed RAM in aggregate; only keys + 12 bytes live in memory.
// Exposed as a C ABI for the Python ctypes binding (no pybind11 in image).

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <sys/file.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include "tpums.h"  // signature check against the shared public API
#include "tpums_internal.h"

namespace {

struct Entry {
  uint64_t offset;  // offset of the value bytes in the log
  uint32_t length;
};

struct Store {
  uint32_t tag = kTpumsStoreTag;  // handle dispatch (tpums_internal.h):
                                  // arena handles share the read API
  std::string dir;
  std::string log_path;
  int fd = -1;
  uint64_t end = 0;        // append position
  uint64_t live_bytes = 0; // bytes of records still referenced
  bool wedged = false;     // unrecoverable write failure: reads-only mode
  std::unordered_map<std::string, Entry> index;
  std::mutex mu;
};

constexpr uint32_t kTombstone = 0xFFFFFFFFu;

bool read_exact(int fd, void* buf, size_t n, uint64_t off) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = pread(fd, p, n, off);
    if (r <= 0) return false;
    p += r;
    off += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_all(int fd, const void* buf, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t w = write(fd, p, n);
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

constexpr uint64_t kScanFailed = ~0ull;

// Scan the log, rebuilding the index; returns the offset of the first
// incomplete record (the recovery truncation point), or kScanFailed when
// the log length cannot even be determined (distinct from "empty log" —
// returning 0 there would let the caller truncate a healthy store).
uint64_t rebuild_index(Store* s) {
  struct stat st;
  if (fstat(s->fd, &st) != 0) return kScanFailed;
  uint64_t size = static_cast<uint64_t>(st.st_size);
  uint64_t pos = 0;
  std::string key;
  while (pos + 8 <= size) {
    uint32_t hdr[2];
    // both reads below are fully inside [0, size): a failure is a real I/O
    // error (EIO, concurrent truncation), NOT a torn tail — refusing to open
    // beats truncating away committed records after the failure point
    if (!read_exact(s->fd, hdr, 8, pos)) return kScanFailed;
    uint32_t klen = hdr[0], vlen = hdr[1];
    uint64_t vbytes = (vlen == kTombstone) ? 0 : vlen;
    if (klen > (1u << 20) || (vlen != kTombstone && vlen > (1u << 28)))
      break;  // corrupt header
    if (pos + 8 + klen + vbytes > size) break;  // torn tail
    key.resize(klen);
    if (klen && !read_exact(s->fd, &key[0], klen, pos + 8)) return kScanFailed;
    auto it = s->index.find(key);
    if (it != s->index.end()) {
      s->live_bytes -= 8 + key.size() + it->second.length;
      s->index.erase(it);
    }
    if (vlen != kTombstone) {
      s->index[key] = Entry{pos + 8 + klen, vlen};
      s->live_bytes += 8 + klen + vlen;
    }
    pos += 8 + klen + vbytes;
  }
  return pos;
}

constexpr uint32_t kMaxKeyLen = 1u << 20;    // matched by rebuild_index's
constexpr uint32_t kMaxValueLen = 1u << 28;  // corruption heuristics

int append_record(Store* s, const char* k, uint32_t klen, const char* v,
                  uint32_t vlen) {
  // enforce the same limits the recovery scan treats as corruption —
  // otherwise an oversized record would truncate itself and everything
  // after it on the next reopen
  if (klen > kMaxKeyLen || (vlen != kTombstone && vlen > kMaxValueLen))
    return -1;
  if (s->wedged) return -1;
  uint32_t hdr[2] = {klen, vlen};
  uint64_t vbytes = (vlen == kTombstone) ? 0 : vlen;
  if (!write_all(s->fd, hdr, 8) || (klen && !write_all(s->fd, k, klen)) ||
      (vbytes && !write_all(s->fd, v, vbytes))) {
    // partial append (ENOSPC/EIO): roll the file back to the last complete
    // record, otherwise every later record's indexed offset is shifted
    // (the fd is O_APPEND, so retries would land past the partial bytes)
    if (ftruncate(s->fd, static_cast<off_t>(s->end)) != 0) {
      // can't restore the invariant offset==end: refuse further writes,
      // keep serving reads from the already-indexed prefix
      s->wedged = true;
    }
    return -1;
  }
  std::string key(k, klen);
  auto it = s->index.find(key);
  if (it != s->index.end()) {
    s->live_bytes -= 8 + key.size() + it->second.length;
    s->index.erase(it);
  }
  if (vlen != kTombstone) {
    s->index[std::move(key)] = Entry{s->end + 8 + klen, vlen};
    s->live_bytes += 8 + klen + vlen;
  }
  s->end += 8 + klen + vbytes;
  return 0;
}

}  // namespace

extern "C" {

void* tpums_open(const char* dir) {
  Store* s = new Store();
  s->dir = dir;
  ::mkdir(dir, 0777);  // best effort; open below reports real failures
  s->log_path = s->dir + "/data.log";
  s->fd = ::open(s->log_path.c_str(), O_RDWR | O_CREAT | O_APPEND, 0644);
  if (s->fd < 0) {
    delete s;
    return nullptr;
  }
  // single-writer guard: a second process (or a leaked handle) opening the
  // same store would interleave appends and corrupt the log
  if (flock(s->fd, LOCK_EX | LOCK_NB) != 0) {
    close(s->fd);
    delete s;
    return nullptr;
  }
  uint64_t valid = rebuild_index(s);
  struct stat st;
  if (valid == kScanFailed || fstat(s->fd, &st) != 0) {
    // can't tell log length: refuse to open rather than risk truncating a
    // healthy log against garbage st_size
    close(s->fd);
    delete s;
    return nullptr;
  }
  if (valid < static_cast<uint64_t>(st.st_size)) {
    // torn tail from a crash mid-append: truncate to last complete record
    if (ftruncate(s->fd, static_cast<off_t>(valid)) != 0) {
      close(s->fd);
      delete s;
      return nullptr;
    }
  }
  s->end = valid;
  return s;
}

int tpums_put(void* h, const char* k, uint32_t klen, const char* v,
              uint32_t vlen) {
  if (!h || vlen == kTombstone) return -1;
  if (tpums_is_arena(h)) return -1;  // arena rows are written in place by
                                     // the consumer's mmap, never pushed
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  return append_record(s, k, klen, v, vlen);
}

int tpums_delete(void* h, const char* k, uint32_t klen) {
  if (!h || tpums_is_arena(h)) return -1;
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  return append_record(s, k, klen, nullptr, kTombstone);
}

int tpums_ingest_buf(void* h, const char* buf, uint64_t len, int mode,
                     uint64_t* rows_out, uint64_t* errs_out) {
  // The serving consumer's hot loop, natively: parse journal lines,
  // serialize every record into ONE buffer, and commit it with ONE
  // write() — replaces a per-row Python->ctypes round trip plus three
  // syscalls per record (the measured ingest bottleneck).  Malformed
  // rows (and key/value-limit violations) are counted and skipped, the
  // deliberate skip-and-count policy of the serving loop.
  if (!h || tpums_is_arena(h) || (mode != 0 && mode != 1)) return -1;
  Store* s = static_cast<Store*>(h);
  uint64_t rows = 0, errs = 0;
  std::string key;  // reused across rows (ALS key is id + '-' + type)
  struct Pending {
    uint64_t key_rel;  // key offset within the chunk buffer (no per-row
    uint32_t klen;     // heap copy — the bytes already live in outbuf)
    uint64_t val_rel;  // value offset within the chunk buffer
    uint32_t vlen;
  };
  std::vector<char> outbuf;
  outbuf.reserve(static_cast<size_t>(len) + (len >> 3) + 64);
  std::vector<Pending> pend;
  std::lock_guard<std::mutex> lock(s->mu);
  if (s->wedged) return -1;

  auto emit = [&](const char* k, uint32_t klen, const char* v,
                  uint32_t vlen) {
    if (klen > kMaxKeyLen || vlen > kMaxValueLen) {
      errs++;
      return;
    }
    uint32_t hdr[2] = {klen, vlen};
    const char* hp = reinterpret_cast<const char*>(hdr);
    outbuf.insert(outbuf.end(), hp, hp + 8);
    uint64_t key_rel = outbuf.size();
    outbuf.insert(outbuf.end(), k, k + klen);
    uint64_t val_rel = outbuf.size();
    outbuf.insert(outbuf.end(), v, v + vlen);
    pend.push_back(Pending{key_rel, klen, val_rel, vlen});
    rows++;
  };

  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    if (!nl) break;  // caller sends complete lines; ignore a torn tail
    const char* line = p;
    uint64_t n = static_cast<uint64_t>(nl - p);
    p = nl + 1;
    if (n == 0) continue;  // blank line, same as the Python loop's skip
    const char* c1 = static_cast<const char*>(memchr(line, ',', n));
    if (mode == 0) {
      // "id,T,payload" -> key "id-T", value payload; fewer than two
      // commas is a parse error (Python split(",", 2) raises)
      if (!c1) {
        errs++;
        continue;
      }
      uint64_t rest = n - (c1 + 1 - line);
      const char* c2 =
          static_cast<const char*>(memchr(c1 + 1, ',', rest));
      if (!c2) {
        errs++;
        continue;
      }
      key.assign(line, c1 - line);
      key.push_back('-');
      key.append(c1 + 1, c2 - (c1 + 1));
      const char* val = c2 + 1;
      emit(key.data(), static_cast<uint32_t>(key.size()), val,
           static_cast<uint32_t>(n - (val - line)));
    } else {
      // SVM: key = first comma token; no comma -> whole line, empty value
      const char* val = c1 ? c1 + 1 : line + n;
      emit(line, static_cast<uint32_t>(c1 ? c1 - line : n), val,
           static_cast<uint32_t>(n - (val - line)));
    }
  }

  if (!outbuf.empty()) {
    if (!write_all(s->fd, outbuf.data(), outbuf.size())) {
      // partial chunk append: roll back to the last complete record so
      // indexed offsets stay valid (same invariant as append_record)
      if (ftruncate(s->fd, static_cast<off_t>(s->end)) != 0)
        s->wedged = true;
      return -1;
    }
    uint64_t base = s->end;
    std::string idx_key;  // one buffer reused across the commit loop
    for (const Pending& pr : pend) {
      idx_key.assign(outbuf.data() + pr.key_rel, pr.klen);
      auto it = s->index.find(idx_key);
      if (it != s->index.end()) {
        s->live_bytes -= 8 + idx_key.size() + it->second.length;
        s->index.erase(it);
      }
      s->index[idx_key] = Entry{base + pr.val_rel, pr.vlen};
      s->live_bytes += 8 + idx_key.size() + pr.vlen;
    }
    s->end += outbuf.size();
  }
  if (rows_out) *rows_out = rows;
  if (errs_out) *errs_out = errs;
  return 0;
}

// Returns a malloc'd value buffer (caller frees via tpums_free_buf) or null.
// A null return with *err_out != 0 is an I/O failure on an EXISTING key —
// callers must surface it as an error, not as "key not found".
char* tpums_get(void* h, const char* k, uint32_t klen, uint32_t* vlen_out,
                int* err_out) {
  if (err_out) *err_out = 0;
  if (!h) return nullptr;
  if (tpums_is_arena(h))
    return tpums_arena_get_impl(h, k, klen, vlen_out, err_out);
  Store* s = static_cast<Store*>(h);
  // the pread must stay under the lock: compaction closes/reopens the fd
  // and relocates every offset, so a lock-free read could hit a stale
  // offset in the rewritten log (or a dead fd)
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->index.find(std::string(k, klen));
  if (it == s->index.end()) return nullptr;
  uint64_t off = it->second.offset;
  uint32_t len = it->second.length;
  char* buf = static_cast<char*>(malloc(len ? len : 1));
  if (!buf) {
    if (err_out) *err_out = 1;
    return nullptr;
  }
  if (len && !read_exact(s->fd, buf, len, off)) {
    free(buf);
    if (err_out) *err_out = 1;
    return nullptr;
  }
  *vlen_out = len;
  return buf;
}

void tpums_free_buf(char* p) { free(p); }

uint64_t tpums_count(void* h) {
  if (!h) return 0;
  if (tpums_is_arena(h)) return tpums_arena_count_impl(h);
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  return s->index.size();
}

int tpums_flush(void* h) {
  if (!h) return -1;
  if (tpums_is_arena(h)) return 0;  // read-only mapping: nothing to sync
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  return fsync(s->fd) == 0 ? 0 : -1;
}

// Iterate keys only (no value reads) — lets bindings stream large stores:
// collect the (small) key set under the lock, then fetch values lazily.
typedef void (*tpums_key_cb)(const char*, uint32_t, void*);
int tpums_keys(void* h, tpums_key_cb cb, void* ctx) {
  if (!h) return -1;
  if (tpums_is_arena(h)) return tpums_arena_keys_impl(h, cb, ctx);
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  for (const auto& kv : s->index)
    cb(kv.first.data(), static_cast<uint32_t>(kv.first.size()), ctx);
  return 0;
}

// Bounded-lock key enumeration: emits the keys of whole hash buckets
// [*cursor, ...) until at least max_keys have been emitted or the table is
// exhausted, advancing *cursor past the buckets consumed.  Returns the
// number emitted (0 = done).  The lock is held only per chunk, so a large
// catalog scan (e.g. the lookup server's top-k index build) cannot stall
// concurrent gets for the whole enumeration.  A rehash between chunks may
// skip or repeat keys — callers needing an exact snapshot use tpums_keys;
// convergent consumers (version-checked index rebuilds) dedup/retry.
uint64_t tpums_keys_chunk(void* h, uint64_t* cursor, uint64_t max_keys,
                          tpums_key_cb cb, void* ctx) {
  if (!h || !cursor) return 0;
  if (tpums_is_arena(h))
    return tpums_arena_keys_chunk_impl(h, cursor, max_keys, cb, ctx);
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  uint64_t nbuckets = s->index.bucket_count();
  uint64_t emitted = 0;
  uint64_t b = *cursor;
  for (; b < nbuckets && emitted < max_keys; ++b) {
    for (auto it = s->index.begin(b); it != s->index.end(b); ++it) {
      cb(it->first.data(), static_cast<uint32_t>(it->first.size()), ctx);
      ++emitted;
    }
  }
  *cursor = b;
  return emitted;
}

uint64_t tpums_log_bytes(void* h) {
  if (!h) return 0;
  if (tpums_is_arena(h)) return tpums_arena_log_bytes_impl(h);
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  return s->end;
}

uint64_t tpums_live_bytes(void* h) {
  if (!h) return 0;
  if (tpums_is_arena(h)) return tpums_arena_live_bytes_impl(h);
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  return s->live_bytes;
}

// Rewrite only live records into a fresh log (atomic rename), reclaiming
// space from overwritten rows.  Called by the backend when garbage > 50%.
int tpums_compact(void* h) {
  if (!h) return -1;
  if (tpums_is_arena(h)) return -1;
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  std::string tmp_path = s->log_path + ".compact";
  int out = ::open(tmp_path.c_str(), O_RDWR | O_CREAT | O_TRUNC, 0644);
  if (out < 0) return -1;
  std::unordered_map<std::string, Entry> new_index;
  uint64_t new_end = 0;
  std::vector<char> buf;
  for (const auto& kv : s->index) {
    uint32_t klen = static_cast<uint32_t>(kv.first.size());
    uint32_t vlen = kv.second.length;
    buf.resize(vlen ? vlen : 1);
    if (vlen && !read_exact(s->fd, buf.data(), vlen, kv.second.offset)) {
      close(out);
      unlink(tmp_path.c_str());
      return -1;
    }
    uint32_t hdr[2] = {klen, vlen};
    if (!write_all(out, hdr, 8) || !write_all(out, kv.first.data(), klen) ||
        (vlen && !write_all(out, buf.data(), vlen))) {
      close(out);
      unlink(tmp_path.c_str());
      return -1;
    }
    new_index[kv.first] = Entry{new_end + 8 + klen, vlen};
    new_end += 8 + klen + vlen;
  }
  // Lock the compacted inode and switch it to append mode BEFORE rename
  // makes it visible at log_path: every failure path still leaves the old
  // locked log fully intact, and after rename the store's own `out` fd
  // already holds the writer lock — no window for a second process, and no
  // post-rename failure can desynchronize the in-memory index.
  if (fsync(out) != 0 || flock(out, LOCK_EX | LOCK_NB) != 0 ||
      fcntl(out, F_SETFL, O_APPEND) != 0 ||
      rename(tmp_path.c_str(), s->log_path.c_str()) != 0) {
    close(out);
    unlink(tmp_path.c_str());
    return -1;
  }
  close(s->fd);  // releases the old inode's lock
  s->fd = out;   // file offset sits at new_end, O_APPEND set: puts append
  s->index = std::move(new_index);
  s->end = new_end;
  s->live_bytes = new_end;
  s->wedged = false;  // fresh fd at new_end: the offset invariant holds again
  return 0;
}

void tpums_close(void* h) {
  if (!h) return;
  if (tpums_is_arena(h)) return tpums_arena_close_impl(h);
  Store* s = static_cast<Store*>(h);
  {
    std::lock_guard<std::mutex> lock(s->mu);
    if (s->fd >= 0) {
      fsync(s->fd);
      close(s->fd);
    }
  }
  delete s;
}

}  // extern "C"
