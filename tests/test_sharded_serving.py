"""Multi-process sharded serving: N real worker processes over one journal,
client-side hash routing, fan-out TOPK merge, and the defined
kill-one-worker / restart-from-checkpoint behavior (the scale-out contract
of ``keyBy(0).asQueryableState`` across TaskManagers —
ALSKafkaConsumer.java:85-92)."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from flink_ms_tpu.core import formats as F
from flink_ms_tpu.serve.journal import Journal
from flink_ms_tpu.serve.sharded import ShardedQueryClient, owner_of

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
N_WORKERS = 3


def _spawn_worker(tmp_path, idx, extra=()):
    port_file = tmp_path / f"port-{idx}.json"
    if port_file.exists():
        port_file.unlink()
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    # worker output goes to a file so a startup death is diagnosable
    # (e.g. --nativeServer on a box without the native build raises a
    # deliberate ValueError that DEVNULL would swallow)
    log_path = tmp_path / f"worker-{idx}.log"
    log_fh = open(log_path, "wb")
    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", "flink_ms_tpu.serve.sharded",
             "--workerIndex", str(idx), "--numWorkers", str(N_WORKERS),
             "--journalDir", str(tmp_path / "bus"), "--topic", "models",
             "--stateBackend", "fs",
             "--checkpointDataUri", str(tmp_path / "chk"),
             "--checkPointInterval", "200",
             "--host", "127.0.0.1", "--port", "0",
             "--portFile", str(port_file), *extra],
            env=env, cwd=REPO,
            stdout=log_fh, stderr=subprocess.STDOUT,
        )
    finally:
        log_fh.close()
    deadline = time.time() + 60
    while time.time() < deadline:
        if port_file.exists() and port_file.stat().st_size > 0:
            with open(port_file) as f:
                return proc, json.load(f)["port"]
        if proc.poll() is not None:
            raise RuntimeError(
                f"worker {idx} died rc={proc.returncode}:\n"
                + log_path.read_text(errors="replace")[-800:]
            )
        time.sleep(0.05)
    proc.kill()
    raise RuntimeError(f"worker {idx} never published its port")


def _seed_and_spawn(tmp_path, seed, extra=()):
    """Seed the journal with a small ALS model and spawn N workers —
    shared by the Python-plane cluster fixture and the native-plane
    test, which differ only in rng seed and worker flags."""
    journal = Journal(str(tmp_path / "bus"), "models")
    k = 4
    rng = np.random.default_rng(seed)
    uf = rng.normal(size=(20, k))
    itf = rng.normal(size=(30, k))
    rows = [F.format_als_row(u, "U", uf[u]) for u in range(20)]
    rows += [F.format_als_row(i, "I", itf[i]) for i in range(30)]
    journal.append(rows)
    procs, ports = [], []
    for idx in range(N_WORKERS):
        proc, port = _spawn_worker(tmp_path, idx, extra)
        procs.append(proc)
        ports.append(port)
    return journal, procs, ports, uf, itf


@pytest.fixture
def cluster(tmp_path):
    from flink_ms_tpu.serve.sharded import stop_worker_procs

    procs = []
    try:
        journal, procs, ports, uf, itf = _seed_and_spawn(tmp_path, 0)
        yield journal, procs, ports, uf, itf, tmp_path
    finally:
        stop_worker_procs(procs)


def _wait_keys(client, keys, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if all(
                client.query_state("ALS_MODEL", key) is not None
                for key in keys
            ):
                return True
        except (ConnectionError, OSError):
            pass
        time.sleep(0.1)
    return False


def test_routing_and_ownership(cluster):
    _journal, _procs, ports, uf, itf, _tmp = cluster
    with ShardedQueryClient([("127.0.0.1", p) for p in ports]) as client:
        all_keys = [f"{u}-U" for u in range(20)] + [f"{i}-I" for i in range(30)]
        assert _wait_keys(client, all_keys)
        # every key resolves through the router to its owner, with the
        # exact payload
        for u in range(20):
            payload = client.query_state("ALS_MODEL", f"{u}-U")
            np.testing.assert_allclose(
                [float(t) for t in payload.split(";")], uf[u]
            )
        # keys are spread across ALL workers (no worker owns everything)
        owners = {owner_of(key, N_WORKERS) for key in all_keys}
        assert owners == set(range(N_WORKERS))
        # each worker holds ONLY its slice: asking a non-owner directly
        # must miss
        from flink_ms_tpu.serve.client import QueryClient

        key = "0-U"
        own = owner_of(key, N_WORKERS)
        wrong = (own + 1) % N_WORKERS
        with QueryClient("127.0.0.1", ports[wrong]) as direct:
            assert direct.query_state("ALS_MODEL", key) is None
        # batched lookups reassemble across workers in request order
        batch = ["5-U", "17-I", "nope-U", "3-I"]
        values = client.query_states("ALS_MODEL", batch)
        assert values[2] is None
        np.testing.assert_allclose(
            [float(t) for t in values[0].split(";")], uf[5]
        )
        np.testing.assert_allclose(
            [float(t) for t in values[3].split(";")], itf[3]
        )


def test_fanout_topk_matches_brute_force(cluster):
    _journal, _procs, ports, uf, itf, _tmp = cluster
    # the first TOPKV on each worker pays the index build + real-shape jit
    # (the cold-pipeline cost is pre-warmed at worker startup, but a loaded
    # machine can still push the remainder past the 5 s default)
    with ShardedQueryClient(
        [("127.0.0.1", p) for p in ports], timeout_s=30
    ) as client:
        assert _wait_keys(
            client,
            [f"{u}-U" for u in range(20)] + [f"{i}-I" for i in range(30)],
        )
        k = 5
        got = client.topk("ALS_MODEL", "7", k)
        scores = itf @ uf[7]
        best = np.argsort(-scores)[:k]
        assert [item for item, _ in got] == [str(i) for i in best]
        np.testing.assert_allclose(
            [s for _, s in got], scores[best], rtol=1e-5
        )
        assert client.topk("ALS_MODEL", "999", k) is None


def test_kill_one_worker_and_restart(cluster):
    journal, procs, ports, uf, _itf, tmp_path = cluster
    with ShardedQueryClient(
        [("127.0.0.1", p) for p in ports], timeout_s=2
    ) as client:
        assert _wait_keys(client, [f"{u}-U" for u in range(20)])
        victim = owner_of("0-U", N_WORKERS)
        survivor_key = next(
            f"{u}-U" for u in range(20)
            if owner_of(f"{u}-U", N_WORKERS) != victim
        )
        procs[victim].send_signal(signal.SIGKILL)
        procs[victim].wait(timeout=10)
        # defined behavior: dead worker's keys raise, the rest keep serving
        assert client.query_state("ALS_MODEL", survivor_key) is not None
        with pytest.raises((ConnectionError, OSError)):
            client.query_state("ALS_MODEL", "0-U")

    # restart: restores its checkpoint (or replays the journal) and its
    # keys resolve again — the reference's fixed-delay-restart story
    proc, port = _spawn_worker(tmp_path, victim)
    procs[victim] = proc
    ports[victim] = port
    with ShardedQueryClient([("127.0.0.1", p) for p in ports]) as client:
        assert _wait_keys(client, ["0-U"])
        payload = client.query_state("ALS_MODEL", "0-U")
        np.testing.assert_allclose(
            [float(t) for t in payload.split(";")], uf[0]
        )


def test_sharded_ingest_filter_counts():
    """The parse wrapper drops foreign rows without counting them as
    errors."""
    from flink_ms_tpu.serve.consumer import parse_als_record
    from flink_ms_tpu.serve.sharded import sharded_parse

    rows = [F.format_als_row(i, "U", [float(i)]) for i in range(40)]
    kept = 0
    parse = sharded_parse(parse_als_record, 1, N_WORKERS)
    for row in rows:
        parsed = parse(row)
        if parsed is not None:
            kept += 1
            assert owner_of(parsed[0], N_WORKERS) == 1
    assert 0 < kept < 40


def test_native_worker_cluster_serves_and_fans_out(tmp_path):
    """--nativeServer true per shard (round 5): the C++ epoll plane over
    each worker's rocksdb slice answers the same routing, MGET, and
    TOPKV-fan-out contract as the Python-plane cluster."""
    from flink_ms_tpu.serve.sharded import stop_worker_procs

    procs = []
    try:
        _journal, procs, ports, uf, itf = _seed_and_spawn(
            tmp_path, 1,
            extra=("--stateBackend", "rocksdb", "--nativeServer", "true"),
        )
        with ShardedQueryClient(
            [("127.0.0.1", p) for p in ports], timeout_s=30
        ) as client:
            assert _wait_keys(
                client,
                [f"{u}-U" for u in range(20)] + [f"{i}-I" for i in range(30)],
            )
            # hash routing + batched MGET through the C++ plane
            got = client.query_states(
                "ALS_MODEL", ["3-U", "17-I", "nope-U"])
            assert got[0] is not None and got[1] is not None
            assert got[2] is None
            # catalog-scored TOPKV fan-out + merge across native workers
            got_topk = client.topk("ALS_MODEL", "7", 5)
            scores = itf @ uf[7]
            best = np.argsort(-scores)[:5]
            assert [item for item, _ in got_topk] == [str(i) for i in best]
            np.testing.assert_allclose(
                [s for _, s in got_topk], scores[best], rtol=1e-5
            )
    finally:
        stop_worker_procs(procs)
