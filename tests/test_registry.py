"""Job location registry (serve/registry.py): jobId -> endpoint resolution,
the counterpart of the reference's JobManager-side queryable-state lookup
(QueryClientHelper.java:82-92,121 — clients name a jobId, never a server
port), plus the producer's checkpoint-cadence flush parity
(ALSKafkaProducer.java:35-37)."""

import json

import pytest

from flink_ms_tpu.core.params import Params
from flink_ms_tpu.serve import registry
from flink_ms_tpu.serve.consumer import (
    ALS_STATE,
    ServingJob,
    make_backend,
    parse_als_record,
)
from flink_ms_tpu.serve.journal import Journal


# registry isolation comes from conftest.py's autouse fixture (every test
# gets a private TPUMS_REGISTRY_DIR)


def test_register_resolve_unregister_roundtrip():
    registry.register("job-a", "127.0.0.1", 7001, ALS_STATE)
    entry = registry.resolve("job-a")
    assert entry["port"] == 7001 and entry["host"] == "127.0.0.1"
    assert entry["state"] == ALS_STATE
    registry.unregister("job-a")
    assert registry.resolve("job-a") is None


def test_resolve_endpoint_precedence():
    registry.register("job-b", "10.0.0.9", 7002, ALS_STATE)
    # explicit --jobManagerPort wins over the registry
    host, port = registry.resolve_endpoint(Params.from_dict({
        "jobId": "job-b", "jobManagerHost": "h", "jobManagerPort": 9999,
    }))
    assert (host, port) == ("h", 9999)
    # jobId alone routes through the registry (host too, none given)
    host, port = registry.resolve_endpoint(Params.from_dict({
        "jobId": "job-b",
    }))
    assert (host, port) == ("10.0.0.9", 7002)
    # an explicit host is kept even when the registry resolves the port
    host, port = registry.resolve_endpoint(Params.from_dict({
        "jobId": "job-b", "jobManagerHost": "override",
    }))
    assert (host, port) == ("override", 7002)
    # unknown jobId: the reference defaults (localhost:6123)
    host, port = registry.resolve_endpoint(Params.from_dict({
        "jobId": "nope",
    }))
    assert (host, port) == ("localhost", 6123)


def test_wildcard_bind_resolves_via_client_host():
    registry.register("job-c", "0.0.0.0", 7003, ALS_STATE)
    host, port = registry.resolve_endpoint(Params.from_dict({
        "jobId": "job-c",
    }))
    assert (host, port) == ("localhost", 7003)


def test_serving_job_registers_and_unregisters(tmp_path):
    journal = Journal(str(tmp_path / "bus"), "t")
    journal.append(["1,U,0.5;1.5"])
    job = ServingJob(
        journal, ALS_STATE, parse_als_record, make_backend("memory", None),
        host="127.0.0.1", port=0, poll_interval_s=0.01, job_id="reg-e2e",
    ).start()
    try:
        entry = registry.resolve("reg-e2e")
        assert entry is not None and entry["port"] == job.port
        # a client holding only the jobId reaches the plane
        from flink_ms_tpu.serve.client import QueryClient

        host, port = registry.resolve_endpoint(
            Params.from_dict({"jobId": "reg-e2e"}))
        with QueryClient(host, port, timeout_s=10) as c:
            deadline = 100
            while c.query_state(ALS_STATE, "1-U") is None and deadline:
                deadline -= 1
        assert deadline
    finally:
        job.stop()
    assert registry.resolve("reg-e2e") is None


def test_repl_client_resolves_port_from_registry(tmp_path):
    from flink_ms_tpu.client.common import repl_client_from_argv

    registry.register("repl-job", "127.0.0.1", 7044, ALS_STATE)
    c = repl_client_from_argv(["repl-job"], usage="u")
    assert (c.host, c.port) == ("127.0.0.1", 7044)
    # positional host+port still win
    c = repl_client_from_argv(["repl-job", "h2", "7055"], usage="u")
    assert (c.host, c.port) == ("h2", 7055)


def test_registry_entry_is_json_file():
    import pathlib

    registry.register("weird/../id", "127.0.0.1", 7005, ALS_STATE)
    files = list(pathlib.Path(registry.registry_dir()).iterdir())
    assert len(files) == 1 and files[0].suffix == ".json"
    assert json.loads(files[0].read_text())["port"] == 7005
    # sanitization must stay injective: a jobId that sanitizes to the
    # same name must not overwrite or unregister the first job's entry
    registry.register("weird_.._id", "127.0.0.1", 7006, ALS_STATE)
    assert registry.resolve("weird/../id")["port"] == 7005
    registry.unregister("weird_.._id")
    assert registry.resolve("weird/../id") is not None


def _rewrite_entry(path, **updates):
    entry = json.loads(path.read_text())
    entry.update(updates)
    path.write_text(json.dumps(entry))
    return entry


def _dead_pid():
    import subprocess
    import sys

    child = subprocess.Popen([sys.executable, "-c", "pass"])
    child.wait()
    return child.pid


def test_resolve_reaps_dead_local_pid():
    """A SIGKILL'd ServingJob never unregisters: an entry recorded by THIS
    machine whose pid is gone must resolve to None (clients fall back to
    defaults) and the stale file must be reaped.  Entries recorded by
    another machine (shared-FS registry) are never pid-checked."""
    import pathlib

    dead = _dead_pid()
    registry.register("job-dead", "127.0.0.1", 7009, ALS_STATE)
    path = next(pathlib.Path(registry.registry_dir()).iterdir())
    _rewrite_entry(path, pid=dead)
    assert registry.resolve("job-dead") is None
    assert not path.exists(), "stale entry not reaped"

    # same dead pid, but recorded by a different machine: liveness is
    # unknowable here, the entry must survive
    registry.register("job-remote", "10.9.9.9", 7010, ALS_STATE)
    path = next(pathlib.Path(registry.registry_dir()).iterdir())
    _rewrite_entry(path, pid=dead, pid_host="some-other-machine")
    assert registry.resolve("job-remote")["port"] == 7010
    assert path.exists()


def test_resolve_reap_spares_fresh_reregistration(monkeypatch):
    """TOCTOU guard: if a supervisor re-registers the job between
    resolve()'s read of a dead-pid entry and its unlink, the FRESH live
    entry must be returned, not deleted."""
    import pathlib

    dead = _dead_pid()
    registry.register("job-flap", "127.0.0.1", 7011, ALS_STATE)
    path = next(pathlib.Path(registry.registry_dir()).iterdir())
    _rewrite_entry(path, pid=dead)

    real_check = registry._pid_is_ours_and_dead

    def check_then_reregister(entry):
        out = real_check(entry)
        # the supervisor restart lands exactly in the race window
        registry.register("job-flap", "127.0.0.1", 7012, ALS_STATE)
        return out

    monkeypatch.setattr(registry, "_pid_is_ours_and_dead",
                        check_then_reregister)
    resolved = registry.resolve("job-flap")
    assert resolved is not None and resolved["port"] == 7012
    assert path.exists(), "fresh re-registration was reaped"


def test_producer_flushes_slow_source_partial_batch(tmp_path, monkeypatch):
    """A source slower than one 10k batch per flush interval must still
    bound crash loss to ~one interval: the deadline is checked per line,
    so partial batches fsync on cadence (flushOnCheckpoint parity —
    ALSKafkaProducer.java:35-37)."""
    from flink_ms_tpu.serve import producer

    model = tmp_path / "model"
    model.write_text("".join(f"{i},U,0.1;0.2\n" for i in range(10)))

    flushes = []
    real_append = Journal.append

    def spy_append(self, lines, flush=True):
        flushes.append((len(lines), bool(flush)))
        return real_append(self, lines, flush=flush)

    monkeypatch.setattr(Journal, "append", spy_append)
    clock = [0.0]
    monkeypatch.setattr(producer.time, "monotonic",
                        lambda: clock.__setitem__(0, clock[0] + 40.0)
                        or clock[0])  # +40s/call: every line passes a deadline
    n = producer.run(Params.from_dict({
        "journalDir": str(tmp_path / "bus"), "topic": "t",
        "input": str(model), "flushInterval": 60_000,
    }))
    assert n == 10
    # 10 lines < _BATCH: before the per-line deadline check these would
    # reach the journal only at end-of-stream (one flush, full loss bound)
    mid_flushes = [f for f in flushes[:-1] if f[1]]
    assert mid_flushes, flushes
    assert all(size < producer._BATCH for size, _ in flushes)


def test_producer_flush_interval(tmp_path, monkeypatch):
    """--flushInterval fsyncs mid-load on the checkpoint cadence
    (ALSKafkaProducer.java:35-37 flushes every checkpoint); 0 disables."""
    from flink_ms_tpu.serve import producer

    model = tmp_path / "model"
    model.write_text("".join(f"{i},U,0.1;0.2\n" for i in range(25_000)))

    flushes = []
    real_append = Journal.append

    def spy_append(self, lines, flush=True):
        flushes.append(bool(flush))
        return real_append(self, lines, flush=flush)

    monkeypatch.setattr(Journal, "append", spy_append)
    clock = [0.0]
    monkeypatch.setattr(producer.time, "monotonic",
                        lambda: clock.__setitem__(0, clock[0] + 40.0)
                        or clock[0])  # +40s per call: every batch is due
    n = producer.run(Params.from_dict({
        "journalDir": str(tmp_path / "bus"), "topic": "t",
        "input": str(model), "flushInterval": 60_000,
    }))
    assert n == 25_000
    # two full batches flushed on cadence + the final checkpoint flush
    assert flushes.count(True) >= 2 and flushes[-1] is True

    flushes.clear()
    producer.run(Params.from_dict({
        "journalDir": str(tmp_path / "bus2"), "topic": "t",
        "input": str(model), "flushInterval": 0,
    }))
    # interval disabled: only the end-of-stream fsync
    assert flushes.count(True) == 1 and flushes[-1] is True
