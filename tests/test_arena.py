"""Shared-memory arena (serve/arena.py + native/arena.cpp): seqlock row
framing, writer exclusion, growth/remap, native zero-copy reads, crash
semantics, O(state) snapshot publish, and byte parity with the dict-table
Python server."""

import ctypes
import json
import os
import random
import signal
import socket
import struct
import subprocess
import sys
import time

import pytest

from flink_ms_tpu.serve import arena as ar
from flink_ms_tpu.serve import snapshot as snapshot_mod
from flink_ms_tpu.serve.arena import (
    Arena,
    ArenaBusy,
    ArenaModelTable,
    clone_file,
    iter_arena_file,
)
from flink_ms_tpu.serve.consumer import (
    ALS_STATE,
    MemoryStateBackend,
    ServingJob,
    parse_als_record,
)
from flink_ms_tpu.serve.journal import Journal
from flink_ms_tpu.serve.native_store import NativeArena, NativeLookupServer
from flink_ms_tpu.serve.server import LookupServer
from flink_ms_tpu.serve.table import ModelTable


# -- Python-side table semantics ---------------------------------------------

def test_put_get_update_len_items(tmp_path):
    t = ArenaModelTable(4, dir=str(tmp_path / "a"))
    try:
        assert t.get("x") is None
        t.put("x", "1")
        t.put("y", "2")
        assert (t.get("x"), t.get("y")) == ("1", "2")
        assert len(t) == 2
        t.put("x", "updated")  # in-place, count unchanged
        assert t.get("x") == "updated"
        assert len(t) == 2
        assert dict(t.items()) == {"x": "updated", "y": "2"}
        assert t.puts == 3 and t.version == 3
    finally:
        t.close()


def test_change_listeners_fire(tmp_path):
    t = ArenaModelTable(2, dir=str(tmp_path / "a"))
    try:
        seen, batches = [], []
        t.add_change_listener(seen.append)
        t.add_change_listener(lambda k: None, batches.append)
        t.put("a", "1")
        t.put_many_columns(["b", "c"], ["2", "3"])
        assert seen == ["a", "b", "c"]
        assert batches == [["b", "c"]]  # batch fan-out on batched ingest only
    finally:
        t.close()


def test_writer_exclusion_flock(tmp_path):
    t = ArenaModelTable(2, dir=str(tmp_path / "a"))
    try:
        with pytest.raises(ArenaBusy):
            ArenaModelTable(2, dir=str(tmp_path / "a"))
    finally:
        t.close()
    # released on close: a successor writer attaches to the same file
    t2 = ArenaModelTable(2, dir=str(tmp_path / "a"))
    try:
        t2.put("k", "v")
        assert t2.get("k") == "v"
    finally:
        t2.close()


def test_growth_rehash_preserves_rows(tmp_path):
    t = ArenaModelTable(2, dir=str(tmp_path / "a"),
                        capacity=64, stride=16, key_cap=8)
    try:
        gen0 = t.arena.path
        for i in range(200):  # load-factor growth
            t.put(f"k{i}", f"v{i}")
        t.put("big", "x" * 500)  # stride growth
        t.put("long-key-beyond-cap", "y")  # key_cap growth
        assert t.arena.path != gen0
        for i in range(200):
            assert t.get(f"k{i}") == f"v{i}"
        assert t.get("big") == "x" * 500
        assert t.get("long-key-beyond-cap") == "y"
        assert len(t) == 202
    finally:
        t.close()


def test_odd_seq_slot_reads_missing_and_chain_continues(tmp_path):
    """A writer SIGKILLed mid-row leaves an odd seq: that key reads as
    MISSING (never torn), and probe chains continue PAST the dead slot so
    other keys remain reachable."""
    t = ArenaModelTable(2, dir=str(tmp_path / "a"), capacity=64)
    try:
        t.put_many([(f"k{i}", f"v{i}") for i in range(10)])
        a = t.arena
        # find k3's slot and forge a mid-write crash (odd seq)
        idx = ar._fnv1a_bytes(b"k3") % a.capacity
        while True:
            off = a._slot_off(idx)
            klen = struct.unpack_from("<I", a.mm, off + 4)[0]
            if a.mm[off + 12:off + 12 + klen] == b"k3":
                break
            idx = (idx + 1) % a.capacity
        seq = struct.unpack_from("<I", a.mm, off)[0]
        struct.pack_into("<I", a.mm, off, seq | 1)
        assert t.get("k3") is None  # missing, not a torn value
        for i in range(10):  # everyone else still reachable
            if i != 3:
                assert t.get(f"k{i}") == f"v{i}"
        # journal-replay repair: the same key re-put lands readable
        struct.pack_into("<I", a.mm, off, seq)  # writer respawn path
        t.put("k3", "repaired")
        assert t.get("k3") == "repaired"
    finally:
        t.close()


def test_iter_arena_file_portable(tmp_path):
    t = ArenaModelTable(2, dir=str(tmp_path / "a"))
    rows = {f"k{i}": f"v{i}" for i in range(100)}
    try:
        t.put_many(sorted(rows.items()))
        t.flush()
        assert dict(iter_arena_file(t.arena.path)) == rows
    finally:
        t.close()


def test_clone_file_preserves_content_and_holes(tmp_path):
    t = ArenaModelTable(2, dir=str(tmp_path / "a"))
    try:
        t.put_many([(f"k{i}", f"v{i}" * 8) for i in range(500)])
        t.flush()
        src = t.arena.path
        dst = str(tmp_path / "copy.dat")
        size = clone_file(src, dst)
        assert size == os.path.getsize(src) == os.path.getsize(dst)
        assert dict(iter_arena_file(dst)) == dict(t.items())
        # the arena file is sparse; the copy must not densify it (reflink
        # or hole-aware extent copy — never a full-capacity write)
        assert (os.stat(dst).st_blocks * 512
                <= os.stat(src).st_blocks * 512 + (1 << 20))
    finally:
        t.close()


# -- native reader (tag-dispatched C++ side) ---------------------------------

def test_native_reader_sees_python_writes(tmp_path):
    t = ArenaModelTable(4, dir=str(tmp_path / "a"))
    a = NativeArena(str(tmp_path / "a"))
    try:
        t.put_many([(f"k{i}", f"v{i}") for i in range(100)])
        assert a.refresh()
        assert len(a) == 100
        assert a.get("k42") == "v42"
        assert a.get("missing") is None
        t.put("k42", "fresh")  # in-place: visible with zero pushes
        assert a.get("k42") == "fresh"
        st = a.stats()
        assert st["rows"] == 100 and 0 < st["load_factor"] < 1
        assert st["resident_bytes"] > 0
    finally:
        a.close()
        t.close()


def test_native_reader_remaps_across_growth(tmp_path):
    t = ArenaModelTable(2, dir=str(tmp_path / "a"),
                        capacity=64, stride=16, key_cap=8)
    a = NativeArena(str(tmp_path / "a"))
    try:
        t.put_many([(f"k{i}", f"v{i}") for i in range(40)])
        assert a.get("k0") == "v0"
        gen0 = t.arena.path
        t.put_many([(f"g{i}", "x" * 14) for i in range(100)])
        assert t.arena.path != gen0
        assert a.get("k0") == "v0"  # remapped through CURRENT
        assert a.get("g99") == "x" * 14
        assert len(a) == 140
    finally:
        a.close()
        t.close()


def test_native_mutating_verbs_rejected(tmp_path):
    """Zero-push pin, FFI level: every Python->C++ row-push verb FAILS on
    an arena handle — the mmap is the only write path."""
    t = ArenaModelTable(2, dir=str(tmp_path / "a"))
    a = NativeArena(str(tmp_path / "a"))
    try:
        t.put("k", "v")
        lib = a._lib
        assert lib.tpums_put(a._h, b"x", 1, b"y", 1) == -1
        assert lib.tpums_delete(a._h, b"k", 1) == -1
        rows = ctypes.c_uint64(0)
        errs = ctypes.c_uint64(0)
        assert lib.tpums_ingest_buf(a._h, b"1,U,2\n", 6, 0,
                                    ctypes.byref(rows),
                                    ctypes.byref(errs)) == -1
        assert lib.tpums_compact(a._h) == -1
        assert a.get("k") == "v"  # reads unaffected
    finally:
        a.close()
        t.close()


def test_serving_job_arena_needs_no_native_store(tmp_path, monkeypatch):
    """Zero-push pin, job level: --table arena --nativeServer serves
    without ANY NativeStore existing (nothing to push rows into)."""
    from flink_ms_tpu.serve import native_store as ns

    def _boom(*a, **k):
        raise AssertionError("arena serving must not construct a NativeStore")

    monkeypatch.setattr(ns, "NativeStore", _boom)
    j = Journal(str(tmp_path), "als")
    j.append([f"{i},U,{i}.5" for i in range(50)])
    job = ServingJob(j, ALS_STATE, parse_als_record, MemoryStateBackend(),
                     port=0, native_server=True, table="arena",
                     snapshots=False)
    try:
        job.start()
        deadline = time.time() + 20
        while not job._ready.is_set() and time.time() < deadline:
            time.sleep(0.02)
        assert job._ready.is_set()
        with socket.create_connection(("127.0.0.1", job.port), timeout=5) as s:
            s.sendall(b"GET\tALS_MODEL\t7-U\n")
            assert s.recv(4096) == b"V\t7.5\n"
            s.sendall(b"COUNT\tALS_MODEL\n")
            assert s.recv(4096) == b"C\t50\n"
    finally:
        job.stop()


def _raw(port: int, payload: bytes) -> bytes:
    with socket.create_connection(("127.0.0.1", port), timeout=5) as sock:
        sock.sendall(payload)
        sock.shutdown(socket.SHUT_WR)
        out = b""
        while True:
            chunk = sock.recv(65536)
            if not chunk:
                return out
            out += chunk


def test_parity_fuzz_arena_vs_dict_reply_bytes(tmp_path):
    """Randomized workload, byte-for-byte reply parity: the SAME queries
    against the dict table's Python server and the arena's native server
    must produce identical bytes (the dict plane is the semantics
    contract; the arena must be invisible to clients)."""
    rng = random.Random(20260807)
    keys = [f"{rng.randrange(4000)}-{'UI'[rng.randrange(2)]}"
            for _ in range(600)]
    rows = {}
    for k in set(keys):
        rows[k] = ";".join(f"{rng.uniform(-5, 5):.4f}" for _ in range(4))

    dict_t = ModelTable(4)
    at = ArenaModelTable(4, dir=str(tmp_path / "a"))
    try:
        items = list(rows.items())
        rng.shuffle(items)
        for k, v in items:
            dict_t.put(k, v)
        at.put_many(items)
        # a randomized slice updated in place (arena exercises the odd/
        # even seq flip; dict just overwrites)
        for k in rng.sample(sorted(rows), 100):
            rows[k] = "9.9;8.8"
            dict_t.put(k, rows[k])
            at.put(k, rows[k])

        req = []
        for _ in range(300):
            verb = rng.randrange(3)
            if verb == 0:
                probe = rng.choice(keys) if rng.random() < 0.8 else "nope-X"
                req.append(f"GET\t{ALS_STATE}\t{probe}".encode())
            elif verb == 1:
                ks = ",".join(rng.choice(keys)
                              for _ in range(rng.randrange(1, 8)))
                req.append(f"MGET\t{ALS_STATE}\t{ks}".encode())
            else:
                req.append(f"COUNT\t{ALS_STATE}".encode())
        payload = b"\n".join(req) + b"\n"

        pysrv = LookupServer({ALS_STATE: dict_t}, host="127.0.0.1",
                             port=0, job_id="jid").start()
        try:
            with NativeLookupServer(NativeArena(str(tmp_path / "a")),
                                    ALS_STATE, job_id="jid",
                                    port=0) as nsrv:
                assert _raw(nsrv.port, payload) == _raw(pysrv.port, payload)
        finally:
            pysrv.stop()
    finally:
        at.close()


def test_native_metrics_includes_arena_gauges(tmp_path):
    t = ArenaModelTable(2, dir=str(tmp_path / "a"))
    try:
        t.put_many([(f"k{i}", "v") for i in range(32)])
        with NativeLookupServer(NativeArena(str(tmp_path / "a")),
                                ALS_STATE, job_id="jid", port=0) as srv:
            reply = _raw(srv.port, b"METRICS\n").decode()
        assert reply.startswith("J\t")
        snap = json.loads(reply[2:])
        gauges = {g["name"]: g["value"] for g in snap["gauges"]}
        assert gauges["tpums_arena_rows"] == 32
        assert gauges["tpums_arena_resident_bytes"] > 0
        assert 0 < gauges["tpums_arena_index_load_factor"] < 1
        counters = {c["name"] for c in snap["counters"]}
        assert "tpums_arena_read_retries_total" in counters
    finally:
        t.close()


# -- snapshot plane (O(state) publish) ---------------------------------------

def test_arena_snapshot_publish_and_bootstrap_both_kinds(tmp_path):
    root = str(tmp_path / "snaps")
    t = ArenaModelTable(4, dir=str(tmp_path / "a"))
    try:
        t.put_many([(f"k{i}", f"v{i}") for i in range(1000)])
        m = snapshot_mod.publish(root, t, 777, shard=0, num_shards=1)
        assert m["format"] == snapshot_mod.ARENA_FORMAT
        assert m["rows"] == 1000
    finally:
        t.close()
    # restores into a dict table (portable)...
    dt = ModelTable(4)
    info = snapshot_mod.bootstrap(dt, root, owner=(0, 1))
    assert info["rows"] == 1000 and info["offset"] == 777
    assert dt.get("k999") == "v999"
    # ...and into a fresh arena table
    t2 = ArenaModelTable(4, dir=str(tmp_path / "b"))
    try:
        info2 = snapshot_mod.bootstrap(t2, root, owner=(0, 1))
        assert info2["rows"] == 1000
        assert t2.get("k0") == "v0"
    finally:
        t2.close()


def test_link_publish_o1_and_lww_convergence(tmp_path):
    """publish_mode=link hardlinks the live inode (0 bytes written) and
    stays restorable after post-publish upserts: new keys push the decode
    PAST the manifest row count (>= floor for linked members) and updated
    rows show newer values — both converge under LWW journal replay."""
    root = str(tmp_path / "snaps")
    t = ArenaModelTable(4, dir=str(tmp_path / "a"), publish_mode="link")
    try:
        t.put_many([(f"k{i}", "old") for i in range(500)])
        m = snapshot_mod.publish(root, t, 500, shard=0, num_shards=1)
        assert m["arena"]["publish"] == "link"
        assert m["arena"]["bytes_copied"] == 0  # one hardlink, O(1)
        assert os.stat(
            os.path.join(m["path"], "arena.dat")).st_ino == os.stat(
            t.arena.path).st_ino
        # post-publish mutations: one update + one brand-new key
        t.put("k0", "newer")
        t.put("extra", "row")
    finally:
        t.close()
    dt = ModelTable(4)
    info = snapshot_mod.bootstrap(dt, root, owner=(0, 1))
    assert info["offset"] == 500
    assert dt.get("k0") == "newer"  # shares the inode -> newer value,
    assert dt.get("extra") == "row"  # replay from offset 500 converges
    assert dt.get("k1") == "old"


def test_link_publish_survives_growth(tmp_path):
    """Growth retires + unlinks the old generation file; a link-published
    snapshot holds its own hardlink so the artifact stays decodable."""
    root = str(tmp_path / "snaps")
    t = ArenaModelTable(2, dir=str(tmp_path / "a"), capacity=64,
                        stride=16, key_cap=8, publish_mode="link")
    try:
        t.put_many([(f"k{i}", f"v{i}") for i in range(40)])
        snapshot_mod.publish(root, t, 40, shard=0, num_shards=1)
        # force a rehash into generation g+1 (load factor + oversize val)
        t.put_many([(f"g{i}", "x" * 200) for i in range(200)])
        assert t.arena.generation >= 1
    finally:
        t.close()
    dt = ModelTable(2)
    info = snapshot_mod.bootstrap(dt, root, owner=(0, 1))
    assert info["offset"] == 40
    assert dt.get("k39") == "v39"


def test_corrupt_arena_snapshot_falls_down_chain(tmp_path):
    root = str(tmp_path / "snaps")
    t = ArenaModelTable(2, dir=str(tmp_path / "a"))
    try:
        t.put_many([(f"k{i}", "old") for i in range(10)])
        snapshot_mod.publish(root, t, 100, shard=0, num_shards=1)
        time.sleep(0.002)
        t.put_many([(f"k{i}", "new") for i in range(10)])
        m2 = snapshot_mod.publish(root, t, 200, shard=0, num_shards=1)
    finally:
        t.close()
    # truncate the newest member's arena mid-file: structural decode fails
    with open(os.path.join(m2["path"], "arena.dat"), "r+b") as f:
        f.truncate(96)
    corrupt = []
    dt = ModelTable(2)
    info = snapshot_mod.bootstrap(dt, root, owner=(0, 1),
                                  on_corrupt=corrupt.append)
    assert info["offset"] == 100  # fell back to the older snapshot
    assert dt.get("k5") == "old"
    assert len(corrupt) == 1


def test_memory_backend_checkpoint_cycle_with_arena(tmp_path):
    t = ArenaModelTable(2, dir=str(tmp_path / "a"))
    try:
        be = MemoryStateBackend()
        t.put("k", "v")
        be.snapshot(t, 4242)
        assert be.restore(t) == 4242
        assert t.get("k") == "v"  # rows live in the arena, untouched
    finally:
        t.close()


# -- update plane ------------------------------------------------------------

def test_update_worker_writes_arena_in_place(tmp_path):
    """A co-located update worker's SGD rows become queryable through the
    shared pages immediately — no journal round-trip for visibility."""
    from flink_ms_tpu.serve import update_plane as up

    t = ArenaModelTable(2, dir=str(tmp_path / "a"))
    a = NativeArena(str(tmp_path / "a"))
    try:
        t.put("1-U", "0.0;0.0")
        t.put("5-I", "1.0;1.0")

        class _Client:
            def query_state(self, state, key):
                return t.get(key)

            def mget(self, state, keys):
                return [t.get(k) for k in keys]

        base = str(tmp_path)
        up.UpdatePlaneClient(base, "models", partitions=2).submit_many(
            [(1, 5, 4.0)])
        w = up.UpdateWorker(
            base, "models", 0, 1, table=t,
            client_factory=_Client, partitions=2, batch_size=8,
            poll_s=0.005, visibility_probe=False)
        w.start()
        try:
            deadline = time.time() + 20
            while w.stats["applied"] < 1 and time.time() < deadline:
                time.sleep(0.01)
            assert w.stats["applied"] >= 1
            # the updated user vector is in the ARENA (native reader sees
            # it) without any consumer replaying the model journal
            assert a.get("1-U") not in (None, "0.0;0.0")
        finally:
            w.stop()
    finally:
        a.close()
        t.close()


# -- native write plane (round 17) -------------------------------------------

def test_batch_writer_byte_parity_fuzz(tmp_path, monkeypatch):
    """The C++ batch writer must be byte-for-byte the Python writer: the
    same randomized batch sequence (inserts, in-place updates, growth
    triggered mid-batch by load factor / oversize rows) produces an
    IDENTICAL arena file either way — seqlock values, untouched value
    tails, header counters and all."""
    rng = random.Random(17)
    pool = [f"{rng.randrange(3000)}-{'UI'[rng.randrange(2)]}"
            for _ in range(2500)]
    batches = []
    for _ in range(30):
        n = rng.randrange(1, 200)
        ks = [rng.choice(pool) for _ in range(n)]
        vs = [";".join(f"{rng.uniform(-9, 9):.4f}"
                       for _ in range(rng.randrange(1, 6)))
              for _ in range(n)]
        batches.append((ks, vs))
    # one batch straddles a geometry flip: an oversize value mid-batch
    # forces the native path's grow-and-resume fallback
    batches.insert(10, ([f"g{i}" for i in range(50)],
                        ["x" * 300 if i == 25 else f"v{i}"
                         for i in range(50)]))

    def build(native: bool) -> bytes:
        monkeypatch.setenv("TPUMS_ARENA_BATCH", "1" if native else "0")
        t = ArenaModelTable(4, dir=str(tmp_path / f"n{int(native)}"),
                            capacity=256, stride=32, key_cap=16)
        try:
            assert (t._writer_h is not None) == native
            for ks, vs in batches:
                t.put_many_columns(list(ks), list(vs))
            t.flush()
            path = t.arena.path
        finally:
            t.close()
        with open(path, "rb") as f:
            return f.read()

    native_bytes = build(True)
    assert native_bytes == build(False)
    assert len(native_bytes) > ar.HEADER_SIZE


def test_put_many_columns_newline_rows_fall_back(tmp_path):
    """Rows with embedded newlines can't ride the '\\n'-joined columnar
    blobs — the per-row path must absorb them transparently."""
    t = ArenaModelTable(2, dir=str(tmp_path / "a"))
    try:
        t.put_many_columns(["a", "b"], ["line1\nline2", "plain"])
        assert t.get("a") == "line1\nline2"
        assert t.get("b") == "plain"
    finally:
        t.close()


def test_cas_many_columns_semantics(tmp_path):
    t = ArenaModelTable(2, dir=str(tmp_path / "a"))
    try:
        t.put_many_columns(["k1", "k2", "k3"], ["a", "b", "c"])
        v0 = t.version
        failed = t.cas_many_columns(
            ["k1", "k2", "missing", "k3"],
            ["a", "WRONG", "x", None],
            ["a2", "b2", "x2", "c2"])
        assert failed == [1, 2, 3]  # drift, missing key, None expected
        assert t.get("k1") == "a2"  # swapped in place
        assert t.get("k2") == "b"   # drift NOT clobbered — LWW is caller's
        assert t.get("k3") == "c"
        assert t.version == v0 + 1 and t.puts >= 4
    finally:
        t.close()


def test_cas_vs_put_sgd_batch_parity(tmp_path):
    """Applying an online/sgd.py vectorized batch through CAS-in-place
    must land the exact same state (same file bytes) as the re-put path
    the update plane used before."""
    from flink_ms_tpu.online.sgd import SGDStep

    rng = random.Random(99)
    seeds = {}
    for i in range(40):
        seeds[f"{i}-U"] = ";".join(
            f"{rng.uniform(-1, 1):.4f}" for _ in range(4))
        seeds[f"{i}-I"] = ";".join(
            f"{rng.uniform(-1, 1):.4f}" for _ in range(4))
    ratings = [(rng.randrange(40), rng.randrange(40), rng.uniform(1, 5))
               for _ in range(120)]  # repeated keys exercise CAS drift

    def make(name):
        t = ArenaModelTable(2, dir=str(tmp_path / name), capacity=1024)
        t.put_many_columns(list(seeds), [seeds[k] for k in seeds])
        return t

    ta, tb = make("cas"), make("put")
    mean = "0.5;0.5;0.5;0.5"
    step = SGDStep(ta.get, mean, mean,
                   lookup_many=lambda ks: [ta.get(k) for k in ks])
    rows = step.process_batch(ratings)
    updates = []
    for row in rows:
        id_, typ, vec = row.split(",", 2)
        updates.append((f"{id_}-{typ}", vec))
    keys = [k for k, _ in updates]
    vals = [v for _, v in updates]
    # CAS path: expected = the value on disk BEFORE this batch (what the
    # update worker recorded at read time); intra-batch repeats drift and
    # fall back to the LWW re-put, exactly like the worker does
    expected = [ta.get(k) for k in keys]
    failed = ta.cas_many_columns(keys, expected, vals)
    if failed:
        ta.put_many_columns([keys[i] for i in failed],
                            [vals[i] for i in failed])
    tb.put_many_columns(keys, vals)
    try:
        assert dict(ta.items()) == dict(tb.items())
        ta.flush()
        tb.flush()
        with open(ta.arena.path, "rb") as fa, \
                open(tb.arena.path, "rb") as fb:
            assert fa.read() == fb.read()
    finally:
        ta.close()
        tb.close()


def test_native_metrics_includes_write_plane_counters(tmp_path):
    """The C++ METRICS verb splices the writer.stats sidecar counters, so
    server processes export the write plane without any Python push."""
    t = ArenaModelTable(2, dir=str(tmp_path / "a"))
    try:
        if t._writer_h is None:
            pytest.skip("native batch writer unavailable (no toolchain)")
        t.put_many_columns([f"k{i}" for i in range(128)], ["v"] * 128)
        failed = t.cas_many_columns(["k1", "k2"], ["v", "nope"],
                                    ["w1", "w2"])
        assert failed == [1]
        a = NativeArena(str(tmp_path / "a"))
        try:
            ws = a.write_stats()
            assert ws is not None and ws["batch_rows"] >= 128
            assert ws["cas_success"] >= 1 and ws["cas_retry"] >= 1
            with NativeLookupServer(a, ALS_STATE, job_id="jid",
                                    port=0) as srv:
                reply = _raw(srv.port, b"METRICS\n").decode()
            snap = json.loads(reply[2:])
            counters = {c["name"]: c["value"] for c in snap["counters"]}
            assert counters["tpums_arena_batch_rows_total"] >= 128
            assert counters["tpums_arena_batch_put_seconds_total"] > 0
            assert counters["tpums_arena_cas_success_total"] >= 1
            assert counters["tpums_arena_cas_retry_total"] >= 1
        finally:
            a.close()
    finally:
        t.close()


def test_b2_64get_frame_reply_syscall_budget(tmp_path):
    """Acceptance: a 64-GET B2 frame costs <= 4 reply-path syscalls with
    io_uring; the epoll + scatter-gather sendmsg fallback must still beat
    64 per-reply send() calls by >= 8x.  Counted through the server's own
    io accounting (tpums_server_io_stats) — strace is unavailable in the
    CI sandbox."""
    from flink_ms_tpu.serve import proto

    t = ArenaModelTable(4, dir=str(tmp_path / "a"))
    try:
        keys = [f"{i}-U" for i in range(64)]
        t.put_many_columns(keys, [f"{i}.5" for i in range(64)])

        def read_frame(s, buf):
            while True:
                res = proto.decode_reply_frame(buf, 0)
                if res is not None:
                    return res[0], buf[res[1]:]
                chunk = s.recv(1 << 20)
                assert chunk, "server closed mid-frame"
                buf += chunk

        with NativeLookupServer(NativeArena(str(tmp_path / "a")),
                                ALS_STATE, job_id="jid", port=0) as srv:
            with socket.create_connection(("127.0.0.1", srv.port),
                                          timeout=10) as s:
                f = s.makefile("rb")
                s.sendall(b"HELLO\tB2\n")
                assert f.readline() == b"HELLO\tB2\n"
                # warm the connection: the first frame pays one-time costs
                s.sendall(proto.encode_request_frame(["PING"]))
                _, rest = read_frame(s, b"")
                before = srv.io_stats()
                s.sendall(proto.encode_request_frame(
                    [f"GET\t{ALS_STATE}\t{k}" for k in keys]))
                texts, _ = read_frame(s, rest)
                after = srv.io_stats()
        assert len(texts) == 64
        assert all(x.startswith("V\t") for x in texts)
        delta = after["reply_syscalls"] - before["reply_syscalls"]
        if after["uring"]:
            assert delta <= 4, f"{delta} reply syscalls with io_uring"
        else:
            # skip reason for the <=4 budget: io_uring unavailable on
            # this kernel (TPUMS_URING=0 or probe failed) — hold the
            # fallback to the >=8x-vs-per-reply-send bound instead
            assert delta <= 8, f"{delta} reply syscalls on sendmsg fallback"
    finally:
        t.close()


_KILL_BATCH_WRITER = r"""
import os, sys, time
sys.path.insert(0, {repo!r})
from flink_ms_tpu.serve.arena import ArenaModelTable
t = ArenaModelTable(2, dir={dir!r}, capacity=1024)
assert t._writer_h is not None, "native batch writer required"
keys = [f"k{{i}}" for i in range(64)]
t.put_many_columns(keys, [f"v{{i}}" for i in range(64)])
t.flush()
print("SEEDED", flush=True)
i = 0
while True:  # hot native batch + CAS loop until SIGKILLed mid-call
    vals = [f"update-{{i}}-{{j}}" for j in range(64)]
    t.put_many_columns(keys, vals)
    t.cas_many_columns(["k7"], [vals[7]], [f"cas-{{i}}"])
    i += 1
"""


def test_sigkill_mid_native_batch_no_torn_rows(tmp_path):
    """SIGKILL during the C++ batch writer / CAS hot loop: every row
    post-mortem is a VALID value from some write (or missing via an
    odd-stuck seq) — never interleaved garbage — and a respawned writer
    repairs the arena."""
    adir = str(tmp_path / "a")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-c",
         _KILL_BATCH_WRITER.format(repo=repo, dir=adir)],
        stdout=subprocess.PIPE)
    try:
        assert proc.stdout.readline().strip() == b"SEEDED"
        a = NativeArena(adir)
        try:
            time.sleep(0.1)  # let the native hot loop spin
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
            for j in range(64):
                v = a.get(f"k{j}")
                ok = (
                    v is None or v == f"v{j}"
                    or (v.startswith("update-") and v.endswith(f"-{j}"))
                    or (j == 7 and v.startswith("cas-"))
                )
                assert ok, f"torn row k{j}: {v!r}"
        finally:
            a.close()
        t = ArenaModelTable(2, dir=adir)
        try:
            t.put_many_columns([f"k{j}" for j in range(64)],
                               ["repaired"] * 64)
            for j in range(64):
                assert t.get(f"k{j}") == "repaired"
        finally:
            t.close()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


# -- crash semantics (SIGKILL the writer process) ----------------------------

_KILL_WRITER = r"""
import os, sys, time
sys.path.insert(0, {repo!r})
from flink_ms_tpu.serve.arena import ArenaModelTable
t = ArenaModelTable(2, dir={dir!r})
t.put_many([(f"k{{i}}", f"v{{i}}") for i in range(64)])
t.flush()
print("SEEDED", flush=True)
i = 0
while True:  # hot update loop until SIGKILLed mid-row
    t.put("k7", f"update-{{i}}")
    i += 1
"""


def test_sigkill_writer_never_yields_torn_rows(tmp_path):
    adir = str(tmp_path / "a")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-c",
         _KILL_WRITER.format(repo=repo, dir=adir)],
        stdout=subprocess.PIPE)
    try:
        assert proc.stdout.readline().strip() == b"SEEDED"
        a = NativeArena(adir)
        try:
            time.sleep(0.05)  # let the hot loop spin
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
            # post-mortem reads: k7 is either a VALID update-N value or
            # missing (odd-stuck) — never garbage; everything else intact
            v = a.get("k7")
            assert v is None or v == "v7" or v.startswith("update-")
            for i in range(64):
                if i == 7:
                    continue
                assert a.get(f"k{i}") == f"v{i}"
        finally:
            a.close()
        # the flock died with the writer: a respawn attaches and repairs
        t = ArenaModelTable(2, dir=adir)
        try:
            t.put("k7", "repaired")
            assert t.get("k7") == "repaired"
        finally:
            t.close()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
