"""Push plane (serve/push.py + the edge hub, round 20): wire helpers and
the client-observed sequence audit, literal byte pins proving pull-only
connections are untouched on both planes while the engine is live,
SUBSCRIBE/RESUME/UNSUB end-to-end over B2 and tab, materialized top-k
deltas with re-score selectivity, and the zero-miss/zero-dup invariant
through the edge hub across replica death, a live 2->4 reshard, a region
failover, and cross-connection RESUME."""

import socket
import threading
import time

import pytest

from flink_ms_tpu.obs import metrics as obs_metrics
from flink_ms_tpu.serve import proto, registry
from flink_ms_tpu.serve import push as push_plane
from flink_ms_tpu.serve.client import QueryClient
from flink_ms_tpu.serve.consumer import ALS_STATE
from flink_ms_tpu.serve.edge import EdgeClient, EdgeProxy
from flink_ms_tpu.serve.elastic import generation_group
from flink_ms_tpu.serve.ha import shard_group
from flink_ms_tpu.serve.push import (
    apply_delta,
    audit_push_sequences,
    format_push,
    parse_push,
)
from flink_ms_tpu.serve.server import LookupServer
from flink_ms_tpu.serve.sharded import owner_of
from flink_ms_tpu.serve.table import ModelTable
from flink_ms_tpu.serve.topk import make_als_topk_handler

# the 0.25-grid fixture from test_native_protocol: every product and sum
# is exact in f32, so snapshots and deltas format deterministic scores
ROWS = [
    ("10-I", "1.0;0.5;-2.0;0.25"),
    ("11-I", "0.5;0.5;0.5;0.5"),
    ("12-I", "-1.0;2.0;1.5;-0.5"),
    ("7-U", "1.0;2.0;0.5;-1.0"),
]
Q7 = "1.0;2.0;0.5;-1.0"  # 7-U's factors; TOPK k=2 -> 12:4.25;11:1.25

HELLO = b"HELLO\tB2\n"


def _server(rows=ROWS, job_id="jid"):
    table = ModelTable(2)
    for k, v in rows:
        table.put(k, v)
    srv = LookupServer(
        {ALS_STATE: table}, host="127.0.0.1", port=0, job_id=job_id,
        topk_handlers={ALS_STATE: make_als_topk_handler(table)},
    ).start()
    return srv, table


@pytest.fixture
def pysrv():
    srv, table = _server()
    srv.table = table
    yield srv
    srv.stop()


def _raw(port, payload):
    with socket.create_connection(("127.0.0.1", port), timeout=10) as sock:
        sock.sendall(payload)
        sock.shutdown(socket.SHUT_WR)
        out = b""
        while True:
            chunk = sock.recv(65536)
            if not chunk:
                return out
            out += chunk


def _push_client(port, **kw):
    return QueryClient("127.0.0.1", port, proto="b2", push=True,
                       timeout_s=10, **kw)


def _counter_total(name, **labels):
    snap = obs_metrics.get_registry().snapshot()
    out = 0.0
    for c in snap.get("counters", []):
        if c["name"] != name:
            continue
        if labels and any(c.get("labels", {}).get(k) != v
                          for k, v in labels.items()):
            continue
        out += c["value"]
    return out


# ---------------------------------------------------------------------------
# wire helpers + the sequence audit
# ---------------------------------------------------------------------------

def test_parse_hello_push_flag():
    assert proto.parse_hello(["HELLO", "B2"])["push"] is False
    assert proto.parse_hello(["HELLO", "B2", "su=1"])["push"] is True
    assert proto.parse_hello(
        ["HELLO", "B2", "tr=1", "su=1"]) == {
            "proto": "B2", "tenant": None, "trace": True,
            "stale": False, "push": True}
    # duplicate and unknown extensions stay malformed
    assert proto.parse_hello(["HELLO", "B2", "su=1", "su=1"]) is None
    assert proto.parse_hello(["HELLO", "B2", "su=2"]) is None


def test_push_text_format_parse_roundtrip():
    text = format_push("3-7", 12, "+12:10.0;-11")
    assert text == "PUSH\t3-7\t12\t+12:10.0;-11"
    assert proto.is_push_text(text)
    assert parse_push(text) == ("3-7", 12, "+12:10.0;-11")
    # the prefix is deliberately not P\t: PROFILE replies own that
    assert not proto.is_push_text("P\tprofile-things")
    assert not proto.is_push_text("PONG\tjid\tALS_MODEL")
    with pytest.raises(ValueError):
        parse_push("V\t1.0;2.0")


def test_apply_delta_folds_shortlist():
    shortlist = {"12": 4.25, "11": 1.25}
    apply_delta(shortlist, "+12:10.0")
    assert shortlist == {"12": 10.0, "11": 1.25}
    apply_delta(shortlist, "-11;+10:12.5")
    assert shortlist == {"12": 10.0, "10": 12.5}
    with pytest.raises(ValueError):
        apply_delta(shortlist, "12:4.0")


def test_audit_clean_stream_and_resume_baselines():
    events = [("S", "a", 0), ("P", "a", 1), ("P", "a", 2),
              ("S", "a", 2),               # RESUME replay ack at seq 2
              ("P", "a", 3),
              ("S", "b", 0), ("P", "b", 1)]
    audit = audit_push_sequences(events, tiles=4)
    assert (audit["missed"], audit["duplicates"]) == (0, 0)
    assert audit["subs"] == 2 and audit["delivered"] == 4
    assert sum(t["delivered"] for t in audit["tiles"]) == 4


def test_audit_detects_holes_and_duplicates():
    audit = audit_push_sequences(
        [("S", "a", 0), ("P", "a", 1), ("P", "a", 3),   # hole: 2
         ("P", "a", 3),                                 # duplicate
         ("P", "b", 5)])                                # no baseline
    assert audit["missed"] == 1 + 4   # a's hole + b's missing 1..4
    assert audit["duplicates"] == 1
    with pytest.raises(ValueError):
        audit_push_sequences([("X", "a", 1)])


def test_push_freshness_survives_counter_reset():
    """The rehearsal freshness gate folds the scrape SERIES reset-aware:
    a generation cutover that replaces every counter-holding process
    must not read a healthy push plane as a silent one (endpoint
    differencing would: after - before clamps to zero)."""
    from flink_ms_tpu.obs.scrape import push_freshness

    def snap(deltas, hist_count):
        le = [0.001, 0.01, 0.1]
        counts = [hist_count, 0, 0, 0]  # all observations under 1ms
        return {
            "counters": [{"name": "tpums_push_deltas_total",
                          "labels": {"state": "S", "kind": "KEY"},
                          "value": deltas}],
            "histograms": [{"name": "tpums_push_latency_seconds",
                            "labels": {"state": "S"}, "le": le,
                            "counts": counts, "count": hist_count,
                            "sum": hist_count * 0.0005}],
        }

    # gen 1 climbs to 40, cutover resets to 0, gen 2 climbs to 6
    series = [(0.0, snap(0, 0)), (1.0, snap(25, 25)),
              (2.0, snap(40, 40)), (3.0, snap(0, 0)),
              (4.0, snap(6, 6))]
    out = push_freshness(series)
    assert out["deltas"] == 46 and out["dt_s"] == 4.0
    assert out["p99_s"] is not None and out["p99_s"] <= 0.001
    # the endpoint pair alone would have seen nothing
    from flink_ms_tpu.obs.scrape import fleet_signals
    sig = fleet_signals(series[0][1], series[-1][1])
    assert sig["push_p99_s"] is not None  # 6 post-reset obs survive...
    assert sig["push_deltas_per_s"] * sig["dt_s"] < out["deltas"]
    # empty / single-sample series degrade to "no evidence", not a crash
    assert push_freshness([])["p99_s"] is None
    assert push_freshness([(0.0, snap(9, 9))])["deltas"] == 0.0


# ---------------------------------------------------------------------------
# pull-only byte identity: the opt-in costs unsubscribed clients nothing
# ---------------------------------------------------------------------------

_PULL_TAB_REQUESTS = (
    b"GET\tALS_MODEL\t7-U\n"
    b"TOPK\tALS_MODEL\t7\t2\n"
    b"SUBSCRIBE\n"          # malformed arity -> the generic error
    b"PING\n"
)
_PULL_TAB_REPLIES = (
    b"V\t1.0;2.0;0.5;-1.0\n"
    b"V\t12:4.25;11:1.25\n"
    b"E\tbad request\n"
    b"PONG\tjid\tALS_MODEL\n"
)
# literal frame bytes, NOT computed: if the B2 plane's framing or reply
# rendering drifts for pull-only clients, this fails even if the codec
# helpers drift in sympathy
_PULL_B2_REQUEST = (
    HELLO
    + b"B2 \x03\x01\tALS_MODEL\x037-U\x03\tALS_MODEL\x017\x012\t"
)
_PULL_B2_REPLIES = (
    HELLO
    + b"B2\x39\x03"                 # one frame, three replies
    + b"\x12V\t1.0;2.0;0.5;-1.0"
    + b"\x11V\t12:4.25;11:1.25"
    + b"\x12PONG\tjid\tALS_MODEL"
)


def test_pull_only_bytes_pinned_while_engine_live(pysrv):
    """Both pull planes answer byte-identically even while the SAME
    server holds a live subscription and is streaming deltas."""
    with _push_client(pysrv.port) as sub_c:
        sub_c.subscribe_key(ALS_STATE, "11-I")
        assert _raw(pysrv.port, _PULL_TAB_REQUESTS) == _PULL_TAB_REPLIES
        assert _raw(pysrv.port, _PULL_B2_REQUEST) == _PULL_B2_REPLIES
        # the engine really was live: the pull exchanges above did not
        # swallow the subscriber's delta
        pysrv.table.put("11-I", "2.0;2.0;2.0;2.0")
        msg = sub_c.next_push(timeout_s=5.0)
        assert msg is not None and msg[2] == "2.0;2.0;2.0;2.0"


def test_pull_only_client_request_bytes_pinned():
    """A pull-only QueryClient/EdgeClient (push defaulted off) puts
    exactly the frozen bytes on the wire — no su=1, no framing drift."""
    captured = []
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)
    port = lsock.getsockname()[1]

    def serve():
        conn, _ = lsock.accept()
        with conn, conn.makefile("rb") as f:
            line = f.readline()
            captured.append(line)
            conn.sendall(b"V\t1.0;2.0\n")

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    try:
        with EdgeClient(endpoints=[("127.0.0.1", port)],
                        timeout_s=10) as c:
            c.query_state(ALS_STATE, "7-U")
        t.join(timeout=5)
    finally:
        lsock.close()
    assert captured == [b"GET\tALS_MODEL\t7-U\n"]


def test_b2_subscribe_without_su_refused(pysrv):
    """SUBSCRIBE on a B2 connection that did not send su=1 is the
    pinned generic error — subscribing is strictly opt-in."""
    body = proto.encode_request_frame(
        [f"SUBSCRIBE\t{ALS_STATE}\tKEY\t10-I\t0"])
    out = _raw(pysrv.port, HELLO + body)
    assert out == HELLO + b"B2\x0f\x01\rE\tbad request"


def _native_available():
    from flink_ms_tpu.serve import native_store

    try:
        native_store._load_lib()
        return True
    except (OSError, RuntimeError):
        return False


@pytest.mark.skipif(not _native_available(),
                    reason="native toolchain/libtpums.so unavailable")
def test_native_plane_refuses_push_hello(tmp_path):
    """The C++ plane never learned su=1 — the unknown-extension HELLO is
    refused identically on both planes (stays tab, generic error) and
    the native pull path is untouched."""
    from flink_ms_tpu.serve.native_store import (NativeLookupServer,
                                                 NativeStore)

    store = NativeStore(str(tmp_path / "store"))
    for k, v in ROWS:
        store.put(k, v)
    srv_py, _ = _server()
    try:
        with NativeLookupServer(store, ALS_STATE, job_id="jid", port=0,
                                topk_suffixes=("-I", "-U")) as nsrv:
            nat = _raw(nsrv.port, b"HELLO\tB2\tsu=1\nPING\n")
            # refused exactly like the Python plane refuses an UNKNOWN
            # extension: generic error, the connection stays tab
            assert nat == _raw(srv_py.port, b"HELLO\tB2\txx=1\nPING\n")
            assert nat.startswith(b"E\tbad request\n")
            # the native pull path is untouched by the push plane
            assert _raw(nsrv.port, _PULL_TAB_REQUESTS) == \
                _PULL_TAB_REPLIES
    finally:
        srv_py.stop()
        store.close()


# ---------------------------------------------------------------------------
# SUBSCRIBE / UNSUB / RESUME end-to-end (direct B2 connection)
# ---------------------------------------------------------------------------

def test_subscribe_key_snapshot_delta_monotone_seq(pysrv):
    with _push_client(pysrv.port) as c:
        sub = c.subscribe_key(ALS_STATE, "10-I")
        assert sub["seq"] == 0
        assert sub["snapshot"] == "1.0;0.5;-2.0;0.25"
        pysrv.table.put("10-I", "5.0;5.0;5.0;5.0")
        assert c.next_push(timeout_s=5.0) == (
            sub["sub_id"], 1, "5.0;5.0;5.0;5.0")
        pysrv.table.put("10-I", "6.0;6.0;6.0;6.0")
        assert c.next_push(timeout_s=5.0) == (
            sub["sub_id"], 2, "6.0;6.0;6.0;6.0")


def test_subscribe_topk_materialized_delta_folds_to_truth(pysrv):
    with _push_client(pysrv.port) as c:
        sub = c.subscribe_topk(ALS_STATE, Q7, 2)
        assert sub["snapshot"] == "12:4.25;11:1.25"
        shortlist = {}
        apply_delta(shortlist, ";".join(
            f"+{e}" for e in sub["snapshot"].split(";")))
        pysrv.table.put("12-I", "2.0;4.0;1.0;0.5")  # q.12 -> 10.0
        sid, seq, payload = c.next_push(timeout_s=5.0)
        assert (sid, seq, payload) == (sub["sub_id"], 1, "+12:10.0")
        apply_delta(shortlist, payload)
        # the folded client shortlist equals a fresh materialization
        fresh = c.subscribe_topk(ALS_STATE, Q7, 2)
        assert shortlist == {item: float(s) for item, s in
                             (e.rsplit(":", 1)
                              for e in fresh["snapshot"].split(";"))}


def test_pull_queries_interleave_with_pushes(pysrv):
    with _push_client(pysrv.port) as c:
        sub = c.subscribe_key(ALS_STATE, "11-I")
        pysrv.table.put("11-I", "1.5;1.5;1.5;1.5")
        # the pull reply routes around the buffered push...
        assert c.query_state(ALS_STATE, "11-I") == "1.5;1.5;1.5;1.5"
        # ...and the push is still delivered, in order
        assert c.next_push(timeout_s=5.0) == (
            sub["sub_id"], 1, "1.5;1.5;1.5;1.5")


def test_unsubscribe_stops_deltas(pysrv):
    with _push_client(pysrv.port) as c:
        sub = c.subscribe_key(ALS_STATE, "10-I")
        c.unsubscribe(sub["sub_id"])
        pysrv.table.put("10-I", "9.0;9.0;9.0;9.0")
        assert c.next_push(timeout_s=0.4) is None
        with pytest.raises(RuntimeError):
            c.unsubscribe(sub["sub_id"])  # unknown now


def test_resume_replay_rebinds_live_subscription(pysrv):
    """A second connection RESUMEs a live subscription: the ring replays
    exactly the cursor gap and later deltas follow to the NEW conn."""
    c1 = _push_client(pysrv.port)
    sub = c1.subscribe_key(ALS_STATE, "10-I")
    pysrv.table.put("10-I", "5.0;5.0;5.0;5.0")
    assert c1.next_push(timeout_s=5.0)[1] == 1
    with _push_client(pysrv.port) as c2:
        r = c2.resume_subscription(ALS_STATE, "KEY", "10-I", 0,
                                   sub["sub_id"], 0)
        assert r == {"mode": "replay", "sub_id": sub["sub_id"], "seq": 0}
        assert c2.next_push(timeout_s=5.0) == (
            sub["sub_id"], 1, "5.0;5.0;5.0;5.0")
        c1.close()  # the old conn's death must not kill the rebound sub
        pysrv.table.put("10-I", "6.0;6.0;6.0;6.0")
        assert c2.next_push(timeout_s=5.0) == (
            sub["sub_id"], 2, "6.0;6.0;6.0;6.0")


def test_resume_unknown_falls_back_to_fresh_snapshot(pysrv):
    """A cursor nothing can bridge -> a FRESH subscription whose
    snapshot is the catch-up (zero-miss without replay)."""
    with _push_client(pysrv.port) as c:
        r = c.resume_subscription(ALS_STATE, "KEY", "10-I", 0,
                                  "999-1", 7)
        assert r["mode"] == "snapshot"
        assert r["sub_id"] != "999-1" and r["seq"] == 0
        assert r["snapshot"] == "1.0;0.5;-2.0;0.25"
        # the audit treats the fresh baseline as a clean stream
        audit = audit_push_sequences([("S", r["sub_id"], r["seq"])])
        assert (audit["missed"], audit["duplicates"]) == (0, 0)


def test_tab_subscribe_self_opts_in():
    """Sending SUBSCRIBE on a tab connection IS the opt-in: the S reply
    and newline-framed PUSH lines arrive on the same socket."""
    srv, table = _server()
    try:
        with socket.create_connection(("127.0.0.1", srv.port),
                                      timeout=10) as sock:
            f = sock.makefile("rb")
            sock.sendall(b"SUBSCRIBE\tALS_MODEL\tKEY\t10-I\t0\n")
            reply = f.readline().decode("utf-8").rstrip("\n")
            assert reply.startswith("S\t")
            sub_id = reply.split("\t")[1]
            table.put("10-I", "3.0;3.0;3.0;3.0")
            assert f.readline().decode("utf-8").rstrip("\n") == \
                f"PUSH\t{sub_id}\t1\t3.0;3.0;3.0;3.0"
    finally:
        srv.stop()


def test_rescore_selectivity_narrows_to_intersecting_subs(pysrv):
    """One dirty item re-scores only subscriptions whose shortlist holds
    it (member index) or that it could enter (entrant filter) — never
    the whole population."""
    eng = None
    clients = []
    try:
        # 8 subscriptions whose k=1 shortlists pin to distinct items
        for q in ("1.0;0.0;0.0;0.0", "0.0;1.0;0.0;0.0",
                  "0.0;0.0;1.0;0.0", "0.0;0.0;0.0;1.0",
                  "-1.0;0.0;0.0;0.0", "0.0;-1.0;0.0;0.0",
                  "0.0;0.0;-1.0;0.0", "0.0;0.0;0.0;-1.0"):
            c = _push_client(pysrv.port)
            c.subscribe_topk(ALS_STATE, q, 1)
            clients.append(c)
        eng = pysrv._push_engine
        b0, c0, t0 = eng.batches, eng.candidates, eng.candidate_total
        pysrv.table.put("11-I", "0.5;0.5;0.5;0.25")  # small nudge
        deadline = time.time() + 10
        while eng.candidate_total == t0 and time.time() < deadline:
            time.sleep(0.02)
        population = eng.candidate_total - t0
        candidates = eng.candidates - c0
        assert population >= 8
        assert 0 < candidates < population
    finally:
        for c in clients:
            c.close()


# ---------------------------------------------------------------------------
# client tolerance: unsolicited push frames between replies (fake server)
# ---------------------------------------------------------------------------

class _PushyFakeServer:
    """A one-connection B2 server that injects an unsolicited PUSH frame
    BEFORE every reply — the torture case for reply routing."""

    def __init__(self, replies):
        self._replies = list(replies)
        self._srv = socket.socket()
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(1)
        self.port = self._srv.getsockname()[1]
        self._t = threading.Thread(target=self._serve, daemon=True)
        self._t.start()

    def _serve(self):
        conn, _ = self._srv.accept()
        with conn:
            hello = b""
            while not hello.endswith(b"\n"):  # byte-wise: no buffer theft
                b_ = conn.recv(1)
                if not b_:
                    return
                hello += b_
            conn.sendall(HELLO)
            n = 0
            buf = b""
            while self._replies:
                chunk = conn.recv(65536)
                if not chunk:
                    return
                buf += chunk
                while self._replies:
                    res = proto.decode_request_frame(buf, 0)
                    if res is None:
                        break
                    _, pos = res
                    buf = buf[pos:]
                    n += 1
                    conn.sendall(proto.encode_reply_frame(
                        [format_push("9-1", n, f"payload{n}")]))
                    conn.sendall(proto.encode_reply_frame(
                        [self._replies.pop(0)]))
            time.sleep(0.2)

    def close(self):
        self._srv.close()


@pytest.mark.parametrize("client_cls", ["query", "edge"])
def test_reader_loop_tolerates_unsolicited_push_frames(client_cls):
    fake = _PushyFakeServer(["V\t1.0;2.0", "C\t4"])
    try:
        if client_cls == "edge":
            c = EdgeClient(endpoints=[("127.0.0.1", fake.port)],
                           proto="b2", push=True, timeout_s=10)
        else:
            c = _push_client(fake.port)
        with c:
            # each reply is preceded by a push frame: replies still
            # pair with their requests, pushes queue for next_push
            assert c.query_state(ALS_STATE, "7-U") == "1.0;2.0"
            assert c.count(ALS_STATE) == 4
            assert c.next_push(timeout_s=1.0) == ("9-1", 1, "payload1")
            assert c.next_push(timeout_s=1.0) == ("9-1", 2, "payload2")
    finally:
        fake.close()


# ---------------------------------------------------------------------------
# the edge hub: dedup fan-out, resync across deaths, RESUME across conns
# ---------------------------------------------------------------------------

def _register_worker(srv, group, gen=1, shard=0, replica=0):
    registry.register(
        f"w:{group}@g{gen}:s{shard}r{replica}:{srv.port}",
        "127.0.0.1", srv.port, ALS_STATE,
        replica_of=shard_group(generation_group(group, gen), shard),
        replica=replica, ready=True, ttl_s=300.0)


def _edge_push_client(proxy, **kw):
    return EdgeClient(endpoints=[("127.0.0.1", proxy.port)],
                      proto="b2", push=True, timeout_s=10, **kw)


def _collect(c, n, timeout_s=20.0):
    """Drain n pushes (or time out) -> list of (sub_id, seq, payload)."""
    out = []
    deadline = time.time() + timeout_s
    while len(out) < n and time.time() < deadline:
        msg = c.next_push(timeout_s=0.25)
        if msg is not None:
            out.append(msg)
    return out


def test_edge_hub_dedups_fanout_one_upstream_many_downstream():
    group = "push-fan"
    srv, table = _server()
    proxy = None
    clients = []
    try:
        _register_worker(srv, group)
        registry.publish_topology(group, 1)
        proxy = EdgeProxy(group, register=False, hedge=False).start()
        up0 = _counter_total("tpums_push_upstream_deltas_total")
        no0 = _counter_total("tpums_push_notifications_total")
        clients = [_edge_push_client(proxy) for _ in range(3)]
        subs = [c.subscribe_key(ALS_STATE, "10-I") for c in clients]
        assert len({s["sub_id"] for s in subs}) == 3  # per-client ids
        table.put("10-I", "4.0;4.0;4.0;4.0")
        events = []
        for c, s in zip(clients, subs):
            events.append(("S", s["sub_id"], s["seq"]))
            (got,) = _collect(c, 1)
            assert got[2] == "4.0;4.0;4.0;4.0"
            events.append(("P", got[0], got[1]))
        audit = audit_push_sequences(events)
        assert (audit["missed"], audit["duplicates"]) == (0, 0)
        # N downstream notifications per ONE upstream delta
        assert _counter_total("tpums_push_upstream_deltas_total") - up0 \
            == 1
        assert _counter_total("tpums_push_notifications_total") - no0 \
            == 3
    finally:
        for c in clients:
            c.close()
        if proxy is not None:
            proxy.stop()
        srv.stop()


def test_edge_resume_replays_exact_gap_across_connections():
    """Downstream conn dies; the hub ring keeps accumulating; RESUME on
    a fresh conn replays exactly the missed seqs — nothing more."""
    group = "push-resume"
    srv, table = _server()
    proxy = None
    try:
        _register_worker(srv, group)
        registry.publish_topology(group, 1)
        proxy = EdgeProxy(group, register=False, hedge=False).start()
        c1 = _edge_push_client(proxy)
        sub = c1.subscribe_key(ALS_STATE, "10-I")
        table.put("10-I", "1.0;1.0;1.0;1.0")
        assert _collect(c1, 1)[0][1] == 1
        c1.close()
        table.put("10-I", "2.0;2.0;2.0;2.0")  # accumulates unbound
        with _edge_push_client(proxy) as c2:
            deadline = time.time() + 10
            while True:  # the hub needs a beat to ingest the delta
                r = c2.resume_subscription(ALS_STATE, "KEY", "10-I", 0,
                                           sub["sub_id"], 1)
                if r["mode"] == "replay":
                    got = _collect(c2, 1)
                    if got and got[0] == (sub["sub_id"], 2,
                                          "2.0;2.0;2.0;2.0"):
                        break
                assert time.time() < deadline, r
                time.sleep(0.1)
            # a cursor nothing holds -> fresh-id snapshot fallback
            r = c2.resume_subscription(ALS_STATE, "KEY", "10-I", 0,
                                       "bogus-9", 3)
            assert r["mode"] == "snapshot"
            assert r["sub_id"] != sub["sub_id"]
            assert r["snapshot"] == "2.0;2.0;2.0;2.0"
    finally:
        if proxy is not None:
            proxy.stop()
        srv.stop()


def _await_catchup(c, events, expect, timeout_s=25.0):
    """Collect pushes until each predicate in ``expect`` matched one, in
    order, appending every push to the audit event log."""
    deadline = time.time() + timeout_s
    want = list(expect)
    while want and time.time() < deadline:
        msg = c.next_push(timeout_s=0.25)
        if msg is None:
            continue
        events.append(("P", msg[0], msg[1]))
        if want and want[0](msg):
            want.pop(0)
    assert not want, f"missed expected pushes, {len(want)} left"


def test_edge_resync_bridges_replica_death_zero_gap():
    """HA kill: the subscribed-to replica dies; the hub re-subscribes
    against its sibling and emits ONE catch-up delta on the SAME sub id
    with the next contiguous seq — no hole, no duplicate."""
    group = "push-ha"
    srv_a, table_a = _server(job_id="r0")
    srv_b, table_b = _server(job_id="r1")
    proxy = None
    clients = []
    try:
        _register_worker(srv_a, group, shard=0, replica=0)
        registry.publish_topology(group, 1)
        proxy = EdgeProxy(group, register=False, hedge=False).start()
        clients = [_edge_push_client(proxy) for _ in range(2)]
        events = []
        subs = []
        for c in clients:
            s = c.subscribe_key(ALS_STATE, "10-I")
            subs.append(s)
            events.append(("S", s["sub_id"], s["seq"]))
        table_a.put("10-I", "1.0;1.0;1.0;1.0")
        for c in clients:
            (got,) = _collect(c, 1)
            events.append(("P", got[0], got[1]))
        # the sibling holds newer state; the primary dies
        table_b.put("10-I", "7.0;7.0;7.0;7.0")
        _register_worker(srv_b, group, shard=0, replica=1)
        srv_a.stop()
        for c, s in zip(clients, subs):
            _await_catchup(
                c, events,
                [lambda m, sid=s["sub_id"]:
                 m[0] == sid and m[2] == "7.0;7.0;7.0;7.0"])
        audit = audit_push_sequences(events)
        assert (audit["missed"], audit["duplicates"]) == (0, 0)
        assert _counter_total("tpums_push_upstream_resyncs_total") > 0
    finally:
        for c in clients:
            c.close()
        if proxy is not None:
            proxy.stop()
        srv_b.stop()


def test_edge_resync_bridges_live_reshard_2_to_4():
    """2->4 reshard under a live TOPK subscription: gen-1 workers drain
    and die, the hub re-subscribes against the gen-2 topology, and the
    merged shortlist converges with contiguous seqs."""
    group = "push-reshard"
    gen1 = [_server(job_id=f"g1s{s}")[0:2] for s in range(2)]
    gen2 = []
    proxy = None
    c = None
    try:
        for s, (srv, _) in enumerate(gen1):
            _register_worker(srv, group, gen=1, shard=s)
        registry.publish_topology(group, 2)
        proxy = EdgeProxy(group, register=False, hedge=False).start()
        c = _edge_push_client(proxy)
        events = []
        sub = c.subscribe_topk(ALS_STATE, Q7, 2)
        events.append(("S", sub["sub_id"], sub["seq"]))
        # a delta flows on gen 1 first (both shards hold the full
        # fixture, so the merged union stays consistent)
        for _, table in gen1:
            table.put("12-I", "2.0;4.0;1.0;0.5")  # q.12 -> 10.0
        _await_catchup(c, events,
                       [lambda m: "+12:10.0" in m[2]])
        # gen 2: four workers seeded with CHANGED state (10-I enters)
        rows2 = [("10-I", "5.0;5.0;5.0;5.0"), ("11-I", "0.5;0.5;0.5;0.5"),
                 ("12-I", "2.0;4.0;1.0;0.5"), ("7-U", Q7)]
        gen2 = [_server(rows=rows2, job_id=f"g2s{s}")[0]
                for s in range(4)]
        for s, srv in enumerate(gen2):
            _register_worker(srv, group, gen=2, shard=s)
        registry.publish_topology(group, 4)
        for srv, _ in gen1:
            srv.stop()  # the cutover: gen-1 pipes die, resync follows
        # catch-up: 10-I (q.10 = 12.5) displaces 11 in the k=2 shortlist
        _await_catchup(c, events,
                       [lambda m: "+10:12.5" in m[2]])
        audit = audit_push_sequences(events)
        assert (audit["missed"], audit["duplicates"]) == (0, 0)
    finally:
        if c is not None:
            c.close()
        if proxy is not None:
            proxy.stop()
        for srv, _ in gen1:
            srv.stop()
        for srv in gen2:
            srv.stop()


def test_edge_resync_bridges_region_failover():
    """Region failover: the home fleet vanishes wholesale and a promoted
    follower (same group, new endpoint, newer state) takes over — the
    subscription stream stays gapless on the same sub id."""
    group = "push-region"
    home, home_table = _server(job_id="home")
    follower, follower_table = _server(job_id="follower")
    proxy = None
    c = None
    try:
        _register_worker(home, group, gen=1, shard=0)
        registry.publish_topology(group, 1)
        proxy = EdgeProxy(group, register=False, hedge=False).start()
        c = _edge_push_client(proxy)
        events = []
        sub = c.subscribe_key(ALS_STATE, "12-I")
        events.append(("S", sub["sub_id"], sub["seq"]))
        home_table.put("12-I", "1.0;1.0;1.0;1.0")
        _await_catchup(c, events, [lambda m: m[2] == "1.0;1.0;1.0;1.0"])
        # the follower replicated past the home's last visible write
        follower_table.put("12-I", "8.0;8.0;8.0;8.0")
        _register_worker(follower, group, gen=2, shard=0)
        registry.publish_topology(group, 1)
        home.stop()  # the whole home region goes dark
        _await_catchup(c, events,
                       [lambda m, sid=sub["sub_id"]:
                        m[0] == sid and m[2] == "8.0;8.0;8.0;8.0"])
        audit = audit_push_sequences(events)
        assert (audit["missed"], audit["duplicates"]) == (0, 0)
    finally:
        if c is not None:
            c.close()
        if proxy is not None:
            proxy.stop()
        follower.stop()
