"""Tail-latency forensics (obs/forensics.py) and its feeders: span-tree
assembly with missing-parent tolerance, self-time critical paths,
slow-vs-fast diffing that names the injected stage, spill collection
across rotated files, exemplar-linked histograms, the watch plane's
incident enrichment, the update plane's apply->publish->visible chain,
and the new fleet_signals keys."""

import json
import os
import time

import pytest

from flink_ms_tpu.obs import forensics as FX
from flink_ms_tpu.obs import metrics as M
from flink_ms_tpu.obs import tracing as T
from flink_ms_tpu.obs.rules import Rule
from flink_ms_tpu.obs.scrape import fleet_signals


def _span(tid, sid, kind, t0, dur, psid=None, **fields):
    ev = {"ts": t0 + dur, "tid": tid, "kind": kind, "sid": sid,
          "t0": t0, "dur_s": dur}
    if psid:
        ev["psid"] = psid
    ev.update(fields)
    return ev


# ---------------------------------------------------------------------------
# tree assembly + critical path
# ---------------------------------------------------------------------------

def test_assemble_links_children_and_promotes_orphans():
    evs = [
        _span("t1", "r0", "client_pipeline", 0.0, 0.010),
        _span("t1", "c1", "client_rpc", 0.001, 0.008, psid="r0"),
        # parent "gone" never spilled: subtree must become a root, not drop
        _span("t1", "o1", "server_reply", 0.002, 0.003, psid="gone",
              verb="GET"),
        # annotation (no sid, no dur) rides along without becoming a span
        {"ts": 0.004, "tid": "t1", "kind": "ha_failover"},
        # second trace stays separate
        _span("t2", "r0", "client_rpc", 0.0, 0.002),
    ]
    trees = FX.assemble(evs)
    assert set(trees) == {"t1", "t2"}
    t1 = trees["t1"]
    assert sorted(t1.roots) == ["o1", "r0"]
    assert t1.children["r0"] == ["c1"]
    assert [a["kind"] for a in t1.annotations] == ["ha_failover"]
    # duplicate sid keeps the longer duration (retried spill write)
    dup = FX.assemble([_span("t3", "s", "x", 0.0, 0.001),
                       _span("t3", "s", "x", 0.0, 0.005)])
    assert dup["t3"].spans["s"]["dur_s"] == 0.005


def test_total_is_wall_extent_not_sum_of_durations():
    # two overlapping fan-out legs under one root: wall = 10ms, sum = 19ms
    evs = [
        _span("t", "r", "fanout", 0.0, 0.010),
        _span("t", "a", "client_rpc", 0.001, 0.009, psid="r"),
        _span("t", "b", "client_rpc", 0.001, 0.009, psid="r"),
    ]
    tree = FX.assemble(evs)["t"]
    assert tree.total_s() == pytest.approx(0.010)


def test_self_time_subtracts_children_and_clips():
    evs = [
        _span("t", "p", "server_reply", 0.0, 0.010, verb="TOPKV"),
        _span("t", "c", "mb_device", 0.001, 0.009, psid="p"),
        # child longer than parent (clock skew): parent self clips to 0
        _span("t", "q", "server_reply", 0.0, 0.002, verb="GET"),
        _span("t", "d", "mb_device", 0.0, 0.004, psid="q"),
    ]
    tree = FX.assemble(evs)["t"]
    st = tree.self_times()
    assert st["server_reply:TOPKV"] == pytest.approx(0.001)
    assert st["server_reply:GET"] == 0.0
    assert st["mb_device"] == pytest.approx(0.013)
    ranked = FX.critical_path(tree)
    assert ranked[0]["stage"] == "mb_device"
    assert ranked[0]["share"] > 0.5
    # render shows nesting depth by indentation
    out = tree.render()
    assert "server_reply:TOPKV" in out and "  mb_device" in out


# ---------------------------------------------------------------------------
# slow-vs-fast diff
# ---------------------------------------------------------------------------

def _synthetic_trees(n=20, slow_every=10, slow_extra=0.020):
    """n traces of ~2ms GETs; every ``slow_every``-th carries an extra
    ``injected_slow`` child span of ``slow_extra`` seconds."""
    evs = []
    for i in range(n):
        tid = f"t{i:03d}"
        slow = (i % slow_every) == 0
        dur = 0.002 + (slow_extra if slow else 0.0) + i * 1e-6
        evs.append(_span(tid, "r", "client_rpc", 0.0, dur))
        evs.append(_span(tid, "s", "server_reply", 0.0005, 0.001,
                         psid="r", verb="GET"))
        if slow:
            evs.append(_span(tid, "x", "injected_slow", 0.0015,
                             slow_extra, psid="r"))
    return FX.assemble(evs)


def test_diff_ranks_injected_stage_first():
    trees = _synthetic_trees()
    d = FX.diff_slow_fast(trees, slow_q=0.9)
    assert d["slow_n"] >= 1 and d["fast_n"] >= 1
    assert d["stages"][0]["stage"] == "injected_slow"
    assert d["stages"][0]["delta_s"] == pytest.approx(0.020, rel=0.05)
    # the injected stage owns essentially the whole slow-fast gap
    assert d["stages"][0]["delta_share"] > 0.9
    # slow_tids lead with the slowest trace, and every one is an injected one
    assert all(int(t[1:]) % 10 == 0 for t in d["slow_tids"])
    assert d["quantiles"]["p99"] > d["quantiles"]["p50"]


def test_diff_degrades_gracefully_below_four_traces():
    trees = _synthetic_trees(n=3, slow_every=2)
    d = FX.diff_slow_fast(trees)
    assert d["n_traces"] == 3 and d["stages"] == [] and d["slow_tids"] == []


def test_report_and_render_name_the_stage(tmp_path):
    spill = tmp_path / "spill.jsonl"
    with open(spill, "w") as f:
        for tree in _synthetic_trees().values():
            for ev in tree.spans.values():
                f.write(json.dumps(ev) + "\n")
    rep = FX.report([str(spill)])
    assert rep["diff"]["stages"][0]["stage"] == "injected_slow"
    human = FX.render_human(rep)
    assert "#1 injected_slow" in human and "% of the gap" in human
    # CLI --json path round-trips the same report
    rc = FX.main([str(spill), "--json"])
    assert rc == 0
    # --tree renders a specific trace
    assert FX.main([str(spill), "--tree", rep["diff"]["slow_tids"][0]]) == 0
    assert FX.main([str(spill), "--tree", "nonexistent"]) == 1


def test_expand_paths_picks_up_rotated_siblings(tmp_path):
    p = tmp_path / "s.jsonl"
    for name in ["s.jsonl", "s.jsonl.1", "s.jsonl.2"]:
        (tmp_path / name).write_text("")
    got = FX.expand_paths([str(p)])
    assert got == [str(p), str(p) + ".1", str(p) + ".2"]
    # glob form finds the same set; duplicates collapse
    got2 = FX.expand_paths([str(tmp_path / "s.jsonl*"), str(p)])
    assert sorted(got2) == sorted(got)


def test_collect_merges_rotated_files_and_sets_staleness_gauges(tmp_path):
    p = tmp_path / "s.jsonl"
    (tmp_path / "s.jsonl.1").write_text(
        json.dumps(_span("old", "a", "client_rpc", 0.0, 0.001)) + "\n")
    p.write_text(
        json.dumps(_span("new", "b", "client_rpc", 10.0, 0.001)) + "\n"
        + "not json\n")
    evs = FX.collect([str(p)])
    assert [e["tid"] for e in evs] == ["old", "new"]  # ts-ordered
    snap = M.get_registry().snapshot()
    by = {g["name"]: g["value"] for g in snap["gauges"]}
    assert by["tpums_forensics_events"] == 2.0
    assert time.time() - by["tpums_forensics_last_collect_ts"] < 60.0


# ---------------------------------------------------------------------------
# exemplar-linked histograms
# ---------------------------------------------------------------------------

def test_histogram_retains_exemplars_only_with_gate_and_trace():
    reg = M.MetricsRegistry()
    h = reg.histogram("lat_s", bounds=[0.001, 0.01, 0.1])
    prev = M.set_exemplars(True)
    try:
        h.observe(0.0005)  # no trace in hand -> no exemplar
        h.observe(0.05, tid="aaaa000000000001")
        h.observe(0.0005, tid="aaaa000000000002")
        ex = h.exemplars()
        # bucket index 2 holds the 0.05 observation for the traced request
        assert ex[2][0] == "aaaa000000000001"
        assert ex[2][1] == pytest.approx(0.05)
        assert ex[0][0] == "aaaa000000000002"
        snap = reg.snapshot()
        hist = [e for e in snap["histograms"] if e["name"] == "lat_s"][0]
        assert hist["exemplars"]["2"][0] == "aaaa000000000001"
        # merge keeps the freshest exemplar per bucket
        other = dict(hist, exemplars={
            "2": ["bbbb000000000001", 0.09, time.time() + 100]})
        merged = M.merge_snapshots(
            [snap, {"ts": snap["ts"], "counters": [], "gauges": [],
                    "histograms": [other]}])
        mh = [e for e in merged["histograms"] if e["name"] == "lat_s"][0]
        assert mh["exemplars"]["2"][0] == "bbbb000000000001"
    finally:
        M.set_exemplars(prev)


def test_exemplars_off_by_default_costs_nothing():
    reg = M.MetricsRegistry()
    h = reg.histogram("lat2_s", bounds=[0.01])
    h.observe(0.5, tid="cccc000000000001")  # gate off: tid is ignored
    assert h.exemplars() == {}
    assert "exemplars" not in [e for e in reg.snapshot()["histograms"]
                               if e["name"] == "lat2_s"][0]


# ---------------------------------------------------------------------------
# watch plane: incident enrichment
# ---------------------------------------------------------------------------

def _fake_scrape(series, tid, bucket=5, value=0.08):
    return {"fleet": {"histograms": [
        {"name": series,
         "exemplars": {str(bucket): [tid, value, time.time()],
                       "1": ["fast-tid", 0.001, time.time()]}}]}}


def test_exemplar_tids_prefers_slowest_bucket_and_dedups():
    from flink_ms_tpu.obs.watch import _exemplar_tids
    sc = _fake_scrape("tpums_server_latency_seconds", "slow-tid")
    assert _exemplar_tids(sc, "tpums_server_latency_seconds") == \
        ["slow-tid", "fast-tid"]
    assert _exemplar_tids(sc, "other_series") == []


def test_watch_attaches_critical_path_to_quantile_firing(monkeypatch):
    """An alert_firing transition for a quantile rule gains exemplar tids
    and per-trace critical paths mined from the in-process ring."""
    from flink_ms_tpu.obs.watch import FleetWatcher
    monkeypatch.delenv("TPUMS_TRACE", raising=False)
    T.clear_events()
    tid = "feed000000000001"
    with T.trace_span(tid):
        with T.span("client_pipeline"):
            with T.span("injected_slow"):
                pass
    rule = Rule(name="p99", kind="threshold", series="lat_s",
                mode="quantile", q=99.0, op=">", value=0.01)
    w = FleetWatcher(interval_s=0.1, rules=[rule], publish=False)
    tr = {"ts": time.time(), "kind": "alert_firing", "rule": "p99",
          "severity": "warn", "measured": 0.08, "value": 0.01}
    w._attach_forensics([tr], _fake_scrape("lat_s", tid))
    assert tr["exemplar_tids"][0] == tid
    stages = [r["stage"] for r in tr["critical_path"][0]["critical_path"]]
    assert "injected_slow" in stages
    # a non-quantile firing is left untouched
    tr2 = {"ts": time.time(), "kind": "alert_firing", "rule": "nope"}
    w._attach_forensics([tr2], _fake_scrape("lat_s", tid))
    assert "exemplar_tids" not in tr2


def test_incident_context_tolerates_unknown_tids():
    T.clear_events()
    with T.trace_span("cafe000000000001"):
        with T.span("server_reply", verb="GET"):
            pass
    ctx = FX.incident_context(["cafe000000000001", "missing", "", None])
    assert ctx["exemplar_tids"] == ["cafe000000000001", "missing"]
    assert len(ctx["critical_path"]) == 1
    assert ctx["critical_path"][0]["tid"] == "cafe000000000001"


# ---------------------------------------------------------------------------
# spill rotation
# ---------------------------------------------------------------------------

def test_spill_rotation_keeps_k_files(tmp_path, monkeypatch):
    spill = tmp_path / "rot.jsonl"
    monkeypatch.setenv("TPUMS_TRACE", str(spill))
    monkeypatch.setenv("TPUMS_TRACE_MAX_BYTES", "400")
    monkeypatch.setenv("TPUMS_TRACE_KEEP", "2")
    for i in range(60):
        T.event("rotkind", tid=f"{i:016x}", seq=i)
    names = sorted(os.listdir(tmp_path))
    assert "rot.jsonl" in names and "rot.jsonl.1" in names
    assert "rot.jsonl.2" in names and "rot.jsonl.3" not in names
    # rotated generations stay parseable and forensics reads them as one
    total = len(FX.collect([str(spill)]))
    live = len(T.load_events(str(spill)))
    assert total > live  # rotated siblings contributed events
    # re-point the sink so later tests don't append here
    monkeypatch.setenv("TPUMS_TRACE", "0")
    T.event("flush")


# ---------------------------------------------------------------------------
# update plane: apply -> publish -> visible chain
# ---------------------------------------------------------------------------

def test_update_plane_emits_apply_publish_chain(tmp_path, monkeypatch):
    from flink_ms_tpu.serve import update_plane as up
    from tests.test_update_plane import TableClient, seed_table
    monkeypatch.setenv("TPUMS_TRACE_SAMPLE", "1")
    T.clear_events()
    table = seed_table()
    cli = up.UpdatePlaneClient(str(tmp_path), "models", partitions=2)
    cli.submit_many([(1, 2, 4.5), (3, 4, 2.0)])
    w = up.UpdateWorker(
        str(tmp_path), "models", 0, 1, table=table,
        client_factory=lambda: TableClient(table), partitions=2,
        batch_size=8, poll_s=0.005, visibility_probe=False).start()
    deadline = time.time() + 20
    while time.time() < deadline:
        if sum(up.applied_watermarks(str(tmp_path), "models", 2)
               .values()) >= 2:
            break
        time.sleep(0.01)
    w.stop()
    applies = T.recent_events(kind="update_apply")
    publishes = T.recent_events(kind="update_publish")
    assert applies and publishes
    # publish parents under apply within the same sampled trace
    by_tid = {a["tid"]: a for a in applies}
    linked = [p for p in publishes
              if p.get("psid") == by_tid.get(p["tid"], {}).get("sid")]
    assert linked, "no publish span parented under its apply span"
    tree = FX.assemble(applies + publishes)[linked[0]["tid"]]
    assert linked[0]["sid"] in tree.children[linked[0]["psid"]]
    ranked = [r["stage"] for r in FX.critical_path(tree)]
    assert "update_apply" in ranked and "update_publish" in ranked


# ---------------------------------------------------------------------------
# fleet_signals: new forensic keys
# ---------------------------------------------------------------------------

def test_fleet_signals_reports_span_rate_exemplars_and_staleness():
    now = time.time()
    before = {"ts": now - 10, "counters": [
        {"name": "tpums_trace_spans_total", "labels": {}, "value": 100.0}],
        "gauges": [], "histograms": []}
    after = {"ts": now, "counters": [
        {"name": "tpums_trace_spans_total", "labels": {}, "value": 150.0}],
        "gauges": [{"name": "tpums_forensics_last_collect_ts",
                    "labels": {}, "value": now - 5}],
        "histograms": [{"name": "lat_s", "counts": [], "bounds": [],
                        "sum": 0.0, "count": 0,
                        "exemplars": {"3": ["t1", 0.05, now],
                                      "5": ["t2", 0.2, now]}}]}
    sig = fleet_signals(before, after, dt_s=10.0)
    assert sig["trace_spans_per_s"] == pytest.approx(5.0)
    assert sig["exemplar_count"] == 2
    assert sig["forensics_staleness_s"] == pytest.approx(5.0, abs=2.0)
    # no collect ever -> staleness is None, not a crash
    after2 = dict(after, gauges=[])
    assert fleet_signals(before, after2, dt_s=10.0)[
        "forensics_staleness_s"] is None
