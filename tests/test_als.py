"""Blocked-ALS kernel tests: closed-form parity on tiny problems, numpy
reference half-sweeps, and multi-block == single-block equivalence on the
virtual 8-device CPU mesh (SURVEY.md §4 test strategy)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flink_ms_tpu.ops import als as A
from flink_ms_tpu.parallel.mesh import make_mesh


def _synthetic(rng, n_users=40, n_items=30, k_true=3, frac=0.6, noise=0.0):
    uf = rng.normal(size=(n_users, k_true))
    itf = rng.normal(size=(n_items, k_true))
    full = uf @ itf.T
    mask = rng.uniform(size=full.shape) < frac
    u, i = np.nonzero(mask)
    r = full[u, i] + noise * rng.normal(size=u.shape)
    return u.astype(np.int64), i.astype(np.int64), r


def _numpy_user_halfsweep(u, i, r, itf, k, lam, weighted):
    """Direct per-user normal-equation solve — the spec the kernel must match."""
    n_users = int(u.max()) + 1
    out = np.zeros((n_users, k))
    for uu in np.unique(u):
        sel = u == uu
        Y = itf[i[sel]]
        n_u = sel.sum()
        reg = lam * (n_u if weighted else 1.0)
        Amat = Y.T @ Y + reg * np.eye(k)
        out[uu] = np.linalg.solve(Amat, Y.T @ r[sel])
    return out


def test_prepare_blocked_layout(rng):
    u, i, r = _synthetic(rng)
    p = A.prepare_blocked(u, i, r, 4)
    assert all(a.shape[0] == 4 for a in p.u.idx)
    # every rating accounted for exactly once (counts sum to nnz; pad
    # entries = idx pointing at the opposite side's dummy slot)
    assert int(p.u.count.sum()) == p.nnz == len(r)
    assert int(p.i.count.sum()) == p.nnz
    i_pad_slot = p.i.per_block - 1
    n_pads = sum(int((ix == i_pad_slot).sum()) for ix in p.u.idx)
    total_cells = sum(ix.size for ix in p.u.idx)
    assert total_cells - n_pads == p.nnz
    # pad entries carry zero rating
    for ix, v in zip(p.u.idx, p.u.val):
        assert (v[ix == i_pad_slot] == 0).all()
    # the dummy slot is real: never a destination for any entity's factors
    assert i_pad_slot not in set(p.i.perm.tolist())
    assert (p.i.count[:, -1] == 0).all()  # every block's last slot is dummy
    # perm is a bijection into the slot space and respects block membership
    assert len(np.unique(p.u.perm)) == p.n_users
    dense_pb = -(-p.n_users // 4)
    np.testing.assert_array_equal(
        p.u.perm // p.u.per_block, np.arange(p.n_users) // dense_pb
    )
    # every bucket row's real-entry count fits its width
    for w, ix in zip(p.u.widths, p.u.idx):
        per_row = (ix != i_pad_slot).sum(axis=-1)
        assert per_row.max() <= w


def test_assembly_matches_numpy(rng):
    u, i, r = _synthetic(rng, n_users=12, n_items=9)
    k = 4
    p = A.prepare_blocked(u, i, r, 1)
    itf = rng.normal(size=(9, k)).astype(np.float32)
    y_all = np.zeros((p.i.per_block, k), dtype=np.float32)
    y_all[p.i.perm] = itf  # factor table lives in slot order
    buckets = [
        (jnp.asarray(p.u.idx[j][0]), jnp.asarray(p.u.val[j][0]))
        for j in range(len(p.u.widths))
    ]
    Amat, b = A._assemble_normal_eqs(
        jnp.asarray(y_all), buckets, False, 40.0, jnp.float32
    )
    Amat, b = np.asarray(Amat), np.asarray(b)
    for uu in range(12):
        sel = u == uu
        Y = itf[i[sel]]
        slot = p.u.perm[uu]
        np.testing.assert_allclose(Amat[slot], Y.T @ Y, rtol=1e-4)
        np.testing.assert_allclose(b[slot], Y.T @ r[sel], rtol=1e-4)


@pytest.mark.parametrize("k", [3, 8, 16, 50])
def test_chol_solve_unrolled_matches_numpy(rng, k):
    n = 257
    G = rng.standard_normal((n, k, k)).astype(np.float32)
    A_ = G @ G.transpose(0, 2, 1) + 5.0 * np.eye(k, dtype=np.float32)
    b = rng.standard_normal((n, k)).astype(np.float32)
    x = np.asarray(
        jax.jit(A._chol_solve_unrolled)(jnp.asarray(A_), jnp.asarray(b))
    )
    x_ref = np.linalg.solve(
        A_.astype(np.float64), b.astype(np.float64)[..., None]
    )[..., 0]
    np.testing.assert_allclose(x, x_ref, rtol=2e-3, atol=2e-4)


@pytest.mark.parametrize("k", [3, 8, 16, 50])
def test_chol_solve_panel_matches_numpy(rng, k):
    n = 257
    G = rng.standard_normal((n, k, k)).astype(np.float32)
    A_ = G @ G.transpose(0, 2, 1) + 5.0 * np.eye(k, dtype=np.float32)
    b = rng.standard_normal((n, k)).astype(np.float32)
    x = np.asarray(
        jax.jit(A._chol_solve_panel)(jnp.asarray(A_), jnp.asarray(b))
    )
    x_ref = np.linalg.solve(
        A_.astype(np.float64), b.astype(np.float64)[..., None]
    )[..., 0]
    np.testing.assert_allclose(x, x_ref, rtol=2e-3, atol=2e-4)


def test_predict_chunked_equals_unchunked(rng, monkeypatch):
    """Chunked prediction (padded-tail fixed-shape device calls) is
    element-equal to the single-call path — the chunking exists because an
    unchunked 20M-pair predict OOM'd 16 GB HBM in the round-3 bench
    quality anchor."""
    m = A.ALSModel(
        user_ids=np.arange(80), item_ids=np.arange(50),
        user_factors=rng.normal(size=(80, 6)).astype(np.float32),
        item_factors=rng.normal(size=(50, 6)).astype(np.float32),
    )
    u = rng.integers(0, 90, 30000)  # incl. some unknown ids -> score 0
    i = rng.integers(0, 55, 30000)
    full = A.predict(m, u, i)
    monkeypatch.setenv("FLINK_MS_PREDICT_CHUNK", "4097")
    np.testing.assert_array_equal(A.predict(m, u, i), full)


def test_auto_solver_resolution(monkeypatch):
    """"auto" resolves per backend: the round-3 on-chip matrix made pallas
    the TPU default (62.7 vs 444.9 ms/iter unrolled at 5M nnz / k=50); CPU
    keeps LAPACK-backed lax; explicit overrides pass through."""
    monkeypatch.delenv("FLINK_MS_ALS_SOLVER", raising=False)
    assert A.resolve_solver("tpu") == "pallas"
    assert A.resolve_solver("cpu") == "lax"
    assert A.resolve_solver(None) == "auto"  # unknown backend: k-heuristic
    monkeypatch.setenv("FLINK_MS_ALS_SOLVER", "panel")
    assert A.resolve_solver("tpu") == "panel"


def test_auto_exchange_resolution():
    """exchange_dtype="auto" resolves per backend — bfloat16 on TPU
    (chip-measured +20% at +1.4e-5 relative RMSE delta), full precision
    elsewhere; explicit values and None pass through untouched."""
    assert A.resolve_exchange("auto", "tpu") == "bfloat16"
    assert A.resolve_exchange("auto", "cpu") is None
    assert A.resolve_exchange("auto", None) is None
    assert A.resolve_exchange(None, "tpu") is None
    assert A.resolve_exchange("bfloat16", "cpu") == "bfloat16"
    assert A.ALSConfig().exchange_dtype == "auto"


def test_fit_with_panel_solver_matches_default(rng, monkeypatch):
    u, i, r = _synthetic(rng, n_users=30, n_items=20)
    k = 5
    uf0 = rng.normal(size=(30, k)).astype(np.float32)
    itf0 = rng.normal(size=(20, k)).astype(np.float32)
    cfg = A.ALSConfig(num_factors=k, iterations=2, lambda_=0.1)
    mesh = make_mesh(1)
    base = A.als_fit(u, i, r, cfg, mesh, init=(uf0, itf0))
    monkeypatch.setenv("FLINK_MS_ALS_SOLVER", "panel")
    panel = A.als_fit(u, i, r, cfg, mesh, init=(uf0, itf0))
    np.testing.assert_allclose(
        panel.user_factors, base.user_factors, rtol=1e-3, atol=1e-5
    )
    np.testing.assert_allclose(
        panel.item_factors, base.item_factors, rtol=1e-3, atol=1e-5
    )


@pytest.mark.parametrize("weighted", [True, False])
def test_one_iteration_matches_numpy(rng, weighted):
    u, i, r = _synthetic(rng, n_users=15, n_items=11)
    k, lam = 4, 0.3
    uf0 = rng.normal(size=(15, k)).astype(np.float32)
    itf0 = rng.normal(size=(11, k)).astype(np.float32)
    mesh = make_mesh(1)
    cfg = A.ALSConfig(num_factors=k, iterations=1, lambda_=lam, weighted_reg=weighted)
    model = A.als_fit(u, i, r, cfg, mesh, init=(uf0, itf0))

    uf_expect = _numpy_user_halfsweep(u, i, r, itf0, k, lam, weighted)
    np.testing.assert_allclose(model.user_factors, uf_expect, rtol=2e-3, atol=2e-4)
    itf_expect = _numpy_user_halfsweep(i, u, r, uf_expect, k, lam, weighted)
    np.testing.assert_allclose(model.item_factors, itf_expect, rtol=2e-3, atol=2e-4)


def test_multiblock_equals_singleblock(rng):
    u, i, r = _synthetic(rng, n_users=50, n_items=37)
    k = 5
    uf0 = rng.normal(size=(50, k)).astype(np.float32)
    itf0 = rng.normal(size=(37, k)).astype(np.float32)
    cfg = A.ALSConfig(num_factors=k, iterations=3, lambda_=0.1)
    m1 = A.als_fit(u, i, r, cfg, make_mesh(1), init=(uf0, itf0))
    m8 = A.als_fit(u, i, r, cfg, make_mesh(8), init=(uf0, itf0))
    np.testing.assert_allclose(
        m1.user_factors, m8.user_factors, rtol=5e-2, atol=5e-3
    )
    np.testing.assert_allclose(
        m1.item_factors, m8.item_factors, rtol=5e-2, atol=5e-3
    )


def test_dense_ids_matches_unique(rng):
    """Bitmap fast path == np.unique on every id regime it claims."""
    for arr in (
        rng.integers(0, 50, 500),                      # dense small ints
        rng.integers(0, 10**6, 300),                   # sparse, under the
        #                            1<<20 bitmap floor: still fast path
        np.array([5, 5_000_000, 5, 7]),                # huge gap (fallback:
        #                            mx > max(4n, 1<<20))
        np.array([-3, 7, 7, 0]),                       # negative (fallback)
        rng.uniform(0, 9, 100).round(1),               # floats (fallback)
    ):
        ids, inv = A._dense_ids(np.asarray(arr))
        ids_ref, inv_ref = np.unique(np.asarray(arr), return_inverse=True)
        np.testing.assert_array_equal(ids, ids_ref)
        np.testing.assert_array_equal(inv, inv_ref)


def test_chunked_assembly_matches_unchunked(rng, monkeypatch):
    """A tiny FLINK_MS_ALS_ASSEMBLY_CHUNK_BYTES forces the lax.map chunked
    path; factors must match the single-shot assembly (same math on the
    same rows — tolerance only covers codegen-level rounding)."""
    u, i, r = _synthetic(rng, n_users=30, n_items=20)
    k = 4
    uf0 = rng.normal(size=(30, k)).astype(np.float32)
    itf0 = rng.normal(size=(20, k)).astype(np.float32)
    cfg = A.ALSConfig(num_factors=k, iterations=2, lambda_=0.1)
    mesh = make_mesh(2)
    plain = A.als_fit(u, i, r, cfg, mesh, init=(uf0, itf0))
    monkeypatch.setenv("FLINK_MS_ALS_ASSEMBLY_CHUNK_BYTES", "512")
    chunked = A.als_fit(u, i, r, cfg, mesh, init=(uf0, itf0))
    np.testing.assert_allclose(
        chunked.user_factors, plain.user_factors, rtol=1e-3, atol=1e-5
    )
    np.testing.assert_allclose(
        chunked.item_factors, plain.item_factors, rtol=1e-3, atol=1e-5
    )


def test_fused_solve_matches_unfused(rng, monkeypatch):
    """FLINK_MS_ALS_FUSED=1 solves each bucket straight out of its
    assembly chunks (the (per_block, k, k) tensor never materializes);
    multi-block factors must match the unfused path — chunking is over
    the batch row axis only, so the per-row arithmetic is identical."""
    u, i, r = _synthetic(rng, n_users=60, n_items=45, k_true=3, noise=0.05)
    k = 5
    uf0 = rng.normal(size=(60, k)).astype(np.float32)
    itf0 = rng.normal(size=(45, k)).astype(np.float32)
    cfg = A.ALSConfig(num_factors=k, iterations=3, lambda_=0.1)
    mesh = make_mesh()
    # same pin as the implicit sibling below: the fused-solve path can't
    # use the pallas assembly, so an ambient FLINK_MS_ALS_ASSEMBLY=pallas
    # would make this a cross-engine comparison
    monkeypatch.setenv("FLINK_MS_ALS_ASSEMBLY", "xla")
    plain = A.als_fit(u, i, r, cfg, mesh, init=(uf0, itf0))
    monkeypatch.setenv("FLINK_MS_ALS_FUSED", "1")
    fused = A.als_fit(u, i, r, cfg, mesh, init=(uf0, itf0))
    np.testing.assert_allclose(
        fused.user_factors, plain.user_factors, rtol=1e-4, atol=1e-6
    )
    np.testing.assert_allclose(
        fused.item_factors, plain.item_factors, rtol=1e-4, atol=1e-6
    )
    # fused + forced lax.map chunking (the scale-envelope configuration)
    monkeypatch.setenv("FLINK_MS_ALS_ASSEMBLY_CHUNK_BYTES", "2048")
    fused_c = A.als_fit(u, i, r, cfg, mesh, init=(uf0, itf0))
    np.testing.assert_allclose(
        fused_c.user_factors, plain.user_factors, rtol=1e-4, atol=1e-6
    )
    # fused + chunked + the pallas solver (interpret off-TPU): the exact
    # combination whose lane-major relayout OOM'd on chip — the scan body
    # must trace the solve at the full chunk batch (batch-major layout),
    # not per padded row
    monkeypatch.setenv("FLINK_MS_ALS_SOLVER", "pallas")
    fused_cp = A.als_fit(u, i, r, cfg, mesh, init=(uf0, itf0))
    monkeypatch.delenv("FLINK_MS_ALS_SOLVER")
    np.testing.assert_allclose(
        fused_cp.user_factors, plain.user_factors, rtol=1e-4, atol=1e-6
    )
    # fused composes with the bf16 exchange dtype: same answer as the
    # UNFUSED bf16 run (bf16 vs f32 convergence itself is pinned in
    # test_bf16_exchange_converges_close_to_f32)
    monkeypatch.delenv("FLINK_MS_ALS_ASSEMBLY_CHUNK_BYTES")
    cfg_bf = A.ALSConfig(num_factors=k, iterations=3, lambda_=0.1,
                         exchange_dtype="bfloat16")
    fused_bf = A.als_fit(u, i, r, cfg_bf, mesh, init=(uf0, itf0))
    monkeypatch.delenv("FLINK_MS_ALS_FUSED")
    plain_bf = A.als_fit(u, i, r, cfg_bf, mesh, init=(uf0, itf0))
    np.testing.assert_allclose(
        fused_bf.user_factors, plain_bf.user_factors, rtol=1e-4, atol=1e-6
    )


def test_fused_solve_matches_unfused_implicit(rng, monkeypatch):
    """Fused mode in implicit/HKV mode: the psum'd Gramian is added per
    chunk instead of to the materialized tensor — same factors."""
    u, i, r = _synthetic(rng, n_users=40, n_items=30, k_true=3)
    r = np.abs(r)  # implicit confidence weights are nonnegative counts
    k = 4
    uf0 = rng.normal(size=(40, k)).astype(np.float32)
    itf0 = rng.normal(size=(30, k)).astype(np.float32)
    cfg = A.ALSConfig(num_factors=k, iterations=2, lambda_=0.1,
                      implicit=True, alpha=10.0)
    mesh = make_mesh(4)
    # pin one assembly engine for BOTH sides: the fused-solve path cannot
    # route through the pallas assembly (post-stage), so an ambient
    # FLINK_MS_ALS_ASSEMBLY=pallas would turn this tight fused-vs-unfused
    # comparison into a cross-engine one (reassociated arithmetic)
    monkeypatch.setenv("FLINK_MS_ALS_ASSEMBLY", "xla")
    plain = A.als_fit(u, i, r, cfg, mesh, init=(uf0, itf0))
    monkeypatch.setenv("FLINK_MS_ALS_FUSED", "1")
    fused = A.als_fit(u, i, r, cfg, mesh, init=(uf0, itf0))
    # fp32 tolerance: the fused implicit path accumulates the psum'd
    # Gramian in a different association order, and the exact rounding
    # depends on which compiled variants already sit in the jit cache —
    # at 1e-4/1e-6 this comparison is order-of-tests sensitive (a few
    # elements land near 5e-6 abs / 3e-4 rel in a full-module run)
    np.testing.assert_allclose(
        fused.user_factors, plain.user_factors, rtol=1e-3, atol=1e-5
    )
    np.testing.assert_allclose(
        fused.item_factors, plain.item_factors, rtol=1e-3, atol=1e-5
    )


def test_skewed_degrees_match_numpy(rng):
    """Power-law degree distribution (one super-popular item, many
    degree-1 users — the ML-20M shape) must bucket correctly: one
    iteration still matches the per-row normal-equation spec."""
    n_users, n_items, k, lam = 60, 10, 3, 0.2
    # item 0 is in every user's list; other items are rare; several users
    # rate exactly one item (narrowest bucket, heavy pad)
    u_list, i_list = [], []
    for uu in range(n_users):
        u_list.append(uu)
        i_list.append(0)
        if uu % 3 == 0:  # two-thirds of users are degree-1
            for extra in range(1 + uu % 7):
                u_list.append(uu)
                i_list.append(1 + (uu + extra) % (n_items - 1))
    u = np.array(u_list)
    i = np.array(i_list)
    r = rng.uniform(1, 5, len(u))
    uf0 = rng.normal(size=(n_users, k)).astype(np.float32)
    itf0 = rng.normal(size=(n_items, k)).astype(np.float32)
    for blocks in (1, 4):
        cfg = A.ALSConfig(num_factors=k, iterations=1, lambda_=lam,
                          weighted_reg=True)
        model = A.als_fit(u, i, r, cfg, make_mesh(blocks), init=(uf0, itf0))
        uf_expect = _numpy_user_halfsweep(u, i, r, itf0, k, lam, True)
        np.testing.assert_allclose(
            model.user_factors, uf_expect, rtol=2e-3, atol=2e-4
        )
        itf_expect = _numpy_user_halfsweep(i, u, r, uf_expect, k, lam, True)
        np.testing.assert_allclose(
            model.item_factors, itf_expect, rtol=2e-3, atol=2e-4
        )


def test_blocks_exceed_devices_runs_and_converges(rng):
    """--blocks > devices (legal in the reference: more blocks than slots,
    ALSImpl.scala:39-41): for ALS the solve is row-exact, so the logical
    block count is a parallelism hint only — mesh_for_blocks spans all
    devices and training must run and converge."""
    from flink_ms_tpu.parallel.mesh import mesh_for_blocks

    u, i, r = _synthetic(rng, n_users=50, n_items=37)
    mesh16 = mesh_for_blocks(16)  # 16 logical blocks on the 8-device mesh
    assert mesh16.devices.size == 8
    cfg = A.ALSConfig(num_factors=5, iterations=6, lambda_=1e-3,
                      weighted_reg=False)
    model = A.als_fit(u, i, r, cfg, mesh16)
    assert A.rmse(model, u, i, r) < 0.05


def test_recovers_low_rank_matrix(rng):
    u, i, r = _synthetic(rng, n_users=60, n_items=45, k_true=3, frac=0.5)
    cfg = A.ALSConfig(num_factors=6, iterations=12, lambda_=1e-3, weighted_reg=False)
    model = A.als_fit(u, i, r, cfg, make_mesh(8))
    assert A.rmse(model, u, i, r) < 0.05


def test_ids_are_preserved_not_dense(rng):
    # raw ids with gaps and large values must round-trip
    u = np.array([5, 1000000, 5, 7])
    i = np.array([3, 3, 900, 900])
    r = np.array([1.0, 2.0, 3.0, 4.0])
    model = A.als_fit(u, i, r, A.ALSConfig(num_factors=2, iterations=2), make_mesh(2))
    assert list(model.user_ids) == [5, 7, 1000000]
    assert list(model.item_ids) == [3, 900]
    assert model.user_factors.shape == (3, 2)


def test_predict_unknown_ids_zero(rng):
    u, i, r = _synthetic(rng, n_users=10, n_items=8)
    model = A.als_fit(u, i, r, A.ALSConfig(num_factors=3, iterations=2), make_mesh(1))
    p = A.predict(model, np.array([0, 9999]), np.array([0, 0]))
    assert p[1] == 0.0
    assert p[0] != 0.0


def test_implicit_mode_ranks_observed_higher(rng):
    # implicit: observed (u,i) pairs should score above unobserved on average
    n_users, n_items = 30, 20
    u, i, _ = _synthetic(rng, n_users=n_users, n_items=n_items, frac=0.3)
    r = np.ones_like(u, dtype=np.float64)  # binary implicit feedback
    cfg = A.ALSConfig(
        num_factors=8, iterations=8, lambda_=0.1, implicit=True, alpha=40.0
    )
    model = A.als_fit(u, i, r, cfg, make_mesh(4))
    obs = set(zip(u.tolist(), i.tolist()))
    all_u, all_i = np.meshgrid(model.user_ids, model.item_ids, indexing="ij")
    scores = A.predict(model, all_u.ravel(), all_i.ravel())
    is_obs = np.array([(a, b) in obs for a, b in zip(all_u.ravel(), all_i.ravel())])
    assert scores[is_obs].mean() > scores[~is_obs].mean() + 0.2


def test_more_iterations_do_not_diverge(rng):
    u, i, r = _synthetic(rng, n_users=40, n_items=30, noise=0.1)
    cfg3 = A.ALSConfig(num_factors=4, iterations=3, lambda_=0.05)
    cfg10 = A.ALSConfig(num_factors=4, iterations=10, lambda_=0.05)
    mesh = make_mesh(2)
    uf0 = np.random.default_rng(1).normal(size=(40, 4)).astype(np.float32)
    itf0 = np.random.default_rng(2).normal(size=(30, 4)).astype(np.float32)
    r3 = A.rmse(A.als_fit(u, i, r, cfg3, mesh, init=(uf0, itf0)), u, i, r)
    r10 = A.rmse(A.als_fit(u, i, r, cfg10, mesh, init=(uf0, itf0)), u, i, r)
    assert r10 <= r3 + 1e-3


def test_multiblock_equals_singleblock_implicit(rng):
    # regression: pad factor rows must not pollute the psum'd Gramian
    u, i, _ = _synthetic(rng, n_users=21, n_items=11, frac=0.4)
    r = np.ones_like(u, dtype=np.float64)
    k = 4
    uf0 = rng.normal(size=(len(set(u.tolist())), k)).astype(np.float32)
    itf0 = rng.normal(size=(len(set(i.tolist())), k)).astype(np.float32)
    cfg = A.ALSConfig(num_factors=k, iterations=2, lambda_=0.1, implicit=True)
    m1 = A.als_fit(u, i, r, cfg, make_mesh(1), init=(uf0, itf0))
    m8 = A.als_fit(u, i, r, cfg, make_mesh(8), init=(uf0, itf0))
    np.testing.assert_allclose(m1.user_factors, m8.user_factors, rtol=5e-2, atol=5e-3)


def test_default_init_pad_rows_zeroed(rng):
    # implicit mode, default init, tiny item count on a wide mesh: result
    # must match a run whose pad rows are explicitly zero
    u, i, _ = _synthetic(rng, n_users=9, n_items=11, frac=0.6)
    r = np.ones_like(u, dtype=np.float64)
    cfg = A.ALSConfig(num_factors=3, iterations=1, lambda_=0.1, implicit=True)
    mesh = make_mesh(4)
    m_default = A.als_fit(u, i, r, cfg, mesh)
    # reconstruct the same init matrices (first n rows of the padded init)
    import jax
    import jax.numpy as jnp

    p = A.prepare_blocked(u, i, r, 4)
    key_u, key_i = jax.random.split(jax.random.PRNGKey(cfg.seed))
    uf0 = np.asarray(A.init_factors(p.users_per_block * 4, 3, key_u, jnp.float32))
    itf0 = np.asarray(A.init_factors(p.items_per_block * 4, 3, key_i, jnp.float32))
    m_pinned = A.als_fit(
        u, i, r, cfg, mesh, init=(uf0[: p.n_users], itf0[: p.n_items])
    )
    np.testing.assert_allclose(
        m_default.user_factors, m_pinned.user_factors, rtol=1e-4, atol=1e-5
    )


def test_staged_fit_matches_fused(rng, tmp_path):
    """--temporaryPath semantics: per-iteration staging produces the same
    factors as the fused loop, and snapshots land at every boundary."""
    u, i, r = _synthetic(rng)
    mesh = make_mesh(2)
    cfg = A.ALSConfig(num_factors=4, iterations=3, lambda_=0.1)
    k = cfg.num_factors
    init = (
        rng.normal(size=(int(u.max()) + 1, k)).astype(np.float32),
        rng.normal(size=(int(i.max()) + 1, k)).astype(np.float32),
    )
    fused = A.als_fit(u, i, r, cfg, mesh, init=init)
    staged_dir = str(tmp_path / "stage")
    staged = A.als_fit(u, i, r, cfg, mesh, init=init,
                       temporary_path=staged_dir)
    np.testing.assert_allclose(
        staged.user_factors, fused.user_factors, rtol=2e-4, atol=2e-5
    )
    import os

    # superseded snapshots are pruned; the newest two remain
    snaps = sorted(n for n in os.listdir(staged_dir) if n.endswith(".npz"))
    assert snaps == ["iter_00002.npz", "iter_00003.npz"]


def test_staged_prunes_orphan_tmp_and_times_steps(rng, tmp_path):
    """A mid-write kill leaves iter_*.npz.tmp orphans; the next staged run
    must clean them up.  A passed StepTimer records one entry per staged
    iteration."""
    import os

    from flink_ms_tpu.utils.profiling import StepTimer

    u, i, r = _synthetic(rng)
    mesh = make_mesh(1)
    staged_dir = tmp_path / "stage"
    staged_dir.mkdir()
    (staged_dir / "iter_00009.npz.tmp").write_bytes(b"partial")
    cfg = A.ALSConfig(num_factors=3, iterations=2, lambda_=0.1)
    timer = StepTimer("als-iteration")
    A.als_fit(u, i, r, cfg, mesh, temporary_path=str(staged_dir),
              step_timer=timer)
    names = os.listdir(staged_dir)
    assert not any(n.endswith(".tmp") for n in names)
    assert len(timer.durations_s) == 2


def test_staged_rerun_with_fewer_iterations_not_overtrained(rng, tmp_path):
    """Re-running with a smaller --iterations must not return the later
    (over-trained) snapshot from a previous longer run."""
    u, i, r = _synthetic(rng)
    mesh = make_mesh(1)
    k = 3
    init = (
        rng.normal(size=(int(u.max()) + 1, k)).astype(np.float32),
        rng.normal(size=(int(i.max()) + 1, k)).astype(np.float32),
    )
    staged_dir = str(tmp_path / "stage")
    cfg5 = A.ALSConfig(num_factors=k, iterations=5, lambda_=0.1)
    cfg2 = A.ALSConfig(num_factors=k, iterations=2, lambda_=0.1)
    A.als_fit(u, i, r, cfg5, mesh, init=init, temporary_path=staged_dir)
    short = A.als_fit(u, i, r, cfg2, mesh, init=init,
                      temporary_path=staged_dir)
    plain2 = A.als_fit(u, i, r, cfg2, mesh, init=init)
    np.testing.assert_allclose(
        short.user_factors, plain2.user_factors, rtol=2e-4, atol=2e-5
    )


def test_staged_fit_resumes_from_snapshot(rng, tmp_path):
    """Killing training mid-run and re-running picks up from the latest
    snapshot instead of starting over."""
    u, i, r = _synthetic(rng)
    mesh = make_mesh(2)
    k = 4
    init = (
        rng.normal(size=(int(u.max()) + 1, k)).astype(np.float32),
        rng.normal(size=(int(i.max()) + 1, k)).astype(np.float32),
    )
    staged_dir = str(tmp_path / "stage")
    cfg2 = A.ALSConfig(num_factors=k, iterations=2, lambda_=0.1)
    cfg5 = A.ALSConfig(num_factors=k, iterations=5, lambda_=0.1)
    # run 2 of 5 iterations, "crash", then run the full 5: identical problem
    # and config identity except iterations, so the resume must kick in
    A.als_fit(u, i, r, cfg2, mesh, init=init, temporary_path=staged_dir)
    resumed = A.als_fit(u, i, r, cfg5, mesh, init=init,
                        temporary_path=staged_dir)
    full = A.als_fit(u, i, r, cfg5, mesh, init=init)
    np.testing.assert_allclose(
        resumed.user_factors, full.user_factors, rtol=2e-4, atol=2e-5
    )


def test_staged_mismatched_snapshot_ignored(rng, tmp_path):
    """A snapshot from a different config (lambda changed) must not resume."""
    u, i, r = _synthetic(rng)
    mesh = make_mesh(1)
    staged_dir = str(tmp_path / "stage")
    cfg_a = A.ALSConfig(num_factors=3, iterations=1, lambda_=0.5)
    cfg_b = A.ALSConfig(num_factors=3, iterations=1, lambda_=0.01)
    A.als_fit(u, i, r, cfg_a, mesh, temporary_path=staged_dir)
    fresh = A.als_fit(u, i, r, cfg_b, mesh, temporary_path=staged_dir)
    plain = A.als_fit(u, i, r, cfg_b, mesh)
    np.testing.assert_allclose(
        fresh.user_factors, plain.user_factors, rtol=2e-4, atol=2e-5
    )

def test_bucket_ladder_bounds_padding(rng, monkeypatch):
    """The geometric width ladder bounds per-list padding by ~ratio: every
    entity lands in the smallest rung >= its degree, and rungs are 8-round
    so the worst-case pad is ratio * degree + 8."""
    import os
    u = np.repeat(np.arange(200), rng.integers(1, 300, 200))
    i = rng.integers(0, 50, len(u))
    r = rng.uniform(1, 5, len(u)).astype(np.float64)
    for ratio in ("1.5", "2.0"):
        monkeypatch.setenv("FLINK_MS_ALS_BUCKET_RATIO", ratio)
        p = A.prepare_blocked(u, i, r, 2)
        deg = np.bincount(u, minlength=200)
        widths = np.asarray(p.u.widths)
        for uu in range(200):
            slot = p.u.perm[uu]
            # find the bucket whose slot range holds this entity
            block = slot // p.u.per_block
            local = slot - block * p.u.per_block
            offsets = np.concatenate([[0], np.cumsum(p.u.rows)])
            j = int(np.searchsorted(offsets, local, side="right") - 1)
            w = widths[j]
            assert w >= deg[uu]
            assert w <= float(ratio) * max(deg[uu], 8) + 8, (w, deg[uu])

def test_implicit_halfsweep_matches_numpy_hkv(rng):
    """One implicit iteration vs the dense Hu-Koren-Volinsky spec:
    x_u = (YtY + sum a*r*y y^T + lam I)^-1 sum (1+a*r) y, YtY over the
    WHOLE catalog."""
    u, i, r = _synthetic(rng, n_users=14, n_items=10)
    r = np.abs(r) + 0.5  # implicit confidences must be positive
    k, lam, alpha = 4, 0.3, 3.0
    uf0 = rng.normal(size=(14, k)).astype(np.float32)
    itf0 = rng.normal(size=(10, k)).astype(np.float32)
    cfg = A.ALSConfig(num_factors=k, iterations=1, lambda_=lam,
                      implicit=True, alpha=alpha)
    model = A.als_fit(u, i, r, cfg, make_mesh(1), init=(uf0, itf0))

    def hkv_halfsweep(row, col, rr, Y, n_rows):
        YtY = Y.T @ Y
        out = np.zeros((n_rows, k))
        for e in range(n_rows):
            sel = row == e
            Ys = Y[col[sel]]
            cw = alpha * rr[sel]
            Amat = YtY + (Ys * cw[:, None]).T @ Ys + lam * np.eye(k)
            b = ((1.0 + alpha * rr[sel])[:, None] * Ys).sum(axis=0)
            out[e] = np.linalg.solve(Amat, b)
        return out

    uf_expect = hkv_halfsweep(u, i, r, itf0.astype(np.float64), 14)
    np.testing.assert_allclose(model.user_factors, uf_expect,
                               rtol=2e-3, atol=2e-4)
    itf_expect = hkv_halfsweep(i, u, r, uf_expect, 10)
    np.testing.assert_allclose(model.item_factors, itf_expect,
                               rtol=2e-3, atol=2e-4)

def test_bench_default_config_matches_f64_reference_rmse(rng):
    """VERDICT r3 #3 pinning test: the ALS config the benchmark times (all
    shipped solver/precision/exchange defaults) must reach the same train
    RMSE as an exact float64 normal-equation solve at equal iterations from
    the same init — the 'identical RMSE' half of the north star."""
    u, i, r = _synthetic(rng, n_users=50, n_items=40, k_true=4, noise=0.1)
    k, lam, iters = 6, 0.1, 4
    n_u, n_i = int(u.max()) + 1, int(i.max()) + 1
    # init is passed in dense-id order; with this seed every id occurs
    assert len(np.unique(u)) == n_u and len(np.unique(i)) == n_i
    rng2 = np.random.default_rng(3)
    u0 = 0.1 * rng2.standard_normal((n_u, k))
    i0 = 0.1 * rng2.standard_normal((n_i, k))

    uf, itf = u0.copy(), i0.copy()
    for _ in range(iters):
        uf = _numpy_user_halfsweep(u, i, r, itf, k, lam, True)
        itf = _numpy_user_halfsweep(i, u, r, uf, k, lam, True)
    pred = np.sum(uf[u] * itf[i], axis=1)
    rmse_ref = float(np.sqrt(np.mean((r - pred) ** 2)))

    mesh = make_mesh()
    cfg = A.ALSConfig(num_factors=k, iterations=iters, lambda_=lam, seed=42)
    model = A.als_fit(u, i, r, cfg, mesh, init=(u0, i0))
    rmse_bench = A.rmse(model, u, i, r)
    assert abs(rmse_bench - rmse_ref) / rmse_ref < 5e-3, (
        rmse_bench, rmse_ref)


def test_bf16_exchange_converges_close_to_f32(rng):
    """exchange_dtype=bfloat16 (half the all_gather + gather bytes) must
    train to nearly the same factors as full-precision exchange."""
    u, i, r = _synthetic(rng, n_users=40, n_items=30)
    k = 5
    uf0 = rng.normal(size=(40, k)).astype(np.float32)
    itf0 = rng.normal(size=(30, k)).astype(np.float32)
    full = A.als_fit(u, i, r, A.ALSConfig(num_factors=k, iterations=3,
                                          lambda_=0.1),
                     make_mesh(2), init=(uf0, itf0))
    bf16 = A.als_fit(u, i, r, A.ALSConfig(num_factors=k, iterations=3,
                                          lambda_=0.1,
                                          exchange_dtype="bfloat16"),
                     make_mesh(2), init=(uf0, itf0))
    # bf16 has ~3 decimal digits: same solution to ~1e-2 relative
    np.testing.assert_allclose(bf16.user_factors, full.user_factors,
                               rtol=5e-2, atol=5e-3)
    r_full = A.rmse(full, u, i, r)
    r_bf16 = A.rmse(bf16, u, i, r)
    assert abs(r_full - r_bf16) < 0.05


# ---------------------------------------------------------------------------
# warm start (round 13 — the autopilot's retrain path)
# ---------------------------------------------------------------------------

def test_warm_start_zero_iteration_parity(rng):
    """A zero-iteration warm-started fit returns the init verbatim — the
    override feeds the SAME init path the seed draw does, no extra
    transform between the caller's factors and the sweep."""
    u, i, r = _synthetic(rng, n_users=12, n_items=9)
    k = 4
    uf0 = rng.normal(size=(12, k)).astype(np.float32)
    itf0 = rng.normal(size=(9, k)).astype(np.float32)
    model = A.als_fit(
        u, i, r, A.ALSConfig(num_factors=k, iterations=0, lambda_=0.1),
        make_mesh(1), init_user_factors=uf0, init_item_factors=itf0)
    np.testing.assert_allclose(model.user_factors, uf0, rtol=1e-6)
    np.testing.assert_allclose(model.item_factors, itf0, rtol=1e-6)


def test_warm_start_kwargs_validation(rng):
    u, i, r = _synthetic(rng, n_users=12, n_items=9)
    k = 3
    uf0 = rng.normal(size=(12, k)).astype(np.float32)
    itf0 = rng.normal(size=(9, k)).astype(np.float32)
    cfg = A.ALSConfig(num_factors=k, iterations=1, lambda_=0.1)
    mesh = make_mesh(1)
    with pytest.raises(ValueError, match="together"):
        A.als_fit(u, i, r, cfg, mesh, init_user_factors=uf0)
    with pytest.raises(ValueError, match="mutually exclusive"):
        A.als_fit(u, i, r, cfg, mesh, init=(uf0, itf0),
                  init_user_factors=uf0, init_item_factors=itf0)
    with pytest.raises(ValueError, match="shapes"):
        A.als_fit(u, i, r, cfg, mesh,
                  init_user_factors=uf0[:5], init_item_factors=itf0)


def test_warm_start_factors_alignment(rng):
    """warm_start_factors carries known ids over verbatim and seeds novel
    ids from the deterministic cold draw."""
    k = 3
    prev_u = {0: np.full(k, 1.0), 2: np.full(k, 2.0)}
    prev_i = {5: np.full(k, 3.0)}
    user_ids = np.asarray([0, 1, 2])
    item_ids = np.asarray([4, 5])
    uf, itf = A.warm_start_factors(user_ids, item_ids, prev_u, prev_i, k,
                                   seed=7)
    np.testing.assert_allclose(uf[0], 1.0)
    np.testing.assert_allclose(uf[2], 2.0)
    np.testing.assert_allclose(itf[1], 3.0)
    # novel rows come from the seed draw, not zeros (a zero row is a
    # stationary point of the opposite half-sweep)
    assert np.abs(uf[1]).max() > 0
    assert np.abs(itf[0]).max() > 0
    # deterministic in (ids, seed)
    uf2, itf2 = A.warm_start_factors(user_ids, item_ids, prev_u, prev_i,
                                     k, seed=7)
    np.testing.assert_array_equal(uf, uf2)
    np.testing.assert_array_equal(itf, itf2)
    # rank-mismatched carryover rows are ignored, not truncated
    uf3, _ = A.warm_start_factors(
        user_ids, item_ids, {0: np.ones(k + 2)}, prev_i, k, seed=7)
    assert np.abs(uf3[0] - 1.0).max() > 0


def test_warm_start_converges_faster_than_cold(rng):
    """Warm-starting from a near-optimum beats the cold seed init at equal
    iteration count on incrementally grown data — the autopilot's whole
    reason to thread serving factors back into the trainer."""
    u, i, r = _synthetic(rng, n_users=40, n_items=30, k_true=3)
    k = 3
    lam = 0.1
    mesh = make_mesh(1)
    # near-optimum on the first 80% of ratings
    n_seed = int(0.8 * len(r))
    opt = A.als_fit(u[:n_seed], i[:n_seed], r[:n_seed],
                    A.ALSConfig(num_factors=k, iterations=12, lambda_=lam),
                    make_mesh(1))
    prev_u = {int(uu): f for uu, f in zip(opt.user_ids, opt.user_factors)}
    prev_i = {int(ii): f for ii, f in zip(opt.item_ids, opt.item_factors)}
    uf0, itf0 = A.warm_start_factors(
        np.unique(u), np.unique(i), prev_u, prev_i, k, seed=42)
    cfg = A.ALSConfig(num_factors=k, iterations=1, lambda_=lam, seed=42)
    warm = A.als_fit(u, i, r, cfg, mesh,
                     init_user_factors=uf0, init_item_factors=itf0)
    cold = A.als_fit(u, i, r, cfg, mesh)
    assert A.rmse(warm, u, i, r) < A.rmse(cold, u, i, r)
