"""Vectorized ingest plane (ISSUE 2): the columnar chunk parser must be
byte-identical to the per-line scalar path — same keys, same values, same
parse-error counts, same shard routing — and the batched change-notification
path must leave the top-k index in the same state the per-key path would."""

import time

import numpy as np
import pytest

from flink_ms_tpu.core import formats as F
from flink_ms_tpu.core.formats import (
    CHUNK_ALS,
    CHUNK_SVM,
    split_journal_chunk,
)
from flink_ms_tpu.serve.consumer import (
    ALS_STATE,
    MemoryStateBackend,
    ServingJob,
    parse_als_record,
    parse_svm_record,
)
from flink_ms_tpu.serve.journal import Journal
from flink_ms_tpu.serve.sharded import sharded_parse
from flink_ms_tpu.serve.table import ModelTable, _fnv1a


def _wait_until(pred, timeout=30.0, interval=0.02):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return True
        time.sleep(interval)
    return False


def _scalar_reference(data: bytes, parse_fn):
    """The pre-columnar semantics, verbatim: decode + splitlines + per-line
    parse, empty lines skipped, ValueError -> skip-and-count."""
    pairs, errors = [], 0
    for line in data.decode("utf-8").splitlines():
        if not line:
            continue
        try:
            pairs.append(parse_fn(line))
        except ValueError:
            errors += 1
    return pairs, errors


def _assert_chunk_parity(data: bytes, mode: int, parse_fn):
    keys, values, errs = split_journal_chunk(data, mode)
    ref_pairs, ref_errs = _scalar_reference(data, parse_fn)
    assert list(zip(keys, values)) == ref_pairs, data
    assert errs == ref_errs, data
    k2, v2, e2, hashes = split_journal_chunk(data, mode, with_hashes=True)
    assert (k2, v2, e2) == (keys, values, errs)
    if hashes is None:
        hashes = np.array([], np.uint32) if not keys else None
    assert hashes is not None, "hash fast path must cover normal keys"
    assert [int(h) for h in hashes] == [_fnv1a(k) for k in keys], data


# -- columnar chunk parser: unit parity -------------------------------------

ALS_CASES = [
    b"",
    b"\n",
    b"\n\n\n",
    b"1,U,0.5;1.5\n2,I,2.5;3.5\n",
    b"1,U,0.5;1.5\r\n2,I,2.5;3.5\r\n",          # CRLF
    b"garbage\n1,U,0.5\nalso,bad\n",            # <2 commas -> skip+count
    b"nocommas\n\nstill none\n",                # all-error chunk
    b"1,U,a,b,c\n",                             # payload keeps its commas
    b"\xc3\xa9,U,0.5\n\xe6\x97\xa5,I,1;2\n",    # unicode ids
    b"1,U,\n2,I,x\n",                           # empty / odd payloads
    b"9,I,0.25\n9,I,0.75\n",                    # last-writer-wins order
    b"1,U,0.5",                                 # no trailing newline
]

SVM_CASES = [
    b"",
    b"\n",
    b"f1,0.5;3\nf2,1.5;7\n",
    b"lonely\nf1,0.5\nalso-lonely\n",           # comma-less -> (line, "")
    b"lonely\r\nf1,0.5\r\n",                    # CRLF + loner
    b"a,1\n\nb,2\n\nloner\n",                   # order across loners
    b"\xc3\xa9,0.5\nno-comma-\xe6\x97\xa5\n",   # unicode loner
    b"k,v,w,x\n",                               # payload keeps its commas
    b"f1,0.5",                                  # no trailing newline
]


@pytest.mark.parametrize("data", ALS_CASES)
def test_columnar_als_parity(data):
    _assert_chunk_parity(data, CHUNK_ALS, parse_als_record)


@pytest.mark.parametrize("data", SVM_CASES)
def test_columnar_svm_parity(data):
    _assert_chunk_parity(data, CHUNK_SVM, parse_svm_record)


def test_columnar_fuzz_parity():
    """Random chunks over a hostile alphabet (separators, unicode, empty
    fields) must match the scalar reference row for row in both modes."""
    rng = np.random.default_rng(7)
    alphabet = ["a", "1", ",", ";", "-", "é", "日", ""]
    for trial in range(60):
        lines = []
        for _ in range(int(rng.integers(0, 12))):
            lines.append("".join(
                alphabet[int(i)]
                for i in rng.integers(0, len(alphabet), rng.integers(0, 9))
            ))
        sep = "\r\n" if trial % 3 == 0 else "\n"
        data = sep.join(lines).encode("utf-8")
        if trial % 2:
            data += sep.encode()
        _assert_chunk_parity(data, CHUNK_ALS, parse_als_record)
        _assert_chunk_parity(data, CHUNK_SVM, parse_svm_record)


def test_columnar_oversized_key_hash_falls_back():
    """Keys longer than the vectorized hasher's padded-width bound must
    still hash correctly (per-key fallback), not crash or go quiet."""
    big = "x" * 400
    data = f"{big},U,1.0\n".encode()
    keys, values, errs, hashes = split_journal_chunk(
        data, CHUNK_ALS, with_hashes=True)
    assert keys == [f"{big}-U"] and errs == 0
    if hashes is not None:  # None = caller recomputes; both are valid
        assert int(hashes[0]) == _fnv1a(keys[0])


# -- batched table writes ---------------------------------------------------

def test_put_many_columns_matches_per_key_put():
    rng = np.random.default_rng(3)
    keys = [f"{int(i)}-I" for i in rng.integers(0, 200, 500)]  # dup-heavy
    values = [f"{float(v):.3f}" for v in rng.random(500)]
    a, b = ModelTable(8), ModelTable(8)
    seen_a, seen_b = [], []
    a.add_change_listener(seen_a.append)
    b.add_change_listener(seen_b.append, lambda ks: seen_b.extend(ks))
    for k, v in zip(keys, values):
        a.put(k, v)
    b.put_many_columns(keys, values)
    assert a._shards == b._shards  # byte-identical incl. last-writer-wins
    assert seen_a == seen_b == keys
    assert a.puts == b.puts == 500
    # precomputed hashes route identically
    c = ModelTable(8)
    c.put_many_columns(
        keys, values,
        hashes=np.array([_fnv1a(k) for k in keys], np.uint32))
    assert c._shards == a._shards


# -- end-to-end: columnar vs scalar ServingJob ------------------------------

def _mixed_journal(tmp_path, n=3000):
    j = Journal(str(tmp_path / "bus"), "models")
    rng = np.random.default_rng(11)
    rows, bad = [], 0
    for i in range(n):
        r = int(rng.integers(0, 20))
        if r == 0:
            rows.append("malformed-no-commas")
            bad += 1
        elif r == 1:
            rows.append(f"{i},onlyone")
            bad += 1
        else:
            rows.append(F.format_als_row(
                i % (n // 3), "I" if i % 2 else "U",
                rng.random(4) - 0.5))
    j.append(rows)
    return j, n, bad


@pytest.mark.parametrize("mode", ["columnar", "scalar"])
def test_serving_job_modes_reach_same_state(tmp_path, mode):
    journal, n, bad = _mixed_journal(tmp_path)
    job = ServingJob(
        journal, ALS_STATE, parse_als_record, MemoryStateBackend(),
        host="127.0.0.1", port=0, poll_interval_s=0.01,
        ingest_mode=mode, topk_index=False,
    ).start()
    try:
        assert _wait_until(lambda: job.parse_errors + job.ingest_rows >= n)
        stats = job.ingest_stats()
        assert stats["path"] == mode
        assert job.parse_errors == bad
        # the reference state, computed scalar-side
        expect = ModelTable(job.table.n_shards)
        with open(journal.path, "rb") as f:
            pairs, errs = _scalar_reference(f.read(), parse_als_record)
        for k, v in pairs:
            expect.put(k, v)
        assert errs == bad
        assert job.table._shards == expect._shards
    finally:
        job.stop()


def test_sharded_columnar_ownership_matches_scalar(tmp_path):
    """Vectorized ownership filtering (hash % W on the raw chunk) must give
    each worker exactly the slice the scalar shard filter would."""
    journal, n, bad = _mixed_journal(tmp_path, n=1200)
    slices = {}
    for mode in ("columnar", "scalar"):
        for w in range(2):
            job = ServingJob(
                journal, ALS_STATE, sharded_parse(parse_als_record, w, 2),
                MemoryStateBackend(), host="127.0.0.1", port=0,
                poll_interval_s=0.01, ingest_mode=mode, topk_index=False,
            ).start()
            try:
                assert _wait_until(
                    lambda: job.ingest_stats()["offset"]
                    >= journal.end_offset())
                assert job.ingest_stats()["path"] == mode
                # sharded fleet members default to the arena table now —
                # rebuild the per-shard view through the table contract
                # instead of reaching into dict-table internals
                t = job.table
                if hasattr(t, "_shards"):
                    slices[(mode, w)] = [dict(s) for s in t._shards]
                else:
                    shards = [dict() for _ in range(t.n_shards)]
                    for k, v in t.items():
                        shards[t.shard_of(k)][k] = v
                    slices[(mode, w)] = shards
            finally:
                job.stop()
    for w in range(2):
        assert slices[("columnar", w)] == slices[("scalar", w)]
    union = {}
    for w in range(2):
        for shard in slices[("columnar", w)]:
            assert not (set(shard) & set(union)), "owners must be disjoint"
            union.update(shard)
    with open(journal.path, "rb") as f:
        pairs, _ = _scalar_reference(f.read(), parse_als_record)
    assert union == dict(pairs)


# -- batched listener -> top-k index ----------------------------------------

def test_small_batch_keeps_exact_dirty_set():
    from flink_ms_tpu.serve.topk import DeviceFactorIndex

    table = ModelTable(4)
    index = DeviceFactorIndex(table, "-I")
    table.put_many_columns(
        ["1-I", "2-U", "MEAN-I", "3-I"],
        ["0.1", "0.2", "0.3", "0.4"])
    assert index._dirty == {"1-I", "3-I"}
    assert index._replay_backlog == 0


def test_bulk_replay_triggers_rebuild_and_correct_topk(monkeypatch):
    """A replay-scale batch through the columnar path must (a) not stall
    the writer on per-key dirty tracking, (b) be absorbed by ONE background
    rebuild, and (c) leave the index returning exactly the brute-force
    top-k."""
    monkeypatch.setenv("TPUMS_TOPK_APPLY_CAP", "2")  # rebuild_backlog=16
    from flink_ms_tpu.serve.topk import DeviceFactorIndex

    table = ModelTable(4)
    index = DeviceFactorIndex(table, "-I")
    k = 4
    rng = np.random.default_rng(5)
    seed = rng.random((4, k)) - 0.5
    for i, row in enumerate(seed):
        table.put(f"{i}-I", ";".join(f"{x:.6f}" for x in row))
    q = np.ones(k, np.float32)
    index.topk(q, 2)  # initial build
    builds0 = index.full_builds

    mat = rng.random((40, k)) - 0.5
    keys = [f"{100 + i}-I" for i in range(40)]
    values = [";".join(f"{x:.6f}" for x in row) for row in mat]
    table.put_many_columns(keys, values)
    assert index._replay_backlog >= 40  # counted, not stored
    assert len(index._dirty) == 0

    index.topk(q, 2)  # kicks the background rebuild
    t = index._rebuild_thread
    assert t is not None
    t.join(timeout=60)
    assert index.full_builds > builds0

    got = index.topk(q, 5)
    all_ids = [str(i) for i in range(4)] + [str(100 + i) for i in range(40)]
    all_rows = np.vstack([seed, mat])
    # parse exactly what the table stores — the index scores the stored
    # text, so the expectation must too
    stored = np.array([
        [float(tok) for tok in table.get(f"{i}-I").split(";")]
        for i in all_ids
    ], np.float32)
    assert stored.shape == all_rows.shape
    scores = stored @ q
    want = [all_ids[i] for i in np.argsort(-scores)[:5]]
    assert [gid for gid, _ in got] == want


# -- checkpoint deferral during replay backlog ------------------------------

def test_checkpoints_deferred_while_replaying(tmp_path, monkeypatch):
    monkeypatch.setattr(ServingJob, "CHUNK_CAP", 4096)
    journal = Journal(str(tmp_path / "bus"), "models")
    rows = [F.format_als_row(i, "I", [0.5] * 8) for i in range(2000)]
    journal.append(rows)  # ~100 KB >> 4 KB chunks: a real backlog
    backend = MemoryStateBackend()
    job = ServingJob(
        journal, ALS_STATE, parse_als_record, backend,
        host="127.0.0.1", port=0, poll_interval_s=0.01,
        checkpoint_interval_ms=1, topk_index=False,
    ).start()
    try:
        assert _wait_until(lambda: job.ingest_rows >= 2000)
        assert job.checkpoints_deferred >= 1
        # once drained, the wall-clock checkpoint goes through again
        assert _wait_until(lambda: backend._snap is not None)
        assert backend._snap[0] == journal.end_offset()
    finally:
        job.stop()


# -- mode selection ---------------------------------------------------------

def test_ingest_mode_validation_and_env(tmp_path, monkeypatch):
    journal = Journal(str(tmp_path / "bus"), "models")
    with pytest.raises(ValueError):
        ServingJob(
            journal, ALS_STATE, parse_als_record, MemoryStateBackend(),
            host="127.0.0.1", port=0, ingest_mode="bogus")
    monkeypatch.setenv("TPUMS_INGEST_MODE", "scalar")
    job = ServingJob(
        journal, ALS_STATE, parse_als_record, MemoryStateBackend(),
        host="127.0.0.1", port=0)
    assert job.ingest_mode == "scalar"
    explicit = ServingJob(
        journal, ALS_STATE, parse_als_record, MemoryStateBackend(),
        host="127.0.0.1", port=0, ingest_mode="columnar")
    assert explicit.ingest_mode == "columnar"
