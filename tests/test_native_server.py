"""C++ epoll lookup server (native/lookup_server.cpp): protocol parity with
the Python LookupServer, concurrency, and the ServingJob --nativeServer
integration (end-to-end journal -> native store -> C++ data plane)."""

import socket
import threading

import pytest

from flink_ms_tpu.serve.client import QueryClient
from flink_ms_tpu.serve.consumer import (
    ALS_STATE,
    ServingJob,
    make_backend,
    parse_als_record,
)
from flink_ms_tpu.serve.journal import Journal
from flink_ms_tpu.serve.native_store import NativeLookupServer, NativeStore
from flink_ms_tpu.serve.server import LookupServer
from flink_ms_tpu.serve.table import ModelTable


@pytest.fixture
def store(tmp_path):
    s = NativeStore(str(tmp_path / "store"))
    s.put("1-U", "0.5;1.5")
    s.put("2-I", "2.0;-1.0")
    yield s
    s.close()


@pytest.fixture
def server(store):
    with NativeLookupServer(store, ALS_STATE, job_id="jid", port=0) as srv:
        yield srv


def _raw(port: int, payload: bytes) -> bytes:
    with socket.create_connection(("127.0.0.1", port), timeout=5) as sock:
        sock.sendall(payload)
        sock.shutdown(socket.SHUT_WR)
        out = b""
        while True:
            chunk = sock.recv(65536)
            if not chunk:
                return out
            out += chunk


def test_get_ping_and_misses(server):
    with QueryClient("127.0.0.1", server.port) as c:
        assert c.query_state(ALS_STATE, "1-U") == "0.5;1.5"
        assert c.query_state(ALS_STATE, "2-I") == "2.0;-1.0"
        assert c.query_state(ALS_STATE, "999-U") is None
        assert c.count(ALS_STATE) == 2  # the fixture's two rows
        assert "jid" in c.ping()
        with pytest.raises(Exception):
            c.query_state("NO_SUCH_STATE", "1-U")
    assert server.requests >= 5


def test_protocol_matches_python_server(store):
    """Byte-for-byte response parity on every verb (the Python server is the
    semantics contract)."""
    table = ModelTable(2)
    for k, v in store.items():
        table.put(k, v)
    pysrv = LookupServer({ALS_STATE: table}, host="127.0.0.1", port=0,
                         job_id="jid").start()
    requests = (
        b"GET\tALS_MODEL\t1-U\n"
        b"GET\tALS_MODEL\tmissing\n"
        b"GET\tOTHER\tx\n"
        b"TOPK\tALS_MODEL\t1\t5\n"
        b"PING\n"
        b"PING\textra\tfields\n"
        b"NONSENSE\n"
        b"GET\ttoo\tmany\ttabs\n"
        b"GET\teven\tmore\ttabs\there\n"
        b"TOPK\ta\tb\tc\td\n"
        b"TOPK\tALS_MODEL\t1\n"
        b"MGET\tALS_MODEL\t1-U,missing,2-I\n"
        b"MGET\tALS_MODEL\t1-U\n"
        b"MGET\tALS_MODEL\t\n"
        b"MGET\tOTHER\t1-U\n"
        b"MGET\tALS_MODEL\ta\tb\n"
        b"COUNT\tALS_MODEL\n"
        b"COUNT\tOTHER\n"
        b"COUNT\tALS_MODEL\textra\n"
        b"\n"
    )
    try:
        with NativeLookupServer(store, ALS_STATE, job_id="jid",
                                port=0) as nsrv:
            assert _raw(nsrv.port, requests) == _raw(pysrv.port, requests)
    finally:
        pysrv.stop()


def test_pipelined_and_split_requests(server):
    # two requests in one segment, then one request dribbled byte-by-byte
    out = _raw(server.port, b"GET\tALS_MODEL\t1-U\nPING\n")
    assert out == b"V\t0.5;1.5\nPONG\tjid\tALS_MODEL\n"
    with socket.create_connection(("127.0.0.1", server.port), timeout=5) as s:
        for b in b"GET\tALS_MODEL\t2-I\n":
            s.sendall(bytes([b]))
        f = s.makefile("rb")
        assert f.readline() == b"V\t2.0;-1.0\n"


def test_final_line_without_newline_is_answered(server):
    # readline()-at-EOF parity: the Python server answers a trailing
    # partial line on half-close, so the native server must too
    assert _raw(server.port, b"PING") == b"PONG\tjid\tALS_MODEL\n"
    assert _raw(server.port, b"PING\nGET\tALS_MODEL\t1-U") == (
        b"PONG\tjid\tALS_MODEL\nV\t0.5;1.5\n"
    )


def test_large_pipelined_burst_is_answered(server):
    # >1 MB of small valid requests in one burst: the request-line cap must
    # bound a single line, not the whole unparsed buffer
    n = 80_000
    burst = b"GET\tALS_MODEL\t1-U\n" * n
    assert len(burst) > (1 << 20)
    out = _raw(server.port, burst)
    assert out == b"V\t0.5;1.5\n" * n


def test_slow_reader_is_disconnected(server):
    # a client that pipelines forever without reading responses must be
    # dropped once the buffered-response cap is hit, not OOM the server
    line = b"GET\tALS_MODEL\t1-U\n"
    with socket.create_connection(("127.0.0.1", server.port), timeout=10) as s:
        try:
            # 32 MB of requests -> ~18 MB of buffered responses > 16 MB cap
            for _ in range(2048):
                s.sendall(line * 1024)
        except (ConnectionResetError, BrokenPipeError):
            return  # server dropped us: expected
        # server may also close gracefully after we stop sending
        s.shutdown(socket.SHUT_WR)
        total = 0
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            total += len(chunk)
        assert total < (32 << 20)


def test_oversized_single_line_closes_connection(server):
    # the server drops the connection mid-send; depending on timing the
    # client sees a clean EOF with no payload, a reset, or a failed
    # shutdown on the already-closed socket (ENOTCONN)
    try:
        out = _raw(server.port, b"GET\tALS_MODEL\t" + b"x" * (2 << 20) + b"\n")
    except OSError:
        return
    assert out == b""


def test_concurrent_clients(server):
    errors = []

    def worker():
        try:
            with QueryClient("127.0.0.1", server.port) as c:
                for _ in range(50):
                    assert c.query_state(ALS_STATE, "1-U") == "0.5;1.5"
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    assert server.requests >= 400


def test_serving_job_native_server_end_to_end(tmp_path):
    journal = Journal(str(tmp_path / "journal"), "als-topic")
    journal.append(["1,U,0.5;1.5", "7,I,3.0;4.0"])
    backend = make_backend("rocksdb", str(tmp_path / "chk"))
    job = ServingJob(
        journal, ALS_STATE, parse_als_record, backend,
        port=0, poll_interval_s=0.05, checkpoint_interval_ms=100,
        native_server=True,
    ).start()
    try:
        with QueryClient("127.0.0.1", job.port) as c:
            deadline = 50
            while c.query_state(ALS_STATE, "7-I") is None and deadline:
                threading.Event().wait(0.1)
                deadline -= 1
            assert c.query_state(ALS_STATE, "1-U") == "0.5;1.5"
            assert c.query_state(ALS_STATE, "7-I") == "3.0;4.0"
            # TOPK is a Python-server feature; the native plane must say so
            with pytest.raises(Exception):
                c.topk(ALS_STATE, "1", 3)
    finally:
        job.stop()


def test_native_server_requires_native_backend(tmp_path):
    journal = Journal(str(tmp_path / "journal"), "t")
    with pytest.raises(ValueError, match="nativeServer"):
        ServingJob(journal, ALS_STATE, parse_als_record,
                   make_backend("memory", None), port=0, native_server=True)


def test_mget_batches_native(server):
    """MGET on the C++ server: order-preserving, one round trip."""
    with QueryClient("127.0.0.1", server.port) as c:
        before = server.requests
        vals = c.query_states(ALS_STATE, ["2-I", "nope", "1-U"])
        assert vals == ["2.0;-1.0", None, "0.5;1.5"]
        assert server.requests == before + 1
        assert c.query_states(ALS_STATE, []) == []
