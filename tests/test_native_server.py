"""C++ epoll lookup server (native/lookup_server.cpp): protocol parity with
the Python LookupServer, concurrency, and the ServingJob --nativeServer
integration (end-to-end journal -> native store -> C++ data plane)."""

import socket
import threading
import time

import pytest

from flink_ms_tpu.serve.client import QueryClient
from flink_ms_tpu.serve.consumer import (
    ALS_STATE,
    ServingJob,
    make_backend,
    parse_als_record,
)
from flink_ms_tpu.serve.journal import Journal
from flink_ms_tpu.serve.native_store import NativeLookupServer, NativeStore
from flink_ms_tpu.serve.server import LookupServer
from flink_ms_tpu.serve.table import ModelTable


@pytest.fixture
def store(tmp_path):
    s = NativeStore(str(tmp_path / "store"))
    s.put("1-U", "0.5;1.5")
    s.put("2-I", "2.0;-1.0")
    yield s
    s.close()


@pytest.fixture
def server(store):
    with NativeLookupServer(store, ALS_STATE, job_id="jid", port=0) as srv:
        yield srv


def _raw(port: int, payload: bytes) -> bytes:
    with socket.create_connection(("127.0.0.1", port), timeout=5) as sock:
        sock.sendall(payload)
        sock.shutdown(socket.SHUT_WR)
        out = b""
        while True:
            chunk = sock.recv(65536)
            if not chunk:
                return out
            out += chunk


def test_get_ping_and_misses(server):
    with QueryClient("127.0.0.1", server.port) as c:
        assert c.query_state(ALS_STATE, "1-U") == "0.5;1.5"
        assert c.query_state(ALS_STATE, "2-I") == "2.0;-1.0"
        assert c.query_state(ALS_STATE, "999-U") is None
        assert c.count(ALS_STATE) == 2  # the fixture's two rows
        assert "jid" in c.ping()
        with pytest.raises(Exception):
            c.query_state("NO_SUCH_STATE", "1-U")
    assert server.requests >= 5


def test_protocol_matches_python_server(store):
    """Byte-for-byte response parity on every verb (the Python server is the
    semantics contract)."""
    table = ModelTable(2)
    for k, v in store.items():
        table.put(k, v)
    pysrv = LookupServer({ALS_STATE: table}, host="127.0.0.1", port=0,
                         job_id="jid").start()
    requests = (
        b"GET\tALS_MODEL\t1-U\n"
        b"GET\tALS_MODEL\tmissing\n"
        b"GET\tOTHER\tx\n"
        b"TOPK\tALS_MODEL\t1\t5\n"
        b"PING\n"
        b"PING\textra\tfields\n"
        b"NONSENSE\n"
        b"GET\ttoo\tmany\ttabs\n"
        b"GET\teven\tmore\ttabs\there\n"
        b"TOPK\ta\tb\tc\td\n"
        b"TOPK\tALS_MODEL\t1\n"
        b"MGET\tALS_MODEL\t1-U,missing,2-I\n"
        b"MGET\tALS_MODEL\t1-U\n"
        b"MGET\tALS_MODEL\t\n"
        b"MGET\tOTHER\t1-U\n"
        b"MGET\tALS_MODEL\ta\tb\n"
        b"COUNT\tALS_MODEL\n"
        b"COUNT\tOTHER\n"
        b"COUNT\tALS_MODEL\textra\n"
        b"\n"
    )
    try:
        with NativeLookupServer(store, ALS_STATE, job_id="jid",
                                port=0) as nsrv:
            assert _raw(nsrv.port, requests) == _raw(pysrv.port, requests)
    finally:
        pysrv.stop()


def test_pipelined_and_split_requests(server):
    # two requests in one segment, then one request dribbled byte-by-byte
    out = _raw(server.port, b"GET\tALS_MODEL\t1-U\nPING\n")
    assert out == b"V\t0.5;1.5\nPONG\tjid\tALS_MODEL\n"
    with socket.create_connection(("127.0.0.1", server.port), timeout=5) as s:
        for b in b"GET\tALS_MODEL\t2-I\n":
            s.sendall(bytes([b]))
        f = s.makefile("rb")
        assert f.readline() == b"V\t2.0;-1.0\n"


def test_final_line_without_newline_is_answered(server):
    # readline()-at-EOF parity: the Python server answers a trailing
    # partial line on half-close, so the native server must too
    assert _raw(server.port, b"PING") == b"PONG\tjid\tALS_MODEL\n"
    assert _raw(server.port, b"PING\nGET\tALS_MODEL\t1-U") == (
        b"PONG\tjid\tALS_MODEL\nV\t0.5;1.5\n"
    )


def test_large_pipelined_burst_is_answered(server):
    # >1 MB of small valid requests in one burst: the request-line cap must
    # bound a single line, not the whole unparsed buffer
    n = 80_000
    burst = b"GET\tALS_MODEL\t1-U\n" * n
    assert len(burst) > (1 << 20)
    out = _raw(server.port, burst)
    assert out == b"V\t0.5;1.5\n" * n


def test_slow_reader_is_disconnected(server):
    # a client that pipelines forever without reading responses must be
    # dropped once the buffered-response cap is hit, not OOM the server
    line = b"GET\tALS_MODEL\t1-U\n"
    with socket.create_connection(("127.0.0.1", server.port), timeout=10) as s:
        try:
            # 32 MB of requests -> ~18 MB of buffered responses > 16 MB cap
            for _ in range(2048):
                s.sendall(line * 1024)
        except (ConnectionResetError, BrokenPipeError):
            return  # server dropped us: expected
        # server may also close gracefully after we stop sending
        s.shutdown(socket.SHUT_WR)
        total = 0
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            total += len(chunk)
        assert total < (32 << 20)


def test_oversized_single_line_closes_connection(server):
    # the server drops the connection mid-send; depending on timing the
    # client sees a clean EOF with no payload, a reset, or a failed
    # shutdown on the already-closed socket (ENOTCONN)
    try:
        out = _raw(server.port, b"GET\tALS_MODEL\t" + b"x" * (2 << 20) + b"\n")
    except OSError:
        return
    assert out == b""


def test_concurrent_clients(server):
    errors = []

    def worker():
        try:
            with QueryClient("127.0.0.1", server.port) as c:
                for _ in range(50):
                    assert c.query_state(ALS_STATE, "1-U") == "0.5;1.5"
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    assert server.requests >= 400


def test_serving_job_native_server_end_to_end(tmp_path):
    journal = Journal(str(tmp_path / "journal"), "als-topic")
    journal.append(["1,U,0.5;1.5", "7,I,3.0;4.0"])
    backend = make_backend("rocksdb", str(tmp_path / "chk"))
    job = ServingJob(
        journal, ALS_STATE, parse_als_record, backend,
        port=0, poll_interval_s=0.05, checkpoint_interval_ms=100,
        native_server=True,
    ).start()
    try:
        with QueryClient("127.0.0.1", job.port) as c:
            deadline = 50
            while c.query_state(ALS_STATE, "7-I") is None and deadline:
                threading.Event().wait(0.1)
                deadline -= 1
            assert c.query_state(ALS_STATE, "1-U") == "0.5;1.5"
            assert c.query_state(ALS_STATE, "7-I") == "3.0;4.0"
            # the native ALS plane serves the full verb set: TOPK scores
            # the "-I" catalog straight from the store (round 4; it used
            # to answer E)
            got = c.topk(ALS_STATE, "1", 3)
            assert got == [("7", pytest.approx(0.5 * 3.0 + 1.5 * 4.0))]
    finally:
        job.stop()


def test_native_server_requires_native_backend(tmp_path):
    journal = Journal(str(tmp_path / "journal"), "t")
    with pytest.raises(ValueError, match="nativeServer"):
        ServingJob(journal, ALS_STATE, parse_als_record,
                   make_backend("memory", None), port=0, native_server=True)


def test_mget_batches_native(server):
    """MGET on the C++ server: order-preserving, one round trip."""
    with QueryClient("127.0.0.1", server.port) as c:
        before = server.requests
        vals = c.query_states(ALS_STATE, ["2-I", "nope", "1-U"])
        assert vals == ["2.0;-1.0", None, "0.5;1.5"]
        assert server.requests == before + 1
        assert c.query_states(ALS_STATE, []) == []


# -- native TOPK/TOPKV (VERDICT r3 missing #2: the C++ plane now serves the
# -- full verb set; serve/topk.py + server.py are the semantics contract)

def _als_store(tmp_path, rows):
    s = NativeStore(str(tmp_path / "topk_store"))
    for k, v in rows:
        s.put(k, v)
    return s


def _als_pyserver(rows):
    from flink_ms_tpu.serve.topk import make_als_topk_handler

    table = ModelTable(2)
    for k, v in rows:
        table.put(k, v)
    return LookupServer(
        {ALS_STATE: table}, host="127.0.0.1", port=0, job_id="jid",
        topk_handlers={ALS_STATE: make_als_topk_handler(table)},
    ).start()


# factor values on a 0.25 grid: every product and 4-term sum is exactly
# representable in f32, so the XLA-scored Python plane and the C++ plane
# compute bit-identical scores and byte-identical formatted payloads
_EXACT_ROWS = [
    ("10-I", "1.0;0.5;-2.0;0.25"),
    ("11-I", "0.5;0.5;0.5;0.5"),
    ("12-I", "-1.0;2.0;1.5;-0.5"),
    ("13-I", "2.0;-0.25;0.75;1.0"),
    ("7-U", "1.0;2.0;0.5;-1.0"),
    ("MEAN-I", "9.0;9.0;9.0;9.0"),      # cold-start row: excluded
    ("bad-I", "1.0;2.0"),               # off the modal width: dropped
]


def test_native_topkv_byte_parity(tmp_path):
    # formatting edges ride along: a 4e5-scale score (Python repr stays
    # fixed-notation where bare to_chars would flip to "4e+05") and a
    # ~1e-5 score (scientific on both sides)
    rows = _EXACT_ROWS + [
        ("20-I", "400000.0;0.0;0.0;0.0"),
        ("21-I", "0.00001;0.0;0.0;0.0"),
    ]
    pysrv = _als_pyserver(rows)
    store = _als_store(tmp_path, rows)
    requests = (
        b"TOPKV\tALS_MODEL\t3\t1.0;2.0;0.5;-1.0\n"
        b"TOPKV\tALS_MODEL\t99\t1.0;2.0;0.5;-1.0\n"   # k > catalog
        b"TOPK\tALS_MODEL\t7\t2\n"                     # resolves 7-U
        b"TOPK\tALS_MODEL\tmissing\t2\n"               # unknown user -> N
        b"TOPKV\tALS_MODEL\t0\t1.0\n"                  # k < 1
        b"TOPKV\tALS_MODEL\tx\t1.0\n"                  # non-integer k
        b"TOPKV\tALS_MODEL\t2\t1.0;2.0\n"              # width mismatch
        b"TOPKV\tALS_MODEL\t2\t1.0;oops;3.0;4.0\n"     # non-numeric token
        b"TOPKV\tOTHER\t2\t1.0\n"                      # unknown state
    )
    try:
        with NativeLookupServer(store, ALS_STATE, job_id="jid", port=0,
                                topk_suffixes=("-I", "-U")) as nsrv:
            native = _raw(nsrv.port, requests)
            python = _raw(pysrv.port, requests)
            assert native == python, (native, python)
    finally:
        pysrv.stop()
        store.close()


def test_native_topkv_semantic_parity_random(tmp_path):
    """Random float factors: ranking identical, scores equal to f32
    round-off (the planes may differ in accumulation order)."""
    import numpy as np

    rng = np.random.default_rng(5)
    rows = [(f"{i}-I", ";".join(repr(float(x)) for x in rng.normal(size=6)))
            for i in range(40)]
    rows += [(f"{u}-U", ";".join(repr(float(x)) for x in rng.normal(size=6)))
             for u in range(3)]
    pysrv = _als_pyserver(rows)
    store = _als_store(tmp_path, rows)
    try:
        with NativeLookupServer(store, ALS_STATE, job_id="jid", port=0,
                                topk_suffixes=("-I", "-U")) as nsrv:
            with QueryClient("127.0.0.1", nsrv.port) as nc, \
                    QueryClient("127.0.0.1", pysrv.port) as pc:
                payload = ";".join(repr(float(x))
                                   for x in rng.normal(size=6))
                nat = nc.topk_by_vector(ALS_STATE, payload, 7)
                pyr = pc.topk_by_vector(ALS_STATE, payload, 7)
                assert [i for i, _ in nat] == [i for i, _ in pyr]
                for (_, a), (_, b) in zip(nat, pyr):
                    assert a == pytest.approx(b, rel=1e-5, abs=1e-5)
                nat_u = nc.topk(ALS_STATE, "1", 5)
                pyr_u = pc.topk(ALS_STATE, "1", 5)
                assert [i for i, _ in nat_u] == [i for i, _ in pyr_u]
    finally:
        pysrv.stop()
        store.close()


def test_native_topkv_index_refreshes_on_store_change(tmp_path):
    store = _als_store(tmp_path, _EXACT_ROWS)
    try:
        with NativeLookupServer(store, ALS_STATE, job_id="jid", port=0,
                                topk_suffixes=("-I", "-U")) as nsrv:
            with QueryClient("127.0.0.1", nsrv.port) as c:
                def poll_until(expect_ids, k):
                    deadline = time.time() + 20
                    while time.time() < deadline:
                        got = c.topk_by_vector(
                            ALS_STATE, "1.0;0.0;0.0;0.0", k)
                        if [i for i, _ in got] == expect_ids:
                            return got
                        time.sleep(0.02)
                    return got

                got = c.topk_by_vector(ALS_STATE, "1.0;0.0;0.0;0.0", 1)
                assert got[0][0] == "13"      # 2.0 leads dim 0
                # overwrite an existing row to the new best: the version
                # proxy (count unchanged, log_bytes grew) must invalidate.
                # Serve-stale semantics: the change lands via a BACKGROUND
                # rebuild, so poll rather than assert the first answer.
                store.put("11-I", "50.0;0.0;0.0;0.0")
                got = poll_until(["11"], 1)
                assert got[0] == ("11", 50.0)
                # and a brand-new item (count changes) lands too
                store.put("99-I", "100.0;0.0;0.0;0.0")
                got = poll_until(["99", "11"], 2)
                assert [i for i, _ in got] == ["99", "11"]
    finally:
        store.close()


def test_native_topkv_serve_stale_under_writes(tmp_path):
    """A streaming writer must not head-of-line-block the plane: once a
    snapshot exists, queries under continuous writes answer from the
    current (possibly stale) index while the rebuild runs in the
    background, and the new best eventually lands."""
    store = _als_store(tmp_path, _EXACT_ROWS)
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            store.put(f"{100 + (i % 50)}-I", "0.125;0.125;0.125;0.125")
            i += 1

    try:
        with NativeLookupServer(store, ALS_STATE, job_id="jid", port=0,
                                topk_suffixes=("-I", "-U")) as nsrv:
            with QueryClient("127.0.0.1", nsrv.port, timeout_s=30) as c:
                c.topk_by_vector(ALS_STATE, "1.0;0.0;0.0;0.0", 1)  # build
                t = threading.Thread(target=writer)
                t.start()
                try:
                    # under the writer every query window sees a moved
                    # version; answers must keep coming (stale is fine)
                    for _ in range(50):
                        got = c.topk_by_vector(
                            ALS_STATE, "1.0;0.0;0.0;0.0", 1)
                        assert got, "no answer under streaming writes"
                    # a decisive new best lands once a rebuild completes
                    store.put("999-I", "1000.0;0.0;0.0;0.0")
                    deadline = time.time() + 20
                    while time.time() < deadline:
                        got = c.topk_by_vector(
                            ALS_STATE, "1.0;0.0;0.0;0.0", 1)
                        if got and got[0][0] == "999":
                            break
                        time.sleep(0.05)
                    assert got[0][0] == "999"
                finally:
                    stop.set()
                    t.join()
    finally:
        store.close()


def test_native_topkv_empty_catalog(tmp_path):
    store = NativeStore(str(tmp_path / "empty_store"))
    try:
        with NativeLookupServer(store, ALS_STATE, job_id="jid", port=0,
                                topk_suffixes=("-I", "-U")) as nsrv:
            out = _raw(nsrv.port, b"TOPKV\tALS_MODEL\t3\t1.0;2.0\n")
            assert out == b"V\t\n"
    finally:
        store.close()


def test_native_topkv_pipelined_reply_order(tmp_path):
    """A GET pipelined behind a TOPKV on one connection must come back
    AFTER the TOPKV reply even though the top-k runs on the worker thread
    (per-connection FIFO via deferred reply slots)."""
    store = _als_store(tmp_path, _EXACT_ROWS)
    try:
        with NativeLookupServer(store, ALS_STATE, job_id="jid", port=0,
                                topk_suffixes=("-I", "-U")) as nsrv:
            out = _raw(nsrv.port,
                       b"TOPKV\tALS_MODEL\t1\t1.0;0.0;0.0;0.0\n"
                       b"GET\tALS_MODEL\t7-U\n"
                       b"TOPK\tALS_MODEL\t7\t1\n"
                       b"PING\n")
            lines = out.split(b"\n")
            assert lines[0].startswith(b"V\t13:")   # TOPKV first
            assert lines[1] == b"V\t1.0;2.0;0.5;-1.0"
            assert lines[2].startswith(b"V\t")      # TOPK third
            assert lines[3].startswith(b"PONG")
    finally:
        store.close()


def test_native_topkv_nan_scores_deterministic(tmp_path):
    """NaN tokens parse (like Python float('nan')) and rank above +inf in
    lax.top_k's total order, with deterministic id-sorted tie-breaking —
    no undefined comparator behavior."""
    rows = [("1-I", "1.0;2.0"), ("2-I", "3.0;1.0"), ("3-I", "0.5;0.5")]
    store = _als_store(tmp_path, rows)
    try:
        with NativeLookupServer(store, ALS_STATE, job_id="jid", port=0,
                                topk_suffixes=("-I", "-U")) as nsrv:
            out = _raw(nsrv.port, b"TOPKV\tALS_MODEL\t3\tnan;0.0\n")
            # every score is NaN -> all tie -> id-sorted catalog order
            assert out == b"V\t1:nan;2:nan;3:nan\n"
            out = _raw(nsrv.port, b"TOPKV\tALS_MODEL\t2\tinf;0.0\n")
            # finite*inf = inf for rows 1,2; 0.5*inf = inf too -> ties in
            # id order
            assert out == b"V\t1:inf;2:inf\n"
    finally:
        store.close()


def test_native_dot_byte_parity(tmp_path):
    """DOT verb across planes (round 5): the native server answers the
    server-side sparse dot byte-identically to the Python contract plane
    on exact-grid fixtures — valid dots, in-row duplicate fids resolving
    last-wins, missing-bucket reporting, empty query, bad range, unknown
    state, and wrong arity."""
    from flink_ms_tpu.serve.consumer import SVM_STATE

    rows = [
        ("0", "1:1.0;2:0.5;3:-2.0"),
        ("1", "5:0.25;7:2.0"),
        ("2", "9:1.0;9:2.5"),       # duplicate fid: last wins (2.5)
        ("3", "13:4.0;"),           # trailing ';' must parse
    ]
    table = ModelTable(2)
    for k, v in rows:
        table.put(k, v)
    pysrv = LookupServer({SVM_STATE: table}, host="127.0.0.1", port=0,
                         job_id="jid").start()
    store = _als_store(tmp_path, rows)
    requests = (
        b"DOT\tSVM_MODEL\t4\t1:2.0;2:-4.0;7:0.5\n"   # all-hit dot
        b"DOT\tSVM_MODEL\t4\t9:1.0\n"                # dup fid -> 2.5
        b"DOT\tSVM_MODEL\t4\t1:2.0;17:1.0;100:3.0\n" # missing buckets 4,25
        b"DOT\tSVM_MODEL\t4\t13:0.25;15:1.0\n"       # fid miss, bucket hit
        b"DOT\tSVM_MODEL\t4\t\n"                     # empty query
        b"DOT\tSVM_MODEL\t0\t1:1.0\n"                # range < 1
        b"DOT\tSVM_MODEL\tx\t1:1.0\n"                # non-integer range
        b"DOT\tOTHER\t4\t1:1.0\n"                    # unknown state
        b"DOT\tSVM_MODEL\t4\n"                       # arity -> bad request
        b"DOT\tSVM_MODEL\t 4 \t 1 : 2.0 \n"          # whitespace padding
        b"DOT\tSVM_MODEL\t4\t5:0.25;;;\n"            # trailing ';' run ok
        b"DOT\tSVM_MODEL\t4\t1:1.0;;2:0.5\n"         # empty interior seg
        b"DOT\tSVM_MODEL\t4\t1:2.0:3.0\n"            # two colons in a pair
    )
    try:
        with NativeLookupServer(store, SVM_STATE, job_id="jid",
                                port=0) as nsrv:
            native = _raw(nsrv.port, requests)
            python = _raw(pysrv.port, requests)
            assert native == python, (native, python)
            # pin the actual semantics, not just agreement
            lines = python.decode().splitlines()
            assert lines[0] == "D\t1.0\t"       # 2-2+1
            assert lines[1] == "D\t2.5\t"
            assert lines[2] == "D\t2.0\t4,25"
            assert lines[3] == "D\t1.0\t"
            assert lines[4] == "D\t0.0\t"
            assert lines[5] == "E\trange must be >= 1"
            assert lines[8] == "E\tbad request"
            assert lines[9] == "D\t2.0\t"
            assert lines[10] == "D\t0.0625\t"
            assert lines[11].startswith("E\tdot failed: malformed pair")
            assert lines[12].startswith("E\tdot failed: malformed pair")
            # numeric-literal failures: both planes reject (E), but the
            # message text is plane-specific (numpy vs strtod) — compare
            # acceptance only
            for bad in (b"DOT\tSVM_MODEL\t4\t1:abc\n",
                        b"DOT\tSVM_MODEL\t4\tzz:1.0\n"):
                assert _raw(nsrv.port, bad).startswith(b"E\tdot failed")
                assert _raw(pysrv.port, bad).startswith(b"E\tdot failed")
    finally:
        pysrv.stop()
        store.close()
