import os

import numpy as np
import pytest

from flink_ms_tpu.core import formats as F


def test_ratings_roundtrip(tmp_path):
    users = np.array([1, 2, 3])
    items = np.array([10, 20, 30])
    ratings = np.array([4.0, 3.5, 1.0])
    p = str(tmp_path / "ratings.csv")
    F.write_ratings(p, users, items, ratings)
    u, i, r = F.read_ratings(p)
    np.testing.assert_array_equal(u, users)
    np.testing.assert_array_equal(i, items)
    np.testing.assert_allclose(r, ratings)


def test_ratings_tab_and_header(tmp_path):
    p = str(tmp_path / "r.tsv")
    with open(p, "w") as f:
        f.write("userId\titemId\trating\n1\t2\t5.0\n7\t8\t2.5\n")
    u, i, r = F.read_ratings(p, field_delimiter="\t", ignore_first_line=True)
    assert list(u) == [1, 7]
    assert list(i) == [2, 8]
    np.testing.assert_allclose(r, [5.0, 2.5])


def test_ratings_directory_of_parts(tmp_path):
    d = tmp_path / "out"
    d.mkdir()
    (d / "1").write_text("1,2,3.0\n")
    (d / "2").write_text("4,5,1.0\n")
    u, i, r = F.read_ratings(str(d))
    assert len(u) == 2


def test_als_row_roundtrip():
    line = F.format_als_row(42, F.USER, [0.5, -1.25, 3.0])
    assert line == "42,U,0.5;-1.25;3.0"
    id_, typ, vec = F.parse_als_row(line)
    assert id_ == "42" and typ == "U"
    np.testing.assert_allclose(vec, [0.5, -1.25, 3.0])


def test_als_model_file_roundtrip(tmp_path):
    p = str(tmp_path / "user_factors")
    mat = np.array([[1.0, 2.0], [3.0, 4.0]])
    F.write_als_model(p, [10, 20], F.USER, mat)
    ids, types, out = F.read_als_model(p)
    assert ids == ["10", "20"]
    assert types == ["U", "U"]
    np.testing.assert_allclose(out, mat)


def test_mean_row():
    assert F.format_mean_row(F.ITEM, [0.5, 0.5]) == "MEAN,I,0.5;0.5"


def test_svm_flat_rows():
    rows = list(F.format_svm_flat_rows(np.array([0.1, -0.2])))
    assert rows == ["1,0.1", "2,-0.2"]
    assert F.parse_svm_flat_row(rows[1]) == (2, -0.2)


def test_svm_range_rows_bucketing():
    # 1-based idx // range: with range=2, idx1=1 -> bucket 0, idx1=2 -> 1,
    # idx1=3 -> 1, idx1=4 -> 2 (matches SVMImpl.scala:42 integer division)
    w = np.array([1.0, 2.0, 3.0, 4.0])
    rows = list(F.format_svm_range_rows(w, 2))
    assert rows == ["0,1:1.0", "1,2:2.0;3:3.0", "2,4:4.0"]
    b, entries = F.parse_svm_range_row(rows[1])
    assert b == 1 and entries == [(2, 2.0), (3, 3.0)]


def test_read_svm_model_flat_and_ranged(tmp_path):
    w = np.array([0.5, 0.0, -1.5])
    flat = str(tmp_path / "flat")
    F.write_lines(flat, F.format_svm_flat_rows(w))
    np.testing.assert_allclose(F.read_svm_model(flat), w)

    ranged = str(tmp_path / "ranged")
    F.write_lines(ranged, F.format_svm_range_rows(w, 1000))
    np.testing.assert_allclose(F.read_svm_model(ranged, partitioned=True), w)


def test_libsvm_parse(tmp_path):
    p = str(tmp_path / "data.libsvm")
    with open(p, "w") as f:
        f.write("+1 1:0.5 3:1.5\n")
        f.write("-1 2:2.0 # a comment\n")
        f.write("\n")
    d = F.read_libsvm(p)
    assert d.n_examples == 2
    assert d.n_features == 3
    np.testing.assert_allclose(d.labels, [1.0, -1.0])
    idx0, val0 = d.row(0)
    assert list(idx0) == [0, 2]  # 1-based on disk -> 0-based
    np.testing.assert_allclose(val0, [0.5, 1.5])
    idx1, val1 = d.row(1)
    assert list(idx1) == [1]


def test_libsvm_rejects_zero_index(tmp_path):
    p = str(tmp_path / "bad.libsvm")
    with open(p, "w") as f:
        f.write("1 0:1.0\n")
    with pytest.raises(ValueError):
        F.read_libsvm(p)


def test_latency_rows():
    assert F.format_als_latency_row(1, 2, 3.5, 12.6) == "1,2,3.5,13"
    assert F.format_svm_latency_row(9, 4, -1.0, 0.4) == "9,4,-1.0,0"


def test_iter_lines_skips_hidden(tmp_path):
    d = tmp_path / "model"
    d.mkdir()
    (d / "part-1").write_text("a\n")
    (d / ".crc").write_text("junk\n")
    (d / "_SUCCESS").write_text("\n")
    assert list(F.iter_lines(str(d))) == ["a"]


def test_ratings_header_skipped_per_file(tmp_path):
    # Flink's CsvInputFormat skips the first line of every file
    d = tmp_path / "parts"
    d.mkdir()
    (d / "1").write_text("u,i,r\n1,2,3.0\n")
    (d / "2").write_text("u,i,r\n4,5,1.0\n")
    u, i, r = F.read_ratings(str(d), ignore_first_line=True)
    assert list(u) == [1, 4]


def test_interior_empty_factor_token_raises():
    with pytest.raises(ValueError):
        F.parse_als_row("7,U,1.0;;2.0")
    # trailing separator still tolerated (Java split semantics)
    _, _, v = F.parse_als_row("7,U,1.0;2.0;")
    assert list(v) == [1.0, 2.0]

def test_range_payload_cache_coherent_and_bounded():
    from flink_ms_tpu.core.formats import RangePayloadCache

    cache = RangePayloadCache(max_entries=2)
    idx, w = cache.lookup("3:0.5;1:0.25;")
    # sorted ascending by index
    assert idx.tolist() == [1, 3] and w.tolist() == [0.25, 0.5]
    # same string -> same (cached) arrays
    idx2, _ = cache.lookup("3:0.5;1:0.25;")
    assert idx2 is idx
    # a republished bucket arrives as a DIFFERENT string: must miss
    idx3, w3 = cache.lookup("3:0.75;1:0.25;")
    assert w3.tolist() == [0.25, 0.75]
    # bounded: inserting past max evicts, no growth
    cache.lookup("7:1.0")
    assert len(cache._cache) <= 2

def test_range_payload_malformed_still_raises():
    """The vectorized fast path must not silently re-pair corrupted rows:
    structure violations raise exactly as the per-token parser did."""
    from flink_ms_tpu.core.formats import parse_svm_range_row

    for bad in ("5,1;2", "5,1:2:3;4", "5,:1;2:3", "5,1:2;3"):
        with pytest.raises(ValueError):
            parse_svm_range_row(bad)


def test_float_formatted_index_rejected_like_exact_path():
    """ADVICE r2: the fast path must agree with the per-token path on what
    is malformed — a float-shaped index ("3.0:w", "3e0:w") raises, while
    negative/plus-signed integer indices still take the fast path."""
    from flink_ms_tpu.core.formats import parse_svm_range_payload

    for bad in ("3.0:1.5;4:2.0", "3e0:1.5", "4:2.0;0x3:1.0"):
        with pytest.raises(ValueError):
            parse_svm_range_payload(bad)
    # exponent/decimal in the VALUE region stays fast-path legal
    idx, w = parse_svm_range_payload("3:1.5e-2;-4:2.0;+5:.25")
    assert idx.tolist() == [3, -4, 5]
    assert w.tolist() == [0.015, 2.0, 0.25]


def test_range_cache_duplicate_index_last_wins():
    """ADVICE r2: duplicate feature ids within one payload resolve to the
    LAST occurrence — the dict-parse semantics the range client had before
    the vectorized cache."""
    from flink_ms_tpu.core.formats import RangePayloadCache

    cache = RangePayloadCache()
    w, hit = cache.gather("5:1.0;7:2.0;5:9.0", [5, 7])
    assert w.tolist() == [9.0, 2.0]
    assert hit.all()
