"""Bench harness integrity: the section dispatch table in bench.py must
reference real functions in bench_sections.py, and the tiny-config serving
pipeline must produce its metric keys without error keys (the artifact
contract the driver's end-of-round run depends on)."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_section_table_names_resolve():
    import ast

    import bench_sections

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tree = ast.parse(open(os.path.join(root, "bench.py")).read())
    names = [
        n.value for n in ast.walk(tree)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
        and n.value.startswith("run_") and n.value.endswith("_section")
    ]
    assert names, "section table not found in bench.py"
    for fn_name in names:
        assert callable(getattr(bench_sections, fn_name, None)), fn_name


@pytest.mark.slow
def test_tiny_serving_section_clean(monkeypatch):
    """Serving section at a tiny config: all metric families present, no
    *_error keys."""
    for k, v in {
        "BENCH_SERVE_USERS": "60", "BENCH_SERVE_ITEMS": "40",
        "BENCH_SERVE_K": "4", "BENCH_SERVE_QUERIES": "20",
        "BENCH_SERVE_TOPK_QUERIES": "4", "BENCH_SGD_RATINGS": "20",
        "BENCH_MSE_RATINGS": "30", "BENCH_SHARD_WORKERS": "2",
    }.items():
        monkeypatch.setenv(k, v)
    from bench_sections import run_serving_section

    out = run_serving_section(small=True)
    errors = {k: v for k, v in out.items() if k.endswith("_error")}
    assert not errors, errors
    for prefix in (
        "gen_rows_per_sec", "ingest_rows_per_sec", "serving_get_p50_ms",
        "serving_mget_p50_ms", "serving_topk_p50_ms",
        "sgd_ratings_per_sec", "mse_live_value",
        "serving_native_mget_p50_ms", "serving_shard_mget_p50_ms",
    ):
        assert prefix in out, (prefix, sorted(out))
