"""Bench harness integrity: the section dispatch table in bench.py must
reference real functions in bench_sections.py, and the tiny-config serving
pipeline must produce its metric keys without error keys (the artifact
contract the driver's end-of-round run depends on)."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_section_table_names_resolve():
    import ast

    import bench_sections

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tree = ast.parse(open(os.path.join(root, "bench.py")).read())
    names = [
        n.value for n in ast.walk(tree)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
        and n.value.startswith("run_") and n.value.endswith("_section")
    ]
    assert names, "section table not found in bench.py"
    for fn_name in names:
        assert callable(getattr(bench_sections, fn_name, None)), fn_name


@pytest.mark.slow
def test_stdout_is_exactly_one_json_line():
    """The driver parses bench.py stdout as THE artifact; in-process CLI
    mains (producer/SGD/MSE job summaries) must not leak onto it."""
    import json
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ambient = {k: v for k, v in os.environ.items()
               if not k.startswith("BENCH_")}
    env = dict(ambient,
               BENCH_SECTIONS="als,svm,serving,svmserve",
               JAX_PLATFORMS="cpu", BENCH_SMALL="1", BENCH_SKIP_CPU="1",
               BENCH_NNZ="2000", BENCH_USERS="100", BENCH_ITEMS="50",
               BENCH_RANK="4", BENCH_SVM_EXAMPLES="400",
               BENCH_SVM_FEATURES="60", BENCH_SVM_ROUNDS="2",
               BENCH_SERVE_USERS="40", BENCH_SERVE_ITEMS="30",
               BENCH_SERVE_K="4", BENCH_SERVE_QUERIES="10",
               BENCH_SERVE_TOPK_QUERIES="2", BENCH_SGD_RATINGS="10",
               BENCH_MSE_RATINGS="10", BENCH_SHARD_WORKERS="2",
               BENCH_SVMSERVE_FEATURES="50", BENCH_SVMSERVE_QUERIES="5")
    proc = subprocess.run(
        [sys.executable, "bench.py"], cwd=root, env=env,
        capture_output=True, text=True, timeout=1200,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"stdout polluted: {lines[:5]}"
    parsed = json.loads(lines[0])
    assert "metric" in parsed and "value" in parsed


@pytest.mark.slow
def test_tiny_serving_section_clean(monkeypatch):
    """Serving section at a tiny config: all metric families present, no
    *_error keys."""
    for k, v in {
        "BENCH_SERVE_USERS": "60", "BENCH_SERVE_ITEMS": "40",
        "BENCH_SERVE_K": "4", "BENCH_SERVE_QUERIES": "20",
        "BENCH_SERVE_TOPK_QUERIES": "4", "BENCH_SGD_RATINGS": "20",
        "BENCH_MSE_RATINGS": "30", "BENCH_SHARD_WORKERS": "2",
    }.items():
        monkeypatch.setenv(k, v)
    from bench_sections import run_serving_section

    out = run_serving_section(small=True)
    errors = {k: v for k, v in out.items() if k.endswith("_error")}
    assert not errors, errors
    for prefix in (
        "gen_rows_per_sec", "ingest_rows_per_sec", "serving_get_p50_ms",
        "serving_mget_p50_ms", "serving_topk_p50_ms",
        "sgd_ratings_per_sec", "mse_live_value",
        "serving_native_mget_p50_ms", "serving_shard_mget_p50_ms",
    ):
        assert prefix in out, (prefix, sorted(out))
