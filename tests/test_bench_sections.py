"""Bench harness integrity: the section dispatch table in bench.py must
reference real functions in bench_sections.py, and the tiny-config serving
pipeline must produce its metric keys without error keys (the artifact
contract the driver's end-of-round run depends on)."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_section_table_names_resolve():
    import ast

    import bench_sections

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tree = ast.parse(open(os.path.join(root, "bench.py")).read())
    names = [
        n.value for n in ast.walk(tree)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
        and n.value.startswith("run_") and n.value.endswith("_section")
    ]
    assert names, "section table not found in bench.py"
    for fn_name in names:
        assert callable(getattr(bench_sections, fn_name, None)), fn_name


@pytest.mark.slow
def test_stdout_is_exactly_one_json_line(tmp_path):
    """The driver parses bench.py stdout as THE artifact — and records only
    a ~2 KB TAIL of it (BENCH_r02.json lost the head of a 2.3 KB line and
    recorded parsed=null).  So: exactly one line, parseable, COMPACT, with
    the full section detail in the BENCH_DETAIL.json sidecar."""
    import json
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    detail = tmp_path / "BENCH_DETAIL.json"
    ambient = {k: v for k, v in os.environ.items()
               if not k.startswith("BENCH_")}
    env = dict(ambient,
               BENCH_SECTIONS="als,svm,serving,svmserve",
               BENCH_DETAIL_PATH=str(detail),
               JAX_PLATFORMS="cpu", BENCH_SMALL="1", BENCH_SKIP_CPU="1",
               BENCH_NNZ="2000", BENCH_USERS="100", BENCH_ITEMS="50",
               BENCH_RANK="4", BENCH_SVM_EXAMPLES="400",
               BENCH_SVM_FEATURES="60", BENCH_SVM_ROUNDS="2",
               BENCH_SERVE_USERS="40", BENCH_SERVE_ITEMS="30",
               BENCH_SERVE_K="4", BENCH_SERVE_QUERIES="10",
               BENCH_SERVE_TOPK_QUERIES="2", BENCH_SGD_RATINGS="10",
               BENCH_MSE_RATINGS="10", BENCH_SHARD_WORKERS="2",
               BENCH_SVMSERVE_FEATURES="50", BENCH_SVMSERVE_QUERIES="5")
    proc = subprocess.run(
        [sys.executable, "bench.py"], cwd=root, env=env,
        capture_output=True, text=True, timeout=1200,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"stdout polluted: {lines[:5]}"
    assert len(lines[0]) <= 1800, (
        f"compact line {len(lines[0])}B outgrew the driver tail window"
    )
    parsed = json.loads(lines[0])
    assert "metric" in parsed and "value" in parsed
    assert "platform" in parsed  # invisible in r02's truncated artifact
    # a JAX_PLATFORMS=cpu pin is an operator choice, not a failed backend
    assert not parsed.get("degraded")
    full = json.loads(detail.read_text())
    assert parsed["detail"] == "BENCH_DETAIL.json"
    # the sidecar is a superset of the compact line
    for k, v in parsed.items():
        if k not in ("detail", "section_errors", "backend_error"):
            assert full[k] == v, k
    assert "serving_get_p50_ms" in full  # detail-only key


def test_emit_artifact_compact_even_when_result_is_huge(tmp_path, monkeypatch):
    """A result dict far bigger than the driver's stdout-tail window must
    still render to a short parseable line, with everything in the sidecar."""
    import json

    import bench

    monkeypatch.setattr(bench, "_DETAIL_PATH", str(tmp_path / "d.json"))
    result = {"metric": "als_ml20m_sec_per_iter", "value": 1.0,
              "unit": "s/iter", "vs_baseline": 2.0, "platform": "tpu",
              "degraded": False}
    result.update({f"extra_key_{i}": i * 0.123 for i in range(200)})
    result["svm_error"] = "boom\n" * 50
    line = bench.emit_artifact(result)
    assert len(line) <= 1800
    parsed = json.loads(line)
    assert parsed["metric"] == "als_ml20m_sec_per_iter"
    assert parsed["section_errors"] == ["svm_error"]
    full = json.loads((tmp_path / "d.json").read_text())
    assert full["extra_key_199"] == 199 * 0.123


def test_recovery_gating_is_cheap_and_safe(monkeypatch):
    """try_recover_accelerator must no-op (without probing) when the run is
    not degraded / already recovered / past deadline, and the relay
    classifier must call an unconfigured tunnel wedged."""
    import time as _time

    import bench

    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    assert bench.relay_looks_wedged() is True

    def boom(*a, **k):  # any probe attempt is a test failure
        raise AssertionError("probe should not run")

    monkeypatch.setattr(bench, "relay_looks_wedged", boom)
    bench.try_recover_accelerator({}, {}, _time.time() + 100)
    bench.try_recover_accelerator(
        {"degraded": True, "recovered": True}, {}, _time.time() + 100)
    bench.try_recover_accelerator({"degraded": True}, {}, _time.time() - 1)


def test_relay_classifier_eof_is_not_wedged(monkeypatch):
    """Round-3 observation: a healthy chip answered jax probes behind a
    relay that accepts the TCP connect and instantly EOFs.  The classifier
    must therefore treat connect+EOF as probe-worthy (False) and reserve
    True for refused/unconfigured relays."""
    import socket
    import threading

    import bench

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    port = srv.getsockname()[1]
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    monkeypatch.setenv("PALLAS_AXON_RELAY_PORT", str(port))

    def eof_once():
        conn, _ = srv.accept()
        conn.close()  # instant EOF after accept — the old "wedge" shape

    t = threading.Thread(target=eof_once, daemon=True)
    t.start()
    try:
        assert bench.relay_looks_wedged() is False
    finally:
        t.join(timeout=5)
        srv.close()
    # listener gone -> connect refused -> definitely absent
    assert bench.relay_looks_wedged() is True


def test_recovery_hang_backoff_skips_probe(monkeypatch):
    """After a probe hangs to its timeout (the one reliable wedge
    signature), further recovery attempts inside the backoff window must
    not touch the relay or probe again."""
    import time as _time

    import bench

    def boom(*a, **k):
        raise AssertionError("no relay/probe inside hang backoff")

    monkeypatch.setattr(bench, "relay_looks_wedged", boom)
    monkeypatch.setattr(bench, "_accel_probe_ok", boom)
    monkeypatch.setattr(bench, "_last_probe_hang", _time.time())
    bench.try_recover_accelerator(
        {"degraded": True}, {}, _time.time() + 600)


@pytest.mark.slow
def test_tiny_als_section_records_resolved_knobs(monkeypatch):
    """The ALS section artifact records RESOLVED kernel knobs (solver,
    exchange dtype) — not raw 'auto' markers — and the exchange A/B
    sections stay off on CPU runs."""
    for k, v in {
        "BENCH_USERS": "300", "BENCH_ITEMS": "200", "BENCH_NNZ": "5000",
        "BENCH_RANK": "4", "BENCH_ITERS": "2", "BENCH_SKIP_CPU": "1",
        "BENCH_SKIP_QUALITY": "1",
    }.items():
        monkeypatch.setenv(k, v)
    import jax

    from bench import run_als_section

    out = run_als_section(jax.devices("cpu")[:1], "cpu", True)
    assert out["als_solver"] == "lax"
    assert out["als_exchange_dtype"] == "f32"
    assert out["value"] > 0
    for key in ("als_bf16_sec_per_iter", "als_f32_sec_per_iter",
                "als_exchange_ab_error"):
        assert key not in out, key


@pytest.mark.slow
def test_tiny_serving_section_clean(monkeypatch):
    """Serving section at a tiny config: all metric families present, no
    *_error keys."""
    for k, v in {
        "BENCH_SERVE_USERS": "60", "BENCH_SERVE_ITEMS": "40",
        "BENCH_SERVE_K": "4", "BENCH_SERVE_QUERIES": "20",
        "BENCH_SERVE_TOPK_QUERIES": "4", "BENCH_SGD_RATINGS": "20",
        "BENCH_MSE_RATINGS": "30", "BENCH_SHARD_WORKERS": "2",
    }.items():
        monkeypatch.setenv(k, v)
    from bench_sections import run_serving_section

    out = run_serving_section(small=True)
    errors = {k: v for k, v in out.items() if k.endswith("_error")}
    assert not errors, errors
    for prefix in (
        "gen_rows_per_sec", "ingest_rows_per_sec", "serving_get_p50_ms",
        "serving_mget_p50_ms", "serving_topk_p50_ms",
        "sgd_ratings_per_sec", "mse_live_value",
        "serving_native_mget_p50_ms", "serving_shard_mget_p50_ms",
    ):
        assert prefix in out, (prefix, sorted(out))
    # the live MSE runs against a bounded-factor plane: predictions land in
    # [0,5), so against 1..5 ratings the value is a bounded sanity signal
    # (the r2 artifact recorded 9.5e154 off the heavy-tailed plane)
    import math

    assert math.isfinite(out["mse_live_value"])
    assert 0.0 <= out["mse_live_value"] < 30.0, out["mse_live_value"]
    # the real gate (VERDICT r3 weak #7): the live served value must match
    # the offline ground truth computed from the same model files.  The two
    # paths read identical text rows but compute at different precisions
    # (offline scores through f32 jax _predict_dense, live through f64
    # numpy dots), so the tolerance allows per-prediction f32 rounding —
    # abs ~1e-5 bounds it at any MSE magnitude — while a serving-plane
    # corruption (wrong rows, truncated payloads, silently missed keys)
    # moves the live value by far more
    assert out["mse_live_value"] == pytest.approx(
        out["mse_offline_value"], rel=1e-4, abs=1e-5
    ), (out["mse_live_value"], out["mse_offline_value"])


def test_recovery_merge_flips_degraded_and_keeps_initial_error(monkeypatch):
    """On a successful mid-run recovery the accelerator sections overwrite
    the degraded values, degraded flips false, and the original backend
    error is preserved under backend_error_initial."""
    import json as _json

    import time as _time

    import bench

    monkeypatch.setattr(bench, "relay_looks_wedged", lambda: False)
    monkeypatch.setattr(bench, "_accel_probe_ok", lambda env, t: True)
    sub_json = {"platform": "tpu", "n_devices": 1, "value": 0.5,
                "metric": "als_ml20m_sec_per_iter", "als_nnz": 20_000_000,
                # soft sub-section errors must NOT veto a valid headline
                "als_implicit_error": "soft failure, rides along"}

    class FakeProc:
        returncode = 0
        stdout = _json.dumps(sub_json) + "\n"
        stderr = "[bench] recovered run\n"

    captured = {}

    def fake_run(cmd, env, budget, cwd):
        captured["env"] = env
        return FakeProc()

    monkeypatch.setattr(bench, "_tracked_child", fake_run)
    result = {"degraded": True, "backend_error": "init hung",
              "degraded_skipped_config": {"als_nnz": 20_000_000},
              "als_quality_error": "stale degraded-run failure",
              "value": 4.8, "als_nnz": 2_000_000, "platform": "cpu"}
    orig_env = {"PATH": "/usr/bin", "BENCH_ITERS": "5"}
    # a section list without als/svm must not trigger any probe
    bench.try_recover_accelerator(result, orig_env, _time.time() + 600,
                                  ["serving"])
    assert not result.get("recovered")
    bench.try_recover_accelerator(result, orig_env, _time.time() + 600)
    assert result["recovered"] is True and result["degraded"] is False
    assert result["platform"] == "tpu"
    assert result["value"] == 0.5 and result["als_nnz"] == 20_000_000
    assert result["backend_error_initial"] == "init hung"
    assert "backend_error" not in result
    assert "degraded_skipped_config" not in result
    # stale degraded-run section errors must not survive the merge, while
    # the recovered run's own soft errors do
    assert "als_quality_error" not in result
    assert result["als_implicit_error"] == "soft failure, rides along"
    # the subprocess must see the PRE-degrade environment, not the caps
    assert captured["env"]["BENCH_ITERS"] == "5"
    # second call is a no-op (already recovered)
    monkeypatch.setattr(bench, "relay_looks_wedged",
                        lambda: (_ for _ in ()).throw(AssertionError))
    bench.try_recover_accelerator(result, orig_env, _time.time() + 600)


def test_recovery_rejects_cpu_subprocess(monkeypatch):
    """A recovery subprocess that itself degraded to CPU must not flip the
    artifact to recovered."""
    import json as _json

    import time as _time

    import bench

    monkeypatch.setattr(bench, "relay_looks_wedged", lambda: False)
    monkeypatch.setattr(bench, "_accel_probe_ok", lambda env, t: True)

    class FakeProc:
        returncode = 0
        stdout = _json.dumps({"platform": "cpu", "value": 9.9}) + "\n"
        stderr = ""

    monkeypatch.setattr(bench, "_tracked_child",
                        lambda cmd, env, budget, cwd: FakeProc())
    result = {"degraded": True, "backend_error": "init hung", "value": 4.8}
    bench.try_recover_accelerator(result, {}, _time.time() + 600)
    assert not result.get("recovered")
    assert result["degraded"] is True and result["value"] == 4.8
    assert "recovery_error" in result


@pytest.mark.slow
def test_sections_json_entry_point(tmp_path):
    """`bench.py --sections-json svm` (the recovery subprocess entry
    point): full JSON on the last stdout line, platform recorded, no
    sidecar writing (that's the parent's job)."""
    import json
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ambient = {k: v for k, v in os.environ.items()
               if not k.startswith("BENCH_")}
    env = dict(ambient, JAX_PLATFORMS="cpu", BENCH_SMALL="1",
               BENCH_SKIP_CPU="1", BENCH_SVM_EXAMPLES="400",
               BENCH_SVM_FEATURES="60", BENCH_SVM_ROUNDS="2",
               BENCH_DETAIL_PATH=str(tmp_path / "should_not_exist.json"))
    proc = subprocess.run(
        [sys.executable, "bench.py", "--sections-json", "svm"],
        cwd=root, env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    parsed = json.loads(proc.stdout.strip().splitlines()[-1])
    assert parsed["platform"] == "cpu"
    assert "svm_small_sec_per_round" in parsed
    assert not (tmp_path / "should_not_exist.json").exists()


def test_host_reference_op_is_quick_and_stable():
    """Every artifact carries a fixed host-op timing so cross-round
    throughput swings are attributable to environment vs regression
    (r4's SGD rate halved with nothing in the artifact to say why)."""
    import time as _time

    import bench

    t0 = _time.time()
    a = bench.host_reference_ms()
    b = bench.host_reference_ms()
    assert _time.time() - t0 < 30
    assert 0.1 < a < 10_000 and 0.1 < b < 10_000
    # medians of 5 on the same box: same order of magnitude
    assert max(a, b) / min(a, b) < 5, (a, b)


def test_final_recovery_loop_has_its_own_budget(monkeypatch):
    """Round 4 lost the artifact because the final loop's deadline (3000 s
    from start) outlived the driver's budget.  The loop must now respect
    BENCH_FINAL_RECOVERY_BUDGET_S independently of the global deadline."""
    import time as _time

    import bench

    monkeypatch.setenv("BENCH_FINAL_RECOVERY_BUDGET_S", "0")
    # keep the regression blast radius small: if the budget clamp is ever
    # removed the loop must hit THIS deadline (seconds) with no sleeping,
    # not idle out an hour swallowing the sentinel's AssertionError
    monkeypatch.setenv("BENCH_RECOVER_PROBE_INTERVAL_S", "0")

    def boom(*a, **k):
        raise AssertionError("no probe inside a zero budget")

    monkeypatch.setattr(bench, "try_recover_accelerator", boom)
    result = {"degraded": True}
    t0 = _time.time()
    bench.final_recovery_loop(result, {}, _time.time() + 3)
    assert _time.time() - t0 < 5
    assert result["final_recovery_attempts"] == 0


@pytest.mark.slow
def test_artifact_line_survives_driver_kill_mid_recovery(tmp_path):
    """VERDICT r4 #1 (the fourth consecutive 'get a number into the driver
    artifact' item): the compact JSON line must be on stdout BEFORE the
    end-of-run recovery loop starts, and a SIGTERM mid-loop must re-emit a
    parseable line (terminated=true) and exit 124 — so the driver artifact
    parses under EVERY tunnel state, including a budget kill mid-probing."""
    import json
    import signal
    import subprocess
    import threading

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ambient = {k: v for k, v in os.environ.items()
               if not k.startswith("BENCH_")}
    env = dict(ambient,
               # a bogus platform pin fails the probe fast (non-transient)
               # -> degrade to CPU with backend_error -> degraded artifact
               JAX_PLATFORMS="nosuchbackend",
               BENCH_INIT_ATTEMPTS="1", BENCH_INIT_TIMEOUT_S="60",
               BENCH_SECTIONS="als", BENCH_SMALL="1", BENCH_SKIP_CPU="1",
               BENCH_SKIP_QUALITY="1", BENCH_NNZ="2000", BENCH_USERS="100",
               BENCH_ITEMS="50", BENCH_RANK="4", BENCH_ITERS="1",
               BENCH_DETAIL_PATH=str(tmp_path / "detail.json"),
               # keep the final loop alive (probes fail fast on the bogus
               # pin) so the kill lands mid-loop, as round 4's did
               BENCH_RECOVER_DEADLINE_S="900",
               BENCH_FINAL_RECOVERY_BUDGET_S="600",
               BENCH_RECOVER_PROBE_INTERVAL_S="10")
    proc = subprocess.Popen(
        [sys.executable, "bench.py"], cwd=root, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
    )
    watchdog = threading.Timer(600, proc.kill)
    watchdog.start()
    try:
        first = proc.stdout.readline()  # emitted BEFORE the final loop
        parsed = json.loads(first)
        assert parsed["degraded"] is True
        assert "metric" in parsed and "backend_error" in parsed
        assert proc.poll() is None, "bench exited instead of probing"
        proc.send_signal(signal.SIGTERM)  # the driver-budget kill
        rest = proc.stdout.read()
        rc = proc.wait(timeout=60)
    finally:
        watchdog.cancel()
        proc.kill()
    assert rc == 124, rc
    lines = [ln for ln in rest.splitlines() if ln.strip()]
    assert lines, "SIGTERM emitter printed nothing"
    last = json.loads(lines[-1])
    assert last["terminated"] is True
    assert last["degraded"] is True


@pytest.mark.slow
def test_als_quality_anchor_small(monkeypatch):
    """The quality anchor must produce a small bench-vs-f64 RMSE delta at
    toy scale (equal iterations, same init) and survive the x64 subprocess
    round trip."""
    import jax
    import numpy as np

    import bench
    from flink_ms_tpu.ops.als import ALSConfig, prepare_blocked
    from flink_ms_tpu.parallel.mesh import make_mesh

    monkeypatch.setenv("BENCH_RMSE_REF_NNZ", "3000")
    monkeypatch.setenv("BENCH_RMSE_REF_ITERS", "3")
    monkeypatch.delenv("BENCH_SKIP_CPU", raising=False)
    rng = np.random.default_rng(0)
    users = rng.integers(0, 50, 3000)
    items = rng.integers(0, 40, 3000)
    ratings = rng.uniform(1, 5, 3000)
    mesh = make_mesh(devices=jax.devices("cpu")[:1])
    problem = prepare_blocked(users, items, ratings, 1)
    cfg = ALSConfig(num_factors=4, iterations=1, lambda_=0.1, seed=42)
    out = bench.als_quality_anchor(
        mesh, problem, users, items, ratings, cfg, iters=3)
    assert out["als_rmse_iters"] == 3
    assert 0.0 < out["als_rmse_at_iters"] < 5.0
    # f32 bench config vs f64 reference: sub-percent at toy scale
    assert abs(out["als_rmse_ref_delta"]) < 0.01, out


def test_watchdog_emits_partial_snapshot_until_real_line(monkeypatch):
    """The artifact watchdog (2026-08-02 wedge variant: devices() answers,
    in-process compiles hang, SIGTERM handler can't run mid-C-call) must
    emit a parseable partial snapshot from its daemon thread after the
    deadline, re-emit while the run is stuck, and go silent the moment
    the real artifact prints."""
    import io
    import json
    import time as _time

    import bench

    monkeypatch.setenv("BENCH_WATCHDOG_S", "0.2")
    monkeypatch.setenv("BENCH_WATCHDOG_REEMIT_S", "0.2")
    monkeypatch.setattr(bench, "_CURRENT_RESULT",
                        {"platform": "axon", "als_nnz": 123})
    buf = io.StringIO()
    bench._start_watchdog(buf)
    deadline = _time.time() + 5.0
    while _time.time() < deadline:
        if len(buf.getvalue().splitlines()) >= 2:
            break
        _time.sleep(0.05)
    lines = buf.getvalue().splitlines()
    assert len(lines) >= 2, "watchdog never re-emitted"
    for ln in lines:
        d = json.loads(ln)
        assert d["watchdog"] is True
        assert d["metric"] == "als_ml20m_sec_per_iter"  # headline keys
        assert "value" in d and "vs_baseline" in d
        assert d["degraded"] is True
    # the real emission path sets the event under the lock: no snapshot
    # may land afterwards
    with bench._PRINT_LOCK:
        bench._ARTIFACT_PRINTED.set()
    n = len(buf.getvalue().splitlines())
    _time.sleep(0.5)
    assert len(buf.getvalue().splitlines()) == n


def test_watchdog_silent_when_run_finishes_first(monkeypatch):
    """A healthy run that emits before the watchdog deadline must produce
    zero watchdog lines."""
    import io
    import time as _time

    import bench

    monkeypatch.setenv("BENCH_WATCHDOG_S", "0.3")
    buf = io.StringIO()
    bench._start_watchdog(buf)
    bench._ARTIFACT_PRINTED.set()  # "run finished" before the deadline
    _time.sleep(0.6)
    assert buf.getvalue() == ""


def test_backend_probes_roundtrip_a_compile():
    """Both subprocess probes must execute a jit, not just list devices:
    the 2026-08-02 wedge answers jax.devices() while every compile hangs,
    and a devices-level probe would pass the run straight into an
    untimeouted in-process hang."""
    import ast
    import os as _os

    root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    src = open(_os.path.join(root, "bench.py")).read()
    assert "jax.jit(lambda x: (x @ x).sum())" in src  # _PROBE_JIT body
    tree = ast.parse(src)
    probe_users = {
        n.name for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)
        and "_PROBE_JIT" in ast.dump(n)
    }
    assert {"acquire_devices", "_accel_probe_ok"} <= probe_users
